"""Pallas megakernel for the bind scan (fast path).

The XLA scan pays ~5 µs of per-op overhead for each of the ~30 HLO ops in
a scheduling step. This kernel fuses the entire step — static-filter gather,
resource fit, Least/BalancedAllocation, Simon share, PodTopologySpread
(hard + soft), inter-pod affinity (required / anti / preferred, incoming and
symmetric), selectHost, and the bind state update — into ONE Pallas program
whose cluster state lives in VMEM for the whole scan: a bind costs
VMEM-bandwidth, not kernel launches.

Scope: every scheduler feature — resource fit, topology spread, inter-pod
affinity, GPU-share devices, open-local storage, host ports, preferred node
affinity, PreferNoSchedule and NodePreferAvoidPods scoring — bounded by
table-size caps and at most five topology keys (hostname + four zone-like
keys, stacked per-key count blocks); `engine/fastpath.py`
gates applicability and guarantees identical placements to the XLA scan
(tests + randomized differential fuzzing assert equality). Past 512
templates the kernel switches to big-U mode: the [U, N]/[X, U] template
tables stay in HBM and each pod step DMAs its row/column into VMEM scratch,
so VMEM no longer scales with U (cap 2048, bounded by SMEM scalars). The kernel is
generated per feature-flag combination so absent features cost nothing, and
node validity is a runtime row so scenario sweeps re-dispatch with nothing
but a new mask and spread-weight table.

Layouts (N = padded node axis, lanes; rows padded to sublane multiples):
  alloc_T     [R, N]    f32  allocatable per resource row
  used        [R, N]    f32  scratch, persistent across the grid
  static_pass [U, N]    f32  0/1 from kernels.precompute_static
  node_cnt    [A, N]    f32  scratch — per-hostname-domain selector counts
  zone_cnt    [K*A, Z]  f32  scratch — per-(zone-key, selector) counts
  anti_node   [G, N]    f32  scratch — existing-pod anti-affinity terms
  prefw_node  [Gp, N]   f32  scratch — symmetric preferred-term weights
  matches_AU  [A, U]    f32  selector-match matrix (column = template)
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..encoding import vocab as V

NEG = -1e30
MAX_SCORE = 100.0
# SMEM int32 streams tile at 1024 on current Mosaic; block shapes must match
CHUNK = 1024


class FastInputs(NamedTuple):
    """Host-prepared tensors for the kernel (see engine/fastpath.py)."""

    alloc_T: np.ndarray  # [R, N]
    used0_T: np.ndarray  # [R, N]
    static_pass: np.ndarray  # [U, N]
    aff_mask: np.ndarray  # [U, N]
    share_raw: np.ndarray  # [U, N]
    zone_NZ: np.ndarray  # [K, N, Z] — per-zone-key one-hot blocks (lane offset 0 per key)
    zone_ZN: np.ndarray  # [K*Z, N]
    has_zone: np.ndarray  # [K, N] f32 — node has key k's label
    matches_AU: np.ndarray  # [A, U]
    node_valid: np.ndarray  # [1, N] f32
    # SMEM scalar tables
    req: np.ndarray  # [U, R] f32
    cpu_nz: np.ndarray  # [U] f32 nonzero-default cpu (milli)
    mem_nz: np.ndarray  # [U] f32 nonzero-default memory
    pin: np.ndarray  # [U] i32
    # spread constraints, [U, Cs] each
    spr_active: np.ndarray  # i32 0/1
    spr_key: np.ndarray  # i32 topology key index: 0 = hostname, 1..K = zone keys
    spr_sel: np.ndarray  # i32 selector id
    spr_skew: np.ndarray  # f32
    spr_hard: np.ndarray  # i32 0/1
    spr_self: np.ndarray  # f32 0/1 template matches own selector
    spr_weight: np.ndarray  # f32 log(size+2)
    # inter-pod affinity (all zero-shaped semantics when has_interpod=False)
    at_active: np.ndarray  # [U, Ti] i32 — incoming required affinity terms
    at_key: np.ndarray  # [U, Ti] i32 key index (0 = hostname, 1..K = zone)
    at_sel: np.ndarray  # [U, Ti] i32
    at_self: np.ndarray  # [U, Ti] f32 — bootstrap self-match
    an_active: np.ndarray  # [U, Tn] i32 — incoming anti terms
    an_key: np.ndarray  # [U, Tn] i32
    an_sel: np.ndarray  # [U, Tn] i32
    pt_active: np.ndarray  # [U, Tp] i32 — incoming preferred terms
    pt_key: np.ndarray  # [U, Tp] i32
    pt_sel: np.ndarray  # [U, Tp] i32
    pt_w: np.ndarray  # [U, Tp] f32 signed weights
    anti_g_key: np.ndarray  # [G] i32 — global existing-anti term key indices
    prefg_key: np.ndarray  # [Gp] i32 — global symmetric-preferred term key indices
    antig_GU: np.ndarray  # [G, U] f32 — template carries term g
    gmatch_GU: np.ndarray  # [G, U] f32 — template matches term g's selector
    prefg_GU: np.ndarray  # [Gp, U] f32 — carried symmetric weights
    pmatch_GU: np.ndarray  # [Gp, U] f32 — template matches pref term's selector
    # gpu-share (zero-shaped semantics when has_gpu=False)
    gpu_mem: np.ndarray  # [U] f32 per-GPU memory request
    gpu_cnt: np.ndarray  # [U] f32 requested GPU count
    gpu0_DN: np.ndarray  # [Gd, N] f32 initial per-device free memory
    # open-local storage (inert when has_local=False)
    lvm_req: np.ndarray  # [U] f32 total LVM bytes
    dev_req: np.ndarray  # [U, 2] f32 exclusive-device max size by media (score)
    dev_need: np.ndarray  # [U, 2] f32 device count by media
    dev_sizes: np.ndarray  # [U, 2*Mv] f32 per-volume sizes desc (ssd rows then hdd)
    vg_cap_VN: np.ndarray  # [Vg, N] f32 VG capacities
    vg0_VN: np.ndarray  # [Vg, N] f32 initial VG free
    dev_cap_DN: np.ndarray  # [Dv, N] f32 device capacities
    dev0_DN: np.ndarray  # [Dv, N] f32 initial device free
    dev_media_DN: np.ndarray  # [2*Dv, N] f32 media one-hots (ssd rows then hdd rows)
    # host ports (inert when has_ports=False)
    port_HU: np.ndarray  # [Hp, U] f32 — template uses port row h (bind marks)
    port_conf_HU: np.ndarray  # [Hp, U] f32 — template conflicts with row h (filter)
    # static score tables (inert when the matching feature flag is off)
    na_raw: np.ndarray  # [U, N] f32 preferred-node-affinity weights
    tt_raw: np.ndarray  # [U, N] f32 intolerable PreferNoSchedule counts
    avoid_raw: np.ndarray  # [U, N] f32 NodePreferAvoidPods raw score (0 or 100)


def _input_layout(
    has_interpod: bool,
    has_gpu: bool,
    has_local: bool,
    has_ports: bool,
    has_na: bool,
    has_tt: bool,
    has_avoid: bool,
    big_u: bool,
):
    """Ordered (name, kind) list of kernel inputs for one feature-flag
    combination; kind ∈ {stream, smem, vmem, any}. The pallas_call signature
    is generated from this, so a workload with a feature off pays ZERO
    VMEM/SMEM for that feature's tables — the buffers don't exist."""
    ut = "any" if big_u else "vmem"  # U-scaled tables move to HBM in big-U mode
    L = [
        ("tmpl", "stream"), ("valid", "stream"), ("forced", "stream"),
        ("req", "smem"), ("cpu_nz", "smem"), ("mem_nz", "smem"), ("pin", "smem"),
        ("spr_active", "smem"), ("spr_key", "smem"), ("spr_sel", "smem"),
        ("spr_skew", "smem"), ("spr_hard", "smem"), ("spr_self", "smem"),
        ("spr_weight", "smem"),
    ]
    if has_interpod:
        L += [
            ("at_active", "smem"), ("at_key", "smem"), ("at_sel", "smem"),
            ("at_self", "smem"),
            ("an_active", "smem"), ("an_key", "smem"), ("an_sel", "smem"),
            ("pt_active", "smem"), ("pt_key", "smem"), ("pt_sel", "smem"),
            ("pt_w", "smem"),
            ("anti_g_key", "smem"), ("prefg_key", "smem"),
        ]
    if has_gpu:
        L += [("gpu_mem", "smem"), ("gpu_cnt", "smem")]
    if has_local:
        L += [("lvm_req", "smem"), ("dev_req", "smem"), ("dev_need", "smem"),
              ("dev_sizes", "smem")]
    L += [
        ("alloc_T", "vmem"), ("used0_T", "vmem"),
        ("static_pass", ut), ("aff_mask", ut), ("share_raw", ut),
        ("zone_NZ", "vmem"), ("zone_ZN", "vmem"), ("has_zone", "vmem"),
        ("matches_AU", ut), ("node_valid", "vmem"),
    ]
    if has_interpod:
        L += [("antig_GU", ut), ("gmatch_GU", ut), ("prefg_GU", ut), ("pmatch_GU", ut)]
    if has_gpu:
        L += [("gpu0_DN", "vmem")]
    if has_local:
        L += [("vg_cap_VN", "vmem"), ("vg0_VN", "vmem"), ("dev_cap_DN", "vmem"),
              ("dev0_DN", "vmem"), ("dev_media_DN", "vmem")]
    if has_ports:
        L += [("port_HU", ut), ("port_conf_HU", ut)]
    if has_na:
        L += [("na_raw", ut)]
    if has_tt:
        L += [("tt_raw", ut)]
    if has_avoid:
        L += [("avoid_raw", ut)]
    return L


def _scratch_names(has_interpod, has_gpu, has_local, has_ports):
    names = ["used", "node_cnt", "zone_cnt"]
    if has_interpod:
        names += ["anti_node", "anti_zone", "prefw_node", "prefw_zone"]
    if has_gpu:
        names += ["gpu_free"]
    if has_local:
        names += ["vg_free", "dev_free"]
    if has_ports:
        names += ["port_used"]
    return names


def _make_kernel(
    has_interpod: bool,
    has_gpu: bool,
    has_local: bool,
    has_ports: bool,
    has_na: bool,
    has_tt: bool,
    has_avoid: bool,
    n_anti: int,
    n_pref: int,
    n_gpu: int,
    n_vg: int,
    n_dev: int,
    n_dvol: int,
    big_u: bool = False,
    n_zkeys: int = 1,
    gc_row: int = -1,
):
    layout = _input_layout(has_interpod, has_gpu, has_local, has_ports, has_na, has_tt, has_avoid, big_u)
    in_names = [n for n, _ in layout]
    out_names = ["chosen", "used_out"]
    if has_gpu:
        out_names += ["gpu_take", "gpu_out"]
    if has_local:
        out_names += ["vg_out", "dev_out"]
    scratch_names = _scratch_names(has_interpod, has_gpu, has_local, has_ports)

    def kernel(*refs):
        Rd = dict(zip(in_names + out_names + scratch_names, refs))
        u_scratch = refs[len(in_names) + len(out_names) + len(scratch_names):]
        # SMEM streams + tables
        tmpl_ref, valid_ref, forced_ref = Rd["tmpl"], Rd["valid"], Rd["forced"]
        req_ref, cpu_nz_ref, mem_nz_ref, pin_ref = (
            Rd["req"], Rd["cpu_nz"], Rd["mem_nz"], Rd["pin"])
        sa_ref, sh_ref, ss_ref, sk_ref, shard_ref, sself_ref, sw_ref = (
            Rd["spr_active"], Rd["spr_key"], Rd["spr_sel"], Rd["spr_skew"],
            Rd["spr_hard"], Rd["spr_self"], Rd["spr_weight"])
        if has_interpod:
            ata_ref, ath_ref, ats_ref, atf_ref = (
                Rd["at_active"], Rd["at_key"], Rd["at_sel"], Rd["at_self"])
            ana_ref, anh_ref, ans_ref = Rd["an_active"], Rd["an_key"], Rd["an_sel"]
            pta_ref, pth_ref, pts_ref, ptw_ref = (
                Rd["pt_active"], Rd["pt_key"], Rd["pt_sel"], Rd["pt_w"])
            agh_ref, pgh_ref = Rd["anti_g_key"], Rd["prefg_key"]
            antig_ref, gmatch_ref = Rd["antig_GU"], Rd["gmatch_GU"]
            prefg_ref, pmatch_ref = Rd["prefg_GU"], Rd["pmatch_GU"]
            anti_node_ref, anti_zone_ref = Rd["anti_node"], Rd["anti_zone"]
            prefw_node_ref, prefw_zone_ref = Rd["prefw_node"], Rd["prefw_zone"]
        if has_gpu:
            gmem_ref, gcnt_ref = Rd["gpu_mem"], Rd["gpu_cnt"]
            gpu0_ref, gpu_free_ref = Rd["gpu0_DN"], Rd["gpu_free"]
            gpu_take_ref, gpu_out_ref = Rd["gpu_take"], Rd["gpu_out"]
        if has_local:
            lvm_ref, dreq_ref, dneed_ref, dsz_ref = (
                Rd["lvm_req"], Rd["dev_req"], Rd["dev_need"], Rd["dev_sizes"])
            vgcap_ref, vg0_ref = Rd["vg_cap_VN"], Rd["vg0_VN"]
            devcap_ref, dev0_ref, media_ref = (
                Rd["dev_cap_DN"], Rd["dev0_DN"], Rd["dev_media_DN"])
            vg_free_ref, dev_free_ref = Rd["vg_free"], Rd["dev_free"]
            vg_out_ref, dev_out_ref = Rd["vg_out"], Rd["dev_out"]
        if has_ports:
            port_hu_ref, port_conf_hu_ref = Rd["port_HU"], Rd["port_conf_HU"]
            port_used_ref = Rd["port_used"]
        if has_na:
            na_ref = Rd["na_raw"]
        if has_tt:
            tt_ref = Rd["tt_raw"]
        if has_avoid:
            av_ref = Rd["avoid_raw"]
        alloc_ref, used0_ref = Rd["alloc_T"], Rd["used0_T"]
        static_ref, affm_ref, shraw_ref = (
            Rd["static_pass"], Rd["aff_mask"], Rd["share_raw"])
        zone_nz_ref, zone_zn_ref, has_zone_ref = (
            Rd["zone_NZ"], Rd["zone_ZN"], Rd["has_zone"])
        matches_ref, nodevalid_ref = Rd["matches_AU"], Rd["node_valid"]
        chosen_ref, used_out_ref = Rd["chosen"], Rd["used_out"]
        used_ref, node_cnt_ref, zone_cnt_ref = (
            Rd["used"], Rd["node_cnt"], Rd["zone_cnt"])
        R, N = alloc_ref.shape
        U = static_ref.shape[0]
        Cs = sa_ref.shape[0]
        if has_interpod:
            Ti = ata_ref.shape[0]
            Tn = ana_ref.shape[0]
            Tp = pta_ref.shape[0]

        @pl.when(pl.program_id(0) == 0)
        def _init():
            used_ref[:] = used0_ref[:]
            node_cnt_ref[:] = jnp.zeros_like(node_cnt_ref)
            zone_cnt_ref[:] = jnp.zeros_like(zone_cnt_ref)
            if has_interpod:
                anti_node_ref[:] = jnp.zeros_like(anti_node_ref)
                anti_zone_ref[:] = jnp.zeros_like(anti_zone_ref)
                prefw_node_ref[:] = jnp.zeros_like(prefw_node_ref)
                prefw_zone_ref[:] = jnp.zeros_like(prefw_zone_ref)
            if has_gpu:
                gpu_free_ref[:] = gpu0_ref[:]
            if has_local:
                vg_free_ref[:] = vg0_ref[:]
                dev_free_ref[:] = dev0_ref[:]
            if has_ports:
                port_used_ref[:] = jnp.zeros_like(port_used_ref)

        iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        iota_u = jax.lax.broadcasted_iota(jnp.int32, (U, 1), 0)
        valid_row = nodevalid_ref[:]  # [1, N]
        ones_1n = jnp.ones((1, N), jnp.float32)

        A_rows = node_cnt_ref.shape[0]
        Zk = zone_zn_ref.shape[0] // n_zkeys

        def _flag_row(flag_ref, n_rows):
            """Expand an SMEM int-flag table into a [1, n_rows] f32 vector
            (loop-invariant: built once, outside the pod loop)."""
            row = jnp.zeros((1, n_rows), jnp.float32)
            r_iota = jax.lax.broadcasted_iota(jnp.int32, (1, n_rows), 1)
            for g in range(n_rows):
                row = jnp.where(r_iota == g, jnp.float32(flag_ref[g]), row)
            return row

        def _flag_col(flag_ref, n_rows):
            col = jnp.zeros((n_rows, 1), jnp.float32)
            c_iota = jax.lax.broadcasted_iota(jnp.int32, (n_rows, 1), 0)
            for g in range(n_rows):
                col = jnp.where(c_iota == g, jnp.float32(flag_ref[g]), col)
            return col

        if has_interpod:
            g_key_row = _flag_row(agh_ref, n_anti)
            p_key_row = _flag_row(pgh_ref, n_pref)
            g_key_col = _flag_col(agh_ref, n_anti)
            p_key_col = _flag_col(pgh_ref, n_pref)

        def sel_cnt(sel, key):
            """Count of bound pods matching selector `sel` in the candidate
            node's domain under topology key index `key` (0 = hostname,
            1..K = zone keys; zone counts live in per-key row blocks)."""
            host_cnt = node_cnt_ref[pl.ds(sel, 1), :]  # [1, N]
            k = jnp.maximum(key - 1, 0)
            zrow = zone_cnt_ref[pl.ds(k * A_rows + sel, 1), :]  # [1, Zk]
            zone_gather = jnp.dot(
                zrow, zone_zn_ref[pl.ds(k * Zk, Zk), :], preferred_element_type=jnp.float32
            )
            has = has_zone_ref[pl.ds(k, 1), :]
            return jnp.where(key == 0, host_cnt, zone_gather), jnp.where(
                key == 0, ones_1n, has
            )

        def body(i, _):
            u = tmpl_ref[i]
            if big_u:
                # template tables live in HBM: DMA this step's row (for
                # [U, N] tables) / 128-lane column block (for [X, U] tables
                # — a 1-lane HBM slice violates the (8,128) tiling, so the
                # aligned block containing column u is copied and the single
                # column extracted in VMEM by a one-hot dot) — all copies in
                # flight together, one wait. VMEM stays independent of U.
                sems = u_scratch[-1]
                bufs = list(u_scratch[:-1])
                dma_state = {"k": 0}
                copies = []
                u_blk = (u // 128) * 128

                def _dma(ref, col):
                    k = dma_state["k"]
                    dma_state["k"] = k + 1
                    scratch = bufs[k]
                    src = ref.at[:, pl.ds(u_blk, 128)] if col else ref.at[pl.ds(u, 1)]
                    cp = pltpu.make_async_copy(src, scratch, sems.at[k])
                    cp.start()
                    copies.append(cp)
                    return scratch

                s_static = _dma(static_ref, False)
                s_aff = _dma(affm_ref, False)
                s_share = _dma(shraw_ref, False)
                s_match = _dma(matches_ref, True)
                s_na = _dma(na_ref, False) if has_na else None
                s_tt = _dma(tt_ref, False) if has_tt else None
                s_av = _dma(av_ref, False) if has_avoid else None
                if has_ports:
                    s_port = _dma(port_hu_ref, True)
                    s_portc = _dma(port_conf_hu_ref, True)
                if has_interpod:
                    s_antig = _dma(antig_ref, True)
                    s_gmatch = _dma(gmatch_ref, True)
                    s_prefg = _dma(prefg_ref, True)
                    s_pmatch = _dma(pmatch_ref, True)
                for cp in copies:
                    cp.wait()
                lane_oh = (
                    jax.lax.broadcasted_iota(jnp.int32, (128, 1), 0) == (u - u_blk)
                ).astype(jnp.float32)

                def col_of(scratch):  # [X, 128] block -> [X, 1] column u
                    return jnp.dot(scratch[:], lane_oh, preferred_element_type=jnp.float32)

                static_row = s_static[:]
            else:
                static_row = static_ref[pl.ds(u, 1), :]  # [1, N] (validity applied separately)
            if has_gpu:
                for d in range(n_gpu):  # SMEM outputs have no default value
                    gpu_take_ref[d, i] = jnp.float32(0.0)

            # --- NodeResourcesFit
            # dynamic gpu-count allocatable (Features.gc_dyn; the gpushare
            # Reserve rewrite, open-gpu-share.go:177-182): on device-bearing
            # nodes the gc_row alloc is the count of not-fully-used devices
            use_gc = has_gpu and gc_row >= 0
            if use_gc:
                gc_dyn_row = jnp.zeros((1, N), jnp.float32)
                gc_has_dev = jnp.zeros((1, N), jnp.float32)
                for d in range(n_gpu):
                    valid_d = (gpu0_ref[pl.ds(d, 1), :] > 0).astype(jnp.float32)
                    free_d = (gpu_free_ref[pl.ds(d, 1), :] > 0).astype(jnp.float32)
                    gc_dyn_row = gc_dyn_row + valid_d * free_d
                    gc_has_dev = jnp.maximum(gc_has_dev, valid_d)
            fit = ones_1n
            for r in range(R):
                req_r = req_ref[r, u]
                alloc_r = alloc_ref[pl.ds(r, 1), :]
                if use_gc and r == gc_row:
                    alloc_r = jnp.where(gc_has_dev > 0, gc_dyn_row, alloc_r)
                over = (used_ref[pl.ds(r, 1), :] + req_r > alloc_r).astype(jnp.float32)
                fit = fit * jnp.where(req_r > 0, 1.0 - over, 1.0)
            # node validity is a runtime row (NOT folded into static_pass) so
            # scenario sweeps can vary it without re-marshalling the tables
            feasible = static_row * fit * valid_row

            if has_ports:
                # NodePorts: any CONFLICTING port already used on the node
                # (wildcard-expanded template rows via one-hot matvec, or the
                # DMA'd column in big-U mode)
                if big_u:
                    my_ports = col_of(s_portc)  # [Hp, 1]
                else:
                    onehot_u_p = (iota_u == u).astype(jnp.float32)
                    my_ports = jnp.dot(
                        port_conf_hu_ref[:], onehot_u_p, preferred_element_type=jnp.float32
                    )  # [Hp, 1]
                conflicts = jnp.dot(
                    my_ports.reshape(1, -1),
                    (port_used_ref[:] > 0).astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )  # [1, N]
                feasible = feasible * (conflicts == 0).astype(jnp.float32)

            if has_gpu:
                # Open-Gpu-Share filter: sum_d floor(free_d / mem) >= count
                gmem = gmem_ref[u]
                gcnt = gcnt_ref[u]
                chunks_sum = jnp.zeros((1, N), jnp.float32)
                for d in range(n_gpu):
                    chunks_sum = chunks_sum + jnp.floor(
                        gpu_free_ref[pl.ds(d, 1), :] / jnp.maximum(gmem, 1.0)
                    )
                gpu_ok = ((chunks_sum >= gcnt) & (gcnt > 0)).astype(jnp.float32)
                feasible = jnp.where(gmem > 0, feasible * gpu_ok, feasible)

            if has_local:
                # Open-Local filter: LVM fits the best VG; enough exclusive
                # devices of each media type
                lvm = lvm_ref[u]
                best_vg_free = jnp.full((1, N), -1e30, jnp.float32)
                for v in range(n_vg):
                    best_vg_free = jnp.maximum(best_vg_free, vg_free_ref[pl.ds(v, 1), :])
                feasible = jnp.where(
                    lvm > 0, feasible * (best_vg_free >= lvm).astype(jnp.float32), feasible
                )
                # one-device-per-volume matching: the i-th largest volume
                # needs ≥ i+1 free fitting devices (common.go:290-349)
                for m in range(2):
                    for vi in range(n_dvol):
                        size = dsz_ref[m * n_dvol + vi, u]
                        cnt_fit = jnp.zeros((1, N), jnp.float32)
                        for d in range(n_dev):
                            free_d = dev_free_ref[pl.ds(d, 1), :]
                            media_d = media_ref[pl.ds(m * n_dev + d, 1), :]
                            cnt_fit = cnt_fit + media_d * ((free_d >= size) & (free_d > 0)).astype(jnp.float32)
                        feasible = jnp.where(
                            size > 0, feasible * (cnt_fit >= (vi + 1)).astype(jnp.float32), feasible
                        )

            # --- PodTopologySpread
            aff_row = (s_aff[:] if big_u else affm_ref[pl.ds(u, 1), :]) * valid_row
            soft_raw = jnp.zeros((1, N), jnp.float32)
            ignored = jnp.zeros((1, N), jnp.float32)
            any_soft = jnp.float32(0.0)
            for c in range(Cs):
                active = sa_ref[c, u]
                skew = sk_ref[c, u]
                cnt, has_label = sel_cnt(ss_ref[c, u], sh_ref[c, u])
                activef = active == 1
                hardf = activef & (shard_ref[c, u] == 1)
                softf = activef & (shard_ref[c, u] == 0)

                elig = aff_row * has_label
                masked = jnp.where(elig > 0, cnt, jnp.float32(1e30))
                min_cnt = jnp.min(masked)
                ok = (cnt + sself_ref[c, u] - min_cnt <= skew) & (has_label > 0)
                feasible = jnp.where(hardf, feasible * ok.astype(jnp.float32), feasible)

                contrib = jnp.where(has_label > 0, cnt * sw_ref[c, u] + (skew - 1.0), 0.0)
                soft_raw = soft_raw + jnp.where(softf, contrib, 0.0)
                ignored = jnp.maximum(ignored, jnp.where(softf, 1.0 - has_label, 0.0))
                any_soft = jnp.maximum(any_soft, jnp.where(softf, 1.0, 0.0))

            ip_raw = jnp.zeros((1, N), jnp.float32)
            if has_interpod:
                if not big_u:
                    onehot_u_col = (iota_u == u).astype(jnp.float32)  # [U, 1]
                # incoming required anti-affinity: no matching pod in domain
                for t in range(Tn):
                    cnt, has_label = sel_cnt(ans_ref[t, u], anh_ref[t, u])
                    violated = (cnt > 0) & (has_label > 0)
                    feasible = jnp.where(
                        ana_ref[t, u] == 1, feasible * (1.0 - violated.astype(jnp.float32)), feasible
                    )
                # incoming required affinity: counts use the all-terms
                # conjunction selector (filtering.go:113-127). A node passes
                # when every term's topology label exists and every term's
                # domain count is positive, or via the bootstrap — global
                # count map empty AND full self-match AND labels present
                # (satisfyPodAffinity, filtering.go:347-374).
                at_all_ok = jnp.ones((1, N), jnp.float32)
                at_labels_ok = jnp.ones((1, N), jnp.float32)
                at_map_total = jnp.float32(0.0)
                at_self_all = jnp.float32(1.0)
                for t in range(Ti):
                    cnt, has_label = sel_cnt(ats_ref[t, u], ath_ref[t, u])
                    total_host = jnp.sum(node_cnt_ref[pl.ds(ats_ref[t, u], 1), :])
                    at_k = jnp.maximum(ath_ref[t, u] - 1, 0)
                    total_zone = jnp.sum(
                        zone_cnt_ref[pl.ds(at_k * A_rows + ats_ref[t, u], 1), :]
                    )
                    total = jnp.where(ath_ref[t, u] == 0, total_host, total_zone)
                    activef = ata_ref[t, u] == 1
                    term_ok = ((cnt > 0) & (has_label > 0)).astype(jnp.float32)
                    at_all_ok = jnp.where(activef, at_all_ok * term_ok, at_all_ok)
                    at_labels_ok = jnp.where(
                        activef, at_labels_ok * (has_label > 0).astype(jnp.float32), at_labels_ok
                    )
                    at_map_total = at_map_total + jnp.where(activef, total, 0.0)
                    at_self_all = at_self_all * jnp.where(
                        activef, (atf_ref[t, u] > 0).astype(jnp.float32), 1.0
                    )
                at_bootstrap = ((at_map_total == 0.0) & (at_self_all > 0)).astype(jnp.float32)
                feasible = feasible * jnp.maximum(at_all_ok, at_labels_ok * at_bootstrap)
                # symmetric: existing pods' anti terms vs the incoming pod.
                # counts are non-negative, so "any matching term has pods in
                # my domain" == "match-weighted count sum > 0" — three dots
                # instead of per-term loops. Host-key domains always have
                # the label (applicable() enforces hostname-identity); zone
                # gathers give 0 on label-less nodes via the one-hot.
                if big_u:
                    my_gmatch = col_of(s_gmatch)
                else:
                    my_gmatch = jnp.dot(gmatch_ref[:], onehot_u_col, preferred_element_type=jnp.float32)
                m_row = my_gmatch.reshape(1, n_anti)
                m_host = m_row * (g_key_row == 0).astype(jnp.float32)
                sym_cnt = jnp.dot(m_host, anti_node_ref[:], preferred_element_type=jnp.float32)
                for zk in range(n_zkeys):
                    m_k = m_row * (g_key_row == zk + 1).astype(jnp.float32)
                    sym_cnt = sym_cnt + jnp.dot(
                        jnp.dot(m_k, anti_zone_ref[:], preferred_element_type=jnp.float32),
                        zone_zn_ref[pl.ds(zk * Zk, Zk), :],
                        preferred_element_type=jnp.float32,
                    )
                feasible = feasible * (1.0 - (sym_cnt > 0).astype(jnp.float32))
                # score: incoming preferred terms
                for t in range(Tp):
                    cnt, has_label = sel_cnt(pts_ref[t, u], pth_ref[t, u])
                    ip_raw = ip_raw + jnp.where(
                        pta_ref[t, u] == 1, cnt * ptw_ref[t, u] * has_label, 0.0
                    )
                # score: symmetric preferred/hard-affinity weights — same
                # three-dot contraction over the term axis
                if big_u:
                    my_pmatch = col_of(s_pmatch)
                else:
                    my_pmatch = jnp.dot(pmatch_ref[:], onehot_u_col, preferred_element_type=jnp.float32)
                pm_row = my_pmatch.reshape(1, n_pref)
                pm_host = pm_row * (p_key_row == 0).astype(jnp.float32)
                ip_raw = ip_raw + jnp.dot(pm_host, prefw_node_ref[:], preferred_element_type=jnp.float32)
                for zk in range(n_zkeys):
                    pm_k = pm_row * (p_key_row == zk + 1).astype(jnp.float32)
                    ip_raw = ip_raw + jnp.dot(
                        jnp.dot(pm_k, prefw_zone_ref[:], preferred_element_type=jnp.float32),
                        zone_zn_ref[pl.ds(zk * Zk, Zk), :],
                        preferred_element_type=jnp.float32,
                    )

            # --- scores
            cpu_req = cpu_nz_ref[u]
            mem_req = mem_nz_ref[u]
            alloc_cpu = alloc_ref[pl.ds(V.RES_CPU, 1), :]
            alloc_mem = alloc_ref[pl.ds(V.RES_MEMORY, 1), :]
            used_cpu = used_ref[pl.ds(V.RES_CPU, 1), :] + cpu_req
            used_mem = used_ref[pl.ds(V.RES_MEMORY, 1), :] + mem_req
            l_cpu = jnp.where(
                (alloc_cpu == 0) | (used_cpu > alloc_cpu),
                0.0,
                (alloc_cpu - used_cpu) * MAX_SCORE / jnp.maximum(alloc_cpu, 1.0),
            )
            l_mem = jnp.where(
                (alloc_mem == 0) | (used_mem > alloc_mem),
                0.0,
                (alloc_mem - used_mem) * MAX_SCORE / jnp.maximum(alloc_mem, 1.0),
            )
            least = (l_cpu + l_mem) / 2.0
            cpu_frac = used_cpu / jnp.maximum(alloc_cpu, 1.0)
            mem_frac = used_mem / jnp.maximum(alloc_mem, 1.0)
            balanced = jnp.where(
                (cpu_frac >= 1.0) | (mem_frac >= 1.0),
                0.0,
                (1.0 - jnp.abs(cpu_frac - mem_frac)) * MAX_SCORE,
            )

            share_row = s_share[:] if big_u else shraw_ref[pl.ds(u, 1), :]
            if use_gc:
                # add back the gpu-count share with the Reserve-updated
                # value (share_raw zeroed that column on device-bearing
                # nodes; algo.Share semantics, greed.go:70-83)
                gc_req = req_ref[gc_row, u]
                declared = (alloc_ref[pl.ds(gc_row, 1), :] > 0).astype(jnp.float32)
                avail = gc_dyn_row - gc_req
                sh = jnp.where(
                    avail == 0,
                    jnp.where(gc_req == 0, 0.0, 1.0),
                    gc_req / jnp.where(avail == 0, 1.0, avail),
                )
                sh = jnp.where(
                    (declared > 0) & (gc_has_dev > 0), jnp.maximum(sh, 0.0), 0.0
                ) * MAX_SCORE
                share_row = jnp.maximum(share_row, jnp.where(gc_req > 0, sh, 0.0))
            feas_b = feasible > 0
            lo = jnp.min(jnp.where(feas_b, share_row, jnp.float32(1e30)))
            hi = jnp.max(jnp.where(feas_b, share_row, jnp.float32(-1e30)))
            rng = hi - lo
            share_norm = jnp.where(rng > 0, (share_row - lo) * MAX_SCORE / rng, 0.0)

            scored = feas_b & (ignored == 0)
            smn = jnp.min(jnp.where(scored, soft_raw, jnp.float32(1e30)))
            smx = jnp.max(jnp.where(scored, soft_raw, jnp.float32(-1e30)))
            spread_norm = jnp.where(
                smx <= 0, MAX_SCORE, MAX_SCORE * (smx + smn - soft_raw) / jnp.maximum(smx, 1.0)
            )
            spread_norm = jnp.where(ignored > 0, 0.0, spread_norm)
            spread_norm = jnp.where(any_soft > 0, spread_norm, 0.0)

            score = least + balanced + 2.0 * share_norm + 2.0 * spread_norm
            if has_na:
                # NodeAffinity preferred-term weights, max-normalized over
                # the feasible set (DefaultNormalizeScore)
                na_row = s_na[:] if big_u else na_ref[pl.ds(u, 1), :]
                na_max = jnp.max(jnp.where(feas_b, na_row, 0.0))
                score = score + jnp.where(
                    na_max > 0, na_row * MAX_SCORE / jnp.maximum(na_max, 1.0), na_row
                )
            if has_tt:
                # TaintToleration: intolerable PreferNoSchedule counts,
                # reverse-normalized
                tt_row = s_tt[:] if big_u else tt_ref[pl.ds(u, 1), :]
                tt_max = jnp.max(jnp.where(feas_b, tt_row, 0.0))
                score = score + jnp.where(
                    tt_max > 0, MAX_SCORE - tt_row * MAX_SCORE / jnp.maximum(tt_max, 1.0), MAX_SCORE
                )
            if has_avoid:
                # NodePreferAvoidPods (w=10000, no NormalizeScore): raw
                # 0/100 static table, same shape class as na_raw
                av_row = s_av[:] if big_u else av_ref[pl.ds(u, 1), :]
                score = score + 10000.0 * av_row
            if has_local:
                # Open-Local binpack score (local_score in kernels.py):
                # mean over units of used/capacity × 10, min-max normalized
                lvm = lvm_ref[u]
                big_f = jnp.float32(1e30)
                best_free = jnp.full((1, N), big_f, jnp.float32)
                best_cap = jnp.zeros((1, N), jnp.float32)
                for v in range(n_vg):
                    free_v = vg_free_ref[pl.ds(v, 1), :]
                    fits_v = free_v >= lvm
                    better = fits_v & (free_v < best_free)
                    best_free = jnp.where(better, free_v, best_free)
                    best_cap = jnp.where(better, vgcap_ref[pl.ds(v, 1), :], best_cap)
                parts = jnp.where(
                    (lvm > 0) & (best_free < big_f), lvm / jnp.maximum(best_cap, 1.0), 0.0
                )
                count = jnp.where(lvm > 0, 1.0, 0.0)
                for m in range(2):
                    size = dreq_ref[m, u]
                    need = dneed_ref[m, u]
                    first_cap = jnp.full((1, N), big_f, jnp.float32)
                    for d in range(n_dev):
                        free_d = dev_free_ref[pl.ds(d, 1), :]
                        media_d = media_ref[pl.ds(m * n_dev + d, 1), :]
                        fitting = (media_d > 0) & (free_d >= size) & (free_d > 0)
                        first_cap = jnp.where(
                            fitting, jnp.minimum(first_cap, devcap_ref[pl.ds(d, 1), :]), first_cap
                        )
                    parts = parts + jnp.where(size > 0, need * size / jnp.maximum(first_cap, 1.0), 0.0)
                    count = count + jnp.where(size > 0, need, 0.0)
                local_raw = jnp.where(count > 0, parts / jnp.maximum(count, 1.0) * 10.0, 0.0)
                l_lo = jnp.min(jnp.where(feas_b, local_raw, big_f))
                l_hi = jnp.max(jnp.where(feas_b, local_raw, -big_f))
                l_rng = l_hi - l_lo
                score = score + jnp.where(l_rng > 0, (local_raw - l_lo) * MAX_SCORE / l_rng, 0.0)
            if has_interpod:
                # interpod_score normalization: min/max seeded with 0
                ip_masked = jnp.where(feas_b, ip_raw, 0.0)
                ip_hi = jnp.maximum(jnp.max(ip_masked), 0.0)
                ip_lo = jnp.minimum(jnp.min(ip_masked), 0.0)
                ip_rng = ip_hi - ip_lo
                score = score + jnp.where(
                    ip_rng > 0, MAX_SCORE * (ip_raw - ip_lo) / jnp.maximum(ip_rng, 1.0), 0.0
                )

            # --- selectHost: lowest index among maxima — Mosaic's argmax
            # breaks ties by HIGHEST index, diverging from the XLA scan
            masked_score = jnp.where(feas_b, score, jnp.float32(NEG))
            mx_score = jnp.max(masked_score)
            best = jnp.min(jnp.where(masked_score == mx_score, iota_n, N)).astype(jnp.int32)
            any_feasible = jnp.max(feasible) > 0
            sel_choice = jnp.where(any_feasible, best, jnp.int32(-1))
            is_forced = forced_ref[i] == 1
            pin_u = pin_ref[u]
            choice = jnp.where(is_forced, jnp.where(pin_u >= 0, pin_u, -1), sel_choice)
            do_bind = (valid_ref[i] == 1) & (choice >= 0)
            chosen_ref[i] = jnp.where(do_bind, choice, -1)

            # --- bind update
            @pl.when(do_bind)
            def _bind():
                c = jnp.maximum(choice, 0)
                onehot = (iota_n == c).astype(jnp.float32)  # [1, N]
                iota_r = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)
                req_col = jnp.zeros((R, 1), jnp.float32)
                for r in range(R):  # static unroll; .at[] would lower to scatter
                    req_col = jnp.where(iota_r == r, req_ref[r, u], req_col)
                used_ref[:] = used_ref[:] + req_col * onehot

                if big_u:
                    m_col = col_of(s_match)  # [A, 1]
                else:
                    onehot_u = (iota_u == u).astype(jnp.float32)  # [U, 1]
                    m_col = jnp.dot(matches_ref[:], onehot_u, preferred_element_type=jnp.float32)
                # per-key [1, Zk] one-hot rows of the chosen node's zones —
                # read from the 3-D [K, N, Z] table so every key's row sits
                # at lane offset 0 (a lane-offset slice can't broadcast)
                zrow_k = [
                    zone_nz_ref[zk, pl.ds(c, 1), :] for zk in range(n_zkeys)
                ]
                node_cnt_ref[:] = node_cnt_ref[:] + m_col * onehot
                for zk in range(n_zkeys):
                    zone_cnt_ref[pl.ds(zk * A_rows, A_rows), :] = (
                        zone_cnt_ref[pl.ds(zk * A_rows, A_rows), :]
                        + m_col * zrow_k[zk]
                    )
                if has_ports:
                    p_col = col_of(s_port) if big_u else jnp.dot(
                        port_hu_ref[:], onehot_u, preferred_element_type=jnp.float32
                    )
                    port_used_ref[:] = port_used_ref[:] + p_col * onehot
                if has_gpu:
                    # device packing on the chosen node (computed for all
                    # nodes, applied via the one-hot): single-GPU tightest
                    # fit, multi-GPU greedy with reuse (gpunodeinfo.go)
                    gmem = gmem_ref[u]
                    gcnt = gcnt_ref[u]
                    best_free = jnp.full((1, N), 1e30, jnp.float32)
                    for d in range(n_gpu):
                        free_d = gpu_free_ref[pl.ds(d, 1), :]
                        best_free = jnp.where(free_d >= gmem, jnp.minimum(best_free, free_d), best_free)
                    assigned = jnp.zeros((1, N), jnp.float32)
                    cum = jnp.zeros((1, N), jnp.float32)
                    for d in range(n_gpu):
                        free_d = gpu_free_ref[pl.ds(d, 1), :]
                        fits_d = (free_d >= gmem).astype(jnp.float32)
                        take_tight = fits_d * (free_d == best_free).astype(jnp.float32) * (1.0 - jnp.minimum(assigned, 1.0))
                        assigned = assigned + take_tight
                        chunks_d = jnp.floor(free_d / jnp.maximum(gmem, 1.0))
                        take_greedy = jnp.clip(gcnt - cum, 0.0, chunks_d)
                        cum = cum + chunks_d
                        take_d = jnp.where(gcnt == 1, take_tight, take_greedy)
                        take_d = jnp.where(gmem > 0, take_d, 0.0)
                        gpu_free_ref[pl.ds(d, 1), :] = free_d - take_d * gmem * onehot
                        gpu_take_ref[d, i] = jnp.sum(take_d * onehot)
                if has_local:
                    # LVM: tightest-fitting VG (first among equals)
                    lvm = lvm_ref[u]
                    big_f = jnp.float32(1e30)
                    best_free = jnp.full((1, N), big_f, jnp.float32)
                    for v in range(n_vg):
                        free_v = vg_free_ref[pl.ds(v, 1), :]
                        best_free = jnp.where(free_v >= lvm, jnp.minimum(best_free, free_v), best_free)
                    taken_vg = jnp.zeros((1, N), jnp.float32)
                    for v in range(n_vg):
                        free_v = vg_free_ref[pl.ds(v, 1), :]
                        take_v = (
                            (free_v >= lvm) & (free_v == best_free)
                        ).astype(jnp.float32) * (1.0 - jnp.minimum(taken_vg, 1.0))
                        taken_vg = taken_vg + take_v
                        vg_free_ref[pl.ds(v, 1), :] = free_v - jnp.maximum(lvm, 0.0) * take_v * onehot
                    # exclusive devices: one device per volume, smallest
                    # volume onto the smallest-capacity fitting free device
                    # (common.go:290-349; ties by lowest device index) —
                    # must mirror the XLA bind exactly
                    big_cap = jnp.float32(1e30)
                    taken_rows = [jnp.zeros((1, N), jnp.float32) for _ in range(n_dev)]
                    for m in range(2):
                        for vi in reversed(range(n_dvol)):  # ascending sizes
                            size = dsz_ref[m * n_dvol + vi, u]
                            best_cap = jnp.full((1, N), big_cap, jnp.float32)
                            for d in range(n_dev):
                                free_d = dev_free_ref[pl.ds(d, 1), :]
                                media_d = media_ref[pl.ds(m * n_dev + d, 1), :]
                                cand_d = (
                                    (media_d > 0) & (free_d >= size) & (free_d > 0)
                                    & (taken_rows[d] == 0)
                                )
                                best_cap = jnp.where(
                                    cand_d,
                                    jnp.minimum(best_cap, devcap_ref[pl.ds(d, 1), :]),
                                    best_cap,
                                )
                            assigned = jnp.zeros((1, N), jnp.float32)
                            for d in range(n_dev):
                                free_d = dev_free_ref[pl.ds(d, 1), :]
                                media_d = media_ref[pl.ds(m * n_dev + d, 1), :]
                                cand_d = (
                                    (media_d > 0) & (free_d >= size) & (free_d > 0)
                                    & (taken_rows[d] == 0)
                                )
                                take_d = (
                                    cand_d & (devcap_ref[pl.ds(d, 1), :] == best_cap)
                                ).astype(jnp.float32) * (1.0 - jnp.minimum(assigned, 1.0))
                                take_d = take_d * jnp.where(size > 0, 1.0, 0.0)
                                assigned = assigned + take_d
                                taken_rows[d] = jnp.maximum(taken_rows[d], take_d)
                                dev_free_ref[pl.ds(d, 1), :] = free_d * (1.0 - take_d * onehot)
                if has_interpod:
                    a_col = col_of(s_antig) if big_u else jnp.dot(
                        antig_ref[:], onehot_u, preferred_element_type=jnp.float32
                    )
                    anti_node_ref[:] = anti_node_ref[:] + a_col * onehot
                    for zk in range(n_zkeys):
                        key_mask = (g_key_col == zk + 1).astype(jnp.float32)
                        anti_zone_ref[:] = (
                            anti_zone_ref[:] + a_col * key_mask * zrow_k[zk]
                        )
                    p_col = col_of(s_prefg) if big_u else jnp.dot(
                        prefg_ref[:], onehot_u, preferred_element_type=jnp.float32
                    )
                    prefw_node_ref[:] = prefw_node_ref[:] + p_col * onehot
                    for zk in range(n_zkeys):
                        key_mask = (p_key_col == zk + 1).astype(jnp.float32)
                        prefw_zone_ref[:] = (
                            prefw_zone_ref[:] + p_col * key_mask * zrow_k[zk]
                        )

            return 0

        jax.lax.fori_loop(0, tmpl_ref.shape[0], body, 0)
        used_out_ref[:] = used_ref[:]
        if has_gpu:
            gpu_out_ref[:] = gpu_free_ref[:]
        if has_local:
            vg_out_ref[:] = vg_free_ref[:]
            dev_out_ref[:] = dev_free_ref[:]

    return kernel


def run_fast_scan(
    fi: FastInputs,
    tmpl_ids,
    pod_valid,
    forced,
    has_interpod: bool,
    has_gpu: bool,
    has_local: bool = False,
    has_ports: bool = False,
    has_na: bool = False,
    has_tt: bool = False,
    has_avoid: bool = False,
    interpret: bool = False,
    big_u: bool = False,
    gc_row: int = -1,
):
    """Execute the megakernel. tmpl_ids/pod_valid/forced are [P] (P a
    multiple of CHUNK). Returns (chosen [P] i32, used_final [R, N],
    gpu_take [P, Gd], gpu_final [Gd, N], vg_final [Vg, N], dev_final [Dv, N]).

    `big_u` keeps the [U, N] / [X, U] template tables in HBM and DMAs one
    row/column per pod step into VMEM scratch — VMEM use then no longer
    scales with U, lifting the template cap (fastpath.applicable)."""
    P = tmpl_ids.shape[0]
    assert P % CHUNK == 0, P
    R, N = fi.alloc_T.shape
    A = fi.matches_AU.shape[0]
    K = fi.has_zone.shape[0]  # number of non-hostname topology keys (>= 1)
    Z = fi.zone_NZ.shape[2]
    G = fi.antig_GU.shape[0]
    Gp = fi.prefg_GU.shape[0]
    Gd = fi.gpu0_DN.shape[0]
    Vg = fi.vg0_VN.shape[0]
    Dv = fi.dev0_DN.shape[0]
    Hp = fi.port_HU.shape[0]
    grid = (P // CHUNK,)

    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    # big-U tables are pinned to HBM (not ANY): if Mosaic places an ANY
    # buffer in VMEM — which it does when the table happens to fit — the
    # per-step 1-row DMA slice violates the (8,128) VMEM tiling alignment
    # and the kernel fails to compile
    anyspace = lambda: pl.BlockSpec(memory_space=pltpu.HBM)
    stream = lambda: pl.BlockSpec((CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM)

    _I32 = {"tmpl", "valid", "forced", "pin", "spr_active", "spr_key", "spr_sel",
            "spr_hard", "at_active", "at_key", "at_sel", "an_active", "an_key",
            "an_sel", "pt_active", "pt_key", "pt_sel", "anti_g_key", "prefg_key"}
    # [X, U] tables whose big-U DMA copies an aligned 128-lane column block:
    # pad U to a 128 multiple so the block at (u // 128)·128 never overruns
    _COL_TABLES = {"matches_AU", "port_HU", "port_conf_HU",
                   "antig_GU", "gmatch_GU", "prefg_GU", "pmatch_GU"}
    # 2-D SMEM scalar tables are stored TRANSPOSED ([X, U], U minor): an
    # SMEM array's minor dim pads to 128 lanes, so the natural [U, X] layout
    # with X ≤ 8 would cost 128/X× the memory — fatal at big U (a [2048, 2]
    # table would pad to 1 MB, the whole SMEM)
    _SMEM_T = {"req", "spr_active", "spr_key", "spr_sel", "spr_skew",
               "spr_hard", "spr_self", "spr_weight",
               "at_active", "at_key", "at_sel", "at_self",
               "an_active", "an_key", "an_sel",
               "pt_active", "pt_key", "pt_sel", "pt_w",
               "dev_req", "dev_need", "dev_sizes"}
    layout = _input_layout(has_interpod, has_gpu, has_local, has_ports, has_na, has_tt, has_avoid, big_u)
    in_specs, args = [], []
    for name, kind in layout:
        if kind == "stream":
            in_specs.append(stream())
            src = {"tmpl": tmpl_ids, "valid": pod_valid, "forced": forced}[name]
        else:
            in_specs.append({"smem": smem, "vmem": vmem, "any": anyspace}[kind]())
            src = getattr(fi, name)
        arr = jnp.asarray(src, jnp.int32 if name in _I32 else jnp.float32)
        if name in _SMEM_T:
            arr = arr.T
        if big_u and name in _COL_TABLES:
            pad_u = (-arr.shape[1]) % 128
            if pad_u:
                arr = jnp.pad(arr, ((0, 0), (0, pad_u)))
        args.append(arr)

    # outputs: feature-gated, like the inputs. gpu_take is [Gd, P] (device
    # rows × pod lanes): an SMEM window's minor dim pads to 128 lanes, so the
    # natural [P, Gd] layout would burn 1 MB of the chip's 1 MB SMEM on
    # 8-lane rows — transposed, the window is [Gd, CHUNK] = 32 KB.
    out_shape = [jax.ShapeDtypeStruct((P,), jnp.int32),
                 jax.ShapeDtypeStruct((R, N), jnp.float32)]
    out_specs = [pl.BlockSpec((CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM),
                 pl.BlockSpec((R, N), lambda i: (0, 0), memory_space=pltpu.VMEM)]
    if has_gpu:
        out_shape += [jax.ShapeDtypeStruct((Gd, P), jnp.float32),
                      jax.ShapeDtypeStruct((Gd, N), jnp.float32)]
        out_specs += [pl.BlockSpec((Gd, CHUNK), lambda i: (0, i), memory_space=pltpu.SMEM),
                      pl.BlockSpec((Gd, N), lambda i: (0, 0), memory_space=pltpu.VMEM)]
    if has_local:
        out_shape += [jax.ShapeDtypeStruct((Vg, N), jnp.float32),
                      jax.ShapeDtypeStruct((Dv, N), jnp.float32)]
        out_specs += [pl.BlockSpec((Vg, N), lambda i: (0, 0), memory_space=pltpu.VMEM),
                      pl.BlockSpec((Dv, N), lambda i: (0, 0), memory_space=pltpu.VMEM)]

    scratch = [pltpu.VMEM((R, N), jnp.float32),
               pltpu.VMEM((A, N), jnp.float32),
               pltpu.VMEM((K * A, Z), jnp.float32)]
    if has_interpod:
        scratch += [pltpu.VMEM((G, N), jnp.float32),
                    pltpu.VMEM((G, Z), jnp.float32),
                    pltpu.VMEM((Gp, N), jnp.float32),
                    pltpu.VMEM((Gp, Z), jnp.float32)]
    if has_gpu:
        scratch += [pltpu.VMEM((Gd, N), jnp.float32)]
    if has_local:
        scratch += [pltpu.VMEM((Vg, N), jnp.float32),
                    pltpu.VMEM((Dv, N), jnp.float32)]
    if has_ports:
        scratch += [pltpu.VMEM((Hp, N), jnp.float32)]

    if big_u:
        # per-step scratch: rows [1, N] for the [U, N] tables, 128-lane
        # column blocks [X, 128] for the [X, U] tables — order must match
        # the kernel's _dma calls
        u_scratch = [pltpu.VMEM((1, N), jnp.float32)] * 3  # static, affm, shraw
        u_scratch.append(pltpu.VMEM((A, 128), jnp.float32))  # matches block
        if has_na:
            u_scratch.append(pltpu.VMEM((1, N), jnp.float32))
        if has_tt:
            u_scratch.append(pltpu.VMEM((1, N), jnp.float32))
        if has_avoid:
            u_scratch.append(pltpu.VMEM((1, N), jnp.float32))
        if has_ports:
            u_scratch += [pltpu.VMEM((Hp, 128), jnp.float32)] * 2
        if has_interpod:
            u_scratch += [
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((Gp, 128), jnp.float32),
                pltpu.VMEM((Gp, 128), jnp.float32),
            ]
        u_scratch.append(pltpu.SemaphoreType.DMA((len(u_scratch),)))
        scratch += u_scratch

    out = pl.pallas_call(
        _make_kernel(
            has_interpod, has_gpu, has_local, has_ports, has_na, has_tt, has_avoid,
            G, Gp, Gd, Vg, Dv, fi.dev_sizes.shape[1] // 2, big_u, K, gc_row,
        ),
        grid=grid,
        out_shape=tuple(out_shape),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)

    # normalize to the fixed 6-tuple callers expect: (chosen, used_T,
    # gpu_take [P, Gd], gpu_final, vg_final, dev_final) — absent features
    # report their initial state / zero takes
    res = list(out)
    chosen, used_T = res[0], res[1]
    idx = 2
    if has_gpu:
        gpu_take = res[idx].T
        gpu_T = res[idx + 1]
        idx += 2
    else:
        gpu_take = jnp.zeros((P, Gd), jnp.float32)
        gpu_T = jnp.asarray(fi.gpu0_DN, jnp.float32)
    if has_local:
        vg_T = res[idx]
        dev_T = res[idx + 1]
    else:
        vg_T = jnp.asarray(fi.vg0_VN, jnp.float32)
        dev_T = jnp.asarray(fi.dev0_DN, jnp.float32)
    return chosen, used_T, gpu_take, gpu_T, vg_T, dev_T
