"""Pallas megakernel for the bind scan (fast path).

The XLA scan pays ~5 µs of per-op overhead for each of the ~30 HLO ops in
a scheduling step. This kernel fuses the entire step — static-filter gather,
resource fit, Least/BalancedAllocation, Simon share, PodTopologySpread
(hard + soft), selectHost, and the bind state update — into ONE Pallas
program whose cluster state lives in VMEM for the whole scan: a bind costs
VMEM-bandwidth, not kernel launches.

Scope: workloads whose feature set is {resources, static filters, topology
spread} — i.e. `Features(ports=False, gpu=False, local=False,
interpod=False, prefg=False, ...)` with the default SchedulerConfig and at
most two topology keys (hostname + one zone-like key). Everything else
falls back to `engine.scheduler.schedule_pods`; `engine/fastpath.py` makes
the choice and guarantees identical placements (tests assert equality).

Layouts (N = padded node axis, lanes; rows padded to sublane multiples):
  alloc_T     [R, N]   f32   allocatable per resource row
  used        [R, N]   f32   scratch, persistent across the grid
  static_pass [U, N]   f32   0/1 from kernels.precompute_static
  aff_mask    [U, N]   f32   node-affinity mask (spread eligibility)
  share_raw   [U, N]   f32   Simon share × 100
  node_cnt    [A, N]   f32   scratch — per-hostname-domain selector counts
  zone_cnt    [A, Z]   f32   scratch — per-zone selector counts
  zone_NZ     [N, Z]   f32   node → zone one-hot
  zone_ZN     [Z, N]   f32   transpose (for the gather matvec)
  matches_AU  [A, U]   f32   selector-match matrix (column = template)
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..encoding import vocab as V

NEG = -1e30
MAX_SCORE = 100.0
# SMEM int32 streams tile at 1024 on current Mosaic; block shapes must match
CHUNK = 1024


class FastInputs(NamedTuple):
    """Host-prepared tensors for the kernel (see engine/fastpath.py)."""

    alloc_T: np.ndarray  # [R, N]
    used0_T: np.ndarray  # [R, N]
    static_pass: np.ndarray  # [U, N]
    aff_mask: np.ndarray  # [U, N]
    share_raw: np.ndarray  # [U, N]
    share_const: np.ndarray  # [U] 1.0 where the template has no requests (score = Max everywhere)
    zone_NZ: np.ndarray  # [N, Z]
    zone_ZN: np.ndarray  # [Z, N]
    has_zone: np.ndarray  # [1, N] f32
    matches_AU: np.ndarray  # [A, U]
    node_valid: np.ndarray  # [1, N] f32
    # SMEM scalar tables
    req: np.ndarray  # [U, R] f32
    cpu_nz: np.ndarray  # [U] f32 nonzero-default cpu (milli)
    mem_nz: np.ndarray  # [U] f32 nonzero-default memory
    pin: np.ndarray  # [U] i32
    # spread constraints, [U, Cs] each
    spr_active: np.ndarray  # i32 0/1
    spr_hostname: np.ndarray  # i32 1 = hostname topology
    spr_sel: np.ndarray  # i32 selector id
    spr_skew: np.ndarray  # f32
    spr_hard: np.ndarray  # i32 0/1
    spr_self: np.ndarray  # f32 0/1 template matches own selector
    spr_weight: np.ndarray  # f32 log(size+2)


def _kernel(
    # scalar-prefetch / SMEM inputs
    tmpl_ref,  # [CHUNK] i32
    valid_ref,  # [CHUNK] i32
    forced_ref,  # [CHUNK] i32
    req_ref,  # [U, R] f32 SMEM
    cpu_nz_ref,  # [U] f32 SMEM
    mem_nz_ref,  # [U] f32 SMEM
    pin_ref,  # [U] i32 SMEM
    sa_ref, sh_ref, ss_ref, sk_ref, shard_ref, sself_ref, sw_ref,  # [U, Cs] SMEM
    share_const_ref,  # [U] f32 SMEM
    # VMEM inputs
    alloc_ref,  # [R, N]
    used0_ref,  # [R, N]
    static_ref,  # [U, N]
    affm_ref,  # [U, N]
    shraw_ref,  # [U, N]
    zone_nz_ref,  # [N, Z]
    zone_zn_ref,  # [Z, N]
    has_zone_ref,  # [1, N]
    matches_ref,  # [A, U]
    nodevalid_ref,  # [1, N]
    # outputs
    chosen_ref,  # [CHUNK] i32 SMEM
    used_out_ref,  # [R, N] VMEM
    # scratch
    used_ref,  # [R, N]
    node_cnt_ref,  # [A, N]
    zone_cnt_ref,  # [A, Z]
):
    R, N = alloc_ref.shape
    U = static_ref.shape[0]
    A = node_cnt_ref.shape[0]
    Z = zone_cnt_ref.shape[1]
    Cs = sa_ref.shape[1]

    @pl.when(pl.program_id(0) == 0)
    def _init():
        used_ref[:] = used0_ref[:]
        node_cnt_ref[:] = jnp.zeros_like(node_cnt_ref)
        zone_cnt_ref[:] = jnp.zeros_like(zone_cnt_ref)

    iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    iota_u = jax.lax.broadcasted_iota(jnp.int32, (U, 1), 0)
    valid_row = nodevalid_ref[:]  # [1, N]

    def body(i, _):
        u = tmpl_ref[i]

        static_row = static_ref[pl.ds(u, 1), :]  # [1, N] (valid folded in)

        # --- NodeResourcesFit
        fit = jnp.ones((1, N), jnp.float32)
        for r in range(R):
            req_r = req_ref[u, r]
            over = (used_ref[pl.ds(r, 1), :] + req_r > alloc_ref[pl.ds(r, 1), :]).astype(jnp.float32)
            fit = fit * jnp.where(req_r > 0, 1.0 - over, 1.0)

        feasible = static_row * fit  # [1, N] f32 mask

        # --- PodTopologySpread + scores that need per-constraint counts
        aff_row = affm_ref[pl.ds(u, 1), :] * valid_row  # eligibility for min
        soft_raw = jnp.zeros((1, N), jnp.float32)
        ignored = jnp.zeros((1, N), jnp.float32)  # feasible nodes missing a soft topo label
        any_soft = jnp.float32(0.0)
        for c in range(Cs):
            active = sa_ref[u, c]
            is_host = sh_ref[u, c]
            sel = ss_ref[u, c]
            skew = sk_ref[u, c]
            hard = shard_ref[u, c]
            selfm = sself_ref[u, c]
            weight = sw_ref[u, c]

            host_cnt = node_cnt_ref[pl.ds(sel, 1), :]  # [1, N]
            zrow = zone_cnt_ref[pl.ds(sel, 1), :]  # [1, Z]
            zone_gather = jnp.dot(
                zrow, zone_zn_ref[:], preferred_element_type=jnp.float32
            )  # [1, N]
            cnt = jnp.where(is_host == 1, host_cnt, zone_gather)
            has_label = jnp.where(is_host == 1, jnp.ones((1, N), jnp.float32), has_zone_ref[:])

            activef = (active == 1)
            hardf = activef & (hard == 1)
            softf = activef & (hard == 0)

            # hard constraint: cnt + self - min(eligible) <= skew
            elig = aff_row * has_label
            masked = jnp.where(elig > 0, cnt, jnp.float32(1e30))
            min_cnt = jnp.min(masked)
            ok = (cnt + selfm - min_cnt <= skew) & (has_label > 0)
            feasible = jnp.where(hardf, feasible * ok.astype(jnp.float32), feasible)

            # soft constraint: raw score contribution
            contrib = jnp.where(has_label > 0, cnt * weight + (skew - 1.0), 0.0)
            soft_raw = soft_raw + jnp.where(softf, contrib, 0.0)
            ignored = jnp.maximum(
                ignored, jnp.where(softf, (1.0 - has_label), 0.0)
            )
            any_soft = jnp.maximum(any_soft, jnp.where(softf, 1.0, 0.0))

        # --- scores
        cpu_req = cpu_nz_ref[u]
        mem_req = mem_nz_ref[u]
        alloc_cpu = alloc_ref[pl.ds(V.RES_CPU, 1), :]
        alloc_mem = alloc_ref[pl.ds(V.RES_MEMORY, 1), :]
        used_cpu = used_ref[pl.ds(V.RES_CPU, 1), :] + cpu_req
        used_mem = used_ref[pl.ds(V.RES_MEMORY, 1), :] + mem_req
        l_cpu = jnp.where(
            (alloc_cpu == 0) | (used_cpu > alloc_cpu),
            0.0,
            (alloc_cpu - used_cpu) * MAX_SCORE / jnp.maximum(alloc_cpu, 1.0),
        )
        l_mem = jnp.where(
            (alloc_mem == 0) | (used_mem > alloc_mem),
            0.0,
            (alloc_mem - used_mem) * MAX_SCORE / jnp.maximum(alloc_mem, 1.0),
        )
        least = (l_cpu + l_mem) / 2.0
        cpu_frac = used_cpu / jnp.maximum(alloc_cpu, 1.0)
        mem_frac = used_mem / jnp.maximum(alloc_mem, 1.0)
        balanced = jnp.where(
            (cpu_frac >= 1.0) | (mem_frac >= 1.0),
            0.0,
            (1.0 - jnp.abs(cpu_frac - mem_frac)) * MAX_SCORE,
        )

        share_row = shraw_ref[pl.ds(u, 1), :]
        share_row = jnp.where(share_const_ref[u] > 0, jnp.full((1, N), MAX_SCORE), share_row)
        feas_b = feasible > 0
        lo = jnp.min(jnp.where(feas_b, share_row, jnp.float32(1e30)))
        hi = jnp.max(jnp.where(feas_b, share_row, jnp.float32(-1e30)))
        rng = hi - lo
        share_norm = jnp.where(rng > 0, (share_row - lo) * MAX_SCORE / rng, 0.0)

        scored = feas_b & (ignored == 0)
        smn = jnp.min(jnp.where(scored, soft_raw, jnp.float32(1e30)))
        smx = jnp.max(jnp.where(scored, soft_raw, jnp.float32(-1e30)))
        spread_norm = jnp.where(
            smx <= 0, MAX_SCORE, MAX_SCORE * (smx + smn - soft_raw) / jnp.maximum(smx, 1.0)
        )
        spread_norm = jnp.where(ignored > 0, 0.0, spread_norm)
        spread_norm = jnp.where(any_soft > 0, spread_norm, 0.0)

        score = least + balanced + 2.0 * share_norm + 2.0 * spread_norm

        # --- selectHost: lowest index among maxima — Mosaic's argmax breaks
        # ties by HIGHEST index, diverging from the XLA scan's first-max
        masked_score = jnp.where(feas_b, score, jnp.float32(NEG))
        mx_score = jnp.max(masked_score)
        best = jnp.min(jnp.where(masked_score == mx_score, iota_n, N)).astype(jnp.int32)
        any_feasible = jnp.max(feasible) > 0
        sel_choice = jnp.where(any_feasible, best, jnp.int32(-1))
        is_forced = forced_ref[i] == 1
        pin_u = pin_ref[u]
        choice = jnp.where(is_forced, jnp.where(pin_u >= 0, pin_u, -1), sel_choice)
        do_bind = (valid_ref[i] == 1) & (choice >= 0)
        choice_out = jnp.where(do_bind, choice, -1)
        chosen_ref[i] = choice_out

        # --- bind update
        @pl.when(do_bind)
        def _bind():
            c = jnp.maximum(choice, 0)
            onehot = (iota_n == c).astype(jnp.float32)  # [1, N]
            iota_r = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)
            req_col = jnp.zeros((R, 1), jnp.float32)
            for r in range(R):  # static unroll; .at[] would lower to scatter
                req_col = jnp.where(iota_r == r, req_ref[u, r], req_col)
            used_ref[:] = used_ref[:] + req_col * onehot

            # matches column u via one-hot matvec: [A, U] @ [U, 1]
            onehot_u = (iota_u == u).astype(jnp.float32)  # [U, 1]
            m_col = jnp.dot(matches_ref[:], onehot_u, preferred_element_type=jnp.float32)  # [A, 1]
            node_cnt_ref[:] = node_cnt_ref[:] + m_col * onehot
            zrow_c = zone_nz_ref[pl.ds(c, 1), :]  # [1, Z]
            zone_cnt_ref[:] = zone_cnt_ref[:] + m_col * zrow_c

        return 0

    jax.lax.fori_loop(0, tmpl_ref.shape[0], body, 0)
    used_out_ref[:] = used_ref[:]


def run_fast_scan(fi: FastInputs, tmpl_ids, pod_valid, forced, interpret: bool = False):
    """Execute the megakernel. tmpl_ids/pod_valid/forced are [P] (P a
    multiple of CHUNK). Returns (chosen [P] i32, used_final [R, N])."""
    P = tmpl_ids.shape[0]
    assert P % CHUNK == 0, P
    R, N = fi.alloc_T.shape
    grid = (P // CHUNK,)

    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        _kernel,
        grid=grid,
        out_shape=(
            jax.ShapeDtypeStruct((P,), jnp.int32),
            jax.ShapeDtypeStruct((R, N), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec((CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM),  # tmpl
            pl.BlockSpec((CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM),  # valid
            pl.BlockSpec((CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM),  # forced
            smem(),  # req
            smem(),  # cpu_nz
            smem(),  # mem_nz
            smem(),  # pin
            smem(), smem(), smem(), smem(), smem(), smem(), smem(),  # spread tables
            smem(),  # share_const
            vmem(),  # alloc
            vmem(),  # used0
            vmem(),  # static
            vmem(),  # aff
            vmem(),  # share_raw
            vmem(),  # zone_NZ
            vmem(),  # zone_ZN
            vmem(),  # has_zone
            vmem(),  # matches
            vmem(),  # node_valid
        ],
        out_specs=(
            pl.BlockSpec((CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((R, N), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((R, N), jnp.float32),
            pltpu.VMEM(fi.matches_AU.shape[:1] + (N,), jnp.float32),
            pltpu.VMEM(fi.matches_AU.shape[:1] + (fi.zone_NZ.shape[1],), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray(tmpl_ids, jnp.int32),
        jnp.asarray(pod_valid, jnp.int32),
        jnp.asarray(forced, jnp.int32),
        jnp.asarray(fi.req, jnp.float32),
        jnp.asarray(fi.cpu_nz, jnp.float32),
        jnp.asarray(fi.mem_nz, jnp.float32),
        jnp.asarray(fi.pin, jnp.int32),
        jnp.asarray(fi.spr_active, jnp.int32),
        jnp.asarray(fi.spr_hostname, jnp.int32),
        jnp.asarray(fi.spr_sel, jnp.int32),
        jnp.asarray(fi.spr_skew, jnp.float32),
        jnp.asarray(fi.spr_hard, jnp.int32),
        jnp.asarray(fi.spr_self, jnp.float32),
        jnp.asarray(fi.spr_weight, jnp.float32),
        jnp.asarray(fi.share_const, jnp.float32),
        jnp.asarray(fi.alloc_T, jnp.float32),
        jnp.asarray(fi.used0_T, jnp.float32),
        jnp.asarray(fi.static_pass, jnp.float32),
        jnp.asarray(fi.aff_mask, jnp.float32),
        jnp.asarray(fi.share_raw, jnp.float32),
        jnp.asarray(fi.zone_NZ, jnp.float32),
        jnp.asarray(fi.zone_ZN, jnp.float32),
        jnp.asarray(fi.has_zone, jnp.float32),
        jnp.asarray(fi.matches_AU, jnp.float32),
        jnp.asarray(fi.node_valid, jnp.float32),
    )
    return out


run_fast_scan_jit = jax.jit(run_fast_scan, static_argnames=("interpret",))
