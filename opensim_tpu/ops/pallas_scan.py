"""Pallas megakernel for the bind scan (fast path).

The XLA scan pays ~5 µs of per-op overhead for each of the ~30 HLO ops in
a scheduling step. This kernel fuses the entire step — static-filter gather,
resource fit, Least/BalancedAllocation, Simon share, PodTopologySpread
(hard + soft), inter-pod affinity (required / anti / preferred, incoming and
symmetric), selectHost, and the bind state update — into ONE Pallas program
whose cluster state lives in VMEM for the whole scan: a bind costs
VMEM-bandwidth, not kernel launches.

Scope: every scheduler feature — resource fit, topology spread, inter-pod
affinity, GPU-share devices, open-local storage, host ports, preferred node
affinity and PreferNoSchedule scoring — bounded by table-size caps and at
most three topology keys (hostname + two zone-like keys, stacked per-key
count blocks); `engine/fastpath.py`
gates applicability and guarantees identical placements to the XLA scan
(tests + randomized differential fuzzing assert equality). Past 512
templates the kernel switches to big-U mode: the [U, N]/[X, U] template
tables stay in HBM and each pod step DMAs its row/column into VMEM scratch,
so VMEM no longer scales with U (cap 2048, bounded by SMEM scalars). The kernel is
generated per feature-flag combination so absent features cost nothing, and
node validity is a runtime row so scenario sweeps re-dispatch with nothing
but a new mask and spread-weight table.

Layouts (N = padded node axis, lanes; rows padded to sublane multiples):
  alloc_T     [R, N]    f32  allocatable per resource row
  used        [R, N]    f32  scratch, persistent across the grid
  static_pass [U, N]    f32  0/1 from kernels.precompute_static
  node_cnt    [A, N]    f32  scratch — per-hostname-domain selector counts
  zone_cnt    [K*A, Z]  f32  scratch — per-(zone-key, selector) counts
  anti_node   [G, N]    f32  scratch — existing-pod anti-affinity terms
  prefw_node  [Gp, N]   f32  scratch — symmetric preferred-term weights
  matches_AU  [A, U]    f32  selector-match matrix (column = template)
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..encoding import vocab as V

NEG = -1e30
MAX_SCORE = 100.0
# SMEM int32 streams tile at 1024 on current Mosaic; block shapes must match
CHUNK = 1024


class FastInputs(NamedTuple):
    """Host-prepared tensors for the kernel (see engine/fastpath.py)."""

    alloc_T: np.ndarray  # [R, N]
    used0_T: np.ndarray  # [R, N]
    static_pass: np.ndarray  # [U, N]
    aff_mask: np.ndarray  # [U, N]
    share_raw: np.ndarray  # [U, N]
    zone_NZ: np.ndarray  # [N, K*Z] — per-zone-key one-hot blocks
    zone_ZN: np.ndarray  # [K*Z, N]
    has_zone: np.ndarray  # [K, N] f32 — node has key k's label
    matches_AU: np.ndarray  # [A, U]
    node_valid: np.ndarray  # [1, N] f32
    # SMEM scalar tables
    req: np.ndarray  # [U, R] f32
    cpu_nz: np.ndarray  # [U] f32 nonzero-default cpu (milli)
    mem_nz: np.ndarray  # [U] f32 nonzero-default memory
    pin: np.ndarray  # [U] i32
    # spread constraints, [U, Cs] each
    spr_active: np.ndarray  # i32 0/1
    spr_key: np.ndarray  # i32 topology key index: 0 = hostname, 1..K = zone keys
    spr_sel: np.ndarray  # i32 selector id
    spr_skew: np.ndarray  # f32
    spr_hard: np.ndarray  # i32 0/1
    spr_self: np.ndarray  # f32 0/1 template matches own selector
    spr_weight: np.ndarray  # f32 log(size+2)
    # inter-pod affinity (all zero-shaped semantics when has_interpod=False)
    at_active: np.ndarray  # [U, Ti] i32 — incoming required affinity terms
    at_key: np.ndarray  # [U, Ti] i32 key index (0 = hostname, 1..K = zone)
    at_sel: np.ndarray  # [U, Ti] i32
    at_self: np.ndarray  # [U, Ti] f32 — bootstrap self-match
    an_active: np.ndarray  # [U, Tn] i32 — incoming anti terms
    an_key: np.ndarray  # [U, Tn] i32
    an_sel: np.ndarray  # [U, Tn] i32
    pt_active: np.ndarray  # [U, Tp] i32 — incoming preferred terms
    pt_key: np.ndarray  # [U, Tp] i32
    pt_sel: np.ndarray  # [U, Tp] i32
    pt_w: np.ndarray  # [U, Tp] f32 signed weights
    anti_g_key: np.ndarray  # [G] i32 — global existing-anti term key indices
    prefg_key: np.ndarray  # [Gp] i32 — global symmetric-preferred term key indices
    antig_GU: np.ndarray  # [G, U] f32 — template carries term g
    gmatch_GU: np.ndarray  # [G, U] f32 — template matches term g's selector
    prefg_GU: np.ndarray  # [Gp, U] f32 — carried symmetric weights
    pmatch_GU: np.ndarray  # [Gp, U] f32 — template matches pref term's selector
    # gpu-share (zero-shaped semantics when has_gpu=False)
    gpu_mem: np.ndarray  # [U] f32 per-GPU memory request
    gpu_cnt: np.ndarray  # [U] f32 requested GPU count
    gpu0_DN: np.ndarray  # [Gd, N] f32 initial per-device free memory
    # open-local storage (inert when has_local=False)
    lvm_req: np.ndarray  # [U] f32 total LVM bytes
    dev_req: np.ndarray  # [U, 2] f32 exclusive-device max size by media (score)
    dev_need: np.ndarray  # [U, 2] f32 device count by media
    dev_sizes: np.ndarray  # [U, 2*Mv] f32 per-volume sizes desc (ssd rows then hdd)
    vg_cap_VN: np.ndarray  # [Vg, N] f32 VG capacities
    vg0_VN: np.ndarray  # [Vg, N] f32 initial VG free
    dev_cap_DN: np.ndarray  # [Dv, N] f32 device capacities
    dev0_DN: np.ndarray  # [Dv, N] f32 initial device free
    dev_media_DN: np.ndarray  # [2*Dv, N] f32 media one-hots (ssd rows then hdd rows)
    # host ports (inert when has_ports=False)
    port_HU: np.ndarray  # [Hp, U] f32 — template uses port row h (bind marks)
    port_conf_HU: np.ndarray  # [Hp, U] f32 — template conflicts with row h (filter)
    # static score tables (inert when the matching feature flag is off)
    na_raw: np.ndarray  # [U, N] f32 preferred-node-affinity weights
    tt_raw: np.ndarray  # [U, N] f32 intolerable PreferNoSchedule counts


def _make_kernel(
    has_interpod: bool,
    has_gpu: bool,
    has_local: bool,
    has_ports: bool,
    has_na: bool,
    has_tt: bool,
    n_anti: int,
    n_pref: int,
    n_gpu: int,
    n_vg: int,
    n_dev: int,
    n_dvol: int,
    big_u: bool = False,
    n_zkeys: int = 1,
):
    def kernel(
        # SMEM streams + tables
        tmpl_ref, valid_ref, forced_ref,
        req_ref, cpu_nz_ref, mem_nz_ref, pin_ref,
        sa_ref, sh_ref, ss_ref, sk_ref, shard_ref, sself_ref, sw_ref,
        ata_ref, ath_ref, ats_ref, atf_ref,
        ana_ref, anh_ref, ans_ref,
        pta_ref, pth_ref, pts_ref, ptw_ref,
        agh_ref, pgh_ref,
        gmem_ref, gcnt_ref,
        lvm_ref, dreq_ref, dneed_ref, dsz_ref,
        # VMEM inputs
        alloc_ref, used0_ref, static_ref, affm_ref, shraw_ref,
        zone_nz_ref, zone_zn_ref, has_zone_ref, matches_ref, nodevalid_ref,
        antig_ref, gmatch_ref, prefg_ref, pmatch_ref, gpu0_ref,
        vgcap_ref, vg0_ref, devcap_ref, dev0_ref, media_ref,
        port_hu_ref, port_conf_hu_ref, na_ref, tt_ref,
        # outputs
        chosen_ref, used_out_ref, gpu_take_ref, gpu_out_ref, vg_out_ref, dev_out_ref,
        # scratch
        used_ref, node_cnt_ref, zone_cnt_ref,
        anti_node_ref, anti_zone_ref, prefw_node_ref, prefw_zone_ref,
        gpu_free_ref, vg_free_ref, dev_free_ref, port_used_ref,
        # big-U mode appends per-step row/column scratches + DMA semaphores
        *u_scratch,
    ):
        R, N = alloc_ref.shape
        U = static_ref.shape[0]
        Cs = sa_ref.shape[1]
        Ti = ata_ref.shape[1]
        Tn = ana_ref.shape[1]
        Tp = pta_ref.shape[1]

        @pl.when(pl.program_id(0) == 0)
        def _init():
            used_ref[:] = used0_ref[:]
            node_cnt_ref[:] = jnp.zeros_like(node_cnt_ref)
            zone_cnt_ref[:] = jnp.zeros_like(zone_cnt_ref)
            anti_node_ref[:] = jnp.zeros_like(anti_node_ref)
            anti_zone_ref[:] = jnp.zeros_like(anti_zone_ref)
            prefw_node_ref[:] = jnp.zeros_like(prefw_node_ref)
            prefw_zone_ref[:] = jnp.zeros_like(prefw_zone_ref)
            gpu_free_ref[:] = gpu0_ref[:]
            vg_free_ref[:] = vg0_ref[:]
            dev_free_ref[:] = dev0_ref[:]
            port_used_ref[:] = jnp.zeros_like(port_used_ref)

        iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        iota_u = jax.lax.broadcasted_iota(jnp.int32, (U, 1), 0)
        valid_row = nodevalid_ref[:]  # [1, N]
        ones_1n = jnp.ones((1, N), jnp.float32)

        A_rows = node_cnt_ref.shape[0]
        Zk = zone_zn_ref.shape[0] // n_zkeys

        def _flag_row(flag_ref, n_rows):
            """Expand an SMEM int-flag table into a [1, n_rows] f32 vector
            (loop-invariant: built once, outside the pod loop)."""
            row = jnp.zeros((1, n_rows), jnp.float32)
            r_iota = jax.lax.broadcasted_iota(jnp.int32, (1, n_rows), 1)
            for g in range(n_rows):
                row = jnp.where(r_iota == g, jnp.float32(flag_ref[g]), row)
            return row

        def _flag_col(flag_ref, n_rows):
            col = jnp.zeros((n_rows, 1), jnp.float32)
            c_iota = jax.lax.broadcasted_iota(jnp.int32, (n_rows, 1), 0)
            for g in range(n_rows):
                col = jnp.where(c_iota == g, jnp.float32(flag_ref[g]), col)
            return col

        if has_interpod:
            g_key_row = _flag_row(agh_ref, n_anti)
            p_key_row = _flag_row(pgh_ref, n_pref)
            g_key_col = _flag_col(agh_ref, n_anti)
            p_key_col = _flag_col(pgh_ref, n_pref)

        def sel_cnt(sel, key):
            """Count of bound pods matching selector `sel` in the candidate
            node's domain under topology key index `key` (0 = hostname,
            1..K = zone keys; zone counts live in per-key row blocks)."""
            host_cnt = node_cnt_ref[pl.ds(sel, 1), :]  # [1, N]
            k = jnp.maximum(key - 1, 0)
            zrow = zone_cnt_ref[pl.ds(k * A_rows + sel, 1), :]  # [1, Zk]
            zone_gather = jnp.dot(
                zrow, zone_zn_ref[pl.ds(k * Zk, Zk), :], preferred_element_type=jnp.float32
            )
            has = has_zone_ref[pl.ds(k, 1), :]
            return jnp.where(key == 0, host_cnt, zone_gather), jnp.where(
                key == 0, ones_1n, has
            )

        def body(i, _):
            u = tmpl_ref[i]
            if big_u:
                # template tables live in HBM (ANY space): DMA this step's
                # row (for [U, N] tables) / column (for [X, U] tables) into
                # VMEM scratch — all copies in flight together, one wait.
                # VMEM stays independent of U; only SMEM scalars scale.
                sems = u_scratch[-1]
                bufs = list(u_scratch[:-1])
                dma_state = {"k": 0}
                copies = []

                def _dma(ref, col):
                    k = dma_state["k"]
                    dma_state["k"] = k + 1
                    scratch = bufs[k]
                    src = ref.at[:, pl.ds(u, 1)] if col else ref.at[pl.ds(u, 1)]
                    cp = pltpu.make_async_copy(src, scratch, sems.at[k])
                    cp.start()
                    copies.append(cp)
                    return scratch

                s_static = _dma(static_ref, False)
                s_aff = _dma(affm_ref, False)
                s_share = _dma(shraw_ref, False)
                s_match = _dma(matches_ref, True)
                s_na = _dma(na_ref, False) if has_na else None
                s_tt = _dma(tt_ref, False) if has_tt else None
                if has_ports:
                    s_port = _dma(port_hu_ref, True)
                    s_portc = _dma(port_conf_hu_ref, True)
                if has_interpod:
                    s_antig = _dma(antig_ref, True)
                    s_gmatch = _dma(gmatch_ref, True)
                    s_prefg = _dma(prefg_ref, True)
                    s_pmatch = _dma(pmatch_ref, True)
                for cp in copies:
                    cp.wait()
                static_row = s_static[:]
            else:
                static_row = static_ref[pl.ds(u, 1), :]  # [1, N] (validity applied separately)
            for d in range(n_gpu):  # SMEM outputs have no default value
                gpu_take_ref[i, d] = jnp.float32(0.0)

            # --- NodeResourcesFit
            fit = ones_1n
            for r in range(R):
                req_r = req_ref[u, r]
                over = (used_ref[pl.ds(r, 1), :] + req_r > alloc_ref[pl.ds(r, 1), :]).astype(jnp.float32)
                fit = fit * jnp.where(req_r > 0, 1.0 - over, 1.0)
            # node validity is a runtime row (NOT folded into static_pass) so
            # scenario sweeps can vary it without re-marshalling the tables
            feasible = static_row * fit * valid_row

            if has_ports:
                # NodePorts: any CONFLICTING port already used on the node
                # (wildcard-expanded template rows via one-hot matvec, or the
                # DMA'd column in big-U mode)
                if big_u:
                    my_ports = s_portc[:]  # [Hp, 1]
                else:
                    onehot_u_p = (iota_u == u).astype(jnp.float32)
                    my_ports = jnp.dot(
                        port_conf_hu_ref[:], onehot_u_p, preferred_element_type=jnp.float32
                    )  # [Hp, 1]
                conflicts = jnp.dot(
                    my_ports.reshape(1, -1),
                    (port_used_ref[:] > 0).astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )  # [1, N]
                feasible = feasible * (conflicts == 0).astype(jnp.float32)

            if has_gpu:
                # Open-Gpu-Share filter: sum_d floor(free_d / mem) >= count
                gmem = gmem_ref[u]
                gcnt = gcnt_ref[u]
                chunks_sum = jnp.zeros((1, N), jnp.float32)
                for d in range(n_gpu):
                    chunks_sum = chunks_sum + jnp.floor(
                        gpu_free_ref[pl.ds(d, 1), :] / jnp.maximum(gmem, 1.0)
                    )
                gpu_ok = ((chunks_sum >= gcnt) & (gcnt > 0)).astype(jnp.float32)
                feasible = jnp.where(gmem > 0, feasible * gpu_ok, feasible)

            if has_local:
                # Open-Local filter: LVM fits the best VG; enough exclusive
                # devices of each media type
                lvm = lvm_ref[u]
                best_vg_free = jnp.full((1, N), -1e30, jnp.float32)
                for v in range(n_vg):
                    best_vg_free = jnp.maximum(best_vg_free, vg_free_ref[pl.ds(v, 1), :])
                feasible = jnp.where(
                    lvm > 0, feasible * (best_vg_free >= lvm).astype(jnp.float32), feasible
                )
                # one-device-per-volume matching: the i-th largest volume
                # needs ≥ i+1 free fitting devices (common.go:290-349)
                for m in range(2):
                    for vi in range(n_dvol):
                        size = dsz_ref[u, m * n_dvol + vi]
                        cnt_fit = jnp.zeros((1, N), jnp.float32)
                        for d in range(n_dev):
                            free_d = dev_free_ref[pl.ds(d, 1), :]
                            media_d = media_ref[pl.ds(m * n_dev + d, 1), :]
                            cnt_fit = cnt_fit + media_d * ((free_d >= size) & (free_d > 0)).astype(jnp.float32)
                        feasible = jnp.where(
                            size > 0, feasible * (cnt_fit >= (vi + 1)).astype(jnp.float32), feasible
                        )

            # --- PodTopologySpread
            aff_row = (s_aff[:] if big_u else affm_ref[pl.ds(u, 1), :]) * valid_row
            soft_raw = jnp.zeros((1, N), jnp.float32)
            ignored = jnp.zeros((1, N), jnp.float32)
            any_soft = jnp.float32(0.0)
            for c in range(Cs):
                active = sa_ref[u, c]
                skew = sk_ref[u, c]
                cnt, has_label = sel_cnt(ss_ref[u, c], sh_ref[u, c])
                activef = active == 1
                hardf = activef & (shard_ref[u, c] == 1)
                softf = activef & (shard_ref[u, c] == 0)

                elig = aff_row * has_label
                masked = jnp.where(elig > 0, cnt, jnp.float32(1e30))
                min_cnt = jnp.min(masked)
                ok = (cnt + sself_ref[u, c] - min_cnt <= skew) & (has_label > 0)
                feasible = jnp.where(hardf, feasible * ok.astype(jnp.float32), feasible)

                contrib = jnp.where(has_label > 0, cnt * sw_ref[u, c] + (skew - 1.0), 0.0)
                soft_raw = soft_raw + jnp.where(softf, contrib, 0.0)
                ignored = jnp.maximum(ignored, jnp.where(softf, 1.0 - has_label, 0.0))
                any_soft = jnp.maximum(any_soft, jnp.where(softf, 1.0, 0.0))

            ip_raw = jnp.zeros((1, N), jnp.float32)
            if has_interpod:
                if not big_u:
                    onehot_u_col = (iota_u == u).astype(jnp.float32)  # [U, 1]
                # incoming required anti-affinity: no matching pod in domain
                for t in range(Tn):
                    cnt, has_label = sel_cnt(ans_ref[u, t], anh_ref[u, t])
                    violated = (cnt > 0) & (has_label > 0)
                    feasible = jnp.where(
                        ana_ref[u, t] == 1, feasible * (1.0 - violated.astype(jnp.float32)), feasible
                    )
                # incoming required affinity: counts use the all-terms
                # conjunction selector (filtering.go:113-127). A node passes
                # when every term's topology label exists and every term's
                # domain count is positive, or via the bootstrap — global
                # count map empty AND full self-match AND labels present
                # (satisfyPodAffinity, filtering.go:347-374).
                at_all_ok = jnp.ones((1, N), jnp.float32)
                at_labels_ok = jnp.ones((1, N), jnp.float32)
                at_map_total = jnp.float32(0.0)
                at_self_all = jnp.float32(1.0)
                for t in range(Ti):
                    cnt, has_label = sel_cnt(ats_ref[u, t], ath_ref[u, t])
                    total_host = jnp.sum(node_cnt_ref[pl.ds(ats_ref[u, t], 1), :])
                    at_k = jnp.maximum(ath_ref[u, t] - 1, 0)
                    total_zone = jnp.sum(
                        zone_cnt_ref[pl.ds(at_k * A_rows + ats_ref[u, t], 1), :]
                    )
                    total = jnp.where(ath_ref[u, t] == 0, total_host, total_zone)
                    activef = ata_ref[u, t] == 1
                    term_ok = ((cnt > 0) & (has_label > 0)).astype(jnp.float32)
                    at_all_ok = jnp.where(activef, at_all_ok * term_ok, at_all_ok)
                    at_labels_ok = jnp.where(
                        activef, at_labels_ok * (has_label > 0).astype(jnp.float32), at_labels_ok
                    )
                    at_map_total = at_map_total + jnp.where(activef, total, 0.0)
                    at_self_all = at_self_all * jnp.where(
                        activef, (atf_ref[u, t] > 0).astype(jnp.float32), 1.0
                    )
                at_bootstrap = ((at_map_total == 0.0) & (at_self_all > 0)).astype(jnp.float32)
                feasible = feasible * jnp.maximum(at_all_ok, at_labels_ok * at_bootstrap)
                # symmetric: existing pods' anti terms vs the incoming pod.
                # counts are non-negative, so "any matching term has pods in
                # my domain" == "match-weighted count sum > 0" — three dots
                # instead of per-term loops. Host-key domains always have
                # the label (applicable() enforces hostname-identity); zone
                # gathers give 0 on label-less nodes via the one-hot.
                if big_u:
                    my_gmatch = s_gmatch[:]
                else:
                    my_gmatch = jnp.dot(gmatch_ref[:], onehot_u_col, preferred_element_type=jnp.float32)
                m_row = my_gmatch.reshape(1, n_anti)
                m_host = m_row * (g_key_row == 0).astype(jnp.float32)
                sym_cnt = jnp.dot(m_host, anti_node_ref[:], preferred_element_type=jnp.float32)
                for zk in range(n_zkeys):
                    m_k = m_row * (g_key_row == zk + 1).astype(jnp.float32)
                    sym_cnt = sym_cnt + jnp.dot(
                        jnp.dot(m_k, anti_zone_ref[:], preferred_element_type=jnp.float32),
                        zone_zn_ref[pl.ds(zk * Zk, Zk), :],
                        preferred_element_type=jnp.float32,
                    )
                feasible = feasible * (1.0 - (sym_cnt > 0).astype(jnp.float32))
                # score: incoming preferred terms
                for t in range(Tp):
                    cnt, has_label = sel_cnt(pts_ref[u, t], pth_ref[u, t])
                    ip_raw = ip_raw + jnp.where(
                        pta_ref[u, t] == 1, cnt * ptw_ref[u, t] * has_label, 0.0
                    )
                # score: symmetric preferred/hard-affinity weights — same
                # three-dot contraction over the term axis
                if big_u:
                    my_pmatch = s_pmatch[:]
                else:
                    my_pmatch = jnp.dot(pmatch_ref[:], onehot_u_col, preferred_element_type=jnp.float32)
                pm_row = my_pmatch.reshape(1, n_pref)
                pm_host = pm_row * (p_key_row == 0).astype(jnp.float32)
                ip_raw = ip_raw + jnp.dot(pm_host, prefw_node_ref[:], preferred_element_type=jnp.float32)
                for zk in range(n_zkeys):
                    pm_k = pm_row * (p_key_row == zk + 1).astype(jnp.float32)
                    ip_raw = ip_raw + jnp.dot(
                        jnp.dot(pm_k, prefw_zone_ref[:], preferred_element_type=jnp.float32),
                        zone_zn_ref[pl.ds(zk * Zk, Zk), :],
                        preferred_element_type=jnp.float32,
                    )

            # --- scores
            cpu_req = cpu_nz_ref[u]
            mem_req = mem_nz_ref[u]
            alloc_cpu = alloc_ref[pl.ds(V.RES_CPU, 1), :]
            alloc_mem = alloc_ref[pl.ds(V.RES_MEMORY, 1), :]
            used_cpu = used_ref[pl.ds(V.RES_CPU, 1), :] + cpu_req
            used_mem = used_ref[pl.ds(V.RES_MEMORY, 1), :] + mem_req
            l_cpu = jnp.where(
                (alloc_cpu == 0) | (used_cpu > alloc_cpu),
                0.0,
                (alloc_cpu - used_cpu) * MAX_SCORE / jnp.maximum(alloc_cpu, 1.0),
            )
            l_mem = jnp.where(
                (alloc_mem == 0) | (used_mem > alloc_mem),
                0.0,
                (alloc_mem - used_mem) * MAX_SCORE / jnp.maximum(alloc_mem, 1.0),
            )
            least = (l_cpu + l_mem) / 2.0
            cpu_frac = used_cpu / jnp.maximum(alloc_cpu, 1.0)
            mem_frac = used_mem / jnp.maximum(alloc_mem, 1.0)
            balanced = jnp.where(
                (cpu_frac >= 1.0) | (mem_frac >= 1.0),
                0.0,
                (1.0 - jnp.abs(cpu_frac - mem_frac)) * MAX_SCORE,
            )

            share_row = s_share[:] if big_u else shraw_ref[pl.ds(u, 1), :]
            feas_b = feasible > 0
            lo = jnp.min(jnp.where(feas_b, share_row, jnp.float32(1e30)))
            hi = jnp.max(jnp.where(feas_b, share_row, jnp.float32(-1e30)))
            rng = hi - lo
            share_norm = jnp.where(rng > 0, (share_row - lo) * MAX_SCORE / rng, 0.0)

            scored = feas_b & (ignored == 0)
            smn = jnp.min(jnp.where(scored, soft_raw, jnp.float32(1e30)))
            smx = jnp.max(jnp.where(scored, soft_raw, jnp.float32(-1e30)))
            spread_norm = jnp.where(
                smx <= 0, MAX_SCORE, MAX_SCORE * (smx + smn - soft_raw) / jnp.maximum(smx, 1.0)
            )
            spread_norm = jnp.where(ignored > 0, 0.0, spread_norm)
            spread_norm = jnp.where(any_soft > 0, spread_norm, 0.0)

            score = least + balanced + 2.0 * share_norm + 2.0 * spread_norm
            if has_na:
                # NodeAffinity preferred-term weights, max-normalized over
                # the feasible set (DefaultNormalizeScore)
                na_row = s_na[:] if big_u else na_ref[pl.ds(u, 1), :]
                na_max = jnp.max(jnp.where(feas_b, na_row, 0.0))
                score = score + jnp.where(
                    na_max > 0, na_row * MAX_SCORE / jnp.maximum(na_max, 1.0), na_row
                )
            if has_tt:
                # TaintToleration: intolerable PreferNoSchedule counts,
                # reverse-normalized
                tt_row = s_tt[:] if big_u else tt_ref[pl.ds(u, 1), :]
                tt_max = jnp.max(jnp.where(feas_b, tt_row, 0.0))
                score = score + jnp.where(
                    tt_max > 0, MAX_SCORE - tt_row * MAX_SCORE / jnp.maximum(tt_max, 1.0), MAX_SCORE
                )
            if has_local:
                # Open-Local binpack score (local_score in kernels.py):
                # mean over units of used/capacity × 10, min-max normalized
                lvm = lvm_ref[u]
                big_f = jnp.float32(1e30)
                best_free = jnp.full((1, N), big_f, jnp.float32)
                best_cap = jnp.zeros((1, N), jnp.float32)
                for v in range(n_vg):
                    free_v = vg_free_ref[pl.ds(v, 1), :]
                    fits_v = free_v >= lvm
                    better = fits_v & (free_v < best_free)
                    best_free = jnp.where(better, free_v, best_free)
                    best_cap = jnp.where(better, vgcap_ref[pl.ds(v, 1), :], best_cap)
                parts = jnp.where(
                    (lvm > 0) & (best_free < big_f), lvm / jnp.maximum(best_cap, 1.0), 0.0
                )
                count = jnp.where(lvm > 0, 1.0, 0.0)
                for m in range(2):
                    size = dreq_ref[u, m]
                    need = dneed_ref[u, m]
                    first_cap = jnp.full((1, N), big_f, jnp.float32)
                    for d in range(n_dev):
                        free_d = dev_free_ref[pl.ds(d, 1), :]
                        media_d = media_ref[pl.ds(m * n_dev + d, 1), :]
                        fitting = (media_d > 0) & (free_d >= size) & (free_d > 0)
                        first_cap = jnp.where(
                            fitting, jnp.minimum(first_cap, devcap_ref[pl.ds(d, 1), :]), first_cap
                        )
                    parts = parts + jnp.where(size > 0, need * size / jnp.maximum(first_cap, 1.0), 0.0)
                    count = count + jnp.where(size > 0, need, 0.0)
                local_raw = jnp.where(count > 0, parts / jnp.maximum(count, 1.0) * 10.0, 0.0)
                l_lo = jnp.min(jnp.where(feas_b, local_raw, big_f))
                l_hi = jnp.max(jnp.where(feas_b, local_raw, -big_f))
                l_rng = l_hi - l_lo
                score = score + jnp.where(l_rng > 0, (local_raw - l_lo) * MAX_SCORE / l_rng, 0.0)
            if has_interpod:
                # interpod_score normalization: min/max seeded with 0
                ip_masked = jnp.where(feas_b, ip_raw, 0.0)
                ip_hi = jnp.maximum(jnp.max(ip_masked), 0.0)
                ip_lo = jnp.minimum(jnp.min(ip_masked), 0.0)
                ip_rng = ip_hi - ip_lo
                score = score + jnp.where(
                    ip_rng > 0, MAX_SCORE * (ip_raw - ip_lo) / jnp.maximum(ip_rng, 1.0), 0.0
                )

            # --- selectHost: lowest index among maxima — Mosaic's argmax
            # breaks ties by HIGHEST index, diverging from the XLA scan
            masked_score = jnp.where(feas_b, score, jnp.float32(NEG))
            mx_score = jnp.max(masked_score)
            best = jnp.min(jnp.where(masked_score == mx_score, iota_n, N)).astype(jnp.int32)
            any_feasible = jnp.max(feasible) > 0
            sel_choice = jnp.where(any_feasible, best, jnp.int32(-1))
            is_forced = forced_ref[i] == 1
            pin_u = pin_ref[u]
            choice = jnp.where(is_forced, jnp.where(pin_u >= 0, pin_u, -1), sel_choice)
            do_bind = (valid_ref[i] == 1) & (choice >= 0)
            chosen_ref[i] = jnp.where(do_bind, choice, -1)

            # --- bind update
            @pl.when(do_bind)
            def _bind():
                c = jnp.maximum(choice, 0)
                onehot = (iota_n == c).astype(jnp.float32)  # [1, N]
                iota_r = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)
                req_col = jnp.zeros((R, 1), jnp.float32)
                for r in range(R):  # static unroll; .at[] would lower to scatter
                    req_col = jnp.where(iota_r == r, req_ref[u, r], req_col)
                used_ref[:] = used_ref[:] + req_col * onehot

                if big_u:
                    m_col = s_match[:]  # [A, 1]
                else:
                    onehot_u = (iota_u == u).astype(jnp.float32)  # [U, 1]
                    m_col = jnp.dot(matches_ref[:], onehot_u, preferred_element_type=jnp.float32)
                zrow_c_full = zone_nz_ref[pl.ds(c, 1), :]  # [1, K*Zk]
                node_cnt_ref[:] = node_cnt_ref[:] + m_col * onehot
                for zk in range(n_zkeys):
                    zone_cnt_ref[pl.ds(zk * A_rows, A_rows), :] = (
                        zone_cnt_ref[pl.ds(zk * A_rows, A_rows), :]
                        + m_col * zrow_c_full[:, zk * Zk : (zk + 1) * Zk]
                    )
                if has_ports:
                    p_col = s_port[:] if big_u else jnp.dot(
                        port_hu_ref[:], onehot_u, preferred_element_type=jnp.float32
                    )
                    port_used_ref[:] = port_used_ref[:] + p_col * onehot
                if has_gpu:
                    # device packing on the chosen node (computed for all
                    # nodes, applied via the one-hot): single-GPU tightest
                    # fit, multi-GPU greedy with reuse (gpunodeinfo.go)
                    gmem = gmem_ref[u]
                    gcnt = gcnt_ref[u]
                    best_free = jnp.full((1, N), 1e30, jnp.float32)
                    for d in range(n_gpu):
                        free_d = gpu_free_ref[pl.ds(d, 1), :]
                        best_free = jnp.where(free_d >= gmem, jnp.minimum(best_free, free_d), best_free)
                    assigned = jnp.zeros((1, N), jnp.float32)
                    cum = jnp.zeros((1, N), jnp.float32)
                    for d in range(n_gpu):
                        free_d = gpu_free_ref[pl.ds(d, 1), :]
                        fits_d = (free_d >= gmem).astype(jnp.float32)
                        take_tight = fits_d * (free_d == best_free).astype(jnp.float32) * (1.0 - jnp.minimum(assigned, 1.0))
                        assigned = assigned + take_tight
                        chunks_d = jnp.floor(free_d / jnp.maximum(gmem, 1.0))
                        take_greedy = jnp.clip(gcnt - cum, 0.0, chunks_d)
                        cum = cum + chunks_d
                        take_d = jnp.where(gcnt == 1, take_tight, take_greedy)
                        take_d = jnp.where(gmem > 0, take_d, 0.0)
                        gpu_free_ref[pl.ds(d, 1), :] = free_d - take_d * gmem * onehot
                        gpu_take_ref[i, d] = jnp.sum(take_d * onehot)
                if has_local:
                    # LVM: tightest-fitting VG (first among equals)
                    lvm = lvm_ref[u]
                    big_f = jnp.float32(1e30)
                    best_free = jnp.full((1, N), big_f, jnp.float32)
                    for v in range(n_vg):
                        free_v = vg_free_ref[pl.ds(v, 1), :]
                        best_free = jnp.where(free_v >= lvm, jnp.minimum(best_free, free_v), best_free)
                    taken_vg = jnp.zeros((1, N), jnp.float32)
                    for v in range(n_vg):
                        free_v = vg_free_ref[pl.ds(v, 1), :]
                        take_v = (
                            (free_v >= lvm) & (free_v == best_free)
                        ).astype(jnp.float32) * (1.0 - jnp.minimum(taken_vg, 1.0))
                        taken_vg = taken_vg + take_v
                        vg_free_ref[pl.ds(v, 1), :] = free_v - jnp.maximum(lvm, 0.0) * take_v * onehot
                    # exclusive devices: one device per volume, smallest
                    # volume onto the smallest-capacity fitting free device
                    # (common.go:290-349; ties by lowest device index) —
                    # must mirror the XLA bind exactly
                    big_cap = jnp.float32(1e30)
                    taken_rows = [jnp.zeros((1, N), jnp.float32) for _ in range(n_dev)]
                    for m in range(2):
                        for vi in reversed(range(n_dvol)):  # ascending sizes
                            size = dsz_ref[u, m * n_dvol + vi]
                            best_cap = jnp.full((1, N), big_cap, jnp.float32)
                            for d in range(n_dev):
                                free_d = dev_free_ref[pl.ds(d, 1), :]
                                media_d = media_ref[pl.ds(m * n_dev + d, 1), :]
                                cand_d = (
                                    (media_d > 0) & (free_d >= size) & (free_d > 0)
                                    & (taken_rows[d] == 0)
                                )
                                best_cap = jnp.where(
                                    cand_d,
                                    jnp.minimum(best_cap, devcap_ref[pl.ds(d, 1), :]),
                                    best_cap,
                                )
                            assigned = jnp.zeros((1, N), jnp.float32)
                            for d in range(n_dev):
                                free_d = dev_free_ref[pl.ds(d, 1), :]
                                media_d = media_ref[pl.ds(m * n_dev + d, 1), :]
                                cand_d = (
                                    (media_d > 0) & (free_d >= size) & (free_d > 0)
                                    & (taken_rows[d] == 0)
                                )
                                take_d = (
                                    cand_d & (devcap_ref[pl.ds(d, 1), :] == best_cap)
                                ).astype(jnp.float32) * (1.0 - jnp.minimum(assigned, 1.0))
                                take_d = take_d * jnp.where(size > 0, 1.0, 0.0)
                                assigned = assigned + take_d
                                taken_rows[d] = jnp.maximum(taken_rows[d], take_d)
                                dev_free_ref[pl.ds(d, 1), :] = free_d * (1.0 - take_d * onehot)
                if has_interpod:
                    a_col = s_antig[:] if big_u else jnp.dot(
                        antig_ref[:], onehot_u, preferred_element_type=jnp.float32
                    )
                    anti_node_ref[:] = anti_node_ref[:] + a_col * onehot
                    for zk in range(n_zkeys):
                        key_mask = (g_key_col == zk + 1).astype(jnp.float32)
                        anti_zone_ref[:] = anti_zone_ref[:] + a_col * key_mask * zrow_c_full[
                            :, zk * Zk : (zk + 1) * Zk
                        ]
                    p_col = s_prefg[:] if big_u else jnp.dot(
                        prefg_ref[:], onehot_u, preferred_element_type=jnp.float32
                    )
                    prefw_node_ref[:] = prefw_node_ref[:] + p_col * onehot
                    for zk in range(n_zkeys):
                        key_mask = (p_key_col == zk + 1).astype(jnp.float32)
                        prefw_zone_ref[:] = prefw_zone_ref[:] + p_col * key_mask * zrow_c_full[
                            :, zk * Zk : (zk + 1) * Zk
                        ]

            return 0

        jax.lax.fori_loop(0, tmpl_ref.shape[0], body, 0)
        used_out_ref[:] = used_ref[:]
        gpu_out_ref[:] = gpu_free_ref[:]
        vg_out_ref[:] = vg_free_ref[:]
        dev_out_ref[:] = dev_free_ref[:]

    return kernel


def run_fast_scan(
    fi: FastInputs,
    tmpl_ids,
    pod_valid,
    forced,
    has_interpod: bool,
    has_gpu: bool,
    has_local: bool = False,
    has_ports: bool = False,
    has_na: bool = False,
    has_tt: bool = False,
    interpret: bool = False,
    big_u: bool = False,
):
    """Execute the megakernel. tmpl_ids/pod_valid/forced are [P] (P a
    multiple of CHUNK). Returns (chosen [P] i32, used_final [R, N],
    gpu_take [P, Gd], gpu_final [Gd, N], vg_final [Vg, N], dev_final [Dv, N]).

    `big_u` keeps the [U, N] / [X, U] template tables in HBM and DMAs one
    row/column per pod step into VMEM scratch — VMEM use then no longer
    scales with U, lifting the template cap (fastpath.applicable)."""
    P = tmpl_ids.shape[0]
    assert P % CHUNK == 0, P
    R, N = fi.alloc_T.shape
    A = fi.matches_AU.shape[0]
    K = fi.has_zone.shape[0]  # number of non-hostname topology keys (>= 1)
    Z = fi.zone_NZ.shape[1] // K
    G = fi.antig_GU.shape[0]
    Gp = fi.prefg_GU.shape[0]
    Gd = fi.gpu0_DN.shape[0]
    Vg = fi.vg0_VN.shape[0]
    Dv = fi.dev0_DN.shape[0]
    Hp = fi.port_HU.shape[0]
    grid = (P // CHUNK,)

    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    stream = lambda: pl.BlockSpec((CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM)

    # which of the 24 VMEM inputs move to HBM (ANY) in big-U mode: the
    # U-dimensioned tables, in kernel parameter order
    _U_TABLE_POS = {2, 3, 4, 8, 10, 11, 12, 13, 20, 21, 22, 23}
    if big_u:
        vmem_specs = [
            pl.BlockSpec(memory_space=pl.ANY) if k in _U_TABLE_POS else vmem()
            for k in range(24)
        ]
        # per-step scratch: rows [1, N] for the [U, N] tables, columns [X, 1]
        # for the [X, U] tables — order must match the kernel's _dma calls
        u_scratch = [pltpu.VMEM((1, N), jnp.float32)] * 3  # static, affm, shraw
        u_scratch.append(pltpu.VMEM((A, 1), jnp.float32))  # matches column
        if has_na:
            u_scratch.append(pltpu.VMEM((1, N), jnp.float32))
        if has_tt:
            u_scratch.append(pltpu.VMEM((1, N), jnp.float32))
        if has_ports:
            u_scratch += [pltpu.VMEM((Hp, 1), jnp.float32)] * 2
        if has_interpod:
            u_scratch += [
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((Gp, 1), jnp.float32),
                pltpu.VMEM((Gp, 1), jnp.float32),
            ]
        u_scratch.append(pltpu.SemaphoreType.DMA((len(u_scratch),)))
    else:
        vmem_specs = [vmem()] * 24
        u_scratch = []

    out = pl.pallas_call(
        _make_kernel(
            has_interpod, has_gpu, has_local, has_ports, has_na, has_tt,
            G, Gp, Gd, Vg, Dv, fi.dev_sizes.shape[1] // 2, big_u, K,
        ),
        grid=grid,
        out_shape=(
            jax.ShapeDtypeStruct((P,), jnp.int32),
            jax.ShapeDtypeStruct((R, N), jnp.float32),
            jax.ShapeDtypeStruct((P, Gd), jnp.float32),
            jax.ShapeDtypeStruct((Gd, N), jnp.float32),
            jax.ShapeDtypeStruct((Vg, N), jnp.float32),
            jax.ShapeDtypeStruct((Dv, N), jnp.float32),
        ),
        in_specs=(
            [stream(), stream(), stream()]
            + [smem()] * 4  # req, cpu_nz, mem_nz, pin
            + [smem()] * 7  # spread tables
            + [smem()] * 4  # at_*
            + [smem()] * 3  # an_*
            + [smem()] * 4  # pt_*
            + [smem()] * 2  # anti_g_key, prefg_key
            + [smem()] * 2  # gpu_mem, gpu_cnt
            + [smem()] * 4  # lvm_req, dev_req, dev_need, dev_sizes
            + vmem_specs  # VMEM (or ANY, big-U mode) inputs
        ),
        out_specs=(
            pl.BlockSpec((CHUNK,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((R, N), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((CHUNK, Gd), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((Gd, N), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Vg, N), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Dv, N), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((R, N), jnp.float32),
            pltpu.VMEM((A, N), jnp.float32),
            pltpu.VMEM((K * A, Z), jnp.float32),
            pltpu.VMEM((G, N), jnp.float32),
            pltpu.VMEM((G, Z), jnp.float32),
            pltpu.VMEM((Gp, N), jnp.float32),
            pltpu.VMEM((Gp, Z), jnp.float32),
            pltpu.VMEM((Gd, N), jnp.float32),
            pltpu.VMEM((Vg, N), jnp.float32),
            pltpu.VMEM((Dv, N), jnp.float32),
            pltpu.VMEM((Hp, N), jnp.float32),
        ]
        + u_scratch,
        interpret=interpret,
    )(
        jnp.asarray(tmpl_ids, jnp.int32),
        jnp.asarray(pod_valid, jnp.int32),
        jnp.asarray(forced, jnp.int32),
        jnp.asarray(fi.req, jnp.float32),
        jnp.asarray(fi.cpu_nz, jnp.float32),
        jnp.asarray(fi.mem_nz, jnp.float32),
        jnp.asarray(fi.pin, jnp.int32),
        jnp.asarray(fi.spr_active, jnp.int32),
        jnp.asarray(fi.spr_key, jnp.int32),
        jnp.asarray(fi.spr_sel, jnp.int32),
        jnp.asarray(fi.spr_skew, jnp.float32),
        jnp.asarray(fi.spr_hard, jnp.int32),
        jnp.asarray(fi.spr_self, jnp.float32),
        jnp.asarray(fi.spr_weight, jnp.float32),
        jnp.asarray(fi.at_active, jnp.int32),
        jnp.asarray(fi.at_key, jnp.int32),
        jnp.asarray(fi.at_sel, jnp.int32),
        jnp.asarray(fi.at_self, jnp.float32),
        jnp.asarray(fi.an_active, jnp.int32),
        jnp.asarray(fi.an_key, jnp.int32),
        jnp.asarray(fi.an_sel, jnp.int32),
        jnp.asarray(fi.pt_active, jnp.int32),
        jnp.asarray(fi.pt_key, jnp.int32),
        jnp.asarray(fi.pt_sel, jnp.int32),
        jnp.asarray(fi.pt_w, jnp.float32),
        jnp.asarray(fi.anti_g_key, jnp.int32),
        jnp.asarray(fi.prefg_key, jnp.int32),
        jnp.asarray(fi.gpu_mem, jnp.float32),
        jnp.asarray(fi.gpu_cnt, jnp.float32),
        jnp.asarray(fi.lvm_req, jnp.float32),
        jnp.asarray(fi.dev_req, jnp.float32),
        jnp.asarray(fi.dev_need, jnp.float32),
        jnp.asarray(fi.dev_sizes, jnp.float32),
        jnp.asarray(fi.alloc_T, jnp.float32),
        jnp.asarray(fi.used0_T, jnp.float32),
        jnp.asarray(fi.static_pass, jnp.float32),
        jnp.asarray(fi.aff_mask, jnp.float32),
        jnp.asarray(fi.share_raw, jnp.float32),
        jnp.asarray(fi.zone_NZ, jnp.float32),
        jnp.asarray(fi.zone_ZN, jnp.float32),
        jnp.asarray(fi.has_zone, jnp.float32),
        jnp.asarray(fi.matches_AU, jnp.float32),
        jnp.asarray(fi.node_valid, jnp.float32),
        jnp.asarray(fi.antig_GU, jnp.float32),
        jnp.asarray(fi.gmatch_GU, jnp.float32),
        jnp.asarray(fi.prefg_GU, jnp.float32),
        jnp.asarray(fi.pmatch_GU, jnp.float32),
        jnp.asarray(fi.gpu0_DN, jnp.float32),
        jnp.asarray(fi.vg_cap_VN, jnp.float32),
        jnp.asarray(fi.vg0_VN, jnp.float32),
        jnp.asarray(fi.dev_cap_DN, jnp.float32),
        jnp.asarray(fi.dev0_DN, jnp.float32),
        jnp.asarray(fi.dev_media_DN, jnp.float32),
        jnp.asarray(fi.port_HU, jnp.float32),
        jnp.asarray(fi.port_conf_HU, jnp.float32),
        jnp.asarray(fi.na_raw, jnp.float32),
        jnp.asarray(fi.tt_raw, jnp.float32),
    )
    return out
