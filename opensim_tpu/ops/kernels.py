"""Filter / score kernels — the vectorized scheduler plugin pipeline.

Each kernel computes over the FULL node axis at once, replacing the
reference's goroutine fan-out (``parallelize.Until`` with 16 workers,
``vendor/.../internal/parallelize/parallelism.go:56``) with data
parallelism on the TPU vector units. One ``pod_step`` = one pod through
Filter → Score → selectHost, exactly the pipeline of
``generic_scheduler.Schedule`` (``vendor/.../core/generic_scheduler.go:131-180``)
with ``PercentageOfNodesToScore = 100`` (``pkg/simulator/utils.go:370``).

Kernel ↔ reference-plugin parity map (score weights from
``algorithmprovider/registry.go:119-132``):
  filter: NodeName, NodeUnschedulable, TaintToleration, NodeAffinity,
          NodePorts, NodeResourcesFit, PodTopologySpread, InterPodAffinity,
          GpuShare (open-gpu-share.go:51-81), OpenLocal (open-local.go:51-92)
  score:  BalancedAllocation (w1), ImageLocality (w1, 0 — no images in sim),
          InterPodAffinity (w1), LeastAllocated (w1), NodeAffinity (w1),
          NodePreferAvoidPods (w10000, annotation table), PodTopologySpread (w2),
          TaintToleration (w1), Simon share (w1, plugin/simon.go:45-101),
          GpuShare share (w1), OpenLocal (w1)

All functions take the EncodedCluster (`ec`), the scan carry (`st`) and a
traced template index `u`; shapes are static.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..encoding import vocab as V
from ..encoding.state import EncodedCluster, ScanState

MAX_NODE_SCORE = 100.0

# Filter kernel ids (order = reason-attribution precedence, roughly the
# order the default profile runs them).
F_NODE_PIN = 0  # NodeName
F_UNSCHEDULABLE = 1
F_TAINT = 2
F_AFFINITY = 3  # NodeAffinity + nodeSelector
F_PORTS = 4
F_FIT = 5  # NodeResourcesFit
F_SPREAD = 6
F_INTERPOD = 7
F_GPU = 8
F_LOCAL = 9
F_EXTRA = 10  # out-of-tree plugins registered via extra_plugins
NUM_FILTERS = 11

# the registered reason-code table (engine/reasons.py, ISSUE 7): one copy
# of the kube FitError phrasings shared by every engine and report surface
from ..engine.reasons import FILTER_MESSAGES as FILTER_REASONS  # noqa: E402


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _gather_label(label_arr, keys):
    """label_arr [N, K], keys [...]-shaped int32 (may be -1) →
    values [N, ...]; -1 keys yield -1/NaN."""
    safe = jnp.maximum(keys, 0)
    vals = label_arr[:, safe]  # [N, ...]
    return vals


def _requirements_match(ec, keys, ops, vals, nums):
    """Evaluate node-selector requirements against all nodes.

    keys/ops/nums: [...]; vals: [..., Vv]. Returns bool [N, ...] — True
    where the requirement holds (padding requirements are vacuously True).
    """
    node_val = _gather_label(ec.label_val, keys)  # [N, ...]
    node_num = _gather_label(ec.label_num, keys)  # [N, ...]
    present = node_val >= 0
    in_set = jnp.any(node_val[..., None] == vals[None, ...], axis=-1)  # [N, ...]
    ops_b = ops[None, ...]
    result = jnp.ones_like(present)
    result = jnp.where(ops_b == V.OP_IN, present & in_set, result)
    result = jnp.where(ops_b == V.OP_NOT_IN, ~(present & in_set), result)
    result = jnp.where(ops_b == V.OP_EXISTS, present, result)
    result = jnp.where(ops_b == V.OP_DOES_NOT_EXIST, ~present, result)
    result = jnp.where(ops_b == V.OP_GT, node_num > nums[None, ...], result)
    result = jnp.where(ops_b == V.OP_LT, node_num < nums[None, ...], result)
    return result


def _minmax_normalize(scores, feasible):
    """SimonPlugin.NormalizeScore (plugin/simon.go:76-101): min-max over the
    feasible set to [0, 100]; degenerate range → 0."""
    big = jnp.float32(1e30)
    lo = jnp.min(jnp.where(feasible, scores, big))
    hi = jnp.max(jnp.where(feasible, scores, -big))
    rng = hi - lo
    return jnp.where(rng > 0, (scores - lo) * MAX_NODE_SCORE / rng, 0.0)


# ---------------------------------------------------------------------------
# filter kernels
# ---------------------------------------------------------------------------

def taint_filter(ec, u):
    """TaintToleration: every NoSchedule/NoExecute taint must be tolerated."""
    t_key = ec.taint_key  # [N, Tt]
    t_val = ec.taint_val
    t_eff = ec.taint_effect
    tol_valid = ec.tol_valid[u]  # [Tl]
    tol_key = ec.tol_key[u]
    tol_op = ec.tol_op[u]
    tol_val = ec.tol_val[u]
    tol_eff = ec.tol_effect[u]

    # [N, Tt, Tl]: does toleration l tolerate taint t?
    key_ok = (tol_key[None, None, :] == -1) | (tol_key[None, None, :] == t_key[:, :, None])
    eff_ok = (tol_eff[None, None, :] == -1) | (tol_eff[None, None, :] == t_eff[:, :, None])
    val_ok = jnp.where(
        tol_op[None, None, :] == V.TOL_EXISTS, True, tol_val[None, None, :] == t_val[:, :, None]
    )
    # empty-key (-1) tolerations require operator Exists to match all
    empty_key_bad = (tol_key[None, None, :] == -1) & (tol_op[None, None, :] != V.TOL_EXISTS)
    tolerated = key_ok & eff_ok & val_ok & ~empty_key_bad & tol_valid[None, None, :]
    taint_tolerated = jnp.any(tolerated, axis=-1)  # [N, Tt]
    taint_blocking = (t_eff == V.EFFECT_NO_SCHEDULE) | (t_eff == V.EFFECT_NO_EXECUTE)
    return ~jnp.any(taint_blocking & ~taint_tolerated, axis=-1)


def node_affinity_filter(ec, u):
    """NodeAffinity plugin: nodeSelector map AND required node affinity
    (OR over terms, AND over requirements)."""
    # nodeSelector map: each (key, val) must match exactly.
    ns_key = ec.ns_key[u]  # [Qs]
    ns_val = ec.ns_val[u]
    node_val = _gather_label(ec.label_val, ns_key)  # [N, Qs]
    sel_ok = jnp.all((ns_key[None, :] < 0) | (node_val == ns_val[None, :]), axis=-1)

    req_ok = _requirements_match(ec, ec.aff_key[u], ec.aff_op[u], ec.aff_val[u], ec.aff_num[u])
    term_ok = jnp.all(req_ok, axis=-1)  # [N, T] AND over requirements
    term_valid = ec.aff_term_valid[u]  # [T]
    any_term = jnp.any(term_ok & term_valid[None, :], axis=-1)
    aff_ok = jnp.where(ec.has_req_aff[u], any_term, True)
    return sel_ok & aff_ok


def ports_filter(ec, st, u):
    """NodePorts: requested host ports must be free on the node. A request
    conflicts with any in-use port its conflict row overlaps — wildcard
    0.0.0.0 overlaps every specific hostIP on the same port/protocol
    (nodeports.go ckConflict)."""
    ports = ec.ports[u]  # [Hp]
    safe = jnp.maximum(ports, 0)
    conf = ec.port_conflict[safe].astype(jnp.float32)  # [Hp, Hports]
    hits = st.port_used @ conf.T  # [N, Hp] — weighted count of conflicting uses
    conflict = (ports[None, :] >= 0) & (hits > 0)
    return ~jnp.any(conflict, axis=-1)


def gc_row_of(ec) -> int:
    """Host-side resource-axis row of alibabacloud.com/gpu-count, -1 when
    absent. The single source for the engines' static `gc_row` parameter —
    keep fastpath/nativepath/preemption in lockstep through this."""
    import numpy as np

    mask = np.asarray(ec.gc_mask)
    return int(np.argmax(mask)) if mask.any() else -1


def gc_dynamic_alloc(ec, st):
    """The gpushare Reserve rewrite (open-gpu-share.go:177-182 →
    ExportGpuNodeInfoAsNodeGpuInfo, gpunodeinfo.go:354-369): a device-bearing
    node's ``gpu-count`` allocatable is the count of devices that are not
    fully used. Returns (dyn [N] f32, has_dev [N] bool)."""
    valid_dev = ec.node_gpu_mem > 0
    dyn = jnp.sum(valid_dev & (st.gpu_free > 0), axis=-1).astype(jnp.float32)
    return dyn, jnp.any(valid_dev, axis=-1)


def effective_alloc(ec, st):
    """Allocatable with the dynamic gpu-count column substituted on
    device-bearing nodes (all other columns — and device-less nodes, whose
    fake-client objects the reference never updates — stay static)."""
    dyn, has_dev = gc_dynamic_alloc(ec, st)
    return jnp.where(ec.gc_mask[None, :] & has_dev[:, None], dyn[:, None], ec.alloc)


def fit_filter(ec, st, u, alloc=None, ignored_cols: tuple = ()):
    """NodeResourcesFit (noderesources/fit.go:195-260): requested resources
    must fit allocatable - used. Returns (mask, insufficient [N, R]).
    `alloc` overrides ec.alloc (the Features.gc_dyn dynamic-allocatable
    path); `ignored_cols` are static resource columns the filter skips
    (NodeResourcesFitArgs.ignoredResources, fit.go podutil filtering)."""
    alloc = ec.alloc if alloc is None else alloc
    req = ec.req[u]  # [R]
    insufficient = (req[None, :] > 0) & (st.used + req[None, :] > alloc)
    for c in ignored_cols:
        insufficient = insufficient.at[:, c].set(False)
    return ~jnp.any(insufficient, axis=-1), insufficient


def spread_filter(ec, st, u, node_aff_mask):
    """PodTopologySpread DoNotSchedule constraints
    (podtopologyspread/filtering.go:276): for each hard constraint,
    matchCount(domain) + selfMatch - minMatch(eligible domains) <= maxSkew."""
    topo = ec.spr_topo[u]  # [Cs] topo-key idx, -1 pad
    sel = ec.spr_sel[u]
    skew = ec.spr_skew[u]
    hard = ec.spr_hard[u]
    active = (topo >= 0) & hard

    dom = ec.node_domain[:, jnp.maximum(topo, 0)]  # [N, Cs]
    has_label = dom < ec.domain_topo.shape[0] - 1  # trash row = missing label
    cnt = st.dom_sel[dom, sel[None, :]]  # [N, Cs]
    self_match = ec.matches_sel[u, sel]  # [Cs]

    # min matchNum over eligible domains: nodes passing node affinity with the
    # label present (k8s filtering.go calPreFilterState node filter).
    eligible = node_aff_mask[:, None] & has_label & ec.node_valid[:, None]
    big = jnp.float32(1e30)
    min_cnt = jnp.min(jnp.where(eligible, cnt, big), axis=0)  # [Cs]
    ok = cnt + self_match[None, :].astype(jnp.float32) - min_cnt <= skew[None, :].astype(jnp.float32)
    ok = ok & has_label  # nodes missing the topology label fail the constraint
    return jnp.all(ok | ~active[None, :], axis=-1)


def interpod_filter(ec, st, u):
    """InterPodAffinity filter (interpodaffinity/filtering.go:378):
    1) incoming pod's required anti-affinity: no existing pod in the
       candidate's topology domain may match;
    2) existing pods' anti-affinity terms must not match the incoming pod;
    3) incoming pod's required affinity: some domain pod matches (with the
       self-match bootstrap rule)."""
    D_trash = ec.domain_topo.shape[0] - 1

    # (1) incoming anti terms
    an_sel = ec.an_sel[u]  # [Tn]
    an_topo = ec.an_topo[u]
    an_active = an_sel >= 0
    dom = ec.node_domain[:, an_topo]  # [N, Tn]
    anti_cnt = st.dom_sel[dom, jnp.maximum(an_sel, 0)[None, :]]  # [N, Tn]
    # k8s: a node missing the topology label forms no topology pair, so the
    # anti-affinity term is vacuously satisfied there.
    has_label = dom < D_trash
    anti_ok = jnp.all(~an_active[None, :] | ~has_label | (anti_cnt == 0), axis=-1)

    # (2) existing pods' anti terms (symmetric check); label-less candidate
    # nodes can't be in any violating domain
    g_topo = ec.anti_g_topo  # [G]
    g_sel = ec.anti_g_sel
    dom_g = ec.node_domain[:, g_topo]  # [N, G]
    has_label_g = dom_g < D_trash
    exist_cnt = st.dom_anti[dom_g, jnp.arange(g_topo.shape[0])[None, :]]  # [N, G]
    incoming_matches = ec.matches_sel[u, g_sel]  # [G]
    sym_ok = jnp.all(~(has_label_g & (exist_cnt > 0) & incoming_matches[None, :]), axis=-1)

    # (3) incoming required affinity terms. All of a template's terms share
    # one conjunction selector id (templates.py), so `aff_cnt` counts pods
    # matching ALL terms — k8s's topologyToMatchedAffinityTerms basis
    # (filtering.go:113-127). satisfyPodAffinity (filtering.go:347-374):
    # every term's topology label must exist on the node; the first-pod
    # bootstrap needs the GLOBAL count map empty AND a full self-match, and
    # still requires the labels.
    at_sel = ec.at_sel[u]  # [Ti]
    at_topo = ec.at_topo[u]
    at_active = at_sel >= 0
    dom_a = ec.node_domain[:, at_topo]  # [N, Ti]
    aff_cnt = st.dom_sel[dom_a, jnp.maximum(at_sel, 0)[None, :]]  # [N, Ti]
    has_label_a = dom_a < D_trash
    dom_is_key = ec.domain_topo[None, :] == at_topo[:, None]  # [Ti, D+1]
    total = jnp.sum(jnp.where(dom_is_key, st.dom_sel[:, jnp.maximum(at_sel, 0)].T, 0.0), axis=-1)  # [Ti]
    map_empty = jnp.sum(jnp.where(at_active, total, 0.0)) == 0
    self_match = ec.matches_sel[u, jnp.maximum(at_sel, 0)]  # [Ti]
    bootstrap = map_empty & jnp.all(~at_active | self_match) & jnp.any(at_active)
    per_term_ok = ~at_active[None, :] | (has_label_a & (aff_cnt > 0))
    labels_ok = ~at_active[None, :] | has_label_a
    aff_ok = jnp.all(per_term_ok, axis=-1) | (jnp.all(labels_ok, axis=-1) & bootstrap)

    return anti_ok & sym_ok & aff_ok


def gpu_filter(ec, st, u):
    """Open-Gpu-Share filter (open-gpu-share.go:51-81 + AllocateGpuId,
    gpunodeinfo.go:232-290): per-GPU memory × count must be packable. The
    greedy multi-GPU packing with device reuse is equivalent to
    sum_d floor(free_d / mem) >= count."""
    mem = ec.gpu_mem[u]
    cnt = ec.gpu_count[u].astype(jnp.float32)
    chunks = jnp.sum(jnp.floor_divide(st.gpu_free, jnp.maximum(mem, 1.0)), axis=-1)  # [N]
    ok = (chunks >= cnt) & (cnt > 0)
    return jnp.where(mem > 0, ok, True)


def local_filter(ec, st, u):
    """Open-Local filter (open-local.go:51-92): LVM request fits the best
    VG; exclusive-device volumes must admit a one-device-per-volume
    matching (CheckExclusiveResourceMeetsPVCSize, common.go:290-349).
    With volume sizes sorted descending, a matching exists iff the i-th
    largest volume has at least i free fitting devices (Hall's condition
    on the nested fit sets)."""
    lvm = ec.lvm_req[u]
    lvm_ok = jnp.max(st.vg_free, axis=-1) >= lvm
    ok = jnp.where(lvm > 0, lvm_ok, True)
    for media in (0, 1):
        sizes = ec.dev_req_sizes[u, media]  # [Mv] descending, 0 pad
        free = st.dev_free  # [N, Dv]
        fitting = (
            (ec.node_dev_media[:, None, :] == media)
            & (free[:, None, :] >= sizes[None, :, None])
            & (free[:, None, :] > 0)
        )  # [N, Mv, Dv]
        fit_cnt = jnp.sum(fitting, axis=-1)  # [N, Mv]
        rank = jnp.arange(sizes.shape[0]) + 1  # [Mv]
        ok = ok & jnp.all((sizes[None, :] <= 0) | (fit_cnt >= rank[None, :]), axis=-1)
    return ok


# ---------------------------------------------------------------------------
# score kernels
# ---------------------------------------------------------------------------

def _nonzero_req(ec, u):
    """GetNonzeroRequests defaults: 100m CPU / 200Mi memory when a pod
    declares no request (used by Least/BalancedAllocation)."""
    cpu = ec.req[u, V.RES_CPU]
    mem = ec.req[u, V.RES_MEMORY]
    return jnp.where(cpu > 0, cpu, 100.0), jnp.where(mem > 0, mem, 200.0 * 1024 * 1024)


def least_allocated_score(ec, st, u):
    """NodeResourcesLeastAllocated (least_allocated.go:93-117)."""
    cpu_req, mem_req = _nonzero_req(ec, u)
    cpu_score = _least_requested(st.used[:, V.RES_CPU] + cpu_req, ec.alloc[:, V.RES_CPU])
    mem_score = _least_requested(st.used[:, V.RES_MEMORY] + mem_req, ec.alloc[:, V.RES_MEMORY])
    return (cpu_score + mem_score) / 2.0


def _least_requested(requested, capacity):
    score = (capacity - requested) * MAX_NODE_SCORE / jnp.maximum(capacity, 1.0)
    return jnp.where((capacity == 0) | (requested > capacity), 0.0, score)


def balanced_allocation_score(ec, st, u):
    """NodeResourcesBalancedAllocation (balanced_allocation.go:82-112)."""
    cpu_req, mem_req = _nonzero_req(ec, u)
    cpu_frac = (st.used[:, V.RES_CPU] + cpu_req) / jnp.maximum(ec.alloc[:, V.RES_CPU], 1.0)
    mem_frac = (st.used[:, V.RES_MEMORY] + mem_req) / jnp.maximum(ec.alloc[:, V.RES_MEMORY], 1.0)
    score = (1.0 - jnp.abs(cpu_frac - mem_frac)) * MAX_NODE_SCORE
    return jnp.where((cpu_frac >= 1.0) | (mem_frac >= 1.0), 0.0, score)


def node_affinity_raw(ec, u):
    """NodeAffinity score (pre-normalization): sum of matching
    preferred-term weights; DefaultNormalizeScore (max → 100) is applied in
    pod_step over the feasible set."""
    req_ok = _requirements_match(ec, ec.pna_key[u], ec.pna_op[u], ec.pna_val[u], ec.pna_num[u])
    term_ok = jnp.all(req_ok, axis=-1)  # [N, Pp]
    weights = ec.pna_weight[u]  # [Pp]
    return jnp.sum(jnp.where(term_ok, weights[None, :], 0.0), axis=-1)


def taint_toleration_raw(ec, u):
    """TaintToleration score input: count of intolerable PreferNoSchedule
    taints; reverse DefaultNormalizeScore is applied in pod_step."""
    t_key, t_val, t_eff = ec.taint_key, ec.taint_val, ec.taint_effect
    tol_valid = ec.tol_valid[u]
    tol_key, tol_op, tol_val, tol_eff = ec.tol_key[u], ec.tol_op[u], ec.tol_val[u], ec.tol_effect[u]
    key_ok = (tol_key[None, None, :] == -1) | (tol_key[None, None, :] == t_key[:, :, None])
    eff_ok = (tol_eff[None, None, :] == -1) | (tol_eff[None, None, :] == t_eff[:, :, None])
    val_ok = jnp.where(
        tol_op[None, None, :] == V.TOL_EXISTS, True, tol_val[None, None, :] == t_val[:, :, None]
    )
    empty_key_bad = (tol_key[None, None, :] == -1) & (tol_op[None, None, :] != V.TOL_EXISTS)
    tolerated = jnp.any(key_ok & eff_ok & val_ok & ~empty_key_bad & tol_valid[None, None, :], axis=-1)
    return jnp.sum((t_eff == V.EFFECT_PREFER_NO_SCHEDULE) & ~tolerated, axis=-1).astype(jnp.float32)


def interpod_score(ec, st, u, feasible):
    """InterPodAffinity score (interpodaffinity/scoring.go): incoming
    preferred terms against existing pods + existing pods' symmetric
    preferred/hard-affinity terms against the incoming pod, min-max
    normalized over the feasible set (min/max seeded with 0 per k8s)."""
    D_trash = ec.domain_topo.shape[0] - 1
    # incoming side: pt terms gather dom_sel counts; nodes missing the
    # topology label form no pair (k8s: no contribution, not trash-row reads)
    pt_sel = ec.pt_sel[u]  # [Tpp]
    pt_topo = ec.pt_topo[u]
    pt_w = ec.pt_w[u]
    dom = ec.node_domain[:, pt_topo]  # [N, Tpp]
    has_label = dom < D_trash
    cnt = st.dom_sel[dom, jnp.maximum(pt_sel, 0)[None, :]]
    incoming = jnp.sum(
        jnp.where((pt_sel[None, :] >= 0) & has_label, cnt * pt_w[None, :], 0.0), axis=-1
    )

    # symmetric side: existing pods' terms whose selector matches the pod
    g_topo = ec.prefg_topo  # [Gp]
    g_sel = ec.prefg_sel
    dom_g = ec.node_domain[:, g_topo]  # [N, Gp]
    has_label_g = dom_g < D_trash
    w_sum = st.dom_prefw[dom_g, jnp.arange(g_topo.shape[0])[None, :]]  # [N, Gp]
    matches = ec.matches_sel[u, g_sel].astype(jnp.float32)  # [Gp]
    symmetric = jnp.sum(jnp.where(has_label_g, w_sum * matches[None, :], 0.0), axis=-1)

    raw = incoming + symmetric
    masked = jnp.where(feasible, raw, 0.0)
    hi = jnp.maximum(jnp.max(masked), 0.0)
    lo = jnp.minimum(jnp.min(masked), 0.0)
    rng = hi - lo
    return jnp.where(rng > 0, MAX_NODE_SCORE * (raw - lo) / jnp.maximum(rng, 1.0), 0.0)


def spread_score(ec, stat: StaticTables, st, u, feasible):
    """PodTopologySpread score (podtopologyspread/scoring.go:175-248):
    ScheduleAnyway constraints; score_n = Σ_c cnt*log-weight + (maxSkew-1),
    inverted-normalized so spreading wins. The log(size+2) normalizing
    weight uses the statically precomputed per-key domain count."""
    topo = ec.spr_topo[u]  # [Cs]
    sel = ec.spr_sel[u]
    skew = ec.spr_skew[u].astype(jnp.float32)
    soft = (topo >= 0) & ~ec.spr_hard[u]
    any_soft = jnp.any(soft)

    D_trash = ec.domain_topo.shape[0] - 1
    dom = ec.node_domain[:, jnp.maximum(topo, 0)]  # [N, Cs]
    has_label = dom < D_trash
    cnt = st.dom_sel[dom, sel[None, :]]  # [N, Cs]

    ignored = feasible & ~jnp.all(has_label | ~soft[None, :], axis=-1)  # [N]
    scored = feasible & ~ignored
    weight = stat.spread_weight[jnp.maximum(topo, 0)]  # [Cs]

    contrib = jnp.where(soft[None, :] & has_label, cnt * weight[None, :] + (skew[None, :] - 1.0), 0.0)
    raw = jnp.sum(contrib, axis=-1)  # [N]

    big = jnp.float32(1e30)
    mn = jnp.min(jnp.where(scored, raw, big))
    mx = jnp.max(jnp.where(scored, raw, -big))
    norm = jnp.where(
        mx <= 0, MAX_NODE_SCORE, MAX_NODE_SCORE * (mx + mn - raw) / jnp.maximum(mx, 1.0)
    )
    norm = jnp.where(ignored, 0.0, norm)
    return jnp.where(any_soft, norm, 0.0)


def share_raw(ec, u):
    """Simon / Open-Gpu-Share share score (plugin/simon.go:45-74 +
    algo.Share, pkg/algo/greed.go:70-83), pre-normalization: max over
    node-allocatable resources of req/(allocatable - req). Allocatable is
    static — the fake client's node objects are never decremented — EXCEPT
    the gpu-count column on device-bearing nodes, which the gpushare
    Reserve rewrites (open-gpu-share.go:177-182): that column is excluded
    here and re-added per step by gc_share_dyn when Features.gc_dyn."""
    req = ec.req[u].at[V.RES_PODS].set(0.0)  # 'pods' request is not in PodRequestsAndLimits
    avail = ec.alloc - req[None, :]
    share = jnp.where(
        avail == 0, jnp.where(req[None, :] == 0, 0.0, 1.0), req[None, :] / avail
    )
    # only resources the node actually declares participate; negative shares
    # (req > allocatable) floor at 0 like the Go accumulator starting at 0
    share = jnp.where(ec.alloc > 0, share, 0.0)
    # the gpu-count column is DYNAMIC on device-bearing nodes (the gpushare
    # Reserve rewrite, open-gpu-share.go:177-182): its static contribution is
    # excluded here and pod_step adds the usage-dependent term per step
    # (gc_share_dyn). The exclusion MUST mirror Features.gc_dyn exactly —
    # some template must carry a gpushare annotation (else devices never
    # fill and no add-back runs) and some template must request gpu-count
    # (else the column is 0 anyway). Device-less nodes keep the static
    # column in all cases.
    has_dev = jnp.any(ec.node_gpu_mem > 0, axis=-1)  # [N]
    dyn_active = jnp.any(ec.gpu_mem > 0) & jnp.any(
        jnp.where(ec.gc_mask[None, :], ec.req, 0.0) > 0
    )
    share = jnp.where(
        ec.gc_mask[None, :] & has_dev[:, None] & dyn_active, 0.0, share
    )
    raw = jnp.maximum(jnp.max(share, axis=-1), 0.0) * MAX_NODE_SCORE
    # pods with no requests score MaxNodeScore on every node
    return jnp.where(jnp.any(req > 0), raw, MAX_NODE_SCORE)


def gc_share_dyn(ec, st, u):
    """Per-step share term for the dynamic gpu-count allocatable
    (algo.Share over the Reserve-updated value, open-gpu-share.go:94-106):
    req / (dyn_alloc - req), 1 when the denominator is 0, negative floored
    at 0 (the Go accumulator starts at 0). Zero on device-less nodes (their
    static column stays in share_raw) and for templates not requesting
    gpu-count."""
    gc_req = jnp.sum(jnp.where(ec.gc_mask, ec.req[u], 0.0))
    dyn, has_dev = gc_dynamic_alloc(ec, st)
    declared = jnp.sum(jnp.where(ec.gc_mask[None, :], ec.alloc, 0.0), axis=-1) > 0
    avail = dyn - gc_req
    share = jnp.where(avail == 0, jnp.where(gc_req == 0, 0.0, 1.0), gc_req / avail)
    share = jnp.where(declared & has_dev, jnp.maximum(share, 0.0), 0.0)
    return jnp.where(gc_req > 0, share * MAX_NODE_SCORE, 0.0)


class StaticTables(NamedTuple):
    """Per-(template, node) quantities that never change during a scan —
    precomputed once with a vmap over the template axis, so the scan body
    only runs the usage-dependent kernels. This is the TPU answer to the
    reference re-running every plugin per pod (generic_scheduler.go:270-345):
    pods sharing a template share all topology-independent work."""

    static_pass: jnp.ndarray  # [U, N] bool — AND of the four static filters
    aff_mask: jnp.ndarray  # [U, N] bool (NodeAffinity + nodeSelector, for spread eligibility)
    static_fail: jnp.ndarray  # [U, 4] i32 first-fail counts for pin/unsched/taint/affinity
    na_raw: jnp.ndarray  # [U, N] f32 preferred-node-affinity weights
    tt_raw: jnp.ndarray  # [U, N] f32 intolerable PreferNoSchedule counts
    share_raw: jnp.ndarray  # [U, N] f32 Simon/GpuShare share × 100
    spread_weight: jnp.ndarray  # [Tk] f32 log(domain count + 2) per topology key


def precompute_static(ec: EncodedCluster, cfg=None) -> StaticTables:  # opensim-lint: jit-region
    """NodeName pinning is handled by the forced-bind path in the scan step
    (pods with spec.nodeName never reach the scheduler, reference
    simulator.go:329-331), so the pin filter is NOT part of static_pass —
    a defrag scenario that un-forces a drained node's pods lets them
    reschedule anywhere. Its static_fail column stays zero."""
    from ..engine.schedconfig import DEFAULT_CONFIG

    cfg = cfg or DEFAULT_CONFIG
    U = ec.req.shape[0]
    us = jnp.arange(U)
    taint = jax.vmap(lambda u: taint_filter(ec, u))(us)
    aff = jax.vmap(lambda u: node_affinity_filter(ec, u))(us)
    unsched = jnp.broadcast_to(~ec.unschedulable[None, :], taint.shape)
    true_m = jnp.ones_like(taint)
    pin = true_m
    valid = ec.node_valid[None, :]
    fails = []
    passed = jnp.broadcast_to(valid, taint.shape)
    for m, enabled in (
        (pin, True),
        (unsched, cfg.f_unschedulable),
        (taint, cfg.f_taints),
        (aff, cfg.f_node_affinity),
    ):
        m = m if enabled else true_m
        fails.append(jnp.sum(passed & ~m, axis=-1))
        passed = passed & m

    # topology-spread normalizing weight log(size+2): size = distinct
    # domains per key over valid nodes. k8s computes it over the per-pod
    # filtered set (scoring.go:96-104); using the valid set instead keeps
    # the weight out of the scan (a documented fidelity trade: it only
    # blends the spread score, never feasibility).
    Dp1 = ec.domain_topo.shape[0]
    Tk = ec.node_domain.shape[1]
    dom_present = jnp.zeros((Dp1,), jnp.float32).at[
        jnp.where(ec.node_valid[:, None], ec.node_domain, Dp1 - 1)
    ].max(1.0)
    sizes = jnp.stack(
        [jnp.sum(jnp.where(ec.domain_topo[: Dp1 - 1] == tk, dom_present[: Dp1 - 1], 0.0)) for tk in range(Tk)]
    )

    return StaticTables(
        static_pass=passed,
        aff_mask=aff,
        static_fail=jnp.stack(fails, axis=-1).astype(jnp.int32),
        na_raw=jax.vmap(lambda u: node_affinity_raw(ec, u))(us),
        tt_raw=jax.vmap(lambda u: taint_toleration_raw(ec, u))(us),
        share_raw=jax.vmap(lambda u: share_raw(ec, u))(us),
        # gather, not jnp.log: every engine must read the SAME f32 weights
        # (see EncodedCluster.log_sizes)
        spread_weight=ec.log_sizes[
            jnp.clip(sizes.astype(jnp.int32), 0, ec.log_sizes.shape[0] - 1)
        ],
    )


def _unique_rows_np(*arrays):
    """(index, inverse) of the unique joint rows of per-template field
    arrays — live-cluster replays dedup pods per PINNED NODE (U ≈ N
    templates differing only in `pin`), but none of the static-table
    computations read the pin, so computing on unique field rows and
    scattering back turns an O(U·N·…) broadcast into O(U_eff·N·…) with
    U_eff = the handful of genuinely distinct specs."""
    import numpy as np

    packed = np.concatenate(
        [
            np.ascontiguousarray(a.reshape(a.shape[0], -1))
            .view(np.uint8)
            .reshape(a.shape[0], -1)
            for a in arrays
        ],
        axis=1,
    )
    _, idx, inv = np.unique(packed, axis=0, return_index=True, return_inverse=True)
    return idx, inv


def precompute_core_np(ec):
    """The node_valid- and config-INDEPENDENT half of
    :func:`precompute_static_np`: per-(template, node) filter masks and raw
    score tables. Scenario sweeps compute this ONCE and re-fold each
    scenario's node_valid through :func:`precompute_static_np` (the fold is
    O(U·N); this core is the expensive broadcast part)."""
    import numpy as np

    f32 = np.float32
    label_val = np.asarray(ec.label_val)
    label_num = np.asarray(ec.label_num)
    U = int(np.asarray(ec.req).shape[0])
    N = int(label_val.shape[0])

    def requirements_match(keys, ops, vals, nums):
        # keys/ops/nums [Uc, ...]; vals [Uc, ..., Vv] → bool [Uc, N, ...]
        keys = np.asarray(keys)
        node_val = np.moveaxis(label_val[:, np.maximum(keys, 0)], 0, 1)
        node_num = np.moveaxis(label_num[:, np.maximum(keys, 0)], 0, 1)
        present = node_val >= 0
        vals = np.asarray(vals)
        in_set = (node_val[..., None] == vals[:, None]).any(-1)
        ops_b = np.asarray(ops)[:, None]
        nums_b = np.asarray(nums)[:, None]
        res = np.ones_like(present)
        with np.errstate(invalid="ignore"):
            res = np.where(ops_b == V.OP_IN, present & in_set, res)
            res = np.where(ops_b == V.OP_NOT_IN, ~(present & in_set), res)
            res = np.where(ops_b == V.OP_EXISTS, present, res)
            res = np.where(ops_b == V.OP_DOES_NOT_EXIST, ~present, res)
            res = np.where(ops_b == V.OP_GT, node_num > nums_b, res)
            res = np.where(ops_b == V.OP_LT, node_num < nums_b, res)
        return res

    t_key = np.asarray(ec.taint_key)
    t_val = np.asarray(ec.taint_val)
    t_eff = np.asarray(ec.taint_effect)

    def taints_of(sl):
        tol_valid = np.asarray(ec.tol_valid[sl])
        tol_key = np.asarray(ec.tol_key[sl])[:, None, None, :]
        tol_op = np.asarray(ec.tol_op[sl])[:, None, None, :]
        tol_val = np.asarray(ec.tol_val[sl])[:, None, None, :]
        tol_eff = np.asarray(ec.tol_effect[sl])[:, None, None, :]
        key_ok = (tol_key == -1) | (tol_key == t_key[None, :, :, None])
        eff_ok = (tol_eff == -1) | (tol_eff == t_eff[None, :, :, None])
        val_ok = np.where(tol_op == V.TOL_EXISTS, True, tol_val == t_val[None, :, :, None])
        empty_key_bad = (tol_key == -1) & (tol_op != V.TOL_EXISTS)
        tolerated = (
            key_ok & eff_ok & val_ok & ~empty_key_bad & tol_valid[:, None, None, :]
        ).any(-1)  # [Uc, N, Tt]
        blocking = (t_eff == V.EFFECT_NO_SCHEDULE) | (t_eff == V.EFFECT_NO_EXECUTE)
        mask = ~((blocking[None] & ~tolerated).any(-1))
        ttr = ((t_eff[None] == V.EFFECT_PREFER_NO_SCHEDULE) & ~tolerated).sum(
            -1
        ).astype(f32)
        return mask, ttr

    def affinity_of(sl):
        ns_key = np.asarray(ec.ns_key[sl])
        ns_val = np.asarray(ec.ns_val[sl])
        nv = np.moveaxis(label_val[:, np.maximum(ns_key, 0)], 0, 1)
        sel_ok = ((ns_key[:, None, :] < 0) | (nv == ns_val[:, None, :])).all(-1)
        req_ok = requirements_match(
            ec.aff_key[sl], ec.aff_op[sl], ec.aff_val[sl], ec.aff_num[sl]
        )
        term_ok = req_ok.all(-1)
        any_term = (term_ok & np.asarray(ec.aff_term_valid[sl])[:, None, :]).any(-1)
        return sel_ok & np.where(np.asarray(ec.has_req_aff[sl])[:, None], any_term, True)

    def na_raw_of(sl):
        req_ok = requirements_match(
            ec.pna_key[sl], ec.pna_op[sl], ec.pna_val[sl], ec.pna_num[sl]
        )
        term_ok = req_ok.all(-1)  # [Uc, N, Pp]
        w = np.asarray(ec.pna_weight[sl], f32)[:, None, :]
        return np.where(term_ok, w, f32(0)).sum(-1, dtype=f32)

    # chunk the U axis: the taint/affinity broadcasts are [Uc, N, X, Y]
    per_u = max(
        N * max(int(t_key.shape[1]) * int(np.asarray(ec.tol_key).shape[1]), 1),
        N
        * max(int(np.asarray(ec.aff_key).shape[1]), 1)
        * max(int(np.asarray(ec.aff_key).shape[2]), 1)
        * max(int(np.asarray(ec.aff_val).shape[3]), 1),
    )
    chunk = max(1, int(4e7 // max(per_u, 1)))

    def dedup(fields, compute, outs):
        """Compute per unique field rows, scatter to [U, ...] outputs."""
        idx, inv = _unique_rows_np(*[np.asarray(f) for f in fields])
        ueff = idx.shape[0]
        parts = [np.empty((ueff,) + o.shape[1:], o.dtype) for o in outs]
        for lo in range(0, ueff, chunk):
            sel = idx[lo : lo + chunk]
            vals = compute(sel)
            if not isinstance(vals, tuple):
                vals = (vals,)
            for p, v in zip(parts, vals):
                p[lo : lo + chunk] = v
        for o, p in zip(outs, parts):
            o[:] = p[inv]

    taint = np.empty((U, N), bool)
    aff = np.empty((U, N), bool)
    na_raw = np.empty((U, N), f32)
    tt_raw = np.empty((U, N), f32)
    dedup(
        (ec.tol_valid, ec.tol_key, ec.tol_op, ec.tol_val, ec.tol_effect),
        taints_of, (taint, tt_raw),
    )
    dedup(
        (ec.ns_key, ec.ns_val, ec.has_req_aff, ec.aff_term_valid,
         ec.aff_key, ec.aff_op, ec.aff_val, ec.aff_num),
        affinity_of, (aff,),
    )
    dedup(
        (ec.pna_weight, ec.pna_key, ec.pna_op, ec.pna_val, ec.pna_num),
        na_raw_of, (na_raw,),
    )

    # share_raw (see the jnp version for the formula provenance)
    req_full = np.asarray(ec.req, f32)
    alloc = np.asarray(ec.alloc, f32)
    has_dev = (np.asarray(ec.node_gpu_mem) > 0).any(-1)
    gc_mask = np.asarray(ec.gc_mask, bool)
    dyn_active = bool((np.asarray(ec.gpu_mem) > 0).any()) and bool(
        (np.where(gc_mask[None, :], req_full, 0.0) > 0).any()
    )
    share_tbl = np.empty((U, N), f32)

    def share_of(sel):
        req = req_full[sel].copy()
        req[:, V.RES_PODS] = 0.0
        with np.errstate(divide="ignore", invalid="ignore"):
            avail = alloc[None] - req[:, None, :]
            share = np.where(
                avail == 0,
                np.where(req[:, None, :] == 0, f32(0), f32(1)),
                req[:, None, :] / avail,
            )
        share = np.where(alloc[None] > 0, share, f32(0))
        share = np.where(
            gc_mask[None, None, :] & has_dev[None, :, None] & dyn_active,
            f32(0), share,
        )
        raw = np.maximum(share.max(-1), f32(0)) * f32(MAX_NODE_SCORE)
        return np.where((req > 0).any(-1)[:, None], raw, f32(MAX_NODE_SCORE))

    dedup((req_full,), share_of, (share_tbl,))

    return {
        "taint": taint,
        "aff": aff,
        "na_raw": na_raw,
        "tt_raw": tt_raw,
        "share_raw": share_tbl.astype(f32),
    }


def precompute_static_np(ec: EncodedCluster, cfg=None, core=None) -> StaticTables:
    """Numpy mirror of :func:`precompute_static`, op-for-op in float32, so
    the native C++ path builds its static tables with ZERO XLA compiles
    (``--backend native`` must stay ms-scale cold — a 4.7 s precompute
    compile dwarfed the 27 ms scan on small configs). Every arithmetic step
    is either exact in f32 (integer-valued sums/counts, single IEEE
    divisions, max-reductions) or a shared-table gather (spread weights),
    so the tables are BITWISE equal to the jitted ones —
    tests/test_native.py asserts it. Keep the two implementations in
    lockstep. `core` reuses :func:`precompute_core_np` output across the
    scenarios of one sweep."""
    import numpy as np

    from ..engine.schedconfig import DEFAULT_CONFIG

    cfg = cfg or DEFAULT_CONFIG
    f32 = np.float32
    if core is None:
        core = precompute_core_np(ec)
    taint, aff = core["taint"], core["aff"]

    node_valid = np.asarray(ec.node_valid, bool)
    unsched = np.broadcast_to(~np.asarray(ec.unschedulable, bool)[None, :], taint.shape)
    true_m = np.ones_like(taint)
    fails = []
    passed = np.broadcast_to(node_valid[None, :], taint.shape)
    for m, enabled in (
        (true_m, True),  # pin column stays zero (forced-bind path)
        (unsched, cfg.f_unschedulable),
        (taint, cfg.f_taints),
        (aff, cfg.f_node_affinity),
    ):
        m = m if enabled else true_m
        fails.append((passed & ~m).sum(-1))
        passed = passed & m

    Dp1 = int(np.asarray(ec.domain_topo).shape[0])
    Tk = int(np.asarray(ec.node_domain).shape[1])
    dom_present = np.zeros((Dp1,), f32)
    nd = np.where(node_valid[:, None], np.asarray(ec.node_domain), Dp1 - 1)
    dom_present[np.unique(nd)] = 1.0
    domain_topo = np.asarray(ec.domain_topo)
    sizes = np.array(
        [
            np.where(domain_topo[: Dp1 - 1] == tk, dom_present[: Dp1 - 1], 0.0).sum()
            for tk in range(Tk)
        ]
    )
    log_sizes = np.asarray(ec.log_sizes)
    spread_weight = log_sizes[
        np.clip(sizes.astype(np.int32), 0, log_sizes.shape[0] - 1)
    ]

    return StaticTables(
        static_pass=passed,
        aff_mask=aff,
        static_fail=np.stack(fails, axis=-1).astype(np.int32),
        na_raw=core["na_raw"],
        tt_raw=core["tt_raw"],
        share_raw=core["share_raw"],
        spread_weight=spread_weight.astype(f32),
    )


def local_score(ec, st, u):
    """Open-Local score (open-local.go:94-138 → ScoreLVMVolume/ScoreDevice
    Volume, vendored common.go:487-509,:660-690, StrategyBinpack default,
    types.go:142): mean over allocated units of used/capacity × MaxScore(10).
    The LVM unit lands on the tightest-fitting VG (ascending free-size
    first-fit, common.go:111-116); min-max normalization happens with the
    other score plugins in pod_step."""
    lvm = ec.lvm_req[u]
    big = jnp.float32(1e30)
    fits = st.vg_free >= lvm  # [N, Vg]
    tight_free = jnp.min(jnp.where(fits, st.vg_free, big), axis=-1)  # [N]
    # capacity of the chosen VG: gather via argmin over masked free
    choice = jnp.argmin(jnp.where(fits, st.vg_free, big), axis=-1)  # [N]
    vg_cap = jnp.take_along_axis(ec.node_vg_cap, choice[:, None], axis=-1)[:, 0]
    lvm_part = jnp.where((lvm > 0) & (tight_free < big), lvm / jnp.maximum(vg_cap, 1.0), 0.0)

    parts = lvm_part
    count = (lvm > 0).astype(jnp.float32)
    for media in (0, 1):
        size = ec.dev_req[u, media]
        n_dev = ec.dev_req_count[u, media].astype(jnp.float32)
        fitting = (ec.node_dev_media == media) & (st.dev_free >= size) & (st.dev_free > 0)
        dev_cap = jnp.where(fitting, ec.node_dev_cap, big)
        first_cap = jnp.min(dev_cap, axis=-1)  # first-fit proxy: smallest fitting device
        parts = parts + jnp.where(size > 0, n_dev * size / jnp.maximum(first_cap, 1.0), 0.0)
        count = count + jnp.where(size > 0, n_dev, 0.0)

    raw = jnp.where(count > 0, parts / jnp.maximum(count, 1.0) * 10.0, 0.0)
    return raw


class Features(NamedTuple):
    """Static (trace-time) feature flags of the whole workload set: any
    kernel whose inputs are empty across every template is eliminated from
    the compiled scan entirely. Computed host-side at encode time."""

    ports: bool
    gpu: bool
    local: bool
    interpod: bool  # any required pod affinity/anti-affinity term
    prefg: bool  # any preferred/symmetric inter-pod score term
    spread_hard: bool
    spread_soft: bool
    pref_node_affinity: bool
    prefer_taints: bool
    prefer_avoid: bool
    # some template requests alibabacloud.com/gpu-count as a SPEC resource
    # while gpushare devices exist: the allocatable column follows the device
    # state (Reserve rewrite) instead of the static table
    gc_dyn: bool = False

    @property
    def sel_counts(self) -> bool:
        return self.interpod or self.spread_hard or self.spread_soft


ALL_FEATURES = Features(*([True] * 11))


def features_of(ec_np) -> Features:
    """Derive feature flags from the (host-side numpy) encoded cluster."""
    import numpy as np

    return Features(
        ports=bool((np.asarray(ec_np.ports) >= 0).any()),
        gpu=bool((np.asarray(ec_np.gpu_mem) > 0).any()),
        local=bool(
            (np.asarray(ec_np.lvm_req) > 0).any() or (np.asarray(ec_np.dev_req) > 0).any()
        ),
        interpod=bool(
            (np.asarray(ec_np.at_sel) >= 0).any() or (np.asarray(ec_np.an_sel) >= 0).any()
        ),
        prefg=bool((np.asarray(ec_np.prefg_w) != 0).any()),
        spread_hard=bool(
            ((np.asarray(ec_np.spr_topo) >= 0) & np.asarray(ec_np.spr_hard)).any()
        ),
        spread_soft=bool(
            ((np.asarray(ec_np.spr_topo) >= 0) & ~np.asarray(ec_np.spr_hard)).any()
        ),
        pref_node_affinity=bool((np.asarray(ec_np.pna_weight) != 0).any()),
        prefer_taints=bool(
            (np.asarray(ec_np.taint_effect) == V.EFFECT_PREFER_NO_SCHEDULE).any()
        ),
        prefer_avoid=bool((np.asarray(ec_np.avoid_score) < 100.0).any()),
        gc_dyn=bool(
            (np.asarray(ec_np.gpu_mem) > 0).any()
            and np.asarray(ec_np.gc_mask).any()
            and (np.asarray(ec_np.req)[:, np.asarray(ec_np.gc_mask)] > 0).any()
        ),
    )


class StepResult(NamedTuple):
    feasible: jnp.ndarray  # [N] bool
    score: jnp.ndarray  # [N] f32 weighted total
    chosen: jnp.ndarray  # scalar i32 node index (-1 infeasible)
    fail_counts: jnp.ndarray  # [NUM_FILTERS] i32 first-fail node counts
    insufficient: jnp.ndarray  # [R] i32 nodes short of each resource


def score_parts(
    ec, stat: "StaticTables", st, u, feasible, feat: Features = ALL_FEATURES,
    cfg=None, extra: tuple = (),
):
    """Per-plugin weighted score contributions for one pod over the node
    axis, keyed by the kube plugin name, in the exact accumulation order of
    ``pod_step``'s selectHost sum (insertion-ordered dict — summing the
    values reproduces the engine's score bit-for-bit). This is the single
    scoring source shared by the scan and the decision audit's per-plugin
    breakdown (``simon explain``), so the two can never drift."""
    from ..engine.schedconfig import DEFAULT_CONFIG

    cfg = cfg or DEFAULT_CONFIG
    parts = {}
    if cfg.w_balanced:
        parts["NodeResourcesBalancedAllocation"] = (
            cfg.w_balanced * balanced_allocation_score(ec, st, u)
        )
    if cfg.w_least:
        parts["NodeResourcesLeastAllocated"] = (
            cfg.w_least * least_allocated_score(ec, st, u)
        )
    if feat.pref_node_affinity and cfg.w_node_affinity:
        na_raw = stat.na_raw[u]
        na_max = jnp.max(jnp.where(feasible, na_raw, 0.0))
        parts["NodeAffinity"] = cfg.w_node_affinity * jnp.where(
            na_max > 0, na_raw * MAX_NODE_SCORE / jnp.maximum(na_max, 1.0), na_raw
        )
    if feat.prefer_taints and cfg.w_taint_toleration:
        tt_raw = stat.tt_raw[u]
        tt_max = jnp.max(jnp.where(feasible, tt_raw, 0.0))
        parts["TaintToleration"] = cfg.w_taint_toleration * jnp.where(
            tt_max > 0,
            MAX_NODE_SCORE - tt_raw * MAX_NODE_SCORE / jnp.maximum(tt_max, 1.0),
            MAX_NODE_SCORE,
        )
    if (feat.prefg or feat.interpod) and cfg.w_interpod:
        parts["InterPodAffinity"] = cfg.w_interpod * interpod_score(ec, st, u, feasible)
    if feat.spread_soft and cfg.w_spread:
        parts["PodTopologySpread"] = cfg.w_spread * spread_score(ec, stat, st, u, feasible)
    if cfg.w_simon + cfg.w_gpu_share:
        # Simon + Open-Gpu-Share share the same formula and normalization
        share_row = stat.share_raw[u]
        if feat.gc_dyn:
            # add back the gpu-count column with the Reserve-updated value
            # (share_raw zeroed it on device-bearing nodes); max mirrors the
            # Go accumulator taking the largest per-resource share
            share_row = jnp.maximum(share_row, gc_share_dyn(ec, st, u))
        parts["Simon/GpuShare"] = (cfg.w_simon + cfg.w_gpu_share) * _minmax_normalize(
            share_row, feasible
        )
    if feat.local and cfg.w_local:
        parts["OpenLocal"] = cfg.w_local * _minmax_normalize(
            local_score(ec, st, u), feasible
        )
    if feat.prefer_avoid and cfg.w_prefer_avoid:
        # NodePreferAvoidPods (w=10000, no NormalizeScore): raw 0/100 table
        parts["NodePreferAvoidPods"] = cfg.w_prefer_avoid * ec.avoid_score[u]
    for k, entry in enumerate(extra):
        if entry[0] == "score":
            parts[f"Extra[{k}]"] = float(entry[2]) * entry[1](ec, st, u, feasible)
    return parts


def pod_step(  # opensim-lint: jit-region
    ec: EncodedCluster, stat: StaticTables, st: ScanState, u,
    feat: Features = ALL_FEATURES, cfg=None, extra: tuple = (),
    count_all: bool = False,
) -> StepResult:
    """One pod through the full pipeline. Mirrors scheduleOne
    (vendor/.../scheduler/scheduler.go:441) minus the bind goroutine.
    The four static filters are a single precomputed-row gather; only
    usage-dependent kernels the workload actually exercises evaluate per
    step (see Features). `cfg` (SchedulerConfig) adjusts plugin weights and
    disables, mirroring --default-scheduler-config.

    `extra` is the WithExtraRegistry equivalent (simulator.go:190-200,
    :471-500): out-of-tree plugins as jittable callables. Each entry is
    ("filter", fn) where fn(ec, st, u) -> bool [N], or ("score", fn, weight)
    where fn(ec, st, u, feasible) -> f32 [N] (already 0-100 scaled)."""
    from ..engine.schedconfig import DEFAULT_CONFIG

    cfg = cfg or DEFAULT_CONFIG
    valid = ec.node_valid
    aff_mask = stat.aff_mask[u]
    static_pass = stat.static_pass[u]  # valid already folded in
    true_mask = jnp.ones_like(static_pass)
    masks = [ports_filter(ec, st, u) if feat.ports and cfg.f_ports else true_mask]
    alloc_eff = effective_alloc(ec, st) if feat.gc_dyn else None
    if cfg.f_fit:
        fit_mask, insufficient = fit_filter(
            ec, st, u, alloc=alloc_eff, ignored_cols=cfg.fit_ignored_cols
        )
    else:
        fit_mask, insufficient = true_mask, jnp.zeros_like(ec.alloc, dtype=bool)
    masks.append(fit_mask)
    masks.append(
        spread_filter(ec, st, u, aff_mask & valid)
        if feat.spread_hard and cfg.f_spread
        else true_mask
    )
    masks.append(interpod_filter(ec, st, u) if feat.interpod and cfg.f_interpod else true_mask)
    masks.append(gpu_filter(ec, st, u) if feat.gpu and cfg.f_gpu else true_mask)
    masks.append(local_filter(ec, st, u) if feat.local and cfg.f_local else true_mask)
    extra_filter = true_mask
    for entry in extra:
        if entry[0] == "filter":
            extra_filter = extra_filter & entry[1](ec, st, u)
    masks.append(extra_filter)  # dedicated F_EXTRA reason slot

    passed_list = []
    passed_so_far = static_pass
    insufficient_attributed = None
    for i, m in enumerate(masks):
        passed_list.append(passed_so_far)
        if i == F_FIT - F_PORTS:
            # per-resource counts attribute only nodes that reached the fit
            # filter (k8s reports each node under its first failing plugin)
            insufficient_attributed = insufficient & passed_so_far[:, None]
        passed_so_far = passed_so_far & m
    feasible = passed_so_far

    # Failure accounting (several reductions) only runs on the rare
    # unschedulable step — lax.cond skips it on every successful bind.
    def count_fails(_):
        counts = jnp.stack(
            [jnp.sum(p & ~m) for p, m in zip(passed_list, masks)]
        ).astype(jnp.int32)
        per_res = jnp.sum(insufficient_attributed & valid[:, None], axis=0).astype(jnp.int32)
        return counts, per_res

    def no_fails(_):
        return (
            jnp.zeros((len(masks),), jnp.int32),
            jnp.zeros((insufficient.shape[1],), jnp.int32),
        )

    any_feasible = jnp.any(feasible)
    if count_all:
        # explain mode (ISSUE 7): per-filter reject counts for EVERY step,
        # not just failures — the decision-audit aggregate needs to see
        # filter pressure on successful binds too. Trace-time flag, so the
        # default compile keeps the cond-skipped accounting below.
        fail_counts, per_res_insufficient = count_fails(None)
    else:
        fail_counts, per_res_insufficient = jax.lax.cond(
            any_feasible, no_fails, count_fails, None
        )

    # score plugins × weights (registry.go:119-132 + the three sim plugins):
    # accumulated in score_parts order — the per-plugin breakdown IS the
    # scoring code path, so the decision audit (engine/explain.py) reports
    # exactly the terms selectHost summed. Normalization runs over the
    # feasible set, matching the framework normalizing the filtered-node
    # score list (framework.go:635).
    score = jnp.zeros_like(stat.share_raw[u])
    for term in score_parts(ec, stat, st, u, feasible, feat, cfg, extra).values():
        score = score + term
    # ImageLocality: 0 (no images in sim)

    neg = jnp.float32(-1e30)
    best = jnp.argmax(jnp.where(feasible, score, neg))
    chosen = jnp.where(any_feasible, best, -1).astype(jnp.int32)
    return StepResult(
        feasible=feasible,
        score=score,
        chosen=chosen,
        fail_counts=fail_counts,
        insufficient=per_res_insufficient,
    )


def bind_update(ec: EncodedCluster, st: ScanState, u, node, apply,
                feat: Features = ALL_FEATURES):  # opensim-lint: jit-region
    """State transition on bind — the tensorized equivalent of the Reserve +
    Bind plugin chain writing back into the fake clientset
    (plugin/simon.go:104-126, open-gpu-share.go:147-245, open-local.go:175-254).

    `apply` (bool scalar) gates the whole update so the scan body needs no
    state-select afterwards. Every update is a single-ROW
    dynamic-update-slice (``.at[row]``): with the scan carry donated, XLA
    performs them in place, so per-step HBM traffic is O(row), not O(state)
    — the difference between 50k binds costing ~50 MB vs ~50 GB of writes.

    Returns (new_state, gpu_take[Gd]) — gpu_take is the number of requested
    GPU slots packed onto each device (the reference's devId annotation)."""
    applyf = apply.astype(jnp.float32)

    used = st.used.at[node].add(ec.req[u] * applyf)

    # host-port counts: one row, multi-hot over the template's ports
    port_used = st.port_used
    if feat.ports:
        ports = ec.ports[u]  # [Hp]
        Hports = st.port_used.shape[1]
        port_hot = jnp.sum(
            (jnp.arange(Hports)[None, :] == ports[:, None]) & (ports[:, None] >= 0), axis=0
        ).astype(jnp.float32)  # [Hports]
        port_used = st.port_used.at[node].add(port_hot * applyf)

    # domain selector counts: one row per topology key (Tk is tiny, the
    # Python loop unrolls into Tk dynamic-update-slices)
    dom_sel = st.dom_sel
    if feat.sel_counts:
        doms = ec.node_domain[node]  # [Tk]
        matches = ec.matches_sel[u].astype(jnp.float32) * applyf  # [A]
        for tk in range(int(ec.node_domain.shape[1])):
            dom_sel = dom_sel.at[doms[tk]].add(matches)

    # existing-anti / symmetric-preferred term counts: element updates
    dom_anti = st.dom_anti
    if feat.interpod:
        g_doms = ec.node_domain[node, ec.anti_g_topo]  # [G]
        anti_vals = ec.anti_g[u].astype(jnp.float32) * applyf
        for g in range(int(ec.anti_g_topo.shape[0])):
            dom_anti = dom_anti.at[g_doms[g], g].add(anti_vals[g])

    dom_prefw = st.dom_prefw
    if feat.prefg:
        p_doms = ec.node_domain[node, ec.prefg_topo]  # [Gp]
        pref_vals = ec.prefg_w[u] * applyf
        for g in range(int(ec.prefg_topo.shape[0])):
            dom_prefw = dom_prefw.at[p_doms[g], g].add(pref_vals[g])

    # gpu-share packing (AllocateGpuId, gpunodeinfo.go:232-290): single-GPU
    # pods take the tightest-fitting device; multi-GPU pods use the greedy
    # two-pointer packing with device reuse.
    gpu_free = st.gpu_free
    take = jnp.zeros_like(st.gpu_free[0])
    if feat.gpu:
        mem = ec.gpu_mem[u]
        cnt = ec.gpu_count[u].astype(jnp.float32)
        free = st.gpu_free[node]  # [Gd]
        chunks = jnp.floor_divide(free, jnp.maximum(mem, 1.0))
        cum = jnp.cumsum(chunks)
        take_greedy = jnp.clip(cnt - (cum - chunks), 0.0, chunks)
        big = jnp.float32(1e30)
        fits = free >= mem
        tight = jnp.argmin(jnp.where(fits, free, big))
        # a force-bound pod can land on a node where nothing fits — take 0
        # rather than driving gpu_free negative
        take_tight = ((jnp.arange(free.shape[0]) == tight) & jnp.any(fits)).astype(jnp.float32)
        take = jnp.where(cnt == 1, take_tight, take_greedy)
        take = jnp.where(mem > 0, take, 0.0)
        gpu_free = st.gpu_free.at[node].add(-(take * mem) * applyf)

    vg_free = st.vg_free
    dev_free = st.dev_free
    if feat.local:
        # open-local LVM: tightest-fitting VG (ascending free-size first-fit,
        # vendored common.go:111-116); a force-bound pod that fits nowhere
        # takes nothing rather than driving vg_free negative
        lvm = ec.lvm_req[u]
        vg_free_n = st.vg_free[node]
        big = jnp.float32(1e30)
        vg_fits = vg_free_n >= lvm
        vg_choice = jnp.argmin(jnp.where(vg_fits, vg_free_n, big))
        vg_hot = ((jnp.arange(st.vg_free.shape[1]) == vg_choice) & jnp.any(vg_fits)).astype(jnp.float32)
        vg_free = st.vg_free.at[node].add(-(vg_hot * jnp.maximum(lvm, 0.0)) * applyf)

        # open-local exclusive devices: one device per volume, smallest
        # volume first onto the smallest-capacity fitting free device
        # (CheckExclusiveResourceMeetsPVCSize, common.go:290-349; ties by
        # lowest device index)
        dev_free_n = st.dev_free[node]  # [Dv]
        dev_cap_n = ec.node_dev_cap[node]
        dev_taken = jnp.zeros_like(dev_free_n)
        big = jnp.float32(1e30)
        Mv = ec.dev_req_sizes.shape[2]
        for media in (0, 1):
            for i in reversed(range(Mv)):  # ascending sizes; 0-pads skipped
                size = ec.dev_req_sizes[u, media, i]
                cand = (
                    (ec.node_dev_media[node] == media)
                    & (dev_free_n >= size)
                    & (dev_free_n > 0)
                    & (dev_taken == 0)
                )
                choice = jnp.argmin(jnp.where(cand, dev_cap_n, big))
                hot = (jnp.arange(dev_free_n.shape[0]) == choice) & jnp.any(cand) & (size > 0)
                dev_taken = jnp.maximum(dev_taken, hot.astype(jnp.float32))
        dev_free = st.dev_free.at[node].set(
            jnp.where((dev_taken > 0) & apply, 0.0, dev_free_n)
        )

    return (
        st._replace(
            used=used,
            port_used=port_used,
            dom_sel=dom_sel,
            dom_anti=dom_anti,
            dom_prefw=dom_prefw,
            gpu_free=gpu_free,
            vg_free=vg_free,
            dev_free=dev_free,
        ),
        take * applyf,
    )
