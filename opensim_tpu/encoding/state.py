"""ClusterState tensor assembly.

Builds the device-resident encoded cluster: node resource/label/taint
tensors, per-template scheduling encodings, global inter-pod-affinity term
tables, and the initial scan carry. This is the TPU-native replacement for
the reference's scheduler cache + snapshot
(``vendor/k8s.io/kubernetes/pkg/scheduler/internal/cache``): instead of an
object graph snapshotted per cycle, the cluster IS a set of HBM tensors and
the "snapshot" is the ``lax.scan`` carry.

Shape conventions (all static, padded):
  N  nodes (padded, ``node_valid`` masks)     R  resource axis
  K  label keys        Tt taints/node         Tl tolerations/template
  U  templates         T/Q/V node-affinity terms/reqs/values per template
  A  selectors         G  global anti-affinity terms
  Gp global preferred/symmetric-score terms   Tk topology keys
  D  topology domains (+1 trash row for masked scatters)
  Hp host-ports/template                      Cs spread constraints/template
  Ti/Tn required pod-affinity/anti terms      Pp preferred node-affinity terms
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..models.objects import Node, Pod
from . import vocab as V
from .templates import SchedTemplate, TemplateSet

_NAN = float("nan")


class EncodedCluster(NamedTuple):
    """Static (read-only during a scan) cluster tensors."""

    # nodes
    node_valid: np.ndarray  # [N] bool
    alloc: np.ndarray  # [N, R] f32
    unschedulable: np.ndarray  # [N] bool
    taint_key: np.ndarray  # [N, Tt] i32 (-1 pad)
    taint_val: np.ndarray  # [N, Tt] i32
    taint_effect: np.ndarray  # [N, Tt] i32 (-1 pad)
    label_val: np.ndarray  # [N, K] i32 (-1 absent)
    label_num: np.ndarray  # [N, K] f32 (NaN when not numeric)
    node_domain: np.ndarray  # [N, Tk] i32 (D = trash row when label absent)
    domain_topo: np.ndarray  # [D+1] i32 topo-key index owning each domain (-1 trash)
    # templates
    req: np.ndarray  # [U, R] f32
    tol_valid: np.ndarray  # [U, Tl] bool
    tol_key: np.ndarray  # [U, Tl] i32 (-1 = empty key → all)
    tol_op: np.ndarray  # [U, Tl] i32 (TOL_EQUAL/TOL_EXISTS)
    tol_val: np.ndarray  # [U, Tl] i32
    tol_effect: np.ndarray  # [U, Tl] i32 (-1 = all effects)
    ns_key: np.ndarray  # [U, Qs] i32 (-1 pad) nodeSelector map
    ns_val: np.ndarray  # [U, Qs] i32
    has_req_aff: np.ndarray  # [U] bool
    aff_term_valid: np.ndarray  # [U, T] bool
    aff_key: np.ndarray  # [U, T, Q] i32
    aff_op: np.ndarray  # [U, T, Q] i32 (OP_PAD → vacuously true)
    aff_val: np.ndarray  # [U, T, Q, Vv] i32 (-1 pad)
    aff_num: np.ndarray  # [U, T, Q] f32
    pna_weight: np.ndarray  # [U, Pp] f32 (0 pad) preferred node affinity
    pna_key: np.ndarray  # [U, Pp, Q] i32
    pna_op: np.ndarray  # [U, Pp, Q] i32
    pna_val: np.ndarray  # [U, Pp, Q, Vv] i32
    pna_num: np.ndarray  # [U, Pp, Q] f32
    ports: np.ndarray  # [U, Hp] i32 (-1 pad)
    port_conflict: np.ndarray  # [Hports, Hports] bool — wildcard-aware overlap
    spr_topo: np.ndarray  # [U, Cs] i32 topo-key index (-1 pad)
    spr_sel: np.ndarray  # [U, Cs] i32 selector id
    spr_skew: np.ndarray  # [U, Cs] i32
    spr_hard: np.ndarray  # [U, Cs] bool
    at_sel: np.ndarray  # [U, Ti] i32 (-1 pad) required pod affinity
    at_topo: np.ndarray  # [U, Ti] i32 topo-key index
    an_sel: np.ndarray  # [U, Tn] i32 required anti-affinity
    an_topo: np.ndarray  # [U, Tn] i32
    pt_sel: np.ndarray  # [U, Tpp] i32 preferred pod terms (incoming side)
    pt_topo: np.ndarray  # [U, Tpp] i32
    pt_w: np.ndarray  # [U, Tpp] f32 signed
    matches_sel: np.ndarray  # [U, A] bool
    anti_g: np.ndarray  # [U, G] bool — template carries global anti term g
    prefg_w: np.ndarray  # [U, Gp] f32 — signed weights of symmetric terms carried
    pin: np.ndarray  # [U] i32 node index; -1 none; -2 unknown node
    # global term tables
    anti_g_sel: np.ndarray  # [G] i32
    anti_g_topo: np.ndarray  # [G] i32 topo-key index
    prefg_sel: np.ndarray  # [Gp] i32
    prefg_topo: np.ndarray  # [Gp] i32
    # gpu-share extension (zeros when unused)
    gpu_mem: np.ndarray  # [U] f32 per-GPU memory request
    gpu_count: np.ndarray  # [U] i32
    node_gpu_mem: np.ndarray  # [N, Gd] f32 per-device total memory
    # one-hot over the resource axis marking alibabacloud.com/gpu-count. The
    # reference rewrites that allocatable at gpushare Reserve to the count of
    # not-fully-used devices (open-gpu-share.go:147-188, gpunodeinfo.go:354-369),
    # so its alloc column is DYNAMIC on device-bearing nodes — kernels derive
    # it from gpu_free instead of this table when Features.gc_dyn is set.
    gc_mask: np.ndarray  # [R] bool
    # open-local extension
    avoid_score: np.ndarray  # [U, N] f32 NodePreferAvoidPods raw score (0 or 100)
    lvm_req: np.ndarray  # [U] f32 total LVM bytes requested
    dev_req: np.ndarray  # [U, 2] f32 max exclusive-device bytes by media (score proxy)
    dev_req_count: np.ndarray  # [U, 2] i32 number of exclusive devices by media
    dev_req_sizes: np.ndarray  # [U, 2, Mv] f32 per-volume sizes, sorted descending
    node_vg_cap: np.ndarray  # [N, Vg] f32 volume-group capacities
    node_dev_cap: np.ndarray  # [N, Dv] f32 device capacities
    node_dev_media: np.ndarray  # [N, Dv] i32 0=ssd 1=hdd (-1 pad)
    # log(k+2) lookup over possible per-key domain counts (k = 0..N): the
    # topology-spread normalizing weight is a GATHER from this table in
    # every engine, so the XLA scan, the numpy precompute (native path) and
    # the sweeps produce bitwise-identical weights — XLA:CPU's f32 log and
    # numpy's differ by 1 ulp on ~3% of inputs, enough to flip score ties.
    log_sizes: np.ndarray  # [N+1] f32


class ScanState(NamedTuple):
    """Mutable carry threaded through the bind scan."""

    used: np.ndarray  # [N, R] f32
    port_used: np.ndarray  # [N, Hports] f32
    dom_sel: np.ndarray  # [D+1, A] f32
    dom_anti: np.ndarray  # [D+1, G] f32
    dom_prefw: np.ndarray  # [D+1, Gp] f32
    gpu_free: np.ndarray  # [N, Gd] f32
    vg_free: np.ndarray  # [N, Vg] f32
    dev_free: np.ndarray  # [N, Dv] f32 (0 when device is taken or absent)


@dataclass
class ClusterMeta:
    """Host-side decode tables for reports."""

    node_names: List[str] = field(default_factory=list)
    n_real_nodes: int = 0
    vocab: Optional[V.Vocab] = None
    template_set: Optional[TemplateSet] = None
    resource_names: List[str] = field(default_factory=list)
    n_domains: int = 0
    node_gpu_count: Optional[np.ndarray] = None  # [N] i32
    node_vg_names: List[List[str]] = field(default_factory=list)
    node_dev_names: List[List[str]] = field(default_factory=list)
    # original capacities (host copies) for usage reports
    node_gpu_mem: Optional[np.ndarray] = None  # [N, Gd] f32
    node_vg_cap: Optional[np.ndarray] = None  # [N, Vg] f32
    node_dev_cap: Optional[np.ndarray] = None  # [N, Dv] f32
    node_dev_media: Optional[np.ndarray] = None  # [N, Dv] i32


def _pad_to(n: int, mult: int) -> int:
    return max(mult, mult * math.ceil(n / mult))


def encode_labels(vocab: V.Vocab, labels: Dict[str, str], extra: Dict[str, str]) -> Dict[int, Tuple[int, float]]:
    out: Dict[int, Tuple[int, float]] = {}
    for k, v in {**labels, **extra}.items():
        kid = vocab.key_id(k)
        vid = vocab.val_id(str(v))
        try:
            num = float(int(str(v)))
        except ValueError:
            num = _NAN
        out[kid] = (vid, num)
    return out


class ClusterEncoder:
    """Accumulates nodes + pods, then materializes the tensors.

    Usage:
        enc = ClusterEncoder()
        enc.add_nodes(nodes)
        tmpl_ids = [enc.add_pod(p, owner_selector) for p in pods]
        cluster, state0, meta = enc.build()
    """

    def __init__(self, node_pad: int = 8) -> None:
        self.vocab = V.Vocab()
        self.ts = TemplateSet()
        self.nodes: List[Node] = []
        self.node_index: Dict[str, int] = {}
        self.node_pad = node_pad
        # encoded labels per node, built once at add_nodes and reused by
        # build() — encode_labels is 2×5k calls at headline shape otherwise
        self._node_enc: List[Dict[int, Tuple[int, float]]] = []

    # -- ingestion ----------------------------------------------------------

    def add_nodes(self, nodes: List[Node]) -> None:
        for n in nodes:
            if n.metadata.name in self.node_index:
                continue
            self.node_index[n.metadata.name] = len(self.nodes)
            self.nodes.append(n)
            # Pre-intern label/taint strings so vocab is complete.
            self._node_enc.append(
                encode_labels(self.vocab, n.metadata.labels, {"metadata.name": n.metadata.name})
            )
            for t in n.taints:
                self.vocab.key_id(t.key)
                self.vocab.val_id(t.value)
            for r in n.allocatable:
                self.vocab.resource_id(r)

    def add_pod(self, pod: Pod, owner_selector: Optional[dict] = None, hint: Optional[tuple] = None) -> int:
        return self.ts.add_pod(pod, owner_selector, hint=hint)

    # -- template feature interning (strings → ids) -------------------------

    def _intern_template(self, t: SchedTemplate) -> None:
        vb = self.vocab
        for r in t.requests:
            vb.resource_id(r)
        for k, v in t.node_selector.items():
            vb.key_id(k)
            vb.val_id(str(v))
        for key, _op, val, _eff in t.tolerations:
            if key:
                vb.key_id(key)
            vb.val_id(val)
        for term in t.affinity_terms:
            for e in (term.get("matchExpressions") or []) + (term.get("matchFields") or []):
                vb.key_id(str(e.get("key", "")) if e.get("key") != "metadata.name" else "metadata.name")
                for v in e.get("values") or []:
                    vb.val_id(str(v))
        for pref in t.pref_node_affinity:
            for e in ((pref.get("preference") or {}).get("matchExpressions") or []) + (
                (pref.get("preference") or {}).get("matchFields") or []
            ):
                vb.key_id(str(e.get("key", "")))
                for v in e.get("values") or []:
                    vb.val_id(str(v))
        for proto, port, ip in t.host_ports:
            vb.port_id(proto, port, ip)
        for c in t.spread:
            vb.topo_key_id(c.topo_key)
        for term in t.aff_terms + t.anti_terms:
            vb.topo_key_id(term.topo_key)
        for term in t.pref_terms:
            vb.topo_key_id(term.topo_key)

    # -- node-affinity term encoding helper ---------------------------------

    def _encode_terms(self, terms: List[dict], T: int, Q: int, Vv: int):
        vb = self.vocab
        valid = np.zeros((T,), dtype=bool)
        key = np.full((T, Q), -1, dtype=np.int32)
        op = np.full((T, Q), V.OP_PAD, dtype=np.int32)
        val = np.full((T, Q, Vv), -1, dtype=np.int32)
        num = np.full((T, Q), _NAN, dtype=np.float32)
        for ti, term in enumerate(terms[:T]):
            reqs = list(term.get("matchExpressions") or [])
            for f in term.get("matchFields") or []:
                f = dict(f)
                f["key"] = "metadata.name"
                reqs.append(f)
            valid[ti] = True
            for qi, e in enumerate(reqs[:Q]):
                key[ti, qi] = vb.label_keys.get(str(e.get("key", "metadata.name") if e.get("key") else ""), -1)
                if key[ti, qi] < 0:
                    key[ti, qi] = vb.key_id(str(e.get("key", "")))
                op[ti, qi] = V.NODE_OP_CODES.get(str(e.get("operator", "")), V.OP_PAD)
                vals = [str(x) for x in (e.get("values") or [])]
                for vi, x in enumerate(vals[:Vv]):
                    val[ti, qi, vi] = vb.val_id(x)
                if op[ti, qi] in (V.OP_GT, V.OP_LT) and vals:
                    try:
                        num[ti, qi] = float(int(vals[0]))
                    except ValueError:
                        num[ti, qi] = _NAN
        return valid, key, op, val, num

    # -- build --------------------------------------------------------------

    def build(self) -> Tuple[EncodedCluster, ScanState, ClusterMeta]:
        vb = self.vocab
        templates = self.ts.templates or [SchedTemplate()]
        for t in templates:
            self._intern_template(t)

        N = _pad_to(len(self.nodes), self.node_pad)
        R = vb.n_resources
        K = max(vb.n_label_keys, 1)
        U = len(templates)
        A = max(len(self.ts.selectors), 1)
        Tk = max(vb.n_topo_keys, 1)
        Hports = max(vb.n_ports, 1)

        Tt = max([len(n.taints) for n in self.nodes] + [1])
        Tl = max([len(t.tolerations) for t in templates] + [1])
        Qs = max([len(t.node_selector) for t in templates] + [1])
        T = max([len(t.affinity_terms) for t in templates] + [1])
        Q = max(
            [
                len((term.get("matchExpressions") or [])) + len((term.get("matchFields") or []))
                for t in templates
                for term in t.affinity_terms
            ]
            + [1]
        )
        Vv = max(
            [
                len(e.get("values") or [])
                for t in templates
                for term in t.affinity_terms
                for e in (term.get("matchExpressions") or []) + (term.get("matchFields") or [])
            ]
            + [
                len(e.get("values") or [])
                for t in templates
                for pref in t.pref_node_affinity
                for e in ((pref.get("preference") or {}).get("matchExpressions") or [])
            ]
            + [1]
        )
        Pp = max([len(t.pref_node_affinity) for t in templates] + [1])
        Qp = max(
            [
                len(((pref.get("preference") or {}).get("matchExpressions") or []))
                + len(((pref.get("preference") or {}).get("matchFields") or []))
                for t in templates
                for pref in t.pref_node_affinity
            ]
            + [1]
        )
        Qmax = max(Q, Qp)
        Hp = max([len(t.host_ports) for t in templates] + [1])
        Cs = max([len(t.spread) for t in templates] + [1])
        Ti = max([len(t.aff_terms) for t in templates] + [1])
        Tn = max([len(t.anti_terms) for t in templates] + [1])
        Tpp = max([len(t.pref_terms) for t in templates] + [1])

        # ---- node tensors
        node_valid = np.zeros((N,), dtype=bool)
        alloc = np.zeros((N, R), dtype=np.float32)
        unschedulable = np.zeros((N,), dtype=bool)
        taint_key = np.full((N, Tt), -1, dtype=np.int32)
        taint_val = np.full((N, Tt), -1, dtype=np.int32)
        taint_effect = np.full((N, Tt), -1, dtype=np.int32)
        label_val = np.full((N, K), -1, dtype=np.int32)
        label_num = np.full((N, K), _NAN, dtype=np.float32)

        for i, n in enumerate(self.nodes):
            node_valid[i] = True
            unschedulable[i] = n.unschedulable
            for rname, v in n.allocatable.items():
                rid = vb.resource_id(rname)
                if rid >= 0:
                    alloc[i, rid] = v * 1000.0 if rname == "cpu" else v
            for j, t in enumerate(n.taints[:Tt]):
                taint_key[i, j] = vb.key_id(t.key)
                taint_val[i, j] = vb.val_id(t.value)
                taint_effect[i, j] = V.EFFECT_CODES.get(t.effect, -1)
            for kid, (vid, num) in self._node_enc[i].items():
                if kid < K:
                    label_val[i, kid] = vid
                    label_num[i, kid] = num

        # ---- topology domains
        domain_ids: Dict[Tuple[int, int], int] = {}
        node_domain = np.zeros((N, Tk), dtype=np.int32)
        topo_key_to_label = [vb.label_keys.get(k) for k in vb.topo_keys.items()]
        for i in range(N):
            for tki in range(Tk):
                lk = topo_key_to_label[tki] if tki < len(topo_key_to_label) else -1
                vid = label_val[i, lk] if (node_valid[i] and lk is not None and lk >= 0) else -1
                if vid < 0:
                    node_domain[i, tki] = -1
                else:
                    node_domain[i, tki] = domain_ids.setdefault((tki, vid), len(domain_ids))
        D = max(len(domain_ids), 1)
        node_domain = np.where(node_domain < 0, D, node_domain).astype(np.int32)  # D = trash row
        domain_topo = np.full((D + 1,), -1, dtype=np.int32)
        for (tki, _vid), did in domain_ids.items():
            domain_topo[did] = tki

        # ---- global inter-pod term tables
        topo_idx = {k: i for i, k in enumerate(vb.topo_keys.items())}
        anti_table: Dict[Tuple[int, int], int] = {}
        pref_table: Dict[Tuple[int, int], int] = {}
        for t in templates:
            for term in t.anti_terms:
                anti_table.setdefault((term.sel_id, topo_idx.get(term.topo_key, -1)), len(anti_table))
            for term in t.pref_terms:
                pref_table.setdefault((term.sel_id, topo_idx.get(term.topo_key, -1)), len(pref_table))
            # existing pods' REQUIRED affinity terms score with hard weight 1
            for term in t.aff_terms:
                pref_table.setdefault((term.sel_id, topo_idx.get(term.topo_key, -1)), len(pref_table))
        G = max(len(anti_table), 1)
        Gp = max(len(pref_table), 1)
        anti_g_sel = np.zeros((G,), dtype=np.int32)
        anti_g_topo = np.zeros((G,), dtype=np.int32)
        for (sid, tki), g in anti_table.items():
            anti_g_sel[g] = sid
            anti_g_topo[g] = max(tki, 0)
        prefg_sel = np.zeros((Gp,), dtype=np.int32)
        prefg_topo = np.zeros((Gp,), dtype=np.int32)
        for (sid, tki), g in pref_table.items():
            prefg_sel[g] = sid
            prefg_topo[g] = max(tki, 0)

        # ---- template tensors
        req = np.zeros((U, R), dtype=np.float32)
        tol_valid = np.zeros((U, Tl), dtype=bool)
        tol_key = np.full((U, Tl), -1, dtype=np.int32)
        tol_op = np.zeros((U, Tl), dtype=np.int32)
        tol_val = np.full((U, Tl), -1, dtype=np.int32)
        tol_effect = np.full((U, Tl), -1, dtype=np.int32)
        ns_key = np.full((U, Qs), -1, dtype=np.int32)
        ns_val = np.full((U, Qs), -1, dtype=np.int32)
        has_req_aff = np.zeros((U,), dtype=bool)
        aff_term_valid = np.zeros((U, T), dtype=bool)
        aff_key = np.full((U, T, Qmax), -1, dtype=np.int32)
        aff_op = np.full((U, T, Qmax), V.OP_PAD, dtype=np.int32)
        aff_val = np.full((U, T, Qmax, Vv), -1, dtype=np.int32)
        aff_num = np.full((U, T, Qmax), _NAN, dtype=np.float32)
        pna_weight = np.zeros((U, Pp), dtype=np.float32)
        pna_key = np.full((U, Pp, Qmax), -1, dtype=np.int32)
        pna_op = np.full((U, Pp, Qmax), V.OP_PAD, dtype=np.int32)
        pna_val = np.full((U, Pp, Qmax, Vv), -1, dtype=np.int32)
        pna_num = np.full((U, Pp, Qmax), _NAN, dtype=np.float32)
        ports = np.full((U, Hp), -1, dtype=np.int32)
        spr_topo = np.full((U, Cs), -1, dtype=np.int32)
        spr_sel = np.zeros((U, Cs), dtype=np.int32)
        spr_skew = np.zeros((U, Cs), dtype=np.int32)
        spr_hard = np.zeros((U, Cs), dtype=bool)
        at_sel = np.full((U, Ti), -1, dtype=np.int32)
        at_topo = np.zeros((U, Ti), dtype=np.int32)
        an_sel = np.full((U, Tn), -1, dtype=np.int32)
        an_topo = np.zeros((U, Tn), dtype=np.int32)
        pt_sel = np.full((U, Tpp), -1, dtype=np.int32)
        pt_topo = np.zeros((U, Tpp), dtype=np.int32)
        pt_w = np.zeros((U, Tpp), dtype=np.float32)
        anti_g = np.zeros((U, G), dtype=bool)
        prefg_w = np.zeros((U, Gp), dtype=np.float32)
        pin = np.full((U,), -1, dtype=np.int32)
        gpu_mem = np.zeros((U,), dtype=np.float32)
        gpu_count = np.zeros((U,), dtype=np.int32)

        for u, t in enumerate(templates):
            for rid, v in vb.encode_resources(t.requests).items():
                req[u, rid] = v
            req[u, V.RES_PODS] += 1.0  # every pod consumes one pod slot
            if t.node_name:
                pin[u] = self.node_index.get(t.node_name, -2)
            for j, (key, op, val, eff) in enumerate(t.tolerations[:Tl]):
                tol_valid[u, j] = True
                tol_key[u, j] = vb.label_keys.get(key, -1) if key else -1
                tol_op[u, j] = V.TOL_EXISTS if op == "Exists" else V.TOL_EQUAL
                tol_val[u, j] = vb.label_vals.get(val, -1)
                tol_effect[u, j] = V.EFFECT_CODES.get(eff, -1) if eff else -1
            for j, (k, v) in enumerate(sorted(t.node_selector.items())[:Qs]):
                ns_key[u, j] = vb.key_id(k)
                ns_val[u, j] = vb.label_vals.get(str(v), -1)
            if t.affinity_terms:
                has_req_aff[u] = True
                tv, tk_, to, tva, tn = self._encode_terms(t.affinity_terms, T, Qmax, Vv)
                aff_term_valid[u], aff_key[u], aff_op[u], aff_val[u], aff_num[u] = tv, tk_, to, tva, tn
            if t.pref_node_affinity:
                terms = [p.get("preference") or {} for p in t.pref_node_affinity]
                tv, tk_, to, tva, tn = self._encode_terms(terms, Pp, Qmax, Vv)
                pna_key[u], pna_op[u], pna_val[u], pna_num[u] = tk_, to, tva, tn
                for j, p in enumerate(t.pref_node_affinity[:Pp]):
                    pna_weight[u, j] = float(p.get("weight", 0))
            for j, (proto, port, ip) in enumerate(t.host_ports[:Hp]):
                ports[u, j] = vb.port_id(proto, port, ip)
            for j, c in enumerate(t.spread[:Cs]):
                spr_topo[u, j] = topo_idx.get(c.topo_key, -1)
                spr_sel[u, j] = c.sel_id
                spr_skew[u, j] = c.max_skew
                spr_hard[u, j] = c.hard
            for j, term in enumerate(t.aff_terms[:Ti]):
                # filter counts pods matching ALL terms — use the conjunction
                # selector when the template has several (templates.py)
                at_sel[u, j] = t.aff_conj if t.aff_conj >= 0 else term.sel_id
                at_topo[u, j] = max(topo_idx.get(term.topo_key, -1), 0)
            for j, term in enumerate(t.anti_terms[:Tn]):
                an_sel[u, j] = term.sel_id
                an_topo[u, j] = max(topo_idx.get(term.topo_key, -1), 0)
                anti_g[u, anti_table[(term.sel_id, topo_idx.get(term.topo_key, -1))]] = True
            for j, term in enumerate(t.pref_terms[:Tpp]):
                pt_sel[u, j] = term.sel_id
                pt_topo[u, j] = max(topo_idx.get(term.topo_key, -1), 0)
                pt_w[u, j] = term.weight
                prefg_w[u, pref_table[(term.sel_id, topo_idx.get(term.topo_key, -1))]] += term.weight
            for term in t.aff_terms:
                # symmetric hard-affinity weight (HardPodAffinityWeight = 1)
                prefg_w[u, pref_table[(term.sel_id, topo_idx.get(term.topo_key, -1))]] += 1.0
            gpu_mem[u] = t.gpu_mem
            gpu_count[u] = t.gpu_count

        matches_sel = np.zeros((U, A), dtype=bool)
        mm = self.ts.match_matrix()
        if mm.size:
            matches_sel[: mm.shape[0], : mm.shape[1]] = mm

        # ---- NodePreferAvoidPods (node_prefer_avoid_pods.go:47-82): pods
        # controlled by an RS/RC listed in the node's preferAvoidPods
        # annotation score 0 there, 100 elsewhere
        avoid_score = np.full((U, N), 100.0, dtype=np.float32)
        for i, n in enumerate(self.nodes):
            anno = n.metadata.annotations.get("scheduler.alpha.kubernetes.io/preferAvoidPods")
            if not anno:
                continue
            try:
                entries = json.loads(anno).get("preferAvoidPods") or []
            except (ValueError, AttributeError):
                continue
            avoided = {
                (
                    str(((e.get("podSignature") or {}).get("podController") or {}).get("kind", "")),
                    str(((e.get("podSignature") or {}).get("podController") or {}).get("uid", "")),
                )
                for e in entries
            }
            for u, t in enumerate(templates):
                if t.controller[0] and tuple(t.controller) in avoided:
                    avoid_score[u, i] = 0.0

        # ---- extensions: encoded by their dedicated modules (task: gpu/local)
        from .extensions import encode_gpu_nodes, encode_local_storage, encode_local_requests

        node_gpu_mem, node_gpu_count = encode_gpu_nodes(self.nodes, N)
        from ..models.objects import RES_GPU_COUNT

        gc_mask = np.zeros((R,), dtype=bool)
        gc_col = vb.resources.get(RES_GPU_COUNT)
        if gc_col >= 0:
            gc_mask[gc_col] = True
        node_vg_cap, node_dev_cap, node_dev_media, vg_names, dev_names = encode_local_storage(self.nodes, N)
        lvm_req, dev_req, dev_req_count, dev_req_sizes = encode_local_requests(templates)

        cluster = EncodedCluster(
            node_valid=node_valid,
            alloc=alloc,
            unschedulable=unschedulable,
            taint_key=taint_key,
            taint_val=taint_val,
            taint_effect=taint_effect,
            label_val=label_val,
            label_num=label_num,
            node_domain=node_domain,
            domain_topo=domain_topo,
            req=req,
            tol_valid=tol_valid,
            tol_key=tol_key,
            tol_op=tol_op,
            tol_val=tol_val,
            tol_effect=tol_effect,
            ns_key=ns_key,
            ns_val=ns_val,
            has_req_aff=has_req_aff,
            aff_term_valid=aff_term_valid,
            aff_key=aff_key,
            aff_op=aff_op,
            aff_val=aff_val,
            aff_num=aff_num,
            pna_weight=pna_weight,
            pna_key=pna_key,
            pna_op=pna_op,
            pna_val=pna_val,
            pna_num=pna_num,
            ports=ports,
            port_conflict=vb.port_conflict_matrix(),
            spr_topo=spr_topo,
            spr_sel=spr_sel,
            spr_skew=spr_skew,
            spr_hard=spr_hard,
            at_sel=at_sel,
            at_topo=at_topo,
            an_sel=an_sel,
            an_topo=an_topo,
            pt_sel=pt_sel,
            pt_topo=pt_topo,
            pt_w=pt_w,
            matches_sel=matches_sel,
            anti_g=anti_g,
            prefg_w=prefg_w,
            pin=pin,
            avoid_score=avoid_score,
            anti_g_sel=anti_g_sel,
            anti_g_topo=anti_g_topo,
            prefg_sel=prefg_sel,
            prefg_topo=prefg_topo,
            gpu_mem=gpu_mem,
            gpu_count=gpu_count,
            node_gpu_mem=node_gpu_mem,
            gc_mask=gc_mask,
            lvm_req=lvm_req,
            dev_req=dev_req,
            dev_req_count=dev_req_count,
            dev_req_sizes=dev_req_sizes,
            node_vg_cap=node_vg_cap,
            node_dev_cap=node_dev_cap,
            node_dev_media=node_dev_media,
            log_sizes=np.log(np.arange(N + 1, dtype=np.float64) + 2.0).astype(
                np.float32
            ),
        )

        state0 = ScanState(
            used=np.zeros((N, R), dtype=np.float32),
            port_used=np.zeros((N, Hports), dtype=np.float32),
            dom_sel=np.zeros((D + 1, A), dtype=np.float32),
            dom_anti=np.zeros((D + 1, G), dtype=np.float32),
            dom_prefw=np.zeros((D + 1, Gp), dtype=np.float32),
            gpu_free=node_gpu_mem.copy(),
            vg_free=node_vg_cap.copy(),
            dev_free=node_dev_cap.copy(),
        )

        meta = ClusterMeta(
            node_names=[n.metadata.name for n in self.nodes],
            n_real_nodes=len(self.nodes),
            vocab=vb,
            template_set=self.ts,
            resource_names=list(vb.resources.items()),
            n_domains=D,
            node_gpu_count=node_gpu_count,
            node_vg_names=vg_names,
            node_dev_names=dev_names,
            node_gpu_mem=node_gpu_mem.copy(),
            node_vg_cap=node_vg_cap.copy(),
            node_dev_cap=node_dev_cap.copy(),
            node_dev_media=node_dev_media.copy(),
        )
        return cluster, state0, meta
