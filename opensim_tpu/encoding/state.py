"""ClusterState tensor assembly.

Builds the device-resident encoded cluster: node resource/label/taint
tensors, per-template scheduling encodings, global inter-pod-affinity term
tables, and the initial scan carry. This is the TPU-native replacement for
the reference's scheduler cache + snapshot
(``vendor/k8s.io/kubernetes/pkg/scheduler/internal/cache``): instead of an
object graph snapshotted per cycle, the cluster IS a set of HBM tensors and
the "snapshot" is the ``lax.scan`` carry.

Shape conventions (all static, padded):
  N  nodes (padded, ``node_valid`` masks)     R  resource axis
  K  label keys        Tt taints/node         Tl tolerations/template
  U  templates         T/Q/V node-affinity terms/reqs/values per template
  A  selectors         G  global anti-affinity terms
  Gp global preferred/symmetric-score terms   Tk topology keys
  D  topology domains (+1 trash row for masked scatters)
  Hp host-ports/template                      Cs spread constraints/template
  Ti/Tn required pod-affinity/anti terms      Pp preferred node-affinity terms
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..models.objects import Node, Pod
from . import vocab as V
from .dtypes import log_size_table
from .templates import SchedTemplate, TemplateSet

_NAN = float("nan")


class EncodedCluster(NamedTuple):
    """Static (read-only during a scan) cluster tensors."""

    # nodes
    node_valid: np.ndarray  # [N] bool
    alloc: np.ndarray  # [N, R] f32
    unschedulable: np.ndarray  # [N] bool
    taint_key: np.ndarray  # [N, Tt] i32 (-1 pad)
    taint_val: np.ndarray  # [N, Tt] i32
    taint_effect: np.ndarray  # [N, Tt] i32 (-1 pad)
    label_val: np.ndarray  # [N, K] i32 (-1 absent)
    label_num: np.ndarray  # [N, K] f32 (NaN when not numeric)
    node_domain: np.ndarray  # [N, Tk] i32 (D = trash row when label absent)
    domain_topo: np.ndarray  # [D+1] i32 topo-key index owning each domain (-1 trash)
    # templates
    req: np.ndarray  # [U, R] f32
    tol_valid: np.ndarray  # [U, Tl] bool
    tol_key: np.ndarray  # [U, Tl] i32 (-1 = empty key → all)
    tol_op: np.ndarray  # [U, Tl] i32 (TOL_EQUAL/TOL_EXISTS)
    tol_val: np.ndarray  # [U, Tl] i32
    tol_effect: np.ndarray  # [U, Tl] i32 (-1 = all effects)
    ns_key: np.ndarray  # [U, Qs] i32 (-1 pad) nodeSelector map
    ns_val: np.ndarray  # [U, Qs] i32
    has_req_aff: np.ndarray  # [U] bool
    aff_term_valid: np.ndarray  # [U, T] bool
    aff_key: np.ndarray  # [U, T, Q] i32
    aff_op: np.ndarray  # [U, T, Q] i32 (OP_PAD → vacuously true)
    aff_val: np.ndarray  # [U, T, Q, Vv] i32 (-1 pad)
    aff_num: np.ndarray  # [U, T, Q] f32
    pna_weight: np.ndarray  # [U, Pp] f32 (0 pad) preferred node affinity
    pna_key: np.ndarray  # [U, Pp, Q] i32
    pna_op: np.ndarray  # [U, Pp, Q] i32
    pna_val: np.ndarray  # [U, Pp, Q, Vv] i32
    pna_num: np.ndarray  # [U, Pp, Q] f32
    ports: np.ndarray  # [U, Hp] i32 (-1 pad)
    port_conflict: np.ndarray  # [Hports, Hports] bool — wildcard-aware overlap
    spr_topo: np.ndarray  # [U, Cs] i32 topo-key index (-1 pad)
    spr_sel: np.ndarray  # [U, Cs] i32 selector id
    spr_skew: np.ndarray  # [U, Cs] i32
    spr_hard: np.ndarray  # [U, Cs] bool
    at_sel: np.ndarray  # [U, Ti] i32 (-1 pad) required pod affinity
    at_topo: np.ndarray  # [U, Ti] i32 topo-key index
    an_sel: np.ndarray  # [U, Tn] i32 required anti-affinity
    an_topo: np.ndarray  # [U, Tn] i32
    pt_sel: np.ndarray  # [U, Tpp] i32 preferred pod terms (incoming side)
    pt_topo: np.ndarray  # [U, Tpp] i32
    pt_w: np.ndarray  # [U, Tpp] f32 signed
    matches_sel: np.ndarray  # [U, A] bool
    anti_g: np.ndarray  # [U, G] bool — template carries global anti term g
    prefg_w: np.ndarray  # [U, Gp] f32 — signed weights of symmetric terms carried
    pin: np.ndarray  # [U] i32 node index; -1 none; -2 unknown node
    # global term tables
    anti_g_sel: np.ndarray  # [G] i32
    anti_g_topo: np.ndarray  # [G] i32 topo-key index
    prefg_sel: np.ndarray  # [Gp] i32
    prefg_topo: np.ndarray  # [Gp] i32
    # gpu-share extension (zeros when unused)
    gpu_mem: np.ndarray  # [U] f32 per-GPU memory request
    gpu_count: np.ndarray  # [U] i32
    node_gpu_mem: np.ndarray  # [N, Gd] f32 per-device total memory
    # one-hot over the resource axis marking alibabacloud.com/gpu-count. The
    # reference rewrites that allocatable at gpushare Reserve to the count of
    # not-fully-used devices (open-gpu-share.go:147-188, gpunodeinfo.go:354-369),
    # so its alloc column is DYNAMIC on device-bearing nodes — kernels derive
    # it from gpu_free instead of this table when Features.gc_dyn is set.
    gc_mask: np.ndarray  # [R] bool
    # open-local extension
    avoid_score: np.ndarray  # [U, N] f32 NodePreferAvoidPods raw score (0 or 100)
    lvm_req: np.ndarray  # [U] f32 total LVM bytes requested
    dev_req: np.ndarray  # [U, 2] f32 max exclusive-device bytes by media (score proxy)
    dev_req_count: np.ndarray  # [U, 2] i32 number of exclusive devices by media
    dev_req_sizes: np.ndarray  # [U, 2, Mv] f32 per-volume sizes, sorted descending
    node_vg_cap: np.ndarray  # [N, Vg] f32 volume-group capacities
    node_dev_cap: np.ndarray  # [N, Dv] f32 device capacities
    node_dev_media: np.ndarray  # [N, Dv] i32 0=ssd 1=hdd (-1 pad)
    # log(k+2) lookup over possible per-key domain counts (k = 0..N): the
    # topology-spread normalizing weight is a GATHER from this table in
    # every engine, so the XLA scan, the numpy precompute (native path) and
    # the sweeps produce bitwise-identical weights — XLA:CPU's f32 log and
    # numpy's differ by 1 ulp on ~3% of inputs, enough to flip score ties.
    log_sizes: np.ndarray  # [N+1] f32


class ScanState(NamedTuple):
    """Mutable carry threaded through the bind scan."""

    used: np.ndarray  # [N, R] f32
    port_used: np.ndarray  # [N, Hports] f32
    dom_sel: np.ndarray  # [D+1, A] f32
    dom_anti: np.ndarray  # [D+1, G] f32
    dom_prefw: np.ndarray  # [D+1, Gp] f32
    gpu_free: np.ndarray  # [N, Gd] f32
    vg_free: np.ndarray  # [N, Vg] f32
    dev_free: np.ndarray  # [N, Dv] f32 (0 when device is taken or absent)


@dataclass
class ClusterMeta:
    """Host-side decode tables for reports."""

    node_names: List[str] = field(default_factory=list)
    n_real_nodes: int = 0
    vocab: Optional[V.Vocab] = None
    template_set: Optional[TemplateSet] = None
    resource_names: List[str] = field(default_factory=list)
    n_domains: int = 0
    node_gpu_count: Optional[np.ndarray] = None  # [N] i32
    node_vg_names: List[List[str]] = field(default_factory=list)
    node_dev_names: List[List[str]] = field(default_factory=list)
    # original capacities (host copies) for usage reports
    node_gpu_mem: Optional[np.ndarray] = None  # [N, Gd] f32
    node_vg_cap: Optional[np.ndarray] = None  # [N, Vg] f32
    node_dev_cap: Optional[np.ndarray] = None  # [N, Dv] f32
    node_dev_media: Optional[np.ndarray] = None  # [N, Dv] i32


def _pad_to(n: int, mult: int) -> int:
    return max(mult, mult * math.ceil(n / mult))


def _grown(a: np.ndarray, shape: Tuple[int, ...], fill: object) -> np.ndarray:
    """Re-allocate `a` at `shape`, copying the existing prefix block and
    filling the rest with `fill` (axis growth for delta re-encoding)."""
    out = np.full(shape, fill, dtype=a.dtype)
    out[tuple(slice(0, s) for s in a.shape)] = a
    return out


@dataclass
class NodeArenas:
    """The O(N) node-axis build products, cached across builds.

    This is the expensive half of ``ClusterEncoder.build()`` at cluster
    scale (the per-node python loop over labels/taints/resources/domains).
    The incremental-prepare layer reuses these arenas across repeated
    builds so a delta build pays O(changes), not O(cluster). Arrays are
    immutable once built — ``extend`` paths re-allocate instead of
    mutating — so forked encoders share them by reference."""

    N: int
    K: int  # label-key axis width the arrays were built at
    R: int  # resource axis width the arrays were built at
    Tt: int
    node_valid: np.ndarray
    alloc: np.ndarray
    unschedulable: np.ndarray
    taint_key: np.ndarray
    taint_val: np.ndarray
    taint_effect: np.ndarray
    label_val: np.ndarray
    label_num: np.ndarray
    domain_ids: Dict[Tuple[int, int], int]  # (topo key idx, label vid) -> domain id
    node_domain: np.ndarray  # [N, n_topo] raw domain ids, -1 = absent (pre-trash)
    n_topo: int  # real topo-key count covered by node_domain columns
    node_gpu_mem: np.ndarray
    node_gpu_count: np.ndarray
    node_vg_cap: np.ndarray
    node_dev_cap: np.ndarray
    node_dev_media: np.ndarray
    vg_names: List[List[str]]
    dev_names: List[List[str]]
    avoid_entries: List[Tuple[int, frozenset]]  # (node idx, {(kind, uid)})

    def clone(self) -> "NodeArenas":
        import copy as _copy

        new = _copy.copy(self)
        # the only pieces mutated in place by domain-column extension
        new.domain_ids = dict(self.domain_ids)
        return new


def encode_labels(vocab: V.Vocab, labels: Dict[str, str], extra: Dict[str, str]) -> Dict[int, Tuple[int, float]]:
    out: Dict[int, Tuple[int, float]] = {}
    for k, v in {**labels, **extra}.items():
        kid = vocab.key_id(k)
        vid = vocab.val_id(str(v))
        try:
            num = float(int(str(v)))
        except ValueError:
            num = _NAN
        out[kid] = (vid, num)
    return out


class ClusterEncoder:
    """Accumulates nodes + pods, then materializes the tensors.

    Usage:
        enc = ClusterEncoder()
        enc.add_nodes(nodes)
        tmpl_ids = [enc.add_pod(p, owner_selector) for p in pods]
        cluster, state0, meta = enc.build()
    """

    def __init__(self, node_pad: int = 8) -> None:
        self.vocab = V.Vocab()
        self.ts = TemplateSet()
        self.nodes: List[Node] = []
        self.node_index: Dict[str, int] = {}
        self.node_pad = node_pad
        # encoded labels per node, built once at add_nodes and reused by
        # build() — encode_labels is 2×5k calls at headline shape otherwise
        self._node_enc: List[Dict[int, Tuple[int, float]]] = []
        # cached node-axis build (incremental prepare: rebuilds skip the
        # O(N) node loop) and the count of templates already interned
        self._arenas: Optional[NodeArenas] = None
        self._n_interned = 0

    def fork(self) -> "ClusterEncoder":
        """Copy-on-write fork for delta re-encoding: vocab and template
        tables are copied (they are append-only, so the base stays valid),
        built node arenas are shared by reference."""
        new = object.__new__(ClusterEncoder)
        new.vocab = self.vocab.clone()
        new.ts = self.ts.clone()
        new.nodes = list(self.nodes)
        new.node_index = dict(self.node_index)
        new.node_pad = self.node_pad
        new._node_enc = list(self._node_enc)
        new._arenas = self._arenas.clone() if self._arenas is not None else None
        new._n_interned = self._n_interned
        return new

    # -- ingestion ----------------------------------------------------------

    def add_nodes(self, nodes: List[Node]) -> None:
        for n in nodes:
            if n.metadata.name in self.node_index:
                continue
            self.node_index[n.metadata.name] = len(self.nodes)
            self.nodes.append(n)
            # Pre-intern label/taint strings so vocab is complete.
            self._node_enc.append(
                encode_labels(self.vocab, n.metadata.labels, {"metadata.name": n.metadata.name})
            )
            for t in n.taints:
                self.vocab.key_id(t.key)
                self.vocab.val_id(t.value)
            for r in n.allocatable:
                self.vocab.resource_id(r)

    def add_pod(self, pod: Pod, owner_selector: Optional[dict] = None, hint: Optional[tuple] = None) -> int:
        return self.ts.add_pod(pod, owner_selector, hint=hint)

    # -- template feature interning (strings → ids) -------------------------

    def _intern_template(self, t: SchedTemplate) -> None:
        vb = self.vocab
        for r in t.requests:
            vb.resource_id(r)
        for k, v in t.node_selector.items():
            vb.key_id(k)
            vb.val_id(str(v))
        for key, _op, val, _eff in t.tolerations:
            if key:
                vb.key_id(key)
            vb.val_id(val)
        for term in t.affinity_terms:
            for e in (term.get("matchExpressions") or []) + (term.get("matchFields") or []):
                vb.key_id(str(e.get("key", "")) if e.get("key") != "metadata.name" else "metadata.name")
                for v in e.get("values") or []:
                    vb.val_id(str(v))
        for pref in t.pref_node_affinity:
            for e in ((pref.get("preference") or {}).get("matchExpressions") or []) + (
                (pref.get("preference") or {}).get("matchFields") or []
            ):
                vb.key_id(str(e.get("key", "")))
                for v in e.get("values") or []:
                    vb.val_id(str(v))
        for proto, port, ip in t.host_ports:
            vb.port_id(proto, port, ip)
        for c in t.spread:
            vb.topo_key_id(c.topo_key)
        for term in t.aff_terms + t.anti_terms:
            vb.topo_key_id(term.topo_key)
        for term in t.pref_terms:
            vb.topo_key_id(term.topo_key)

    # -- node-affinity term encoding helper ---------------------------------

    def _encode_terms(
        self, terms: List[dict], T: int, Q: int, Vv: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        vb = self.vocab
        valid = np.zeros((T,), dtype=bool)
        key = np.full((T, Q), -1, dtype=np.int32)
        op = np.full((T, Q), V.OP_PAD, dtype=np.int32)
        val = np.full((T, Q, Vv), -1, dtype=np.int32)
        num = np.full((T, Q), _NAN, dtype=np.float32)
        for ti, term in enumerate(terms[:T]):
            reqs = list(term.get("matchExpressions") or [])
            for f in term.get("matchFields") or []:
                f = dict(f)
                f["key"] = "metadata.name"
                reqs.append(f)
            valid[ti] = True
            for qi, e in enumerate(reqs[:Q]):
                key[ti, qi] = vb.label_keys.get(str(e.get("key", "metadata.name") if e.get("key") else ""), -1)
                if key[ti, qi] < 0:
                    key[ti, qi] = vb.key_id(str(e.get("key", "")))
                op[ti, qi] = V.NODE_OP_CODES.get(str(e.get("operator", "")), V.OP_PAD)
                vals = [str(x) for x in (e.get("values") or [])]
                for vi, x in enumerate(vals[:Vv]):
                    val[ti, qi, vi] = vb.val_id(x)
                if op[ti, qi] in (V.OP_GT, V.OP_LT) and vals:
                    try:
                        num[ti, qi] = float(int(vals[0]))
                    except ValueError:
                        num[ti, qi] = _NAN
        return valid, key, op, val, num

    # -- build --------------------------------------------------------------

    def build(self) -> Tuple[EncodedCluster, ScanState, ClusterMeta]:
        """Materialize the tensors. Repeat builds on the same encoder (the
        incremental-prepare layer: a fork with extra pods or nodes) reuse
        the cached node arenas, so a rebuild pays O(templates + changes)
        instead of re-running the O(N) node loop."""
        for t in self.ts.templates[self._n_interned :]:
            self._intern_template(t)
        self._n_interned = len(self.ts.templates)
        templates = self.ts.templates or [SchedTemplate()]
        if self._arenas is None:
            self._arenas = self._build_node_arenas()
        self._extend_domain_columns(self._arenas)
        return self._assemble(self._arenas, templates)

    def _build_node_arenas(self) -> NodeArenas:
        """The O(N) half: per-node resource/taint/label tensors, topology
        domains, extension capacities, preferAvoidPods annotations."""
        vb = self.vocab
        N = _pad_to(len(self.nodes), self.node_pad)
        R = vb.n_resources
        K = max(vb.n_label_keys, 1)
        Tt = max([len(n.taints) for n in self.nodes] + [1])

        arrays = {
            "node_valid": np.zeros((N,), dtype=bool),
            "alloc": np.zeros((N, R), dtype=np.float32),
            "unschedulable": np.zeros((N,), dtype=bool),
            "taint_key": np.full((N, Tt), -1, dtype=np.int32),
            "taint_val": np.full((N, Tt), -1, dtype=np.int32),
            "taint_effect": np.full((N, Tt), -1, dtype=np.int32),
            "label_val": np.full((N, K), -1, dtype=np.int32),
            "label_num": np.full((N, K), _NAN, dtype=np.float32),
        }
        self._encode_node_rows(arrays, 0, K, Tt)

        # topology domains, raw ids (-1 = label absent); the trash-row
        # substitution happens at assemble time once D is final
        n_topo = vb.n_topo_keys
        domain_ids: Dict[Tuple[int, int], int] = {}
        node_domain = np.full((N, n_topo), -1, dtype=np.int32)
        label_val = arrays["label_val"]
        topo_key_to_label = [vb.label_keys.get(k) for k in vb.topo_keys.items()]
        for i in range(len(self.nodes)):
            for tki in range(n_topo):
                lk = topo_key_to_label[tki]
                vid = label_val[i, lk] if lk >= 0 else -1
                if vid >= 0:
                    node_domain[i, tki] = domain_ids.setdefault(
                        (tki, int(vid)), len(domain_ids)
                    )

        from .extensions import encode_gpu_nodes, encode_local_storage

        node_gpu_mem, node_gpu_count = encode_gpu_nodes(self.nodes, N)
        node_vg_cap, node_dev_cap, node_dev_media, vg_names, dev_names = (
            encode_local_storage(self.nodes, N)
        )

        avoid_entries: List[Tuple[int, frozenset]] = []
        for i, n in enumerate(self.nodes):
            avoided = self._node_avoid_set(n)
            if avoided:
                avoid_entries.append((i, avoided))

        return NodeArenas(
            N=N, K=K, R=R, Tt=Tt,
            node_valid=arrays["node_valid"], alloc=arrays["alloc"],
            unschedulable=arrays["unschedulable"],
            taint_key=arrays["taint_key"], taint_val=arrays["taint_val"],
            taint_effect=arrays["taint_effect"],
            label_val=arrays["label_val"], label_num=arrays["label_num"],
            domain_ids=domain_ids, node_domain=node_domain, n_topo=n_topo,
            node_gpu_mem=node_gpu_mem, node_gpu_count=node_gpu_count,
            node_vg_cap=node_vg_cap, node_dev_cap=node_dev_cap,
            node_dev_media=node_dev_media, vg_names=vg_names,
            dev_names=dev_names, avoid_entries=avoid_entries,
        )

    def _encode_node_rows(self, arrays: dict, start: int, K: int, Tt: int) -> None:
        vb = self.vocab
        for i in range(start, len(self.nodes)):
            n = self.nodes[i]
            arrays["node_valid"][i] = True
            arrays["unschedulable"][i] = n.unschedulable
            for rname, v in n.allocatable.items():
                rid = vb.resource_id(rname)
                if rid >= 0:
                    arrays["alloc"][i, rid] = v * 1000.0 if rname == "cpu" else v
            for j, t in enumerate(n.taints[:Tt]):
                arrays["taint_key"][i, j] = vb.key_id(t.key)
                arrays["taint_val"][i, j] = vb.val_id(t.value)
                arrays["taint_effect"][i, j] = V.EFFECT_CODES.get(t.effect, -1)
            for kid, (vid, num) in self._node_enc[i].items():
                if kid < K:
                    arrays["label_val"][i, kid] = vid
                    arrays["label_num"][i, kid] = num

    @staticmethod
    def _node_avoid_set(n: Node) -> Optional[frozenset]:
        """NodePreferAvoidPods (node_prefer_avoid_pods.go:47-82): the set of
        (controller kind, uid) the node's preferAvoidPods annotation names."""
        anno = n.metadata.annotations.get("scheduler.alpha.kubernetes.io/preferAvoidPods")
        if not anno:
            return None
        try:
            entries = json.loads(anno).get("preferAvoidPods") or []
        except (ValueError, AttributeError):
            return None
        return frozenset(
            (
                str(((e.get("podSignature") or {}).get("podController") or {}).get("kind", "")),
                str(((e.get("podSignature") or {}).get("podController") or {}).get("uid", "")),
            )
            for e in entries
        )

    def _extend_domain_columns(self, ar: NodeArenas) -> None:
        """Add node_domain columns for topo keys interned since the arenas
        were built (a delta pod batch spreading on a new topology key):
        O(N) per new key instead of an O(N·Tk) domain rebuild."""
        vb = self.vocab
        n_now = vb.n_topo_keys
        if n_now <= ar.n_topo:
            return
        topo_keys = vb.topo_keys.items()
        cols = np.full((ar.N, n_now - ar.n_topo), -1, dtype=np.int32)
        label_val = ar.label_val
        for c, tki in enumerate(range(ar.n_topo, n_now)):
            lk = vb.label_keys.get(topo_keys[tki])
            if lk < 0 or lk >= ar.K:
                continue  # key unknown to every node: whole column absent
            for i in range(len(self.nodes)):
                vid = label_val[i, lk]
                if vid >= 0:
                    cols[i, c] = ar.domain_ids.setdefault(
                        (tki, int(vid)), len(ar.domain_ids)
                    )
        ar.node_domain = np.concatenate([ar.node_domain, cols], axis=1)
        ar.n_topo = n_now

    def extend_nodes(self, new_nodes: List[Node]) -> None:
        """Delta re-encode for node addition: append nodes to a BUILT
        encoder by re-allocating the node arenas and encoding only the new
        rows — O(new nodes) host work plus O(N) memcpy, instead of the full
        O(N) python node build."""
        if self._arenas is None:
            raise ValueError("extend_nodes needs a built encoder (call build() first)")
        ar = self._arenas
        n0 = len(self.nodes)
        self.add_nodes(new_nodes)  # interns labels/taints/resources + _node_enc
        added = self.nodes[n0:]
        if not added:
            return
        vb = self.vocab
        n1 = len(self.nodes)
        N2 = max(_pad_to(n1, self.node_pad), ar.N)
        K2 = max(vb.n_label_keys, ar.K)
        R2 = max(vb.n_resources, ar.R)
        Tt2 = max([len(n.taints) for n in added] + [ar.Tt])

        arrays = {
            "node_valid": _grown(ar.node_valid, (N2,), False),
            "alloc": _grown(ar.alloc, (N2, R2), 0.0),
            "unschedulable": _grown(ar.unschedulable, (N2,), False),
            "taint_key": _grown(ar.taint_key, (N2, Tt2), -1),
            "taint_val": _grown(ar.taint_val, (N2, Tt2), -1),
            "taint_effect": _grown(ar.taint_effect, (N2, Tt2), -1),
            "label_val": _grown(ar.label_val, (N2, K2), -1),
            "label_num": _grown(ar.label_num, (N2, K2), _NAN),
        }
        self._encode_node_rows(arrays, n0, K2, Tt2)

        domain_ids = dict(ar.domain_ids)
        node_domain = _grown(ar.node_domain, (N2, ar.n_topo), -1)
        label_val = arrays["label_val"]
        topo_key_to_label = [
            vb.label_keys.get(k) for k in vb.topo_keys.items()[: ar.n_topo]
        ]
        for i in range(n0, n1):
            for tki in range(ar.n_topo):
                lk = topo_key_to_label[tki]
                vid = label_val[i, lk] if lk >= 0 else -1
                if vid >= 0:
                    node_domain[i, tki] = domain_ids.setdefault(
                        (tki, int(vid)), len(domain_ids)
                    )

        from .extensions import encode_gpu_nodes, encode_local_storage

        gm_new, gc_new = encode_gpu_nodes(added, len(added))
        vg_new, dev_new, media_new, vgn_new, devn_new = encode_local_storage(
            added, len(added)
        )
        Gd2 = max(ar.node_gpu_mem.shape[1], gm_new.shape[1])
        Vg2 = max(ar.node_vg_cap.shape[1], vg_new.shape[1])
        Dv2 = max(ar.node_dev_cap.shape[1], dev_new.shape[1])
        node_gpu_mem = _grown(ar.node_gpu_mem, (N2, Gd2), 0.0)
        node_gpu_mem[n0:n1, : gm_new.shape[1]] = gm_new
        node_gpu_count = _grown(ar.node_gpu_count, (N2,), 0)
        node_gpu_count[n0:n1] = gc_new
        node_vg_cap = _grown(ar.node_vg_cap, (N2, Vg2), 0.0)
        node_vg_cap[n0:n1, : vg_new.shape[1]] = vg_new
        node_dev_cap = _grown(ar.node_dev_cap, (N2, Dv2), 0.0)
        node_dev_cap[n0:n1, : dev_new.shape[1]] = dev_new
        node_dev_media = _grown(ar.node_dev_media, (N2, Dv2), -1)
        node_dev_media[n0:n1, : media_new.shape[1]] = media_new

        avoid_entries = list(ar.avoid_entries)
        for k, n in enumerate(added):
            avoided = self._node_avoid_set(n)
            if avoided:
                avoid_entries.append((n0 + k, avoided))

        self._arenas = NodeArenas(
            N=N2, K=K2, R=R2, Tt=Tt2,
            node_valid=arrays["node_valid"], alloc=arrays["alloc"],
            unschedulable=arrays["unschedulable"],
            taint_key=arrays["taint_key"], taint_val=arrays["taint_val"],
            taint_effect=arrays["taint_effect"],
            label_val=arrays["label_val"], label_num=arrays["label_num"],
            domain_ids=domain_ids, node_domain=node_domain, n_topo=ar.n_topo,
            node_gpu_mem=node_gpu_mem, node_gpu_count=node_gpu_count,
            node_vg_cap=node_vg_cap, node_dev_cap=node_dev_cap,
            node_dev_media=node_dev_media,
            vg_names=ar.vg_names + vgn_new, dev_names=ar.dev_names + devn_new,
            avoid_entries=avoid_entries,
        )

    def _assemble(
        self, ar: NodeArenas, templates: List[SchedTemplate]
    ) -> Tuple[EncodedCluster, ScanState, ClusterMeta]:
        """The O(U) half: template tensors + global term tables, assembled
        against the (possibly cached) node arenas."""
        vb = self.vocab
        N = ar.N
        R = vb.n_resources
        K = max(vb.n_label_keys, 1)
        U = len(templates)
        A = max(len(self.ts.selectors), 1)
        Tk = max(vb.n_topo_keys, 1)
        Hports = max(vb.n_ports, 1)

        Tt = ar.Tt
        # node arrays: shared from the arenas; axes that grew since the
        # arenas were built (new label keys / resources from delta pods)
        # are padded with "absent" on the node side
        node_valid = ar.node_valid
        unschedulable = ar.unschedulable
        taint_key, taint_val, taint_effect = ar.taint_key, ar.taint_val, ar.taint_effect
        alloc = ar.alloc if R == ar.R else _grown(ar.alloc, (N, R), 0.0)
        label_val = ar.label_val if K == ar.K else _grown(ar.label_val, (N, K), -1)
        label_num = ar.label_num if K == ar.K else _grown(ar.label_num, (N, K), _NAN)

        Tl = max([len(t.tolerations) for t in templates] + [1])
        Qs = max([len(t.node_selector) for t in templates] + [1])
        T = max([len(t.affinity_terms) for t in templates] + [1])
        Q = max(
            [
                len((term.get("matchExpressions") or [])) + len((term.get("matchFields") or []))
                for t in templates
                for term in t.affinity_terms
            ]
            + [1]
        )
        Vv = max(
            [
                len(e.get("values") or [])
                for t in templates
                for term in t.affinity_terms
                for e in (term.get("matchExpressions") or []) + (term.get("matchFields") or [])
            ]
            + [
                len(e.get("values") or [])
                for t in templates
                for pref in t.pref_node_affinity
                for e in ((pref.get("preference") or {}).get("matchExpressions") or [])
            ]
            + [1]
        )
        Pp = max([len(t.pref_node_affinity) for t in templates] + [1])
        Qp = max(
            [
                len(((pref.get("preference") or {}).get("matchExpressions") or []))
                + len(((pref.get("preference") or {}).get("matchFields") or []))
                for t in templates
                for pref in t.pref_node_affinity
            ]
            + [1]
        )
        Qmax = max(Q, Qp)
        Hp = max([len(t.host_ports) for t in templates] + [1])
        Cs = max([len(t.spread) for t in templates] + [1])
        Ti = max([len(t.aff_terms) for t in templates] + [1])
        Tn = max([len(t.anti_terms) for t in templates] + [1])
        Tpp = max([len(t.pref_terms) for t in templates] + [1])

        # ---- topology domains: trash-row substitution over the raw arena
        # ids (the arena keeps -1 for absent so D can keep growing)
        raw_domain = ar.node_domain
        if raw_domain.shape[1] < Tk:
            raw_domain = np.concatenate(
                [raw_domain, np.full((N, Tk - raw_domain.shape[1]), -1, np.int32)],
                axis=1,
            )
        D = max(len(ar.domain_ids), 1)
        node_domain = np.where(raw_domain < 0, D, raw_domain).astype(np.int32)  # D = trash row
        domain_topo = np.full((D + 1,), -1, dtype=np.int32)
        for (tki, _vid), did in ar.domain_ids.items():
            domain_topo[did] = tki

        # ---- global inter-pod term tables
        topo_idx = {k: i for i, k in enumerate(vb.topo_keys.items())}
        anti_table: Dict[Tuple[int, int], int] = {}
        pref_table: Dict[Tuple[int, int], int] = {}
        for t in templates:
            for term in t.anti_terms:
                anti_table.setdefault((term.sel_id, topo_idx.get(term.topo_key, -1)), len(anti_table))
            for term in t.pref_terms:
                pref_table.setdefault((term.sel_id, topo_idx.get(term.topo_key, -1)), len(pref_table))
            # existing pods' REQUIRED affinity terms score with hard weight 1
            for term in t.aff_terms:
                pref_table.setdefault((term.sel_id, topo_idx.get(term.topo_key, -1)), len(pref_table))
        G = max(len(anti_table), 1)
        Gp = max(len(pref_table), 1)
        anti_g_sel = np.zeros((G,), dtype=np.int32)
        anti_g_topo = np.zeros((G,), dtype=np.int32)
        for (sid, tki), g in anti_table.items():
            anti_g_sel[g] = sid
            anti_g_topo[g] = max(tki, 0)
        prefg_sel = np.zeros((Gp,), dtype=np.int32)
        prefg_topo = np.zeros((Gp,), dtype=np.int32)
        for (sid, tki), g in pref_table.items():
            prefg_sel[g] = sid
            prefg_topo[g] = max(tki, 0)

        # ---- template tensors
        req = np.zeros((U, R), dtype=np.float32)
        tol_valid = np.zeros((U, Tl), dtype=bool)
        tol_key = np.full((U, Tl), -1, dtype=np.int32)
        tol_op = np.zeros((U, Tl), dtype=np.int32)
        tol_val = np.full((U, Tl), -1, dtype=np.int32)
        tol_effect = np.full((U, Tl), -1, dtype=np.int32)
        ns_key = np.full((U, Qs), -1, dtype=np.int32)
        ns_val = np.full((U, Qs), -1, dtype=np.int32)
        has_req_aff = np.zeros((U,), dtype=bool)
        aff_term_valid = np.zeros((U, T), dtype=bool)
        aff_key = np.full((U, T, Qmax), -1, dtype=np.int32)
        aff_op = np.full((U, T, Qmax), V.OP_PAD, dtype=np.int32)
        aff_val = np.full((U, T, Qmax, Vv), -1, dtype=np.int32)
        aff_num = np.full((U, T, Qmax), _NAN, dtype=np.float32)
        pna_weight = np.zeros((U, Pp), dtype=np.float32)
        pna_key = np.full((U, Pp, Qmax), -1, dtype=np.int32)
        pna_op = np.full((U, Pp, Qmax), V.OP_PAD, dtype=np.int32)
        pna_val = np.full((U, Pp, Qmax, Vv), -1, dtype=np.int32)
        pna_num = np.full((U, Pp, Qmax), _NAN, dtype=np.float32)
        ports = np.full((U, Hp), -1, dtype=np.int32)
        spr_topo = np.full((U, Cs), -1, dtype=np.int32)
        spr_sel = np.zeros((U, Cs), dtype=np.int32)
        spr_skew = np.zeros((U, Cs), dtype=np.int32)
        spr_hard = np.zeros((U, Cs), dtype=bool)
        at_sel = np.full((U, Ti), -1, dtype=np.int32)
        at_topo = np.zeros((U, Ti), dtype=np.int32)
        an_sel = np.full((U, Tn), -1, dtype=np.int32)
        an_topo = np.zeros((U, Tn), dtype=np.int32)
        pt_sel = np.full((U, Tpp), -1, dtype=np.int32)
        pt_topo = np.zeros((U, Tpp), dtype=np.int32)
        pt_w = np.zeros((U, Tpp), dtype=np.float32)
        anti_g = np.zeros((U, G), dtype=bool)
        prefg_w = np.zeros((U, Gp), dtype=np.float32)
        pin = np.full((U,), -1, dtype=np.int32)
        gpu_mem = np.zeros((U,), dtype=np.float32)
        gpu_count = np.zeros((U,), dtype=np.int32)

        for u, t in enumerate(templates):
            for rid, v in vb.encode_resources(t.requests).items():
                req[u, rid] = v
            req[u, V.RES_PODS] += 1.0  # every pod consumes one pod slot
            if t.node_name:
                pin[u] = self.node_index.get(t.node_name, -2)
            for j, (key, op, val, eff) in enumerate(t.tolerations[:Tl]):
                tol_valid[u, j] = True
                tol_key[u, j] = vb.label_keys.get(key, -1) if key else -1
                tol_op[u, j] = V.TOL_EXISTS if op == "Exists" else V.TOL_EQUAL
                tol_val[u, j] = vb.label_vals.get(val, -1)
                tol_effect[u, j] = V.EFFECT_CODES.get(eff, -1) if eff else -1
            for j, (k, v) in enumerate(sorted(t.node_selector.items())[:Qs]):
                ns_key[u, j] = vb.key_id(k)
                ns_val[u, j] = vb.label_vals.get(str(v), -1)
            if t.affinity_terms:
                has_req_aff[u] = True
                tv, tk_, to, tva, tn = self._encode_terms(t.affinity_terms, T, Qmax, Vv)
                aff_term_valid[u], aff_key[u], aff_op[u], aff_val[u], aff_num[u] = tv, tk_, to, tva, tn
            if t.pref_node_affinity:
                terms = [p.get("preference") or {} for p in t.pref_node_affinity]
                tv, tk_, to, tva, tn = self._encode_terms(terms, Pp, Qmax, Vv)
                pna_key[u], pna_op[u], pna_val[u], pna_num[u] = tk_, to, tva, tn
                for j, p in enumerate(t.pref_node_affinity[:Pp]):
                    pna_weight[u, j] = float(p.get("weight", 0))
            for j, (proto, port, ip) in enumerate(t.host_ports[:Hp]):
                ports[u, j] = vb.port_id(proto, port, ip)
            for j, c in enumerate(t.spread[:Cs]):
                spr_topo[u, j] = topo_idx.get(c.topo_key, -1)
                spr_sel[u, j] = c.sel_id
                spr_skew[u, j] = c.max_skew
                spr_hard[u, j] = c.hard
            for j, term in enumerate(t.aff_terms[:Ti]):
                # filter counts pods matching ALL terms — use the conjunction
                # selector when the template has several (templates.py)
                at_sel[u, j] = t.aff_conj if t.aff_conj >= 0 else term.sel_id
                at_topo[u, j] = max(topo_idx.get(term.topo_key, -1), 0)
            for j, term in enumerate(t.anti_terms[:Tn]):
                an_sel[u, j] = term.sel_id
                an_topo[u, j] = max(topo_idx.get(term.topo_key, -1), 0)
                anti_g[u, anti_table[(term.sel_id, topo_idx.get(term.topo_key, -1))]] = True
            for j, term in enumerate(t.pref_terms[:Tpp]):
                pt_sel[u, j] = term.sel_id
                pt_topo[u, j] = max(topo_idx.get(term.topo_key, -1), 0)
                pt_w[u, j] = term.weight
                prefg_w[u, pref_table[(term.sel_id, topo_idx.get(term.topo_key, -1))]] += term.weight
            for term in t.aff_terms:
                # symmetric hard-affinity weight (HardPodAffinityWeight = 1)
                prefg_w[u, pref_table[(term.sel_id, topo_idx.get(term.topo_key, -1))]] += 1.0
            gpu_mem[u] = t.gpu_mem
            gpu_count[u] = t.gpu_count

        matches_sel = np.zeros((U, A), dtype=bool)
        mm = self.ts.match_matrix()
        if mm.size:
            matches_sel[: mm.shape[0], : mm.shape[1]] = mm

        # ---- NodePreferAvoidPods (node_prefer_avoid_pods.go:47-82): pods
        # controlled by an RS/RC listed in the node's preferAvoidPods
        # annotation score 0 there, 100 elsewhere
        avoid_score = np.full((U, N), 100.0, dtype=np.float32)
        for i, avoided in ar.avoid_entries:
            for u, t in enumerate(templates):
                if t.controller[0] and tuple(t.controller) in avoided:
                    avoid_score[u, i] = 0.0

        # ---- extensions: node side cached in the arenas, template side
        # encoded by its dedicated module (task: gpu/local)
        from .extensions import encode_local_requests

        node_gpu_mem, node_gpu_count = ar.node_gpu_mem, ar.node_gpu_count
        from ..models.objects import RES_GPU_COUNT

        gc_mask = np.zeros((R,), dtype=bool)
        gc_col = vb.resources.get(RES_GPU_COUNT)
        if gc_col >= 0:
            gc_mask[gc_col] = True
        node_vg_cap, node_dev_cap, node_dev_media = (
            ar.node_vg_cap, ar.node_dev_cap, ar.node_dev_media
        )
        vg_names, dev_names = ar.vg_names, ar.dev_names
        lvm_req, dev_req, dev_req_count, dev_req_sizes = encode_local_requests(templates)

        cluster = EncodedCluster(
            node_valid=node_valid,
            alloc=alloc,
            unschedulable=unschedulable,
            taint_key=taint_key,
            taint_val=taint_val,
            taint_effect=taint_effect,
            label_val=label_val,
            label_num=label_num,
            node_domain=node_domain,
            domain_topo=domain_topo,
            req=req,
            tol_valid=tol_valid,
            tol_key=tol_key,
            tol_op=tol_op,
            tol_val=tol_val,
            tol_effect=tol_effect,
            ns_key=ns_key,
            ns_val=ns_val,
            has_req_aff=has_req_aff,
            aff_term_valid=aff_term_valid,
            aff_key=aff_key,
            aff_op=aff_op,
            aff_val=aff_val,
            aff_num=aff_num,
            pna_weight=pna_weight,
            pna_key=pna_key,
            pna_op=pna_op,
            pna_val=pna_val,
            pna_num=pna_num,
            ports=ports,
            port_conflict=vb.port_conflict_matrix(),
            spr_topo=spr_topo,
            spr_sel=spr_sel,
            spr_skew=spr_skew,
            spr_hard=spr_hard,
            at_sel=at_sel,
            at_topo=at_topo,
            an_sel=an_sel,
            an_topo=an_topo,
            pt_sel=pt_sel,
            pt_topo=pt_topo,
            pt_w=pt_w,
            matches_sel=matches_sel,
            anti_g=anti_g,
            prefg_w=prefg_w,
            pin=pin,
            avoid_score=avoid_score,
            anti_g_sel=anti_g_sel,
            anti_g_topo=anti_g_topo,
            prefg_sel=prefg_sel,
            prefg_topo=prefg_topo,
            gpu_mem=gpu_mem,
            gpu_count=gpu_count,
            node_gpu_mem=node_gpu_mem,
            gc_mask=gc_mask,
            lvm_req=lvm_req,
            dev_req=dev_req,
            dev_req_count=dev_req_count,
            dev_req_sizes=dev_req_sizes,
            node_vg_cap=node_vg_cap,
            node_dev_cap=node_dev_cap,
            node_dev_media=node_dev_media,
            log_sizes=log_size_table(N),
        )

        state0 = ScanState(
            used=np.zeros((N, R), dtype=np.float32),
            port_used=np.zeros((N, Hports), dtype=np.float32),
            dom_sel=np.zeros((D + 1, A), dtype=np.float32),
            dom_anti=np.zeros((D + 1, G), dtype=np.float32),
            dom_prefw=np.zeros((D + 1, Gp), dtype=np.float32),
            gpu_free=node_gpu_mem.copy(),
            vg_free=node_vg_cap.copy(),
            dev_free=node_dev_cap.copy(),
        )

        meta = ClusterMeta(
            node_names=[n.metadata.name for n in self.nodes],
            n_real_nodes=len(self.nodes),
            vocab=vb,
            template_set=self.ts,
            resource_names=list(vb.resources.items()),
            n_domains=D,
            node_gpu_count=node_gpu_count,
            node_vg_names=vg_names,
            node_dev_names=dev_names,
            node_gpu_mem=node_gpu_mem.copy(),
            node_vg_cap=node_vg_cap.copy(),
            node_dev_cap=node_dev_cap.copy(),
            node_dev_media=node_dev_media.copy(),
        )
        return cluster, state0, meta
