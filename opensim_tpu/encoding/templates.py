"""Scheduling-template extraction.

Pods expanded from the same workload share an identical scheduling-relevant
spec; 50k pods typically collapse to a few dozen *templates*. All per-pod
device encodings are stored once per template and gathered by ``tmpl_id``
inside the scan — this is the shape-dedup that keeps the encoded cluster
small and the jit cache warm.

Canonical selectors: inter-pod affinity terms and topology-spread constraints
reference label selectors; each distinct (namespace-set, selector) pair
becomes a selector id, and per-template match bits (does a pod of template u
match selector a?) are precomputed on host — the device never does string
matching.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..models.objects import Pod
from ..models.selectors import match_label_selector

ZONE_LABEL = "topology.kubernetes.io/zone"
HOSTNAME_LABEL = "kubernetes.io/hostname"

# System-default topology spread (k8s 1.21 DefaultPodTopologySpread feature,
# scoring-only): maxSkew 3 on hostname, maxSkew 5 on zone, ScheduleAnyway.
SYSTEM_DEFAULT_SPREAD = (
    (HOSTNAME_LABEL, 3, False),
    (ZONE_LABEL, 5, False),
)


def canon_selector(ns, selector: Optional[dict]) -> Optional[tuple]:
    """(namespaces, matchLabels, matchExpressions) canonical form; `ns` is a
    namespace or tuple of namespaces (pod-affinity terms may list several);
    None for a nil selector (matches nothing)."""
    if selector is None:
        return None
    ns_t = tuple(sorted(ns)) if isinstance(ns, (tuple, list, set)) else (ns,)
    ml = tuple(sorted((str(k), str(v)) for k, v in (selector.get("matchLabels") or {}).items()))
    exprs = tuple(
        sorted(
            (
                str(e.get("key", "")),
                str(e.get("operator", "")),
                tuple(sorted(str(v) for v in (e.get("values") or []))),
            )
            for e in (selector.get("matchExpressions") or [])
        )
    )
    return (ns_t, ml, exprs)


def selector_matches(canon: Optional[tuple], ns: str, labels: Dict[str, str]) -> bool:
    """Host-side evaluation of a canonical selector against a pod's
    namespace + labels (the golden form used to precompute match bits)."""
    if canon is None:
        return False
    if canon[0] == "AND":
        # conjunction selector: a pod matches iff it matches every member
        # (podMatchesAllAffinityTerms, interpodaffinity/filtering.go:150-161)
        return all(selector_matches(sub, ns, labels) for sub in canon[1])
    sel_ns, ml, exprs = canon
    if ns not in sel_ns:
        return False
    sel = {
        "matchLabels": dict(ml),
        "matchExpressions": [{"key": k, "operator": op, "values": list(vals)} for k, op, vals in exprs],
    }
    return match_label_selector(sel, labels)


@dataclass(frozen=True)
class PodAffinityTerm:
    sel_id: int
    topo_key: str


@dataclass(frozen=True)
class PrefPodAffinityTerm:
    sel_id: int
    topo_key: str
    weight: float  # signed: negative for anti-affinity


@dataclass(frozen=True)
class SpreadConstraint:
    topo_key: str
    sel_id: int
    max_skew: int
    hard: bool  # DoNotSchedule vs ScheduleAnyway


@dataclass
class SchedTemplate:
    """One deduplicated scheduling spec."""

    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    requests: Dict[str, float] = field(default_factory=dict)  # resource name -> base units
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity_terms: List[dict] = field(default_factory=list)  # required node-affinity terms
    pref_node_affinity: List[dict] = field(default_factory=list)  # {weight, preference}
    tolerations: List[tuple] = field(default_factory=list)  # (key, op, value, effect)
    host_ports: List[Tuple[str, int, str]] = field(default_factory=list)
    spread: List[SpreadConstraint] = field(default_factory=list)
    aff_terms: List[PodAffinityTerm] = field(default_factory=list)  # required pod affinity
    aff_conj: int = -1  # conjunction selector id when len(aff_terms) > 1
    anti_terms: List[PodAffinityTerm] = field(default_factory=list)  # required pod anti-affinity
    pref_terms: List[PrefPodAffinityTerm] = field(default_factory=list)  # preferred, signed weights
    gpu_mem: float = 0.0  # per-GPU memory request (gpu-share extension)
    gpu_count: int = 0
    local_volumes: tuple = ()  # ((kind, size, scName), ...) open-local extension
    controller: tuple = ("", "")  # (kind, uid) when owned by a ReplicaSet/RC
    #   (NodePreferAvoidPods matches on controller kind+uid,
    #    node_prefer_avoid_pods.go:58-80)


class TemplateSet:
    """Dedupes pods into templates and interns selectors."""

    def __init__(self) -> None:
        self.templates: List[SchedTemplate] = []
        self._index: Dict[str, int] = {}
        self._hint_index: Dict[tuple, int] = {}
        self.selectors: List[Optional[tuple]] = []
        self._sel_index: Dict[Optional[tuple], int] = {}
        self._mm = None  # cached match matrix (incremental rebuilds)

    def clone(self) -> "TemplateSet":
        """Fork for delta re-encoding: template/selector ids are
        append-only, so a fork can add pods without touching the base.
        SchedTemplate objects are shared (immutable after extraction)."""
        new = object.__new__(TemplateSet)
        new.templates = list(self.templates)
        new._index = dict(self._index)
        new._hint_index = dict(self._hint_index)
        new.selectors = list(self.selectors)
        new._sel_index = dict(self._sel_index)
        new._mm = self._mm  # replaced, never mutated, on rebuild
        return new

    def selector_id(self, ns: "str | tuple", selector: Optional[dict]) -> int:
        canon = canon_selector(ns, selector)
        idx = self._sel_index.get(canon)
        if idx is None:
            idx = len(self.selectors)
            self._sel_index[canon] = idx
            self.selectors.append(canon)
        return idx

    def conjunction_id(self, sel_ids: List[int]) -> int:
        """Selector id matching pods that match ALL of `sel_ids` — the
        counting basis k8s uses for a pod's required affinity terms
        (updateWithAffinityTerms → podMatchesAllAffinityTerms,
        interpodaffinity/filtering.go:113-127)."""
        subs = tuple(sorted({self.selectors[i] for i in sel_ids}, key=repr))
        if len(subs) == 1:
            return self._sel_index[subs[0]]
        canon = ("AND", subs)
        idx = self._sel_index.get(canon)
        if idx is None:
            idx = len(self.selectors)
            self._sel_index[canon] = idx
            self.selectors.append(canon)
        return idx

    def add_pod(self, pod: Pod, owner_selector: Optional[dict] = None, hint: Optional[tuple] = None) -> int:
        """Returns the template id for this pod (creating it if new).

        `hint` is an optional cheap identity key (e.g. the owning workload):
        pods expanded from one workload share an identical scheduling spec,
        so the full canonical-extraction path runs once per workload instead
        of once per pod — the host-side analogue of the chunked pod
        validation the reference needed for >3k-node scale
        (pkg/simulator/utils.go:77)."""
        if hint is not None:
            idx = self._hint_index.get(hint)
            if idx is not None:
                return idx
        # owner_selector may be a callable (lazy): hint hits above never pay
        # the selector dict build, only actual extractions do
        if callable(owner_selector):
            owner_selector = owner_selector()
        tmpl = self._extract(pod, owner_selector)
        key = self._canon_key(tmpl)
        idx = self._index.get(key)
        if idx is None:
            idx = len(self.templates)
            self._index[key] = idx
            self.templates.append(tmpl)
        if hint is not None:
            self._hint_index[hint] = idx
        return idx

    # -- extraction ---------------------------------------------------------

    def _extract(self, pod: Pod, owner_selector: Optional[dict]) -> SchedTemplate:
        ns = pod.metadata.namespace or "default"
        t = SchedTemplate(namespace=ns, labels=dict(pod.metadata.labels))
        t.requests = pod.resource_requests()
        t.node_name = pod.spec.node_name
        t.node_selector = dict(pod.spec.node_selector)
        aff = pod.spec.affinity or {}
        node_aff = aff.get("nodeAffinity") or {}
        required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution")
        if required is not None:
            t.affinity_terms = list(required.get("nodeSelectorTerms") or [])
            if not t.affinity_terms:
                # empty terms matches no node; encode an impossible term
                t.affinity_terms = [{"matchExpressions": [{"key": "", "operator": "In", "values": []}]}]
        t.pref_node_affinity = list(node_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
        t.tolerations = [
            (tol.key, tol.operator, tol.value, tol.effect) for tol in pod.spec.tolerations
        ]
        t.host_ports = [(p.protocol, p.host_port, p.host_ip) for p in pod.host_ports()]

        # -- inter-pod affinity
        pod_aff = aff.get("podAffinity") or {}
        pod_anti = aff.get("podAntiAffinity") or {}
        for term in pod_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
            t.aff_terms.append(self._pod_term(ns, term))
        if len(t.aff_terms) > 1:
            # k8s counts only existing pods matching ALL required affinity
            # terms (filtering.go:113-127): the FILTER uses this interned
            # conjunction as its counting basis, while the symmetric
            # hard-affinity SCORE keeps the per-term selectors
            # (scoring.go processExistingPod matches terms individually).
            t.aff_conj = self.conjunction_id([x.sel_id for x in t.aff_terms])
        for term in pod_anti.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
            t.anti_terms.append(self._pod_term(ns, term))
        for pref in pod_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            term = self._pod_term(ns, pref.get("podAffinityTerm") or {})
            t.pref_terms.append(PrefPodAffinityTerm(term.sel_id, term.topo_key, float(pref.get("weight", 0))))
        for pref in pod_anti.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            term = self._pod_term(ns, pref.get("podAffinityTerm") or {})
            t.pref_terms.append(PrefPodAffinityTerm(term.sel_id, term.topo_key, -float(pref.get("weight", 0))))

        # -- topology spread
        explicit = pod.spec.topology_spread_constraints
        if explicit:
            for c in explicit:
                sel_id = self.selector_id(ns, c.get("labelSelector"))
                t.spread.append(
                    SpreadConstraint(
                        topo_key=str(c.get("topologyKey", "")),
                        sel_id=sel_id,
                        max_skew=int(c.get("maxSkew", 1)),
                        hard=(c.get("whenUnsatisfiable", "DoNotSchedule") == "DoNotSchedule"),
                    )
                )
        elif owner_selector is not None:
            # System-default spreading (scoring only) using the owning
            # workload's selector — stands in for k8s's service/RS/STS
            # selector lookup in defaultConstraints.
            for topo_key, max_skew, hard in SYSTEM_DEFAULT_SPREAD:
                sel_id = self.selector_id(ns, owner_selector)
                t.spread.append(SpreadConstraint(topo_key, sel_id, max_skew, hard))

        # -- extensions (gpu-share, open-local)
        t.gpu_mem = pod.gpu_mem_request()
        t.gpu_count = pod.gpu_count_request()
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.kind in ("ReplicaSet", "ReplicationController"):
                t.controller = (ref.kind, ref.uid)
                break
        t.local_volumes = tuple(
            (str(v.get("kind", "")), int(v.get("size", 0)), str(v.get("scName", "")))
            for v in pod.local_volumes()
        )
        return t

    def _pod_term(self, ns: str, term: dict) -> PodAffinityTerm:
        # a term's selector applies within its explicit namespaces, or the
        # owning pod's namespace by default; the canonical selector carries
        # the whole namespace set so multi-namespace terms match exactly
        namespaces = tuple(str(n) for n in (term.get("namespaces") or [])) or (ns,)
        sel_id = self.selector_id(namespaces, term.get("labelSelector"))
        return PodAffinityTerm(sel_id=sel_id, topo_key=str(term.get("topologyKey", "")))

    # -- canonical dedupe key ----------------------------------------------

    @staticmethod
    def _canon_key(t: SchedTemplate) -> str:
        return json.dumps(
            {
                "ns": t.namespace,
                "labels": sorted(t.labels.items()),
                "req": sorted(t.requests.items()),
                "node": t.node_name,
                "nsel": sorted(t.node_selector.items()),
                "aff": t.affinity_terms,
                "paff": t.pref_node_affinity,
                "tol": t.tolerations,
                "ports": t.host_ports,
                "spread": [(c.topo_key, c.sel_id, c.max_skew, c.hard) for c in t.spread],
                "at": [(x.sel_id, x.topo_key) for x in t.aff_terms],
                "nt": [(x.sel_id, x.topo_key) for x in t.anti_terms],
                "pt": [(x.sel_id, x.topo_key, x.weight) for x in t.pref_terms],
                "gpu": [t.gpu_mem, t.gpu_count],
                "lv": list(t.local_volumes),
                "ctl": list(t.controller),
            },
            sort_keys=True,
            default=str,
        )

    # -- host-side match precompute ----------------------------------------

    def match_matrix(self):
        """[U, A] bool: does a pod of template u match selector a?

        Incremental: the previous matrix (if any) fills the known block, so
        a delta build evaluates only new-template rows and new-selector
        columns — O(ΔU·A + U·ΔA) python selector matches, not O(U·A)."""
        import numpy as np

        U, A = len(self.templates), len(self.selectors)
        m = np.zeros((U, A), dtype=bool)
        u0 = a0 = 0
        prev = self._mm
        if prev is not None and prev.shape[0] <= U and prev.shape[1] <= A:
            u0, a0 = prev.shape
            m[:u0, :a0] = prev
        for u, t in enumerate(self.templates):
            for a, canon in enumerate(self.selectors):
                if u < u0 and a < a0:
                    continue
                m[u, a] = selector_matches(canon, t.namespace, t.labels)
        self._mm = m
        return m
