"""Extended-resource encodings: GPU-share device matrices and open-local
node storage tensors.

GPU parity: a node advertises ``alibabacloud.com/gpu-count`` devices whose
per-device memory is total-gpu-mem / count (reference NewGpuNodeInfo,
``pkg/type/open-gpu-share/cache/gpunodeinfo.go:33-66``). Pods request
per-GPU memory + count via annotations (``utils/pod.go:83-100``).

Local-storage parity: node annotation ``simon/node-local-storage`` carries
``{"vgs": [{name, capacity}], "devices": [{device, capacity, mediaType}]}``
(``pkg/utils/utils.go:510-556``); statefulset pods carry
``simon/pod-local-storage`` volume requests (LVM or exclusive-device).
"""

from __future__ import annotations

import json
from typing import List, Tuple

import numpy as np

from ..models.objects import ANNO_NODE_LOCAL_STORAGE, Node
from ..models.quantity import parse_quantity
from .templates import SchedTemplate

MEDIA_SSD = 0
MEDIA_HDD = 1


def encode_gpu_nodes(nodes: List[Node], n_pad: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-device total memory [N, Gd] and device count [N]."""
    counts = []
    mems = []
    for n in nodes:
        total = n.capacity.get("alibabacloud.com/gpu-mem", n.allocatable.get("alibabacloud.com/gpu-mem", 0.0))
        cnt = int(n.capacity.get("alibabacloud.com/gpu-count", n.allocatable.get("alibabacloud.com/gpu-count", 0)))
        counts.append(cnt if total > 0 else 0)
        mems.append(total / cnt if cnt > 0 and total > 0 else 0.0)
    Gd = max(counts + [1])
    node_gpu_mem = np.zeros((n_pad, Gd), dtype=np.float32)
    node_gpu_count = np.zeros((n_pad,), dtype=np.int32)
    for i, (cnt, mem) in enumerate(zip(counts, mems)):
        node_gpu_count[i] = cnt
        node_gpu_mem[i, :cnt] = mem
    return node_gpu_mem, node_gpu_count


def parse_node_storage(node: Node):
    """Decode the simon/node-local-storage annotation; returns (vgs, devices)
    as lists of (name, capacity) / (name, capacity, media)."""
    raw = node.metadata.annotations.get(ANNO_NODE_LOCAL_STORAGE)
    if not raw:
        return [], []
    try:
        data = json.loads(raw)
    except ValueError:
        return [], []
    vgs = []
    for vg in data.get("vgs") or []:
        vgs.append((str(vg.get("name", "")), float(parse_quantity(vg.get("capacity", 0)))))
    devices = []
    for dev in data.get("devices") or []:
        media = str(dev.get("mediaType", "")).lower()
        devices.append(
            (
                str(dev.get("device", dev.get("name", ""))),
                float(parse_quantity(dev.get("capacity", 0))),
                MEDIA_SSD if media == "ssd" else MEDIA_HDD,
            )
        )
    return vgs, devices


def encode_local_storage(nodes: List[Node], n_pad: int):
    """VG capacity [N, Vg], device capacity [N, Dv], device media [N, Dv]."""
    parsed = [parse_node_storage(n) for n in nodes]
    Vg = max([len(v) for v, _ in parsed] + [1])
    Dv = max([len(d) for _, d in parsed] + [1])
    vg_cap = np.zeros((n_pad, Vg), dtype=np.float32)
    dev_cap = np.zeros((n_pad, Dv), dtype=np.float32)
    dev_media = np.full((n_pad, Dv), -1, dtype=np.int32)
    vg_names: List[List[str]] = []
    dev_names: List[List[str]] = []
    for i, (vgs, devs) in enumerate(parsed):
        vg_names.append([name for name, _ in vgs])
        dev_names.append([name for name, _, _ in devs])
        for j, (_, cap) in enumerate(vgs):
            vg_cap[i, j] = cap
        for j, (_, cap, media) in enumerate(devs):
            dev_cap[i, j] = cap
            dev_media[i, j] = media
    return vg_cap, dev_cap, dev_media, vg_names, dev_names


def encode_local_requests(templates: List[SchedTemplate]):
    """Per-template storage requests: total LVM bytes; exclusive-device
    volumes by media. `dev_req_sizes[u, media]` carries each volume's size
    sorted DESCENDING (the reference allocates one device per volume,
    smallest-volume → smallest fitting device, common.go:290-349); the
    max-size `dev_req` and `dev_req_count` remain for the score proxy."""
    U = len(templates)
    lvm_req = np.zeros((U,), dtype=np.float32)
    dev_req = np.zeros((U, 2), dtype=np.float32)
    dev_req_count = np.zeros((U, 2), dtype=np.int32)
    per_media: List[List[List[float]]] = [[[], []] for _ in range(U)]
    for u, t in enumerate(templates):
        for kind, size, _sc in t.local_volumes:
            if kind == "LVM":
                lvm_req[u] += size
            elif kind in ("SSD", "HDD"):
                media = MEDIA_SSD if kind == "SSD" else MEDIA_HDD
                dev_req[u, media] = max(dev_req[u, media], size)
                dev_req_count[u, media] += 1
                per_media[u][media].append(float(size))
    Mv = max([len(v) for row in per_media for v in row] + [1])
    dev_req_sizes = np.zeros((U, 2, Mv), dtype=np.float32)
    for u in range(U):
        for media in (0, 1):
            for i, size in enumerate(sorted(per_media[u][media], reverse=True)):
                dev_req_sizes[u, media, i] = size
    return lvm_req, dev_req, dev_req_count, dev_req_sizes
