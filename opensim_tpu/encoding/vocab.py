"""Dictionary encoding: the string world → int world bridge.

Every string that matters to scheduling (label keys, label values, taint
keys/values, namespaces, host ports, resource names) is interned into a
dense id space so the kernels in ``opensim_tpu/ops`` operate on int32
tensors. This replaces the reference's string-keyed map lookups inside the
vendored scheduler's hot loop (e.g. label matching in
``vendor/k8s.io/kubernetes/pkg/scheduler/framework/plugins``).
"""

from __future__ import annotations

from typing import Dict, Hashable, List

# Operator codes shared by node-selector requirement encodings.
OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5
OP_PAD = -1  # absent requirement slot (vacuously true)

NODE_OP_CODES = {
    "In": OP_IN,
    "NotIn": OP_NOT_IN,
    "Exists": OP_EXISTS,
    "DoesNotExist": OP_DOES_NOT_EXIST,
    "Gt": OP_GT,
    "Lt": OP_LT,
}

# Taint effects.
EFFECT_NO_SCHEDULE = 0
EFFECT_PREFER_NO_SCHEDULE = 1
EFFECT_NO_EXECUTE = 2
EFFECT_CODES = {
    "NoSchedule": EFFECT_NO_SCHEDULE,
    "PreferNoSchedule": EFFECT_PREFER_NO_SCHEDULE,
    "NoExecute": EFFECT_NO_EXECUTE,
}
EFFECT_ALL = -1  # toleration with empty effect matches all effects

# Toleration operators.
TOL_EQUAL = 0
TOL_EXISTS = 1

# Canonical resource axis prefix; extended resources get appended by Vocab.
# cpu is stored in millicores, all others in base units.
RES_CPU = 0
RES_MEMORY = 1
RES_EPHEMERAL = 2
RES_PODS = 3
BASE_RESOURCES = ["cpu", "memory", "ephemeral-storage", "pods"]

# Resources ignored for fit (hugepages-* would be checked by k8s, keep them
# as extended resources instead of ignoring).
_SKIP_RESOURCES = set()


class Interner:
    """Monotonic string→id table."""

    def __init__(self) -> None:
        self._ids: Dict[Hashable, int] = {}
        self._items: List[Hashable] = []

    def clone(self) -> "Interner":
        new = Interner()
        new._ids = dict(self._ids)
        new._items = list(self._items)
        return new

    def intern(self, item: Hashable) -> int:
        idx = self._ids.get(item)
        if idx is None:
            idx = len(self._items)
            self._ids[item] = idx
            self._items.append(item)
        return idx

    def get(self, item: Hashable, default: int = -1) -> int:
        return self._ids.get(item, default)

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, item: Hashable) -> bool:
        return item in self._ids

    def items(self) -> List[Hashable]:
        return list(self._items)

    def lookup(self, idx: int) -> Hashable:
        return self._items[idx]


class Vocab:
    """All interners for one simulation."""

    def __init__(self) -> None:
        self.label_keys = Interner()  # label keys + the metadata.name pseudo-key
        self.label_vals = Interner()  # global value space (shared across keys)
        self.ports = Interner()  # (protocol, port, hostIP) triples
        self.resources = Interner()  # resource-name axis
        self.topo_keys = Interner()  # label keys used as topology keys (subset)
        for r in BASE_RESOURCES:
            self.resources.intern(r)

    def clone(self) -> "Vocab":
        """Fork for delta re-encoding: ids are append-only, so a forked
        vocab can intern new strings without invalidating the base's
        already-encoded tensors."""
        new = object.__new__(Vocab)
        new.label_keys = self.label_keys.clone()
        new.label_vals = self.label_vals.clone()
        new.ports = self.ports.clone()
        new.resources = self.resources.clone()
        new.topo_keys = self.topo_keys.clone()
        return new

    # -- resources ----------------------------------------------------------

    def resource_id(self, name: str) -> int:
        if name in _SKIP_RESOURCES:
            return -1
        return self.resources.intern(name)

    def encode_resources(self, requests: Dict[str, float]) -> Dict[int, float]:
        """Resource dict → {axis index: value}, cpu scaled to millicores."""
        out: Dict[int, float] = {}
        for name, val in requests.items():
            rid = self.resource_id(name)
            if rid < 0:
                continue
            out[rid] = val * 1000.0 if name == "cpu" else val
        return out

    # -- labels -------------------------------------------------------------

    def key_id(self, key: str) -> int:
        return self.label_keys.intern(key)

    def val_id(self, val: str) -> int:
        return self.label_vals.intern(str(val))

    def topo_key_id(self, key: str) -> int:
        self.key_id(key)
        return self.topo_keys.intern(key)

    def port_id(self, protocol: str, port: int, host_ip: str = "") -> int:
        # 0.0.0.0 and "" are the same wildcard address for conflict purposes.
        ip = "" if host_ip in ("", "0.0.0.0") else host_ip
        return self.ports.intern((protocol or "TCP", int(port), ip))

    def port_conflict_matrix(self):
        """[Hports, Hports] bool: interned triples i and j conflict when
        protocol+port match and either hostIP is the wildcard or they are
        equal (nodeports.go ckConflict semantics — 0.0.0.0 overlaps every
        specific address on the same port)."""
        import numpy as np

        triples = self.ports.items()
        n = max(len(triples), 1)
        m = np.zeros((n, n), dtype=bool)
        for i, (proto_i, port_i, ip_i) in enumerate(triples):
            for j, (proto_j, port_j, ip_j) in enumerate(triples):
                if proto_i == proto_j and port_i == port_j:
                    m[i, j] = ip_i == ip_j or ip_i == "" or ip_j == ""
        return m

    @property
    def n_resources(self) -> int:
        return len(self.resources)

    @property
    def n_label_keys(self) -> int:
        return len(self.label_keys)

    @property
    def n_topo_keys(self) -> int:
        return len(self.topo_keys)

    @property
    def n_ports(self) -> int:
        return len(self.ports)
