"""Encoder dtype policy — the single place width decisions live.

The vendored Go scheduler does resource math in float32-comparable space
and keys everything else by integer id, and the differential oracle
compares scores bit-exactly. Every array the encoder builds therefore
names its dtype from here; ``opensim-lint``'s dtype-drift rule (OSL201)
flags any encoder-path array that doesn't.

This module is also the **array contract registry** (ISSUE 17): every
``EncodedCluster``/``ScanState`` arena field declares its
``(policy dtype name, symbolic axis names)`` here, and the XLA kernel
entry points declare boundary contracts for their array arguments. The
OSL18xx rule family (``analysis/arrays.py``) checks the encoder and
engine against these declarations, and OSL1804 gates the registry, the
policy constants above it, and the C++ ``ScanArgs`` widths into one
three-way sync — so narrowing a dtype here without updating the native
ABI (or vice versa) fails the build naming the exact field.

Contract convention (docs/static-analysis.md "Array contracts"):

- dtype is a **policy constant name** from this module (``FLOAT_DTYPE``,
  ``INT_DTYPE``, ``INT64_DTYPE``, ``LOG_ACC_DTYPE``) or one of the two
  structural names ``BOOL_DTYPE``/``UINT8_DTYPE`` — never a raw numpy
  dtype, so a policy change re-types every contracted field at once;
- axes are the symbolic names the shape-convention table in
  ``encoding/state.py`` documents (``N`` nodes, ``R`` resources, ``U``
  templates, ...). ``AXIS_ALIASES`` maps builder-local spellings
  (``Qmax``, ``N2``, ``n_topo``) onto the canonical axis; matching is
  case-insensitive.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

#: All resource/score/weight tensors. Go parity: float32 end to end — a
#: float64 leak makes XLA insert converts and can flip score ties.
FLOAT_DTYPE = np.float32

#: All id/index tensors (template ids, vocab ids, domain ids, node indices).
INT_DTYPE = np.int32

#: Quantities that must round-trip Go int64 exactly (resourceVersion,
#: replica counts) stay host-side Python ints; when they must enter an
#: array, this is the dtype.
INT64_DTYPE = np.int64

#: Accumulation dtype for the log(k+2) topology-spread weight table — the
#: one sanctioned float64 in the encoder. The table is computed in float64
#: and cast to FLOAT_DTYPE so the XLA scan, the numpy precompute and the
#: sweeps gather bitwise-identical weights (XLA:CPU's f32 log and numpy's
#: differ by 1 ulp on ~3% of inputs, enough to flip score ties).
LOG_ACC_DTYPE = np.float64


def log_size_table(n: int) -> np.ndarray:
    """The shared [n+1] float32 log(k+2) lookup (see LOG_ACC_DTYPE).

    Used by the encoder (encoding/state.py) and by checkpoint loading
    (utils/checkpoint.py) for pre-log_sizes checkpoints — both must produce
    the same bits for the same node count."""
    return np.log(np.arange(n + 1, dtype=LOG_ACC_DTYPE) + 2.0).astype(FLOAT_DTYPE)


# --------------------------------------------------------------------------
# Array contract registry (OSL1801–OSL1804)
# --------------------------------------------------------------------------

#: Structural dtypes for mask/byte arenas. Not "policy" in the narrowing
#: sense — bool masks marshal to the native engine as u8 — but contracts
#: name them so every arena field resolves through this module.
BOOL_DTYPE = np.bool_
UINT8_DTYPE = np.uint8

#: Builder-local axis spellings → canonical axis names (case-insensitive on
#: both sides). ``extend_nodes`` grows arenas at ``N2/K2/R2/Tt2``; the
#: template assembler pads the requirement axis to ``Qmax = max(Q, Qp)``;
#: the raw arena's topology axis is ``n_topo`` columns wide.
AXIS_ALIASES: Dict[str, str] = {
    "n2": "N",
    "k2": "K",
    "r2": "R",
    "tt2": "Tt",
    "gd2": "Gd",
    "vg2": "Vg",
    "dv2": "Dv",
    "qmax": "Q",
    "n_topo": "Tk",
    "n_now": "Tk",
}

#: (policy-constant name, symbolic axes) for every ``EncodedCluster`` field.
#: Key set is gated against ``EncodedCluster._fields`` by
#: tests/test_arena_contracts.py AND by OSL1804, so adding an arena field
#: without a contract fails the build.
ARENA_CONTRACTS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    # nodes
    "node_valid": ("BOOL_DTYPE", ("N",)),
    "alloc": ("FLOAT_DTYPE", ("N", "R")),
    "unschedulable": ("BOOL_DTYPE", ("N",)),
    "taint_key": ("INT_DTYPE", ("N", "Tt")),
    "taint_val": ("INT_DTYPE", ("N", "Tt")),
    "taint_effect": ("INT_DTYPE", ("N", "Tt")),
    "label_val": ("INT_DTYPE", ("N", "K")),
    "label_num": ("FLOAT_DTYPE", ("N", "K")),
    "node_domain": ("INT_DTYPE", ("N", "Tk")),
    "domain_topo": ("INT_DTYPE", ("D+1",)),
    # templates
    "req": ("FLOAT_DTYPE", ("U", "R")),
    "tol_valid": ("BOOL_DTYPE", ("U", "Tl")),
    "tol_key": ("INT_DTYPE", ("U", "Tl")),
    "tol_op": ("INT_DTYPE", ("U", "Tl")),
    "tol_val": ("INT_DTYPE", ("U", "Tl")),
    "tol_effect": ("INT_DTYPE", ("U", "Tl")),
    "ns_key": ("INT_DTYPE", ("U", "Qs")),
    "ns_val": ("INT_DTYPE", ("U", "Qs")),
    "has_req_aff": ("BOOL_DTYPE", ("U",)),
    "aff_term_valid": ("BOOL_DTYPE", ("U", "T")),
    "aff_key": ("INT_DTYPE", ("U", "T", "Q")),
    "aff_op": ("INT_DTYPE", ("U", "T", "Q")),
    "aff_val": ("INT_DTYPE", ("U", "T", "Q", "Vv")),
    "aff_num": ("FLOAT_DTYPE", ("U", "T", "Q")),
    "pna_weight": ("FLOAT_DTYPE", ("U", "Pp")),
    "pna_key": ("INT_DTYPE", ("U", "Pp", "Q")),
    "pna_op": ("INT_DTYPE", ("U", "Pp", "Q")),
    "pna_val": ("INT_DTYPE", ("U", "Pp", "Q", "Vv")),
    "pna_num": ("FLOAT_DTYPE", ("U", "Pp", "Q")),
    "ports": ("INT_DTYPE", ("U", "Hp")),
    "port_conflict": ("BOOL_DTYPE", ("Hports", "Hports")),
    "spr_topo": ("INT_DTYPE", ("U", "Cs")),
    "spr_sel": ("INT_DTYPE", ("U", "Cs")),
    "spr_skew": ("INT_DTYPE", ("U", "Cs")),
    "spr_hard": ("BOOL_DTYPE", ("U", "Cs")),
    "at_sel": ("INT_DTYPE", ("U", "Ti")),
    "at_topo": ("INT_DTYPE", ("U", "Ti")),
    "an_sel": ("INT_DTYPE", ("U", "Tn")),
    "an_topo": ("INT_DTYPE", ("U", "Tn")),
    "pt_sel": ("INT_DTYPE", ("U", "Tpp")),
    "pt_topo": ("INT_DTYPE", ("U", "Tpp")),
    "pt_w": ("FLOAT_DTYPE", ("U", "Tpp")),
    "matches_sel": ("BOOL_DTYPE", ("U", "A")),
    "anti_g": ("BOOL_DTYPE", ("U", "G")),
    "prefg_w": ("FLOAT_DTYPE", ("U", "Gp")),
    "pin": ("INT_DTYPE", ("U",)),
    # global term tables
    "anti_g_sel": ("INT_DTYPE", ("G",)),
    "anti_g_topo": ("INT_DTYPE", ("G",)),
    "prefg_sel": ("INT_DTYPE", ("Gp",)),
    "prefg_topo": ("INT_DTYPE", ("Gp",)),
    # gpu-share extension
    "gpu_mem": ("FLOAT_DTYPE", ("U",)),
    "gpu_count": ("INT_DTYPE", ("U",)),
    "node_gpu_mem": ("FLOAT_DTYPE", ("N", "Gd")),
    "gc_mask": ("BOOL_DTYPE", ("R",)),
    # open-local extension
    "avoid_score": ("FLOAT_DTYPE", ("U", "N")),
    "lvm_req": ("FLOAT_DTYPE", ("U",)),
    "dev_req": ("FLOAT_DTYPE", ("U", "2")),
    "dev_req_count": ("INT_DTYPE", ("U", "2")),
    "dev_req_sizes": ("FLOAT_DTYPE", ("U", "2", "Mv")),
    "node_vg_cap": ("FLOAT_DTYPE", ("N", "Vg")),
    "node_dev_cap": ("FLOAT_DTYPE", ("N", "Dv")),
    "node_dev_media": ("INT_DTYPE", ("N", "Dv")),
    "log_sizes": ("FLOAT_DTYPE", ("N+1",)),
}

#: (policy-constant name, symbolic axes) for every ``ScanState`` field —
#: the scan carry is float32 end to end (Go score parity).
STATE_CONTRACTS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "used": ("FLOAT_DTYPE", ("N", "R")),
    "port_used": ("FLOAT_DTYPE", ("N", "Hports")),
    "dom_sel": ("FLOAT_DTYPE", ("D+1", "A")),
    "dom_anti": ("FLOAT_DTYPE", ("D+1", "G")),
    "dom_prefw": ("FLOAT_DTYPE", ("D+1", "Gp")),
    "gpu_free": ("FLOAT_DTYPE", ("N", "Gd")),
    "vg_free": ("FLOAT_DTYPE", ("N", "Vg")),
    "dev_free": ("FLOAT_DTYPE", ("N", "Dv")),
}

#: ctypes-pack buffer name → arena/state field name, where they differ.
#: ``nativepath.schedule`` renames ``node_gpu_mem`` to the engine's
#: ``node_gpu_cap``; OSL1804 follows this map when cross-checking
#: ``_BUFFERS``/``ScanArgs`` widths against the contracts above.
BUFFER_FIELD_ALIASES: Dict[str, str] = {
    "node_gpu_cap": "node_gpu_mem",
}

#: Boundary contracts for the XLA kernel entries and the jit wrapper:
#: array-typed parameters that cross into traced/compiled code. Values are
#: (policy-constant name, symbolic axes); ``P`` is the padded pod-stream
#: axis. Struct-typed parameters (``ec``/``st``) are covered field-by-field
#: by ARENA_CONTRACTS/STATE_CONTRACTS; the abstract interpreter types them
#: via the struct map below.
KERNEL_ARG_CONTRACTS: Dict[str, Dict[str, Tuple[str, Tuple[str, ...]]]] = {
    "pod_step": {"u": ("INT_DTYPE", ())},
    "bind_update": {"u": ("INT_DTYPE", ())},
    "_schedule_pods_jit": {
        "tmpl_ids": ("INT_DTYPE", ("P",)),
        "pod_valid": ("BOOL_DTYPE", ("P",)),
        "forced": ("BOOL_DTYPE", ("P",)),
    },
    "schedule_pods": {
        "tmpl_ids": ("INT_DTYPE", ("P",)),
        "pod_valid": ("BOOL_DTYPE", ("P",)),
        "forced": ("BOOL_DTYPE", ("P",)),
    },
    # native scan attribution buffers (abi v5): marshalled by
    # nativepath.schedule into ScanArgs.bail_out/class_steps; contracting
    # them here lets OSL1804 gate the ctypes packing AND the C++ pointer
    # width against one declared policy (counts accumulate in i64 like
    # filter_rejects — a 32-bit slot would wrap on long campaign runs)
    "run_scan": {
        "bail_out": ("INT64_DTYPE", ("B",)),
        "class_steps": ("INT64_DTYPE", ("K",)),
    },
}

#: Parameter names conventionally bound to contract-carrying structs at the
#: kernel boundaries (used when a parameter has no ``EncodedCluster``/
#: ``ScanState`` annotation, e.g. inside ``jax.jit``-traced helpers).
STRUCT_PARAM_NAMES: Dict[str, str] = {
    "ec": "EncodedCluster",
    "st": "ScanState",
    "st0": "ScanState",
}
