"""Encoder dtype policy — the single place width decisions live.

The vendored Go scheduler does resource math in float32-comparable space
and keys everything else by integer id, and the differential oracle
compares scores bit-exactly. Every array the encoder builds therefore
names its dtype from here; ``opensim-lint``'s dtype-drift rule (OSL201)
flags any encoder-path array that doesn't.
"""

from __future__ import annotations

import numpy as np

#: All resource/score/weight tensors. Go parity: float32 end to end — a
#: float64 leak makes XLA insert converts and can flip score ties.
FLOAT_DTYPE = np.float32

#: All id/index tensors (template ids, vocab ids, domain ids, node indices).
INT_DTYPE = np.int32

#: Quantities that must round-trip Go int64 exactly (resourceVersion,
#: replica counts) stay host-side Python ints; when they must enter an
#: array, this is the dtype.
INT64_DTYPE = np.int64

#: Accumulation dtype for the log(k+2) topology-spread weight table — the
#: one sanctioned float64 in the encoder. The table is computed in float64
#: and cast to FLOAT_DTYPE so the XLA scan, the numpy precompute and the
#: sweeps gather bitwise-identical weights (XLA:CPU's f32 log and numpy's
#: differ by 1 ulp on ~3% of inputs, enough to flip score ties).
LOG_ACC_DTYPE = np.float64


def log_size_table(n: int) -> np.ndarray:
    """The shared [n+1] float32 log(k+2) lookup (see LOG_ACC_DTYPE).

    Used by the encoder (encoding/state.py) and by checkpoint loading
    (utils/checkpoint.py) for pre-log_sizes checkpoints — both must produce
    the same bits for the same node count."""
    return np.log(np.arange(n + 1, dtype=LOG_ACC_DTYPE) + 2.0).astype(FLOAT_DTYPE)
