"""Helm chart rendering — parity with ``pkg/chart/chart.go`` (ProcessChart:
load chart dir/tarball, coalesce values, render templates, drop NOTES.txt,
sort by install order).

The environment ships no ``helm`` binary, so this implements the Go-template
subset real-world simulator charts use (verified against the reference's
``example/application/charts/yoda``): ``{{ .Values.path }}``,
``{{ .Release.* }}``/``{{ .Chart.* }}``, ``$`` root refs, ``int``/``quote``/
``default`` pipelines, and ``{{- if }}/{{- else }}/{{- end }}`` blocks.
If a ``helm`` binary is on PATH it is preferred.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import tarfile
import tempfile
from typing import Any, List, Optional

import yaml

# helm InstallOrder (helm.sh/helm/v3 pkg/releaseutil/kind_sorter.go)
INSTALL_ORDER = [
    "Namespace", "NetworkPolicy", "ResourceQuota", "LimitRange",
    "PodSecurityPolicy", "PodDisruptionBudget", "ServiceAccount", "Secret",
    "SecretList", "ConfigMap", "StorageClass", "PersistentVolume",
    "PersistentVolumeClaim", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleList", "ClusterRoleBinding", "ClusterRoleBindingList",
    "Role", "RoleList", "RoleBinding", "RoleBindingList", "Service",
    "DaemonSet", "Pod", "ReplicationController", "ReplicaSet", "Deployment",
    "HorizontalPodAutoscaler", "StatefulSet", "Job", "CronJob", "Ingress",
    "APIService",
]
_ORDER = {k: i for i, k in enumerate(INSTALL_ORDER)}


class ChartError(ValueError):
    pass


def process_chart(release_name: str, path: str) -> List[str]:
    """Render a chart directory or .tgz into a list of YAML manifests,
    sorted by helm install order (ProcessChart, pkg/chart/chart.go:18-41)."""
    tmpdir = None
    try:
        if os.path.isfile(path) and (path.endswith(".tgz") or path.endswith(".tar.gz")):
            tmpdir = tempfile.mkdtemp(prefix="simon-chart-")
            with tarfile.open(path) as tf:
                tf.extractall(tmpdir, filter="data")
            entries = [os.path.join(tmpdir, e) for e in os.listdir(tmpdir)]
            dirs = [e for e in entries if os.path.isdir(e)]
            path = dirs[0] if dirs else tmpdir
        if shutil.which("helm"):
            out = subprocess.run(
                ["helm", "template", release_name, path],
                capture_output=True, text=True, check=True,
            ).stdout
            docs = _split_docs(out)
        else:
            docs = _render_chart_dir(release_name, path)
        return _sort_manifests(docs)
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


def _split_docs(text: str) -> List[str]:
    return [d.strip() for d in re.split(r"(?m)^---\s*$", text) if d.strip()]


def _render_chart_dir(release_name: str, path: str) -> List[str]:
    chart_yaml = os.path.join(path, "Chart.yaml")
    if not os.path.isfile(chart_yaml):
        raise ChartError(f"{path}: not a chart (no Chart.yaml)")
    with open(chart_yaml) as f:
        chart_meta = yaml.safe_load(f) or {}
    values_path = os.path.join(path, "values.yaml")
    values = {}
    if os.path.isfile(values_path):
        with open(values_path) as f:
            values = yaml.safe_load(f) or {}
    _validate_values_schema(path, chart_meta.get("name", path), values)
    ctx = {
        "Values": values,
        "Release": {"Name": release_name, "Namespace": "default", "Service": "Helm"},
        "Chart": {
            "Name": chart_meta.get("name", ""),
            "Version": chart_meta.get("version", ""),
            "AppVersion": chart_meta.get("appVersion", ""),
        },
        "Capabilities": {"KubeVersion": {"Version": "v1.21.0", "Major": "1", "Minor": "21"}},
    }
    docs: List[str] = []
    tpl_dir = os.path.join(path, "templates")
    for root, _dirs, files in os.walk(tpl_dir):
        for fname in sorted(files):
            if fname == "NOTES.txt" or fname.startswith("_"):
                continue
            if not fname.endswith((".yaml", ".yml", ".tpl")):
                continue
            with open(os.path.join(root, fname)) as f:
                text = f.read()
            try:
                rendered = render_template(text, ctx)
            except ChartError as e:
                # fail the whole chart with the offending template named,
                # before any partial output escapes
                raise ChartError(
                    f"{chart_meta.get('name', path)}/templates/{fname}: {e}; "
                    "install a `helm` binary on PATH for full template support"
                ) from None
            docs.extend(_split_docs(rendered))
    return docs


def _validate_values_schema(path: str, chart_name: str, values: dict) -> None:
    """Schema-validate the coalesced values against ``values.schema.json``
    when the chart ships one — chartutil.ValidateAgainstSchema, invoked by
    the installability check the reference performs (pkg/chart/chart.go:18-41
    → action.Install's chartutil.ProcessDependencies/ValidateAgainstSchema).
    The helm-binary path needs none of this: helm validates itself."""
    schema_path = os.path.join(path, "values.schema.json")
    if not os.path.isfile(schema_path):
        return
    import json

    try:
        with open(schema_path) as f:
            schema = json.load(f)
    except ValueError as e:
        raise ChartError(f"{chart_name}: invalid values.schema.json: {e}") from None
    try:
        import jsonschema
        from jsonschema import validators
    except ImportError:
        # A chart that ships a schema MUST be validated against it — helm
        # would refuse to install on violation, so silently rendering here
        # would be a parity divergence. Fail loudly instead of warning.
        raise ChartError(
            f"{chart_name} ships values.schema.json but the `jsonschema` "
            "package is not installed; install it (or a `helm` binary on "
            "PATH) to render this chart"
        ) from None
    try:
        # honor the schema's declared draft like helm does; Draft7 default
        cls = validators.validator_for(schema, default=jsonschema.Draft7Validator)
        cls.check_schema(schema)
        errors = sorted(
            cls(schema).iter_errors(values),
            key=lambda e: list(e.absolute_path),
        )
    except jsonschema.SchemaError as e:
        raise ChartError(
            f"{chart_name}: invalid values.schema.json: {e.message}"
        ) from None
    if errors:
        # helm's wording: "values don't meet the specifications of the
        # schema(s) in the following chart(s):"
        detail = "; ".join(
            f"{'.'.join(str(p) for p in e.absolute_path) or '(root)'}: {e.message}"
            for e in errors[:5]
        )
        raise ChartError(
            f"{chart_name}: values don't meet the specifications of the "
            f"schema(s) in the following chart(s): {detail}"
        )


def _sort_manifests(docs: List[str]) -> List[str]:
    def order(doc: str) -> int:
        try:
            obj = yaml.safe_load(doc)
            return _ORDER.get((obj or {}).get("kind", ""), len(INSTALL_ORDER))
        except yaml.YAMLError:
            return len(INSTALL_ORDER)

    return sorted(docs, key=order)


# ---------------------------------------------------------------------------
# The Go-template subset renderer.
# ---------------------------------------------------------------------------

_TOKEN = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)


def render_template(text: str, ctx: dict) -> str:
    tokens = _tokenize(text)
    out, _pos = _render_block(tokens, 0, ctx, stop={"end", "else"})
    return out


def _tokenize(text: str):
    """Split into literal / action tokens, applying {{- and -}} whitespace
    trimming to adjacent literals."""
    tokens = []
    last = 0
    for m in _TOKEN.finditer(text):
        lit = text[last : m.start()]
        if m.group(1) == "-":
            lit = lit.rstrip()
        tokens.append(("lit", lit))
        tokens.append(("act", m.group(2), m.group(3) == "-"))
        last = m.end()
    tokens.append(("lit", text[last:]))
    # apply right-trim to following literal
    for i, t in enumerate(tokens):
        if t[0] == "act" and t[2] and i + 1 < len(tokens) and tokens[i + 1][0] == "lit":
            tokens[i + 1] = ("lit", tokens[i + 1][1].lstrip())
    return tokens


def _render_block(tokens, pos, ctx, stop) -> tuple:
    """Render until a stop action at this nesting level; returns (text, pos
    of the stop token or len)."""
    parts: List[str] = []
    i = pos
    while i < len(tokens):
        tok = tokens[i]
        if tok[0] == "lit":
            parts.append(tok[1])
            i += 1
            continue
        action = tok[1]
        word = action.split()[0] if action.split() else ""
        if word in stop:
            return "".join(parts), i
        if word in ("define", "template", "include", "with", "block"):
            # recognized Go-template constructs outside the supported subset:
            # fail loudly rather than silently rendering an empty string
            raise ChartError(f"unsupported template construct: {{{{ {word} }}}}")
        if word == "if":
            cond = _eval_expr(action[2:].strip(), ctx)
            body, j = _render_block(tokens, i + 1, ctx, stop={"else", "end"})
            if j >= len(tokens):
                raise ChartError("unterminated {{ if }} block in template")
            if tokens[j][1].split()[0] == "else":
                else_body, j = _render_block(tokens, j + 1, ctx, stop={"end"})
            else:
                else_body = ""
            parts.append(body if _truthy(cond) else else_body)
            i = j + 1
        elif word == "range":
            # {{ range .Values.list }} / {{ range $k, $v := .Values.map }}
            expr = action[len("range") :].strip()
            var_names = []
            if ":=" in expr:
                names, expr = expr.split(":=", 1)
                var_names = [v.strip().lstrip("$") for v in names.split(",")]
                expr = expr.strip()
            coll = _eval_expr(expr, ctx)
            body_start = i + 1
            _, j = _render_block(tokens, body_start, ctx, stop={"end"})
            if j >= len(tokens):
                raise ChartError("unterminated {{ range }} block in template")
            items = coll.items() if isinstance(coll, dict) else enumerate(coll or [])
            for k, v in items:
                sub = dict(ctx)
                if var_names:
                    if len(var_names) == 2:
                        sub[var_names[0]], sub[var_names[1]] = k, v
                    else:
                        sub[var_names[0]] = v
                sub["."] = v
                body, _ = _render_block(tokens, body_start, sub, stop={"end"})
                parts.append(body)
            i = j + 1
        elif word == "end":
            return "".join(parts), i
        else:
            val = _eval_expr(action, ctx)
            parts.append("" if val is None else _to_str(val))
            i += 1
    return "".join(parts), i


def _truthy(v: Any) -> bool:
    return bool(v)


def _to_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def _eval_expr(expr: str, ctx: dict) -> Any:
    """Evaluate a pipeline: `func arg | func2` with funcs int, quote,
    default, toString, upper, lower, trunc."""
    stages = [s.strip() for s in expr.split("|")]
    val = _eval_atom(stages[0], ctx)
    for stage in stages[1:]:
        parts = stage.split()
        fn, args = parts[0], [_eval_atom(a, ctx) for a in parts[1:]]
        val = _apply_fn(fn, args + [val])
    return val


def _eval_atom(atom: str, ctx: dict) -> Any:
    atom = atom.strip()
    if atom.startswith('"') and atom.endswith('"'):
        return atom[1:-1]
    parts = atom.split()
    if len(parts) > 1:
        fn = parts[0]
        if fn in ("int", "quote", "default", "toString", "upper", "lower", "not", "toYaml", "trunc"):
            args = [_eval_atom(a, ctx) for a in parts[1:]]
            return _apply_fn(fn, args)
        # a call to anything else would silently render as empty — refuse
        raise ChartError(f"unsupported template function: {fn}")
    if re.fullmatch(r"-?\d+", atom):
        return int(atom)
    if re.fullmatch(r"-?\d+\.\d+", atom):
        return float(atom)
    if atom in ("true", "false"):
        return atom == "true"
    if atom.startswith("$."):
        return _lookup(ctx, atom[2:])
    if atom.startswith("$"):
        return ctx.get(atom[1:].split(".")[0])
    if atom == ".":
        return ctx.get(".", ctx)
    if atom.startswith("."):
        base = ctx.get(".", ctx) if "." in ctx and not _is_root_path(atom) else ctx
        return _lookup(ctx if _is_root_path(atom) else base, atom[1:])
    return None


_ROOT_KEYS = ("Values", "Release", "Chart", "Capabilities", "Files")


def _is_root_path(atom: str) -> bool:
    return atom.split(".")[1] in _ROOT_KEYS if atom.count(".") >= 1 and len(atom.split(".")) > 1 else False


def _lookup(obj: Any, path: str) -> Any:
    cur = obj
    for part in path.split("."):
        if not part:
            continue
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
        if cur is None:
            return None
    return cur


def _apply_fn(fn: str, args: List[Any]) -> Any:
    if fn == "int":
        try:
            return int(float(args[-1]))
        except (TypeError, ValueError):
            return 0
    if fn == "quote":
        return '"%s"' % ("" if args[-1] is None else args[-1])
    if fn == "default":
        return args[-1] if args[-1] not in (None, "", 0, False) else args[0]
    if fn == "toString":
        return _to_str(args[-1])
    if fn == "upper":
        return str(args[-1]).upper()
    if fn == "lower":
        return str(args[-1]).lower()
    if fn == "not":
        return not _truthy(args[-1])
    if fn == "toYaml":
        return yaml.safe_dump(args[-1], default_flow_style=False).rstrip()
    if fn == "trunc":
        return str(args[-1])[: int(args[0])]
    raise ChartError(f"unsupported template function: {fn}")
