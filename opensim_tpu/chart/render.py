"""Helm chart rendering — parity with ``pkg/chart/chart.go`` (ProcessChart:
load chart dir/tarball, coalesce values, render templates, drop NOTES.txt,
sort by install order).

The environment ships no ``helm`` binary, so this implements the
Go-template/sprig subset real-world charts use: ``{{ .Values.path }}``,
``{{ .Release.* }}``/``{{ .Chart.* }}``, ``$`` root refs, variables
(``{{ $x := ... }}``), ``if/else``, ``range``, ``with``, named templates
(``define`` / ``include`` / ``template`` — collected globally across the
chart and its subcharts, helm's namespace), subchart rendering with value
coalescing (parent overrides + ``global`` + ``dependencies[].condition``
gating), and the common pipeline functions (``quote``, ``default``,
``toYaml``, ``nindent``/``indent``, ``printf``, ``eq``/``and``/``or``,
``trimPrefix``/``trimSuffix``, ``replace``, ``contains``, ``required``,
...). Constructs outside the subset fail loudly naming the template.
If a ``helm`` binary is on PATH it is preferred.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import tarfile
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import yaml

# helm InstallOrder (helm.sh/helm/v3 pkg/releaseutil/kind_sorter.go)
INSTALL_ORDER = [
    "Namespace", "NetworkPolicy", "ResourceQuota", "LimitRange",
    "PodSecurityPolicy", "PodDisruptionBudget", "ServiceAccount", "Secret",
    "SecretList", "ConfigMap", "StorageClass", "PersistentVolume",
    "PersistentVolumeClaim", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleList", "ClusterRoleBinding", "ClusterRoleBindingList",
    "Role", "RoleList", "RoleBinding", "RoleBindingList", "Service",
    "DaemonSet", "Pod", "ReplicationController", "ReplicaSet", "Deployment",
    "HorizontalPodAutoscaler", "StatefulSet", "Job", "CronJob", "Ingress",
    "APIService",
]
_ORDER = {k: i for i, k in enumerate(INSTALL_ORDER)}


class ChartError(ValueError):
    pass


def process_chart(release_name: str, path: str) -> List[str]:
    """Render a chart directory or .tgz into a list of YAML manifests,
    sorted by helm install order (ProcessChart, pkg/chart/chart.go:18-41)."""
    tmpdir = None
    try:
        if os.path.isfile(path) and (path.endswith(".tgz") or path.endswith(".tar.gz")):
            tmpdir = tempfile.mkdtemp(prefix="simon-chart-")
            with tarfile.open(path) as tf:
                tf.extractall(tmpdir, filter="data")
            entries = [os.path.join(tmpdir, e) for e in os.listdir(tmpdir)]
            dirs = [e for e in entries if os.path.isdir(e)]
            path = dirs[0] if dirs else tmpdir
        if shutil.which("helm"):
            out = subprocess.run(
                ["helm", "template", release_name, path],
                capture_output=True, text=True, check=True,
            ).stdout
            docs = _split_docs(out)
        else:
            docs = _render_chart_dir(release_name, path)
        return _sort_manifests(docs)
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


def _split_docs(text: str) -> List[str]:
    return [d.strip() for d in re.split(r"(?m)^---\s*$", text) if d.strip()]


# ---------------------------------------------------------------------------
# chart tree loading (parent + subcharts, value coalescing)
# ---------------------------------------------------------------------------


class _Chart:
    def __init__(self, name: str, meta: dict, values: dict, tpl_dir: str):
        self.name = name
        self.meta = meta
        self.values = values
        self.tpl_dir = tpl_dir


def _deep_merge(base: dict, override: dict) -> dict:
    """helm's CoalesceValues: override wins; nested maps merge."""
    out = dict(base)
    for k, v in (override or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _load_chart(path: str) -> Tuple[dict, dict]:
    chart_yaml = os.path.join(path, "Chart.yaml")
    if not os.path.isfile(chart_yaml):
        raise ChartError(f"{path}: not a chart (no Chart.yaml)")
    with open(chart_yaml) as f:
        meta = yaml.safe_load(f) or {}
    values = {}
    values_path = os.path.join(path, "values.yaml")
    if os.path.isfile(values_path):
        with open(values_path) as f:
            values = yaml.safe_load(f) or {}
    return meta, values


def _gather_charts(
    path: str, values_override: Optional[dict], parent_globals: Optional[dict]
) -> List[_Chart]:
    """Load a chart and its charts/ subcharts with coalesced values:
    the parent's ``values[<subchart name>]`` overrides the subchart's own
    values.yaml; ``global`` flows down; ``dependencies[].condition`` paths
    evaluated against the PARENT's values gate each subchart (an absent
    condition path keeps the subchart enabled — helm semantics)."""
    meta, own_values = _load_chart(path)
    name = meta.get("name", os.path.basename(path))
    values = _deep_merge(own_values, values_override or {})
    if parent_globals:
        values["global"] = _deep_merge(values.get("global") or {}, parent_globals)
    _validate_values_schema(path, name, values)
    charts = [_Chart(name, meta, values, os.path.join(path, "templates"))]

    conditions: Dict[str, str] = {}
    for dep in meta.get("dependencies") or []:
        if isinstance(dep, dict) and dep.get("name") and dep.get("condition"):
            conditions[str(dep["name"])] = str(dep["condition"])

    charts_dir = os.path.join(path, "charts")
    if os.path.isdir(charts_dir):
        for entry in sorted(os.listdir(charts_dir)):
            sub_path = os.path.join(charts_dir, entry)
            if not os.path.isdir(sub_path) or not os.path.isfile(
                os.path.join(sub_path, "Chart.yaml")
            ):
                continue
            sub_meta, _ = _load_chart(sub_path)
            sub_name = sub_meta.get("name", entry)
            cond = conditions.get(sub_name)
            if cond is not None:
                flag = _lookup(values, cond)
                if flag is not None and not _truthy(flag):
                    continue
            charts.extend(
                _gather_charts(
                    sub_path,
                    values.get(sub_name) if isinstance(values.get(sub_name), dict) else {},
                    values.get("global") or {},
                )
            )
    return charts


def _render_chart_dir(release_name: str, path: str) -> List[str]:
    charts = _gather_charts(path, None, None)

    # pass 1: collect named templates (define blocks) from EVERY template
    # file of every chart — helm's template namespace is global, and
    # helpers conventionally live in _helpers.tpl (collected, not emitted)
    defs: Dict[str, list] = {}
    pending = []  # (chart, fname, tokens) for files that emit output
    for chart in charts:
        if not os.path.isdir(chart.tpl_dir):
            continue
        for root, _dirs, files in os.walk(chart.tpl_dir):
            for fname in sorted(files):
                if not fname.endswith((".yaml", ".yml", ".tpl", ".txt")):
                    continue
                with open(os.path.join(root, fname)) as f:
                    text = f.read()
                try:
                    tokens = _collect_defines(_tokenize(text), defs)
                except ChartError as e:
                    raise ChartError(f"{chart.name}/templates/{fname}: {e}") from None
                if fname == "NOTES.txt" or fname.startswith("_"):
                    continue  # define-collection only
                pending.append((chart, fname, tokens))

    docs: List[str] = []
    for chart, fname, tokens in pending:
        ctx = {
            "Values": chart.values,
            "Release": {"Name": release_name, "Namespace": "default", "Service": "Helm"},
            "Chart": {
                "Name": chart.meta.get("name", ""),
                "Version": chart.meta.get("version", ""),
                "AppVersion": chart.meta.get("appVersion", ""),
            },
            "Capabilities": {
                "KubeVersion": {"Version": "v1.21.0", "Major": "1", "Minor": "21"}
            },
        }
        ctx["__defs__"] = defs
        ctx["__root__"] = ctx  # what $ resolves to (rebound per include arg)
        ctx["__top__"] = ctx  # the file-level context (.Values etc. source)
        ctx["__vars__"] = _Vars()
        try:
            rendered, _ = _render_block(tokens, 0, ctx, stop=set())
        except ChartError as e:
            # fail the whole chart with the offending template named,
            # before any partial output escapes
            raise ChartError(
                f"{chart.name}/templates/{fname}: {e}; "
                "install a `helm` binary on PATH for full template support"
            ) from None
        except Exception as e:  # never a raw traceback without the template name
            raise ChartError(
                f"{chart.name}/templates/{fname}: {type(e).__name__}: {e}"
            ) from e
        docs.extend(_split_docs(rendered))
    return docs


def _validate_values_schema(path: str, chart_name: str, values: dict) -> None:
    """Schema-validate the coalesced values against ``values.schema.json``
    when the chart ships one — chartutil.ValidateAgainstSchema, invoked by
    the installability check the reference performs (pkg/chart/chart.go:18-41
    → action.Install's chartutil.ProcessDependencies/ValidateAgainstSchema).
    The helm-binary path needs none of this: helm validates itself."""
    schema_path = os.path.join(path, "values.schema.json")
    if not os.path.isfile(schema_path):
        return
    import json

    try:
        with open(schema_path) as f:
            schema = json.load(f)
    except ValueError as e:
        raise ChartError(f"{chart_name}: invalid values.schema.json: {e}") from None
    try:
        import jsonschema
        from jsonschema import validators
    except ImportError:
        # A chart that ships a schema MUST be validated against it — helm
        # would refuse to install on violation, so silently rendering here
        # would be a parity divergence. Fail loudly instead of warning.
        raise ChartError(
            f"{chart_name} ships values.schema.json but the `jsonschema` "
            "package is not installed; install it (or a `helm` binary on "
            "PATH) to render this chart"
        ) from None
    try:
        # honor the schema's declared draft like helm does; Draft7 default
        cls = validators.validator_for(schema, default=jsonschema.Draft7Validator)
        cls.check_schema(schema)
        errors = sorted(
            cls(schema).iter_errors(values),
            key=lambda e: list(e.absolute_path),
        )
    except jsonschema.SchemaError as e:
        raise ChartError(
            f"{chart_name}: invalid values.schema.json: {e.message}"
        ) from None
    if errors:
        # helm's wording: "values don't meet the specifications of the
        # schema(s) in the following chart(s):"
        detail = "; ".join(
            f"{'.'.join(str(p) for p in e.absolute_path) or '(root)'}: {e.message}"
            for e in errors[:5]
        )
        raise ChartError(
            f"{chart_name}: values don't meet the specifications of the "
            f"schema(s) in the following chart(s): {detail}"
        )


def _sort_manifests(docs: List[str]) -> List[str]:
    def order(doc: str) -> int:
        try:
            obj = yaml.safe_load(doc)
            return _ORDER.get((obj or {}).get("kind", ""), len(INSTALL_ORDER))
        except yaml.YAMLError:
            return len(INSTALL_ORDER)

    return sorted(docs, key=order)


# ---------------------------------------------------------------------------
# The Go-template subset renderer.
# ---------------------------------------------------------------------------

_TOKEN = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)

_BLOCK_OPENERS = ("if", "range", "with", "define", "block")


def render_template(text: str, ctx: dict) -> str:
    """Render standalone template text (unit-test surface). Collects any
    define blocks in the text itself."""
    ctx = dict(ctx)
    defs = dict(ctx.get("__defs__") or {})
    ctx["__defs__"] = defs
    ctx.setdefault("__root__", ctx)
    ctx.setdefault("__top__", ctx)
    ctx.setdefault("__vars__", _Vars())
    tokens = _collect_defines(_tokenize(text), defs)
    out, _pos = _render_block(tokens, 0, ctx, stop={"end", "else"})
    return out


def _tokenize(text: str):
    """Split into literal / action tokens, applying {{- and -}} whitespace
    trimming to adjacent literals. Comments {{/* ... */}} drop."""
    tokens = []
    last = 0
    for m in _TOKEN.finditer(text):
        lit = text[last : m.start()]
        if m.group(1) == "-":
            lit = lit.rstrip()
        tokens.append(("lit", lit))
        action = m.group(2)
        if not (action.startswith("/*") and action.endswith("*/")):
            tokens.append(("act", action, m.group(3) == "-"))
        else:
            tokens.append(("act", "", m.group(3) == "-"))  # comment: no-op
        last = m.end()
    tokens.append(("lit", text[last:]))
    # apply right-trim to following literal
    for i, t in enumerate(tokens):
        if t[0] == "act" and t[2] and i + 1 < len(tokens) and tokens[i + 1][0] == "lit":
            tokens[i + 1] = ("lit", tokens[i + 1][1].lstrip())
    return tokens


def _first_word(action: str) -> str:
    parts = action.split()
    return parts[0] if parts else ""


def _collect_defines(tokens, defs: Dict[str, list]):
    """Strip {{ define "name" }}...{{ end }} blocks out of the token stream,
    registering their bodies in `defs` (helm's global template namespace).
    Returns the remaining tokens."""
    out = []
    i = 0
    while i < len(tokens):
        tok = tokens[i]
        if tok[0] == "act" and _first_word(tok[1]) == "define":
            m = re.match(r'define\s+"([^"]+)"', tok[1])
            if not m:
                raise ChartError(f"malformed define: {{{{ {tok[1]} }}}}")
            depth = 1
            j = i + 1
            while j < len(tokens) and depth:
                if tokens[j][0] == "act":
                    w = _first_word(tokens[j][1])
                    if w in _BLOCK_OPENERS:
                        depth += 1
                    elif w == "end":
                        depth -= 1
                j += 1
            if depth:
                raise ChartError(f'unterminated define "{m.group(1)}"')
            defs[m.group(1)] = tokens[i + 1 : j - 1]
            i = j
        else:
            out.append(tok)
            i += 1
    return out


class _Vars:
    """Lexically scoped template variables (Go template semantics):
    ``:=`` declares in the current block scope; ``=`` assigns the nearest
    enclosing declaration (the range-accumulator idiom) and fails loudly if
    none exists."""

    def __init__(self, parent: Optional["_Vars"] = None):
        self.map: Dict[str, Any] = {}
        self.parent = parent

    def get(self, name: str):
        scope = self
        while scope is not None:
            if name in scope.map:
                return scope.map[name]
            scope = scope.parent
        return None

    def has(self, name: str) -> bool:
        scope = self
        while scope is not None:
            if name in scope.map:
                return True
            scope = scope.parent
        return False

    def declare(self, name: str, val: Any) -> None:
        self.map[name] = val

    def assign(self, name: str, val: Any) -> None:
        scope = self
        while scope is not None:
            if name in scope.map:
                scope.map[name] = val
                return
            scope = scope.parent
        raise ChartError(f"assignment to undeclared variable ${name}")


def _child_scope(ctx: dict) -> dict:
    sub = dict(ctx)
    sub["__vars__"] = _Vars(ctx.get("__vars__"))
    return sub


def _scan_block(tokens, start) -> tuple:
    """Locate the matching {{ end }} (and top-level {{ else }}) for a block
    whose opener sits just before `start`, WITHOUT evaluating anything —
    falsy branches must never run their bodies' side effects (required,
    include of absent templates...). Returns (else_pos_or_None, end_pos)."""
    depth = 1
    else_pos = None
    i = start
    while i < len(tokens):
        if tokens[i][0] == "act":
            w = _first_word(tokens[i][1])
            if w in _BLOCK_OPENERS:
                depth += 1
            elif w == "end":
                depth -= 1
                if depth == 0:
                    return else_pos, i
            elif w == "else" and depth == 1 and else_pos is None:
                else_pos = i
        i += 1
    raise ChartError("unterminated block in template")


def _render_block(tokens, pos, ctx, stop) -> tuple:
    """Render until a stop action at this nesting level; returns (text, pos
    of the stop token or len)."""
    parts: List[str] = []
    i = pos
    while i < len(tokens):
        tok = tokens[i]
        if tok[0] == "lit":
            parts.append(tok[1])
            i += 1
            continue
        action = tok[1]
        if not action:  # stripped comment
            i += 1
            continue
        word = _first_word(action)
        if word in stop:
            return "".join(parts), i
        if word in ("define", "block"):
            # define is collected pre-render; block (define+emit in place)
            # stays outside the supported subset: fail loudly
            raise ChartError(f"unsupported template construct: {{{{ {word} }}}}")
        m_assign = re.match(r"\$(\w+)\s*(:?=)\s*(.+)$", action, re.S)
        if word == "if":
            else_pos, end_pos = _scan_block(tokens, i + 1)
            if _truthy(_eval_expr(action[2:].strip(), ctx)):
                body, _ = _render_block(
                    tokens, i + 1, _child_scope(ctx), stop={"else", "end"}
                )
                parts.append(body)
            elif else_pos is not None:
                else_action = tokens[else_pos][1][4:].strip()
                if else_action.startswith("if"):
                    # {{ else if X }}: re-enter as a fresh if-chain sharing
                    # the outer end token; the slice is bounded at end_pos so
                    # nothing after the block can leak into the chain render
                    chain = [("act", else_action, False)] + tokens[
                        else_pos + 1 : end_pos + 1
                    ]
                    else_body, _ = _render_block(chain, 0, ctx, stop={"end"})
                else:
                    else_body, _ = _render_block(
                        tokens, else_pos + 1, _child_scope(ctx), stop={"end"}
                    )
                parts.append(else_body)
            i = end_pos + 1
        elif word == "with":
            else_pos, end_pos = _scan_block(tokens, i + 1)
            if else_pos is not None and tokens[else_pos][1].strip() != "else":
                # Go rejects {{ else if }} after with/range at parse time
                raise ChartError(
                    f"unexpected {{{{ {tokens[else_pos][1]} }}}} in with block"
                )
            val = _eval_expr(action[len("with") :].strip(), ctx)
            if _truthy(val):
                sub = _child_scope(ctx)
                sub["."] = val
                # Go scoping: the with body's dot is the pivot value, so
                # .Values/.Release/... resolve against IT (same rule as
                # range bodies; the else branch keeps the outer dot)
                sub["__scoped_dot__"] = True
                body, _ = _render_block(tokens, i + 1, sub, stop={"else", "end"})
                parts.append(body)
            elif else_pos is not None:
                else_body, _ = _render_block(
                    tokens, else_pos + 1, _child_scope(ctx), stop={"end"}
                )
                parts.append(else_body)
            i = end_pos + 1
        elif word == "range":
            # {{ range .Values.list }} / {{ range $k, $v := .Values.map }}
            else_pos, end_pos = _scan_block(tokens, i + 1)
            if else_pos is not None and tokens[else_pos][1].strip() != "else":
                raise ChartError(
                    f"unexpected {{{{ {tokens[else_pos][1]} }}}} in range block"
                )
            expr = action[len("range") :].strip()
            var_names = []
            if ":=" in expr:
                names, expr = expr.split(":=", 1)
                var_names = [v.strip().lstrip("$") for v in names.split(",")]
                expr = expr.strip()
            coll = _eval_expr(expr, ctx)
            if isinstance(coll, dict):
                # Go templates range maps in key order; YAML permits
                # non-string keys, so compare stringified
                items = sorted(coll.items(), key=lambda kv: str(kv[0]))
            else:
                items = list(enumerate(coll or []))
            if not items and else_pos is not None:
                else_body, _ = _render_block(
                    tokens, else_pos + 1, _child_scope(ctx), stop={"end"}
                )
                parts.append(else_body)
            for k, v in items:
                sub = _child_scope(ctx)
                if var_names:
                    if len(var_names) == 2:
                        sub["__vars__"].declare(var_names[0], k)
                        sub["__vars__"].declare(var_names[1], v)
                    else:
                        sub["__vars__"].declare(var_names[0], v)
                sub["."] = v
                # Go scoping: inside the body the dot IS the item, so
                # .Values/.Release/... no longer reach the chart root
                # (_eval_atom enforces it; $.Values stays available)
                sub["__scoped_dot__"] = True
                body, _ = _render_block(tokens, i + 1, sub, stop={"else", "end"})
                parts.append(body)
            i = end_pos + 1
        elif word == "template":
            args = _split_args(action[len("template") :].strip())
            if not args:
                raise ChartError("template invocation needs a name")
            name = _eval_atom(args[0], ctx)
            arg = _eval_expr(" ".join(args[1:]), ctx) if len(args) > 1 else None
            parts.append(_call_template(str(name), arg, ctx))
            i += 1
        elif m_assign:
            name, op, rhs = m_assign.group(1), m_assign.group(2), m_assign.group(3)
            val = _eval_expr(rhs.strip(), ctx)
            scope = ctx.setdefault("__vars__", _Vars())
            if op == ":=":
                scope.declare(name, val)
            else:  # {{ $x = ... }} updates the enclosing declaration
                scope.assign(name, val)
            i += 1
        elif word == "end":
            return "".join(parts), i
        else:
            val = _eval_expr(action, ctx)
            parts.append("" if val is None else _to_str(val))
            i += 1
    return "".join(parts), i


def _call_template(name: str, arg: Any, ctx: dict):
    """include/template: render a named define with "." AND "$" bound to
    the invocation argument — Go template semantics: $ is documented as the
    starting value of dot for the template being executed, so a helper
    invoked with a non-root argument sees that argument through $, not the
    calling file's root. Caller variables do not leak in (Go scoping); the
    file-level keys (.Values, .Release, ...) stay reachable for the helm
    include idiom."""
    defs = ctx.get("__defs__") or {}
    if name not in defs:
        raise ChartError(f'include of undefined template "{name}"')
    top = ctx.get("__top__") or ctx
    sub = {k: v for k, v in top.items() if not k.startswith("__")}
    sub["__defs__"] = defs
    sub["__top__"] = top
    sub["__root__"] = arg
    sub["__vars__"] = _Vars()
    sub["."] = arg
    out, _ = _render_block(defs[name], 0, sub, stop=set())
    return out


def _truthy(v: Any) -> bool:
    return bool(v)


def _to_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


# -- expression evaluation ---------------------------------------------------


def _split_top(s: str, sep_ws: bool) -> List[str]:
    """Split at top level: on whitespace (sep_ws) or on '|', respecting
    double quotes, backquotes and parentheses."""
    out: List[str] = []
    cur = []
    depth = 0
    quote = ""
    i = 0
    while i < len(s):
        c = s[i]
        if quote:
            cur.append(c)
            if c == quote and s[i - 1] != "\\":
                quote = ""
        elif c in ('"', "`"):
            quote = c
            cur.append(c)
        elif c == "(":
            depth += 1
            cur.append(c)
        elif c == ")":
            depth -= 1
            cur.append(c)
        elif depth == 0 and ((c.isspace() and sep_ws) or (c == "|" and not sep_ws)):
            if "".join(cur).strip():
                out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(c)
        i += 1
    if "".join(cur).strip():
        out.append("".join(cur).strip())
    return out


def _split_args(s: str) -> List[str]:
    return _split_top(s, sep_ws=True)


def _eval_expr(expr: str, ctx: dict) -> Any:
    """Evaluate a pipeline: `func arg | func2 ...`."""
    stages = _split_top(expr, sep_ws=False)
    if not stages:
        return None
    val = _eval_atom(stages[0], ctx)
    for stage in stages[1:]:
        parts = _split_args(stage)
        fn, args = parts[0], [_eval_atom(a, ctx) for a in parts[1:]]
        val = _apply_fn(fn, args + [val], ctx)
    return val


_FUNCS = {
    "int", "quote", "squote", "default", "toString", "upper", "lower", "not",
    "toYaml", "trunc", "indent", "nindent", "printf", "print", "eq", "ne",
    "lt", "le", "gt", "ge", "and", "or", "trimSuffix", "trimPrefix", "trim",
    "replace", "contains", "hasPrefix", "hasSuffix", "required", "include",
    "len", "add", "sub", "mul", "title", "kindIs", "empty", "coalesce",
    "ternary", "join", "splitList", "first", "last", "get", "index", "dict",
    "list", "toJson", "b64enc", "b64dec", "sha256sum", "hasKey", "keys",
    "sortAlpha", "min", "max", "until", "repeat",
}


def _eval_atom(atom: str, ctx: dict) -> Any:
    atom = atom.strip()
    if atom.startswith("(") and atom.endswith(")"):
        return _eval_expr(atom[1:-1], ctx)
    if atom.startswith('"') and atom.endswith('"') and len(atom) >= 2:
        return atom[1:-1].replace('\\"', '"').replace("\\n", "\n").replace("\\t", "\t")
    if atom.startswith("`") and atom.endswith("`") and len(atom) >= 2:
        return atom[1:-1]
    parts = _split_args(atom)
    if len(parts) > 1:
        fn = parts[0]
        if fn in _FUNCS:
            args = [_eval_atom(a, ctx) for a in parts[1:]]
            return _apply_fn(fn, args, ctx)
        # a call to anything else would silently render as empty — refuse
        raise ChartError(f"unsupported template function: {fn}")
    if re.fullmatch(r"-?\d+", atom):
        return int(atom)
    if re.fullmatch(r"-?\d+\.\d+", atom):
        return float(atom)
    if atom in ("true", "false"):
        return atom == "true"
    if atom in ("nil", "null"):
        return None
    if atom == "$":
        return ctx.get("__root__", ctx)
    if atom.startswith("$."):
        return _lookup(ctx.get("__root__", ctx), atom[2:])
    if atom.startswith("$"):
        name = atom[1:].split(".")[0]
        vars_ = ctx.get("__vars__")
        if vars_ is None or not vars_.has(name):
            # Go fails template execution on an undefined variable; silently
            # rendering None would feed wrong manifests into the simulation
            raise ChartError(f"undefined variable ${name}")
        base = vars_.get(name)
        rest = atom[1 + len(name) :].lstrip(".")
        return _lookup(base, rest) if rest else base
    if atom == ".":
        return ctx.get(".", ctx)
    if atom.startswith("."):
        if _is_root_path(atom) and ctx.get("__scoped_dot__"):
            # helm/Go scoping: inside a {{ range }}/{{ with }} body the dot
            # is the item/pivot — .Values/.Release/... resolve against it,
            # not the chart root ($.Values reaches the root). Go errors on
            # a non-map dot; a map dot follows plain key lookup. Silently
            # resolving from the root rendered manifests helm refuses.
            dot = ctx.get(".", ctx)
            if isinstance(dot, dict):
                return _lookup(dot, atom[1:])
            raise ChartError(
                f"{atom} inside a range/with body resolves against the "
                f"rebound dot ({type(dot).__name__}), not the chart root — "
                f"use ${atom}"
            )
        base = ctx.get(".", ctx) if "." in ctx and not _is_root_path(atom) else ctx
        return _lookup(ctx if _is_root_path(atom) else base, atom[1:])
    return None


_ROOT_KEYS = ("Values", "Release", "Chart", "Capabilities", "Files")


def _is_root_path(atom: str) -> bool:
    return atom.split(".")[1] in _ROOT_KEYS if atom.count(".") >= 1 and len(atom.split(".")) > 1 else False


def _lookup(obj: Any, path: str) -> Any:
    cur = obj
    for part in path.split("."):
        if not part:
            continue
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
        if cur is None:
            return None
    return cur


def _num(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def _num_strict(fn: str, v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        raise ChartError(f"non-numeric operand for {fn}: {v!r}") from None


def _apply_fn(fn: str, args: List[Any], ctx: Optional[dict] = None) -> Any:
    """Pipeline/function application. Piped values arrive as the LAST arg
    (sprig convention: `"x" | trimSuffix "-"` → trimSuffix("-", "x"))."""
    if fn == "int":
        try:
            return int(float(args[-1]))
        except (TypeError, ValueError):
            return 0
    if fn == "quote":
        v = "" if args[-1] is None else _to_str(args[-1])
        return '"%s"' % v.replace("\\", "\\\\").replace('"', '\\"')
    if fn == "squote":
        v = "" if args[-1] is None else _to_str(args[-1])
        return "'%s'" % v.replace("'", "''")
    if fn == "default":
        return args[-1] if args[-1] not in (None, "", 0, False, [], {}) else args[0]
    if fn == "toString":
        return _to_str(args[-1])
    if fn == "upper":
        return str(args[-1]).upper()
    if fn == "lower":
        return str(args[-1]).lower()
    if fn == "title":
        return str(args[-1]).title()
    if fn == "not":
        return not _truthy(args[-1])
    if fn == "toYaml":
        return yaml.safe_dump(args[-1], default_flow_style=False, sort_keys=False).rstrip()
    if fn == "toJson":
        import json

        return json.dumps(args[-1])
    if fn == "trunc":
        n = int(args[0])
        s = str(args[-1])
        return s[:n] if n >= 0 else s[n:]
    if fn == "indent":
        pad = " " * int(args[0])
        return pad + str(args[-1]).replace("\n", "\n" + pad)
    if fn == "nindent":
        pad = " " * int(args[0])
        return "\n" + pad + str(args[-1]).replace("\n", "\n" + pad)
    if fn == "print":
        return "".join(_to_str(a) for a in args)
    if fn == "printf":
        fmt = str(args[0])
        vals = iter(args[1:])
        out = []
        i = 0
        try:
            while i < len(fmt):
                c = fmt[i]
                if c != "%":
                    out.append(c)
                    i += 1
                    continue
                d = fmt[i + 1] if i + 1 < len(fmt) else ""
                if d == "%":
                    out.append("%")
                elif d in ("s", "v"):
                    out.append(_to_str(next(vals)))
                elif d == "q":
                    v = _to_str(next(vals))
                    out.append('"%s"' % v.replace("\\", "\\\\").replace('"', '\\"'))
                elif d == "d":
                    out.append(str(int(_num_strict("printf %d", next(vals)))))
                elif d == "f":
                    out.append("%f" % _num_strict("printf %f", next(vals)))
                else:
                    raise ChartError(f"printf: unsupported directive %{d}")
                i += 2
        except StopIteration:
            raise ChartError(f"printf {fmt!r}: not enough arguments") from None
        return "".join(out)
    if fn == "eq":
        return any(args[0] == b for b in args[1:])
    if fn == "ne":
        return args[0] != args[1]
    if fn == "lt":
        return _num_strict(fn, args[0]) < _num_strict(fn, args[1])
    if fn == "le":
        return _num_strict(fn, args[0]) <= _num_strict(fn, args[1])
    if fn == "gt":
        return _num_strict(fn, args[0]) > _num_strict(fn, args[1])
    if fn == "ge":
        return _num_strict(fn, args[0]) >= _num_strict(fn, args[1])
    if fn == "and":
        for a in args:
            if not _truthy(a):
                return a
        return args[-1]
    if fn == "or":
        for a in args:
            if _truthy(a):
                return a
        return args[-1]
    if fn == "trimSuffix":
        s, suf = str(args[-1]), str(args[0])
        return s[: -len(suf)] if suf and s.endswith(suf) else s
    if fn == "trimPrefix":
        s, pre = str(args[-1]), str(args[0])
        return s[len(pre) :] if pre and s.startswith(pre) else s
    if fn == "trim":
        return str(args[-1]).strip()
    if fn == "replace":
        return str(args[-1]).replace(str(args[0]), str(args[1]))
    if fn == "contains":
        return str(args[0]) in str(args[-1])
    if fn == "hasPrefix":
        return str(args[-1]).startswith(str(args[0]))
    if fn == "hasSuffix":
        return str(args[-1]).endswith(str(args[0]))
    if fn == "required":
        if args[-1] in (None, ""):
            raise ChartError(str(args[0]))
        return args[-1]
    if fn == "include":
        if ctx is None:
            raise ChartError("include outside a template context")
        return _call_template(str(args[0]), args[1] if len(args) > 1 else None, ctx)
    if fn == "len":
        try:
            return len(args[-1])
        except TypeError:
            return 0
    if fn == "add":
        return sum(int(_num(a)) for a in args)
    if fn == "sub":
        return int(_num(args[0])) - int(_num(args[1]))
    if fn == "mul":
        out = 1
        for a in args:
            out *= int(_num(a))
        return out
    if fn == "kindIs":
        kinds = {dict: "map", list: "slice", str: "string", bool: "bool", int: "int", float: "float64"}
        return kinds.get(type(args[-1])) == str(args[0])
    if fn == "empty":
        return not _truthy(args[-1])
    if fn == "coalesce":
        for a in args:
            if _truthy(a):
                return a
        return None
    if fn == "ternary":
        return args[0] if _truthy(args[-1]) else args[1]
    if fn == "join":
        return str(args[0]).join(_to_str(x) for x in (args[-1] or []))
    if fn == "splitList":
        return str(args[-1]).split(str(args[0]))
    if fn == "first":
        return (args[-1] or [None])[0]
    if fn == "last":
        return (args[-1] or [None])[-1]
    if fn in ("get", "index"):
        # direct call: container first (`index .Values.list 1`); piped:
        # container arrives LAST (`.Values.labels | get "app"`)
        if isinstance(args[0], (dict, list, tuple)):
            cur, keys = args[0], args[1:]
        else:
            cur, keys = args[-1], args[:-1]
        for key in keys:
            if isinstance(cur, dict):
                cur = cur.get(key)
            elif isinstance(cur, (list, tuple)):
                try:
                    cur = cur[int(key)]
                except (IndexError, ValueError, TypeError):
                    return None
            else:
                return None
        return cur
    if fn == "dict":
        return {str(args[i]): args[i + 1] for i in range(0, len(args) - 1, 2)}
    if fn == "list":
        return list(args)
    if fn == "b64enc":
        import base64

        v = "" if args[-1] is None else _to_str(args[-1])
        return base64.b64encode(v.encode()).decode()
    if fn == "b64dec":
        import base64

        try:
            return base64.b64decode(str(args[-1])).decode()
        except Exception as e:
            raise ChartError(f"b64dec: {e}") from None
    if fn == "sha256sum":
        import hashlib

        v = "" if args[-1] is None else _to_str(args[-1])
        return hashlib.sha256(v.encode()).hexdigest()
    if fn == "hasKey":
        if len(args) < 2:
            return False
        # direct form: hasKey DICT KEY; piped: DICT arrives last
        d, k = (args[0], args[1]) if isinstance(args[0], dict) else (args[-1], args[0])
        return isinstance(d, dict) and str(k) in d
    if fn == "keys":
        return list(args[-1]) if isinstance(args[-1], dict) else []
    if fn == "sortAlpha":
        return sorted(_to_str(x) for x in (args[-1] or []))
    if fn == "min":
        return min(int(_num(a)) for a in args)
    if fn == "max":
        return max(int(_num(a)) for a in args)
    if fn == "until":
        return list(range(int(_num(args[-1]))))
    if fn == "repeat":
        return str(args[-1]) * int(_num_strict("repeat", args[0]))
    raise ChartError(f"unsupported template function: {fn}")
