"""Capacity observatory — continuous utilization, headroom & fragmentation
telemetry derived from the live twin (ISSUE 9).

The reference system's actual product is its capacity report (PAPER.md L6:
per-node utilization, new-nodes-needed, per-app landing sites — the
``pkg/apply`` renderer). Our port rendered that only as a one-shot text
dump, while the live twin (``server/watch.py``) already maintains exactly
the cluster state the report needs, continuously and at O(changes) cost.
This module closes that gap: a :class:`CapacityEngine` that keeps the
derived capacity view warm the same way the twin keeps the prep warm.

Incrementality contract (mirrors PR 6's prep deltas):

- **event path is O(1)**: every accepted twin event updates per-node
  request/allocatable aggregates, the per-node utilization *distribution*
  (bucket counts moved between fixed utilization buckets), the spread
  moments (Σu, Σu² per resource — stddev/mean falls out in O(1)), and the
  pending-pod pressure counter. No full-cluster rescan, ever, on the event
  path.
- **sample path is O(nodes), generation-keyed**: fragmentation (largest
  free node vs total free) and the top-K hottest-node list are folds over
  the per-node aggregates, computed at most once per twin generation when
  someone looks (a scrape, a report, the supervisor tick) and memoized.
  These are float folds over in-memory aggregates — never an O(cluster)
  re-expand/re-encode (``make capacity-smoke`` proves the full-prepare
  count stays at bootstrap).
- **headroom is probed, not guessed**: the max additional replicas of each
  registered workload profile (``OPENSIM_HEADROOM_PROFILES``) is found by
  the existing batched scenario scan over the always-warm prep — the app
  is delta re-encoded onto the cached base arenas
  (``prepcache.derive_with_app_slices``) and candidate replica counts are
  probed as pod-validity mask prefixes, so the verdict is bit-consistent
  with a fresh ``simulate`` of the same cluster plus that many replicas.

Surfaces: cardinality-capped Prometheus families in ``/metrics``
(``simon_cluster_utilization_bucket{resource=}`` distribution, top-K
``simon_cluster_node_utilization{node=,resource=}`` series,
``simon_cluster_headroom{profile=}`` and the aggregate gauges),
``GET /api/cluster/report`` (one computation path with the text renderer
in ``planner/report.py``), ``GET /api/debug/capacity`` (the timeline
ring), and the ``simon top`` CLI live view. See docs/observability.md
"Watching cluster capacity".
"""

from __future__ import annotations

import heapq
import io
import logging
import math
import re
import threading
from dataclasses import dataclass
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

if TYPE_CHECKING:
    from ..engine.prepcache import CacheEntry

from ..models.objects import LABEL_APP_NAME, Node, Pod, ResourceTypes
from ..models.quantity import format_milli, format_quantity, parse_quantity
from ..utils import envknobs
from .metrics import UTILIZATION_BUCKETS, escape_label_value, family_header
from .timeline import Sample, Timeline

log = logging.getLogger("opensim_tpu.obs")

__all__ = [
    "CapacityEngine",
    "WorkloadProfile",
    "build_report",
    "format_top",
    "headroom_probe",
    "headroom_profiles",
    "snapshot_result",
    "topk_nodes",
]

#: the resources the observatory tracks per node ("pods" is the bound-pod
#: count vs the node's pod capacity) — a fixed set on purpose: the
#: per-resource label cardinality is part of the registry contract
RESOURCES: Tuple[str, ...] = ("cpu", "memory", "pods")

_CPU, _MEM, _PODS = 0, 1, 2

#: default registered headroom profiles (OPENSIM_HEADROOM_PROFILES
#: overrides): a typical small service pod and a chunky batch pod
DEFAULT_PROFILES = "small=500m:1Gi,large=4:8Gi"

_PROFILE_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]+$")


def topk_nodes() -> int:
    """``OPENSIM_CAPACITY_TOPK`` (default 10): the per-node series cap for
    ``simon_cluster_node_utilization`` — the cardinality governor that
    keeps a 100k-node twin from emitting 300k series per scrape. A typo
    degrades to the default with a warning."""
    raw = envknobs.raw("OPENSIM_CAPACITY_TOPK")
    try:
        return max(0, int(raw)) if raw else 10
    except ValueError:
        log.warning("ignoring unparseable OPENSIM_CAPACITY_TOPK=%r (using 10)", raw)
        return 10


@dataclass(frozen=True)
class WorkloadProfile:
    """One registered headroom probe shape: ``cpu``/``memory`` are quantity
    strings (they parameterize a fake Deployment template), ``max_replicas``
    bounds the probe ladder."""

    name: str
    cpu: str
    memory: str
    max_replicas: int = 256

    @property
    def cpu_cores(self) -> float:
        return parse_quantity(self.cpu)

    @property
    def mem_bytes(self) -> float:
        return parse_quantity(self.memory)


def headroom_profiles() -> List[WorkloadProfile]:
    """Parse ``OPENSIM_HEADROOM_PROFILES`` (``name=cpu:mem[:max],...``).
    Validated loudly like ``watch_policy`` — a silently-dropped typo would
    report headroom for profiles the operator never asked about."""
    raw = envknobs.raw("OPENSIM_HEADROOM_PROFILES").strip() or DEFAULT_PROFILES
    out: List[WorkloadProfile] = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, spec = entry.partition("=")
        parts = spec.split(":")
        if not sep or len(parts) not in (2, 3):
            raise ValueError(
                f"OPENSIM_HEADROOM_PROFILES entry {entry!r} must be "
                "name=cpu:memory[:max_replicas]"
            )
        name = name.strip()
        if not _PROFILE_NAME_RE.match(name):
            raise ValueError(
                f"OPENSIM_HEADROOM_PROFILES profile name {name!r} must match "
                f"{_PROFILE_NAME_RE.pattern}"
            )
        max_replicas = 256
        if len(parts) == 3:
            try:
                max_replicas = int(parts[2])
            except ValueError:
                raise ValueError(
                    f"OPENSIM_HEADROOM_PROFILES max_replicas {parts[2]!r} must be an integer"
                ) from None
            if max_replicas < 1:
                raise ValueError("OPENSIM_HEADROOM_PROFILES max_replicas must be >= 1")
        profile = WorkloadProfile(name, parts[0].strip(), parts[1].strip(), max_replicas)
        if profile.cpu_cores <= 0 and profile.mem_bytes <= 0:
            raise ValueError(
                f"OPENSIM_HEADROOM_PROFILES profile {name!r} requests no cpu and "
                "no memory; its headroom would be unbounded"
            )
        out.append(profile)
    if len({p.name for p in out}) != len(out):
        raise ValueError("OPENSIM_HEADROOM_PROFILES has duplicate profile names")
    return out


class _NodeState:
    """Per-node aggregate the event path maintains in O(1): allocatable and
    requested vectors over :data:`RESOURCES`, plus the utilization-bucket
    index currently credited per resource (-1 = not in the distribution —
    zero allocatable makes the ratio undefined)."""

    __slots__ = ("alloc", "req", "bucket")

    def __init__(self) -> None:
        self.alloc = [0.0, 0.0, 0.0]
        self.req = [0.0, 0.0, 0.0]
        self.bucket = [-1, -1, -1]


class CapacityEngine:
    """The incrementally-maintained capacity view. Thread-safe: the watch
    supervisor's dispatch feeds events while scrapes/reports read samples.

    Wiring: a live-twin server attaches the engine to its
    :class:`~..server.watch.WatchSupervisor` (bootstrap on sync, one
    ``on_twin_change`` per accepted event, ``sample()`` on the maintenance
    tick); a polling/custom-cluster server bootstraps lazily per snapshot
    key via :meth:`ensure_bootstrap`."""

    def __init__(self, topk: Optional[int] = None, timeline: Optional[Timeline] = None) -> None:
        self._lock = threading.RLock()
        self.topk = topk_nodes() if topk is None else max(0, topk)
        self.timeline = timeline if timeline is not None else Timeline()
        self._buckets = tuple(UTILIZATION_BUCKETS) + (math.inf,)
        self._nodes: Dict[str, _NodeState] = {}  # guarded-by: _lock
        # requests accumulated per NODE NAME, independent of whether the
        # node object has been seen yet (a pod can be bound to a node whose
        # ADDED event arrives later; its contribution folds in on arrival)
        self._node_req: Dict[str, List[float]] = {}  # guarded-by: _lock
        self._pods: Dict[Tuple[str, str], Tuple[str, float, float]] = {}  # guarded-by: _lock
        self._pending = 0  # guarded-by: _lock
        # distribution state per resource: bucket counts + spread moments
        self._dist = [[0] * len(self._buckets) for _ in RESOURCES]  # guarded-by: _lock
        self._sum_u = [0.0, 0.0, 0.0]  # guarded-by: _lock
        self._sum_u2 = [0.0, 0.0, 0.0]  # guarded-by: _lock
        self._n_util = [0, 0, 0]  # guarded-by: _lock
        self._alloc_total = [0.0, 0.0, 0.0]  # guarded-by: _lock
        self._req_total = [0.0, 0.0, 0.0]  # guarded-by: _lock
        # < 0: never bootstrapped, render nothing
        self.generation = -1  # guarded-by: _lock
        self._boot_key: Optional[str] = None  # guarded-by: _lock
        self._headroom: Dict[str, int] = {}  # guarded-by: _lock
        self._sample: Optional[Sample] = None  # guarded-by: _lock
        # set by the watch supervisor once it owns the view (bootstrap +
        # per-event feed): snapshot-keyed rebootstraps become no-ops
        self.event_fed = False  # guarded-by: _lock

    # -- bootstrap ----------------------------------------------------------

    def bootstrap(self, cluster: ResourceTypes, generation: int, key: Optional[str] = None) -> None:
        """One O(cluster) pass rebuilding the aggregates from scratch — the
        observatory's analogue of the twin's list+rebase (sync, 410
        recovery, anti-entropy repair, or a polling snapshot change)."""
        with self._lock:
            self._nodes.clear()
            self._node_req.clear()
            self._pods.clear()
            self._pending = 0
            self._dist = [[0] * len(self._buckets) for _ in RESOURCES]
            self._sum_u = [0.0, 0.0, 0.0]
            self._sum_u2 = [0.0, 0.0, 0.0]
            self._n_util = [0, 0, 0]
            self._alloc_total = [0.0, 0.0, 0.0]
            self._req_total = [0.0, 0.0, 0.0]
            for node in cluster.nodes:
                self._node_upsert(node)
            for pod in cluster.pods:
                self._pod_upsert(pod)
            self.generation = generation
            self._boot_key = key
            self._sample = None

    def ensure_bootstrap(self, cluster: ResourceTypes, key: str) -> None:
        """Polling-path maintenance: rebootstrap only when the snapshot key
        (content fingerprint or twin generation key) moved. Once the watch
        supervisor owns the view (``event_fed``) this is a no-op — events,
        not snapshot keys, keep it fresh."""
        with self._lock:
            if self.generation >= 0 and (self.event_fed or self._boot_key == key):
                return
            next_gen = self.generation + 1
        self.bootstrap(cluster, next_gen, key=key)

    # -- event path (O(1) per accepted twin event) --------------------------

    def on_twin_change(
        self, field: str, ev_type: str, obj: dict, change: tuple, generation: int
    ) -> None:
        """Fold one ACCEPTED twin event (``ClusterTwin.apply_event``
        returned a non-None change verdict) into the aggregates. The
        verdict carries decoded objects for the delta-shaped cases; only
        pod/node MODIFIED arrives as a bare ``rebuild`` and pays its own
        O(1) re-wrap here."""
        kind = change[0]
        with self._lock:
            if kind == "pod_add":
                self._pod_upsert(change[1])
            elif kind == "pod_del":
                self._pod_remove(change[1])
            elif kind == "node_add":
                self._node_upsert(change[1])
            elif field == "pods" and ev_type in ("ADDED", "MODIFIED"):
                self._pod_upsert(Pod.from_dict(obj))
            elif field == "nodes":
                meta = obj.get("metadata") or {}
                if ev_type == "DELETED":
                    self._node_remove(str(meta.get("name") or ""))
                elif ev_type in ("ADDED", "MODIFIED"):
                    self._node_upsert(Node.from_dict(obj))
            # non-pod/node resources don't change capacity accounting
            self.generation = generation
            self._boot_key = None  # event-fed: content key no longer applies

    def on_replay(self, record: dict, twin, change: Optional[tuple]) -> None:
        """Fold one replayed journal record (a ``server/journal.py``
        :func:`~..server.journal.replay_events` triple) into the
        aggregates: event records ride the same O(1) ``on_twin_change``
        path the live dispatch uses, and list-shaped records (checkpoint
        fast-forward, 410/anti-entropy rebases) rebootstrap from the
        replay twin — exactly the live supervisor's ``_capacity_rebase``
        moments, so a replayed timeline matches the recorded one."""
        t = record.get("t")
        if t == "ev" and change is not None:
            self.on_twin_change(
                str(record.get("f") or ""), str(record.get("k") or ""),
                record.get("o") or {}, change, int(record.get("gen") or 0),
            )
            return
        if t in ("rb", "ck"):
            with twin._lock:
                cluster = twin.materialize()
                gen = twin.generation
            self.claim_event_fed()
            self.bootstrap(cluster, gen)

    # -- internal accounting -------------------------------------------------

    @staticmethod
    def _pod_vec(pod: Pod) -> Tuple[float, float]:
        req = pod.resource_requests()
        return float(req.get("cpu", 0.0)), float(req.get("memory", 0.0))

    def _bucket_of(self, u: float) -> int:
        for i, bound in enumerate(self._buckets):
            if u <= bound:
                return i
        return len(self._buckets) - 1

    def _retire_node(self, name: str) -> None:
        ns = self._nodes.get(name)
        if ns is None:
            return
        for r in range(len(RESOURCES)):
            if ns.bucket[r] >= 0:
                u = ns.req[r] / ns.alloc[r]
                self._dist[r][ns.bucket[r]] -= 1
                self._sum_u[r] -= u
                self._sum_u2[r] -= u * u
                self._n_util[r] -= 1
                ns.bucket[r] = -1

    def _admit_node(self, name: str) -> None:
        ns = self._nodes.get(name)
        if ns is None:
            return
        req = self._node_req.get(name)
        ns.req = list(req) if req is not None else [0.0, 0.0, 0.0]
        for r in range(len(RESOURCES)):
            if ns.alloc[r] > 0:
                u = ns.req[r] / ns.alloc[r]
                ns.bucket[r] = self._bucket_of(u)
                self._dist[r][ns.bucket[r]] += 1
                self._sum_u[r] += u
                self._sum_u2[r] += u * u
                self._n_util[r] += 1

    def _node_upsert(self, node: Node) -> None:
        name = node.metadata.name
        alloc = [
            float(node.allocatable.get("cpu", 0.0)),
            float(node.allocatable.get("memory", 0.0)),
            float(node.allocatable.get("pods", 0.0)),
        ]
        self._retire_node(name)
        ns = self._nodes.get(name)
        if ns is None:
            ns = self._nodes[name] = _NodeState()
        for r in range(len(RESOURCES)):
            self._alloc_total[r] += alloc[r] - ns.alloc[r]
        ns.alloc = alloc
        self._admit_node(name)
        self._sample = None

    def _node_remove(self, name: str) -> None:
        ns = self._nodes.get(name)
        if ns is None:
            return
        self._retire_node(name)
        for r in range(len(RESOURCES)):
            self._alloc_total[r] -= ns.alloc[r]
        del self._nodes[name]
        # bound-pod contributions stay in _node_req/_req_total: the pods
        # still exist; they fold back into the distribution if the node
        # reappears (the twin treats node flap exactly the same way)
        self._sample = None

    def _add_req(self, node_name: str, cpu: float, mem: float, sign: float) -> None:
        self._retire_node(node_name)
        req = self._node_req.setdefault(node_name, [0.0, 0.0, 0.0])
        req[_CPU] += sign * cpu
        req[_MEM] += sign * mem
        req[_PODS] += sign
        self._req_total[_CPU] += sign * cpu
        self._req_total[_MEM] += sign * mem
        self._req_total[_PODS] += sign
        if sign < 0 and req[_PODS] <= 0 and abs(req[_CPU]) < 1e-12 and abs(req[_MEM]) < 1e-12:
            self._node_req.pop(node_name, None)
        self._admit_node(node_name)

    def _pod_upsert(self, pod: Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        self._pod_remove(key)
        if pod.phase in ("Succeeded", "Failed"):
            # terminal pods hold no capacity (the twin's admissibility rule
            # already deletes them on the event path; this covers bootstrap
            # from custom/unfiltered clusters)
            self._sample = None
            return
        cpu, mem = self._pod_vec(pod)
        node = pod.spec.node_name or ""
        self._pods[key] = (node, cpu, mem)
        if node:
            self._add_req(node, cpu, mem, +1.0)
        else:
            self._pending += 1
        self._sample = None

    def _pod_remove(self, key: Tuple[str, str]) -> None:
        old = self._pods.pop(key, None)
        if old is None:
            return
        node, cpu, mem = old
        if node:
            self._add_req(node, cpu, mem, -1.0)
        else:
            self._pending -= 1
        self._sample = None

    # -- headroom ------------------------------------------------------------

    def fit_upper_bound(self, profile: WorkloadProfile) -> int:
        """Resource-fit upper bound on the profile's additional replicas,
        O(nodes) over the aggregates: Σ over nodes of how many replicas the
        node's FREE cpu/memory/pod-slots admit. An upper bound only — the
        scan is authoritative (scheduling constraints can only reduce it) —
        used to size the probe ladder, never to report headroom."""
        cpu, mem = profile.cpu_cores, profile.mem_bytes
        total = 0
        with self._lock:
            for ns in self._nodes.values():
                k = float("inf")
                if cpu > 0:
                    k = min(k, math.floor(max(0.0, ns.alloc[_CPU] - ns.req[_CPU]) / cpu + 1e-6))
                if mem > 0:
                    k = min(k, math.floor(max(0.0, ns.alloc[_MEM] - ns.req[_MEM]) / mem + 1e-6))
                if ns.alloc[_PODS] > 0:
                    k = min(k, math.floor(max(0.0, ns.alloc[_PODS] - ns.req[_PODS]) + 1e-6))
                if not math.isfinite(k):
                    return profile.max_replicas
                total += int(k)
                if total >= profile.max_replicas:
                    return profile.max_replicas
        return min(total, profile.max_replicas)

    def claim_event_fed(self) -> None:
        """The watch supervisor declares ownership of the view (it will
        bootstrap and feed per-event updates): snapshot-keyed rebootstraps
        via :meth:`ensure_bootstrap` become no-ops from here on."""
        with self._lock:
            self.event_fed = True

    def set_headroom(self, values: Dict[str, int]) -> None:
        """Record the latest probe verdicts (merged into samples and the
        ``simon_cluster_headroom`` gauges until the next probe)."""
        with self._lock:
            self._headroom = dict(values)
            self._sample = None

    def headroom(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._headroom)

    # -- sampling (generation-keyed, O(nodes)) -------------------------------

    def sample(self) -> Optional[Sample]:
        """The derived capacity view for the current generation, memoized —
        repeated scrapes/reports of one generation reuse the fold. Appends
        to (or refreshes) the timeline. None before the first bootstrap."""
        with self._lock:
            if self.generation < 0:
                return None
            if self._sample is not None and self._sample.generation == self.generation:
                return self._sample
            s = Sample(generation=self.generation)
            s.nodes = len(self._nodes)
            s.pods_bound = len(self._pods) - self._pending
            s.pods_pending = self._pending
            free_total = [0.0, 0.0, 0.0]
            free_max = [0.0, 0.0, 0.0]
            for ns in self._nodes.values():
                for r in range(len(RESOURCES)):
                    free = max(0.0, ns.alloc[r] - ns.req[r])
                    free_total[r] += free
                    if free > free_max[r]:
                        free_max[r] = free
            for r, res in enumerate(RESOURCES):
                s.allocatable[res] = self._alloc_total[r]
                s.requested[res] = self._req_total[r]
                s.utilization[res] = (
                    self._req_total[r] / self._alloc_total[r] if self._alloc_total[r] > 0 else 0.0
                )
                n = self._n_util[r]
                if n > 0:
                    mean = self._sum_u[r] / n
                    var = max(0.0, self._sum_u2[r] / n - mean * mean)
                    s.spread[res] = math.sqrt(var) / mean if mean > 0 else 0.0
                else:
                    s.spread[res] = 0.0
                s.fragmentation[res] = (
                    1.0 - free_max[r] / free_total[r] if free_total[r] > 0 else 0.0
                )
            s.headroom = dict(self._headroom)
            s.hottest = self._hottest_locked()
            self._sample = s
        self.timeline.append(s)
        return s

    def _hottest_locked(self) -> List[Tuple[str, Dict[str, float]]]:
        """Top-K nodes by hottest resource ratio (cpu/memory), with a
        deterministic name tie-break so repeat scrapes of an idle cluster
        render identical series."""
        if self.topk <= 0:
            return []

        def heat(item):
            name, ns = item
            us = [
                ns.req[r] / ns.alloc[r]
                for r in (_CPU, _MEM)
                if ns.alloc[r] > 0
            ]
            return max(us) if us else 0.0

        top = heapq.nsmallest(
            self.topk, self._nodes.items(), key=lambda item: (-heat(item), item[0])
        )
        out = []
        for name, ns in top:
            out.append(
                (
                    name,
                    {
                        res: (ns.req[r] / ns.alloc[r] if ns.alloc[r] > 0 else 0.0)
                        for r, res in enumerate(RESOURCES)
                    },
                )
            )
        return out

    # -- /metrics ------------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        """Prometheus lines (rendered by the REST layer). Cardinality is
        governed here: per-resource families are bounded by
        :data:`RESOURCES`, per-node series by :attr:`topk`, per-profile
        gauges by the registered profile list."""
        s = self.sample()
        if s is None:
            return []
        esc = escape_label_value
        lines: List[str] = []
        with self._lock:
            lines += family_header("simon_cluster_nodes")
            lines.append(f"simon_cluster_nodes {s.nodes}")
            lines += family_header("simon_cluster_pods_bound")
            lines.append(f"simon_cluster_pods_bound {s.pods_bound}")
            lines += family_header("simon_cluster_pods_pending")
            lines.append(f"simon_cluster_pods_pending {s.pods_pending}")
            for family, values in (
                ("simon_cluster_allocatable", s.allocatable),
                ("simon_cluster_requested", s.requested),
                ("simon_cluster_utilization_ratio", s.utilization),
                ("simon_cluster_spread", s.spread),
                ("simon_cluster_fragmentation", s.fragmentation),
            ):
                lines += family_header(family)
                lines += [
                    f'{family}{{resource="{esc(res)}"}} {values[res]:.6f}'
                    for res in RESOURCES
                ]
            # the per-node utilization DISTRIBUTION: a histogram-shaped
            # snapshot of current state (bucket counts move as nodes heat
            # and cool — maintained incrementally on the event path)
            lines += family_header("simon_cluster_utilization")
            for r, res in enumerate(RESOURCES):
                cum = 0
                for i, bound in enumerate(self._buckets):
                    cum += self._dist[r][i]
                    le = "+Inf" if math.isinf(bound) else f"{bound:g}"
                    lines.append(
                        f'simon_cluster_utilization_bucket{{resource="{esc(res)}",le="{le}"}} {cum}'
                    )
                lines.append(
                    f'simon_cluster_utilization_sum{{resource="{esc(res)}"}} {self._sum_u[r]:.6f}'
                )
                lines.append(
                    f'simon_cluster_utilization_count{{resource="{esc(res)}"}} {self._n_util[r]}'
                )
            if s.hottest:
                lines += family_header("simon_cluster_node_utilization")
                for name, utils in s.hottest:
                    lines += [
                        f'simon_cluster_node_utilization{{node="{esc(name)}",resource="{esc(res)}"}} '
                        f"{utils[res]:.6f}"
                        for res in RESOURCES
                    ]
            if s.headroom:
                lines += family_header("simon_cluster_headroom")
                lines += [
                    f'simon_cluster_headroom{{profile="{esc(p)}"}} {v}'
                    for p, v in sorted(s.headroom.items())
                ]
        return lines


# ---------------------------------------------------------------------------
# headroom probe: batched mask-prefix scan over the always-warm prep
# ---------------------------------------------------------------------------


def _probe_app(profile: WorkloadProfile, replicas: int):
    from ..engine.simulator import AppResource
    from ..models.fixtures import make_fake_deployment

    rt = ResourceTypes()
    rt.add(
        make_fake_deployment(
            f"simon-headroom-{profile.name}", replicas, profile.cpu, profile.memory
        )
    )
    return AppResource(f"simon-headroom-{profile.name}", rt)


def _probe_scan(prep, app_slice: Tuple[int, int], drop, ks: List[int]) -> List[bool]:
    """One batched sweep: scenario ``s`` enables the base stream (minus the
    twin's event-deleted pods) plus the first ``ks[s]`` probe replicas.
    Feasible = every enabled probe replica placed. The probe pods sit at
    the stream tail, so placements of the first k replicas are identical
    across scenarios — feasibility is monotone in k and a prefix ladder
    plus bisection finds the frontier exactly."""
    import numpy as np

    from ..parallel import scenarios

    lo, _hi = app_slice
    P = len(prep.ordered)
    base_valid = np.ones((P,), dtype=bool)
    base_valid[lo:] = False
    if drop is not None:
        base_valid &= ~np.asarray(drop, dtype=bool)[:P]
    node_row = np.asarray(prep.ec_np.node_valid, dtype=bool)
    S = len(ks)
    pod_valid = np.repeat(base_valid[None, :], S, axis=0)
    for s, k in enumerate(ks):
        pod_valid[s, lo : lo + k] = True
    node_valid = np.repeat(node_row[None, :], S, axis=0)
    res = scenarios.sweep_auto(prep, node_valid, pod_valid)
    chosen = np.asarray(res.chosen)
    return [bool((chosen[s, lo : lo + k] >= 0).all()) for s, k in enumerate(ks)]


def _probe_max(prep, app_slice: Tuple[int, int], drop, kmax: int) -> int:
    """Geometric ladder (one sweep) then bisection (S=1 sweeps) for the max
    feasible replica count in [0, kmax]."""
    ladder = sorted({k for k in (2**i for i in range(kmax.bit_length())) if k <= kmax} | {kmax})
    ok = _probe_scan(prep, app_slice, drop, ladder)
    feasible = [k for k, good in zip(ladder, ok) if good]
    if not feasible:
        return 0
    k_lo = max(feasible)
    infeasible = [k for k, good in zip(ladder, ok) if not good and k > k_lo]
    if not infeasible:
        return k_lo  # kmax itself fits
    k_hi = min(infeasible)
    while k_hi - k_lo > 1:
        mid = (k_lo + k_hi) // 2
        if _probe_scan(prep, app_slice, drop, [mid])[0]:
            k_lo = mid
        else:
            k_hi = mid
    return k_lo


def headroom_probe(
    cluster: ResourceTypes,
    profile: WorkloadProfile,
    base: Optional["CacheEntry"] = None,
    kmax: Optional[int] = None,
) -> int:
    """Max additional replicas of ``profile`` the cluster still schedules.

    With a warm ``base`` (a prep-cache :class:`CacheEntry` whose prep was
    built from ``cluster`` with no apps — the twin's always-warm base or
    the REST base entry), the probe app is DELTA re-encoded onto the cached
    arenas and only pays O(replicas) host work; without one it pays one
    full prepare (the bootstrap). ``kmax`` caps the ladder (callers pass
    the engine's :meth:`CapacityEngine.fit_upper_bound`); when the whole
    cap fits the probe re-derives at a doubled cap so a too-small resource
    bound can never under-report (``profile.max_replicas`` is the hard
    ceiling)."""
    from ..engine import prepcache
    from ..engine.simulator import prepare

    kmax = profile.max_replicas if kmax is None else min(kmax, profile.max_replicas)
    if kmax <= 0:
        return 0
    while True:
        app = _probe_app(profile, kmax)
        if base is not None and base.prep is not None:
            with base.lock:
                base.restore()
                got = prepcache.derive_with_app_slices(
                    base.prep, cluster, [app], base_entry=base
                )
                if got is None:
                    return 0  # empty stream: nothing to probe against
                prep, slices = got
                drop = prepcache.pad_drop_mask(base.base_drop, len(prep.ordered))
                try:
                    got_k = _probe_max(prep, slices[0], drop, kmax)
                finally:
                    base.restore()
        else:
            prep = prepare(cluster, [app])
            if prep is None or not prep.app_slices:
                return 0
            got_k = _probe_max(prep, prep.app_slices[0], None, kmax)
        if got_k < kmax or kmax >= profile.max_replicas:
            return got_k
        # the resource bound under-sized the ladder (everything fit):
        # double and re-probe so the report never understates headroom
        kmax = min(profile.max_replicas, kmax * 2)


# ---------------------------------------------------------------------------
# report assembly: ONE computation path for JSON and text
# ---------------------------------------------------------------------------


def snapshot_result(cluster: ResourceTypes):
    """The OBSERVED cluster as a ``SimulateResult``-shaped view (pods
    grouped under their bound nodes, pending pods as unscheduled entries)
    so the planner's report row builders — the same functions the text
    renderer prints — serve ``GET /api/cluster/report`` unchanged."""
    from ..engine import reasons
    from ..engine.simulator import NodeStatus, SimulateResult, UnscheduledPod

    statuses = [NodeStatus(node=n, pods=[]) for n in cluster.nodes]
    by_name = {ns.node.metadata.name: ns for ns in statuses}
    unscheduled = []
    for pod in cluster.pods:
        if pod.phase in ("Succeeded", "Failed"):
            continue
        node = pod.spec.node_name or ""
        if node:
            ns = by_name.get(node)
            if ns is not None:
                ns.pods.append(pod)
        else:
            unscheduled.append(UnscheduledPod(pod, reasons.pending_observed()))
    return SimulateResult(unscheduled_pods=unscheduled, node_status=statuses)


def build_report(
    engine: CapacityEngine,
    cluster: ResourceTypes,
    extended_resources: Optional[List[str]] = None,
    state: str = "",
) -> dict:
    """The ``/api/cluster/report`` body: the capacity sample plus the SAME
    table rows ``planner/report.py`` renders as text (byte-equal cells —
    gated by the report-parity test)."""
    from ..planner import report as report_mod

    extended = list(extended_resources or [])
    result = snapshot_result(cluster)
    app_names = sorted(
        {
            p.metadata.labels.get(LABEL_APP_NAME)
            for ns in result.node_status
            for p in ns.pods
            if p.metadata.labels.get(LABEL_APP_NAME)
        }
    )
    sample = engine.sample()
    # pods bound to a node ABSENT from the view (the node-flap window: the
    # aggregates still count them — see _node_remove) have no table row;
    # list them explicitly so capacity.pods_bound always reconciles with
    # the tables instead of silently disagreeing
    known = {n.metadata.name for n in cluster.nodes}
    orphaned = [
        f"{p.metadata.namespace}/{p.metadata.name} (on {p.spec.node_name})"
        for p in cluster.pods
        if p.spec.node_name
        and p.spec.node_name not in known
        and p.phase not in ("Succeeded", "Failed")
    ]
    out = {
        "state": state,
        "capacity": sample.to_dict() if sample is not None else None,
        "pending": [
            f"{u.pod.metadata.namespace}/{u.pod.metadata.name}"
            for u in result.unscheduled_pods
        ],
        "orphaned": orphaned,
    }
    out.update(report_mod.report_data(result, extended, app_names))
    return out


def format_top(report: dict) -> str:
    """The ``simon top`` table view of one report body (CLI rendering of
    the same JSON the endpoint serves)."""
    from ..planner.report import _table

    out = io.StringIO()
    cap = report.get("capacity") or {}
    state = report.get("state") or "n/a"
    print(
        f"cluster: {cap.get('nodes', 0)} nodes, {cap.get('pods_bound', 0)} pods bound, "
        f"{cap.get('pods_pending', 0)} pending | twin: {state} "
        f"(generation {cap.get('generation', '?')})",
        file=out,
    )
    rows = [["Resource", "Allocatable", "Requested", "Utilization", "Spread", "Fragmentation"]]
    alloc = cap.get("allocatable") or {}
    req = cap.get("requested") or {}
    util = cap.get("utilization") or {}
    spread = cap.get("spread") or {}
    frag = cap.get("fragmentation") or {}
    for res in RESOURCES:
        if res == "cpu":
            a = format_milli(int(alloc.get(res, 0.0) * 1000))
            r = format_milli(int(req.get(res, 0.0) * 1000))
        elif res == "memory":
            a = format_quantity(alloc.get(res, 0.0))
            r = format_quantity(req.get(res, 0.0))
        else:
            a = str(int(alloc.get(res, 0.0)))
            r = str(int(req.get(res, 0.0)))
        rows.append(
            [
                res,
                a,
                r,
                f"{util.get(res, 0.0) * 100:.1f}%",
                f"{spread.get(res, 0.0):.3f}",
                f"{frag.get(res, 0.0):.3f}",
            ]
        )
    _table(rows, out)
    headroom = cap.get("headroom") or {}
    if headroom:
        print("", file=out)
        rows = [["Profile", "Headroom (replicas)"]]
        for name, v in sorted(headroom.items()):
            rows.append([name, str(v)])
        _table(rows, out)
    hottest = cap.get("hottest") or []
    if hottest:
        print("", file=out)
        rows = [["Hottest Node", "CPU", "Memory", "Pods"]]
        for entry in hottest:
            u = entry.get("utilization") or {}
            rows.append(
                [
                    entry.get("node", ""),
                    f"{u.get('cpu', 0.0) * 100:.1f}%",
                    f"{u.get('memory', 0.0) * 100:.1f}%",
                    f"{u.get('pods', 0.0) * 100:.1f}%",
                ]
            )
        _table(rows, out)
    memory = report.get("memory") or {}
    if memory.get("rows"):
        # the memory block (ISSUE 12, ?mem=1 / simon top --mem): rendered
        # from the SAME rows the JSON carries (obs/footprint.memory_rows) —
        # the byte-equal parity contract every report table follows
        print("", file=out)
        _table(memory["rows"], out)
    pending = report.get("pending") or []
    if pending:
        print("", file=out)
        shown = ", ".join(pending[:8]) + (", …" if len(pending) > 8 else "")
        print(f"pending pods ({len(pending)}): {shown}", file=out)
    orphaned = report.get("orphaned") or []
    if orphaned:
        print("", file=out)
        shown = ", ".join(orphaned[:8]) + (", …" if len(orphaned) > 8 else "")
        print(
            f"pods bound to absent nodes ({len(orphaned)}): {shown}",
            file=out,
        )
    return out.getvalue()
