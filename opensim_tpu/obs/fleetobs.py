"""Fleet-wide trace stitching + the event-to-servable freshness pipeline
(ISSUE 20, docs/observability.md "Watching the fleet").

A request served by the fleet has a story that spans three processes:
the twin-owner accepts a watch event, journals it, and publishes
generation *g* over shared memory; a worker attaches *g* and serves
requests from it. Single-process tracing (PR 5) sees only the last act.
This module stitches the acts together with plain data, not a tracing
protocol:

- the owner stamps every ACCEPTED watch event with a 12-hex **event id**
  and its wall-clock acceptance time (``WatchSupervisor._apply``); the id
  rides the journal record (``{"eid": ...}``) and, once the event's
  generation is published, the seqlock control-block payload
  (``payload["trace"]``) together with a fresh **publication span id**;
- workers record the carried ids on attach (``fleet.attach`` trace
  events) and hand them to every request trace via
  :func:`FleetTwinClient.stitch_info`, so the flight recorder can graft
  the owner-side publication under the worker-side tree
  (:func:`publication_tree`) — one stitched tree per request;
- each milestone observes the **freshness histogram**
  ``simon_fleet_freshness_seconds{stage=}`` — stage ∈ ``journaled`` /
  ``published`` (owner) and ``attached`` / ``served`` (worker), each
  measured from the event's acceptance timestamp. Owner and workers share
  a host (the fleet is SO_REUSEPORT + /dev/shm), so wall clocks compare.

Everything here mutates under the ONE recorder lock
(``obs.metrics.RECORDER.lock``) and is bounded: pending events, carried
ids per publication, and remembered publications all have hard caps.
"""

from __future__ import annotations

import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .metrics import FRESHNESS_BUCKETS, RECORDER, family_header, make_histogram

__all__ = [
    "FRESHNESS",
    "FreshnessTracker",
    "PUB_EVENTS_MAX",
    "STAGES",
    "new_event_id",
    "publication_tree",
]

#: the fixed stage vocabulary (cardinality contract for the histogram)
STAGES = ("journaled", "published", "attached", "served")

#: event ids carried per publication payload — the payload rides the
#: seqlock control block, and a rebase folding thousands of events must
#: not balloon it past the block's fixed size
PUB_EVENTS_MAX = 32

#: accepted-but-unpublished events remembered on the owner (a fleet
#: publishes every OPENSIM_FLEET_PUBLISH_MS, so this only fills when
#: there is no publisher — the single-process server — and then it is
#: simply a bounded no-op)
PENDING_MAX = 4096

#: publications remembered per process for stitching (mirrors the flight
#: recorder's bounded-ring philosophy)
PUBS_MAX = 256


def new_event_id() -> str:
    """A 12-hex id for one accepted event or one publication span —
    the same shape as request ids (uuid4 hex prefix), distinguishable
    by context."""
    return uuid.uuid4().hex[:12]


class FreshnessTracker:
    """The per-process half of the freshness pipeline. The owner calls
    :meth:`event_accepted` / :meth:`event_journaled` / :meth:`publication`;
    workers call :meth:`attached` / :meth:`note_served`. One process never
    calls both sides (the single-process server is "owner side only", and
    its pipeline ends at the journal stage)."""

    def __init__(self) -> None:
        self.lock = RECORDER.lock  # the one metrics lock (an RLock)
        self.hist = make_histogram(
            "simon_fleet_freshness_seconds", ("stage",), buckets=FRESHNESS_BUCKETS
        )
        # eid -> (generation, ts_accepted)   # guarded-by: lock
        self._pending: "OrderedDict[str, Tuple[int, float]]" = OrderedDict()
        # generation -> publication info     # guarded-by: lock
        self._pubs: "OrderedDict[int, dict]" = OrderedDict()
        self._served: set = set()  # generations already first-served  # guarded-by: lock

    # -- owner side ----------------------------------------------------------

    def event_accepted(self, eid: str, generation: int, ts: float) -> None:
        """An accepted watch event (``apply_event`` returned a change),
        stamped at its wall-clock acceptance time."""
        with self.lock:
            self._pending[eid] = (generation, ts)
            while len(self._pending) > PENDING_MAX:
                self._pending.popitem(last=False)

    def event_journaled(self, ts_accepted: float, now: Optional[float] = None) -> None:
        """The journal writer durably wrote the event's record."""
        with self.lock:
            self.hist.observe((now or time.time()) - ts_accepted, ("journaled",))

    def publication(self, generation: int, now: Optional[float] = None) -> dict:
        """Fold every pending event with generation ≤ ``generation`` into
        a publication stamp: observes the ``published`` stage per event
        and returns the trace dict the publisher embeds in the control-
        block payload (span id, publish wall time, carried event ids)."""
        now = now or time.time()
        with self.lock:
            events: List[Tuple[str, float]] = []
            for eid in [
                e for e, (g, _) in self._pending.items() if g <= generation
            ]:
                _, ts = self._pending.pop(eid)
                self.hist.observe(now - ts, ("published",))
                events.append((eid, ts))
            events = events[-PUB_EVENTS_MAX:]
            info = {
                "span": new_event_id(),
                "pub_ts": round(now, 6),
                "events": [[eid, round(ts, 6)] for eid, ts in events],
            }
            self._remember_locked(generation, info)
            return info

    # -- worker side ---------------------------------------------------------

    def attached(self, generation: int, info: Optional[dict],
                 now: Optional[float] = None) -> None:
        """A worker attached (or re-attached) the publication carrying
        ``info`` (the payload's ``trace`` dict). First sight of a
        generation observes the ``attached`` stage per carried event."""
        if not isinstance(info, dict):
            return
        now = now or time.time()
        with self.lock:
            first = generation not in self._pubs
            rec = dict(info)
            rec.setdefault("attached_ts", round(now, 6))
            self._remember_locked(generation, rec)
            if first:
                for _eid, ts in rec.get("events") or []:
                    self.hist.observe(now - float(ts), ("attached",))

    def note_served(self, generation: int,
                    now: Optional[float] = None) -> Optional[dict]:
        """A request is being served at ``generation``: the FIRST such
        request per generation closes the pipeline (``served`` stage per
        carried event). Returns the remembered publication info (for
        request-trace stitching) or None when this generation's
        publication was never seen."""
        with self.lock:
            info = self._pubs.get(generation)
            if generation not in self._served:
                self._served.add(generation)
                now = now or time.time()
                if info is not None:
                    info.setdefault("served_ts", round(now, 6))
                    for _eid, ts in info.get("events") or []:
                        self.hist.observe(now - float(ts), ("served",))
            return info

    # -- shared --------------------------------------------------------------

    def _remember_locked(self, generation: int, info: dict) -> None:
        self._pubs[generation] = info
        while len(self._pubs) > PUBS_MAX:
            old, _ = self._pubs.popitem(last=False)
            self._served.discard(old)

    def pub_info(self, generation: int) -> Optional[dict]:
        with self.lock:
            info = self._pubs.get(generation)
            return dict(info) if info is not None else None

    def metrics_lines(self) -> List[str]:
        """``simon_fleet_freshness_seconds`` exposition lines (header-only
        until a stage has observations, like every sparse family)."""
        with self.lock:
            lines = self.hist.render_lines()
        return lines or family_header("simon_fleet_freshness_seconds")

    def reset(self) -> None:
        """Test isolation (mirrors ``RECORDER.reset``)."""
        with self.lock:
            self.hist.reset()
            self._pending.clear()
            self._pubs.clear()
            self._served.clear()


#: THE per-process tracker (owner-side stages in the twin-owner process,
#: worker-side stages in each worker; the single-process server uses the
#: owner side and stops at the journal stage)
FRESHNESS = FreshnessTracker()


def publication_tree(generation) -> Optional[dict]:
    """The owner-side publication rendered as one synthetic span subtree,
    graftable under a worker-side request trace (``GET
    /api/debug/requests/<id>`` adds it as the ``fleet`` section): the
    publication span plus one child per carried watch event, with the
    per-stage latencies the freshness pipeline measured."""
    try:
        gen = int(generation)
    except (TypeError, ValueError):
        return None
    info = FRESHNESS.pub_info(gen)
    if info is None:
        return None
    pub_ts = float(info.get("pub_ts") or 0.0)
    attached_ts = info.get("attached_ts")
    served_ts = info.get("served_ts")
    events = []
    for eid, ts in info.get("events") or []:
        ev = {
            "event_id": eid,
            "accepted_unix": float(ts),
            "accept_to_publish_s": round(pub_ts - float(ts), 6),
        }
        if attached_ts is not None:
            ev["accept_to_attach_s"] = round(float(attached_ts) - float(ts), 6)
        if served_ts is not None:
            ev["accept_to_serve_s"] = round(float(served_ts) - float(ts), 6)
        events.append(ev)
    node = {
        "name": "fleet.publication",
        "span": info.get("span"),
        "generation": gen,
        "published_unix": pub_ts,
        "events": events,
    }
    if attached_ts is not None:
        node["attached_unix"] = float(attached_ts)
    if served_ts is not None:
        node["first_served_unix"] = float(served_ts)
    return node
