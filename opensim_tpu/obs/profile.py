"""Compile telemetry + cumulative phase profiles (ISSUE 12).

Two blind spots closed:

**Compile telemetry.** The jit cache is the difference between a 50 ms
warm request and a multi-second stall, yet nothing counted compiles or
said WHY a signature recompiled. :class:`CompileWatch` instruments the
repo's jit boundaries (``engine/scheduler.schedule_pods``, the scenario
sweeps) — each call builds the abstract signature (leaf shapes/dtypes +
static flags), detects a compile by the jitted function's cache-size
growth, and attributes the recompile cause by diffing against the
previous signature: ``static`` (a static flag changed), ``dtype`` (same
shapes, different dtypes — the classic policy leak), ``shape`` (bucket
padding failed to hold the signature), ``new``/``first`` otherwise.
Backend-wide compile seconds and the persistent compilation cache's
monitoring events come from ``jax.monitoring`` listeners, and the
persistent cache directory's file/byte footprint from
``utils/jitcache.cache_stats``.

**Cumulative phase profiles.** The flight recorder answers "why was THAT
request slow"; capacity questions need "where do requests spend time in
aggregate". :class:`PhaseProfile` folds every recorded trace's span tree
into per-span-name accumulators — call count, inclusive seconds,
EXCLUSIVE seconds (children subtracted, so `prepare` minus its `encode`
child is visible), and a fixed-bucket histogram that serves p50/p99 — fed
from the same :meth:`FlightRecorder.record` sink the debug endpoints
read, so one query replaces walking N traces.

Surfaces: ``GET /api/debug/profile``, ``simon profile``, and the
``simon_compile_*`` / ``simon_phase_profile_*`` ``/metrics`` families
(registered in ``obs/metrics.py`` FAMILIES, conformance-gated). See
docs/observability.md "Memory & profiles".
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .metrics import DEFAULT_BUCKETS, escape_label_value, family_header

log = logging.getLogger("opensim_tpu.obs")

__all__ = [
    "COMPILES",
    "PROFILE",
    "CompileWatch",
    "PhaseProfile",
    "observed_jit_call",
]

#: signature-table bound per boundary: past it new signatures fold into an
#: "overflow" row instead of growing without limit (a runaway shape
#: churn is exactly what the telemetry should surface, not amplify)
_MAX_SIGNATURES = 256

_BUCKETS: Tuple[float, ...] = tuple(DEFAULT_BUCKETS) + (math.inf,)


def _quantile(counts: List[int], total: int, q: float) -> float:
    """histogram_quantile-style linear interpolation over the fixed
    buckets (the same math ``server/loadgen.py`` applies to scrapes)."""
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0
    lo = 0.0
    for count, hi in zip(counts, _BUCKETS):
        if count:
            if cum + count >= rank:
                if math.isinf(hi):
                    return lo
                frac = (rank - cum) / count
                return lo + (hi - lo) * frac
            cum += count
        lo = 0.0 if math.isinf(hi) else hi
    return lo


# ---------------------------------------------------------------------------
# compile telemetry
# ---------------------------------------------------------------------------


def _leaf_sig(leaves: List[Any]) -> Tuple[Tuple[tuple, str], ...]:
    out = []
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        out.append((shape, dtype))
    return tuple(out)


def _attribute_cause(prev: Optional[dict], sig: dict) -> str:
    """Why did this signature compile? Diffed against the PREVIOUS call's
    signature at the same boundary — the question an operator asks is
    "what changed since the warm call", not "which cache line missed"."""
    if prev is None:
        return "first"
    if prev["static"] != sig["static"]:
        return "static"
    shapes = [s for s, _ in sig["leaves"]]
    dtypes = [d for _, d in sig["leaves"]]
    prev_shapes = [s for s, _ in prev["leaves"]]
    prev_dtypes = [d for _, d in prev["leaves"]]
    if shapes == prev_shapes and dtypes != prev_dtypes:
        return "dtype"
    if shapes != prev_shapes:
        return "shape"
    return "new"


class CompileWatch:
    """Per-boundary compile accounting plus process-wide jax monitoring."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> {"compiles", "seconds", "causes": {cause: n},
        #          "signatures": {sig_key: {"count", "seconds"}}, "last_sig"}
        self._fns: Dict[str, dict] = {}  # guarded-by: _lock
        self._backend_compiles = 0  # guarded-by: _lock
        self._backend_seconds = 0.0  # guarded-by: _lock
        self._cache_events: Dict[str, int] = {}  # guarded-by: _lock
        self._installed = False  # guarded-by: _lock

    # -- jax.monitoring (process-wide) --------------------------------------

    def install(self) -> None:
        """Register the jax monitoring listeners (idempotent). Captures
        every backend compile in the process — including boundaries this
        module does not wrap — and the compilation-cache event stream."""
        with self._lock:
            if self._installed:
                return
            self._installed = True
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(self._on_duration)
            jax.monitoring.register_event_listener(self._on_event)
        except (ImportError, AttributeError) as e:
            log.debug("jax monitoring unavailable: %s", e)

    def _on_duration(self, name: str, duration: float, **_kw) -> None:
        if name.endswith("backend_compile_duration"):
            with self._lock:
                self._backend_compiles += 1
                self._backend_seconds += float(duration)

    def _on_event(self, name: str, **_kw) -> None:
        if "/compilation_cache/" in name:
            leaf = name.rsplit("/", 1)[-1]
            with self._lock:
                self._cache_events[leaf] = self._cache_events.get(leaf, 0) + 1

    # -- instrumented boundaries --------------------------------------------

    def _fn_locked(self, name: str) -> dict:
        return self._fns.setdefault(
            name,
            {"compiles": 0, "seconds": 0.0, "causes": {}, "signatures": {},
             "claimed": set(), "last_sig": None},
        )

    def claim(self, name: str, sig: dict) -> Optional[str]:
        """Atomically observe one call's signature: updates the boundary's
        last-seen signature (cause attribution diffs against the previous
        CALL, compiled or not) and claims the signature for measurement if
        it is NEW at this boundary. Returns the attributed cause for the
        claimant, None for everyone else — under concurrency only ONE
        thread measures a given signature, so two workers racing into the
        same cold signature cannot double-count the compile or bill the
        loser's lock-wait as compile seconds."""
        key = (sig["leaves"], sig["static"])
        with self._lock:
            fn = self._fn_locked(name)
            cause = _attribute_cause(fn["last_sig"], sig)
            fn["last_sig"] = sig
            if key in fn["claimed"]:
                return None
            if len(fn["claimed"]) >= _MAX_SIGNATURES:
                return None  # bounded: runaway signature churn stops recording
            fn["claimed"].add(key)
            return cause

    def record(self, name: str, sig: dict, seconds: float,
               cause: Optional[str] = None) -> None:
        key = (sig["leaves"], sig["static"])
        with self._lock:
            fn = self._fn_locked(name)
            if cause is None:
                cause = _attribute_cause(fn["last_sig"], sig)
            fn["compiles"] += 1
            fn["seconds"] += seconds
            fn["causes"][cause] = fn["causes"].get(cause, 0) + 1
            sigs = fn["signatures"]
            if key not in sigs and len(sigs) >= _MAX_SIGNATURES:
                key = "overflow"
            rec = sigs.setdefault(key, {"count": 0, "seconds": 0.0})
            rec["count"] += 1
            rec["seconds"] += seconds

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> dict:
        from ..utils import jitcache

        with self._lock:
            fns = {
                name: {
                    "compiles": fn["compiles"],
                    "seconds": round(fn["seconds"], 6),
                    "causes": dict(fn["causes"]),
                    "distinct_signatures": len(fn["signatures"]),
                }
                for name, fn in sorted(self._fns.items())
            }
            out = {
                "boundaries": fns,
                "backend": {
                    "compiles": self._backend_compiles,
                    "seconds": round(self._backend_seconds, 6),
                },
                "cache_events": dict(sorted(self._cache_events.items())),
            }
        out["persistent_cache"] = jitcache.cache_stats()
        return out

    def metrics_lines(self) -> List[str]:
        from ..utils import jitcache

        esc = escape_label_value
        lines: List[str] = []
        with self._lock:
            if self._fns:
                lines += family_header("simon_compile_total")
                lines += [
                    f'simon_compile_total{{fn="{esc(n)}"}} {fn["compiles"]}'
                    for n, fn in sorted(self._fns.items())
                ]
                lines += family_header("simon_compile_seconds_total")
                lines += [
                    f'simon_compile_seconds_total{{fn="{esc(n)}"}} {fn["seconds"]:.6f}'
                    for n, fn in sorted(self._fns.items())
                ]
                cause_lines = [
                    f'simon_compile_cause_total{{cause="{esc(c)}",fn="{esc(n)}"}} {k}'
                    for n, fn in sorted(self._fns.items())
                    for c, k in sorted(fn["causes"].items())
                ]
                if cause_lines:
                    lines += family_header("simon_compile_cause_total")
                    lines += cause_lines
            lines += [
                *family_header("simon_backend_compile_total"),
                f"simon_backend_compile_total {self._backend_compiles}",
                *family_header("simon_backend_compile_seconds_total"),
                f"simon_backend_compile_seconds_total {self._backend_seconds:.6f}",
            ]
            if self._cache_events:
                lines += family_header("simon_jitcache_events_total")
                lines += [
                    f'simon_jitcache_events_total{{event="{esc(ev)}"}} {n}'
                    for ev, n in sorted(self._cache_events.items())
                ]
        stats = jitcache.cache_stats()
        if stats is not None:
            lines += [
                *family_header("simon_jitcache_persistent_files"),
                f"simon_jitcache_persistent_files {stats['files']}",
                *family_header("simon_jitcache_persistent_bytes"),
                f"simon_jitcache_persistent_bytes {stats['bytes']}",
            ]
        return lines

    def reset(self) -> None:
        with self._lock:
            self._fns.clear()
            self._backend_compiles = 0
            self._backend_seconds = 0.0
            self._cache_events.clear()


COMPILES = CompileWatch()


def observed_jit_call(name: str, fn, args: tuple, static: Optional[dict] = None):
    """Call a jitted function through the compile watch: build the
    abstract signature, time the call, and record a compile when the
    function's jit cache grew. Transparent under tracing (an inner
    ``vmap``/``jit`` caller passes tracers — the call goes straight
    through) and when the cache size is unreadable."""
    import jax

    static = static or {}
    leaves = jax.tree_util.tree_leaves(args)
    if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
        return fn(*args, **static)
    COMPILES.install()
    sig = {
        "leaves": _leaf_sig(leaves),
        "static": tuple(sorted((k, repr(v)) for k, v in static.items())),
    }
    # one atomic observation: last-sig update + new-signature claim. Only
    # the claimant measures — a repeat signature returns None and the call
    # goes straight through (the warm path pays one lock + dict lookup).
    cause = COMPILES.claim(name, sig)
    if cause is None:
        return fn(*args, **static)
    try:
        # private-but-stable jit API: absence degrades to no per-boundary
        # count (the jax.monitoring backend listener still sees the compile)
        before = fn._cache_size()
    except (AttributeError, TypeError):
        before = None
    t0 = time.monotonic()
    try:
        return fn(*args, **static)
    finally:
        if before is not None:
            try:
                grew = fn._cache_size() > before
            except (AttributeError, TypeError):
                grew = False
            if grew:
                COMPILES.record(name, sig, time.monotonic() - t0, cause=cause)


# ---------------------------------------------------------------------------
# cumulative phase profiles
# ---------------------------------------------------------------------------


class _Agg:
    __slots__ = ("count", "incl", "excl", "max_s", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.incl = 0.0
        self.excl = 0.0
        self.max_s = 0.0
        self.buckets = [0] * len(_BUCKETS)

    def add(self, incl: float, excl: float) -> None:
        self.count += 1
        self.incl += incl
        self.excl += excl
        self.max_s = max(self.max_s, incl)
        for i, hi in enumerate(_BUCKETS):
            if incl <= hi:
                self.buckets[i] += 1
                break

    def clone(self) -> "_Agg":
        """Copy taken under the profile lock: snapshot() reads fields after
        releasing it, and a concurrent add() must not tear count vs buckets
        (a mismatch would push _quantile's rank past the histogram)."""
        out = _Agg()
        out.count = self.count
        out.incl = self.incl
        out.excl = self.excl
        out.max_s = self.max_s
        out.buckets = list(self.buckets)
        return out

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "seconds": round(self.incl, 6),
            "exclusive_seconds": round(self.excl, 6),
            "mean_s": round(self.incl / self.count, 6) if self.count else 0.0,
            "p50_s": round(_quantile(self.buckets, self.count, 0.50), 6),
            "p99_s": round(_quantile(self.buckets, self.count, 0.99), 6),
            "max_s": round(self.max_s, 6),
        }


class PhaseProfile:
    """Cumulative span profiles keyed ``(endpoint, span name)``, fed from
    the flight-recorder sink (every finished request trace)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._agg: Dict[Tuple[str, str], _Agg] = {}  # guarded-by: _lock
        self._traces = 0  # guarded-by: _lock

    def observe_trace(self, trace) -> None:
        rows: List[Tuple[str, float, float]] = []
        endpoint = trace.endpoint
        for sp in trace.walk():
            incl = sp.duration_s
            excl = incl - sum(c.duration_s for c in sp.children)
            rows.append((sp.name, incl, max(0.0, excl)))
        with self._lock:
            self._traces += 1
            for name, incl, excl in rows:
                agg = self._agg.get((endpoint, name))
                if agg is None:
                    agg = self._agg[(endpoint, name)] = _Agg()
                agg.add(incl, excl)

    def snapshot(self) -> dict:
        """The ``/api/debug/profile`` phases body: per span name (summed
        over endpoints) and the per-endpoint breakdown."""
        with self._lock:
            items = [(ep, name, agg.clone()) for (ep, name), agg in self._agg.items()]
            traces = self._traces
        by_span: Dict[str, _Agg] = {}
        for _ep, name, agg in items:
            tot = by_span.get(name)
            if tot is None:
                tot = by_span[name] = _Agg()
            tot.count += agg.count
            tot.incl += agg.incl
            tot.excl += agg.excl
            tot.max_s = max(tot.max_s, agg.max_s)
            tot.buckets = [a + b for a, b in zip(tot.buckets, agg.buckets)]
        return {
            "traces": traces,
            "spans": {
                name: agg.to_dict()
                for name, agg in sorted(by_span.items(), key=lambda kv: -kv[1].incl)
            },
            "endpoints": {
                ep: {
                    name: agg.to_dict()
                    for (e2, name, agg) in sorted(items, key=lambda r: -r[2].incl)
                    if e2 == ep
                }
                for ep in sorted({ep for ep, _n, _a in items})
            },
        }

    def metrics_lines(self) -> List[str]:
        esc = escape_label_value
        snap = self.snapshot()
        if not snap["spans"]:
            return []
        lines = [*family_header("simon_phase_profile_calls_total")]
        lines += [
            f'simon_phase_profile_calls_total{{span="{esc(name)}"}} {d["count"]}'
            for name, d in sorted(snap["spans"].items())
        ]
        lines += family_header("simon_phase_profile_seconds_total")
        lines += [
            f'simon_phase_profile_seconds_total{{span="{esc(name)}"}} {d["seconds"]:.6f}'
            for name, d in sorted(snap["spans"].items())
        ]
        lines += family_header("simon_phase_profile_exclusive_seconds_total")
        lines += [
            f'simon_phase_profile_exclusive_seconds_total{{span="{esc(name)}"}} '
            f'{d["exclusive_seconds"]:.6f}'
            for name, d in sorted(snap["spans"].items())
        ]
        return lines

    def reset(self) -> None:
        with self._lock:
            self._agg.clear()
            self._traces = 0


PROFILE = PhaseProfile()

# arm the process-wide jax.monitoring listeners as soon as anything touches
# the obs surface: backend compiles that happen before the first
# instrumented boundary call (encode-time device ops, fastpath builds)
# must still be counted
COMPILES.install()


def debug_payload() -> dict:
    """The ``GET /api/debug/profile`` body (also what ``simon profile``
    renders): the cumulative phase profiles plus the compile telemetry."""
    return {
        "generated_unix": round(time.time(), 3),
        "phases": PROFILE.snapshot(),
        "compiles": COMPILES.snapshot(),
    }
