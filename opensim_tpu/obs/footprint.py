"""Memory observatory — what the simulator's own hot state actually costs
(ISSUE 12).

ROADMAP item 3 ("memory-lean arenas … so 100k nodes fit comfortably")
needs measured numbers before anyone narrows an encoding, and the serving
story needs a leak tripwire: until now nothing could say how many bytes a
cached :class:`~opensim_tpu.engine.prepcache.CacheEntry` holds, which
arena field dominates, or whether the bounded rings are actually bounded
in practice. This module turns the capacity observatory's lens inward:

- **arena accounting** — per-entry byte attribution over the host numpy
  arenas (every ``EncodedCluster`` field plus the stream-side tensors),
  grouped by the encoder dtype policy (``encoding/dtypes.py``), with
  lineage depth (the ``CacheEntry.base`` chain) and drop-mask density per
  entry. Shared leaves (delta entries alias their base's unchanged
  tensors) are counted ONCE in totals: each leaf is credited to the first
  entry that holds it, so cache totals reconcile exactly with the sum of
  per-entry ``unique_bytes`` (gated by ``make mem-smoke``).
- **ring occupancy** — the flight recorder, the capacity timeline and the
  journal writer queue report len/capacity through one view.
- **process + device watermarks** — RSS/VmHWM from ``/proc/self/status``
  (portable fallback: ``resource.getrusage``) and per-device
  ``memory_stats()`` where the backend provides them, sampled on a
  low-rate ticker (``OPENSIM_MEM_TICKER_S``) so peaks between scrapes are
  not lost.

Surfaces: ``GET /api/debug/memory``, ``simon mem``, the ``simon_mem_*``
``/metrics`` families (registered in ``obs/metrics.py`` FAMILIES,
exposition-conformance-gated), and the ``simon top --mem`` block
(docs/observability.md "Memory & profiles").
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import envknobs
from .metrics import escape_label_value, family_header

log = logging.getLogger("opensim_tpu.obs")

__all__ = [
    "MemoryObservatory",
    "device_memory",
    "entry_host_leaves",
    "fmt_bytes",
    "memory_rows",
    "prepcache_footprint",
    "process_memory",
]

#: the encoder dtype policy vocabulary (encoding/dtypes.py) — the fixed
#: label set for simon_mem_arena_bytes{dtype=}; anything else is a policy
#: leak worth seeing ("other")
_POLICY_DTYPES = ("float32", "int32", "int64", "bool")


def _dtype_class(dtype: np.dtype) -> str:
    name = str(dtype)
    return name if name in _POLICY_DTYPES else "other"


# ---------------------------------------------------------------------------
# process + device watermarks
# ---------------------------------------------------------------------------


def process_memory() -> Dict[str, int]:
    """``{"rss_bytes", "rss_peak_bytes"}`` for this process. Linux reads
    ``/proc/self/status`` (VmRSS/VmHWM); elsewhere ``getrusage`` supplies
    the peak and stands in for the current value too."""
    rss = peak = 0
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                elif line.startswith("VmHWM:"):
                    peak = int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    if rss == 0:
        try:
            import resource

            peak = peak or resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
            rss = peak
        except (ImportError, OSError, ValueError):
            pass  # exotic platform: report zeros rather than fail a debug read
    return {"rss_bytes": rss, "rss_peak_bytes": max(rss, peak)}


def device_memory() -> Dict[str, Dict[str, int]]:
    """Per-device memory stats where the backend exposes them (TPU/GPU;
    CPU returns none). Keys: ``in_use`` / ``peak`` bytes."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax

        for dev in jax.local_devices():
            stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
            if not stats:
                continue
            out[str(dev.id)] = {
                "in_use": int(stats.get("bytes_in_use", 0)),
                "peak": int(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))),
            }
    except Exception as e:
        # device enumeration must never fail a debug read (a dead
        # accelerator tunnel can hang-then-raise here); the gap is logged
        log.debug("device memory stats unavailable: %s: %s", type(e).__name__, e)
    return out


# ---------------------------------------------------------------------------
# arena accounting
# ---------------------------------------------------------------------------


def entry_host_leaves(entry) -> List[Tuple[str, np.ndarray]]:
    """``(field name, host numpy array)`` pairs an entry's prep pins: the
    ``EncodedCluster`` arenas plus the stream-side tensors (template ids,
    forced mask, the twin's drop mask). Device tensors are accounted
    separately — on CPU they typically alias these same buffers."""
    prep = entry.prep
    if prep is None or prep.ec_np is None:
        return []
    leaves: List[Tuple[str, np.ndarray]] = []
    for name, arr in zip(type(prep.ec_np)._fields, prep.ec_np):
        if isinstance(arr, np.ndarray):
            leaves.append((name, arr))
    for name in ("tmpl_ids", "forced"):
        arr = getattr(prep, name, None)
        if isinstance(arr, np.ndarray):
            leaves.append((name, arr))
    if entry.base_drop is not None:
        leaves.append(("base_drop", entry.base_drop))
    return leaves


def _lineage_depth(entry) -> int:
    depth = 0
    seen = set()
    node = entry
    while node.base is not None and id(node.base) not in seen:
        seen.add(id(node))
        node = node.base
        depth += 1
    return depth


def entry_footprint(entry, seen_ids: Optional[set] = None) -> dict:
    """One entry's attribution. With ``seen_ids`` (a cache-walk accumulator
    of leaf ``id()``s), ``unique_bytes`` credits each shared leaf to the
    FIRST entry that held it — summing ``unique_bytes`` over a walk equals
    the cache total exactly (the ``simon mem`` reconciliation contract)."""
    leaves = entry_host_leaves(entry)
    fields: Dict[str, dict] = {}
    dtypes = {k: 0 for k in _POLICY_DTYPES + ("other",)}
    total = unique = 0
    off_policy: List[str] = []
    for name, arr in leaves:
        nbytes = int(arr.nbytes)
        total += nbytes
        cls = _dtype_class(arr.dtype)
        dtypes[cls] += nbytes
        if cls == "other":
            off_policy.append(name)
        if seen_ids is not None:
            if id(arr) not in seen_ids:
                seen_ids.add(id(arr))
                unique += nbytes
        else:
            unique += nbytes
        fields[name] = {
            "bytes": nbytes,
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        }
    prep = entry.prep
    drop = entry.base_drop
    out = {
        "key": entry.key,
        "bytes": total,
        "unique_bytes": unique,
        "lineage_depth": _lineage_depth(entry),
        "pods": len(prep.ordered) if prep is not None else 0,
        "drop_density": (
            round(float(drop.sum()) / max(1, len(drop)), 6) if drop is not None else 0.0
        ),
        "dtypes": {k: v for k, v in dtypes.items() if v},
        "fields": fields,
    }
    if off_policy:
        out["off_policy_fields"] = sorted(off_policy)
    return out


def prepcache_footprint(cache, include_fields: bool = False) -> dict:
    """The whole cache's memory view: entries newest-LRU-last, per-dtype
    totals over DISTINCT leaves, and the cache stats (hits/misses/
    evictions/invalidations plus the twin-delta compaction counter)."""
    from ..engine import prepcache as prepcache_mod

    out: dict = {
        "entries": [],
        "total_bytes": 0,
        "shared_bytes": 0,
        "dtypes": {},
        "stats": {},
        "compactions": prepcache_mod.compactions_total(),
    }
    if cache is None:
        return out
    entries = cache.entries_snapshot()
    out["stats"] = cache.stats.as_dict()
    seen: set = set()
    uniq_dtypes: Dict[str, int] = {}
    walked = []
    for entry in entries:
        # per-entry accounting under the entry lock (a concurrent twin
        # flush swaps base_drop/prep under it) — but BOUNDED: the entry
        # lock deliberately spans multi-second derive/encode work, and a
        # scrape must not stall behind an engine run. A busy entry is
        # reported as such and skipped; totals stay internally consistent
        # (they cover exactly the walked entries).
        if not entry.lock.acquire(timeout=0.5):
            # zero-valued stub: consumers of the total==Σ unique_bytes
            # contract (mem-smoke, simon mem) must not KeyError or skew
            # when an engine run holds the entry mid-walk
            out["entries"].append(
                {
                    "key": entry.key, "busy": True, "bytes": 0,
                    "unique_bytes": 0, "lineage_depth": 0, "pods": 0,
                    "drop_density": 0.0, "dtypes": {},
                }
            )
            continue
        try:
            fp = entry_footprint(entry, seen_ids=seen)
            walked.append((entry, fp))
            # dtype totals over DISTINCT leaves, folded in the same walk
            for _name, arr in entry_host_leaves(entry):
                mark = ("dt", id(arr))
                if mark in seen:
                    continue
                seen.add(mark)
                cls = _dtype_class(arr.dtype)
                uniq_dtypes[cls] = uniq_dtypes.get(cls, 0) + int(arr.nbytes)
        finally:
            entry.lock.release()
        out["total_bytes"] += fp["unique_bytes"]
        if not include_fields:
            fp = dict(fp)
            fp.pop("fields", None)
        out["entries"].append(fp)
    out["dtypes"] = uniq_dtypes
    out["shared_bytes"] = (
        sum(fp["bytes"] for _e, fp in walked) - out["total_bytes"]
    )
    return out


# ---------------------------------------------------------------------------
# the observatory (server wiring + /metrics renderer)
# ---------------------------------------------------------------------------


def mem_ticker_s() -> float:
    """``OPENSIM_MEM_TICKER_S`` (default 10, 0 disables): the watermark
    sampling cadence. A typo degrades to the default with a warning."""
    return float(envknobs.value("OPENSIM_MEM_TICKER_S"))


class MemoryObservatory:
    """The server's memory view: holds references to the structures it
    accounts (prep cache, rings, journal), keeps RSS/device watermarks
    fresh on a low-rate ticker, and renders the ``simon_mem_*`` families.

    All derived numbers are computed on demand (a scrape walks the cache's
    numpy headers — O(entries × fields) pointer work, no array reads);
    only the watermark peaks are stateful."""

    def __init__(self, prep_cache=None, timeline=None, journal=None, recorder=None) -> None:
        from .recorder import FLIGHT_RECORDER

        self.prep_cache = prep_cache
        self.timeline = timeline
        self.journal = journal
        self.recorder = recorder if recorder is not None else FLIGHT_RECORDER
        self._lock = threading.Lock()
        self._peak_rss = 0  # guarded-by: _lock
        self._last_process: Dict[str, int] = {}  # guarded-by: _lock
        self._device_peaks: Dict[str, int] = {}  # guarded-by: _lock
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- sampling ------------------------------------------------------------

    def sample_process(self) -> Dict[str, int]:
        """One watermark sample (ticker tick, scrape, or debug read)."""
        proc, _devices = self._sample()
        return proc

    def _sample(self) -> Tuple[Dict[str, int], Dict[str, Dict[str, int]]]:
        """One combined process + device sample with the watermarks folded
        in — the ONE backend enumeration per read (device_memory can be
        slow/hang-prone on a dead accelerator tunnel, so scrapes must not
        pay it twice). The /proc and device reads happen OUTSIDE the lock
        (no blocking I/O under a lock, OSL1203)."""
        proc = process_memory()
        devices = device_memory()
        with self._lock:
            self._peak_rss = max(self._peak_rss, proc["rss_peak_bytes"])
            proc["rss_peak_bytes"] = self._peak_rss
            self._last_process = proc
            for dev, stats in devices.items():
                self._device_peaks[dev] = max(
                    self._device_peaks.get(dev, 0), stats["peak"]
                )
                stats["peak"] = self._device_peaks[dev]
            for dev, peak in self._device_peaks.items():
                # a device that reported nothing this sample (backend blip)
                # keeps its remembered watermark visible
                devices.setdefault(dev, {"in_use": 0, "peak": peak})
        return proc, devices

    def start_ticker(self) -> None:
        """Start the low-rate watermark sampler (idempotent; no-op when
        ``OPENSIM_MEM_TICKER_S`` is 0)."""
        interval = mem_ticker_s()
        if interval <= 0 or self._ticker is not None:
            return

        def loop() -> None:
            # the first sample runs ON the ticker thread, not inline at
            # startup: device enumeration can hang on a dead accelerator
            # tunnel, and serve() must reach its listener regardless
            self.sample_process()
            while not self._stop.wait(interval):
                self.sample_process()

        self._ticker = threading.Thread(
            target=loop, name="simon-mem-ticker", daemon=True
        )
        self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._ticker = self._ticker, None
        if t is not None:
            t.join(timeout=2.0)

    # -- views ---------------------------------------------------------------

    def ring_occupancy(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {
            "flight_recorder": {
                "entries": len(self.recorder),
                "capacity": int(self.recorder.capacity),
            }
        }
        if self.timeline is not None:
            out["capacity_timeline"] = {
                "entries": len(self.timeline),
                "capacity": int(self.timeline.capacity),
            }
        if self.journal is not None:
            depth, bound = self.journal.queue_occupancy()
            out["journal_queue"] = {"entries": depth, "capacity": bound}
        return out

    def debug_payload(self, include_fields: bool = True) -> dict:
        """The ``GET /api/debug/memory`` body (also what ``simon mem``
        renders): process + device watermarks, the full prep-cache arena
        attribution, and ring occupancy."""
        proc, devices = self._sample()
        return {
            "generated_unix": round(time.time(), 3),
            "process": proc,
            "devices": devices,
            "prepcache": prepcache_footprint(self.prep_cache, include_fields=include_fields),
            "rings": self.ring_occupancy(),
        }

    def summary(self) -> dict:
        """The compact block ``/api/cluster/report?mem=1`` embeds (and
        ``simon top --mem`` renders via :func:`memory_rows`)."""
        proc = self.sample_process()
        cache = prepcache_footprint(self.prep_cache)
        return {
            "rss_bytes": proc["rss_bytes"],
            "rss_peak_bytes": proc["rss_peak_bytes"],
            "prepcache_bytes": cache["total_bytes"],
            "prepcache_entries": len(cache["entries"]),
            "rings": self.ring_occupancy(),
        }

    # -- /metrics ------------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        esc = escape_label_value
        proc, devices = self._sample()
        cache = prepcache_footprint(self.prep_cache)
        rings = self.ring_occupancy()
        lines: List[str] = [
            *family_header("simon_mem_rss_bytes"),
            f"simon_mem_rss_bytes {proc['rss_bytes']}",
            *family_header("simon_mem_rss_peak_bytes"),
            f"simon_mem_rss_peak_bytes {proc['rss_peak_bytes']}",
            *family_header("simon_mem_prepcache_bytes"),
            f"simon_mem_prepcache_bytes {cache['total_bytes']}",
            *family_header("simon_mem_prepcache_entries"),
            f"simon_mem_prepcache_entries {len(cache['entries'])}",
            *family_header("simon_mem_prepcache_evictions_total"),
            f"simon_mem_prepcache_evictions_total {cache['stats'].get('evictions', 0)}",
            *family_header("simon_mem_prepcache_compactions_total"),
            f"simon_mem_prepcache_compactions_total {cache['compactions']}",
        ]
        if cache["dtypes"]:
            lines += family_header("simon_mem_arena_bytes")
            lines += [
                f'simon_mem_arena_bytes{{dtype="{esc(cls)}"}} {nbytes}'
                for cls, nbytes in sorted(cache["dtypes"].items())
            ]
        lines += family_header("simon_mem_ring_entries")
        lines += [
            f'simon_mem_ring_entries{{ring="{esc(ring)}"}} {occ["entries"]}'
            for ring, occ in sorted(rings.items())
        ]
        lines += family_header("simon_mem_ring_capacity")
        lines += [
            f'simon_mem_ring_capacity{{ring="{esc(ring)}"}} {occ["capacity"]}'
            for ring, occ in sorted(rings.items())
        ]
        if devices:
            # _sample() already folded the remembered per-device watermarks in
            lines += family_header("simon_mem_device_bytes")
            for dev, stats in sorted(devices.items()):
                lines += [
                    f'simon_mem_device_bytes{{device="{esc(dev)}",kind="in_use"}} {stats["in_use"]}',
                    f'simon_mem_device_bytes{{device="{esc(dev)}",kind="peak"}} {stats["peak"]}',
                ]
        return lines


# ---------------------------------------------------------------------------
# shared rows builder (simon top --mem / report parity)
# ---------------------------------------------------------------------------


def fmt_bytes(n: int) -> str:
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if value < 1024.0 or unit == "TiB":
            return f"{value:.1f}{unit}" if unit != "B" else f"{int(value)}B"
        value /= 1024.0
    return f"{int(n)}B"


def memory_rows(summary: dict) -> List[List[str]]:
    """The memory table rows — ONE builder serving both the
    ``/api/cluster/report?mem=1`` JSON and the ``simon top --mem`` text
    renderer, so the two stay byte-equal (the report-parity contract)."""
    rows = [["Memory", "Value"]]
    rows.append(["process RSS", fmt_bytes(int(summary.get("rss_bytes", 0)))])
    rows.append(["process RSS peak", fmt_bytes(int(summary.get("rss_peak_bytes", 0)))])
    rows.append(
        [
            "prep cache",
            f"{fmt_bytes(int(summary.get('prepcache_bytes', 0)))} "
            f"in {int(summary.get('prepcache_entries', 0))} entr"
            + ("y" if int(summary.get("prepcache_entries", 0)) == 1 else "ies"),
        ]
    )
    for ring, occ in sorted((summary.get("rings") or {}).items()):
        rows.append(
            [f"ring {ring}", f"{occ.get('entries', 0)}/{occ.get('capacity', 0)}"]
        )
    return rows
