"""Declarative SLOs evaluated as multi-window burn rates over the
time-series ring (ISSUE 20, docs/observability.md "Watching the fleet").

An objective is ``name:target_pct[:threshold_s]`` (the ``OPENSIM_SLO``
knob; comma-separated). Three objective kinds are built in:

- ``availability`` — the good fraction of requests, from
  ``simon_request_seconds_count{status=}`` (good = ``status="ok"``);
- ``latency_p99`` — requests completing under ``threshold_s``, from the
  ``simon_request_seconds`` bucket ladder (the threshold must sit on a
  bucket bound to be measurable; the evaluator uses the smallest bound
  ≥ threshold and says which it used);
- ``freshness`` — watch events reaching the ``served`` stage of the
  fleet pipeline under ``threshold_s``, from
  ``simon_fleet_freshness_seconds`` (``obs/fleetobs.py``).

Each objective is evaluated over every window in ``OPENSIM_SLO_WINDOWS``
(multi-window burn-rate alerting, the Prometheus/SRE-workbook shape):

    burn_rate = (bad / total) / (1 - target)

1.0 means the error budget burns exactly at the sustainable rate; a
classic page is "burn > 14.4 on the short window AND > 6 on the long
one". The engine computes the rates; paging policy belongs to the
operator. Burn rates surface at ``GET /api/fleet/slo``, in ``simon
dash``, and as ``simon_slo_burn_rate{slo=,window=}``.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Dict, List, Optional, Tuple

from .metrics import MetricKey, escape_label_value, family_header
from ..utils import envknobs

log = logging.getLogger("opensim_tpu.slo")

__all__ = [
    "Objective",
    "SLOEngine",
    "parse_objectives",
    "parse_windows",
]

_KINDS = ("availability", "latency_p99", "freshness")


class Objective:
    """One declarative objective: ``kind``, ``target_pct`` (e.g. 99.9),
    optional ``threshold_s`` (latency/freshness kinds)."""

    def __init__(self, kind: str, target_pct: float,
                 threshold_s: Optional[float] = None) -> None:
        if kind not in _KINDS:
            raise ValueError(
                f"unknown SLO kind {kind!r} (known: {', '.join(_KINDS)})"
            )
        if not 0.0 < target_pct < 100.0:
            raise ValueError(f"SLO target must be in (0, 100), got {target_pct!r}")
        if kind in ("latency_p99", "freshness") and not threshold_s:
            raise ValueError(f"SLO {kind!r} needs a threshold: {kind}:<pct>:<seconds>")
        self.kind = kind
        self.target_pct = target_pct
        self.threshold_s = threshold_s

    @property
    def budget(self) -> float:
        """The error budget as a fraction (99.9% → 0.001)."""
        return 1.0 - self.target_pct / 100.0

    def to_dict(self) -> dict:
        return {
            "name": self.kind,
            "target_pct": self.target_pct,
            "threshold_s": self.threshold_s,
            "budget": round(self.budget, 9),
        }


def parse_objectives(spec: Optional[str] = None) -> List[Objective]:
    """``OPENSIM_SLO`` → objectives. Malformed entries fail loudly — a
    silently dropped objective is an SLO that never pages."""
    spec = spec if spec is not None else str(envknobs.value("OPENSIM_SLO"))
    out: List[Objective] = []
    for part in (p.strip() for p in spec.split(",")):
        if not part:
            continue
        bits = part.split(":")
        if len(bits) not in (2, 3):
            raise ValueError(
                f"bad SLO entry {part!r}: want name:target_pct[:threshold_s]"
            )
        threshold = float(bits[2]) if len(bits) == 3 else None
        out.append(Objective(bits[0], float(bits[1]), threshold))
    return out


def parse_windows(spec: Optional[str] = None) -> List[Tuple[str, float]]:
    """``OPENSIM_SLO_WINDOWS`` (e.g. ``5m,1h``) → ``[(label, seconds)]``."""
    spec = spec if spec is not None else str(envknobs.value("OPENSIM_SLO_WINDOWS"))
    units = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
    out: List[Tuple[str, float]] = []
    for part in (p.strip() for p in spec.split(",")):
        if not part:
            continue
        if part[-1] not in units:
            raise ValueError(f"bad SLO window {part!r}: want <number><s|m|h|d>")
        out.append((part, float(part[:-1]) * units[part[-1]]))
    if not out:
        raise ValueError("OPENSIM_SLO_WINDOWS resolved to no windows")
    return out


def _cum_below(series: Dict[MetricKey, float], family: str,
               threshold: float) -> Tuple[float, float, Optional[float]]:
    """(cumulative count ≤ bound, total count, bound used) for one
    histogram family at one sample, summing across series (shared bucket
    ladder). The bound is the smallest ``le`` ≥ threshold."""
    buckets: Dict[float, float] = {}
    total = 0.0
    for (name, labels), v in series.items():
        if name == f"{family}_count":
            total += v
        elif name == f"{family}_bucket":
            ld = dict(labels)
            le = math.inf if ld.get("le") == "+Inf" else float(ld.get("le", "inf"))
            buckets[le] = buckets.get(le, 0.0) + v
    bound = None
    for le in sorted(buckets):
        if le >= threshold:
            bound = le
            break
    if bound is None:
        return 0.0, total, None
    return buckets[bound], total, bound


class SLOEngine:
    """Evaluates objectives over a :class:`TimeSeriesRing`. Stateless
    between calls — every evaluation re-reads the ring, so a takeover's
    adopted ring (or an empty one) needs no migration."""

    #: ring families the evaluator needs (dash prefetches the same set)
    FAMILIES_NEEDED = ("simon_request_seconds", "simon_fleet_freshness_seconds")

    def __init__(self, ring, objectives: Optional[List[Objective]] = None,
                 windows: Optional[List[Tuple[str, float]]] = None) -> None:
        self.ring = ring
        self.objectives = objectives if objectives is not None else parse_objectives()
        self.windows = windows if windows is not None else parse_windows()

    # -- counting ------------------------------------------------------------

    def _bad_total(self, obj: Objective,
                   first: Dict[MetricKey, float],
                   last: Dict[MetricKey, float]) -> Tuple[float, float, dict]:
        """(bad, total, detail) over the window delta ``first → last``.
        Counter resets surface as a larger-than-life delta at worst for
        one window span; the ring is append-only so this is rare and
        self-heals."""
        detail: dict = {}
        if obj.kind == "availability":
            total = bad = 0.0
            for (name, labels), v in last.items():
                if name != "simon_request_seconds_count":
                    continue
                d = max(0.0, v - first.get((name, labels), 0.0))
                total += d
                if dict(labels).get("status") != "ok":
                    bad += d
            return bad, total, detail
        family = (
            "simon_request_seconds" if obj.kind == "latency_p99"
            else "simon_fleet_freshness_seconds"
        )
        good1, total1, bound = _cum_below(last, family, obj.threshold_s or 0.0)
        good0, total0, _ = _cum_below(first, family, obj.threshold_s or 0.0)
        total = max(0.0, total1 - total0)
        good = max(0.0, good1 - good0)
        detail["bucket_bound_s"] = bound
        return max(0.0, total - good), total, detail

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> dict:
        now = now or time.time()
        longest = max(s for _, s in self.windows)
        samples = self.ring.query_parsed(
            family=",".join(self.FAMILIES_NEEDED), range_s=longest, now=now
        )
        out = {"generated_unix": round(now, 3), "objectives": []}
        for obj in self.objectives:
            row = obj.to_dict()
            row["windows"] = {}
            for label, seconds in self.windows:
                in_win = [s for s in samples if s[0] >= now - seconds]
                if len(in_win) < 2:
                    row["windows"][label] = {
                        "burn_rate": 0.0, "bad": 0.0, "total": 0.0,
                        "samples": len(in_win), "no_data": True,
                    }
                    continue
                bad, total, detail = self._bad_total(obj, in_win[0][1], in_win[-1][1])
                burn = (bad / total) / obj.budget if total > 0 else 0.0
                win = {
                    "burn_rate": round(burn, 6),
                    "bad": bad,
                    "total": total,
                    "samples": len(in_win),
                    "span_s": round(in_win[-1][0] - in_win[0][0], 3),
                }
                win.update(detail)
                row["windows"][label] = win
            out["objectives"].append(row)
        return out

    def metrics_lines(self, now: Optional[float] = None) -> List[str]:
        """``simon_slo_burn_rate{slo=,window=}`` gauge lines. The gauge is
        recomputed per scrape from the ring (recording-rule style), not
        accumulated, so it needs no lock beyond the ring's own."""
        try:
            payload = self.evaluate(now=now)
        except Exception as e:  # a torn ring file mid-read
            log.warning("SLO evaluation failed: %s: %s", type(e).__name__, e)
            return family_header("simon_slo_burn_rate")
        lines = family_header("simon_slo_burn_rate")
        for row in payload["objectives"]:
            for label, win in sorted(row["windows"].items()):
                lines.append(
                    "simon_slo_burn_rate{"
                    f'slo="{escape_label_value(row["name"])}",'
                    f'window="{escape_label_value(label)}"'
                    f"}} {win['burn_rate']:.6g}"
                )
        return lines
