"""Capacity timeline — a fixed-size ring of per-generation samples.

The capacity engine (``obs/capacity.py``) derives one :class:`Sample` per
observed twin generation; this module keeps the last N of them so
``GET /api/debug/capacity`` can serve a trend window (utilization climbing,
headroom draining, fragmentation building) without a time-series database
in the loop. The ring is generation-keyed: a generation is sampled at most
once, so an idle cluster does not flood the ring with identical rows, and a
busy one is naturally downsampled to the supervisor's tick cadence (samples
are taken when someone looks — the maintenance loop, a scrape, a report —
never per event).

Bounded like the flight recorder (``obs/recorder.py``):
``OPENSIM_CAPACITY_TIMELINE_N`` caps retained samples (default 512).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..utils import envknobs

log = logging.getLogger("opensim_tpu.obs")

__all__ = ["Sample", "Timeline", "timeline_capacity"]


def timeline_capacity() -> int:
    """``OPENSIM_CAPACITY_TIMELINE_N`` (default 512). A typo degrades to
    the default with a warning — same contract as
    ``OPENSIM_FLIGHT_RECORDER_N``, never a startup crash."""
    raw = envknobs.raw("OPENSIM_CAPACITY_TIMELINE_N")
    try:
        return max(1, int(raw)) if raw else 512
    except ValueError:
        log.warning("ignoring unparseable OPENSIM_CAPACITY_TIMELINE_N=%r (using 512)", raw)
        return 512


@dataclass
class Sample:
    """One generation's derived capacity view (all floats are ratios in
    [0, 1+] unless named otherwise). ``utilization``/``spread``/
    ``fragmentation`` are keyed by resource name (cpu/memory/pods);
    ``headroom`` by registered profile name (absent until first probed);
    ``hottest`` is the top-K ``(node, {resource: util})`` list."""

    generation: int
    ts: float = field(default_factory=time.time)
    nodes: int = 0
    pods_bound: int = 0
    pods_pending: int = 0
    allocatable: Dict[str, float] = field(default_factory=dict)
    requested: Dict[str, float] = field(default_factory=dict)
    utilization: Dict[str, float] = field(default_factory=dict)
    spread: Dict[str, float] = field(default_factory=dict)
    fragmentation: Dict[str, float] = field(default_factory=dict)
    headroom: Dict[str, int] = field(default_factory=dict)
    hottest: List[Tuple[str, Dict[str, float]]] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "ts": round(self.ts, 3),
            "nodes": self.nodes,
            "pods_bound": self.pods_bound,
            "pods_pending": self.pods_pending,
            "allocatable": {k: round(v, 6) for k, v in sorted(self.allocatable.items())},
            "requested": {k: round(v, 6) for k, v in sorted(self.requested.items())},
            "utilization": {k: round(v, 6) for k, v in sorted(self.utilization.items())},
            "spread": {k: round(v, 6) for k, v in sorted(self.spread.items())},
            "fragmentation": {k: round(v, 6) for k, v in sorted(self.fragmentation.items())},
            "headroom": dict(sorted(self.headroom.items())),
            "hottest": [
                {"node": n, "utilization": {k: round(v, 6) for k, v in sorted(u.items())}}
                for n, u in self.hottest
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Sample":
        """Inverse of :meth:`to_dict` (journal checkpoint restore,
        ``server/journal.py``). Unknown/malformed fields degrade to the
        dataclass defaults — a checkpoint written by an older build must
        still restore the samples it does carry."""
        s = cls(generation=int(d.get("generation") or 0))
        s.ts = float(d.get("ts") or 0.0)
        s.nodes = int(d.get("nodes") or 0)
        s.pods_bound = int(d.get("pods_bound") or 0)
        s.pods_pending = int(d.get("pods_pending") or 0)
        for attr in ("allocatable", "requested", "utilization", "spread", "fragmentation"):
            val = d.get(attr)
            if isinstance(val, dict):
                setattr(s, attr, {str(k): float(v) for k, v in val.items()})
        if isinstance(d.get("headroom"), dict):
            s.headroom = {str(k): int(v) for k, v in d["headroom"].items()}
        if isinstance(d.get("hottest"), list):
            s.hottest = [
                (str(h.get("node") or ""), {str(k): float(v) for k, v in (h.get("utilization") or {}).items()})
                for h in d["hottest"]
                if isinstance(h, dict)
            ]
        return s


class Timeline:
    """The bounded, generation-keyed sample ring. Appends under its own
    lock (samples arrive from the supervisor tick AND request threads); a
    repeat generation replaces the newest entry in place rather than
    appending (headroom probes enrich an existing generation's sample)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = timeline_capacity() if capacity is None else max(1, capacity)
        self._lock = threading.Lock()
        self._ring: "collections.deque[Sample]" = collections.deque(maxlen=self.capacity)  # guarded-by: _lock

    def append(self, sample: Sample) -> None:
        with self._lock:
            if self._ring and self._ring[-1].generation == sample.generation:
                self._ring[-1] = sample
                return
            self._ring.append(sample)

    def latest(self) -> Optional[Sample]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def snapshot(self) -> List[Sample]:
        """Oldest-first copy (the debug endpoint serializes it)."""
        with self._lock:
            return list(self._ring)

    def restore(self, samples: List[Sample]) -> None:
        """Seed the ring from a journal checkpoint (oldest first) — only
        samples strictly newer than the current tail append, so a restore
        can never rewind a ring that already has fresher generations."""
        with self._lock:
            for s in samples:
                if self._ring and s.generation <= self._ring[-1].generation:
                    continue
                self._ring.append(s)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
