"""In-process flight recorder: the last N request traces (ISSUE 5).

A bounded ring buffer of finished :class:`~opensim_tpu.obs.trace.TraceContext`
objects, always on while tracing is enabled, served by the REST layer at

- ``GET /api/debug/requests``        — newest-first summary list
- ``GET /api/debug/requests/<id>``   — one request's full span tree

so "why was that request slow / demoted / 504ed?" is answerable from the
live server minutes after the fact, with no prior setup. Capacity comes
from ``OPENSIM_FLIGHT_RECORDER_N`` (default 64); traces are recorded only
after ``finish()``, so everything the endpoints read is immutable.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Dict, List, Optional

from ..utils import envknobs

__all__ = ["FlightRecorder", "FLIGHT_RECORDER"]


def _default_capacity() -> int:
    # the module-level singleton is constructed at import time, and obs is
    # imported from simulate()'s hot path: a typo'd debug knob must degrade
    # to the default with a warning, never take down CLI/library use
    raw = envknobs.raw("OPENSIM_FLIGHT_RECORDER_N")
    try:
        return max(1, int(raw)) if raw else 64
    except ValueError:
        logging.getLogger("opensim_tpu.obs").warning(
            "ignoring unparseable OPENSIM_FLIGHT_RECORDER_N=%r (using 64)", raw
        )
        return 64


class FlightRecorder:
    """Thread-safe bounded ring of finished traces, indexed by request id
    (a client that reuses an id sees its most recent trace)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = capacity if capacity is not None else _default_capacity()
        self._lock = threading.Lock()
        self._ring: deque = deque()  # guarded-by: _lock
        self._by_id: Dict[str, object] = {}  # guarded-by: _lock

    def record(self, trace) -> None:
        if not trace.finished:
            raise ValueError("only finished traces are recordable (call finish() first)")
        # the cumulative phase profiles (ISSUE 12, obs/profile.py) fold in
        # every recorded trace — ONE sink for the ring and the aggregates,
        # outside this ring's lock (PROFILE locks itself)
        from .profile import PROFILE

        PROFILE.observe_trace(trace)
        with self._lock:
            self._ring.append(trace)
            self._by_id[trace.request_id] = trace
            while len(self._ring) > self.capacity:
                old = self._ring.popleft()
                if self._by_id.get(old.request_id) is old:
                    del self._by_id[old.request_id]

    def get(self, request_id: str):
        with self._lock:
            return self._by_id.get(request_id)

    def summaries(self) -> List[dict]:
        with self._lock:
            traces = list(self._ring)
        return [t.summary() for t in reversed(traces)]

    def latest(self):
        with self._lock:
            return self._ring[-1] if self._ring else None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_id.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


FLIGHT_RECORDER = FlightRecorder()
