"""Latency histograms + Prometheus text-format hardening (ISSUE 5).

The serving path used to export only hand-maintained ``*_seconds_total``
counters — totals hide tail behavior entirely. This module adds fixed-bucket
latency *histograms* computed from the same spans the tracer records
(``simon_phase_seconds_bucket{phase=,endpoint=}`` and
``simon_request_seconds_bucket{endpoint=}``), rendered in the Prometheus
exposition format at ``/metrics``.

It also owns the ONE recording lock for the whole metrics surface: the REST
layer's ``_Metrics`` counters, these histograms, and the span sink all
record under :data:`RECORDER`'s RLock, closing the cross-thread bump races
the old per-object locking left open (counters were bumped both from
``_handle`` and from snapshot-retry callbacks).

Label values are escaped per the exposition format (``\\`` → ``\\\\``,
``"`` → ``\\"``, newline → ``\\n``) — a hostile endpoint/path string cannot
corrupt a scrape.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_BUCKETS",
    "FAMILIES",
    "CounterVec",
    "HistogramVec",
    "MetricKey",
    "MetricsRecorder",
    "RECORDER",
    "bucket_deltas",
    "counter_delta",
    "escape_label_value",
    "family_header",
    "histogram_quantile",
    "make_counter",
    "make_histogram",
    "parse_metrics",
    "scrape_metrics",
]

# fixed bucket upper bounds in seconds (the +Inf bucket is implicit):
# sub-ms cache hits through multi-second cold 50k-pod plans
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

#: batch sizes are small integers; the latency bucket ladder would waste
#: every bucket past 32 — count buckets instead (server/admission.py)
BATCH_SIZE_BUCKETS: Tuple[float, ...] = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0)

#: watch-event application is µs-scale dict surgery; the request bucket
#: ladder would collapse the whole distribution into its first bucket
WATCH_APPLY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.5,
)

#: per-node utilization is a ratio in [0, 1+] (requests can legitimately
#: exceed allocatable on over-committed nodes) — capacity-shaped buckets
UTILIZATION_BUCKETS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 0.625, 0.75, 0.875, 0.95, 1.0,
)

#: event-to-servable freshness (obs/fleetobs.py): the publish loop alone
#: adds up to OPENSIM_FLEET_PUBLISH_MS, so the ladder starts at ms scale
#: and reaches the minutes a wedged worker would show
FRESHNESS_BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0,
)

#: THE metric-family registry: ``name -> (help, type)`` for every family
#: the process can render. Family registration — names, help text, types,
#: and therefore cardinality governance — lives HERE and nowhere else
#: (opensim-lint OSL1101 bans ``CounterVec``/``HistogramVec`` construction
#: and ``exposition_headers`` calls outside this module); other modules
#: render their series through :func:`family_header` /
#: :func:`make_counter` / :func:`make_histogram`.
FAMILIES: Dict[str, Tuple[str, str]] = {
    # serving counters (server/rest.py)
    "simon_requests_total": ("Requests served by endpoint", "counter"),
    "simon_simulations_total": ("Successful simulations", "counter"),
    "simon_pods_scheduled_total": ("Pods placed across all simulations", "counter"),
    "simon_pods_unscheduled_total": ("Pods left unschedulable", "counter"),
    "simon_simulate_seconds_total": ("Wall seconds in successful simulations", "counter"),
    "simon_prepare_seconds_total": ("Host-side expand+encode seconds", "counter"),
    "simon_prep_cache_hits_total": ("Encode-cache hits", "counter"),
    "simon_prep_cache_misses_total": ("Encode-cache misses", "counter"),
    "simon_prep_cache_invalidations_total": ("Encode-cache invalidations", "counter"),
    # resilience (docs/resilience.md)
    "simon_request_timeouts_total": ("Requests 504ed at a deadline boundary", "counter"),
    "simon_snapshot_fetch_retries_total": ("Snapshot fetch retry attempts", "counter"),
    "simon_snapshot_stale_served_total": ("Requests served from a stale snapshot", "counter"),
    "simon_stale_prep_retries_total": ("Stale prep-cache internal retries", "counter"),
    "simon_native_steps_total": ("C++ engine scheduled steps by evaluation path", "counter"),
    # cardinality contract: reason ∈ nativepath._BAIL_REASONS (11 values)
    "simon_native_bail_total": ("Incremental-carry envelope bails by gate/flip reason", "counter"),
    "simon_engine_breaker_trips_total": ("Engine circuit-breaker trips", "counter"),
    "simon_engine_breaker_open": ("Engine breaker open (1) or closed (0)", "gauge"),
    "simon_faults_injected_total": ("Chaos faults injected by point", "counter"),
    # live twin (server/watch.py, docs/live-twin.md)
    "simon_watch_state": ("Live-twin state machine (one-hot)", "gauge"),
    "simon_watch_events_total": ("Watch events consumed by kind and resource", "counter"),
    "simon_watch_reconnects_total": ("Watch stream reconnect attempts", "counter"),
    "simon_watch_relists_total": ("Full relists (bootstrap/410/anti-entropy)", "counter"),
    "simon_watch_gone_total": ("410 Gone resourceVersion expiries", "counter"),
    "simon_twin_drift_total": ("Drifted objects repaired, by resource", "counter"),
    "simon_twin_resyncs_total": ("Anti-entropy passes that found drift", "counter"),
    "simon_twin_generation": ("Live-twin generation (bumps on every applied event)", "gauge"),
    "simon_watch_apply_seconds": ("Watch-pipeline latency: event receipt to twin applied", "histogram"),
    # admission / batching (server/admission.py, docs/serving.md)
    "simon_admission_queue_depth": ("Requests waiting in the admission queue", "gauge"),
    "simon_batches_total": ("Batched schedule dispatches", "counter"),
    "simon_shed_total": ("Requests shed at the admission queue by reason", "counter"),
    "simon_batch_size": ("Requests folded into one batched schedule dispatch", "histogram"),
    "simon_queue_wait_seconds": ("Real time-in-queue from admission to execution start", "histogram"),
    # pipelined admission + priority lanes (server/admission.py,
    # docs/serving.md "Continuous batching & priority lanes") —
    # cardinality contract: stage ∈ {prep, dispatch, decode};
    # lane ∈ {interactive, bulk}; reason reuses the typed shed reasons
    "simon_pipeline_stage_seconds": ("Per-batch pipeline stage latency by stage (prep/dispatch/decode)", "histogram"),
    "simon_pipeline_prep_overlap_seconds_total": (
        "Engine-dispatch-busy seconds observed while a later batch's host prep ran (the measured overlap)", "counter",
    ),
    "simon_pipeline_overlapped_batches_total": (
        "Batches whose host prep overlapped another batch's engine dispatch", "counter",
    ),
    "simon_lane_depth": ("Admission queue depth by priority lane", "gauge"),
    "simon_lane_admitted_total": ("Requests admitted by priority lane", "counter"),
    "simon_lane_shed_total": ("Requests shed at the admission queue by lane and reason", "counter"),
    "simon_lane_starvation_promotions_total": (
        "Bulk requests promoted past the lane weight by the starvation bound", "counter",
    ),
    # multi-process serving fleet (server/fleet.py, docs/serving.md
    # "Scaling past one process") — owner-side families are label-free;
    # worker-side attach counters are label-free too
    "simon_fleet_workers": ("Fleet worker processes currently alive", "gauge"),
    "simon_fleet_workers_target": ("Fleet worker processes configured", "gauge"),
    "simon_fleet_respawns_total": ("Fleet worker respawns after a crash", "counter"),
    "simon_fleet_publishes_total": ("Twin publications over shared memory", "counter"),
    "simon_fleet_generation": ("Last twin generation published over shared memory", "gauge"),
    "simon_fleet_shm_segments": ("Live shared-memory segments the publisher owns", "gauge"),
    "simon_fleet_shm_bytes": ("Bytes across live shared-memory segments", "gauge"),
    "simon_fleet_publish_seconds": ("Twin publication latency (delta segments + control swap)", "histogram"),
    "simon_fleet_attaches_total": ("Worker attaches to a published generation", "counter"),
    "simon_fleet_attach_retries_total": ("Seqlock retries during worker attach (torn reads)", "counter"),
    "simon_fleet_attach_retries_exhausted_total": (
        "Worker attaches abandoned after exhausting seqlock retries", "counter",
    ),
    "simon_fleet_attach_generation": ("Twin generation this worker last attached", "gauge"),
    "simon_fleet_segment_reuse_total": ("Segments reused across generations at attach (content-keyed delta hits)", "counter"),
    # HA control plane (server/fleet.py, docs/serving.md "Surviving owner
    # loss & rolling upgrades") — reason ∈ {expired, handover}
    "simon_fleet_takeovers_total": ("Standby-to-owner takeovers by reason (expired/handover)", "counter"),
    "simon_fleet_standby_tail_lag_records": ("Journal records the standby drained at its last tail poll (how far it had fallen behind)", "gauge"),
    "simon_fleet_lease_age_seconds": ("Seconds since the HA lease was last renewed", "gauge"),
    "simon_fleet_fenced_writes_total": ("Publishes refused because the lease epoch moved (a deposed owner fenced out)", "counter"),
    # latency + decision audit (this module's RECORDER)
    "simon_phase_seconds": ("Per-phase latency from the request span trees", "histogram"),
    "simon_request_seconds": ("Whole-request latency by endpoint and outcome", "histogram"),
    "simon_filter_reject_total": (
        "Nodes rejected per filter plugin while attributing unschedulable pods", "counter",
    ),
    "simon_unschedulable_total": ("Unschedulable pods by primary (most-rejecting) reason code", "counter"),
    # capacity observatory (obs/capacity.py, docs/observability.md) —
    # cardinality contract: every family below is label-free or bounded
    # (resource ∈ {cpu, memory, pods}; profile = registered headroom
    # profiles; node series are capped at the top-K hottest nodes)
    "simon_cluster_utilization": ("Per-node utilization distribution by resource", "histogram"),
    "simon_cluster_node_utilization": (
        "Top-K hottest node utilization by resource (cardinality-capped)", "gauge",
    ),
    "simon_cluster_utilization_ratio": ("Aggregate requested/allocatable by resource", "gauge"),
    "simon_cluster_allocatable": ("Cluster-wide allocatable by resource", "gauge"),
    "simon_cluster_requested": ("Cluster-wide requests of counted pods by resource", "gauge"),
    "simon_cluster_spread": ("Allocation spread: stddev/mean of per-node utilization", "gauge"),
    "simon_cluster_fragmentation": (
        "Free-capacity fragmentation: 1 - largest free node / total free", "gauge",
    ),
    "simon_cluster_headroom": (
        "Max additional replicas of a registered workload profile that still fit", "gauge",
    ),
    "simon_cluster_nodes": ("Nodes in the observed cluster", "gauge"),
    "simon_cluster_pods_bound": ("Counted pods bound to a node", "gauge"),
    "simon_cluster_pods_pending": ("Counted pods with no node (unschedulable pressure)", "gauge"),
    # watch-event journal (server/journal.py, docs/live-twin.md) — type ∈
    # {ev, rb, ck}; outcome ∈ {restored, empty, corrupt}
    "simon_journal_records_total": ("Journal records written by type (ev/rb/ck)", "counter"),
    "simon_journal_bytes_total": ("Journal bytes written (framing included)", "counter"),
    "simon_journal_dropped_total": ("Records dropped at the bounded writer queue", "counter"),
    "simon_journal_fsync_seconds": ("Journal fsync latency", "histogram"),
    "simon_journal_recoveries_total": ("Journal recovery attempts by outcome", "counter"),
    # memory observatory (obs/footprint.py, ISSUE 12) — cardinality
    # contract: dtype ∈ the encoder policy set (encoding/dtypes.py) plus
    # "other"; ring ∈ {flight_recorder, capacity_timeline, journal_queue};
    # device series are one per local accelerator, kind ∈ {in_use, peak}
    "simon_mem_rss_bytes": ("Process resident set size", "gauge"),
    "simon_mem_rss_peak_bytes": ("Process RSS high watermark (VmHWM)", "gauge"),
    "simon_mem_device_bytes": ("Per-device accelerator memory by kind (in_use/peak)", "gauge"),
    "simon_mem_prepcache_bytes": ("Prep-cache host arena bytes (shared leaves counted once)", "gauge"),
    "simon_mem_prepcache_entries": ("Prep-cache entries resident", "gauge"),
    "simon_mem_prepcache_evictions_total": ("Prep-cache LRU evictions", "counter"),
    "simon_mem_prepcache_compactions_total": (
        "Twin-delta refusals at the drop-mask density threshold (full rebuild follows)", "counter",
    ),
    "simon_mem_arena_bytes": ("Prep-cache host arena bytes by encoder-policy dtype", "gauge"),
    "simon_mem_ring_entries": ("Bounded-ring occupancy by ring", "gauge"),
    "simon_mem_ring_capacity": ("Bounded-ring capacity by ring", "gauge"),
    # compile telemetry (obs/profile.py, ISSUE 12) — fn is a fixed set of
    # instrumented jit boundaries; cause ∈ {first, shape, dtype, static,
    # new}; event is the jax compilation-cache event leaf name
    "simon_compile_total": ("JIT compiles observed at instrumented boundaries", "counter"),
    "simon_compile_seconds_total": ("Wall seconds inside observed JIT compiles", "counter"),
    "simon_compile_cause_total": ("Recompiles by attributed cause (shape/dtype/static/new)", "counter"),
    "simon_backend_compile_seconds_total": (
        "Backend (XLA) compile seconds from jax monitoring, all call sites", "counter",
    ),
    "simon_backend_compile_total": ("Backend (XLA) compiles from jax monitoring", "counter"),
    "simon_jitcache_persistent_files": ("Entries in the persistent XLA compile cache dir", "gauge"),
    "simon_jitcache_persistent_bytes": ("Bytes in the persistent XLA compile cache dir", "gauge"),
    "simon_jitcache_events_total": ("jax compilation-cache monitoring events by leaf name", "counter"),
    # aggregate phase profiles (obs/profile.py) — span names are the fixed
    # instrumentation vocabulary (phases, engine rungs, native sub-phases)
    "simon_phase_profile_calls_total": ("Spans folded into the cumulative profile, by span name", "counter"),
    "simon_phase_profile_seconds_total": ("Cumulative inclusive span seconds by span name", "counter"),
    "simon_phase_profile_exclusive_seconds_total": (
        "Cumulative exclusive span seconds (children subtracted) by span name", "counter",
    ),
    # fleet-wide observability (ISSUE 20, obs/fleetobs.py): the event-to-
    # servable freshness pipeline — stage ∈ {journaled, published,
    # attached, served}, each measured from watch-event acceptance on the
    # owner's wall clock (owner and workers share a host)
    "simon_fleet_freshness_seconds": (
        "Event-to-servable latency by pipeline stage, from watch-event acceptance", "histogram",
    ),
    # time-series ring (obs/timeseries.py): sampling liveness + disk bound
    "simon_ts_samples_total": ("Time-series ring samples recorded", "counter"),
    "simon_ts_window_bytes": ("Bytes held by the on-disk time-series ring", "gauge"),
    "simon_ts_windows": ("Delta-encoded windows resident in the time-series ring", "gauge"),
    # SLO engine (obs/slo.py): burn rate = observed bad fraction over the
    # window divided by the objective's error budget (1.0 = burning budget
    # exactly at the sustainable rate); slo/window are a fixed small set
    "simon_slo_burn_rate": ("SLO burn rate by objective and evaluation window", "gauge"),
}


def exposition_headers(name: str, help_text: str, kind: str = "counter") -> List[str]:
    """The ``# HELP``/``# TYPE`` header pair every rendered family carries
    (exposition-format conformance, ISSUE 7 satellite) — the one place the
    header layout lives. Prefer :func:`family_header`, which also forces the
    family through the registry above."""
    return [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]


def family_header(name: str) -> List[str]:
    """``# HELP``/``# TYPE`` for a REGISTERED family — the only way modules
    outside this file emit headers (OSL1101), so an unregistered family
    fails loudly at render time instead of silently forking the registry."""
    try:
        help_text, kind = FAMILIES[name]
    except KeyError:
        raise KeyError(
            f"metric family {name!r} is not registered in obs/metrics.py "
            "FAMILIES; register it there (cardinality governance)"
        ) from None
    return exposition_headers(name, help_text, kind)


def make_counter(name: str, label_names: Sequence[str]) -> "CounterVec":
    """A :class:`CounterVec` for a registered family (help text comes from
    the registry)."""
    help_text, kind = FAMILIES[name]  # KeyError = unregistered family
    if kind != "counter":
        raise ValueError(f"{name} is registered as {kind}, not counter")
    return CounterVec(name, label_names, help=help_text)


def make_histogram(
    name: str,
    label_names: Sequence[str],
    buckets: Sequence[float] = DEFAULT_BUCKETS,
) -> "HistogramVec":
    """A :class:`HistogramVec` for a registered family."""
    help_text, kind = FAMILIES[name]  # KeyError = unregistered family
    if kind != "histogram":
        raise ValueError(f"{name} is registered as {kind}, not histogram")
    return HistogramVec(name, label_names, buckets=buckets, help=help_text)


def escape_label_value(value: str) -> str:
    """Prometheus exposition-format label escaping (text format §label
    values): backslash, double quote, and line feed."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_le(bound: float) -> str:
    if math.isinf(bound):
        return "+Inf"
    s = f"{bound:g}"
    return s


# ---------------------------------------------------------------------------
# Prometheus text-format READING (stdlib only) — the inverse of the render
# path above, shared by the loadgen harness (server/loadgen.py), the fleet
# aggregator (server/fleet.py), and the time-series ring (obs/timeseries.py)
# so per-worker histograms are merged once, correctly, in one place
# (ISSUE 20 satellite; this code started life inside loadgen).
# ---------------------------------------------------------------------------

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+([0-9eE+.\-]+|\+Inf|NaN)$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

MetricKey = Tuple[str, Tuple[Tuple[str, str], ...]]


def parse_metrics(text: str) -> Dict[MetricKey, float]:
    """Exposition text → ``{(name, sorted label items): value}``."""
    out: Dict[MetricKey, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        name, labels_body, value = m.groups()
        labels = tuple(sorted(
            (k, v.replace('\\"', '"').replace("\\\\", "\\"))
            for k, v in _LABEL.findall(labels_body or "")
        ))
        out[(name, labels)] = float(value)
    return out


def scrape_metrics(url: str, timeout_s: float = 10.0) -> Dict[MetricKey, float]:
    import urllib.request

    with urllib.request.urlopen(f"{url}/metrics", timeout=timeout_s) as resp:
        return parse_metrics(resp.read().decode())


def _series_delta(after_v: float, before_v: float) -> float:
    """Cumulative-series delta with counter-reset handling (the PromQL
    ``rate()`` convention): a decrease means the process restarted and the
    counter began again at zero, so the post-reset value IS the delta —
    without this a worker restart mid-measurement reports a negative
    count and poisons every merged quantile."""
    d = after_v - before_v
    return after_v if d < 0 else d


def bucket_deltas(
    before: Dict[MetricKey, float],
    after: Dict[MetricKey, float],
    family: str,
    match: Dict[str, str],
) -> List[Tuple[float, float]]:
    """Sorted ``(le, cumulative delta)`` for one histogram family,
    aggregated over every series whose labels are a superset of ``match``
    (summing cumulative bucket counts across series is legal — they share
    the bucket ladder). A series absent from ``before`` (a worker that
    joined mid-measurement, or an empty first scrape) contributes its full
    ``after`` value; a series that DECREASED is a counter reset and
    contributes its post-reset value."""
    sums: Dict[float, float] = {}
    for (name, labels), v in after.items():
        if name != f"{family}_bucket":
            continue
        ld = dict(labels)
        if any(ld.get(k) != want for k, want in match.items()):
            continue
        le = math.inf if ld.get("le") == "+Inf" else float(ld.get("le", "inf"))
        sums[le] = sums.get(le, 0.0) + _series_delta(v, before.get((name, labels), 0.0))
    return sorted(sums.items())


def histogram_quantile(
    before: Dict[MetricKey, float],
    after: Dict[MetricKey, float],
    family: str,
    q: float,
    match: Optional[Dict[str, str]] = None,
) -> Optional[float]:
    """PromQL ``histogram_quantile`` over the scrape DELTA (so a long-lived
    server's history does not pollute the run's distribution): linear
    interpolation inside the target bucket. None when the delta is empty."""
    buckets = bucket_deltas(before, after, family, match or {})
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in buckets:
        if cum >= target:
            if math.isinf(le):
                return prev_le  # tail bucket: the lower bound is the honest answer
            if cum == prev_cum:
                return le
            return prev_le + (le - prev_le) * (target - prev_cum) / (cum - prev_cum)
        prev_le, prev_cum = le, cum
    return buckets[-1][0]


def counter_delta(
    before: Dict[MetricKey, float],
    after: Dict[MetricKey, float],
    name: str,
    match: Optional[Dict[str, str]] = None,
) -> float:
    """Summed counter delta across matching series, reset-safe (see
    :func:`bucket_deltas`)."""
    total = 0.0
    for (n, labels), v in after.items():
        if n != name:
            continue
        ld = dict(labels)
        if match and any(ld.get(k) != want for k, want in match.items()):
            continue
        total += _series_delta(v, before.get((n, labels), 0.0))
    return total


class CounterVec:
    """One counter family over a fixed label set, rendered with its
    ``# HELP``/``# TYPE`` header. Not self-locking — mutations happen under
    the owning :class:`MetricsRecorder`'s lock like everything else."""

    def __init__(self, name: str, label_names: Sequence[str], help: str = "") -> None:
        self.name = name
        self.label_names = tuple(label_names)
        self.help = help
        self._series: Dict[Tuple[str, ...], int] = {}  # guarded-by: RECORDER.lock

    def inc(self, labels: Tuple[str, ...], n: int = 1) -> None:
        self._series[labels] = self._series.get(labels, 0) + n

    def render_lines(self) -> List[str]:
        if not self._series:
            return []
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} counter")
        for labels in sorted(self._series):
            base = ",".join(
                f'{k}="{escape_label_value(v)}"'
                for k, v in zip(self.label_names, labels)
            )
            lines.append(f"{self.name}{{{base}}} {self._series[labels]}")
        return lines

    def reset(self) -> None:
        self._series.clear()


class HistogramVec:
    """One histogram family over a fixed label set. Not self-locking: every
    mutation/read happens under the owning :class:`MetricsRecorder`'s lock
    (the one-lock design is the point — see module docstring)."""

    def __init__(
        self,
        name: str,
        label_names: Sequence[str],
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        help: str = "",
    ) -> None:
        self.name = name
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets) + (math.inf,)
        self.help = help
        # label-values tuple -> [per-bucket counts..., count, sum]
        self._series: Dict[Tuple[str, ...], list] = {}  # guarded-by: RECORDER.lock

    def observe(self, seconds: float, labels: Tuple[str, ...]) -> None:
        series = self._series.get(labels)
        if series is None:
            series = self._series[labels] = [0] * len(self.buckets) + [0, 0.0]
        for i, bound in enumerate(self.buckets):
            if seconds <= bound:
                series[i] += 1
                break
        series[-2] += 1
        series[-1] += seconds

    def render_lines(self) -> List[str]:
        if not self._series:
            return []
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} histogram")
        for labels in sorted(self._series):
            series = self._series[labels]
            base = ",".join(
                f'{k}="{escape_label_value(v)}"'
                for k, v in zip(self.label_names, labels)
            )
            sep = "," if base else ""
            # label-less histograms (e.g. simon_batch_size) must not render
            # empty `{}` braces — the exposition grammar rejects them
            wrap = f"{{{base}}}" if base else ""
            cum = 0
            for i, bound in enumerate(self.buckets):
                cum += series[i]
                lines.append(
                    f'{self.name}_bucket{{{base}{sep}le="{_fmt_le(bound)}"}} {cum}'
                )
            lines.append(f"{self.name}_sum{wrap} {series[-1]:.6f}")
            lines.append(f"{self.name}_count{wrap} {series[-2]}")
        return lines

    def reset(self) -> None:
        self._series.clear()


class MetricsRecorder:
    """The locked recorder every metrics mutation routes through: phase and
    request latency histograms fed from trace spans, plus the shared RLock
    the REST counters borrow."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.phase_seconds = make_histogram("simon_phase_seconds", ("phase", "endpoint"))
        self.request_seconds = make_histogram("simon_request_seconds", ("endpoint", "status"))
        # decision audit (ISSUE 7): per-filter node rejects from the
        # failure attribution, and unschedulable pods by primary reason —
        # bumped by every simulate() regardless of explain mode
        self.filter_rejects = make_counter("simon_filter_reject_total", ("filter",))
        self.unschedulable = make_counter("simon_unschedulable_total", ("reason",))
        # watch-pipeline latency (ISSUE 9 satellite): event receipt → twin
        # applied, fed from the supervisor's dispatch (server/watch.py)
        self.watch_apply = make_histogram(
            "simon_watch_apply_seconds", (), buckets=WATCH_APPLY_BUCKETS
        )

    def observe_request(self, endpoint: str, seconds: float, status: str = "ok") -> None:
        """Whole-request latency — recorded for every outcome (labeled with
        the trace status, so errors/timeouts have their own series), with or
        without tracing enabled (the histogram must not go dark when
        ``OPENSIM_TRACE=0``)."""
        with self.lock:
            self.request_seconds.observe(seconds, (endpoint, status))

    def observe_phase(self, phase: str, endpoint: str, seconds: float) -> None:
        with self.lock:
            self.phase_seconds.observe(seconds, (phase, endpoint))

    def observe_watch_apply(self, seconds: float) -> None:
        """One watch event's receipt→applied latency (server/watch.py
        dispatch — includes the injected-fault bookkeeping and the twin's
        rv-monotonic store surgery, not the network read)."""
        with self.lock:
            self.watch_apply.observe(seconds, ())

    def observe_trace(self, trace) -> None:
        """The span sink: fold a finished trace's phase spans into the
        per-phase histograms. One recording path — the histograms and the
        flight-recorder tree are computed from the SAME span objects."""
        from .trace import PHASES

        phases = set(PHASES)
        with self.lock:
            for sp in trace.walk():
                if sp.name in phases:
                    self.phase_seconds.observe(sp.duration_s, (sp.name, trace.endpoint))

    def simulate_seconds_total(self) -> float:
        """Continuity shim for the pre-histogram ``simon_simulate_seconds_total``
        counter, derived from the one recording path instead of
        hand-maintained. Sums the ``status="ok"`` series only — the old
        counter accumulated successful simulations exclusively, and a
        dashboard dividing it by ``simon_simulations_total`` (also
        success-only) must not spike during an outage."""
        with self.lock:
            return sum(
                s[-1]
                for labels, s in self.request_seconds._series.items()
                if labels[1] == "ok"
            )

    def count_filter_rejects(self, by_filter: Dict[str, int]) -> None:
        with self.lock:
            for name, n in by_filter.items():
                self.filter_rejects.inc((name,), int(n))

    def count_unschedulable(self, by_reason: Dict[str, int]) -> None:
        with self.lock:
            for name, n in by_reason.items():
                self.unschedulable.inc((name,), int(n))

    def render_lines(self) -> List[str]:
        with self.lock:
            return (
                self.filter_rejects.render_lines()
                + self.unschedulable.render_lines()
                + self.phase_seconds.render_lines()
                + self.request_seconds.render_lines()
                + self.watch_apply.render_lines()
            )

    def reset(self) -> None:
        with self.lock:
            self.phase_seconds.reset()
            self.request_seconds.reset()
            self.filter_rejects.reset()
            self.unschedulable.reset()
            self.watch_apply.reset()


RECORDER = MetricsRecorder()
