"""Latency histograms + Prometheus text-format hardening (ISSUE 5).

The serving path used to export only hand-maintained ``*_seconds_total``
counters — totals hide tail behavior entirely. This module adds fixed-bucket
latency *histograms* computed from the same spans the tracer records
(``simon_phase_seconds_bucket{phase=,endpoint=}`` and
``simon_request_seconds_bucket{endpoint=}``), rendered in the Prometheus
exposition format at ``/metrics``.

It also owns the ONE recording lock for the whole metrics surface: the REST
layer's ``_Metrics`` counters, these histograms, and the span sink all
record under :data:`RECORDER`'s RLock, closing the cross-thread bump races
the old per-object locking left open (counters were bumped both from
``_handle`` and from snapshot-retry callbacks).

Label values are escaped per the exposition format (``\\`` → ``\\\\``,
``"`` → ``\\"``, newline → ``\\n``) — a hostile endpoint/path string cannot
corrupt a scrape.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_BUCKETS",
    "CounterVec",
    "HistogramVec",
    "MetricsRecorder",
    "RECORDER",
    "escape_label_value",
]

# fixed bucket upper bounds in seconds (the +Inf bucket is implicit):
# sub-ms cache hits through multi-second cold 50k-pod plans
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)


def exposition_headers(name: str, help_text: str, kind: str = "counter") -> List[str]:
    """The ``# HELP``/``# TYPE`` header pair every rendered family carries
    (exposition-format conformance, ISSUE 7 satellite) — the one place the
    header layout lives, shared by the REST counters and the watch
    supervisor's series."""
    return [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]


def escape_label_value(value: str) -> str:
    """Prometheus exposition-format label escaping (text format §label
    values): backslash, double quote, and line feed."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_le(bound: float) -> str:
    if math.isinf(bound):
        return "+Inf"
    s = f"{bound:g}"
    return s


class CounterVec:
    """One counter family over a fixed label set, rendered with its
    ``# HELP``/``# TYPE`` header. Not self-locking — mutations happen under
    the owning :class:`MetricsRecorder`'s lock like everything else."""

    def __init__(self, name: str, label_names: Sequence[str], help: str = "") -> None:
        self.name = name
        self.label_names = tuple(label_names)
        self.help = help
        self._series: Dict[Tuple[str, ...], int] = {}

    def inc(self, labels: Tuple[str, ...], n: int = 1) -> None:
        self._series[labels] = self._series.get(labels, 0) + n

    def render_lines(self) -> List[str]:
        if not self._series:
            return []
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} counter")
        for labels in sorted(self._series):
            base = ",".join(
                f'{k}="{escape_label_value(v)}"'
                for k, v in zip(self.label_names, labels)
            )
            lines.append(f"{self.name}{{{base}}} {self._series[labels]}")
        return lines

    def reset(self) -> None:
        self._series.clear()


class HistogramVec:
    """One histogram family over a fixed label set. Not self-locking: every
    mutation/read happens under the owning :class:`MetricsRecorder`'s lock
    (the one-lock design is the point — see module docstring)."""

    def __init__(
        self,
        name: str,
        label_names: Sequence[str],
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        help: str = "",
    ) -> None:
        self.name = name
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets) + (math.inf,)
        self.help = help
        # label-values tuple -> [per-bucket counts..., count, sum]
        self._series: Dict[Tuple[str, ...], list] = {}

    def observe(self, seconds: float, labels: Tuple[str, ...]) -> None:
        series = self._series.get(labels)
        if series is None:
            series = self._series[labels] = [0] * len(self.buckets) + [0, 0.0]
        for i, bound in enumerate(self.buckets):
            if seconds <= bound:
                series[i] += 1
                break
        series[-2] += 1
        series[-1] += seconds

    def render_lines(self) -> List[str]:
        if not self._series:
            return []
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} histogram")
        for labels in sorted(self._series):
            series = self._series[labels]
            base = ",".join(
                f'{k}="{escape_label_value(v)}"'
                for k, v in zip(self.label_names, labels)
            )
            sep = "," if base else ""
            # label-less histograms (e.g. simon_batch_size) must not render
            # empty `{}` braces — the exposition grammar rejects them
            wrap = f"{{{base}}}" if base else ""
            cum = 0
            for i, bound in enumerate(self.buckets):
                cum += series[i]
                lines.append(
                    f'{self.name}_bucket{{{base}{sep}le="{_fmt_le(bound)}"}} {cum}'
                )
            lines.append(f"{self.name}_sum{wrap} {series[-1]:.6f}")
            lines.append(f"{self.name}_count{wrap} {series[-2]}")
        return lines

    def reset(self) -> None:
        self._series.clear()


class MetricsRecorder:
    """The locked recorder every metrics mutation routes through: phase and
    request latency histograms fed from trace spans, plus the shared RLock
    the REST counters borrow."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.phase_seconds = HistogramVec(
            "simon_phase_seconds", ("phase", "endpoint"),
            help="Per-phase latency from the request span trees",
        )
        self.request_seconds = HistogramVec(
            "simon_request_seconds", ("endpoint", "status"),
            help="Whole-request latency by endpoint and outcome",
        )
        # decision audit (ISSUE 7): per-filter node rejects from the
        # failure attribution, and unschedulable pods by primary reason —
        # bumped by every simulate() regardless of explain mode
        self.filter_rejects = CounterVec(
            "simon_filter_reject_total", ("filter",),
            help="Nodes rejected per filter plugin while attributing unschedulable pods",
        )
        self.unschedulable = CounterVec(
            "simon_unschedulable_total", ("reason",),
            help="Unschedulable pods by primary (most-rejecting) reason code",
        )

    def observe_request(self, endpoint: str, seconds: float, status: str = "ok") -> None:
        """Whole-request latency — recorded for every outcome (labeled with
        the trace status, so errors/timeouts have their own series), with or
        without tracing enabled (the histogram must not go dark when
        ``OPENSIM_TRACE=0``)."""
        with self.lock:
            self.request_seconds.observe(seconds, (endpoint, status))

    def observe_phase(self, phase: str, endpoint: str, seconds: float) -> None:
        with self.lock:
            self.phase_seconds.observe(seconds, (phase, endpoint))

    def observe_trace(self, trace) -> None:
        """The span sink: fold a finished trace's phase spans into the
        per-phase histograms. One recording path — the histograms and the
        flight-recorder tree are computed from the SAME span objects."""
        from .trace import PHASES

        phases = set(PHASES)
        with self.lock:
            for sp in trace.walk():
                if sp.name in phases:
                    self.phase_seconds.observe(sp.duration_s, (sp.name, trace.endpoint))

    def simulate_seconds_total(self) -> float:
        """Continuity shim for the pre-histogram ``simon_simulate_seconds_total``
        counter, derived from the one recording path instead of
        hand-maintained. Sums the ``status="ok"`` series only — the old
        counter accumulated successful simulations exclusively, and a
        dashboard dividing it by ``simon_simulations_total`` (also
        success-only) must not spike during an outage."""
        with self.lock:
            return sum(
                s[-1]
                for labels, s in self.request_seconds._series.items()
                if labels[1] == "ok"
            )

    def count_filter_rejects(self, by_filter: Dict[str, int]) -> None:
        with self.lock:
            for name, n in by_filter.items():
                self.filter_rejects.inc((name,), int(n))

    def count_unschedulable(self, by_reason: Dict[str, int]) -> None:
        with self.lock:
            for name, n in by_reason.items():
                self.unschedulable.inc((name,), int(n))

    def render_lines(self) -> List[str]:
        with self.lock:
            return (
                self.filter_rejects.render_lines()
                + self.unschedulable.render_lines()
                + self.phase_seconds.render_lines()
                + self.request_seconds.render_lines()
            )

    def reset(self) -> None:
        with self.lock:
            self.phase_seconds.reset()
            self.request_seconds.reset()
            self.filter_rejects.reset()
            self.unschedulable.reset()


RECORDER = MetricsRecorder()
