"""Bounded on-disk time-series ring over the metric registry (ISSUE 20,
docs/observability.md "Watching the fleet").

A :class:`TimeSeriesSampler` thread scrapes the process's own exposition
text every ``OPENSIM_TS_INTERVAL_S`` seconds, parses it with the shared
reader (``obs.metrics.parse_metrics``) and appends the sample to a
:class:`TimeSeriesRing`: a fixed number of **windows**
(``OPENSIM_TS_WINDOWS``), each holding a fixed number of samples
(``OPENSIM_TS_WINDOW_SAMPLES``). Only the newest window lives in memory;
a full window is **sealed** to disk as one delta-encoded JSON file and
the oldest file is unlinked when the ring wraps — the on-disk footprint
is bounded by construction, never by a cleanup job.

Delta encoding is exact, not approximate: a sample stores, per series,
either a float delta ``d`` against the previous sample — only when
``prev + d == value`` reproduces the value bit-for-bit (IEEE addition is
not guaranteed to invert subtraction) — or the absolute value in ``set``
(new series, counter resets, and the rare non-invertible float). The
round-trip test in tests/test_fleetobs.py holds this to equality, not
tolerance.

Queries (``GET /api/debug/timeseries?family=&range=``, ``simon dash``,
the SLO engine) read memory for the open window and decode sealed files
for history; series keys travel as exposition-format sample keys
(``simon_request_seconds_bucket{le="0.1"}``) so every consumer reuses
``parse_metrics`` instead of inventing a second key grammar.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .metrics import (
    RECORDER,
    MetricKey,
    escape_label_value,
    family_header,
    make_counter,
    parse_metrics,
)
from ..utils import envknobs

log = logging.getLogger("opensim_tpu.timeseries")

__all__ = [
    "TimeSeriesRing",
    "TimeSeriesSampler",
    "decode_window",
    "parse_duration_s",
    "render_series_key",
    "sample_interval_s",
]

_FORMAT_VERSION = 1


def sample_interval_s() -> float:
    return float(envknobs.value("OPENSIM_TS_INTERVAL_S"))


def parse_duration_s(spec: Optional[str]) -> Optional[float]:
    """``?range=`` grammar: bare seconds (``300``) or suffixed
    (``5m``/``1h``/``2d``). Empty/None → None (no cutoff). Raises
    ``ValueError`` on garbage — a silently ignored range is a dashboard
    quietly showing the wrong window."""
    spec = (spec or "").strip()
    if not spec:
        return None
    units = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
    if spec[-1] in units:
        return float(spec[:-1]) * units[spec[-1]]
    return float(spec)


def render_series_key(key: MetricKey) -> str:
    """``(name, labels)`` → the exposition sample key (``name{...}``) —
    the inverse of ``parse_metrics`` for a single sample line."""
    name, labels = key
    if not labels:
        return name
    body = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels)
    return f"{name}{{{body}}}"


def parse_series_key(key: str) -> Optional[MetricKey]:
    """One rendered series key back to ``(name, sorted labels)``."""
    parsed = parse_metrics(f"{key} 0")
    for k in parsed:
        return k
    return None


def _encode_samples(samples: List[Tuple[float, Dict[str, float]]]) -> List[dict]:
    """Delta-encode a window's samples (keys are rendered series keys).
    The first sample is stored whole; each later one stores float deltas
    where exactly invertible, absolute values otherwise, and the keys
    that disappeared."""
    out: List[dict] = []
    prev: Dict[str, float] = {}
    for ts, series in samples:
        if not out:
            out.append({"ts": ts, "full": dict(series)})
        else:
            deltas: Dict[str, float] = {}
            absolutes: Dict[str, float] = {}
            for k, v in series.items():
                if k in prev:
                    d = v - prev[k]
                    if prev[k] + d == v:
                        deltas[k] = d
                        continue
                absolutes[k] = v
            rec: dict = {"ts": ts}
            if deltas:
                rec["d"] = deltas
            if absolutes:
                rec["set"] = absolutes
            gone = [k for k in prev if k not in series]
            if gone:
                rec["gone"] = gone
            out.append(rec)
        prev = series
    return out


def _decode_samples(encoded: List[dict]) -> List[Tuple[float, Dict[str, float]]]:
    samples: List[Tuple[float, Dict[str, float]]] = []
    prev: Dict[str, float] = {}
    for rec in encoded:
        if "full" in rec:
            series = dict(rec["full"])
        else:
            series = dict(prev)
            for k in rec.get("gone") or []:
                series.pop(k, None)
            for k, d in (rec.get("d") or {}).items():
                series[k] = series.get(k, 0.0) + d
            for k, v in (rec.get("set") or {}).items():
                series[k] = v
        samples.append((float(rec["ts"]), series))
        prev = series
    return samples


def decode_window(path: str) -> List[Tuple[float, Dict[str, float]]]:
    """Decode one sealed window file → ``[(ts, {series key: value})]``.
    Raises on a malformed file (callers treat that window as lost)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("v") != _FORMAT_VERSION:
        raise ValueError(f"unsupported timeseries window version {doc.get('v')!r}")
    return _decode_samples(doc.get("samples") or [])


class TimeSeriesRing:
    """The bounded ring. ``directory=None`` creates (and owns — removed
    on :meth:`close`) a private tempdir; an explicit directory (the
    ``OPENSIM_TS_DIR`` knob) persists across restarts for post-mortems."""

    def __init__(
        self,
        directory: Optional[str] = None,
        windows: Optional[int] = None,
        window_samples: Optional[int] = None,
    ) -> None:
        self.windows = int(windows or envknobs.value("OPENSIM_TS_WINDOWS"))
        self.window_samples = int(
            window_samples or envknobs.value("OPENSIM_TS_WINDOW_SAMPLES")
        )
        self._owns_dir = directory is None
        self.directory = directory or tempfile.mkdtemp(prefix="simon-ts-")
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        # the open window, newest last            # guarded-by: _lock
        self._open: List[Tuple[float, Dict[str, float]]] = []
        self._sealed: List[str] = []  # sealed file paths, oldest first  # guarded-by: _lock
        self._seq = 0  # monotonic window file index  # guarded-by: _lock
        self._bytes = 0  # on-disk bytes across sealed files  # guarded-by: _lock
        self.samples_total = make_counter("simon_ts_samples_total", ())
        self._closed = False
        with self._lock:
            self._adopt_existing_locked()

    # -- write side ----------------------------------------------------------

    def _adopt_existing_locked(self) -> None:
        """An explicit directory may hold windows from a previous run:
        adopt them into the ring (oldest first) so the bound keeps
        holding across restarts."""
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith("win-") and n.endswith(".json")
            )
        except OSError:
            return
        for name in names:
            path = os.path.join(self.directory, name)
            self._sealed.append(path)
            try:
                self._bytes += os.path.getsize(path)
                self._seq = max(self._seq, int(name[4:-5]) + 1)
            except (OSError, ValueError):
                pass
        self._enforce_bound_locked()

    def append(self, ts: float, series: Dict[MetricKey, float]) -> None:
        """One sample: parsed scrape → rendered series keys → the open
        window, sealing to disk when full. The seal's file write happens
        OUTSIDE the ring lock — a slow disk must not stall queries."""
        rendered = {render_series_key(k): v for k, v in series.items()}
        doc = path = None
        with self._lock:
            if self._closed:
                return
            self._open.append((ts, rendered))
            if len(self._open) >= self.window_samples:
                doc = {
                    "v": _FORMAT_VERSION,
                    "t0": self._open[0][0],
                    "t1": self._open[-1][0],
                    "samples": _encode_samples(self._open),
                }
                path = os.path.join(self.directory, f"win-{self._seq:08d}.json")
                self._seq += 1
                self._open = []
        if doc is not None and path is not None:
            self._write_window(doc, path)
        with RECORDER.lock:
            self.samples_total.inc(())

    def _write_window(self, doc: dict, path: str) -> None:
        """One sealed window to disk (single-writer: only the sampler
        thread seals). Adopted into the ring under the lock after the
        atomic rename; a failed write drops the window — observability
        must not take the server down, and the bound still holds."""
        try:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, separators=(",", ":"))
            os.replace(tmp, path)  # a reader never sees a torn window
            size = os.path.getsize(path)
        except OSError as e:
            log.warning("timeseries window seal failed (%s): window dropped", e)
            return
        with self._lock:
            self._sealed.append(path)
            self._bytes += size
            self._enforce_bound_locked()

    def _enforce_bound_locked(self) -> None:
        while len(self._sealed) > max(1, self.windows - 1):
            path = self._sealed.pop(0)
            try:
                self._bytes -= os.path.getsize(path)
                os.unlink(path)
            except OSError:
                pass

    # -- read side -----------------------------------------------------------

    def query(
        self,
        family: str = "",
        range_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> List[Tuple[float, Dict[str, float]]]:
        """Samples (oldest first) within ``range_s`` seconds of ``now``,
        filtered to ``family`` (comma-separated family names; a family
        matches its own samples plus ``_bucket``/``_sum``/``_count``
        children; empty = everything)."""
        cutoff = None
        if range_s is not None:
            cutoff = (now or time.time()) - max(0.0, float(range_s))
        with self._lock:
            sealed = list(self._sealed)
            out = list(self._open)
        for path in reversed(sealed):
            if out and cutoff is not None and out[0][0] <= cutoff:
                break  # older files cannot contribute in-range samples
            try:
                out = decode_window(path) + out
            except (OSError, ValueError) as e:
                log.warning("timeseries window %s unreadable (%s); skipped", path, e)
        if cutoff is not None:
            out = [(ts, s) for ts, s in out if ts >= cutoff]
        fams = [f for f in family.split(",") if f]
        if fams:
            def keep(key: str) -> bool:
                name = key.split("{", 1)[0]
                for f in fams:
                    if name == f or (
                        name.startswith(f + "_")
                        and name[len(f):] in ("_bucket", "_sum", "_count")
                    ):
                        return True
                return False

            out = [
                (ts, {k: v for k, v in s.items() if keep(k)}) for ts, s in out
            ]
        return out

    def query_parsed(
        self,
        family: str = "",
        range_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> List[Tuple[float, Dict[MetricKey, float]]]:
        """:meth:`query` with series keys decoded back to ``MetricKey`` —
        the shape ``histogram_quantile``/``counter_delta`` consume."""
        out = []
        for ts, series in self.query(family, range_s, now):
            out.append(
                (ts, parse_metrics("\n".join(f"{k} {v!r}" for k, v in series.items())))
            )
        return out

    # -- telemetry / lifecycle -----------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "windows": len(self._sealed) + (1 if self._open else 0),
                "window_capacity": self.windows,
                "window_samples": self.window_samples,
                "open_samples": len(self._open),
                "bytes": self._bytes,
                "directory": self.directory,
            }

    def metrics_lines(self) -> List[str]:
        st = self.stats()
        with RECORDER.lock:
            lines = self.samples_total.render_lines()
        lines = lines or family_header("simon_ts_samples_total")
        for name, value in (
            ("simon_ts_window_bytes", st["bytes"]),
            ("simon_ts_windows", st["windows"]),
        ):
            lines += family_header(name)
            lines.append(f"{name} {value}")
        return lines

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            sealed = list(self._sealed)
        if self._owns_dir:
            for path in sealed:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            try:
                os.rmdir(self.directory)
            except OSError:
                pass


class TimeSeriesSampler:
    """The sampling thread: ``scrape_fn() → parse → ring.append`` every
    interval. One per serving process that owns a scrape surface (the
    single-process server and the fleet owner; workers are sampled
    through the owner's aggregation)."""

    def __init__(
        self,
        ring: TimeSeriesRing,
        scrape_fn: Callable[[], str],
        interval_s: Optional[float] = None,
    ) -> None:
        self.ring = ring
        self.scrape_fn = scrape_fn
        self.interval_s = max(0.05, interval_s or sample_interval_s())
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self, now: Optional[float] = None) -> None:
        self.ring.append(now or time.time(), parse_metrics(self.scrape_fn()))

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception as e:
                # a failed scrape (worker roll mid-aggregation) skips one
                # sample; the ring and the server keep going
                log.warning("timeseries sample failed: %s: %s", type(e).__name__, e)

    def start(self) -> "TimeSeriesSampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="simon-timeseries", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
