"""Request-scoped tracing: contextvar-carried span trees (ISSUE 5).

One :class:`TraceContext` per served request (or per bench/apply run)
carries a tree of :class:`Span` objects through the whole serving path —
snapshot fetch, prepare, encode, schedule (with one child per engine-ladder
rung actually attempted), decode — plus instant *events* for the things the
resilience layer does on the way: snapshot retries, breaker trips, engine
demotions, prep-cache invalidations, fault injections. The C++ engine's
``profile_out`` phase timings and ``PREP_STATS`` host-prepare timings attach
as child spans, so C++ scan time and host encode time appear in one tree.

Design constraints (the tentpole's "allocation-light and dormant-cheap"):

- Spans are plain host-side objects timed with ``time.monotonic``; nothing
  here ever touches JAX tracing/jit internals, so instrumented functions
  stay jit-safe and the tracer works identically under every engine.
- The ambient trace travels in ONE :mod:`contextvars` variable. With no
  active trace (library callers, ``OPENSIM_TRACE=0``), every instrumentation
  point — :func:`span`, :func:`event`, :func:`record_span` — is a single
  contextvar read returning a shared no-op; no objects are allocated.
- One trace == one thread (the HTTP server handles each request on its own
  thread), so the span stack needs no lock; finished traces are immutable
  and safe to read from the flight-recorder endpoints on other threads.

Exporters: :meth:`TraceContext.to_chrome` (Chrome-trace / Perfetto JSON for
``bench.py --trace`` and ``simon apply --trace``) and :meth:`TraceContext.tree`
(the ``/api/debug/requests/<id>`` span-tree JSON).
"""

from __future__ import annotations

import contextvars
import re
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

from ..utils import envknobs

__all__ = [
    "PHASES",
    "Span",
    "TraceContext",
    "current_span",
    "current_trace",
    "enabled",
    "event",
    "new_request_id",
    "record_span",
    "sanitize_request_id",
    "span",
    "start_trace",
    "trace_scope",
    "write_chrome",
]

# the Deadline layer's phase names — spans with these names feed the
# /metrics latency histograms (obs/metrics.py). ``prepare`` contains
# ``encode`` as a child by design: the histograms measure each boundary the
# deadline layer can abandon work at, not disjoint partitions of the wall.
PHASES = ("snapshot", "prepare", "encode", "schedule", "decode")

_STATUSES = ("ok", "error", "deadline-exceeded", "demoted")


class Span:
    """One timed phase. ``status`` is ok / error / deadline-exceeded /
    demoted; ``attrs`` is a small flat dict of typed attributes."""

    __slots__ = ("name", "start", "end", "status", "attrs", "children", "_lay")

    def __init__(self, name: str, start: float, attrs: Optional[dict] = None) -> None:
        self.name = name
        self.start = start
        self.end: Optional[float] = None
        self.status = "ok"
        self.attrs: Dict[str, Any] = attrs or {}
        self.children: List[Span] = []
        self._lay = start  # cursor for synthetic sequential children

    @property
    def duration_s(self) -> float:
        return (self.end if self.end is not None else time.monotonic()) - self.start

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def mark(self, status: str, **attrs: Any) -> None:
        if status not in _STATUSES:
            raise ValueError(f"unknown span status {status!r}; known: {_STATUSES}")
        self.status = status
        self.attrs.update(attrs)

    def child_from_seconds(self, name: str, seconds: float, status: str = "ok",
                           **attrs: Any) -> "Span":
        """Attach a synthetic completed child of ``seconds`` duration, laid
        out sequentially from this span's start — how the C++ engine's
        ``profile_out`` phase timings (measured inside the .so, no start
        timestamps) appear in the same tree as host-side spans."""
        child = Span(name, self._lay, attrs or None)
        child.end = self._lay + seconds
        child.status = status
        self._lay = child.end
        self.children.append(child)
        return child

    def walk(self) -> Iterator["Span"]:
        yield self
        for c in self.children:
            yield from c.walk()

    def __repr__(self) -> str:
        return f"Span({self.name!r}, {self.duration_s * 1000:.2f}ms, {self.status})"


class _NoopSpan:
    """Shared do-nothing span: what instrumentation points get when no
    trace is ambient. Also its own context manager, so ``with span(...)``
    costs no allocation when tracing is dormant."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass

    def mark(self, status: str, **attrs: Any) -> None:
        pass

    def child_from_seconds(self, name: str, seconds: float, status: str = "ok",
                           **attrs: Any) -> "_NoopSpan":
        return self


NOOP_SPAN = _NoopSpan()


class _SpanScope:
    """Context manager opening a real span on the ambient trace's stack."""

    __slots__ = ("trace", "span")

    def __init__(self, trace: "TraceContext", name: str, attrs: Optional[dict]) -> None:
        self.trace = trace
        self.span = Span(name, time.monotonic(), attrs)

    def __enter__(self) -> Span:
        stack = self.trace._stack
        stack[-1].children.append(self.span)
        stack.append(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self.span
        stack = self.trace._stack
        if stack and stack[-1] is sp:
            stack.pop()
        sp.end = time.monotonic()
        if exc_type is not None and sp.status == "ok":
            # DeadlineExceeded is matched by name, not import: obs must not
            # depend on the resilience layer (it is imported beneath it)
            sp.status = (
                "deadline-exceeded" if exc_type.__name__ == "DeadlineExceeded" else "error"
            )
            sp.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        return False


class TraceContext:
    """One request's span tree plus its identity and clock anchors."""

    def __init__(self, endpoint: str, request_id: Optional[str] = None) -> None:
        self.request_id = sanitize_request_id(request_id) or new_request_id()
        self.endpoint = endpoint
        self.started_unix = time.time()
        self.root = Span(endpoint, time.monotonic())
        self.http_status: Optional[int] = None
        self._stack: List[Span] = [self.root]

    # -- recording ----------------------------------------------------------

    def span(self, name: str, attrs: Optional[dict] = None) -> _SpanScope:
        return _SpanScope(self, name, attrs)

    def current_span(self) -> Span:
        return self._stack[-1]

    def finish(self, status: str = "ok", http_status: Optional[int] = None) -> None:
        """Close the root (and any span an escaped exception left open —
        they inherit the final status so a crash never yields a tree that
        claims its interrupted phases succeeded)."""
        now = time.monotonic()
        while len(self._stack) > 1:
            sp = self._stack.pop()
            sp.end = now
            if sp.status == "ok" and status != "ok":
                sp.status = status
        self.root.end = now
        if self.root.status == "ok":
            self.root.status = status
        self.http_status = http_status
        self._stack = [self.root]

    @property
    def finished(self) -> bool:
        return self.root.end is not None

    def walk(self) -> Iterator[Span]:
        return self.root.walk()

    # -- exporters ----------------------------------------------------------

    def summary(self) -> dict:
        out = {
            "request_id": self.request_id,
            "endpoint": self.endpoint,
            "status": self.root.status,
            "http_status": self.http_status,
            "started_unix": round(self.started_unix, 3),
            "duration_s": round(self.root.duration_s, 6),
            "spans": sum(1 for _ in self.walk()) - 1,
        }
        if "engine" in self.root.attrs:
            out["engine"] = self.root.attrs["engine"]
        return out

    def tree(self) -> dict:
        """Full span tree for ``/api/debug/requests/<id>``."""

        def node(sp: Span) -> dict:
            d: dict = {
                "name": sp.name,
                "status": sp.status,
                "start_s": round(sp.start - self.root.start, 6),
                "duration_s": round(sp.duration_s, 6),
            }
            if sp.attrs:
                d["attrs"] = {k: _jsonable(v) for k, v in sp.attrs.items()}
            if sp.children:
                d["children"] = [node(c) for c in sp.children]
            return d

        out = self.summary()
        out["spans"] = node(self.root)
        return out

    def to_chrome(self) -> dict:
        """Chrome-trace JSON (chrome://tracing, Perfetto UI): one complete
        ("X") event per span, timestamps in microseconds from trace start."""
        events: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": f"simon {self.endpoint}"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": f"request {self.request_id}"}},
        ]
        for sp in self.walk():
            events.append(
                {
                    "name": sp.name,
                    "cat": "simon",
                    "ph": "X",
                    "pid": 1,
                    "tid": 1,
                    "ts": round((sp.start - self.root.start) * 1e6, 3),
                    "dur": round(sp.duration_s * 1e6, 3),
                    "args": {
                        "status": sp.status,
                        **{k: _jsonable(v) for k, v in sp.attrs.items()},
                    },
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}


def _jsonable(v: Any) -> Any:
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


# ---------------------------------------------------------------------------
# ambient trace (contextvar) + module-level recording API
# ---------------------------------------------------------------------------

_CURRENT: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "opensim_trace", default=None
)

_REQUEST_ID_OK = re.compile(r"[^A-Za-z0-9._:\-]")


def enabled() -> bool:
    """Tracing is on unless ``OPENSIM_TRACE=0`` (the dormant mode whose whole
    cost is one contextvar read per instrumentation point)."""
    return envknobs.raw("OPENSIM_TRACE", "1") != "0"


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def sanitize_request_id(raw: Optional[str]) -> str:
    """A client-supplied ``X-Simon-Request-Id`` is echoed into a response
    header and a URL path segment: strip anything that could smuggle header
    or path structure, and bound the length."""
    if not raw:
        return ""
    return _REQUEST_ID_OK.sub("", raw)[:64]


def start_trace(
    endpoint: str, request_id: Optional[str] = None, force: bool = False
) -> Optional[TraceContext]:
    """New TraceContext, or None when tracing is disabled (``force=True``
    overrides the env — an explicit ``--trace out.json`` flag wins)."""
    if not force and not enabled():
        return None
    return TraceContext(endpoint, request_id=request_id)


class _TraceScope:
    """Install a trace as the ambient one for a ``with`` body; ``None`` is a
    no-op scope so call sites never need to branch."""

    __slots__ = ("trace", "_token")

    def __init__(self, trace: Optional[TraceContext]) -> None:
        self.trace = trace
        self._token = None

    def __enter__(self) -> Optional[TraceContext]:
        if self.trace is not None:
            self._token = _CURRENT.set(self.trace)
        return self.trace

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        return False


def trace_scope(trace: Optional[TraceContext]) -> _TraceScope:
    return _TraceScope(trace)


def current_trace() -> Optional[TraceContext]:
    return _CURRENT.get()


def current_span():
    tr = _CURRENT.get()
    return NOOP_SPAN if tr is None else tr.current_span()


def span(name: str, **attrs: Any):
    """``with span("schedule", pods=n) as sp:`` — a real span when a trace
    is ambient, the shared no-op otherwise (one contextvar read)."""
    tr = _CURRENT.get()
    if tr is None:
        return NOOP_SPAN
    return tr.span(name, attrs or None)


def event(name: str, status: str = "ok", **attrs: Any) -> None:
    """Instant (zero-duration) span under the current span: retries, breaker
    trips, demotions, cache invalidations, fault injections."""
    tr = _CURRENT.get()
    if tr is None:
        return
    now = time.monotonic()
    sp = Span(name, now, attrs or None)
    sp.end = now
    sp.status = status
    tr.current_span().children.append(sp)


def record_span(name: str, seconds: float, status: str = "ok", **attrs: Any) -> None:
    """Append a completed span that ended *now* and lasted ``seconds`` —
    for code that measured a duration itself (``PREP_STATS.record``)."""
    tr = _CURRENT.get()
    if tr is None:
        return
    now = time.monotonic()
    sp = Span(name, now - seconds, attrs or None)
    sp.end = now
    sp.status = status
    tr.current_span().children.append(sp)


def write_chrome(trace: TraceContext, path: str) -> None:
    import json

    with open(path, "w") as f:
        json.dump(trace.to_chrome(), f)
