"""Unified tracing & telemetry (docs/observability.md, ISSUE 5).

- :mod:`opensim_tpu.obs.trace` — contextvar-carried request span trees,
  Chrome-trace export, instant events for resilience-layer actions.
- :mod:`opensim_tpu.obs.metrics` — fixed-bucket latency histograms fed from
  the same spans, plus the one recording lock and label-value escaping.
- :mod:`opensim_tpu.obs.recorder` — the flight recorder behind
  ``GET /api/debug/requests``.

Import-light on purpose: stdlib only, imported from the engine hot path.
"""

from .trace import (  # noqa: F401
    PHASES,
    Span,
    TraceContext,
    current_span,
    current_trace,
    enabled,
    event,
    new_request_id,
    record_span,
    sanitize_request_id,
    span,
    start_trace,
    trace_scope,
    write_chrome,
)
from .metrics import RECORDER, escape_label_value  # noqa: F401
from .recorder import FLIGHT_RECORDER, FlightRecorder  # noqa: F401
