// C++ serial scheduling baseline — the measured stand-in for the Go
// reference's constant factor (VERDICT r4 #2).
//
// This is the SAME object-at-a-time pipeline as tools/serial_baseline.py,
// which in turn mirrors the reference's vendored serial loop
// (simulator.go:309-348 driving generic_scheduler.go:131-180 with kube's
// incremental NodeInfo / PreFilter-count-map design): for each pod, filter
// every node with hash-map lookups over label/taint/resource strings,
// score the feasible set with the registry.go:119-132 plugin weights, bind
// the lowest-index best. No tensors, no vectorization, no precomputed
// match tables beyond what kube itself memoizes (PreFilter state keyed by
// term signature). Compiled with -O3 this is a defensible measurement of
// what a compiled serial implementation (i.e. the Go baseline) costs on
// the same workloads — BASELINE_MEASURED.json stores it as
// impl: "c++-serial".
//
// Placement parity with tools/serial_baseline.py is exact (same double
// arithmetic in the same insertion order) and asserted by
// tests/test_serial_baseline.py. The input byte format is produced by
// opensim_tpu/native/serial.py:marshal().

#include <algorithm>
#include <array>
#include <cctype>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <tuple>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr double NONZERO_CPU = 0.1;
constexpr double NONZERO_MEM = 200.0 * 1024 * 1024;
constexpr double W_BALANCED = 1.0;
constexpr double W_LEAST = 1.0;
constexpr double W_NODE_AFFINITY = 1.0;
constexpr double W_TAINT = 1.0;
constexpr double W_INTERPOD = 1.0;
constexpr double W_SPREAD = 2.0;
constexpr double W_SHARE = 2.0;
constexpr double W_LOCAL = 1.0;
constexpr double W_AVOID = 10000.0;

const std::string HOSTNAME_KEY = "kubernetes.io/hostname";
const std::string ZONE_KEY = "topology.kubernetes.io/zone";

// ---------------------------------------------------------------------------
// buffer reader
// ---------------------------------------------------------------------------

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) { fail = true; return false; }
    return true;
  }
  uint8_t u8() { if (!need(1)) return 0; return *p++; }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v; std::memcpy(&v, p, 4); p += 4; return v;
  }
  double f64() {
    if (!need(8)) return 0;
    double v; std::memcpy(&v, p, 8); p += 8; return v;
  }
  std::string str() {
    uint32_t n = u32();
    if (!need(n)) return {};
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

using StrMap = std::unordered_map<std::string, std::string>;
using ResMap = std::unordered_map<std::string, double>;

// insertion-ordered map<string,double> — mirrors python dict semantics so
// float accumulation happens in the same order as the python baseline
struct OrderedCounts {
  std::vector<std::pair<std::string, double>> items;
  std::unordered_map<std::string, size_t> index;

  double get(const std::string& k) const {
    auto it = index.find(k);
    return it == index.end() ? 0.0 : items[it->second].second;
  }
  void add(const std::string& k, double w) {
    auto it = index.find(k);
    if (it == index.end()) {
      index.emplace(k, items.size());
      items.emplace_back(k, w);
    } else {
      items[it->second].second += w;
    }
  }
  bool empty() const { return items.empty(); }
};

// ---------------------------------------------------------------------------
// parsed object model
// ---------------------------------------------------------------------------

struct Expr {  // label / node selector expression
  std::string key;
  uint8_t op;  // 0 In 1 NotIn 2 Exists 3 DoesNotExist 4 Gt 5 Lt
  std::vector<std::string> values;
};

struct Selector {
  bool present = false;
  std::vector<std::pair<std::string, std::string>> match_labels;
  std::vector<Expr> exprs;
};

struct NodeTerm {
  std::vector<Expr> exprs;
  std::vector<Expr> fields;
};

struct Toleration {
  std::string key;
  uint8_t op;  // 1 Exists, 0 Equal/empty, 2 other (never tolerates)
  std::string value;
  std::string effect;
};

struct Taint {
  std::string key, value, effect;
};

struct HostPort {
  std::string proto, ip;
  uint32_t port;
};

struct PodTerm {  // inter-pod affinity term
  std::string sig;
  std::vector<std::string> namespaces;
  Selector selector;
  std::string topo;
  double weight = 0;  // unset for synthetic spread terms (never read)
};

struct SpreadC {
  std::string sig;
  std::string key;
  double skew;
  bool hard;
  Selector selector;
};

struct DevVol {
  double size;
  uint8_t media;  // 0 SSD 1 HDD
};

struct Template {
  std::string ns;
  StrMap labels;
  ResMap req;
  std::vector<std::pair<std::string, std::string>> node_selector;
  bool has_req_aff = false;
  std::vector<NodeTerm> req_aff;
  std::vector<std::pair<double, NodeTerm>> pref_aff;
  std::vector<Toleration> tols;
  std::vector<HostPort> ports;
  std::vector<PodTerm> aff_req, anti_req, aff_pref, anti_pref;
  std::vector<SpreadC> spread;
  bool has_default_spread = false;
  Selector owner_sel;
  std::string sig_host, sig_zone;
  double gpu_mem = 0;
  uint32_t gpu_cnt = 0;
  double lvm = 0;
  std::vector<DevVol> dev_vols;
  bool has_ctrl = false;
  std::string ctrl_kind, ctrl_uid;
};

struct NodeInfo {
  std::string name;
  int idx;
  StrMap labels;
  ResMap alloc;
  std::vector<Taint> taints;
  bool unschedulable = false;
  ResMap used;
  double nz_cpu = 0, nz_mem = 0;
  std::vector<HostPort> ports;  // of bound pods
  std::vector<double> gpu_free;
  bool has_dev = false;
  std::vector<std::array<double, 2>> vgs;               // [free, cap]
  std::vector<std::tuple<double, uint8_t, double>> devs;  // free, media, cap
  std::set<std::pair<std::string, std::string>> avoid;
  bool prefer_taints = false;
  double alloc_cpu = 0, alloc_mem = 0;
};

// ---------------------------------------------------------------------------
// matching helpers (mirror opensim_tpu/models/selectors.py)
// ---------------------------------------------------------------------------

bool int_parse(const std::string& s, long long* out) {
  // python int(str): optional surrounding whitespace, optional sign, digits
  size_t i = 0, n = s.size();
  while (i < n && std::isspace(static_cast<unsigned char>(s[i]))) i++;
  size_t j = n;
  while (j > i && std::isspace(static_cast<unsigned char>(s[j - 1]))) j--;
  if (i >= j) return false;
  size_t k = i;
  if (s[k] == '+' || s[k] == '-') k++;
  if (k >= j) return false;
  for (size_t m = k; m < j; m++)
    if (!std::isdigit(static_cast<unsigned char>(s[m]))) return false;
  errno = 0;
  *out = std::strtoll(s.c_str() + i, nullptr, 10);
  return errno == 0;
}

bool match_expr(const Expr& e, const StrMap& labels) {
  auto it = labels.find(e.key);
  bool present = it != labels.end();
  switch (e.op) {
    case 0:  // In
      return present && std::find(e.values.begin(), e.values.end(), it->second) != e.values.end();
    case 1:  // NotIn
      return !present || std::find(e.values.begin(), e.values.end(), it->second) == e.values.end();
    case 2: return present;
    case 3: return !present;
    case 4: case 5: {  // Gt / Lt
      if (!present || e.values.size() != 1) return false;
      long long nv, sv;
      if (!int_parse(it->second, &nv) || !int_parse(e.values[0], &sv)) return false;
      return e.op == 4 ? nv > sv : nv < sv;
    }
  }
  return false;
}

bool match_selector(const Selector& sel, const StrMap& labels) {
  if (!sel.present) return false;  // nil selector matches nothing
  for (const auto& kv : sel.match_labels) {
    auto it = labels.find(kv.first);
    if (it == labels.end() || it->second != kv.second) return false;
  }
  for (const auto& e : sel.exprs)
    if (!match_expr(e, labels)) return false;
  return true;
}

bool match_node_term(const NodeTerm& t, const NodeInfo& ni) {
  if (t.exprs.empty() && t.fields.empty()) return false;  // empty term: no match
  for (const auto& e : t.exprs)
    if (!match_expr(e, ni.labels)) return false;
  if (!t.fields.empty()) {
    StrMap fields{{"metadata.name", ni.name}};
    for (const auto& e : t.fields) {
      if (e.key != "metadata.name") return false;
      if (!match_expr(e, fields)) return false;
    }
  }
  return true;
}

bool node_affinity_ok(const Template& t, const NodeInfo& ni) {
  for (const auto& kv : t.node_selector) {
    auto it = ni.labels.find(kv.first);
    if (it == ni.labels.end() || it->second != kv.second) return false;
  }
  if (t.has_req_aff) {
    bool any = false;
    for (const auto& term : t.req_aff)
      if (match_node_term(term, ni)) { any = true; break; }
    if (!any) return false;
  }
  return true;
}

bool tol_tolerates(const Toleration& tol, const Taint& taint) {
  if (!tol.effect.empty() && tol.effect != taint.effect) return false;
  if (!tol.key.empty() && tol.key != taint.key) return false;
  if (tol.key.empty() && tol.op != 1) return false;
  if (tol.op == 1) return true;         // Exists
  if (tol.op == 0) return tol.value == taint.value;  // Equal / ""
  return false;
}

bool has_untolerated_taint(const std::vector<Taint>& taints,
                           const std::vector<Toleration>& tols) {
  for (const auto& taint : taints) {
    if (taint.effect != "NoSchedule" && taint.effect != "NoExecute") continue;
    bool ok = false;
    for (const auto& tol : tols)
      if (tol_tolerates(tol, taint)) { ok = true; break; }
    if (!ok) return true;
  }
  return false;
}

bool term_matches_pod(const PodTerm& term, const Template& pod) {
  if (std::find(term.namespaces.begin(), term.namespaces.end(), pod.ns) ==
      term.namespaces.end())
    return false;
  return match_selector(term.selector, pod.labels);
}

// ---------------------------------------------------------------------------
// PreFilter state (mirror CarrierCounts / MatchCounts)
// ---------------------------------------------------------------------------

struct CarrierEntry {
  PodTerm term;          // matcher (namespaces + selector); weight unused
  OrderedCounts counts;  // topo value -> weight
};

struct Carrier {
  std::vector<CarrierEntry> entries;  // insertion-ordered
  std::unordered_map<std::string, size_t> index;

  void add(const PodTerm& term, const StrMap& node_labels, double w) {
    auto vi = node_labels.find(term.topo);
    if (vi == node_labels.end()) return;
    auto it = index.find(term.sig);
    size_t k;
    if (it == index.end()) {
      k = entries.size();
      index.emplace(term.sig, k);
      entries.push_back({term, {}});
    } else {
      k = it->second;
    }
    entries[k].counts.add(vi->second, w);
  }
};

struct MatchEntry {
  std::vector<PodTerm> terms;
  std::vector<OrderedCounts> maps;
  double total = 0;
};

struct Scheduler;

struct MatchCounts {
  Scheduler* sched;
  std::vector<std::unique_ptr<MatchEntry>> entries;  // stable addresses
  std::unordered_map<std::string, size_t> index;

  MatchEntry* get(const std::vector<PodTerm>& terms);
  void on_bind(const Template& pod, const NodeInfo& ni);
};

struct Scheduler {
  std::vector<NodeInfo> nodes;
  std::unordered_map<std::string, int> by_name;
  std::vector<std::pair<const Template*, const NodeInfo*>> bound;
  Carrier exist_anti;
  Carrier sym_pref;
  MatchCounts match_counts;
  std::unordered_map<std::string, size_t> key_val_count;  // key -> |values|
  bool any_prefer_taints = false, any_avoid = false;
  // eligible-domain cache: (template idx, topo key) -> set of values
  std::map<std::pair<int, std::string>, std::set<std::string>> elig_cache;

  const std::set<std::string>& eligible_vals(int ti, const Template& t,
                                             const std::string& key) {
    auto k = std::make_pair(ti, key);
    auto it = elig_cache.find(k);
    if (it != elig_cache.end()) return it->second;
    std::set<std::string> vals;
    for (const auto& ni : nodes) {
      auto li = ni.labels.find(key);
      if (li == ni.labels.end()) continue;
      if (node_affinity_ok(t, ni)) vals.insert(li->second);
    }
    return elig_cache.emplace(k, std::move(vals)).first->second;
  }
};

MatchEntry* MatchCounts::get(const std::vector<PodTerm>& terms) {
  std::string sigset;
  for (const auto& t : terms) {
    sigset += t.sig;
    sigset += '\x02';
  }
  auto it = index.find(sigset);
  if (it != index.end()) return entries[it->second].get();
  auto e = std::make_unique<MatchEntry>();
  e->terms = terms;
  e->maps.resize(terms.size());
  for (const auto& bq : sched->bound) {
    const Template& q = *bq.first;
    bool all = true;
    for (const auto& t : terms)
      if (!term_matches_pod(t, q)) { all = false; break; }
    if (!all) continue;
    for (size_t k = 0; k < terms.size(); k++) {
      auto vi = bq.second->labels.find(terms[k].topo);
      if (vi != bq.second->labels.end()) {
        e->maps[k].add(vi->second, 1.0);
        e->total += 1.0;
      }
    }
  }
  index.emplace(std::move(sigset), entries.size());
  entries.push_back(std::move(e));
  return entries.back().get();
}

void MatchCounts::on_bind(const Template& pod, const NodeInfo& ni) {
  for (auto& ep : entries) {
    MatchEntry& e = *ep;
    bool all = true;
    for (const auto& t : e.terms)
      if (!term_matches_pod(t, pod)) { all = false; break; }
    if (!all) continue;
    for (size_t k = 0; k < e.terms.size(); k++) {
      auto vi = ni.labels.find(e.terms[k].topo);
      if (vi != ni.labels.end()) {
        e.maps[k].add(vi->second, 1.0);
        e.total += 1.0;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// parsing
// ---------------------------------------------------------------------------

Expr read_expr(Reader& r) {
  Expr e;
  e.key = r.str();
  e.op = r.u8();
  uint32_t nv = r.u32();
  e.values.reserve(nv);
  for (uint32_t i = 0; i < nv; i++) e.values.push_back(r.str());
  return e;
}

Selector read_selector(Reader& r) {
  Selector s;
  if (!r.u8()) return s;
  s.present = true;
  uint32_t nl = r.u32();
  for (uint32_t i = 0; i < nl; i++) {
    std::string k = r.str(), v = r.str();
    s.match_labels.emplace_back(std::move(k), std::move(v));
  }
  uint32_t ne = r.u32();
  for (uint32_t i = 0; i < ne; i++) s.exprs.push_back(read_expr(r));
  return s;
}

NodeTerm read_node_term(Reader& r) {
  NodeTerm t;
  uint32_t ne = r.u32();
  for (uint32_t i = 0; i < ne; i++) t.exprs.push_back(read_expr(r));
  uint32_t nf = r.u32();
  for (uint32_t i = 0; i < nf; i++) t.fields.push_back(read_expr(r));
  return t;
}

std::vector<PodTerm> read_terms(Reader& r) {
  uint32_t n = r.u32();
  std::vector<PodTerm> out;
  out.reserve(n);
  for (uint32_t i = 0; i < n; i++) {
    PodTerm t;
    t.sig = r.str();
    uint32_t nn = r.u32();
    for (uint32_t k = 0; k < nn; k++) t.namespaces.push_back(r.str());
    t.selector = read_selector(r);
    t.topo = r.str();
    t.weight = r.f64();
    out.push_back(std::move(t));
  }
  return out;
}

StrMap read_strmap(Reader& r) {
  StrMap m;
  uint32_t n = r.u32();
  for (uint32_t i = 0; i < n; i++) {
    std::string k = r.str(), v = r.str();
    m.emplace(std::move(k), std::move(v));
  }
  return m;
}

NodeInfo read_node(Reader& r, int idx) {
  NodeInfo ni;
  ni.idx = idx;
  ni.name = r.str();
  ni.labels = read_strmap(r);
  uint32_t na = r.u32();
  for (uint32_t i = 0; i < na; i++) {
    std::string k = r.str();
    ni.alloc[k] = r.f64();
  }
  ni.alloc_cpu = ni.alloc.count("cpu") ? ni.alloc["cpu"] : 0.0;
  ni.alloc_mem = ni.alloc.count("memory") ? ni.alloc["memory"] : 0.0;
  uint32_t nt = r.u32();
  for (uint32_t i = 0; i < nt; i++) {
    Taint t;
    t.key = r.str();
    t.value = r.str();
    t.effect = r.str();
    if (t.effect == "PreferNoSchedule") ni.prefer_taints = true;
    ni.taints.push_back(std::move(t));
  }
  ni.unschedulable = r.u8();
  double gpu_total = r.f64();
  uint32_t gpu_cnt = r.u32();
  if (gpu_cnt > 0 && gpu_total > 0) {
    ni.gpu_free.assign(gpu_cnt, gpu_total / gpu_cnt);
    ni.has_dev = true;
  }
  uint32_t nvg = r.u32();
  for (uint32_t i = 0; i < nvg; i++) {
    double cap = r.f64();
    ni.vgs.push_back({cap, cap});
  }
  uint32_t nd = r.u32();
  for (uint32_t i = 0; i < nd; i++) {
    double cap = r.f64();
    uint8_t media = r.u8();
    ni.devs.emplace_back(cap, media, cap);
  }
  uint32_t nav = r.u32();
  for (uint32_t i = 0; i < nav; i++) {
    std::string kind = r.str(), uid = r.str();
    ni.avoid.emplace(std::move(kind), std::move(uid));
  }
  return ni;
}

Template read_template(Reader& r) {
  Template t;
  t.ns = r.str();
  t.labels = read_strmap(r);
  uint32_t nr = r.u32();
  for (uint32_t i = 0; i < nr; i++) {
    std::string k = r.str();
    t.req[k] = r.f64();
  }
  uint32_t ns = r.u32();
  for (uint32_t i = 0; i < ns; i++) {
    std::string k = r.str(), v = r.str();
    t.node_selector.emplace_back(std::move(k), std::move(v));
  }
  t.has_req_aff = r.u8();
  if (t.has_req_aff) {
    uint32_t n = r.u32();
    for (uint32_t i = 0; i < n; i++) t.req_aff.push_back(read_node_term(r));
  }
  uint32_t np = r.u32();
  for (uint32_t i = 0; i < np; i++) {
    double w = r.f64();
    t.pref_aff.emplace_back(w, read_node_term(r));
  }
  uint32_t ntl = r.u32();
  for (uint32_t i = 0; i < ntl; i++) {
    Toleration tol;
    tol.key = r.str();
    tol.op = r.u8();
    tol.value = r.str();
    tol.effect = r.str();
    t.tols.push_back(std::move(tol));
  }
  uint32_t nport = r.u32();
  for (uint32_t i = 0; i < nport; i++) {
    HostPort p;
    p.proto = r.str();
    p.ip = r.str();
    p.port = r.u32();
    t.ports.push_back(std::move(p));
  }
  t.aff_req = read_terms(r);
  t.anti_req = read_terms(r);
  t.aff_pref = read_terms(r);
  t.anti_pref = read_terms(r);
  uint32_t nsp = r.u32();
  for (uint32_t i = 0; i < nsp; i++) {
    SpreadC c;
    c.sig = r.str();
    c.key = r.str();
    c.skew = r.f64();
    c.hard = r.u8();
    c.selector = read_selector(r);
    t.spread.push_back(std::move(c));
  }
  t.has_default_spread = r.u8();
  if (t.has_default_spread) {
    t.owner_sel = read_selector(r);
    t.sig_host = r.str();
    t.sig_zone = r.str();
  }
  t.gpu_mem = r.f64();
  t.gpu_cnt = r.u32();
  t.lvm = r.f64();
  uint32_t ndv = r.u32();
  for (uint32_t i = 0; i < ndv; i++) {
    DevVol v;
    v.size = r.f64();
    v.media = r.u8();
    t.dev_vols.push_back(v);
  }
  t.has_ctrl = r.u8();
  if (t.has_ctrl) {
    t.ctrl_kind = r.str();
    t.ctrl_uid = r.str();
  }
  return t;
}

// ---------------------------------------------------------------------------
// per-pod pipeline (mirror SerialScheduler.schedule_one / bind)
// ---------------------------------------------------------------------------

ResMap alloc_view(const NodeInfo& ni) {
  if (!ni.has_dev) return ni.alloc;
  ResMap a = ni.alloc;
  double cnt = 0;
  for (double f : ni.gpu_free)
    if (f > 0) cnt += 1;
  a["alibabacloud.com/gpu-count"] = cnt;
  return a;
}

bool fit_ok(const ResMap& req, const NodeInfo& ni) {
  // alloc_view only differs on gpu-count; avoid the map copy in the loop
  for (const auto& kv : req) {
    if (kv.second <= 0) continue;
    double alloc;
    if (ni.has_dev && kv.first == "alibabacloud.com/gpu-count") {
      alloc = 0;
      for (double f : ni.gpu_free)
        if (f > 0) alloc += 1;
    } else {
      auto it = ni.alloc.find(kv.first);
      alloc = it == ni.alloc.end() ? 0.0 : it->second;
    }
    auto ui = ni.used.find(kv.first);
    double used = ui == ni.used.end() ? 0.0 : ui->second;
    if (used + kv.second > alloc) return false;
  }
  return true;
}

bool ports_ok(const std::vector<HostPort>& mine, const NodeInfo& ni) {
  for (const auto& theirs : ni.ports) {
    for (const auto& m : mine) {
      if (m.proto != theirs.proto || m.port != theirs.port) continue;
      std::string ia = (m.ip.empty() || m.ip == "0.0.0.0") ? "" : m.ip;
      std::string ib = (theirs.ip.empty() || theirs.ip == "0.0.0.0") ? "" : theirs.ip;
      if (ia == ib || ia.empty() || ib.empty()) return false;
    }
  }
  return true;
}

bool gpu_ok(double mem, uint32_t cnt, const NodeInfo& ni) {
  if (mem <= 0) return true;
  if (cnt == 0) return false;
  long long fits = 0;
  for (double f : ni.gpu_free) fits += static_cast<long long>(f / mem);
  return fits >= static_cast<long long>(cnt);
}

// sorted dev volume view per media (size ascending, python sorted() stable)
std::vector<double> sorted_sizes(const std::vector<DevVol>& vols, uint8_t media) {
  std::vector<double> out;
  for (const auto& v : vols)
    if (v.media == media) out.push_back(v.size);
  std::stable_sort(out.begin(), out.end());
  return out;
}

bool local_ok(double lvm, const std::vector<DevVol>& vols, const NodeInfo& ni) {
  if (lvm > 0) {
    bool any = false;
    for (const auto& vg : ni.vgs)
      if (vg[0] >= lvm) { any = true; break; }
    if (!any) return false;
  }
  std::set<size_t> taken;
  for (uint8_t media : {uint8_t(0), uint8_t(1)}) {
    for (double size : sorted_sizes(vols, media)) {
      bool found = false;
      size_t pick = 0;
      double pick_cap = 0;
      for (size_t i = 0; i < ni.devs.size(); i++) {
        double free = std::get<0>(ni.devs[i]);
        uint8_t m = std::get<1>(ni.devs[i]);
        double cap = std::get<2>(ni.devs[i]);
        if (taken.count(i) || m != media || free < size || free <= 0) continue;
        if (!found || cap < pick_cap) { found = true; pick = i; pick_cap = cap; }
      }
      if (!found) return false;
      taken.insert(pick);
    }
  }
  return true;
}

struct Pipeline {
  Scheduler sched;
  std::vector<Template> templates;

  int schedule_one(int ti) {
    const Template& pod = templates[ti];
    ResMap req = pod.req;
    req["pods"] = (req.count("pods") ? req["pods"] : 0.0) + 1;

    // PreFilter
    std::vector<std::pair<const PodTerm*, MatchEntry*>> anti_entries;
    for (const auto& t : pod.anti_req)
      anti_entries.emplace_back(&t, sched.match_counts.get({t}));
    MatchEntry* aff_entry =
        pod.aff_req.empty() ? nullptr : sched.match_counts.get(pod.aff_req);

    // existing pods' anti terms matching this pod
    std::vector<std::pair<const std::string*, const OrderedCounts*>> exist_hits;
    for (const auto& e : sched.exist_anti.entries)
      if (!e.counts.empty() && term_matches_pod(e.term, pod))
        exist_hits.emplace_back(&e.term.topo, &e.counts);

    // spread constraints (explicit, else defaults from the owner selector)
    struct SpreadPre {
      const std::string* key;
      const OrderedCounts* cnts;
      bool has_min;
      double min_cnt;
      double skew;
      double self_match;
    };
    std::vector<SpreadPre> hard_pre;
    std::vector<std::tuple<const std::string*, const OrderedCounts*, double, double>> soft_pre;
    auto add_soft = [&](const std::string& key, const std::string& sig,
                        const Selector& sel, double skew) {
      PodTerm t;
      t.sig = sig;
      t.namespaces = {pod.ns};
      t.selector = sel;
      t.topo = key;
      MatchEntry* e = sched.match_counts.get({t});
      size_t size = sched.key_val_count.count(key) ? sched.key_val_count[key] : 0;
      soft_pre.emplace_back(&e->terms[0].topo, &e->maps[0], std::log(size + 2.0), skew);
    };
    auto add_hard = [&](const std::string& key, const std::string& sig,
                        const Selector& sel, double skew) {
      PodTerm t;
      t.sig = sig;
      t.namespaces = {pod.ns};
      t.selector = sel;
      t.topo = key;
      MatchEntry* e = sched.match_counts.get({t});
      const auto& elig = sched.eligible_vals(ti, pod, key);
      bool has_min = false;
      double min_cnt = 0;
      for (const auto& v : elig) {
        double c = e->maps[0].get(v);
        if (!has_min || c < min_cnt) { has_min = true; min_cnt = c; }
      }
      double self_match =
          sel.present && match_selector(sel, pod.labels) ? 1.0 : 0.0;
      hard_pre.push_back({&e->terms[0].topo, &e->maps[0], has_min, min_cnt, skew, self_match});
    };
    if (!pod.spread.empty()) {
      for (const auto& c : pod.spread) {
        if (c.hard)
          add_hard(c.key, c.sig, c.selector, c.skew);
        else
          add_soft(c.key, c.sig, c.selector, c.skew);
      }
    } else if (pod.has_default_spread) {
      add_soft(HOSTNAME_KEY, pod.sig_host, pod.owner_sel, 3.0);
      add_soft(ZONE_KEY, pod.sig_zone, pod.owner_sel, 5.0);
    }

    // -- Filter
    std::vector<NodeInfo*> feasible;
    for (auto& ni : sched.nodes) {
      if (ni.unschedulable) continue;
      if (!node_affinity_ok(pod, ni)) continue;
      if (!ni.taints.empty() && has_untolerated_taint(ni.taints, pod.tols)) continue;
      if (!fit_ok(req, ni)) continue;
      if (!pod.ports.empty() && !ports_ok(pod.ports, ni)) continue;
      bool ok = true;
      for (const auto& sp : hard_pre) {
        auto vi = ni.labels.find(*sp.key);
        if (vi == ni.labels.end() || !sp.has_min) { ok = false; break; }
        if (sp.cnts->get(vi->second) + sp.self_match - sp.min_cnt > sp.skew) {
          ok = false;
          break;
        }
      }
      if (!ok) continue;
      for (const auto& eh : exist_hits) {
        auto vi = ni.labels.find(*eh.first);
        if (vi != ni.labels.end() && eh.second->get(vi->second) > 0) { ok = false; break; }
      }
      if (!ok) continue;
      for (const auto& ae : anti_entries) {
        auto vi = ni.labels.find(ae.first->topo);
        if (vi != ni.labels.end() && ae.second->maps[0].get(vi->second) > 0) {
          ok = false;
          break;
        }
      }
      if (!ok) continue;
      if (!pod.aff_req.empty()) {
        bool labels_ok = true;
        for (const auto& t : pod.aff_req)
          if (!ni.labels.count(t.topo)) { labels_ok = false; break; }
        bool per_term = labels_ok;
        if (per_term) {
          for (size_t k = 0; k < pod.aff_req.size(); k++) {
            auto vi = ni.labels.find(pod.aff_req[k].topo);
            if (aff_entry->maps[k].get(vi->second) <= 0) { per_term = false; break; }
          }
        }
        if (!per_term) {
          bool bootstrap = labels_ok && aff_entry->total == 0.0;
          if (bootstrap) {
            for (const auto& t : pod.aff_req)
              if (!term_matches_pod(t, pod)) { bootstrap = false; break; }
          }
          if (!bootstrap) continue;
        }
      }
      if (pod.gpu_mem > 0 && !gpu_ok(pod.gpu_mem, pod.gpu_cnt, ni)) continue;
      if ((pod.lvm > 0 || !pod.dev_vols.empty()) &&
          !local_ok(pod.lvm, pod.dev_vols, ni))
        continue;
      feasible.push_back(&ni);
    }
    if (feasible.empty()) return -1;

    // -- Score
    size_t F = feasible.size();
    std::vector<double> scores(F, 0.0);
    double cpu_req = req.count("cpu") && req["cpu"] != 0.0 ? req["cpu"] : NONZERO_CPU;
    double mem_req = req.count("memory") && req["memory"] != 0.0 ? req["memory"] : NONZERO_MEM;
    for (size_t i = 0; i < F; i++) {
      const NodeInfo& ni = *feasible[i];
      double ac = ni.alloc_cpu, am = ni.alloc_mem;
      double rc = ni.nz_cpu + cpu_req, rm = ni.nz_mem + mem_req;
      double ls = (ac == 0 || rc > ac) ? 0.0 : (ac - rc) * 100.0 / ac;
      double ms = (am == 0 || rm > am) ? 0.0 : (am - rm) * 100.0 / am;
      scores[i] += W_LEAST * (ls + ms) / 2.0;
      double cf = ac ? rc / ac : 0.0;
      double mf = am ? rm / am : 0.0;
      double bal = (cf >= 1 || mf >= 1) ? 0.0 : (1.0 - std::fabs(cf - mf)) * 100.0;
      scores[i] += W_BALANCED * bal;
    }

    if (!pod.pref_aff.empty()) {
      std::vector<double> raw(F, 0.0);
      double mx = 0.0;
      for (size_t i = 0; i < F; i++) {
        long long total = 0;
        for (const auto& wt : pod.pref_aff)
          if (match_node_term(wt.second, *feasible[i]))
            total += static_cast<long long>(wt.first);
        raw[i] = static_cast<double>(total);
        if (raw[i] > mx) mx = raw[i];
      }
      for (size_t i = 0; i < F; i++)
        scores[i] += W_NODE_AFFINITY * (mx > 0 ? raw[i] * 100.0 / mx : raw[i]);
    }

    if (sched.any_prefer_taints) {
      std::vector<double> raw(F, 0.0);
      double mx = 0.0;
      for (size_t i = 0; i < F; i++) {
        const NodeInfo& ni = *feasible[i];
        if (ni.prefer_taints) {
          long long cnt = 0;
          for (const auto& taint : ni.taints) {
            if (taint.effect != "PreferNoSchedule") continue;
            bool ok = false;
            for (const auto& tol : pod.tols)
              if (tol_tolerates(tol, taint)) { ok = true; break; }
            if (!ok) cnt++;
          }
          raw[i] = static_cast<double>(cnt);
        }
        if (raw[i] > mx) mx = raw[i];
      }
      for (size_t i = 0; i < F; i++)
        scores[i] += W_TAINT * (mx > 0 ? 100.0 - raw[i] * 100.0 / mx : 100.0);
    }

    interpod_score(pod, feasible, scores);
    spread_score(soft_pre, feasible, scores);
    share_score(req, pod, feasible, scores);
    if (pod.lvm > 0 || !pod.dev_vols.empty()) local_score(pod, feasible, scores);
    if (sched.any_avoid) {
      for (size_t i = 0; i < F; i++) {
        bool avoided = pod.has_ctrl &&
                       feasible[i]->avoid.count({pod.ctrl_kind, pod.ctrl_uid});
        scores[i] += W_AVOID * (avoided ? 0.0 : 100.0);
      }
    }

    size_t best = 0;
    for (size_t i = 1; i < F; i++)
      if (scores[i] > scores[best]) best = i;
    return feasible[best]->idx;
  }

  void interpod_score(const Template& pod, const std::vector<NodeInfo*>& feasible,
                      std::vector<double>& scores) {
    // incoming preferred terms + symmetric carried terms
    struct Part {
      double w;
      const std::string* key;
      const OrderedCounts* m;
    };
    std::vector<Part> parts;
    for (const auto& t : pod.aff_pref) {
      MatchEntry* e = sched.match_counts.get({t});
      parts.push_back({t.weight, &e->terms[0].topo, &e->maps[0]});
    }
    for (const auto& t : pod.anti_pref) {
      MatchEntry* e = sched.match_counts.get({t});
      parts.push_back({-t.weight, &e->terms[0].topo, &e->maps[0]});
    }
    std::vector<std::pair<const std::string*, const OrderedCounts*>> sym;
    for (const auto& e : sched.sym_pref.entries)
      if (!e.counts.empty() && term_matches_pod(e.term, pod))
        sym.emplace_back(&e.term.topo, &e.counts);
    if (parts.empty() && sym.empty()) return;
    size_t F = feasible.size();
    std::vector<double> raw(F, 0.0);
    for (size_t i = 0; i < F; i++) {
      const NodeInfo& ni = *feasible[i];
      double s = 0;
      for (const auto& p : parts) {
        auto vi = ni.labels.find(*p.key);
        if (vi != ni.labels.end()) s += p.w * p.m->get(vi->second);
      }
      for (const auto& p : sym) {
        auto vi = ni.labels.find(*p.first);
        if (vi != ni.labels.end()) s += p.second->get(vi->second);
      }
      raw[i] = s;
    }
    double hi = 0.0, lo = 0.0;
    for (double v : raw) {
      if (v > hi) hi = v;
      if (v < lo) lo = v;
    }
    double rng = hi - lo;
    if (rng > 0)
      for (size_t i = 0; i < F; i++)
        scores[i] += W_INTERPOD * 100.0 * (raw[i] - lo) / rng;
  }

  void spread_score(
      const std::vector<std::tuple<const std::string*, const OrderedCounts*, double, double>>& pre,
      const std::vector<NodeInfo*>& feasible, std::vector<double>& scores) {
    if (pre.empty()) return;
    size_t F = feasible.size();
    std::vector<double> raw(F, 0.0);
    std::vector<bool> ignored(F, false);
    for (size_t i = 0; i < F; i++) {
      const NodeInfo& ni = *feasible[i];
      double s = 0;
      bool ig = false;
      for (const auto& p : pre) {
        auto vi = ni.labels.find(*std::get<0>(p));
        if (vi == ni.labels.end()) {
          ig = true;
          continue;
        }
        s += std::get<1>(p)->get(vi->second) * std::get<2>(p) + (std::get<3>(p) - 1.0);
      }
      raw[i] = s;
      ignored[i] = ig;
    }
    bool any = false;
    double mx = 0, mn = 0;
    for (size_t i = 0; i < F; i++) {
      if (ignored[i]) continue;
      if (!any) { mx = mn = raw[i]; any = true; }
      else {
        if (raw[i] > mx) mx = raw[i];
        if (raw[i] < mn) mn = raw[i];
      }
    }
    if (!any) mx = mn = 0;
    for (size_t i = 0; i < F; i++) {
      if (ignored[i]) continue;
      scores[i] += W_SPREAD * (mx <= 0 ? 100.0 : 100.0 * (mx + mn - raw[i]) / mx);
    }
  }

  void share_score(const ResMap& req_with_pods, const Template& pod,
                   const std::vector<NodeInfo*>& feasible,
                   std::vector<double>& scores) {
    // python uses pod.resource_requests() here (no pods+1)
    const ResMap& req = pod.req;
    size_t F = feasible.size();
    std::vector<double> raw(F, 0.0);
    for (size_t i = 0; i < F; i++) {
      const NodeInfo& ni = *feasible[i];
      if (req.empty()) {
        raw[i] = 100.0;
        continue;
      }
      double best = 0;
      // alloc_view only overrides gpu-count on device-bearing nodes (the
      // key always exists there); avoid the per-node map copy python also
      // avoids for the non-GPU case
      for (const auto& kv : ni.alloc) {
        double alloc = kv.second;
        if (ni.has_dev && kv.first == "alibabacloud.com/gpu-count") {
          alloc = 0;
          for (double f : ni.gpu_free)
            if (f > 0) alloc += 1;
        }
        auto ri = req.find(kv.first);
        double pr = ri == req.end() ? 0.0 : ri->second;
        double avail = alloc - pr;
        double share = avail == 0 ? (pr != 0.0 ? 1.0 : 0.0) : pr / avail;
        if (share > best) best = share;
      }
      raw[i] = best * 100.0;
    }
    double hi = raw[0], lo = raw[0];
    for (double v : raw) {
      if (v > hi) hi = v;
      if (v < lo) lo = v;
    }
    double rng = hi - lo;
    if (rng > 0)
      for (size_t i = 0; i < F; i++)
        scores[i] += W_SHARE * (raw[i] - lo) * 100.0 / rng;
    (void)req_with_pods;
  }

  void local_score(const Template& pod, const std::vector<NodeInfo*>& feasible,
                   std::vector<double>& scores) {
    size_t F = feasible.size();
    std::vector<double> raw(F, 0.0);
    for (size_t i = 0; i < F; i++) {
      const NodeInfo& ni = *feasible[i];
      double parts = 0;
      int count = 0;
      if (pod.lvm > 0) {
        bool found = false;
        double best_free = 0, best_cap = 0;
        for (const auto& vg : ni.vgs) {
          if (vg[0] >= pod.lvm && (!found || vg[0] < best_free)) {
            found = true;
            best_free = vg[0];
            best_cap = vg[1];
          }
        }
        if (found) parts += pod.lvm / best_cap;
        count += 1;
      }
      for (uint8_t media : {uint8_t(0), uint8_t(1)}) {
        std::vector<double> sizes;
        for (const auto& v : pod.dev_vols)
          if (v.media == media) sizes.push_back(v.size);
        if (sizes.empty()) continue;
        double size = *std::max_element(sizes.begin(), sizes.end());
        bool found = false;
        double min_cap = 0;
        for (const auto& d : ni.devs) {
          if (std::get<1>(d) != media) continue;
          double free = std::get<0>(d);
          if (free >= size && free > 0) {
            double cap = std::get<2>(d);
            if (!found || cap < min_cap) { found = true; min_cap = cap; }
          }
        }
        if (found) parts += sizes.size() * size / min_cap;
        count += static_cast<int>(sizes.size());
      }
      raw[i] = count ? parts / count * 10.0 : 0.0;
    }
    double hi = raw[0], lo = raw[0];
    for (double v : raw) {
      if (v > hi) hi = v;
      if (v < lo) lo = v;
    }
    double rng = hi - lo;
    if (rng > 0)
      for (size_t i = 0; i < F; i++)
        scores[i] += W_LOCAL * (raw[i] - lo) * 100.0 / rng;
  }

  void bind(int ti, NodeInfo& ni) {
    const Template& pod = templates[ti];
    sched.bound.emplace_back(&pod, &ni);
    for (const auto& kv : pod.req) ni.used[kv.first] += kv.second;
    ni.used["pods"] += 1;
    double c = pod.req.count("cpu") ? pod.req.at("cpu") : 0.0;
    double m = pod.req.count("memory") ? pod.req.at("memory") : 0.0;
    ni.nz_cpu += c != 0.0 ? c : NONZERO_CPU;
    ni.nz_mem += m != 0.0 ? m : NONZERO_MEM;
    for (const auto& p : pod.ports) ni.ports.push_back(p);

    for (const auto& t : pod.anti_req) sched.exist_anti.add(t, ni.labels, 1.0);
    for (const auto& t : pod.aff_pref) sched.sym_pref.add(t, ni.labels, t.weight);
    for (const auto& t : pod.anti_pref) sched.sym_pref.add(t, ni.labels, -t.weight);
    for (const auto& t : pod.aff_req) sched.sym_pref.add(t, ni.labels, 1.0);
    sched.match_counts.on_bind(pod, ni);

    if (pod.gpu_mem > 0 && pod.gpu_cnt > 0 && !ni.gpu_free.empty()) {
      auto& free = ni.gpu_free;
      if (pod.gpu_cnt == 1) {
        bool found = false;
        size_t tight = 0;
        for (size_t i = 0; i < free.size(); i++) {
          if (free[i] < pod.gpu_mem) continue;
          if (!found || free[i] < free[tight]) { found = true; tight = i; }
        }
        if (found) free[tight] -= pod.gpu_mem;
      } else {
        long long left = pod.gpu_cnt;
        for (size_t i = 0; i < free.size() && left > 0; i++) {
          long long take = std::min(static_cast<long long>(free[i] / pod.gpu_mem), left);
          free[i] -= take * pod.gpu_mem;
          left -= take;
        }
      }
    }
    if (pod.lvm > 0) {
      bool found = false;
      size_t pick = 0;
      for (size_t i = 0; i < ni.vgs.size(); i++) {
        if (ni.vgs[i][0] >= pod.lvm && (!found || ni.vgs[i][0] < ni.vgs[pick][0])) {
          found = true;
          pick = i;
        }
      }
      if (found) ni.vgs[pick][0] -= pod.lvm;
    }
    if (!pod.dev_vols.empty()) {
      std::set<size_t> taken;
      for (uint8_t media : {uint8_t(0), uint8_t(1)}) {
        for (double size : sorted_sizes(pod.dev_vols, media)) {
          bool found = false;
          size_t pick = 0;
          double pick_cap = 0;
          for (size_t i = 0; i < ni.devs.size(); i++) {
            double free = std::get<0>(ni.devs[i]);
            uint8_t dm = std::get<1>(ni.devs[i]);
            double cap = std::get<2>(ni.devs[i]);
            if (taken.count(i) || dm != media || free < size || free <= 0) continue;
            if (!found || cap < pick_cap) { found = true; pick = i; pick_cap = cap; }
          }
          if (found) {
            taken.insert(pick);
            std::get<0>(ni.devs[pick]) = 0.0;
          }
        }
      }
    }
  }
};

}  // namespace

extern "C" {

int64_t opensim_serial_abi() { return 1; }

int opensim_serial_run(const char* buf, int64_t len, int32_t* chosen,
                       double* schedule_s) {
  Reader r{reinterpret_cast<const uint8_t*>(buf),
           reinterpret_cast<const uint8_t*>(buf) + len};
  if (r.u32() != 0x53524C31) return 2;
  if (r.u32() != 1) return 3;

  Pipeline pl;
  pl.sched.match_counts.sched = &pl.sched;
  uint32_t N = r.u32();
  pl.sched.nodes.reserve(N);
  for (uint32_t i = 0; i < N; i++) pl.sched.nodes.push_back(read_node(r, i));
  uint32_t T = r.u32();
  pl.templates.reserve(T);
  for (uint32_t i = 0; i < T; i++) pl.templates.push_back(read_template(r));
  uint32_t P = r.u32();
  struct StreamPod {
    uint32_t ti;
    bool forced;
    std::string node_name;
  };
  std::vector<StreamPod> stream;
  stream.reserve(P);
  for (uint32_t i = 0; i < P; i++) {
    StreamPod sp;
    sp.ti = r.u32();
    sp.forced = r.u8();
    sp.node_name = r.str();
    stream.push_back(std::move(sp));
  }
  if (r.fail) return 4;
  for (const auto& sp : stream)
    if (sp.ti >= T) return 5;

  for (auto& ni : pl.sched.nodes) {
    pl.sched.by_name[ni.name] = ni.idx;
    if (ni.prefer_taints) pl.sched.any_prefer_taints = true;
    if (!ni.avoid.empty()) pl.sched.any_avoid = true;
  }
  {
    std::unordered_map<std::string, std::unordered_set<std::string>> kv;
    for (const auto& ni : pl.sched.nodes)
      for (const auto& l : ni.labels) kv[l.first].insert(l.second);
    for (const auto& e : kv) pl.sched.key_val_count[e.first] = e.second.size();
  }

  struct timespec t0, t1;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  for (uint32_t i = 0; i < P; i++) {
    const StreamPod& sp = stream[i];
    if (sp.forced) {
      auto it = pl.sched.by_name.find(sp.node_name);
      if (it == pl.sched.by_name.end()) {
        chosen[i] = -1;
      } else {
        chosen[i] = it->second;
        pl.bind(sp.ti, pl.sched.nodes[it->second]);
      }
      continue;
    }
    int c = pl.schedule_one(sp.ti);
    chosen[i] = c;
    if (c >= 0) pl.bind(sp.ti, pl.sched.nodes[c]);
  }
  clock_gettime(CLOCK_MONOTONIC, &t1);
  *schedule_s = (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) * 1e-9;
  return 0;
}

}  // extern "C"
