"""C++ serial baseline — marshalling + bindings.

VERDICT r4 #2: BENCH.md's python→Go conversion bracket was a *model*; this
module replaces it with a *measurement*. ``serial_engine.cc`` is the same
object-at-a-time NodeInfo/PreFilter pipeline as ``tools/serial_baseline.py``
— per pod: filter every node, score the feasible set, bind the best — built
on hash-maps over strings and incremental per-node aggregates, the memory
model of the reference's Go scheduler (vendored
``generic_scheduler.go:131-180``), never the tensor encodings. Compiled
C++ with that design is a defensible stand-in for the Go constant factor,
so ``impl: "c++-serial"`` rows in BASELINE_MEASURED.json anchor the true
vs-Go speedup claims.

The marshaller serializes the object model (nodes + deduped pod templates +
the pod stream) into one byte buffer; the C++ side parses it (untimed) and
times only the scheduling loop, exactly like the python tool's
``schedule_s``. Placement parity with the python serial baseline is
asserted by tests/test_serial_baseline.py.
"""

from __future__ import annotations

import ctypes
import json
import struct
import time
from pathlib import Path
from typing import List, Optional, Tuple

from ..models.objects import Pod
from ..models.quantity import parse_quantity

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "serial_engine.cc"
_CXX_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC"]

_LABEL_OPS = {"In": 0, "NotIn": 1, "Exists": 2, "DoesNotExist": 3}
_NODE_OPS = {**_LABEL_OPS, "Gt": 4, "Lt": 5}

HOSTNAME = "kubernetes.io/hostname"
ZONE = "topology.kubernetes.io/zone"

#: wire-format tag ("SRL1", version 1) — machine-readable anchors the
#: OSL1604 abi-parity pass checks against serial_engine.cc's header guards
WIRE_MAGIC = 0x53524C31
WIRE_VERSION = 1


class _Buf:
    def __init__(self):
        self.parts: List[bytes] = []

    def u8(self, v: int):
        self.parts.append(struct.pack("<B", v))

    def u32(self, v: int):
        self.parts.append(struct.pack("<I", v))

    def f64(self, v: float):
        self.parts.append(struct.pack("<d", float(v)))

    def s(self, v: str):
        b = str(v).encode("utf-8")
        self.parts.append(struct.pack("<I", len(b)) + b)

    def strmap(self, d: dict):
        items = list((d or {}).items())
        self.u32(len(items))
        for k, v in items:
            self.s(k)
            self.s(v)

    def bytes(self) -> bytes:
        return b"".join(self.parts)


def _sel_key(sel) -> str:
    return json.dumps(sel, sort_keys=True) if sel is not None else "null"


def _term_sig(term: dict, owner_ns: str) -> str:
    ns = sorted([str(n) for n in (term.get("namespaces") or [])] or [owner_ns])
    return "\x01".join(["|".join(ns), _sel_key(term.get("labelSelector")), term.get("topologyKey", "") or ""])


def _put_selector(b: _Buf, sel: Optional[dict]):
    if sel is None:
        b.u8(0)
        return
    b.u8(1)
    b.strmap(sel.get("matchLabels") or {})
    exprs = sel.get("matchExpressions") or []
    b.u32(len(exprs))
    for e in exprs:
        op = e.get("operator", "")
        if op not in _LABEL_OPS:
            raise ValueError(f"unknown label selector operator: {op}")
        b.s(e.get("key", ""))
        b.u8(_LABEL_OPS[op])
        vals = [str(v) for v in (e.get("values") or [])]
        b.u32(len(vals))
        for v in vals:
            b.s(v)


def _put_node_term(b: _Buf, term: dict):
    for part in ("matchExpressions", "matchFields"):
        exprs = term.get(part) or []
        b.u32(len(exprs))
        for e in exprs:
            op = e.get("operator", "")
            if op not in _NODE_OPS:
                raise ValueError(f"unknown node selector operator: {op}")
            b.s(e.get("key", ""))
            b.u8(_NODE_OPS[op])
            vals = [str(v) for v in (e.get("values") or [])]
            b.u32(len(vals))
            for v in vals:
                b.s(v)


def _put_terms(b: _Buf, terms: list, ns: str, weights: Optional[list]):
    b.u32(len(terms))
    for i, t in enumerate(terms):
        b.s(_term_sig(t, ns))
        nss = [str(n) for n in (t.get("namespaces") or [])] or [ns]
        b.u32(len(nss))
        for n in nss:
            b.s(n)
        _put_selector(b, t.get("labelSelector"))
        b.s(t.get("topologyKey", "") or "")
        b.f64(weights[i] if weights is not None else 0.0)


def _terms(pod: Pod, kind: str, mode: str):
    aff = (pod.spec.affinity or {}).get(kind) or {}
    return aff.get(f"{mode}DuringSchedulingIgnoredDuringExecution") or []


def _put_template(b: _Buf, pod: Pod):
    ns = pod.metadata.namespace
    b.s(ns)
    b.strmap(pod.metadata.labels)
    req = pod.resource_requests()
    b.u32(len(req))
    for k, v in req.items():
        b.s(k)
        b.f64(v)
    b.strmap({k: str(v) for k, v in pod.spec.node_selector.items()})

    aff = (pod.spec.affinity or {}).get("nodeAffinity") or {}
    required = aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if required is None:
        b.u8(0)
    else:
        b.u8(1)
        terms = required.get("nodeSelectorTerms") or []
        b.u32(len(terms))
        for t in terms:
            _put_node_term(b, t)
    preferred = aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    b.u32(len(preferred))
    for p in preferred:
        b.f64(float(p.get("weight", 0)))
        _put_node_term(b, p.get("preference") or {})

    tols = pod.spec.tolerations
    b.u32(len(tols))
    for t in tols:
        b.s(t.key)
        op = t.operator
        b.u8(1 if op == "Exists" else (0 if op in ("Equal", "") else 2))
        b.s(t.value)
        b.s(t.effect)

    ports = pod.host_ports()
    b.u32(len(ports))
    for p in ports:
        b.s(p.protocol)
        b.s(p.host_ip)
        b.u32(int(p.host_port))

    aff_req = _terms(pod, "podAffinity", "required")
    anti_req = _terms(pod, "podAntiAffinity", "required")
    aff_pref_w = _terms(pod, "podAffinity", "preferred")
    anti_pref_w = _terms(pod, "podAntiAffinity", "preferred")
    _put_terms(b, aff_req, ns, None)
    _put_terms(b, anti_req, ns, None)
    _put_terms(
        b, [tw.get("podAffinityTerm") or {} for tw in aff_pref_w], ns,
        [float(tw.get("weight", 0)) for tw in aff_pref_w],
    )
    _put_terms(
        b, [tw.get("podAffinityTerm") or {} for tw in anti_pref_w], ns,
        [float(tw.get("weight", 0)) for tw in anti_pref_w],
    )

    explicit = pod.spec.topology_spread_constraints or []
    b.u32(len(explicit))
    for c in explicit:
        key = c.get("topologyKey", "") or ""
        sel = c.get("labelSelector")
        b.s(_term_sig({"labelSelector": sel, "topologyKey": key, "namespaces": [ns]}, ns))
        b.s(key)
        b.f64(float(c.get("maxSkew", 1)))
        b.u8(1 if c.get("whenUnsatisfiable", "DoNotSchedule") == "DoNotSchedule" else 0)
        _put_selector(b, sel)

    owner = None
    if pod.metadata.annotations.get("simon/workload-kind") and pod.metadata.labels:
        owner = {"matchLabels": dict(pod.metadata.labels)}
    if owner is None:
        b.u8(0)
    else:
        b.u8(1)
        _put_selector(b, owner)
        for key in (HOSTNAME, ZONE):
            b.s(_term_sig({"labelSelector": owner, "topologyKey": key, "namespaces": [ns]}, ns))

    gpu_mem = pod.gpu_mem_request()
    b.f64(gpu_mem)
    b.u32(int(pod.gpu_count_request()) if gpu_mem > 0 else 0)

    lvm, devs = 0.0, []
    for v in pod.local_volumes():
        kind = str(v.get("kind", ""))
        try:
            size = float(parse_quantity(v.get("size", 0)))
        except ValueError:
            continue
        if kind == "LVM":
            lvm += size
        elif kind in ("SSD", "HDD"):
            devs.append((size, kind))
    b.f64(lvm)
    b.u32(len(devs))
    for size, kind in devs:
        b.f64(size)
        b.u8(0 if kind == "SSD" else 1)

    ctrl = None
    for ref in pod.metadata.owner_references:
        if ref.controller and ref.kind in ("ReplicaSet", "ReplicationController"):
            ctrl = (ref.kind, ref.uid)
            break
    if ctrl is None:
        b.u8(0)
    else:
        b.u8(1)
        b.s(ctrl[0])
        b.s(ctrl[1])


def _put_node(b: _Buf, node):
    b.s(node.metadata.name)
    b.strmap(node.metadata.labels)
    alloc = node.allocatable
    b.u32(len(alloc))
    for k, v in alloc.items():
        b.s(k)
        b.f64(v)
    b.u32(len(node.taints))
    for t in node.taints:
        b.s(t.key)
        b.s(t.value)
        b.s(t.effect)
    b.u8(1 if node.unschedulable else 0)
    total = alloc.get("alibabacloud.com/gpu-mem", 0.0)
    cnt = int(alloc.get("alibabacloud.com/gpu-count", 0))
    if not (cnt > 0 and total > 0):
        total, cnt = 0.0, 0
    b.f64(total)
    b.u32(cnt)
    vgs, devs = [], []
    raw = node.metadata.annotations.get("simon/node-local-storage")
    if raw:
        try:
            data = json.loads(raw)
        except ValueError:
            data = {}
        for vg in data.get("vgs") or []:
            vgs.append(float(parse_quantity(vg.get("capacity", 0))))
        for d in data.get("devices") or []:
            cap = float(parse_quantity(d.get("capacity", 0)))
            media = 0 if str(d.get("mediaType", "")).lower() == "ssd" else 1
            devs.append((cap, media))
    b.u32(len(vgs))
    for cap in vgs:
        b.f64(cap)
    b.u32(len(devs))
    for cap, media in devs:
        b.f64(cap)
        b.u8(media)
    avoid = []
    anno = node.metadata.annotations.get("scheduler.alpha.kubernetes.io/preferAvoidPods")
    if anno:
        try:
            entries = json.loads(anno).get("preferAvoidPods") or []
        except (ValueError, AttributeError):
            entries = []
        for e in entries:
            pc = (e.get("podSignature") or {}).get("podController") or {}
            avoid.append((str(pc.get("kind", "")), str(pc.get("uid", ""))))
    b.u32(len(avoid))
    for kind, uid in avoid:
        b.s(kind)
        b.s(uid)


def marshal(nodes, stream: List[Tuple[Pod, bool]]) -> bytes:
    """Serialize nodes + the ordered pod stream (pod, forced) into the
    engine's byte format. Pods are deduped into templates by scheduling
    spec (same hint as simulator._tmpl_hint, else full spec identity)."""
    from ..engine.simulator import _tmpl_hint

    b = _Buf()
    b.u32(WIRE_MAGIC)  # "SRL1"
    b.u32(WIRE_VERSION)
    b.u32(len(nodes))
    for n in nodes:
        _put_node(b, n)

    tmpl_idx: dict = {}
    tmpl_of: List[int] = []
    tmpl_pods: List[Pod] = []
    for pod, _forced in stream:
        hint = _tmpl_hint(pod)
        key = hint if hint is not None else ("__uniq__", len(tmpl_pods))
        idx = tmpl_idx.get(key)
        if idx is None:
            idx = tmpl_idx[key] = len(tmpl_pods)
            tmpl_pods.append(pod)
        tmpl_of.append(idx)
    b.u32(len(tmpl_pods))
    for pod in tmpl_pods:
        _put_template(b, pod)
    b.u32(len(stream))
    for (pod, forced), ti in zip(stream, tmpl_of):
        b.u32(ti)
        b.u8(1 if forced else 0)
        b.s(pod.spec.node_name if forced else "")
    return b.bytes()


# -- build + bindings (loader shared with scan_engine: native.build_cached) --

_lib = None
_lib_error: Optional[str] = None


def load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if _lib_error is not None:
        return None
    from . import build_cached

    out, err = build_cached(_SRC, "_serial_engine_", _CXX_FLAGS)
    if out is None:
        _lib_error = err
        return None
    try:
        lib = ctypes.CDLL(str(out))
    except OSError as e:
        _lib_error = f"dlopen failed: {e}"
        return None
    lib.opensim_serial_abi.restype = ctypes.c_int64
    if lib.opensim_serial_abi() != 1:
        _lib_error = f"serial engine ABI {lib.opensim_serial_abi()} != 1"
        return None
    lib.opensim_serial_run.restype = ctypes.c_int
    lib.opensim_serial_run.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_double),
    ]
    _lib = lib
    return lib


def available() -> bool:
    return load() is not None


def load_error() -> Optional[str]:
    return _lib_error


def run_serial_native(cluster, apps, progress: bool = False):
    """Expand (shared with the python tool), marshal, run the C++ serial
    engine. Returns (scheduled, unscheduled, expand_s, schedule_s,
    chosen_names) — the same shape as tools/serial_baseline.run_serial,
    with schedule_s timed INSIDE the C++ loop (marshal/parse excluded)."""
    import numpy as np

    from ..engine import queues
    from ..engine.simulator import _cluster_pods
    from ..models import expand
    from ..models.objects import LABEL_APP_NAME

    from ..utils.gcpause import gc_paused

    lib = load()
    if lib is None:
        raise RuntimeError(f"serial engine unavailable: {_lib_error}")

    t0 = time.time()
    stream: List[Tuple[Pod, bool]] = []
    with gc_paused():
        cluster_pods, _n_bare, _ds_sizes = _cluster_pods(cluster)
        for p in cluster_pods:
            stream.append((p, bool(p.spec.node_name)))
        for app in apps:
            pods = expand.generate_pods_from_resources(app.resources, cluster.nodes)
            for p in pods:
                p.metadata.labels.setdefault(LABEL_APP_NAME, app.name)
            pods = queues.toleration_sort(queues.affinity_sort(pods))
            stream.extend((p, bool(p.spec.node_name)) for p in pods)
    expand_s = time.time() - t0

    buf = marshal(cluster.nodes, stream)
    chosen = np.full((len(stream),), -1, dtype=np.int32)
    sched_s = ctypes.c_double(0.0)
    rc = lib.opensim_serial_run(
        buf, len(buf),
        chosen.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(sched_s),
    )
    if rc != 0:
        raise RuntimeError(f"serial engine failed with code {rc}")
    names = [cluster.nodes[c].metadata.name if c >= 0 else None for c in chosen]
    scheduled = int((chosen >= 0).sum())
    return scheduled, len(stream) - scheduled, expand_s, float(sched_s.value), names
