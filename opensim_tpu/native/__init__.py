"""Native CPU scan engine — build + ctypes bindings.

``scan_engine.cc`` is compiled on demand with the system ``g++`` into a
shared library cached next to this file (keyed by a hash of the source and
compiler identity, so editing the source or changing the toolchain rebuilds
automatically). The C ABI is a single ``ScanArgs`` struct mirrored here as a
``ctypes.Structure``; ``opensim_args_size()`` is checked at load time so a
layout drift between the two declarations disables the engine instead of
corrupting memory.

This is the framework's answer to the reference's vendored Go scheduler
being its "native engine" (SURVEY.md §2.2): the TPU compute path is
JAX/XLA/Pallas, and this C++ runtime covers hosts without an accelerator at
native speed. Placement parity with the XLA scan is asserted by
tests/test_native.py and the differential fuzz sweep.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
from pathlib import Path
from typing import Optional

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "scan_engine.cc"

#: expected ``opensim_abi_version()`` — the machine-readable anchor the
#: OSL1604 abi-parity pass checks against scan_engine.cc, and the runtime
#: load gate below checks against the compiled library
ABI_VERSION = 5

_DIMS = [
    "N", "R", "U", "P", "Tk", "Dp1", "A", "Hp", "Hports", "Cs", "Ti", "Tn",
    "Tpp", "G", "Gp", "Gd", "Vg", "Dv", "Mv", "res_cpu", "res_mem", "res_gc",
]
_FEATURES = [
    "ft_ports", "ft_gpu", "ft_local", "ft_interpod", "ft_prefg",
    "ft_spread_hard", "ft_spread_soft", "ft_pref_na", "ft_pref_taints",
    "ft_prefer_avoid", "ft_gc_dyn",
]
_FILTER_ENABLES = ["cf_ports", "cf_fit", "cf_spread", "cf_interpod", "cf_gpu", "cf_local"]
# sampled tie-break knobs (--tie-break=sample[:seed]) + the decision-audit
# flag (explain=1 forces the generic path and fills filter_rejects)
_SELECT = ["tie_sample", "tie_seed", "explain"]
_WEIGHTS = [
    "w_balanced", "w_least", "w_node_affinity", "w_taint_toleration",
    "w_interpod", "w_spread", "w_prefer_avoid", "w_simon", "w_gpu_share",
    "w_local",
]
# (name, ctypes pointer type, numpy dtype) in the exact struct order of
# scan_engine.cc — keep in sync
_U8 = ctypes.POINTER(ctypes.c_uint8)
_I32 = ctypes.POINTER(ctypes.c_int32)
_I64 = ctypes.POINTER(ctypes.c_int64)
_F32 = ctypes.POINTER(ctypes.c_float)
_F64 = ctypes.POINTER(ctypes.c_double)
_BUFFERS = [
    ("node_valid", _U8, "u8"), ("alloc", _F32, "f32"),
    ("node_domain", _I32, "i32"), ("domain_topo", _I32, "i32"),
    ("req", _F32, "f32"), ("ports", _I32, "i32"),
    ("port_conflict", _U8, "u8"),
    ("spr_topo", _I32, "i32"), ("spr_sel", _I32, "i32"),
    ("spr_skew", _I32, "i32"), ("spr_hard", _U8, "u8"),
    ("at_sel", _I32, "i32"), ("at_topo", _I32, "i32"),
    ("an_sel", _I32, "i32"), ("an_topo", _I32, "i32"),
    ("pt_sel", _I32, "i32"), ("pt_topo", _I32, "i32"), ("pt_w", _F32, "f32"),
    ("matches_sel", _U8, "u8"), ("anti_g", _U8, "u8"),
    ("anti_g_sel", _I32, "i32"), ("anti_g_topo", _I32, "i32"),
    ("prefg_w", _F32, "f32"), ("prefg_sel", _I32, "i32"),
    ("prefg_topo", _I32, "i32"),
    ("gpu_mem", _F32, "f32"), ("gpu_count", _I32, "i32"),
    ("node_gpu_cap", _F32, "f32"),
    ("avoid_score", _F32, "f32"),
    ("lvm_req", _F32, "f32"), ("dev_req", _F32, "f32"),
    ("dev_req_count", _I32, "i32"), ("dev_req_sizes", _F32, "f32"),
    ("node_vg_cap", _F32, "f32"), ("node_dev_cap", _F32, "f32"),
    ("node_dev_media", _I32, "i32"), ("pin", _I32, "i32"),
    ("static_pass", _U8, "u8"), ("aff_mask", _U8, "u8"),
    ("na_raw", _F32, "f32"), ("tt_raw", _F32, "f32"),
    ("share_raw", _F32, "f32"), ("spread_weight", _F32, "f32"),
    ("tmpl_ids", _I32, "i32"), ("forced", _U8, "u8"), ("pod_valid", _U8, "u8"),
    ("used", _F32, "f32"), ("port_used", _F32, "f32"),
    ("dom_sel", _F32, "f32"), ("dom_anti", _F32, "f32"),
    ("dom_prefw", _F32, "f32"), ("gpu_free", _F32, "f32"),
    ("vg_free", _F32, "f32"), ("dev_free", _F32, "f32"),
    ("chosen", _I32, "i32"), ("fail_counts", _I32, "i32"),
    ("insufficient", _I32, "i32"), ("gpu_take", _F32, "f32"),
    # path attribution ({incremental, generic, full_eval} step counts) and
    # the OPENSIM_NATIVE_PROFILE per-phase {seconds, steps} pairs
    ("path_counts", _I32, "i32"), ("profile_out", _F64, "f64"),
    # decision audit (explain=1, abi v4): per-template static-filter fail
    # counts in, 11-slot per-filter reject totals out
    ("static_fail", _I32, "i32"), ("filter_rejects", _I64, "i64"),
    # incremental-carry attribution (abi v5): 11-slot bail-reason counts
    # (nativepath._BAIL_REASONS order) and 4-slot per-carry-class
    # incremental step counts (ports, gpu, local, score)
    ("bail_out", _I64, "i64"), ("class_steps", _I64, "i64"),
]

_NP_DTYPES = {
    "u8": "uint8", "i32": "int32", "i64": "int64", "f32": "float32", "f64": "float64",
}


class ScanArgs(ctypes.Structure):
    _fields_ = (
        [(n, ctypes.c_int64) for n in _DIMS + _FEATURES + _FILTER_ENABLES + _SELECT]
        + [(n, ctypes.c_double) for n in _WEIGHTS]
        + [(n, t) for n, t, _ in _BUFFERS]
    )


_CXX_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-ffp-contract=off"]

_lib = None
_lib_error: Optional[str] = None


def build_cached(src: Path, prefix: str, flags: list) -> tuple:
    """Compile ``src`` into a content-hash-keyed .so next to it (shared by
    the scan and serial engines). Returns (path, None) or (None, reason).
    Concurrent builders race benignly: each writes its own pid-suffixed tmp
    and only stale *.so* files are cleaned up (never another process's
    in-flight tmp)."""
    try:
        h = hashlib.sha256()
        h.update(src.read_bytes())
        h.update(" ".join(flags).encode())
        try:
            h.update(subprocess.run(["g++", "--version"], capture_output=True).stdout)
        except OSError:
            pass
        key = h.hexdigest()[:16]
    except OSError as e:
        return None, f"cannot read {src}: {e}"
    here = src.parent
    out = here / f"{prefix}{key}.so"
    if out.exists():
        return out, None
    tmp = out.with_suffix(f".tmp{os.getpid()}")
    cmd = ["g++", *flags, "-o", str(tmp), str(src)]
    try:
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            return None, f"g++ unavailable: {e}"
        if r.returncode != 0:
            return None, f"native build failed:\n{r.stderr[-2000:]}"
        os.replace(tmp, out)  # atomic: concurrent builders race benignly
    finally:
        tmp.unlink(missing_ok=True)
    import time as _time

    for stale in here.glob(f"{prefix}*.so"):
        if stale != out:
            try:
                stale.unlink()
            except OSError:
                pass
    # orphaned tmp files from builders killed mid-compile: reap only ones
    # old enough that no in-flight build (<=120 s) can still own them
    for tmp_orphan in here.glob(f"{prefix}*.tmp*"):
        try:
            if _time.time() - tmp_orphan.stat().st_mtime > 600:
                tmp_orphan.unlink()
        except OSError:
            pass
    return out, None


def ensure_built() -> Optional[Path]:
    """Compile the engine if its cached .so is stale. Returns the library
    path, or None (with the reason in ``load_error()``) when no compiler is
    available or the build fails."""
    global _lib_error
    path, err = build_cached(_SRC, "_scan_engine_", _CXX_FLAGS)
    if path is None:
        _lib_error = err
    return path


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and dlopen the engine; ABI-checked. Cached."""
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if _lib_error is not None:
        return None
    path = ensure_built()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError as e:
        _lib_error = f"dlopen failed: {e}"
        return None
    lib.opensim_args_size.restype = ctypes.c_int64
    lib.opensim_abi_version.restype = ctypes.c_int64
    if lib.opensim_abi_version() != ABI_VERSION:
        _lib_error = (
            f"ABI version mismatch: library reports v{lib.opensim_abi_version()} "
            f"but this binding expects v{ABI_VERSION}"
        )
        return None
    if lib.opensim_args_size() != ctypes.sizeof(ScanArgs):
        _lib_error = (
            f"ABI mismatch: C sizeof(ScanArgs)={lib.opensim_args_size()} vs "
            f"ctypes {ctypes.sizeof(ScanArgs)} — struct declarations drifted"
        )
        return None
    lib.opensim_run_scan.restype = ctypes.c_int
    lib.opensim_run_scan.argtypes = [ctypes.POINTER(ScanArgs)]
    _lib = lib
    return lib


def available() -> bool:
    return load() is not None


def load_error() -> Optional[str]:
    return _lib_error


def run_scan(dims: dict, weights: dict, buffers: dict) -> None:
    """Fill ScanArgs from numpy buffers and invoke the engine. `buffers`
    maps field name → numpy array (contiguous, correct dtype — validated
    here); state/output arrays are mutated in place."""
    import numpy as np

    lib = load()
    if lib is None:
        raise RuntimeError(f"native engine unavailable: {_lib_error}")
    args = ScanArgs()
    for n in _DIMS + _FEATURES + _FILTER_ENABLES:
        setattr(args, n, int(dims[n]))
    for n in _SELECT:
        setattr(args, n, int(dims.get(n, 0)))
    for n in _WEIGHTS:
        setattr(args, n, float(weights[n]))
    keep = []  # hold array refs across the call
    for n, ptr_t, kind in _BUFFERS:
        arr = buffers[n]
        want = np.dtype(_NP_DTYPES[kind])
        if arr.dtype != want or not arr.flags["C_CONTIGUOUS"]:
            raise ValueError(f"buffer {n}: need C-contiguous {want}, got {arr.dtype}")
        keep.append(arr)
        setattr(args, n, arr.ctypes.data_as(ptr_t))
    rc = lib.opensim_run_scan(ctypes.byref(args))
    if rc != 0:
        raise RuntimeError(f"native scan failed with code {rc}")
