// Native CPU scan engine — the C++ counterpart of the XLA bind scan.
//
// Mirrors opensim_tpu/ops/kernels.py (pod_step + bind_update) operation for
// operation in float32, same evaluation order, so placements are identical
// to the XLA scan (tests/test_native*.py assert equality). This is the
// framework's native runtime for hosts without an accelerator: the
// reference's "native engine" is the vendored Go kube-scheduler
// (vendor/k8s.io/kubernetes/pkg/scheduler, scheduleOne at
// scheduler.go:441-614); here the same pipeline is a fused sequential scan
// over the pod stream with all per-node work in tight vectorizable loops.
//
// ABI: a single ScanArgs struct of int64 dims followed by double weights and
// raw pointers. The Python side (opensim_tpu/native/__init__.py) builds the
// mirror ctypes.Structure; opensim_args_size() guards against layout drift.
//
// Compile: g++ -O3 -std=c++17 -shared -fPIC -ffp-contract=off
//   (-ffp-contract=off keeps IEEE f32 semantics aligned with XLA:CPU so
//    score ties break identically in both engines)

#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {
constexpr float BIG = 1e30f;
constexpr float NEG = -1e30f;
constexpr float MAXS = 100.0f;  // MAX_NODE_SCORE
}  // namespace

extern "C" {

// splitmix64: the per-step PRNG behind the sampled tie-break (seeded,
// reproducible; stream = f(tie_seed, step index))
static inline uint64_t sm64_next(uint64_t* x) {
  *x += 0x9E3779B97F4A7C15ULL;
  uint64_t z = *x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// abi-begin: ScanArgs
// Field count, order, and widths are gated against the ctypes mirror in
// native/__init__.py by the OSL1604 abi-parity pass (make lint): drift on
// either side fails the build naming the exact field.
struct ScanArgs {
  // --- dims (all int64; mirrored by native/__init__.py _DIMS) ---
  int64_t N, R, U, P, Tk, Dp1, A, Hp, Hports, Cs, Ti, Tn, Tpp, G, Gp, Gd, Vg, Dv, Mv;
  int64_t res_cpu, res_mem;
  int64_t res_gc;  // resource row of alibabacloud.com/gpu-count (-1 absent)
  // workload feature flags (kernels.Features)
  int64_t ft_ports, ft_gpu, ft_local, ft_interpod, ft_prefg, ft_spread_hard,
      ft_spread_soft, ft_pref_na, ft_pref_taints, ft_prefer_avoid, ft_gc_dyn;
  // filter enables (SchedulerConfig.f_*; static-filter disables are already
  // folded into static_pass by precompute_static)
  int64_t cf_ports, cf_fit, cf_spread, cf_interpod, cf_gpu, cf_local;
  // sampled tie-break (--tie-break=sample[:seed]): uniform choice among the
  // score maxima per step — the distribution of the reference's selectHost
  // reservoir sampling (generic_scheduler.go:188-210)
  int64_t tie_sample, tie_seed;
  // decision audit (ISSUE 7): 1 = run failure attribution on EVERY
  // scheduled step (not only failures) and accumulate the per-filter
  // reject totals into filter_rejects. Forces the generic path — the
  // incremental cache never materializes full per-step verdict masks.
  int64_t explain;
  // score weights (SchedulerConfig.w_*; double like the Python floats, cast
  // to f32 at the same point jnp's weak-type promotion does)
  double w_balanced, w_least, w_node_affinity, w_taint_toleration, w_interpod,
      w_spread, w_prefer_avoid, w_simon, w_gpu_share, w_local;

  // --- EncodedCluster (const) ---
  const uint8_t* node_valid;     // [N]
  const float* alloc;            // [N,R]
  const int32_t* node_domain;    // [N,Tk]
  const int32_t* domain_topo;    // [Dp1]
  const float* req;              // [U,R]
  const int32_t* ports;          // [U,Hp]
  const uint8_t* port_conflict;  // [Hports,Hports]
  const int32_t* spr_topo;       // [U,Cs]
  const int32_t* spr_sel;        // [U,Cs]
  const int32_t* spr_skew;       // [U,Cs]
  const uint8_t* spr_hard;       // [U,Cs]
  const int32_t* at_sel;         // [U,Ti]
  const int32_t* at_topo;        // [U,Ti]
  const int32_t* an_sel;         // [U,Tn]
  const int32_t* an_topo;        // [U,Tn]
  const int32_t* pt_sel;         // [U,Tpp]
  const int32_t* pt_topo;        // [U,Tpp]
  const float* pt_w;             // [U,Tpp]
  const uint8_t* matches_sel;    // [U,A]
  const uint8_t* anti_g;         // [U,G]
  const int32_t* anti_g_sel;     // [G]
  const int32_t* anti_g_topo;    // [G]
  const float* prefg_w;          // [U,Gp]
  const int32_t* prefg_sel;      // [Gp]
  const int32_t* prefg_topo;     // [Gp]
  const float* gpu_mem;          // [U]
  const int32_t* gpu_count;      // [U]
  const float* node_gpu_cap;     // [N,Gd] static per-device total memory
  const float* avoid_score;      // [U,N]
  const float* lvm_req;          // [U]
  const float* dev_req;          // [U,2]
  const int32_t* dev_req_count;  // [U,2]
  const float* dev_req_sizes;    // [U,2,Mv]
  const float* node_vg_cap;      // [N,Vg]
  const float* node_dev_cap;     // [N,Dv]
  const int32_t* node_dev_media; // [N,Dv]
  const int32_t* pin;            // [U]

  // --- StaticTables (const, from kernels.precompute_static) ---
  const uint8_t* static_pass;    // [U,N]
  const uint8_t* aff_mask;       // [U,N]
  const float* na_raw;           // [U,N]
  const float* tt_raw;           // [U,N]
  const float* share_raw;        // [U,N]
  const float* spread_weight;    // [Tk]

  // --- pod stream (const) ---
  const int32_t* tmpl_ids;       // [P]
  const uint8_t* forced;         // [P]
  const uint8_t* pod_valid;      // [P]

  // --- ScanState (mutated in place; caller passes copies of st0) ---
  float* used;       // [N,R]
  float* port_used;  // [N,Hports]
  float* dom_sel;    // [Dp1,A]
  float* dom_anti;   // [Dp1,G]
  float* dom_prefw;  // [Dp1,Gp]
  float* gpu_free;   // [N,Gd]
  float* vg_free;    // [N,Vg]
  float* dev_free;   // [N,Dv]

  // --- outputs ---
  int32_t* chosen;        // [P] node index, -1 unscheduled
  int32_t* fail_counts;   // [P,7] dynamic-filter first-fail counts
  int32_t* insufficient;  // [P,R]
  float* gpu_take;        // [P,Gd]
  // path attribution: scheduled steps served by the incremental cache vs
  // the generic full re-evaluation, plus incremental-path full_eval count
  // (a silent cache disengage must be visible to callers, not inferred
  // from wall-clock)
  int32_t* path_counts;   // [3] {incremental steps, generic steps, full_evals}
  // per-phase {seconds, steps} pairs in Prof order (delta, full_eval,
  // argmax, bind, fail, generic); filled only under OPENSIM_NATIVE_PROFILE
  double* profile_out;    // [12]
  // --- decision audit (explain=1; ISSUE 7, abi v4) ---
  // per-template static-filter first-fail counts (kernels.precompute_static
  // static_fail) so the engine attributes the 4 static filters without
  // recomputing them, and the 11-slot per-filter reject accumulator
  // (kernel filter-index order; int64 — P×N node verdicts overflow i32)
  const int32_t* static_fail;  // [U,4]
  int64_t* filter_rejects;     // [11]
  // --- incremental-carry attribution (abi v5) ---
  // bail_out: why the incremental envelope disengaged — the three whole-scan
  // gates (force_generic/explain/Cs) counted once per scan, plus the
  // per-delta bail classes (ports/gpu/local/gc_dyn/fit/spread/interpod/
  // pending), slot order mirrored by nativepath._BAIL_REASONS.
  // class_steps: incremental steps served with each resource-class carry
  // active (ports, gpu-share, local-PV filter, dynamic score), so the
  // engagement gate can assert the new envelope actually ran.
  int64_t* bail_out;     // [11]
  int64_t* class_steps;  // [4]
};
// abi-end: ScanArgs

int64_t opensim_abi_version() { return 5; }
int64_t opensim_args_size() { return (int64_t)sizeof(ScanArgs); }

}  // extern "C"

namespace {

// Dynamic-filter slots, same order as kernels.pod_step's `masks` list
// (F_PORTS..F_EXTRA − F_PORTS).
enum Stage { S_PORTS = 0, S_FIT, S_SPREAD, S_INTERPOD, S_GPU, S_LOCAL, S_EXTRA, N_STAGES };

// bail_out slots (nativepath._BAIL_REASONS order): B_FORCE/B_EXPLAIN/B_CS
// are whole-scan envelope gates; the rest name which carry class's
// feasibility/verdict shift forced a delta back to full evaluation.
enum Bail {
  B_FORCE = 0, B_EXPLAIN, B_CS, B_PORTS, B_GPU, B_LOCAL, B_GCDYN,
  B_FIT, B_SPREAD, B_INTERPOD, B_PENDING, N_BAILS
};

struct Scratch {
  std::vector<uint8_t> mask[N_STAGES];  // per-stage node masks (active stages only)
  // per-topology-key facts, template-independent, memoized lazily:
  // -1 unknown; singleton = every non-trash domain has <= 1 member
  std::vector<int8_t> tk_singleton;
  std::vector<int64_t> tk_domcount;
  std::vector<uint8_t> feas;
  std::vector<float> raw_ip, raw_spr, raw_loc;
  std::vector<uint8_t> spr_ignored;
  std::vector<float> key_sel_total;  // [Tk,A] Σ dom_sel over real domains per key
  std::vector<float> take;           // [Gd]
  std::vector<uint8_t> affected;     // delta scratch
  // incremental-path indexes: nodes per real domain, nodes per key missing
  // the label (trash row is shared across keys, so it needs per-key lists)
  std::vector<std::vector<int32_t>> dom_members;
  std::vector<std::vector<int32_t>> trash_members;
  std::vector<std::vector<int32_t>> key_doms;  // [Tk] real domains per key
  std::vector<int32_t> visited;  // epoch stamps for member-union dedup
  std::vector<int32_t> touch;    // affected nodes collected this delta
  std::vector<int32_t> flip_doms;  // hard-spread domains whose verdict flipped
  int32_t epoch = 0;
  // [N] dynamic gpu-count allocatable (-1 on device-less nodes); filled and
  // maintained only under ft_gc_dyn — gpu_free changes only at bind, so one
  // per-bound-node refresh replaces per-(node, call) device rescans
  std::vector<float> gc_dyn;
  const float* gc_dyn_ptr() const { return gc_dyn.empty() ? nullptr : gc_dyn.data(); }
};

// Incremental same-template cache. Pod streams are dominated by runs of one
// workload's identical pods (the reference schedules app by app,
// simulator.go:232-249); within a run only the bound node's row and its
// topology domains change, so the full per-node evaluation from the last
// step stays valid almost everywhere. Every cached value is recomputed with
// the exact float ops of the full pass when it CAN change, and the cache is
// dropped wholesale on anything nontrivial (feasible-set flip, min/max
// shift it cannot prove unchanged), so placements are bit-identical to the
// non-incremental path.
struct TmplCache {
  int32_t u = -1;
  bool valid = false;
  bool prev_failed = false;
  // (node, binder template) bound since the cache was computed — the
  // binder identifies which (domain, selector) counts a forced foreign
  // bind could have moved
  std::vector<std::pair<int32_t, int32_t>> pending;
  std::vector<uint8_t> feas;
  std::vector<uint8_t> ignored;
  // interpod incremental state (round 9): per-node filter verdicts + score
  // raws cached per template; a bind invalidates only the members of the
  // domains it touched (counts-only-grow + feasibility-flip-bail, the same
  // contract as the spread caches below)
  bool ip_f_act = false;      // template carries filter-relevant terms
  bool ip_s_act = false;      // template carries score-relevant terms
  bool ip_any_at = false, ip_bootstrap = false;
  bool ip_hi_stale = false, ip_lo_stale = false;
  std::vector<uint8_t> ip_mask;  // [N] interpod filter verdict (ip_f_act)
  std::vector<float> ip_raw;     // [N] interpod score raw (ip_s_act)
  float ip_rhi = 0, ip_rlo = 0;  // reductions of the feas-masked raw
  // hard-spread incremental state: every member of a topology domain
  // shares one verdict (cnt + selfm - min_cnt <= skew), so a bind updates
  // per-DOMAIN state and touches member nodes only on a verdict flip
  struct HardSpread {
    int32_t tk, sel;
    float skew, selfm, min_cnt;
    std::vector<uint8_t> elig;  // [Dp1] domain has an eligible member
    std::vector<uint8_t> verd;  // [Dp1] per-domain verdict (trash stays 0)
  };
  std::vector<HardSpread> hards;
  std::vector<uint8_t> sh_mask;  // [N] AND over hards (valid when any)
  bool has_hard = false;
  // per-resource-class carry (abi v5 envelope): a bind mutates ONLY the
  // bound node's port_used/gpu_free/gc_dyn/vg_free/dev_free rows, so the
  // delta recomputes that one node's verdict/raw with the exact single-node
  // helper the batch pass uses, and any feasibility flip routes through the
  // bail-to-full-eval contract (reductions stay over a frozen feasible set)
  bool pt_act = false;           // host-port conflicts possible (cf_ports)
  std::vector<int32_t> pt_ids;   // the template's port ids
  std::vector<uint8_t> pt_mask;  // [N] (pt_act)
  bool gp_act = false;           // gpu_mem[u] > 0 (cf_gpu)
  float gp_memq = 1.0f, gp_cnt = 0.0f;
  std::vector<uint8_t> gp_mask;  // [N] (gp_act)
  bool lc_f_act = false;         // template carries local-PV requests (cf_local)
  std::vector<uint8_t> lc_mask;  // [N] (lc_f_act)
  bool sh_dyn = false;           // share term reads gc_dyn (gc_req > 0)
  bool sh_hi_stale = false, sh_lo_stale = false;
  std::vector<float> sh_val;     // [N] dynamic share value (sh_dyn)
  bool lc_s_act = false;         // nonzero w_local with local requests
  bool lcs_hi_stale = false, lcs_lo_stale = false;
  std::vector<float> lc_raw;     // [N] local score raw (lc_s_act)
  float lc_lo = 0, lc_hi = 0, lc_rng = 0;
  std::vector<float> pre;         // bal+least+na+tt accumulated in pod_step order
  std::vector<float> spr_raw, share_term, av_term, score;
  float sh_lo = 0, sh_hi = 0, sh_rng = 0, na_max = 0, tt_max = 0;
  float spr_mn = 0, spr_mx = 0;
  bool any_soft = false;
  // domain mode: exactly ONE active soft spread constraint. Every member
  // of a topology domain shares the same raw (cnt·w + (skew-1)), so the
  // cache keeps ONE value per domain (dm_V) instead of per node — a bind
  // then updates O(1) state (+ an O(domains) min rescan when the old min
  // domain grew) instead of walking the domain's member nodes.
  bool dom_mode = false;
  int32_t dm_tk = -1, dm_sel = -1;
  float dm_w = 0, dm_k = 0;
  std::vector<float> dm_V;       // [Dp1] per-domain raw; trash row = 0
  std::vector<int32_t> dm_dom;   // [N] node → domain of dm_tk (contiguous)
  std::vector<int32_t> dm_scored;  // [Dp1] feas && !ignored member count
  std::vector<int32_t> dm_doms;  // compact list of scored domains
  std::vector<int32_t> dm_zi;    // [N] compact domain index (0 for ig)
  // hier mode: exactly TWO active soft constraints where one partitions
  // nodes into singleton domains (hostname) — the system-default spread
  // pair. raw = fine-term + coarse-term (in cc order); min/max maintain
  // via per-coarse-domain histograms of the fine count level, so a bind
  // is O(1) amortized (+ an O(coarse domains) global-min recompute).
  bool hier_mode = false;
  bool hier_fine_first = true;   // is the FINE cc the first in cc order?
  int32_t hf_sel = -1, hc_sel = -1;
  float hf_w = 0, hf_k = 0, hc_w = 0, hc_k = 0;
  std::vector<float> hf_V, hc_V;        // [Dp1] per-domain term; trash = 0
  std::vector<int32_t> hf_dom, hc_dom;  // [N] node → domain (contiguous)
  std::vector<int32_t> hf_lev;          // [N] int fine count level
  std::vector<std::vector<int32_t>> hc_hist;  // per coarse dom: scored levels
  std::vector<int32_t> hc_minlev, hc_maxlev;  // [Dp1]
  std::vector<uint8_t> hc_has;          // [Dp1] any scored member
  std::vector<int32_t> hc_doms;         // compact list of scored coarse doms
  std::vector<int32_t> hc_zi;           // [N] compact coarse-dom index (0 for ig)
  std::vector<float> sel_T;             // per-step (zone, level) term LUT scratch
  std::vector<int32_t> fail_row;  // memoized failure outputs (state unchanged)
  std::vector<int32_t> ins_row;
};

inline float least_requested(float requested, float capacity) {
  // kernels._least_requested (least_allocated.go:105-117)
  float sc = (capacity - requested) * MAXS / std::max(capacity, 1.0f);
  return (capacity == 0.0f || requested > capacity) ? 0.0f : sc;
}

// Dynamic gpu-count allocatable of one node (Features.gc_dyn): the
// gpushare Reserve rewrites a device-bearing node's gpu-count allocatable
// to the count of not-fully-used devices (open-gpu-share.go:177-182,
// gpunodeinfo.go:354-369). Returns -1 on device-less nodes (static
// allocatable applies). Invariant between binds — callers pass the
// Scratch::gc_dyn row (recomputed per bound node in bind()) instead of
// rescanning devices per (node, call); nullptr falls back to the scan.
inline float gc_dyn_of(const ScanArgs& a, int64_t n) {
  const float* cap = a.node_gpu_cap + n * a.Gd;
  const float* fr = a.gpu_free + n * a.Gd;
  bool has = false;
  float dyn = 0.0f;
  for (int64_t d = 0; d < a.Gd; d++)
    if (cap[d] > 0.0f) {
      has = true;
      if (fr[d] > 0.0f) dyn += 1.0f;
    }
  return has ? dyn : -1.0f;
}

inline float alloc_at(const ScanArgs& a, const float* gc_dyn, int64_t n, int64_t r) {
  if (a.ft_gc_dyn && r == a.res_gc) {
    float dyn = gc_dyn ? gc_dyn[n] : gc_dyn_of(a, n);
    if (dyn >= 0.0f) return dyn;
  }
  return a.alloc[n * a.R + r];
}

// Simon/GpuShare share with the dynamic gpu-count term folded back in
// (share_raw zeroed that column on device-bearing nodes; algo.Share,
// greed.go:70-83 over the Reserve-updated allocatable).
inline float share_at(const ScanArgs& a, const float* gc_dyn, int32_t u, int64_t n) {
  float s = a.share_raw[(int64_t)u * a.N + n];
  if (a.ft_gc_dyn) {
    float gc_req = a.req[(int64_t)u * a.R + a.res_gc];
    if (gc_req > 0.0f && a.alloc[n * a.R + a.res_gc] > 0.0f) {
      float dyn = gc_dyn ? gc_dyn[n] : gc_dyn_of(a, n);
      if (dyn >= 0.0f) {
        float avail = dyn - gc_req;
        float sh = (avail == 0.0f) ? 1.0f : gc_req / avail;
        s = std::max(s, std::max(sh, 0.0f) * MAXS);
      }
    }
  }
  return s;
}

inline uint8_t fit_at(const ScanArgs& a, const float* gcd, int32_t u, int64_t n) {
  // incremental-cache path only; mirrors fit_mask's two loop bodies so the
  // single-node probe is bit-identical to the batch pass in both modes
  const float* req = a.req + (int64_t)u * a.R;
  const float* us = a.used + n * a.R;
  if (!a.ft_gc_dyn) {
    // static alloc row: keep the tight loop branch-free
    const float* al = a.alloc + n * a.R;
    uint8_t ok = 1;
    for (int64_t r = 0; r < a.R; r++)
      ok &= (uint8_t)(!(req[r] > 0.0f && us[r] + req[r] > al[r]));
    return ok;
  }
  uint8_t ok = 1;
  for (int64_t r = 0; r < a.R; r++)
    ok &= (uint8_t)(!(req[r] > 0.0f && us[r] + req[r] > alloc_at(a, gcd, n, r)));
  return ok;
}

// The first four score components (pod_step order: balanced, least,
// node-affinity, taint-toleration) for one node — single source for the
// generic loop and the incremental cache so both produce identical floats.
struct PreCtx {
  float cpuq, memq, na_max, tt_max;
  float wb, wl, wna, wtt;
  bool use_bal, use_least, use_na, use_tt;
  const float* na;
  const float* tt;
};

inline float pre_at(const ScanArgs& a, const PreCtx& c, int64_t n) {
  float sc = 0.0f;
  float alloc_cpu = a.alloc[n * a.R + a.res_cpu];
  float alloc_mem = a.alloc[n * a.R + a.res_mem];
  float used_cpu = a.used[n * a.R + a.res_cpu];
  float used_mem = a.used[n * a.R + a.res_mem];
  if (c.use_bal) {
    float cf = (used_cpu + c.cpuq) / std::max(alloc_cpu, 1.0f);
    float mf = (used_mem + c.memq) / std::max(alloc_mem, 1.0f);
    float b = (1.0f - std::fabs(cf - mf)) * MAXS;
    sc += c.wb * ((cf >= 1.0f || mf >= 1.0f) ? 0.0f : b);
  }
  if (c.use_least) {
    float cs = least_requested(used_cpu + c.cpuq, alloc_cpu);
    float ms = least_requested(used_mem + c.memq, alloc_mem);
    sc += c.wl * ((cs + ms) / 2.0f);
  }
  if (c.use_na)
    sc += c.wna * (c.na_max > 0.0f ? c.na[n] * MAXS / std::max(c.na_max, 1.0f) : c.na[n]);
  if (c.use_tt)
    sc += c.wtt * (c.tt_max > 0.0f ? MAXS - c.tt[n] * MAXS / std::max(c.tt_max, 1.0f) : MAXS);
  return sc;
}

// Single-node spread raw (same op order as the batch spread_raw loop).
inline float spr_raw_at(const ScanArgs& a, int32_t u, int64_t n, bool* all_labels) {
  const int32_t trash = (int32_t)a.Dp1 - 1;
  const int32_t* nd = a.node_domain + n * a.Tk;
  float raw = 0.0f;
  bool all = true;
  for (int64_t c = 0; c < a.Cs; c++) {
    int32_t tk = a.spr_topo[u * a.Cs + c];
    bool soft = tk >= 0 && !a.spr_hard[u * a.Cs + c];
    if (!soft) continue;
    int32_t dom = nd[tk];
    if (!(dom < trash)) { all = false; continue; }
    float cnt = a.dom_sel[(int64_t)dom * a.A + a.spr_sel[u * a.Cs + c]];
    raw += cnt * a.spread_weight[tk] + ((float)a.spr_skew[u * a.Cs + c] - 1.0f);
  }
  *all_labels = all;
  return raw;
}

// ---- filter stages (kernels.py ports_filter / fit_filter / spread_filter /
// interpod_filter / gpu_filter / local_filter) ----

// Single-node port verdict — the loop body of ports_mask, shared with the
// incremental cache's bound-node recomputation (a bind only ADDS port usage
// on one node, so every other node's cached verdict stays valid).
inline uint8_t ports_ok_at(const ScanArgs& a, const int32_t* pids, size_t np,
                           int64_t n) {
  const int64_t Hq = a.Hports;
  bool conflict = false;
  const float* pu = a.port_used + n * Hq;
  for (size_t k = 0; k < np && !conflict; k++) {
    const uint8_t* crow = a.port_conflict + (int64_t)pids[k] * Hq;
    for (int64_t q = 0; q < Hq; q++)
      if (crow[q] && pu[q] > 0.0f) { conflict = true; break; }
  }
  return (uint8_t)!conflict;
}

void ports_mask(const ScanArgs& a, int32_t u, uint8_t* out) {
  const int64_t N = a.N, Hp = a.Hp;
  std::vector<int32_t> pids;
  pids.reserve(Hp);
  for (int64_t h = 0; h < Hp; h++) {
    int32_t p = a.ports[u * Hp + h];
    if (p >= 0) pids.push_back(p);
  }
  if (pids.empty()) {
    std::memset(out, 1, N);
    return;
  }
  for (int64_t n = 0; n < N; n++) out[n] = ports_ok_at(a, pids.data(), pids.size(), n);
}

void fit_mask(const ScanArgs& a, const float* gc_dyn, int32_t u, uint8_t* out) {
  const int64_t N = a.N, R = a.R;
  const float* req = a.req + (int64_t)u * R;
  if (!a.ft_gc_dyn) {
    // hot path (2e9 inner iterations at headline shape): keep the plain
    // pointer walk fully branch-free and vectorizable
    for (int64_t n = 0; n < N; n++) {
      const float* al = a.alloc + n * R;
      const float* us = a.used + n * R;
      uint8_t ok = 1;
      for (int64_t r = 0; r < R; r++)
        ok &= (uint8_t)(!(req[r] > 0.0f && us[r] + req[r] > al[r]));
      out[n] = ok;
    }
    return;
  }
  for (int64_t n = 0; n < N; n++) {
    const float* us = a.used + n * R;
    uint8_t ok = 1;
    for (int64_t r = 0; r < R; r++)
      ok &= (uint8_t)(!(req[r] > 0.0f && us[r] + req[r] > alloc_at(a, gc_dyn, n, r)));
    out[n] = ok;
  }
}

void spread_mask(const ScanArgs& a, int32_t u, uint8_t* out) {
  const int64_t N = a.N, Cs = a.Cs, Tk = a.Tk, A = a.A;
  const int32_t trash = (int32_t)a.Dp1 - 1;
  const uint8_t* am = a.aff_mask + (int64_t)u * N;
  std::memset(out, 1, N);
  for (int64_t c = 0; c < Cs; c++) {
    int32_t tk = a.spr_topo[u * Cs + c];
    if (tk < 0 || !a.spr_hard[u * Cs + c]) continue;
    int32_t sel = a.spr_sel[u * Cs + c];
    float skew = (float)a.spr_skew[u * Cs + c];
    float selfm = (float)a.matches_sel[(int64_t)u * A + sel];
    // min matchNum over eligible domains (filtering.go:276 calPreFilterState)
    float min_cnt = BIG;
    for (int64_t n = 0; n < N; n++) {
      int32_t dom = a.node_domain[n * Tk + tk];
      if (dom < trash && am[n] && a.node_valid[n]) {
        float cnt = a.dom_sel[(int64_t)dom * A + sel];
        if (cnt < min_cnt) min_cnt = cnt;
      }
    }
    for (int64_t n = 0; n < N; n++) {
      int32_t dom = a.node_domain[n * Tk + tk];
      bool has = dom < trash;
      float cnt = a.dom_sel[(int64_t)dom * A + sel];
      out[n] &= (uint8_t)(has && (cnt + selfm - min_cnt <= skew));
    }
  }
}

// Incoming required-affinity bookkeeping (filtering.go:347-374): the
// bootstrap needs the GLOBAL count map empty and a full self-match.
// Shared by the batch mask and the incremental delta path (the delta bails
// on a bootstrap flip — it invalidates every node's verdict at once).
struct IpBoot {
  bool any_at;
  bool bootstrap;
};

inline IpBoot ip_boot_of(const ScanArgs& a, const Scratch& s, int32_t u) {
  const int64_t A = a.A, Ti = a.Ti;
  float total_active = 0.0f;
  bool all_self = true, any_at = false;
  for (int64_t t = 0; t < Ti; t++) {
    int32_t sel = a.at_sel[u * Ti + t];
    if (sel < 0) continue;
    any_at = true;
    total_active += s.key_sel_total[(int64_t)a.at_topo[u * Ti + t] * A + sel];
    if (!a.matches_sel[(int64_t)u * A + sel]) all_self = false;
  }
  return {any_at, (total_active == 0.0f) && all_self && any_at};
}

// Single-node interpod filter verdict — the loop body of interpod_mask,
// shared with the incremental cache's affected-domain recomputation so
// both produce identical verdicts.
inline uint8_t ip_mask_at(const ScanArgs& a, int32_t u, int64_t n, bool any_at,
                          bool bootstrap) {
  const int64_t Tk = a.Tk, A = a.A, Ti = a.Ti, Tn = a.Tn, G = a.G;
  const int32_t trash = (int32_t)a.Dp1 - 1;
  const int32_t* nd = a.node_domain + n * Tk;
  bool ok = true;
  // (1) incoming pod's required anti-affinity terms
  for (int64_t t = 0; t < Tn && ok; t++) {
    int32_t sel = a.an_sel[u * Tn + t];
    if (sel < 0) continue;
    int32_t dom = nd[a.an_topo[u * Tn + t]];
    if (dom < trash && a.dom_sel[(int64_t)dom * A + sel] > 0.0f) ok = false;
  }
  // (2) existing pods' anti terms matching the incoming pod (symmetric)
  for (int64_t g = 0; g < G && ok; g++) {
    if (!a.matches_sel[(int64_t)u * A + a.anti_g_sel[g]]) continue;
    int32_t dom = nd[a.anti_g_topo[g]];
    if (dom < trash && a.dom_anti[(int64_t)dom * G + g] > 0.0f) ok = false;
  }
  // (3) incoming required affinity
  if (ok && any_at) {
    bool per_ok = true, labels_ok = true;
    for (int64_t t = 0; t < Ti; t++) {
      int32_t sel = a.at_sel[u * Ti + t];
      if (sel < 0) continue;
      int32_t dom = nd[a.at_topo[u * Ti + t]];
      bool has = dom < trash;
      if (!has) labels_ok = false;
      if (!(has && a.dom_sel[(int64_t)dom * A + sel] > 0.0f)) per_ok = false;
    }
    ok = per_ok || (labels_ok && bootstrap);
  }
  return (uint8_t)ok;
}

void interpod_mask(const ScanArgs& a, const Scratch& s, int32_t u, uint8_t* out) {
  const int64_t N = a.N;
  IpBoot b = ip_boot_of(a, s, u);
  for (int64_t n = 0; n < N; n++) out[n] = ip_mask_at(a, u, n, b.any_at, b.bootstrap);
}

// Single-node gpu-share verdict — the loop body of gpu_mask, shared with
// the incremental cache (gpu_free changes only on the bound node at bind).
inline uint8_t gpu_ok_at(const ScanArgs& a, float memq, float cnt, int64_t n) {
  const float* free = a.gpu_free + n * a.Gd;
  float chunks = 0.0f;
  for (int64_t d = 0; d < a.Gd; d++) chunks += std::floor(free[d] / memq);
  return (uint8_t)((chunks >= cnt) && (cnt > 0.0f));
}

void gpu_mask(const ScanArgs& a, int32_t u, uint8_t* out) {
  const int64_t N = a.N;
  float mem = a.gpu_mem[u];
  if (!(mem > 0.0f)) {
    std::memset(out, 1, N);
    return;
  }
  float memq = std::max(mem, 1.0f);
  float cnt = (float)a.gpu_count[u];
  for (int64_t n = 0; n < N; n++) out[n] = gpu_ok_at(a, memq, cnt, n);
}

// Single-node local-PV verdict — the loop body of local_mask, shared with
// the incremental cache (vg_free/dev_free change only on the bound node).
inline uint8_t local_ok_at(const ScanArgs& a, int32_t u, int64_t n) {
  const int64_t Vg = a.Vg, Dv = a.Dv, Mv = a.Mv;
  float lvm = a.lvm_req[u];
  bool ok = true;
  if (lvm > 0.0f) {
    float best = -BIG;
    const float* vf = a.vg_free + n * Vg;
    for (int64_t v = 0; v < Vg; v++) best = std::max(best, vf[v]);
    ok = best >= lvm;
  }
  // exclusive devices: Hall's condition on nested fit sets (volumes
  // sorted descending — common.go:290-349)
  for (int media = 0; media < 2 && ok; media++) {
    const float* sizes = a.dev_req_sizes + ((int64_t)u * 2 + media) * Mv;
    const float* df = a.dev_free + n * Dv;
    const int32_t* dm = a.node_dev_media + n * Dv;
    for (int64_t i = 0; i < Mv; i++) {
      if (!(sizes[i] > 0.0f)) continue;
      int fit_cnt = 0;
      for (int64_t d = 0; d < Dv; d++)
        if (dm[d] == media && df[d] >= sizes[i] && df[d] > 0.0f) fit_cnt++;
      if (fit_cnt < (int)(i + 1)) { ok = false; break; }
    }
  }
  return (uint8_t)ok;
}

void local_mask(const ScanArgs& a, int32_t u, uint8_t* out) {
  const int64_t N = a.N;
  for (int64_t n = 0; n < N; n++) out[n] = local_ok_at(a, u, n);
}

// ---- score raws ----

// Single-node interpod score raw — the loop body of interpod_raw, shared
// with the incremental cache (same float accumulation order, so cached
// values are bit-identical to a full recomputation).
inline float ip_raw_at(const ScanArgs& a, int32_t u, int64_t n) {
  const int64_t Tk = a.Tk, A = a.A, Tpp = a.Tpp, Gp = a.Gp;
  const int32_t trash = (int32_t)a.Dp1 - 1;
  const int32_t* nd = a.node_domain + n * Tk;
  float incoming = 0.0f;
  for (int64_t t = 0; t < Tpp; t++) {
    int32_t sel = a.pt_sel[u * Tpp + t];
    int32_t dom = nd[a.pt_topo[u * Tpp + t]];
    if (sel >= 0 && dom < trash)
      incoming += a.dom_sel[(int64_t)dom * A + sel] * a.pt_w[u * Tpp + t];
  }
  float symmetric = 0.0f;
  for (int64_t g = 0; g < Gp; g++) {
    int32_t dom = nd[a.prefg_topo[g]];
    if (dom < trash)
      symmetric += a.dom_prefw[(int64_t)dom * Gp + g] *
                   (float)a.matches_sel[(int64_t)u * A + a.prefg_sel[g]];
  }
  return incoming + symmetric;
}

void interpod_raw(const ScanArgs& a, int32_t u, float* out) {
  // interpod_score (scoring.go): incoming preferred terms + symmetric terms
  const int64_t N = a.N;
  for (int64_t n = 0; n < N; n++) out[n] = ip_raw_at(a, u, n);
}

bool spread_raw(const ScanArgs& a, int32_t u, const uint8_t* feas, float* out,
                uint8_t* ignored) {
  // spread_score (podtopologyspread/scoring.go:175-248)
  const int64_t N = a.N, Cs = a.Cs, Tk = a.Tk, A = a.A;
  const int32_t trash = (int32_t)a.Dp1 - 1;
  bool any_soft = false;
  for (int64_t c = 0; c < Cs; c++)
    if (a.spr_topo[u * Cs + c] >= 0 && !a.spr_hard[u * Cs + c]) any_soft = true;
  if (!any_soft) return false;
  for (int64_t n = 0; n < N; n++) {
    const int32_t* nd = a.node_domain + n * Tk;
    float raw = 0.0f;
    bool all_labels = true;
    for (int64_t c = 0; c < Cs; c++) {
      int32_t tk = a.spr_topo[u * Cs + c];
      bool soft = tk >= 0 && !a.spr_hard[u * Cs + c];
      if (!soft) continue;
      int32_t dom = nd[tk];
      bool has = dom < trash;
      if (!has) { all_labels = false; continue; }
      float cnt = a.dom_sel[(int64_t)dom * A + a.spr_sel[u * Cs + c]];
      raw += cnt * a.spread_weight[tk] + ((float)a.spr_skew[u * Cs + c] - 1.0f);
    }
    out[n] = raw;
    ignored[n] = feas[n] && !all_labels;
  }
  return true;
}

// Single-node local score raw — the loop body of local_raw, shared with
// the incremental cache (same float op order, so cached raws are
// bit-identical to a full recomputation).
inline float local_raw_at(const ScanArgs& a, int32_t u, int64_t n) {
  const int64_t Vg = a.Vg, Dv = a.Dv;
  float lvm = a.lvm_req[u];
  const float* vf = a.vg_free + n * Vg;
  const float* vc = a.node_vg_cap + n * Vg;
  float tight_free = BIG;
  int64_t choice = 0;
  for (int64_t v = 0; v < Vg; v++) {
    float masked = (vf[v] >= lvm) ? vf[v] : BIG;
    if (masked < tight_free) { tight_free = masked; choice = v; }
  }
  float vg_cap = (Vg > 0) ? vc[choice] : 0.0f;
  float parts = (lvm > 0.0f && tight_free < BIG) ? lvm / std::max(vg_cap, 1.0f) : 0.0f;
  float count = (lvm > 0.0f) ? 1.0f : 0.0f;
  for (int media = 0; media < 2; media++) {
    float size = a.dev_req[(int64_t)u * 2 + media];
    float n_dev = (float)a.dev_req_count[(int64_t)u * 2 + media];
    const float* df = a.dev_free + n * Dv;
    const int32_t* dm = a.node_dev_media + n * Dv;
    float first_cap = BIG;
    for (int64_t d = 0; d < Dv; d++) {
      bool fitting = dm[d] == media && df[d] >= size && df[d] > 0.0f;
      float cap = fitting ? a.node_dev_cap[n * Dv + d] : BIG;
      if (cap < first_cap) first_cap = cap;
    }
    if (size > 0.0f) {
      parts += n_dev * size / std::max(first_cap, 1.0f);
      count += n_dev;
    }
  }
  return (count > 0.0f) ? parts / std::max(count, 1.0f) * 10.0f : 0.0f;
}

void local_raw(const ScanArgs& a, int32_t u, float* out) {
  // local_score (open-local.go:94-138, vendored common.go:487-509,:660-690)
  const int64_t N = a.N;
  for (int64_t n = 0; n < N; n++) out[n] = local_raw_at(a, u, n);
}

// ---- bind (kernels.bind_update) ----

void bind(ScanArgs& a, Scratch& s, int32_t u, int32_t node, float* take_out) {
  const int64_t R = a.R, Tk = a.Tk, A = a.A, Hp = a.Hp, Hq = a.Hports;
  const int64_t G = a.G, Gp = a.Gp, Gd = a.Gd, Vg = a.Vg, Dv = a.Dv, Mv = a.Mv;
  for (int64_t r = 0; r < R; r++) a.used[(int64_t)node * R + r] += a.req[(int64_t)u * R + r];

  if (a.ft_ports) {
    for (int64_t h = 0; h < Hp; h++) {
      int32_t p = a.ports[u * Hp + h];
      if (p >= 0) a.port_used[(int64_t)node * Hq + p] += 1.0f;
    }
  }

  // domain selector counts (gated exactly like Features.sel_counts)
  if (a.ft_interpod || a.ft_spread_hard || a.ft_spread_soft) {
    const uint8_t* m = a.matches_sel + (int64_t)u * A;
    for (int64_t tk = 0; tk < Tk; tk++) {
      int32_t dom = a.node_domain[(int64_t)node * Tk + tk];
      float* row = a.dom_sel + (int64_t)dom * A;
      for (int64_t x = 0; x < A; x++) row[x] += (float)m[x];
      if (a.domain_topo[dom] >= 0) {
        float* tot = s.key_sel_total.data() + tk * A;
        for (int64_t x = 0; x < A; x++) tot[x] += (float)m[x];
      }
    }
  }

  if (a.ft_interpod) {
    for (int64_t g = 0; g < G; g++) {
      int32_t dom = a.node_domain[(int64_t)node * Tk + a.anti_g_topo[g]];
      a.dom_anti[(int64_t)dom * G + g] += (float)a.anti_g[(int64_t)u * G + g];
    }
  }
  if (a.ft_prefg) {
    for (int64_t g = 0; g < Gp; g++) {
      int32_t dom = a.node_domain[(int64_t)node * Tk + a.prefg_topo[g]];
      a.dom_prefw[(int64_t)dom * Gp + g] += a.prefg_w[(int64_t)u * Gp + g];
    }
  }

  // gpu-share packing (AllocateGpuId, gpunodeinfo.go:232-290)
  for (int64_t d = 0; d < Gd; d++) take_out[d] = 0.0f;
  if (a.ft_gpu) {
    float mem = a.gpu_mem[u];
    if (mem > 0.0f) {
      float memq = std::max(mem, 1.0f);
      float cnt = (float)a.gpu_count[u];
      float* free = a.gpu_free + (int64_t)node * Gd;
      if (cnt == 1.0f) {
        // single GPU: tightest fit (first argmin of masked free)
        float best = BIG;
        int64_t tight = 0;
        bool any = false;
        for (int64_t d = 0; d < Gd; d++) {
          float masked = (free[d] >= mem) ? free[d] : BIG;
          if (masked < best) { best = masked; tight = d; }
          if (free[d] >= mem) any = true;
        }
        if (any) take_out[tight] = 1.0f;
      } else {
        // multi GPU: greedy two-pointer packing = prefix-clipped chunks
        float cum = 0.0f;
        for (int64_t d = 0; d < Gd; d++) {
          float chunks = std::floor(free[d] / memq);
          float t = cnt - cum;
          t = std::max(0.0f, std::min(t, chunks));
          take_out[d] = t;
          cum += chunks;
        }
      }
      for (int64_t d = 0; d < Gd; d++) free[d] -= take_out[d] * mem;
    }
  }
  if (a.ft_gc_dyn && !s.gc_dyn.empty())
    s.gc_dyn[node] = gc_dyn_of(a, node);

  if (a.ft_local) {
    // LVM: tightest-fitting VG (ascending free-size first-fit, common.go:111-116)
    float lvm = a.lvm_req[u];
    float* vf = a.vg_free + (int64_t)node * Vg;
    float best = BIG;
    int64_t choice = 0;
    bool any = false;
    for (int64_t v = 0; v < Vg; v++) {
      float masked = (vf[v] >= lvm) ? vf[v] : BIG;
      if (masked < best) { best = masked; choice = v; }
      if (vf[v] >= lvm) any = true;
    }
    if (any && Vg > 0) vf[choice] -= std::max(lvm, 0.0f);

    // exclusive devices: smallest volume first onto the smallest-capacity
    // fitting free device (ties by lowest device index)
    float* df = a.dev_free + (int64_t)node * Dv;
    const float* dc = a.node_dev_cap + (int64_t)node * Dv;
    const int32_t* dm = a.node_dev_media + (int64_t)node * Dv;
    std::vector<uint8_t> taken(Dv, 0);
    for (int media = 0; media < 2; media++) {
      for (int64_t i = Mv - 1; i >= 0; i--) {
        float size = a.dev_req_sizes[((int64_t)u * 2 + media) * Mv + i];
        if (!(size > 0.0f)) continue;
        float bestc = BIG;
        int64_t pick = -1;
        for (int64_t d = 0; d < Dv; d++) {
          bool cand = dm[d] == media && df[d] >= size && df[d] > 0.0f && !taken[d];
          if (cand && dc[d] < bestc) { bestc = dc[d]; pick = d; }
        }
        if (pick >= 0) taken[pick] = 1;
      }
    }
    for (int64_t d = 0; d < Dv; d++)
      if (taken[d]) df[d] = 0.0f;
  }
}

// Failure accounting (pod_step count_fails): first-fail attribution through
// the stage chain; static-filter counts live in static_fail. Assumes
// s.mask[k] is filled for every active stage.
void fail_accounting(ScanArgs& a, Scratch& s, const bool* act, int32_t u, int64_t i) {
  const int64_t N = a.N, R = a.R;
  const uint8_t* sp = a.static_pass + (int64_t)u * N;
  std::vector<uint8_t> passed(sp, sp + N);
  for (int k = 0; k < N_STAGES; k++) {
    // per-resource counts only when the fit plugin is enabled (pod_step's
    // disabled branch zeroes `insufficient`)
    if (k == S_FIT && a.cf_fit) {
      const float* req = a.req + (int64_t)u * R;
      for (int64_t r = 0; r < R; r++) {
        int32_t cnt = 0;
        for (int64_t n = 0; n < N; n++)
          if (passed[n] && a.node_valid[n] && req[r] > 0.0f &&
              a.used[n * R + r] + req[r] > alloc_at(a, s.gc_dyn_ptr(), n, r))
            cnt++;
        a.insufficient[i * R + r] = cnt;
      }
    }
    int32_t cnt = 0;
    if (act[k]) {
      for (int64_t n = 0; n < N; n++) {
        if (passed[n] && !s.mask[k][n]) cnt++;
        passed[n] &= s.mask[k][n];
      }
    }
    a.fail_counts[i * N_STAGES + k] = cnt;
  }
}

struct EnvCtx {
  bool act_ports, act_fit, act_spread, act_interpod, act_gpu, act_local;
  bool use_spr, use_share, use_avoid, use_ip, use_loc;
  float wsp, wshare, wav, wip, wloc;
};

// Decision audit (explain=1): fold one step's first-fail attribution into
// the per-filter reject totals — static filters from the precomputed
// per-template counts, dynamic stages from the row fail_accounting just
// wrote. Kernel filter-index order: 4 static slots then N_STAGES dynamic.
void accumulate_rejects(ScanArgs& a, int32_t u, int64_t i) {
  if (!a.filter_rejects) return;
  for (int k = 0; k < 4; k++)
    a.filter_rejects[k] += (int64_t)a.static_fail[(int64_t)u * 4 + k];
  for (int k = 0; k < N_STAGES; k++)
    a.filter_rejects[4 + k] += (int64_t)a.fail_counts[i * N_STAGES + k];
}

inline float recombine(const TmplCache& tc, const EnvCtx& e, int64_t n) {
  // only called for templates WITHOUT an active soft spread (those
  // combine the spread term on the fly in the select loop)
  float sc = tc.pre[n];
  if (e.use_share) sc += tc.share_term[n];
  if (e.use_avoid) sc += tc.av_term[n];
  return sc;
}

// Full per-template evaluation into the cache. The envelope covers every
// dynamic mask (ports/fit/spread/interpod/gpu/local) and every score term:
// the port/gpu/local carry is per-NODE (a bind touches one node's rows),
// the spread/interpod carry per-DOMAIN, and anything a delta cannot prove
// unchanged bails back here.
void full_eval_env(ScanArgs& a, Scratch& s, TmplCache& tc, const EnvCtx& e,
                   PreCtx& c, int32_t u) {
  const int64_t N = a.N;
  tc.u = u;
  tc.valid = true;
  tc.prev_failed = false;
  tc.pending.clear();

  // hard-spread constraints: one verdict per topology domain (all members
  // share cnt + selfm - min_cnt <= skew); the per-node mask is a gather
  const int32_t trash_d = (int32_t)a.Dp1 - 1;
  tc.hards.clear();
  if (e.act_spread) {
    const uint8_t* am = a.aff_mask + (int64_t)u * N;
    for (int64_t cc = 0; cc < a.Cs; cc++) {
      int32_t tk = a.spr_topo[u * a.Cs + cc];
      if (tk < 0 || !a.spr_hard[u * a.Cs + cc]) continue;
      TmplCache::HardSpread hc;
      hc.tk = tk;
      hc.sel = a.spr_sel[u * a.Cs + cc];
      hc.skew = (float)a.spr_skew[u * a.Cs + cc];
      hc.selfm = (float)a.matches_sel[(int64_t)u * a.A + hc.sel];
      hc.elig.assign(a.Dp1, 0);
      for (int64_t n = 0; n < N; n++) {
        int32_t d = a.node_domain[n * a.Tk + tk];
        if (d < trash_d && am[n] && a.node_valid[n]) hc.elig[d] = 1;
      }
      float mn = BIG;
      for (int32_t d : s.key_doms[tk])
        if (hc.elig[d]) mn = std::min(mn, a.dom_sel[(int64_t)d * a.A + hc.sel]);
      hc.min_cnt = mn;
      hc.verd.assign(a.Dp1, 0);
      for (int32_t d : s.key_doms[tk])
        hc.verd[d] =
            (uint8_t)(a.dom_sel[(int64_t)d * a.A + hc.sel] + hc.selfm - mn <= hc.skew);
      tc.hards.push_back(std::move(hc));
    }
  }
  tc.has_hard = !tc.hards.empty();
  if (tc.has_hard) {
    for (int64_t n = 0; n < N; n++) {
      uint8_t m = 1;
      for (const auto& hc : tc.hards) {
        int32_t d = a.node_domain[n * a.Tk + hc.tk];
        m &= (uint8_t)(d < trash_d && hc.verd[d]);
      }
      tc.sh_mask[n] = m;
    }
  }

  // interpod filter: per-node verdicts cached; the bootstrap flag is a
  // global-count fact re-checked (and bailed on) by every delta
  tc.ip_f_act = false;
  tc.ip_any_at = tc.ip_bootstrap = false;
  if (e.act_interpod) {
    for (int64_t t = 0; t < a.Ti && !tc.ip_f_act; t++)
      if (a.at_sel[u * a.Ti + t] >= 0) tc.ip_f_act = true;
    for (int64_t t = 0; t < a.Tn && !tc.ip_f_act; t++)
      if (a.an_sel[u * a.Tn + t] >= 0) tc.ip_f_act = true;
    for (int64_t g = 0; g < a.G && !tc.ip_f_act; g++)
      if (a.matches_sel[(int64_t)u * a.A + a.anti_g_sel[g]]) tc.ip_f_act = true;
    if (tc.ip_f_act) {
      IpBoot b = ip_boot_of(a, s, u);
      tc.ip_any_at = b.any_at;
      tc.ip_bootstrap = b.bootstrap;
      for (int64_t n = 0; n < N; n++)
        tc.ip_mask[n] = ip_mask_at(a, u, n, b.any_at, b.bootstrap);
    }
  }

  // interpod score: raw cached per node, min/max maintained across deltas
  tc.ip_s_act = false;
  tc.ip_hi_stale = tc.ip_lo_stale = false;
  if (e.use_ip) {
    for (int64_t t = 0; t < a.Tpp && !tc.ip_s_act; t++)
      if (a.pt_sel[u * a.Tpp + t] >= 0) tc.ip_s_act = true;
    for (int64_t g = 0; g < a.Gp && !tc.ip_s_act; g++)
      if (a.matches_sel[(int64_t)u * a.A + a.prefg_sel[g]]) tc.ip_s_act = true;
    // a term-less template's raw is identically 0 → range 0 → the
    // normalized term is exactly 0 for every node: treat as inactive
  }

  // per-resource-class carry: template-level activation + cached per-node
  // verdicts. A class whose template carries no relevant request is all-pass
  // (the batch mask memsets 1) — leave it inactive so deltas cost nothing.
  tc.pt_act = false;
  if (e.act_ports) {
    tc.pt_ids.clear();
    for (int64_t h = 0; h < a.Hp; h++) {
      int32_t p = a.ports[(int64_t)u * a.Hp + h];
      if (p >= 0) tc.pt_ids.push_back(p);
    }
    tc.pt_act = !tc.pt_ids.empty();
    if (tc.pt_act)
      for (int64_t n = 0; n < N; n++)
        tc.pt_mask[n] = ports_ok_at(a, tc.pt_ids.data(), tc.pt_ids.size(), n);
  }
  tc.gp_act = false;
  if (e.act_gpu && a.gpu_mem[u] > 0.0f) {
    tc.gp_act = true;
    tc.gp_memq = std::max(a.gpu_mem[u], 1.0f);
    tc.gp_cnt = (float)a.gpu_count[u];
    for (int64_t n = 0; n < N; n++)
      tc.gp_mask[n] = gpu_ok_at(a, tc.gp_memq, tc.gp_cnt, n);
  }
  // local-PV activation: any LVM request, aggregate device request, or
  // per-volume size (the filter reads sizes, the score reads aggregates —
  // one conservative flag covers both; a miss only costs an all-pass mask
  // or an identically-zero raw, never a wrong verdict)
  bool loc_reqs = a.lvm_req[u] > 0.0f;
  for (int media = 0; media < 2 && !loc_reqs; media++) {
    if (a.dev_req[(int64_t)u * 2 + media] > 0.0f) loc_reqs = true;
    for (int64_t v = 0; v < a.Mv && !loc_reqs; v++)
      if (a.dev_req_sizes[((int64_t)u * 2 + media) * a.Mv + v] > 0.0f) loc_reqs = true;
  }
  tc.lc_f_act = e.act_local && loc_reqs;
  if (tc.lc_f_act)
    for (int64_t n = 0; n < N; n++) tc.lc_mask[n] = local_ok_at(a, u, n);
  // a request-less template's local raw is identically 0 → range 0 → the
  // generic path adds wloc·0 to every node: treat as inactive (±0 never
  // moves a comparison)
  tc.lc_s_act = e.use_loc && loc_reqs;
  tc.lcs_hi_stale = tc.lcs_lo_stale = false;
  // dynamic share: only templates REQUESTING gpu-count read gc_dyn through
  // share_at; for the rest share_at degenerates to the static share_raw row
  // (bit-identical), so the materialized share_term stays valid
  tc.sh_dyn = e.use_share && a.ft_gc_dyn && a.res_gc >= 0 &&
              a.req[(int64_t)u * a.R + a.res_gc] > 0.0f;
  tc.sh_hi_stale = tc.sh_lo_stale = false;

  tc.any_soft = false;
  int n_soft = 0;
  int64_t soft_cc = -1;
  for (int64_t cc = 0; cc < a.Cs; cc++)
    if (a.spr_topo[u * a.Cs + cc] >= 0 && !a.spr_hard[u * a.Cs + cc]) {
      tc.any_soft = true;
      n_soft++;
      soft_cc = cc;
    }
  tc.dom_mode = e.use_spr && n_soft == 1;
  if (tc.dom_mode) {
    const int32_t trash = (int32_t)a.Dp1 - 1;
    tc.dm_tk = a.spr_topo[u * a.Cs + soft_cc];
    tc.dm_sel = a.spr_sel[u * a.Cs + soft_cc];
    tc.dm_w = a.spread_weight[tc.dm_tk];
    tc.dm_k = (float)a.spr_skew[u * a.Cs + soft_cc] - 1.0f;
    tc.dm_V.assign(a.Dp1, 0.0f);
    for (int32_t d = 0; d < trash; d++)
      tc.dm_V[d] = a.dom_sel[(int64_t)d * a.A + tc.dm_sel] * tc.dm_w + tc.dm_k;
    tc.dm_dom.resize(N);
    for (int64_t n = 0; n < N; n++) tc.dm_dom[n] = a.node_domain[n * a.Tk + tc.dm_tk];
    tc.dm_scored.assign(a.Dp1, 0);
  }
  tc.hier_mode = false;
  if (e.use_spr && n_soft == 2) {
    const int32_t trash = (int32_t)a.Dp1 - 1;
    int64_t ccs[2];
    int k = 0;
    for (int64_t cc = 0; cc < a.Cs; cc++)
      if (a.spr_topo[u * a.Cs + cc] >= 0 && !a.spr_hard[u * a.Cs + cc]) ccs[k++] = cc;
    // fine = a cc whose non-trash domains are node-singletons; coarse =
    // the other, with a bounded domain count (global-min recompute is
    // O(coarse domains) per bind). Both facts are per-TOPOLOGY-KEY and
    // template-independent — memoized in Scratch across full_evals.
    if (s.tk_singleton.empty()) {
      s.tk_singleton.assign(a.Tk, -1);
      s.tk_domcount.assign(a.Tk, -1);
    }
    auto tk_facts = [&](int32_t tk) {
      if (s.tk_singleton[tk] < 0) {
        std::vector<int32_t> cnt(a.Dp1, 0);
        bool single = true;
        int64_t doms = 0;
        for (int64_t n = 0; n < N; n++) {
          int32_t d = a.node_domain[n * a.Tk + tk];
          if (d == trash) continue;
          if (++cnt[d] == 1) doms++;
          if (cnt[d] > 1) single = false;
        }
        s.tk_singleton[tk] = single ? 1 : 0;
        s.tk_domcount[tk] = doms;
      }
    };
    auto singleton = [&](int64_t cc) {
      int32_t tk = a.spr_topo[u * a.Cs + cc];
      tk_facts(tk);
      return s.tk_singleton[tk] == 1;
    };
    auto dom_count = [&](int64_t cc) {
      int32_t tk = a.spr_topo[u * a.Cs + cc];
      tk_facts(tk);
      return s.tk_domcount[tk];
    };
    int fine = singleton(ccs[0]) ? 0 : (singleton(ccs[1]) ? 1 : -1);
    if (fine >= 0 && dom_count(ccs[1 - fine]) <= 256) {
      tc.hier_mode = true;
      tc.hier_fine_first = fine == 0;
      int64_t fcc = ccs[fine], ccc = ccs[1 - fine];
      int32_t ftk = a.spr_topo[u * a.Cs + fcc];
      int32_t ctk = a.spr_topo[u * a.Cs + ccc];
      tc.hf_sel = a.spr_sel[u * a.Cs + fcc];
      tc.hc_sel = a.spr_sel[u * a.Cs + ccc];
      tc.hf_w = a.spread_weight[ftk];
      tc.hf_k = (float)a.spr_skew[u * a.Cs + fcc] - 1.0f;
      tc.hc_w = a.spread_weight[ctk];
      tc.hc_k = (float)a.spr_skew[u * a.Cs + ccc] - 1.0f;
      tc.hf_V.assign(a.Dp1, 0.0f);
      tc.hc_V.assign(a.Dp1, 0.0f);
      for (int32_t d = 0; d < trash; d++) {
        tc.hf_V[d] = a.dom_sel[(int64_t)d * a.A + tc.hf_sel] * tc.hf_w + tc.hf_k;
        tc.hc_V[d] = a.dom_sel[(int64_t)d * a.A + tc.hc_sel] * tc.hc_w + tc.hc_k;
      }
      tc.hf_dom.resize(N);
      tc.hc_dom.resize(N);
      tc.hf_lev.resize(N);
      for (int64_t n = 0; n < N; n++) {
        tc.hf_dom[n] = a.node_domain[n * a.Tk + ftk];
        tc.hc_dom[n] = a.node_domain[n * a.Tk + ctk];
        int32_t fd = tc.hf_dom[n];
        tc.hf_lev[n] =
            fd == trash ? 0 : (int32_t)a.dom_sel[(int64_t)fd * a.A + tc.hf_sel];
      }
      tc.hc_hist.assign(a.Dp1, {});
      tc.hc_minlev.assign(a.Dp1, 0);
      tc.hc_maxlev.assign(a.Dp1, 0);
      tc.hc_has.assign(a.Dp1, 0);
    }
  }

  const uint8_t* sp = a.static_pass + (int64_t)u * N;
  const float* share = a.share_raw + (int64_t)u * N;
  float na_m = NEG, tt_m = NEG, shlo = BIG, shhi = NEG;
  float iphi = NEG, iplo = BIG, lclo = BIG, lchi = NEG;
  for (int64_t n = 0; n < N; n++) {
    uint8_t f = sp[n] && (e.act_fit ? fit_at(a, s.gc_dyn_ptr(), u, n) : 1);
    if (tc.pt_act) f = f && tc.pt_mask[n];
    if (tc.gp_act) f = f && tc.gp_mask[n];
    if (tc.lc_f_act) f = f && tc.lc_mask[n];
    if (tc.has_hard) f = f && tc.sh_mask[n];
    if (tc.ip_f_act) f = f && tc.ip_mask[n];
    tc.feas[n] = f;
    if (tc.ip_s_act) {
      float r = ip_raw_at(a, u, n);
      tc.ip_raw[n] = r;
      float v = f ? r : 0.0f;
      iphi = std::max(iphi, v);
      iplo = std::min(iplo, v);
    }
    if (c.use_na) na_m = std::max(na_m, f ? c.na[n] : 0.0f);
    if (c.use_tt) tt_m = std::max(tt_m, f ? c.tt[n] : 0.0f);
    if (e.use_share) {
      float shv = share[n];
      if (tc.sh_dyn) {
        shv = share_at(a, s.gc_dyn_ptr(), u, n);
        tc.sh_val[n] = shv;
      }
      if (f) {
        shlo = std::min(shlo, shv);
        shhi = std::max(shhi, shv);
      }
    }
    if (tc.lc_s_act) {
      float lr = local_raw_at(a, u, n);
      tc.lc_raw[n] = lr;
      if (f) {
        lclo = std::min(lclo, lr);
        lchi = std::max(lchi, lr);
      }
    }
    if (e.use_spr && tc.any_soft) {
      if (tc.dom_mode) {
        // single soft constraint: raw is the domain's value (bit-exact
        // with spr_raw_at: 0.0f + term == term for the non-negative terms)
        int32_t dom = tc.dm_dom[n];
        tc.spr_raw[n] = tc.dm_V[dom];
        tc.ignored[n] = f && dom == (int32_t)a.Dp1 - 1;
      } else if (tc.hier_mode) {
        const int32_t trash = (int32_t)a.Dp1 - 1;
        int32_t fd = tc.hf_dom[n], cd = tc.hc_dom[n];
        float first = tc.hier_fine_first ? tc.hf_V[fd] : tc.hc_V[cd];
        float second = tc.hier_fine_first ? tc.hc_V[cd] : tc.hf_V[fd];
        tc.spr_raw[n] = first + second;  // cc-order sum, trash rows are 0
        tc.ignored[n] = f && (fd == trash || cd == trash);
      } else {
        bool all_labels;
        tc.spr_raw[n] = spr_raw_at(a, u, n, &all_labels);
        tc.ignored[n] = f && !all_labels;
      }
    } else {
      tc.ignored[n] = 0;
    }
  }
  tc.na_max = na_m;
  tc.tt_max = tt_m;
  c.na_max = na_m;
  c.tt_max = tt_m;
  tc.sh_lo = shlo;
  tc.sh_hi = shhi;
  tc.sh_rng = shhi - shlo;
  tc.lc_lo = lclo;
  tc.lc_hi = lchi;
  tc.lc_rng = lchi - lclo;
  tc.ip_rhi = iphi;
  tc.ip_rlo = iplo;
  if (e.use_spr && tc.any_soft) {
    float mn = BIG, mx = NEG;
    for (int64_t n = 0; n < N; n++) {
      if (tc.feas[n] && !tc.ignored[n]) {
        mn = std::min(mn, tc.spr_raw[n]);
        mx = std::max(mx, tc.spr_raw[n]);
        if (tc.dom_mode) tc.dm_scored[tc.dm_dom[n]]++;
        if (tc.hier_mode) {
          int32_t cd = tc.hc_dom[n];
          int32_t lev = tc.hf_lev[n];
          auto& h = tc.hc_hist[cd];
          if ((size_t)lev >= h.size()) h.resize(lev + 1, 0);
          h[lev]++;
          if (!tc.hc_has[cd]) {
            tc.hc_has[cd] = 1;
            tc.hc_minlev[cd] = tc.hc_maxlev[cd] = lev;
          } else {
            tc.hc_minlev[cd] = std::min(tc.hc_minlev[cd], lev);
            tc.hc_maxlev[cd] = std::max(tc.hc_maxlev[cd], lev);
          }
        }
      }
    }
    tc.spr_mn = mn;
    tc.spr_mx = mx;
    if (tc.dom_mode) {
      tc.dm_doms.clear();
      std::vector<int32_t> zidx(a.Dp1, 0);
      for (int32_t d = 0; d < (int32_t)a.Dp1 - 1; d++)
        if (tc.dm_scored[d] > 0) {
          zidx[d] = (int32_t)tc.dm_doms.size();
          tc.dm_doms.push_back(d);
        }
      tc.dm_zi.resize(N);
      for (int64_t n = 0; n < N; n++) tc.dm_zi[n] = zidx[tc.dm_dom[n]];
    }
    if (tc.hier_mode) {
      tc.hc_doms.clear();
      std::vector<int32_t> zidx(a.Dp1, 0);
      for (int32_t d = 0; d < (int32_t)a.Dp1 - 1; d++)
        if (tc.hc_has[d]) {
          zidx[d] = (int32_t)tc.hc_doms.size();
          tc.hc_doms.push_back(d);
        }
      tc.hc_zi.resize(N);
      for (int64_t n = 0; n < N; n++) tc.hc_zi[n] = zidx[tc.hc_dom[n]];
    }
  }
  const float* avoid = a.avoid_score + (int64_t)u * N;
  // select combines on the fly (lazy) whenever a score term's
  // normalization scalars can move between binds (soft spread, interpod,
  // dynamic gpu-share, local-PV score)
  const bool lazy = (e.use_spr && tc.any_soft) || tc.ip_s_act || tc.sh_dyn || tc.lc_s_act;
  for (int64_t n = 0; n < N; n++) {
    tc.pre[n] = pre_at(a, c, n);
    if (e.use_share && !tc.sh_dyn)
      tc.share_term[n] =
          e.wshare * (tc.sh_rng > 0.0f ? (share[n] - tc.sh_lo) * MAXS / tc.sh_rng : 0.0f);
    if (e.use_avoid) tc.av_term[n] = e.wav * avoid[n];
    if (!lazy) tc.score[n] = recombine(tc, e, n);
  }
}

// Fold the pending binds into the cache. Returns false when something it
// cannot prove unchanged shifted (feasible-set flip) — caller re-evaluates;
// *why names the carry class that bailed (Bail slot, for bail_out).
bool apply_deltas(ScanArgs& a, Scratch& s, TmplCache& tc, const EnvCtx& e, PreCtx& c,
                  int* why) {
  const int64_t N = a.N, Tk = a.Tk, Cs = a.Cs, A = a.A;
  const int32_t u = tc.u;
  const int32_t trash_d = (int32_t)a.Dp1 - 1;
  if (tc.ip_f_act) {
    // the affinity bootstrap is a fact about the GLOBAL count map: a flip
    // (it only ever goes true → false; counts grow) moves every node's
    // verdict at once — re-evaluate rather than patch
    IpBoot b = ip_boot_of(a, s, u);
    if (b.bootstrap != tc.ip_bootstrap) { *why = B_INTERPOD; return false; }
  }
  // combined feasibility of node n from the cached masks + a fresh fit
  // probe (a pending bind may have changed n's own used row)
  auto feas_of = [&](int64_t n) -> uint8_t {
    uint8_t f = a.static_pass[(int64_t)u * N + n] &&
                (e.act_fit ? fit_at(a, s.gc_dyn_ptr(), u, n) : 1);
    if (tc.pt_act) f = f && tc.pt_mask[n];
    if (tc.gp_act) f = f && tc.gp_mask[n];
    if (tc.lc_f_act) f = f && tc.lc_mask[n];
    if (tc.has_hard) f = f && tc.sh_mask[n];
    if (tc.ip_f_act) f = f && tc.ip_mask[n];
    return f;
  };
  for (size_t pi = 0; pi < tc.pending.size(); pi++) {
    const int64_t j = tc.pending[pi].first;
    const int32_t bu = tc.pending[pi].second;  // binder's template
    const uint8_t* bm = a.matches_sel + (int64_t)bu * A;

    // --- hard spread: per-domain verdict maintenance ------------------
    // The bind moved dom_sel[dj][sel] only when the bound pod matches the
    // constraint's selector; a verdict flip touches exactly the flipped
    // domain's member nodes (feasibility-flip-bail keeps reductions exact).
    for (auto& hc : tc.hards) {
      if (!bm[hc.sel]) continue;  // counts for this selector did not move
      int32_t dj = a.node_domain[j * Tk + hc.tk];
      if (dj == trash_d) continue;  // only the unread trash row grew
      float mn = BIG;
      for (int32_t d : s.key_doms[hc.tk])
        if (hc.elig[d]) mn = std::min(mn, a.dom_sel[(int64_t)d * A + hc.sel]);
      s.flip_doms.clear();
      if (mn != hc.min_cnt) {
        hc.min_cnt = mn;
        for (int32_t d : s.key_doms[hc.tk]) {
          uint8_t v =
              (uint8_t)(a.dom_sel[(int64_t)d * A + hc.sel] + hc.selfm - mn <= hc.skew);
          if (v != hc.verd[d]) {
            hc.verd[d] = v;
            s.flip_doms.push_back(d);
          }
        }
      } else {
        uint8_t v =
            (uint8_t)(a.dom_sel[(int64_t)dj * A + hc.sel] + hc.selfm - mn <= hc.skew);
        if (v != hc.verd[dj]) {
          hc.verd[dj] = v;
          s.flip_doms.push_back(dj);
        }
      }
      for (int32_t d : s.flip_doms)
        for (int32_t n : s.dom_members[d]) {
          uint8_t m = 1;
          for (const auto& h2 : tc.hards) {
            int32_t dn = a.node_domain[(int64_t)n * Tk + h2.tk];
            m &= (uint8_t)(dn < trash_d && h2.verd[dn]);
          }
          if (m == tc.sh_mask[n]) continue;
          tc.sh_mask[n] = m;
          if (feas_of(n) != tc.feas[n]) {  // feasible set shifted
            *why = B_SPREAD;
            return false;
          }
        }
    }

    // --- interpod filter: affected-domain member recomputation --------
    if (tc.ip_f_act) {
      s.epoch++;
      bool bail = false;
      auto visit_ipm = [&](int32_t d) {
        for (int32_t n : s.dom_members[d]) {
          if (s.visited[n] == s.epoch) continue;
          s.visited[n] = s.epoch;
          uint8_t m = ip_mask_at(a, u, n, tc.ip_any_at, tc.ip_bootstrap);
          if (m == tc.ip_mask[n]) continue;
          tc.ip_mask[n] = m;
          if (feas_of(n) != tc.feas[n]) {
            bail = true;
            return;
          }
        }
      };
      for (int64_t t = 0; t < a.Ti && !bail; t++) {
        int32_t sel = a.at_sel[u * a.Ti + t];
        if (sel < 0 || !bm[sel]) continue;
        int32_t d = a.node_domain[j * Tk + a.at_topo[u * a.Ti + t]];
        if (d < trash_d) visit_ipm(d);
      }
      for (int64_t t = 0; t < a.Tn && !bail; t++) {
        int32_t sel = a.an_sel[u * a.Tn + t];
        if (sel < 0 || !bm[sel]) continue;
        int32_t d = a.node_domain[j * Tk + a.an_topo[u * a.Tn + t]];
        if (d < trash_d) visit_ipm(d);
      }
      for (int64_t g = 0; g < a.G && !bail; g++) {
        if (!a.anti_g[(int64_t)bu * a.G + g]) continue;
        if (!a.matches_sel[(int64_t)u * A + a.anti_g_sel[g]]) continue;
        int32_t d = a.node_domain[j * Tk + a.anti_g_topo[g]];
        if (d < trash_d) visit_ipm(d);
      }
      if (bail) { *why = B_INTERPOD; return false; }
    }

    // --- interpod score raw: affected members + min/max upkeep --------
    // pt/prefg weights are SIGNED (preferred anti-affinity), so a raw can
    // shrink: when a current extremum holder moves inward the reduction is
    // recomputed exactly after the loop (stale flags), never approximated.
    if (tc.ip_s_act) {
      s.epoch++;
      auto visit_ipr = [&](int32_t d) {
        for (int32_t n : s.dom_members[d]) {
          if (s.visited[n] == s.epoch) continue;
          s.visited[n] = s.epoch;
          float nr = ip_raw_at(a, u, n);
          float orr = tc.ip_raw[n];
          if (nr == orr) continue;
          tc.ip_raw[n] = nr;
          if (!tc.feas[n]) continue;  // masked value is 0 either way
          if (orr == tc.ip_rhi && nr < orr)
            tc.ip_hi_stale = true;
          else if (nr > tc.ip_rhi)
            tc.ip_rhi = nr;
          if (orr == tc.ip_rlo && nr > orr)
            tc.ip_lo_stale = true;
          else if (nr < tc.ip_rlo)
            tc.ip_rlo = nr;
        }
      };
      for (int64_t t = 0; t < a.Tpp; t++) {
        int32_t sel = a.pt_sel[u * a.Tpp + t];
        if (sel < 0 || !bm[sel]) continue;
        int32_t d = a.node_domain[j * Tk + a.pt_topo[u * a.Tpp + t]];
        if (d < trash_d) visit_ipr(d);
      }
      for (int64_t g = 0; g < a.Gp; g++) {
        if (a.prefg_w[(int64_t)bu * a.Gp + g] == 0.0f) continue;
        if (!a.matches_sel[(int64_t)u * A + a.prefg_sel[g]]) continue;
        int32_t d = a.node_domain[j * Tk + a.prefg_topo[g]];
        if (d < trash_d) visit_ipr(d);
      }
    }

    // --- per-resource-class carry: the bind touched ONLY node j's
    // port_used/gpu_free/gc_dyn/vg_free/dev_free rows — recompute j's
    // verdicts with the exact single-node helpers; every other node's
    // cached verdict is untouched by construction
    int flip_why = B_FIT;
    if (a.ft_gc_dyn && a.res_gc >= 0 && a.req[(int64_t)u * a.R + a.res_gc] > 0.0f)
      flip_why = B_GCDYN;
    if (tc.pt_act) {
      uint8_t m = ports_ok_at(a, tc.pt_ids.data(), tc.pt_ids.size(), j);
      if (m != tc.pt_mask[j]) { tc.pt_mask[j] = m; flip_why = B_PORTS; }
    }
    if (tc.gp_act) {
      uint8_t m = gpu_ok_at(a, tc.gp_memq, tc.gp_cnt, j);
      if (m != tc.gp_mask[j]) { tc.gp_mask[j] = m; flip_why = B_GPU; }
    }
    if (tc.lc_f_act) {
      uint8_t m = local_ok_at(a, u, j);
      if (m != tc.lc_mask[j]) { tc.lc_mask[j] = m; flip_why = B_LOCAL; }
    }
    uint8_t f = feas_of(j);
    if (f != tc.feas[j]) {  // feasible set shifted: reductions stale
      *why = flip_why;
      return false;
    }
    tc.pre[j] = pre_at(a, c, j);
    // dynamic score raws at j (share under gc_dyn, local-PV): min/max via
    // the ip_rhi/ip_rlo stale-flag pattern — update in place when the new
    // value extends the range, recompute exactly when an extremum holder
    // moved inward (the feasible set is frozen: flips bailed above)
    if (tc.sh_dyn) {
      float nv = share_at(a, s.gc_dyn_ptr(), u, j);
      float ov = tc.sh_val[j];
      if (nv != ov) {
        tc.sh_val[j] = nv;
        if (tc.feas[j]) {  // reductions are over feasible nodes only
          if (ov == tc.sh_hi && nv < ov)
            tc.sh_hi_stale = true;
          else if (nv > tc.sh_hi)
            tc.sh_hi = nv;
          if (ov == tc.sh_lo && nv > ov)
            tc.sh_lo_stale = true;
          else if (nv < tc.sh_lo)
            tc.sh_lo = nv;
        }
      }
    }
    if (tc.lc_s_act) {
      float nv = local_raw_at(a, u, j);
      float ov = tc.lc_raw[j];
      if (nv != ov) {
        tc.lc_raw[j] = nv;
        if (tc.feas[j]) {
          if (ov == tc.lc_hi && nv < ov)
            tc.lcs_hi_stale = true;
          else if (nv > tc.lc_hi)
            tc.lc_hi = nv;
          if (ov == tc.lc_lo && nv > ov)
            tc.lcs_lo_stale = true;
          else if (nv < tc.lc_lo)
            tc.lc_lo = nv;
        }
      }
    }

    if (e.use_spr && tc.any_soft && tc.dom_mode) {
      // single soft constraint: every member of j's domain shares one raw
      // value — update it and the min/max scalars in O(1) (+ an
      // O(domains) min rescan when the previous-minimum domain grew)
      const int32_t trash = (int32_t)a.Dp1 - 1;
      int32_t jdom = tc.dm_dom[j];
      if (jdom != trash) {
        float newV =
            a.dom_sel[(int64_t)jdom * a.A + tc.dm_sel] * tc.dm_w + tc.dm_k;
        float oldV = tc.dm_V[jdom];
        if (newV != oldV) {
          tc.dm_V[jdom] = newV;
          if (tc.dm_scored[jdom] > 0) {
            tc.spr_mx = std::max(tc.spr_mx, newV);
            if (oldV <= tc.spr_mn) {
              float mn = BIG;
              for (int32_t d = 0; d < trash; d++)
                if (tc.dm_scored[d] > 0) mn = std::min(mn, tc.dm_V[d]);
              tc.spr_mn = mn;
            }
          }
        }
      }
    } else if (e.use_spr && tc.any_soft && tc.hier_mode) {
      // default-spread pair: O(1) term updates; min/max via the
      // per-coarse-domain histograms of the fine count level
      const int32_t trash = (int32_t)a.Dp1 - 1;
      int32_t fd = tc.hf_dom[j], cd = tc.hc_dom[j];
      bool cd_changed = false;
      if (fd != trash) {
        float fcount = a.dom_sel[(int64_t)fd * a.A + tc.hf_sel];
        float nV = fcount * tc.hf_w + tc.hf_k;
        if (nV != tc.hf_V[fd]) {
          tc.hf_V[fd] = nV;
          int32_t nl = (int32_t)fcount;
          int32_t ol = tc.hf_lev[j];
          tc.hf_lev[j] = nl;
          if (tc.feas[j] && !tc.ignored[j] && nl != ol) {
            auto& h = tc.hc_hist[cd];
            if ((size_t)nl >= h.size()) h.resize(nl + 1, 0);
            h[ol]--;
            h[nl]++;
            if (nl > tc.hc_maxlev[cd]) tc.hc_maxlev[cd] = nl;
            if (ol == tc.hc_minlev[cd])
              while (tc.hc_minlev[cd] < tc.hc_maxlev[cd] &&
                     h[tc.hc_minlev[cd]] == 0)
                tc.hc_minlev[cd]++;
            cd_changed = true;
          }
        }
      }
      if (cd != trash) {
        float nV = a.dom_sel[(int64_t)cd * a.A + tc.hc_sel] * tc.hc_w + tc.hc_k;
        if (nV != tc.hc_V[cd]) {
          tc.hc_V[cd] = nV;
          if (tc.hc_has[cd]) cd_changed = true;
        }
      }
      if (cd_changed && cd != trash && tc.hc_has[cd]) {
        // fine value from the integer level: (float)lev equals the count
        // float exactly (< 2^24), so these sums are bit-identical to the
        // per-node spr_raw_at recomputation
        auto dom_raw = [&](int32_t d, int32_t lev) {
          float fv = (float)lev * tc.hf_w + tc.hf_k;
          float cv = tc.hc_V[d];
          return tc.hier_fine_first ? fv + cv : cv + fv;
        };
        tc.spr_mx = std::max(tc.spr_mx, dom_raw(cd, tc.hc_maxlev[cd]));
        float mn = BIG;
        for (int32_t d : tc.hc_doms) mn = std::min(mn, dom_raw(d, tc.hc_minlev[d]));
        tc.spr_mn = mn;
      }
    } else if (e.use_spr && tc.any_soft) {
      // only nodes sharing a soft-constraint domain with j see new counts;
      // walk the per-domain member lists instead of scanning the node axis
      const int32_t trash = (int32_t)a.Dp1 - 1;
      s.epoch++;
      s.touch.clear();
      float max_new_aff = NEG;
      bool mn_rescan = false;
      for (int64_t cc = 0; cc < Cs; cc++) {
        int32_t tk = a.spr_topo[u * Cs + cc];
        if (tk < 0 || a.spr_hard[u * Cs + cc]) continue;
        int32_t jdom = a.node_domain[j * Tk + tk];
        const std::vector<int32_t>& mem =
            (jdom == trash) ? s.trash_members[tk] : s.dom_members[jdom];
        for (int32_t n : mem) {
          if (s.visited[n] == s.epoch) continue;
          s.visited[n] = s.epoch;
          s.touch.push_back(n);
          bool scored = tc.feas[n] && !tc.ignored[n];
          if (scored && tc.spr_raw[n] <= tc.spr_mn) mn_rescan = true;
          bool all_labels;
          float nr = spr_raw_at(a, u, n, &all_labels);
          tc.spr_raw[n] = nr;
          if (scored) max_new_aff = std::max(max_new_aff, nr);
        }
      }
      // counts only grow, so max updates in place; min moves only if the
      // old minimum sat in an affected domain
      float new_mx = std::max(tc.spr_mx, max_new_aff);
      float new_mn = tc.spr_mn;
      if (mn_rescan) {
        new_mn = BIG;
        const uint8_t* fe = tc.feas.data();
        const uint8_t* ig = tc.ignored.data();
        const float* raw = tc.spr_raw.data();
        for (int64_t n = 0; n < N; n++) {
          float v = (fe[n] && !ig[n]) ? raw[n] : BIG;
          new_mn = std::min(new_mn, v);
        }
      }
      tc.spr_mx = new_mx;
      tc.spr_mn = new_mn;
      // NOTE: no materialized score for any_soft templates — the select
      // loop combines pre/spr/share/avoid on the fly (identical float op
      // order to the old recombine()+spr_term path, so placements are
      // unchanged). A moved normalization scalar therefore costs nothing
      // here, where it used to rewrite term+score over the node axis.
    }
    if (!(e.use_spr && tc.any_soft) && !tc.ip_s_act && !tc.sh_dyn && !tc.lc_s_act &&
        tc.feas[j])
      tc.score[j] = recombine(tc, e, j);
  }
  if (tc.ip_hi_stale || tc.ip_lo_stale) {
    // an extremum holder moved inward: recompute the exact reduction over
    // the (unchanged — we would have bailed) feasible set
    float hi = NEG, lo = BIG;
    for (int64_t n = 0; n < N; n++) {
      float v = tc.feas[n] ? tc.ip_raw[n] : 0.0f;
      hi = std::max(hi, v);
      lo = std::min(lo, v);
    }
    tc.ip_rhi = hi;
    tc.ip_rlo = lo;
    tc.ip_hi_stale = tc.ip_lo_stale = false;
  }
  if (tc.sh_hi_stale || tc.sh_lo_stale) {
    float hi = NEG, lo = BIG;
    for (int64_t n = 0; n < N; n++)
      if (tc.feas[n]) {
        hi = std::max(hi, tc.sh_val[n]);
        lo = std::min(lo, tc.sh_val[n]);
      }
    tc.sh_hi = hi;
    tc.sh_lo = lo;
    tc.sh_hi_stale = tc.sh_lo_stale = false;
  }
  if (tc.sh_dyn) tc.sh_rng = tc.sh_hi - tc.sh_lo;
  if (tc.lcs_hi_stale || tc.lcs_lo_stale) {
    float hi = NEG, lo = BIG;
    for (int64_t n = 0; n < N; n++)
      if (tc.feas[n]) {
        hi = std::max(hi, tc.lc_raw[n]);
        lo = std::min(lo, tc.lc_raw[n]);
      }
    tc.lc_hi = hi;
    tc.lc_lo = lo;
    tc.lcs_hi_stale = tc.lcs_lo_stale = false;
  }
  if (tc.lc_s_act) tc.lc_rng = tc.lc_hi - tc.lc_lo;
  tc.pending.clear();
  return true;
}

}  // namespace

namespace {
// OPENSIM_NATIVE_PROFILE=1: accumulate per-phase wall time and step
// counts, printed to stderr at the end of each run.
struct Prof {
  bool on = false;
  double t[6] = {};  // delta, full_eval, argmax, bind, fail, generic
  int64_t c[6] = {};
  std::chrono::steady_clock::time_point t0;
  void start() {
    if (on) t0 = std::chrono::steady_clock::now();
  }
  void stop(int k) {
    if (!on) return;
    auto t1 = std::chrono::steady_clock::now();
    t[k] += std::chrono::duration<double>(t1 - t0).count();
    c[k]++;
    t0 = t1;
  }
  void report() const {
    if (!on) return;
    const char* names[6] = {"delta", "full_eval", "argmax", "bind", "fail", "generic"};
    for (int k = 0; k < 6; k++)
      if (c[k])
        std::fprintf(stderr, "[native] %-9s %8.3fs over %8lld steps (%.1f us/step)\n",
                     names[k], t[k], (long long)c[k], t[k] / c[k] * 1e6);
  }
  void dump(double* out) const {  // {seconds, steps} pairs, phase order above
    for (int k = 0; k < 6; k++) {
      out[2 * k] = t[k];
      out[2 * k + 1] = (double)c[k];
    }
  }
};
}  // namespace

extern "C" int opensim_run_scan(ScanArgs* ap) {
  ScanArgs& a = *ap;
  const int64_t N = a.N, R = a.R, P = a.P, A = a.A, Tk = a.Tk, Gd = a.Gd;
  Prof prof;
  prof.on = std::getenv("OPENSIM_NATIVE_PROFILE") != nullptr;
  Scratch s;
  s.feas.resize(N);
  for (auto& m : s.mask) m.resize(N);
  s.raw_ip.resize(N);
  s.raw_spr.resize(N);
  s.raw_loc.resize(N);
  s.spr_ignored.resize(N);
  s.affected.resize(N);
  s.take.resize(std::max<int64_t>(Gd, 1));
  // global per-(topology key, selector) match totals for the interpod
  // bootstrap (Σ over real domains of dom_sel — trash row excluded because
  // domain_topo[trash] = -1); maintained incrementally on bind
  s.key_sel_total.assign(Tk * A, 0.0f);
  if (a.ft_gc_dyn) {
    s.gc_dyn.resize(N);
    for (int64_t n = 0; n < N; n++) s.gc_dyn[n] = gc_dyn_of(a, n);
  }
  for (int64_t d = 0; d < a.Dp1; d++) {
    int32_t tk = a.domain_topo[d];
    if (tk < 0) continue;
    for (int64_t x = 0; x < A; x++) s.key_sel_total[(int64_t)tk * A + x] += a.dom_sel[d * A + x];
  }

  const bool act_ports = a.ft_ports && a.cf_ports;
  const bool act_fit = a.cf_fit;
  const bool act_spread = a.ft_spread_hard && a.cf_spread;
  const bool act_interpod = a.ft_interpod && a.cf_interpod;
  const bool act_gpu = a.ft_gpu && a.cf_gpu;
  const bool act_local = a.ft_local && a.cf_local;
  const bool act[N_STAGES] = {act_ports, act_fit, act_spread, act_interpod,
                              act_gpu, act_local, false};

  const float wb = (float)a.w_balanced, wl = (float)a.w_least;
  const float wna = (float)a.w_node_affinity, wtt = (float)a.w_taint_toleration;
  const float wip = (float)a.w_interpod, wsp = (float)a.w_spread;
  const float wav = (float)a.w_prefer_avoid, wloc = (float)a.w_local;
  const double wshare_d = a.w_simon + a.w_gpu_share;
  const float wshare = (float)wshare_d;
  const bool use_bal = a.w_balanced != 0.0, use_least = a.w_least != 0.0;
  const bool use_na = a.ft_pref_na && a.w_node_affinity != 0.0;
  const bool use_tt = a.ft_pref_taints && a.w_taint_toleration != 0.0;
  const bool use_ip = (a.ft_prefg || a.ft_interpod) && a.w_interpod != 0.0;
  const bool use_spr = a.ft_spread_soft && a.w_spread != 0.0;
  const bool use_share = wshare_d != 0.0;
  const bool use_loc = a.ft_local && a.w_local != 0.0;
  const bool use_avoid = a.ft_prefer_avoid && a.w_prefer_avoid != 0.0;

  // Incremental same-template envelope: every dynamic mask and score term
  // now has carry — per-domain for spread/interpod (dom_sel/dom_anti/
  // dom_prefw), per-NODE for ports/gpu-share/local-PV/gc_dyn (a bind
  // mutates only the bound node's port_used/gpu_free/vg_free/dev_free
  // rows). Only the whole-scan gates remain: explain (audits every step's
  // verdict masks — only the generic path materializes them), a Cs beyond
  // the spread-carry bound, and the force-generic escape hatch
  // (OPENSIM_NATIVE_FORCE_GENERIC=1, parity harness + attribution: a tuned
  // number must name the path that made it).
  const char* fg_env = std::getenv("OPENSIM_NATIVE_FORCE_GENERIC");
  const bool force_generic = fg_env && fg_env[0] && std::strcmp(fg_env, "0") != 0;
  const bool explain = a.explain != 0;
  const bool inc_ok = !force_generic && !explain && a.Cs <= 16;
  if (!inc_ok && a.bail_out) {
    // envelope-gate attribution: one count per scan per closed gate
    if (force_generic) a.bail_out[B_FORCE]++;
    if (explain) a.bail_out[B_EXPLAIN]++;
    if (a.Cs > 16) a.bail_out[B_CS]++;
  }
  constexpr size_t MAX_PENDING = 8;
  TmplCache tc;
  EnvCtx env{act_ports, act_fit, act_spread, act_interpod, act_gpu, act_local,
             use_spr, use_share, use_avoid, use_ip, use_loc,
             wsp, wshare, wav, wip, wloc};
  int32_t n_inc = 0, n_gen = 0, n_full = 0;  // path attribution
  // engagement attribution: incremental steps served with each carry class
  // active (nativepath "classes" keys: ports, gpu, local, score)
  auto count_classes = [&](const TmplCache& t) {
    if (!a.class_steps) return;
    if (t.pt_act) a.class_steps[0]++;
    if (t.gp_act) a.class_steps[1]++;
    if (t.lc_f_act) a.class_steps[2]++;
    if (t.sh_dyn || t.lc_s_act) a.class_steps[3]++;
  };
  if (inc_ok) {
    tc.feas.resize(N);
    tc.ignored.resize(N);
    tc.pre.resize(N);
    tc.spr_raw.resize(N);
    tc.share_term.resize(N);
    tc.av_term.resize(N);
    tc.score.resize(N);
    tc.fail_row.resize(N_STAGES);
    tc.ins_row.resize(R);
    if (act_interpod) tc.ip_mask.resize(N);
    if (use_ip) tc.ip_raw.resize(N);
    if (act_spread) tc.sh_mask.resize(N);
    if (act_ports) tc.pt_mask.resize(N);
    if (act_gpu) tc.gp_mask.resize(N);
    if (act_local) tc.lc_mask.resize(N);
    if (use_loc) tc.lc_raw.resize(N);
    if (use_share && a.ft_gc_dyn) tc.sh_val.resize(N);
    // per-domain node lists for the delta path (a real domain belongs to
    // exactly one topology key; the shared trash row gets per-key lists)
    s.dom_members.resize(a.Dp1);
    s.trash_members.resize(Tk);
    s.key_doms.resize(Tk);
    s.visited.assign(N, 0);
    const int32_t trash = (int32_t)a.Dp1 - 1;
    for (int64_t tk = 0; tk < Tk; tk++)
      for (int64_t n = 0; n < N; n++) {
        int32_t d = a.node_domain[n * Tk + tk];
        if (d == trash)
          s.trash_members[tk].push_back((int32_t)n);
        else
          s.dom_members[d].push_back((int32_t)n);
      }
    for (int32_t d = 0; d < trash; d++) {
      int32_t tk = a.domain_topo[d];
      if (tk >= 0) s.key_doms[tk].push_back(d);
    }
  }

  for (int64_t i = 0; i < P; i++) {
    a.chosen[i] = -1;
    if (!a.pod_valid[i]) continue;
    const int32_t u = a.tmpl_ids[i];

    if (a.forced[i]) {
      // forced-bind path (scheduler._step: simulator.go:329-331 — pods with
      // spec.nodeName never reach the scheduler but still drain resources)
      int32_t p = a.pin[u];
      if (p >= 0) {
        bind(a, s, u, p, s.take.data());
        a.chosen[i] = p;
        for (int64_t d = 0; d < Gd; d++) a.gpu_take[i * Gd + d] = s.take[d];
        if (tc.valid) {
          tc.pending.push_back({p, u});
          if (tc.pending.size() > MAX_PENDING) {
            tc.valid = false;
            if (a.bail_out) a.bail_out[B_PENDING]++;
          }
        }
      }
      continue;
    }

    if (inc_ok) {
      n_inc++;
      PreCtx pc;
      pc.cpuq = 0;  // filled below
      pc.memq = 0;
      pc.na_max = tc.na_max;
      pc.tt_max = tc.tt_max;
      pc.wb = wb;
      pc.wl = wl;
      pc.wna = wna;
      pc.wtt = wtt;
      pc.use_bal = use_bal;
      pc.use_least = use_least;
      pc.use_na = use_na;
      pc.use_tt = use_tt;
      pc.na = a.na_raw + (int64_t)u * N;
      pc.tt = a.tt_raw + (int64_t)u * N;
      float cpu = a.req[(int64_t)u * R + a.res_cpu];
      float mem = a.req[(int64_t)u * R + a.res_mem];
      pc.cpuq = cpu > 0.0f ? cpu : 100.0f;
      pc.memq = mem > 0.0f ? mem : 200.0f * 1024.0f * 1024.0f;

      bool cached = tc.valid && tc.u == u;
      if (cached && tc.prev_failed && tc.pending.empty()) {
        // state untouched since the failed evaluation → identical verdict
        for (int k = 0; k < N_STAGES; k++) a.fail_counts[i * N_STAGES + k] = tc.fail_row[k];
        for (int64_t r = 0; r < R; r++) a.insufficient[i * R + r] = tc.ins_row[r];
        count_classes(tc);
        continue;
      }
      prof.start();
      if (cached && !tc.pending.empty()) {
        int why = B_FIT;
        if (!apply_deltas(a, s, tc, env, pc, &why)) {
          tc.valid = false;
          cached = false;
          if (a.bail_out) a.bail_out[why]++;
        }
        prof.stop(0);
      }
      if (!(tc.valid && tc.u == u)) {
        prof.start();
        full_eval_env(a, s, tc, env, pc, u);
        n_full++;
        prof.stop(1);
      }
      count_classes(tc);

      prof.start();
      // two-pass first-argmax: a branchless masked max (vectorizes), then
      // the first index attaining it — identical to the strict > scan.
      // For soft-spread templates the score is combined on the fly from
      // its cached components (pre + wsp·norm + share + avoid, the exact
      // recombine() op order) so binds never rewrite a full score axis.
      float best = NEG;
      int32_t bi = -1;
      const uint8_t* fe = tc.feas.data();
      const bool lazy_spr = env.use_spr && tc.any_soft;
      const bool uip = tc.ip_s_act;
      const bool shd = tc.sh_dyn;
      const bool ulc = tc.lc_s_act;
      const bool lazy = lazy_spr || uip || shd || ulc;
      const bool dm = tc.dom_mode;
      const bool hm = tc.hier_mode;
      const bool hff = tc.hier_fine_first;
      const float* sc = tc.score.data();
      const float* pre = tc.pre.data();
      const float* raw = tc.spr_raw.data();
      const float* dmV = dm ? tc.dm_V.data() : nullptr;
      const int32_t* dmD = dm ? tc.dm_dom.data() : nullptr;
      const float* hfV = hm ? tc.hf_V.data() : nullptr;
      const float* hcV = hm ? tc.hc_V.data() : nullptr;
      const int32_t* hfD = hm ? tc.hf_dom.data() : nullptr;
      const int32_t* hcD = hm ? tc.hc_dom.data() : nullptr;
      const float* sht = tc.share_term.data();
      const float* avt = tc.av_term.data();
      const uint8_t* ig = tc.ignored.data();
      const float l_mx = tc.spr_mx, l_mn = tc.spr_mn;
      const float l_denom = std::max(l_mx, 1.0f);
      const bool ush = env.use_share, uav = env.use_avoid;
      const float l_wsp = env.wsp, l_wip = env.wip;
      // interpod normalization scalars, exactly the generic path's
      // ip_hi/ip_lo/ip_rng derivation from the raw reductions
      const float* ipr = uip ? tc.ip_raw.data() : nullptr;
      const float l_ip_hi = uip ? std::max(tc.ip_rhi, 0.0f) : 0.0f;
      const float l_ip_lo = uip ? std::min(tc.ip_rlo, 0.0f) : 0.0f;
      const float l_ip_rng = l_ip_hi - l_ip_lo;
      auto ip_term = [&](int64_t n) -> float {
        return l_wip * (l_ip_rng > 0.0f
                            ? MAXS * (ipr[n] - l_ip_lo) / std::max(l_ip_rng, 1.0f)
                            : 0.0f);
      };
      // dynamic share + local-PV score terms (abi v5): normalization
      // scalars maintained across deltas, combined with the generic
      // path's exact float expressions and term order (share before
      // local, both before avoid)
      const float* shv = shd ? tc.sh_val.data() : nullptr;
      const float l_sh_lo = tc.sh_lo, l_sh_rng = tc.sh_rng;
      const float l_wshare = env.wshare;
      const float* lcr = ulc ? tc.lc_raw.data() : nullptr;
      const float l_lc_lo = tc.lc_lo, l_lc_rng = tc.lc_rng;
      const float l_wloc = env.wloc;
      auto sc_at = [&](int64_t n) -> float {
        if (!lazy) return sc[n];
        float v = pre[n];
        if (uip) v += ip_term(n);
        if (lazy_spr) {
          float r;
          if (dm)
            r = dmV[dmD[n]];
          else if (hm) {
            float fv = hfV[hfD[n]], cv = hcV[hcD[n]];
            r = hff ? fv + cv : cv + fv;
          } else
            r = raw[n];
          float norm = (l_mx <= 0.0f) ? MAXS : MAXS * (l_mx + l_mn - r) / l_denom;
          norm = ig[n] ? 0.0f : norm;
          v += l_wsp * norm;
        }
        if (ush) {
          if (shd)
            v += l_wshare *
                 (l_sh_rng > 0.0f ? (shv[n] - l_sh_lo) * MAXS / l_sh_rng : 0.0f);
          else
            v += sht[n];
        }
        if (ulc)
          v += l_wloc *
               (l_lc_rng > 0.0f ? (lcr[n] - l_lc_lo) * MAXS / l_lc_rng : 0.0f);
        if (uav) v += avt[n];
        return v;
      };
      // hier fast path: the spread term takes at most (zones × levels)
      // distinct values per step — precompute the normed term once (same
      // float expression as sc_at, so scores are bit-identical) and run
      // the select as a division-free gather loop
      const float* T = nullptr;
      int64_t TL = 0;
      const int32_t* zi = nullptr;
      const int32_t* lv = nullptr;
      if (lazy_spr && hm) {
        int32_t maxl = 0;
        for (int32_t d : tc.hc_doms) maxl = std::max(maxl, tc.hc_maxlev[d]);
        TL = (int64_t)maxl + 1;
        int64_t Z = (int64_t)tc.hc_doms.size();
        if (Z > 0 && Z * TL <= 4096) {
          tc.sel_T.resize(Z * TL);
          for (int64_t z = 0; z < Z; z++) {
            float cv = tc.hc_V[tc.hc_doms[z]];
            for (int64_t l = 0; l < TL; l++) {
              float fv = (float)l * tc.hf_w + tc.hf_k;
              float r = hff ? fv + cv : cv + fv;
              float norm =
                  (l_mx <= 0.0f) ? MAXS : MAXS * (l_mx + l_mn - r) / l_denom;
              tc.sel_T[z * TL + l] = l_wsp * norm;
            }
          }
          T = tc.sel_T.data();
          zi = tc.hc_zi.data();
          lv = tc.hf_lev.data();
        }
      } else if (lazy_spr && dm && !tc.dm_doms.empty() &&
                 (int64_t)tc.dm_doms.size() <= 4096) {
        // single-constraint LUT: one normed term per scored domain
        TL = 1;
        int64_t Z = (int64_t)tc.dm_doms.size();
        tc.sel_T.resize(Z);
        for (int64_t z = 0; z < Z; z++) {
          float r = tc.dm_V[tc.dm_doms[z]];
          float norm = (l_mx <= 0.0f) ? MAXS : MAXS * (l_mx + l_mn - r) / l_denom;
          tc.sel_T[z] = l_wsp * norm;
        }
        T = tc.sel_T.data();
        zi = tc.dm_zi.data();
        lv = nullptr;
      }
      auto sc_fast = [&](int64_t n) -> float {
        // ignored nodes may carry fine levels beyond the scored LUT range
        // (e.g. a zone-less host full of pods): never index T for them
        float t = ig[n] ? 0.0f : T[(int64_t)zi[n] * TL + (lv ? lv[n] : 0)];
        float v = pre[n];
        if (uip) v += ip_term(n);
        v += t;
        if (ush) {
          if (shd)
            v += l_wshare *
                 (l_sh_rng > 0.0f ? (shv[n] - l_sh_lo) * MAXS / l_sh_rng : 0.0f);
          else
            v += sht[n];
        }
        if (ulc)
          v += l_wloc *
               (l_lc_rng > 0.0f ? (lcr[n] - l_lc_lo) * MAXS / l_lc_rng : 0.0f);
        if (uav) v += avt[n];
        return v;
      };
      if (!a.tie_sample && lazy) {
        // gather-based lazy scoring doesn't vectorize, so the two-pass
        // max+find does double work: one strict-> pass yields the same
        // lowest-index argmax on the same float values
        if (T != nullptr) {
          for (int64_t n = 0; n < N; n++) {
            if (!fe[n]) continue;
            float v = sc_fast(n);
            if (v > best) { best = v; bi = (int32_t)n; }
          }
        } else {
          for (int64_t n = 0; n < N; n++) {
            if (!fe[n]) continue;
            float v = sc_at(n);
            if (v > best) { best = v; bi = (int32_t)n; }
          }
        }
        prof.stop(2);
        goto selected;
      }
      if (T != nullptr) {
        for (int64_t n = 0; n < N; n++) {
          float v = fe[n] ? sc_fast(n) : NEG;
          best = std::max(best, v);
        }
      } else {
        for (int64_t n = 0; n < N; n++) {
          float v = fe[n] ? sc_at(n) : NEG;
          best = std::max(best, v);
        }
      }
      if (best > NEG) {
        if (a.tie_sample) {
          // reservoir over the score maxima: uniform, seeded per step
          uint64_t rs = (uint64_t)a.tie_seed * 0x9E3779B97F4A7C15ULL + (uint64_t)i;
          uint64_t c = 0;
          for (int64_t n = 0; n < N; n++)
            if (fe[n] && (T ? sc_fast(n) : sc_at(n)) == best) {
              c++;
              if (sm64_next(&rs) % c == 0) bi = (int32_t)n;
            }
        } else {
          for (int64_t n = 0; n < N; n++)
            if (fe[n] && (T ? sc_fast(n) : sc_at(n)) == best) {
              bi = (int32_t)n;
              break;
            }
        }
      }
      prof.stop(2);

    selected:
      if (bi < 0) {
        prof.start();
        // fail_accounting reads every ACTIVE stage mask; under the v5
        // envelope that is any dynamic stage (ports/fit/spread/interpod/
        // gpu/local) — materialized here only on the cold failure path
        if (act_ports) ports_mask(a, u, s.mask[S_PORTS].data());
        if (act_fit) fit_mask(a, s.gc_dyn_ptr(), u, s.mask[S_FIT].data());
        if (act_spread) spread_mask(a, u, s.mask[S_SPREAD].data());
        if (act_interpod) interpod_mask(a, s, u, s.mask[S_INTERPOD].data());
        if (act_gpu) gpu_mask(a, u, s.mask[S_GPU].data());
        if (act_local) local_mask(a, u, s.mask[S_LOCAL].data());
        fail_accounting(a, s, act, u, i);
        tc.prev_failed = true;
        for (int k = 0; k < N_STAGES; k++) tc.fail_row[k] = a.fail_counts[i * N_STAGES + k];
        for (int64_t r = 0; r < R; r++) tc.ins_row[r] = a.insufficient[i * R + r];
        prof.stop(4);
        continue;
      }
      tc.prev_failed = false;
      prof.start();
      bind(a, s, u, bi, s.take.data());
      prof.stop(3);
      tc.pending.push_back({bi, u});
      a.chosen[i] = bi;
      for (int64_t d = 0; d < Gd; d++) a.gpu_take[i * Gd + d] = s.take[d];
      continue;
    }
    n_gen++;
    prof.start();

    // --- Filter: active dynamic masks over the full node axis ---
    if (act_ports) ports_mask(a, u, s.mask[S_PORTS].data());
    if (act_fit) fit_mask(a, s.gc_dyn_ptr(), u, s.mask[S_FIT].data());
    if (act_spread) spread_mask(a, u, s.mask[S_SPREAD].data());
    if (act_interpod) interpod_mask(a, s, u, s.mask[S_INTERPOD].data());
    if (act_gpu) gpu_mask(a, u, s.mask[S_GPU].data());
    if (act_local) local_mask(a, u, s.mask[S_LOCAL].data());

    const uint8_t* sp = a.static_pass + (int64_t)u * N;
    bool any_feas = false;
    for (int64_t n = 0; n < N; n++) {
      uint8_t f = sp[n];
      for (int k = 0; k < N_STAGES; k++)
        if (act[k]) f &= s.mask[k][n];
      s.feas[n] = f;
      any_feas |= (bool)f;
    }

    if (!any_feas) {
      fail_accounting(a, s, act, u, i);
      if (explain) accumulate_rejects(a, u, i);
      continue;
    }
    if (explain) {
      // audit the successful step too: per-pod rows + reject totals see
      // the nodes each filter rejected even when the pod still lands
      fail_accounting(a, s, act, u, i);
      accumulate_rejects(a, u, i);
    }

    // --- Score: reductions over the feasible set, then fused accumulate ---
    float na_max = 0.0f, tt_max = 0.0f;
    if (use_na) {
      const float* na = a.na_raw + (int64_t)u * N;
      float m = NEG;
      for (int64_t n = 0; n < N; n++) m = std::max(m, s.feas[n] ? na[n] : 0.0f);
      na_max = m;
    }
    if (use_tt) {
      const float* tt = a.tt_raw + (int64_t)u * N;
      float m = NEG;
      for (int64_t n = 0; n < N; n++) m = std::max(m, s.feas[n] ? tt[n] : 0.0f);
      tt_max = m;
    }
    float ip_hi = 0.0f, ip_lo = 0.0f, ip_rng = 0.0f;
    if (use_ip) {
      interpod_raw(a, u, s.raw_ip.data());
      float hi = NEG, lo = BIG;
      for (int64_t n = 0; n < N; n++) {
        float v = s.feas[n] ? s.raw_ip[n] : 0.0f;
        hi = std::max(hi, v);
        lo = std::min(lo, v);
      }
      ip_hi = std::max(hi, 0.0f);
      ip_lo = std::min(lo, 0.0f);
      ip_rng = ip_hi - ip_lo;
    }
    bool any_soft = false;
    float spr_mn = BIG, spr_mx = NEG;
    if (use_spr) {
      any_soft = spread_raw(a, u, s.feas.data(), s.raw_spr.data(), s.spr_ignored.data());
      if (any_soft) {
        for (int64_t n = 0; n < N; n++) {
          if (s.feas[n] && !s.spr_ignored[n]) {
            spr_mn = std::min(spr_mn, s.raw_spr[n]);
            spr_mx = std::max(spr_mx, s.raw_spr[n]);
          }
        }
      }
    }
    const float* gcd = s.gc_dyn_ptr();
    const float* share = a.share_raw + (int64_t)u * N;
    float sh_lo = BIG, sh_hi = NEG, sh_rng = 0.0f;
    if (use_share) {
      for (int64_t n = 0; n < N; n++) {
        if (s.feas[n]) {
          float sh = a.ft_gc_dyn ? share_at(a, gcd, u, n) : share[n];
          sh_lo = std::min(sh_lo, sh);
          sh_hi = std::max(sh_hi, sh);
        }
      }
      sh_rng = sh_hi - sh_lo;
    }
    float lc_lo = BIG, lc_hi = NEG, lc_rng = 0.0f;
    if (use_loc) {
      local_raw(a, u, s.raw_loc.data());
      for (int64_t n = 0; n < N; n++) {
        if (s.feas[n]) {
          lc_lo = std::min(lc_lo, s.raw_loc[n]);
          lc_hi = std::max(lc_hi, s.raw_loc[n]);
        }
      }
      lc_rng = lc_hi - lc_lo;
    }

    const float* avoid = a.avoid_score + (int64_t)u * N;
    PreCtx pc;
    float cpu = a.req[(int64_t)u * R + a.res_cpu];
    float mem = a.req[(int64_t)u * R + a.res_mem];
    pc.cpuq = cpu > 0.0f ? cpu : 100.0f;  // GetNonzeroRequests defaults
    pc.memq = mem > 0.0f ? mem : 200.0f * 1024.0f * 1024.0f;
    pc.na_max = na_max;
    pc.tt_max = tt_max;
    pc.wb = wb;
    pc.wl = wl;
    pc.wna = wna;
    pc.wtt = wtt;
    pc.use_bal = use_bal;
    pc.use_least = use_least;
    pc.use_na = use_na;
    pc.use_tt = use_tt;
    pc.na = a.na_raw + (int64_t)u * N;
    pc.tt = a.tt_raw + (int64_t)u * N;

    float best = NEG;
    int32_t bi = -1;
    uint64_t tie_c = 0;
    uint64_t rs = (uint64_t)a.tie_seed * 0x9E3779B97F4A7C15ULL + (uint64_t)i;
    for (int64_t n = 0; n < N; n++) {
      if (!s.feas[n]) continue;
      float sc = pre_at(a, pc, n);
      if (use_ip)
        sc += wip * (ip_rng > 0.0f
                         ? MAXS * (s.raw_ip[n] - ip_lo) / std::max(ip_rng, 1.0f)
                         : 0.0f);
      if (use_spr && any_soft) {
        float norm;
        if (spr_mx <= 0.0f)
          norm = MAXS;
        else
          norm = MAXS * (spr_mx + spr_mn - s.raw_spr[n]) / std::max(spr_mx, 1.0f);
        if (s.spr_ignored[n]) norm = 0.0f;
        sc += wsp * norm;
      }
      if (use_share) {
        float sh = a.ft_gc_dyn ? share_at(a, gcd, u, n) : share[n];
        sc += wshare * (sh_rng > 0.0f ? (sh - sh_lo) * MAXS / sh_rng : 0.0f);
      }
      if (use_loc)
        sc += wloc * (lc_rng > 0.0f ? (s.raw_loc[n] - lc_lo) * MAXS / lc_rng : 0.0f);
      if (use_avoid) sc += wav * avoid[n];
      if (a.tie_sample) {
        // one-pass reservoir: reset on a new max, uniform among equals
        if (sc > best) {
          best = sc;
          bi = (int32_t)n;
          tie_c = 1;
        } else if (sc == best && bi >= 0) {
          tie_c++;
          if (sm64_next(&rs) % tie_c == 0) bi = (int32_t)n;
        }
      } else if (sc > best) {
        best = sc;
        bi = (int32_t)n;
      }
    }

    a.chosen[i] = bi;
    if (bi >= 0) {
      bind(a, s, u, bi, s.take.data());
      for (int64_t d = 0; d < Gd; d++) a.gpu_take[i * Gd + d] = s.take[d];
    }
    prof.stop(5);
  }
  prof.report();
  if (a.path_counts) {
    a.path_counts[0] = n_inc;
    a.path_counts[1] = n_gen;
    a.path_counts[2] = n_full;
  }
  if (prof.on && a.profile_out) prof.dump(a.profile_out);
  return 0;
}
