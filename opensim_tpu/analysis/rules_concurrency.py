"""OSL12xx — whole-program concurrency rules over the threaded serving core.

The reference system leans on Go's race detector plus informer-pattern
discipline to keep its concurrent scheduler honest; this family is the
static half of our answer (the runtime half is ``analysis/lockwatch.py``,
``make tsan``). All four rules consult the :class:`~.core.ProjectContext`
built once per lint run — symbol table, call graph, named lock nodes,
critical sections, and the static lock-acquisition graph — so a lock
acquired in ``server/watch.py`` and a mutation in ``obs/capacity.py`` are
finally visible to the same pass.

OSL1201 ``unguarded-shared-state``
    Instance attributes declared shared via a trailing ``# guarded-by:
    <lock>`` comment on their ``__init__`` assignment must only be
    read/mutated inside critical sections of that lock. A method whose
    every intra-project call site sits inside the lock's critical
    sections (directly or through attributed callers) counts as guarded —
    the call-graph attribution that keeps ``CapacityEngine``'s locked
    helper pyramid annotation-clean. ``__init__``/``__post_init__``
    publication is exempt (happens-before thread start).

    Guard tokens: a bare attr of the same class (``_lock``), a
    module-resolved dotted path (``RECORDER.lock``, ``PrepareCache._lock``)
    — resolution failures are findings too (a typo'd guard is worse than
    no guard).

OSL1202 ``lock-order-inversion``
    A cycle in the static lock graph (lock A held while acquiring B,
    attributed through up to two levels of direct calls) is a deadlock
    waiting for the right interleaving. Runtime confirmation comes from
    ``make tsan``.

OSL1203 ``blocking-call-under-lock``
    OSL1001 generalized beyond the admission/dispatch lock: no critical
    section anywhere in the repo may make a blocking call — sleeps,
    socket/HTTP reads, subprocess work, buffered ``open``, future/event
    waits, thread joins, or device/JIT sync points (``block_until_ready``,
    ``device_put``) — directly or through one level of project calls.
    ``cond.wait()`` / ``cond.wait_for()`` on the HELD condition stays
    legal (it releases the lock while blocked). The OSL1001 modules keep
    their original rule and are excluded here.

OSL1204 ``thread-unsafe-contextvar``
    Deadline/Trace ambient state travels in :mod:`contextvars`, which do
    NOT propagate to new threads: a function handed to
    ``threading.Thread(target=...)`` / ``pool.submit(...)`` (or a
    ``Thread`` subclass ``run``) that reads the ambient deadline/trace
    (``current_deadline``, ``check_deadline``, ``tracing.current``)
    without an explicit handoff (``deadline_scope(...)`` /
    ``trace_scope(...)`` / ``copy_context``) silently sees None — request
    deadlines stop being enforced and spans go dark exactly on the pooled
    path. The fix is the ``rest._admitted_solo`` pattern: carry the
    objects on the work item and re-install scopes in the worker.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import (
    CallSite,
    FileContext,
    Finding,
    ProjectContext,
    Rule,
    dotted_name,
    register,
)

_COMMON_EXCLUDES = ("tests/", "tools/", "test_",)


# ---------------------------------------------------------------------------
# OSL1201 unguarded-shared-state
# ---------------------------------------------------------------------------


@register
class UnguardedSharedStateRule(Rule):
    name = "unguarded-shared-state"
    code = "OSL1201"
    description = "`# guarded-by:` attribute touched outside its lock"
    exclude_paths = _COMMON_EXCLUDES
    needs_project = True

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        p = ctx.project
        if p is None:
            return
        # guard-token resolution failures, reported at the declaration
        mi = p.modules.get(ctx.module)
        guards: Dict[Tuple[str, str, str], str] = {}
        if mi is not None:
            for ci in mi.classes.values():
                for info in ci.attrs.values():
                    if not info.guarded_by:
                        continue
                    lock = p.resolve_guard(ctx.module, ci.name, info.guarded_by)
                    if lock is None:
                        yield Finding(
                            rule=self.name, code=self.code, path=ctx.path,
                            line=info.lineno, col=0,
                            message=(
                                f"`# guarded-by: {info.guarded_by}` on "
                                f"{ci.name}.{info.name} does not resolve to a "
                                "known lock (typo, or the lock is invisible to "
                                "the symbol table)"
                            ),
                        )
                    else:
                        guards[(ctx.module, ci.name, info.name)] = lock
        for acc in p.accesses_by_path.get(ctx.path, ()):
            owner_mod, owner_cls = acc.owner
            ci = p.classes.get((owner_mod, owner_cls))
            if ci is None:
                continue
            info = ci.attrs.get(acc.attr)
            if info is None or not info.guarded_by or info.kind == "lock":
                continue
            lock = guards.get((owner_mod, owner_cls, acc.attr))
            if lock is None:
                lock = p.resolve_guard(owner_mod, owner_cls, info.guarded_by)
            if lock is None:
                continue  # already reported at the declaration
            if acc.in_init:
                continue
            if lock in acc.held:
                continue
            if p.attributed_to_lock(acc.func, lock):
                continue
            verb = {"load": "read", "store": "written", "mutate": "mutated"}[acc.kind]
            yield self.finding(
                ctx, acc.node,
                f"{owner_cls}.{acc.attr} is guarded by "
                f"{ProjectContext.short(lock)} but is {verb} here outside any "
                f"of its critical sections (and {acc.func.rsplit('.', 1)[-1]} "
                "is not attributable to the lock through its call sites); "
                "hold the lock, or route through a locked accessor",
            )


# ---------------------------------------------------------------------------
# OSL1202 lock-order-inversion
# ---------------------------------------------------------------------------


@register
class LockOrderInversionRule(Rule):
    name = "lock-order-inversion"
    code = "OSL1202"
    description = "cycle in the static lock-acquisition graph"
    project_rule = True
    exclude_paths = _COMMON_EXCLUDES

    def project_check(self, project: ProjectContext) -> Iterable[Finding]:
        # direct nesting edges were collected during the scan; add edges
        # attributed through calls made while a lock is held (two levels)
        edges: Dict[Tuple[str, str], Tuple[str, ast.AST, str]] = {}
        for (a, b), e in project.lock_edges.items():
            edges[(a, b)] = (e.path, e.node, e.via)
        for caller, sites in project.calls_from.items():
            for site in sites:
                if not site.held or site.callee is None:
                    continue
                for lock, via in project.locks_within(site.callee, depth=1):
                    for held_id, _names in site.held:
                        if held_id != lock and (held_id, lock) not in edges:
                            edges[(held_id, lock)] = (
                                site.path, site.node, site.target or site.callee,
                            )
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        for cycle in _cycles(adj):
            locs = []
            for i, lock in enumerate(cycle):
                nxt = cycle[(i + 1) % len(cycle)]
                path, node, via = edges[(lock, nxt)]
                locs.append(
                    f"{ProjectContext.short(lock)} -> {ProjectContext.short(nxt)}"
                    + (f" (via {via})" if via else "")
                    + f" at {path}:{getattr(node, 'lineno', 1)}"
                )
            first = edges[(cycle[0], cycle[1 % len(cycle)])]
            yield self.finding(
                first[0], first[1],
                "lock-order inversion: "
                + " | ".join(locs)
                + " — a cycle in the static lock graph deadlocks under the "
                "right interleaving; pick one global order and stick to it",
            )


def _cycles(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles, deduped by rotation (small graphs — DFS is fine)."""
    seen_sigs: Set[Tuple[str, ...]] = set()
    out: List[List[str]] = []

    def dfs(start: str, node: str, path: List[str], visiting: Set[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) > 1:
                lo = path.index(min(path))
                sig = tuple(path[lo:] + path[:lo])
                if sig not in seen_sigs:
                    seen_sigs.add(sig)
                    out.append(list(sig))
            elif nxt not in visiting and nxt > start:
                # only explore nodes ordered after `start`: each cycle is
                # found exactly once, from its smallest node
                visiting.add(nxt)
                dfs(start, nxt, path + [nxt], visiting)
                visiting.discard(nxt)

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return out


# ---------------------------------------------------------------------------
# OSL1203 blocking-call-under-lock
# ---------------------------------------------------------------------------

_BLOCKING_LEAVES = {
    "sleep", "recv", "recv_into", "accept", "connect", "urlopen", "select",
    "communicate", "getresponse", "result", "block_until_ready", "device_put",
}
_WAIT_LEAVES = {"wait", "wait_for"}
_BLOCKING_ROOTS = {"subprocess", "socket"}
_THREADISH = ("thread", "proc", "worker", "pool", "future")


def _call_target(node: ast.Call) -> str:
    name = dotted_name(node.func)
    if name:
        return name
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


def _blocking_reason(site: CallSite, held_names: Set[str]) -> Optional[str]:
    """Why this call blocks while a lock is held, or None. ``held_names``
    are the raw names in the held with-expressions (the held-condition
    ``wait``/``wait_for`` exemption)."""
    target = _call_target(site.node)
    if not target:
        return None
    leaf = target.rsplit(".", 1)[-1]
    root = target.split(".", 1)[0]
    if leaf in _WAIT_LEAVES:
        owner = target.rsplit(".", 2)
        owner_name = owner[-2] if len(owner) >= 2 else ""
        if owner_name in held_names:
            return None  # waiting on the HELD condition releases the lock
        return f"`{target}` waits on an object that cannot release the held lock"
    if leaf in _BLOCKING_LEAVES:
        return f"`{target}` blocks"
    if root in _BLOCKING_ROOTS:
        return f"`{target}` does subprocess/socket I/O"
    if target == "open":
        return "buffered `open` does file I/O"
    if leaf == "join":
        owner = target.rsplit(".", 2)
        owner_name = (owner[-2] if len(owner) >= 2 else "").lower()
        if any(t in owner_name for t in _THREADISH):
            return f"`{target}` joins a thread"
    return None


@register
class BlockingCallUnderLockRule(Rule):
    name = "blocking-call-under-lock"
    code = "OSL1203"
    description = "blocking call inside any critical section (repo-wide OSL1001)"
    # the admission/dispatch modules keep OSL1001 (their original, stricter
    # wording); everything else is this rule's territory
    exclude_paths = _COMMON_EXCLUDES + (
        "server/admission", "server/pool", "server/rest",
    )
    needs_project = True

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        p = ctx.project
        if p is None:
            return
        for site in p.held_sites_by_path.get(ctx.path, ()):
            held_names: Set[str] = set()
            for _lid, names in site.held:
                held_names |= set(names)
            reason = _blocking_reason(site, held_names)
            if reason is not None:
                locks = ", ".join(
                    ProjectContext.short(lid) for lid, _n in site.held
                )
                yield self.finding(
                    ctx, site.node,
                    f"{reason} while holding {locks}; move it outside the "
                    "critical section (every waiter convoys behind this)",
                )
                continue
            # one level through the project call graph
            if site.callee is None:
                continue
            for sub in p.calls_from.get(site.callee, ()):
                sub_names: Set[str] = set(held_names)
                for _lid, names in sub.held:
                    sub_names |= set(names)
                sub_reason = _blocking_reason(sub, sub_names)
                if sub_reason is not None:
                    locks = ", ".join(
                        ProjectContext.short(lid) for lid, _n in site.held
                    )
                    yield self.finding(
                        ctx, site.node,
                        f"call to {site.target or site.callee} while "
                        f"holding {locks}: {sub_reason} (at "
                        f"{sub.path}:{getattr(sub.node, 'lineno', 1)}); "
                        "hoist the blocking work out of the lock",
                    )
                    break


# ---------------------------------------------------------------------------
# OSL1204 thread-unsafe-contextvar
# ---------------------------------------------------------------------------

_AMBIENT_READERS = {"current_deadline", "check_deadline"}
_AMBIENT_MODULES = {"tracing", "trace", "obs", "deadline"}
_HANDOFF_LEAVES = {"deadline_scope", "trace_scope", "use_trace", "copy_context"}


def _reads_ambient(target: str) -> bool:
    if not target:
        return False
    leaf = target.rsplit(".", 1)[-1]
    if leaf in _AMBIENT_READERS:
        return True
    root = target.split(".", 1)[0]
    return leaf == "current" and root in _AMBIENT_MODULES


def _has_handoff(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            target = _call_target(sub)
            if target and target.rsplit(".", 1)[-1] in _HANDOFF_LEAVES:
                return True
    return False


@register
class ThreadUnsafeContextvarRule(Rule):
    name = "thread-unsafe-contextvar"
    code = "OSL1204"
    description = "ambient deadline/trace read in a thread entry without handoff"
    exclude_paths = _COMMON_EXCLUDES + ("resilience/deadline", "obs/")
    needs_project = True

    def _ambient_reader_in(
        self, p: ProjectContext, qual: str, depth: int = 1
    ) -> Optional[str]:
        for site in p.calls_from.get(qual, ()):
            if _reads_ambient(site.target):
                return site.target
            if depth > 0 and site.callee is not None:
                got = self._ambient_reader_in(p, site.callee, depth - 1)
                if got:
                    return f"{got} (via {site.target or site.callee})"
        return None

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        p = ctx.project
        if p is None:
            return
        # explicit spawns in this file
        for _sctx, node, kind, entry in p.spawns_by_path.get(ctx.path, ()):
            if entry is None:
                continue
            fi = p.functions.get(entry)
            if fi is None or _has_handoff(fi.node):
                continue
            reader = self._ambient_reader_in(p, entry)
            if reader:
                what = "Thread target" if kind == "thread" else "submitted task"
                yield self.finding(
                    ctx, node,
                    f"{what} {entry.rsplit('.', 1)[-1]} reads the ambient "
                    f"deadline/trace ({reader}) but contextvars do not cross "
                    "threads: the worker silently sees None. Carry the "
                    "Deadline/TraceContext on the work item and re-install "
                    "with deadline_scope(...)/trace_scope(...) in the worker",
                )
        # Thread subclasses defined in this file: `run` is the entry
        mi = p.modules.get(ctx.module)
        if mi is None:
            return
        for ci in mi.classes.values():
            if not p.is_thread_subclass(ctx.module, ci.name):
                continue
            run = ci.methods.get("run")
            if run is None or _has_handoff(run.node):
                continue
            reader = self._ambient_reader_in(p, run.qualname)
            if reader:
                yield self.finding(
                    ctx, run.node,
                    f"{ci.name}.run reads the ambient deadline/trace "
                    f"({reader}) on a fresh thread where contextvars are "
                    "empty; install scopes explicitly at thread entry",
                )
