"""opensim-lint engine: rule registry, per-file AST walk, suppression.

The analyzer is the Python/JAX analogue of the `go vet` + race-detector
gate the reference's vendored kube-scheduler ships under: a small set of
repo-specific rules for the bug classes the tier-1 tests cannot see until
they bite on TPU — host work leaking into jit-traced code, dtype drift off
the Go int64/float32 parity contract, iteration-order nondeterminism in
encoder/fingerprint streams, in-place mutation of fingerprinted objects,
and swallowed exceptions.

Suppression syntax (pylint-style, checked on the finding's line and on a
standalone comment line directly above it):

    do_risky_thing()  # opensim-lint: disable=jit-boundary
    # opensim-lint: disable=determinism,cache-mutation
    next_line_is_exempt()

File-level (anywhere in the first 10 lines):

    # opensim-lint: disable-file=dtype-drift

``disable=all`` suppresses every rule. Rules are addressed by short name
(``jit-boundary``) or code (``OSL101``).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "FileContext",
    "Rule",
    "RULES",
    "register",
    "lint_source",
    "lint_paths",
    "render_human",
    "render_json",
]


@dataclass(frozen=True)
class Finding:
    """One diagnostic: rule identity + location + message."""

    rule: str  # short name, e.g. "jit-boundary"
    code: str  # stable id, e.g. "OSL101"
    path: str
    line: int
    col: int
    message: str

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclass
class FileContext:
    """Parsed source handed to each rule (one parse per file)."""

    path: str  # display path (as given / repo-relative)
    source: str
    tree: ast.Module
    lines: List[str]


class Rule:
    """Base class: subclasses set ``name``/``code`` and implement ``check``.

    ``paths`` restricts the rule to files whose normalized path contains one
    of the fragments (empty = every file); ``exclude_paths`` wins over
    ``paths``."""

    name: str = ""
    code: str = ""
    description: str = ""
    paths: Tuple[str, ...] = ()
    exclude_paths: Tuple[str, ...] = ()

    def applies_to(self, path: str) -> bool:
        p = path.replace(os.sep, "/")
        if any(frag in p for frag in self.exclude_paths):
            return False
        return not self.paths or any(frag in p for frag in self.paths)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.name,
            code=self.code,
            path=ctx.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule (by short name) to the registry."""
    rule = cls()
    if not rule.name or not rule.code:
        raise ValueError(f"rule {cls.__name__} needs name and code")
    RULES[rule.name] = rule
    return cls


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*opensim-lint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)")


def _suppressions(lines: List[str]) -> Tuple[Dict[int, set], set]:
    """(per-line rule sets keyed by 1-based line, file-level rule set).

    A standalone suppression comment (nothing but the comment on its line)
    also covers the next line, so fixes can keep long lines intact."""
    per_line: Dict[int, set] = {}
    file_level: set = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        names = {n.strip().lower() for n in m.group(2).split(",") if n.strip()}
        if m.group(1) == "disable-file":
            if i <= 10:
                file_level |= names
            continue
        per_line.setdefault(i, set()).update(names)
        if text.lstrip().startswith("#"):
            per_line.setdefault(i + 1, set()).update(names)
    return per_line, file_level


def _suppressed(f: Finding, per_line: Dict[int, set], file_level: set) -> bool:
    for names in (file_level, per_line.get(f.line, ())):
        if not names:
            continue
        lowered = {f.rule.lower(), f.code.lower()}
        if "all" in names or (lowered & set(names)):
            return True
    return False


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


def _select_rules(rules: Optional[Sequence[str]]) -> List[Rule]:
    if rules is None:
        return list(RULES.values())
    out = []
    by_code = {r.code.lower(): r for r in RULES.values()}
    for name in rules:
        key = name.strip().lower()
        rule = RULES.get(key) or by_code.get(key)
        if rule is None:
            raise KeyError(f"unknown rule {name!r}; known: {sorted(RULES)}")
        out.append(rule)
    return out


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint one source string (the unit tests' entry point)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [
            Finding(
                rule="parse-error",
                code="OSL000",
                path=path,
                line=e.lineno or 1,
                col=e.offset or 0,
                message=f"syntax error: {e.msg}",
            )
        ]
    lines = source.splitlines()
    ctx = FileContext(path=path, source=source, tree=tree, lines=lines)
    per_line, file_level = _suppressions(lines)
    findings: List[Finding] = []
    for rule in _select_rules(rules):
        if not rule.applies_to(path):
            continue
        for f in rule.check(ctx):
            if not _suppressed(f, per_line, file_level):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d not in ("__pycache__", ".git"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint files/directories; directories are walked for ``.py`` files."""
    findings: List[Finding] = []
    for fpath in _iter_py_files(paths):
        with open(fpath, "r", encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(lint_source(source, path=fpath, rules=rules))
    return findings


def render_human(findings: List[Finding]) -> str:
    lines = [
        f"{f.path}:{f.line}:{f.col + 1}: {f.code} [{f.rule}] {f.message}" for f in findings
    ]
    lines.append(
        f"opensim-lint: {len(findings)} finding(s)" if findings else "opensim-lint: clean"
    )
    return "\n".join(lines)


def render_json(findings: List[Finding]) -> str:
    return json.dumps([f.as_dict() for f in findings], indent=2)


# ---------------------------------------------------------------------------
# shared AST helpers for the rule modules
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """'jax.lax.scan' for nested Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents
