"""opensim-lint engine: rule registry, whole-program context, suppression.

The analyzer is the Python/JAX analogue of the `go vet` + race-detector
gate the reference's vendored kube-scheduler ships under: a small set of
repo-specific rules for the bug classes the tier-1 tests cannot see until
they bite on TPU or under load — host work leaking into jit-traced code,
dtype drift off the Go int64/float32 parity contract, iteration-order
nondeterminism in encoder/fingerprint streams, in-place mutation of
fingerprinted objects, swallowed exceptions, and (the OSL12xx family)
cross-module lock-discipline violations in the threaded serving core.

Two analysis tiers share one parse:

- **per-file rules** see a :class:`FileContext` (one ``ast.parse`` per
  file per run, shared by every rule — the engine never re-parses);
- **whole-program rules** additionally consult the
  :class:`ProjectContext` built once over ALL linted files: a symbol
  table (classes, their attributes, module globals, imports), a call
  graph (calls resolved through ``self``, typed locals/params, and
  module-level singletons), every ``threading.Lock/RLock/Condition``
  attribute as a named **lock node**, every ``with <lock>:`` body as a
  **critical section**, and the static **lock-acquisition graph**
  (lock A held while lock B is acquired, attributed through direct
  calls). Rules that set ``project_rule = True`` run once per project
  via :meth:`Rule.project_check` instead of once per file.

Suppression syntax (pylint-style, checked on the finding's line and on a
standalone comment line directly above it):

    do_risky_thing()  # opensim-lint: disable=jit-boundary
    # opensim-lint: disable=determinism,cache-mutation
    next_line_is_exempt()

File-level (anywhere in the first 10 lines):

    # opensim-lint: disable-file=dtype-drift

``disable=all`` suppresses every rule. Rules are addressed by short name
(``jit-boundary``) or code (``OSL101``).
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "FileContext",
    "ProjectContext",
    "Rule",
    "RULES",
    "register",
    "lint_source",
    "lint_paths",
    "render_human",
    "render_json",
    "render_sarif",
]


@dataclass(frozen=True)
class Finding:
    """One diagnostic: rule identity + location + message."""

    rule: str  # short name, e.g. "jit-boundary"
    code: str  # stable id, e.g. "OSL101"
    path: str
    line: int
    col: int
    message: str

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclass
class FileContext:
    """Parsed source handed to each rule (one parse per file per run).

    ``module`` is the dotted-module guess derived from the path (used by
    import resolution); ``project`` is the whole-program context shared by
    every file in the run (present even for a single-string lint — the
    project is just that one file then)."""

    path: str  # display path (as given / repo-relative)
    source: str
    tree: ast.Module
    lines: List[str]
    module: str = ""
    project: Optional["ProjectContext"] = None
    suppress_line: Dict[int, set] = field(default_factory=dict)
    suppress_file: set = field(default_factory=set)


class Rule:
    """Base class: subclasses set ``name``/``code`` and implement ``check``
    (per-file) or set ``project_rule = True`` and implement
    ``project_check`` (once per run, over the whole program).

    ``paths`` restricts the rule to files whose normalized path contains one
    of the fragments (empty = every file); ``exclude_paths`` wins over
    ``paths``. Per-file rules that consult ``ctx.project`` must set
    ``needs_project = True`` — the whole-program pass is only built when a
    selected rule asks for it, so ``--rules`` runs of plain AST rules skip
    the symbol-table/call-graph cost entirely."""

    name: str = ""
    code: str = ""
    description: str = ""
    paths: Tuple[str, ...] = ()
    exclude_paths: Tuple[str, ...] = ()
    project_rule: bool = False
    needs_project: bool = False

    def applies_to(self, path: str) -> bool:
        p = path.replace(os.sep, "/")
        if any(frag in p for frag in self.exclude_paths):
            return False
        return not self.paths or any(frag in p for frag in self.paths)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    def project_check(self, project: "ProjectContext") -> Iterable[Finding]:
        return ()

    def finding(self, ctx_or_path, node: ast.AST, message: str) -> Finding:
        path = ctx_or_path.path if isinstance(ctx_or_path, FileContext) else str(ctx_or_path)
        return Finding(
            rule=self.name,
            code=self.code,
            path=path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule (by short name) to the registry."""
    rule = cls()
    if not rule.name or not rule.code:
        raise ValueError(f"rule {cls.__name__} needs name and code")
    RULES[rule.name] = rule
    return cls


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*opensim-lint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)")


def _suppressions(lines: List[str]) -> Tuple[Dict[int, set], set]:
    """(per-line rule sets keyed by 1-based line, file-level rule set).

    A standalone suppression comment (nothing but the comment on its line)
    also covers the next line, so fixes can keep long lines intact."""
    per_line: Dict[int, set] = {}
    file_level: set = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        names = {n.strip().lower() for n in m.group(2).split(",") if n.strip()}
        if m.group(1) == "disable-file":
            if i <= 10:
                file_level |= names
            continue
        per_line.setdefault(i, set()).update(names)
        if text.lstrip().startswith("#"):
            per_line.setdefault(i + 1, set()).update(names)
    return per_line, file_level


def _suppressed(f: Finding, per_line: Dict[int, set], file_level: set) -> bool:
    for names in (file_level, per_line.get(f.line, ())):
        if not names:
            continue
        lowered = {f.rule.lower(), f.code.lower()}
        if "all" in names or (lowered & set(names)):
            return True
    return False


# ---------------------------------------------------------------------------
# shared AST helpers for the rule modules
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """'jax.lax.scan' for nested Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


# ---------------------------------------------------------------------------
# whole-program context: symbols, call graph, locks, critical sections
# ---------------------------------------------------------------------------

#: constructors whose result is a lock object (leaf name; the root, when
#: present, must look like the threading module)
_LOCK_CTORS = {"Lock", "RLock", "Condition", "BoundedSemaphore", "Semaphore"}
_LOCK_ROOTS = {"threading", "_threading", ""}

#: with-expression names that *look* like locks when resolution fails —
#: the same heuristic OSL1001 ships (a name ending in lock/cond[ition])
_LOCKISH_SUFFIX = ("lock", "cond", "condition", "mutex")

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z0-9_.]+)")

#: method names that mutate their receiver in place (list/dict/set/deque)
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "add", "discard",
    "setdefault", "sort", "reverse", "rotate", "move_to_end",
}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    leaf = name.rsplit(".", 1)[-1] if name else ""
    root = name.split(".", 1)[0] if "." in name else ""
    return leaf in _LOCK_CTORS and root in _LOCK_ROOTS


def _contains_lock_ctor(node: ast.AST) -> bool:
    return any(_is_lock_ctor(n) for n in ast.walk(node))


@dataclass
class AttrInfo:
    """One ``self.X = ...`` instance attribute discovered in a class."""

    name: str
    lineno: int
    kind: str = "other"  # "lock" | "instance" | "other"
    rhs: Optional[ast.AST] = None
    instance_of: Optional[Tuple[str, str]] = None  # (module, Class)
    guarded_by: Optional[str] = None  # raw `# guarded-by:` token
    ann_class: Optional[str] = None  # class name from a param/attr annotation


@dataclass
class ClassInfo:
    module: str
    name: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    attrs: Dict[str, AttrInfo] = field(default_factory=dict)
    methods: Dict[str, "FunctionInfo"] = field(default_factory=dict)


@dataclass
class FunctionInfo:
    module: str
    qualname: str  # module.Class.meth or module.func
    name: str
    cls: Optional[str]
    node: ast.AST  # FunctionDef | AsyncFunctionDef


@dataclass
class ModuleInfo:
    path: str
    name: str  # dotted
    ctx: FileContext
    imports: Dict[str, Tuple[str, Optional[str]]] = field(default_factory=dict)
    globals_instance: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    globals_lock: Dict[str, str] = field(default_factory=dict)  # name -> lock id
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)  # by bare name


@dataclass
class CriticalSection:
    lock: str  # canonical lock id (or heuristic local id)
    names: Set[str]  # raw names in the with-expression (wait exemption)
    path: str
    func: str  # enclosing function qualname
    node: ast.With


@dataclass
class CallSite:
    caller: str
    callee: Optional[str]  # resolved qualname or None
    target: str  # dotted source text of the callee expression
    path: str
    node: ast.Call
    held: Tuple[Tuple[str, frozenset], ...]  # (lock id, raw names) stack


@dataclass
class AttrAccess:
    """One resolved ``<instance-of-C>.attr`` use outside/inside locks."""

    owner: Tuple[str, str]  # (module, Class) the attribute belongs to
    attr: str
    kind: str  # "load" | "store" | "mutate"
    path: str
    func: str
    node: ast.AST
    held: Tuple[str, ...]  # lock ids held lexically at the access
    in_init: bool  # inside the owning class's __init__/__post_init__


@dataclass
class LockEdge:
    src: str
    dst: str
    path: str
    node: ast.AST
    via: str  # "" for a directly nested `with`, else the call chain text


def _module_name(path: str) -> str:
    p = path.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [x for x in p.split("/") if x and x not in (".", "..")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<module>"


def _annotation_class(node: Optional[ast.AST]) -> Optional[str]:
    """Extract a bare class name from a return/param annotation:
    ``X``, ``"X"``, ``Optional[X]``, ``X | None``."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value.strip().strip("'\"")
        return name.split("[")[-1].rstrip("]") if "[" in name else name
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript):  # Optional[X] / List[X] -> X
        head = dotted_name(node.value).rsplit(".", 1)[-1]
        if head in ("Optional", "Union"):
            inner = node.slice
            if isinstance(inner, ast.Tuple):
                for el in inner.elts:
                    got = _annotation_class(el)
                    if got:
                        return got
            return _annotation_class(inner)
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):  # X | None
        return _annotation_class(node.left) or _annotation_class(node.right)
    return None


class ProjectContext:
    """Symbol table + call graph + lock graph over every linted file."""

    def __init__(self, contexts: Sequence[FileContext]) -> None:
        self.contexts = list(contexts)
        self.by_path: Dict[str, FileContext] = {c.path: c for c in self.contexts}
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}  # by qualname
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        self.critical_sections: List[CriticalSection] = []
        self.call_sites: Dict[str, List[CallSite]] = {}  # callee -> sites
        self.calls_from: Dict[str, List[CallSite]] = {}  # caller -> sites
        self.accesses: List[AttrAccess] = []
        self.lock_edges: Dict[Tuple[str, str], LockEdge] = {}
        self.spawn_sites: List[Tuple[FileContext, ast.Call, str, Optional[str]]] = []
        # (ctx, call node, kind "thread"|"submit", entry qualname or None)
        self._canon_memo: Dict[Tuple[str, str, str], Optional[str]] = {}
        self._ret_memo: Dict[str, Optional[Tuple[str, str]]] = {}
        self._attr_memo: Dict[str, Set[str]] = {}  # lock -> attributed quals
        self._find_memo: Dict[str, Optional[str]] = {}
        for ctx in self.contexts:
            self._collect_symbols(ctx)
        for ctx in self.contexts:
            self._scan_functions(ctx)
        # per-path indices so per-file rules don't rescan the whole project
        # for every file (O(files x accesses) otherwise)
        self.accesses_by_path: Dict[str, List[AttrAccess]] = {}
        for acc in self.accesses:
            self.accesses_by_path.setdefault(acc.path, []).append(acc)
        self.held_sites_by_path: Dict[str, List[CallSite]] = {}
        for sites in self.calls_from.values():
            for site in sites:
                if site.held:
                    self.held_sites_by_path.setdefault(site.path, []).append(site)
        self.spawns_by_path: Dict[
            str, List[Tuple[FileContext, ast.Call, str, Optional[str]]]
        ] = {}
        for spawn in self.spawn_sites:
            self.spawns_by_path.setdefault(spawn[0].path, []).append(spawn)

    # -- naming helpers ------------------------------------------------------

    @staticmethod
    def short(lock_id: str) -> str:
        """Human-sized tail of a canonical id (messages/docs)."""
        return ".".join(lock_id.split(".")[-3:])

    def _find_module(self, target: str) -> Optional[str]:
        if target in self._find_memo:
            return self._find_memo[target]
        got: Optional[str] = None
        if target in self.modules:
            got = target
        else:
            tail = "." + target
            hits = [m for m in self.modules if m.endswith(tail)]
            if len(hits) == 1:
                got = hits[0]
        self._find_memo[target] = got
        return got

    # -- phase 1: per-module symbols ----------------------------------------

    def _collect_symbols(self, ctx: FileContext) -> None:
        mi = ModuleInfo(path=ctx.path, name=ctx.module, ctx=ctx)
        self.modules[ctx.module] = mi
        body = list(ctx.tree.body)
        top_level = set(map(id, body))
        for node in ast.walk(ctx.tree):
            # imports bind names wherever they appear — `if TYPE_CHECKING:`
            # blocks bind for annotations, and function-level imports (the
            # deferred-import idiom breaking module cycles, e.g. watch.py's
            # `from ..engine import prepcache`) must resolve for call-graph
            # attribution to see through them. Collisions with a top-level
            # name are possible in principle; in practice the idiom imports
            # the same module either way.
            if isinstance(node, (ast.Import, ast.ImportFrom)) and id(node) not in top_level:
                body.append(node)
        for node in body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mi.imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name, None,
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # in a package __init__ the module name already IS the
                    # package, so `from .` resolves one level higher than in
                    # a plain module
                    drop = node.level - 1 if ctx.path.endswith("__init__.py") else node.level
                    parts = ctx.module.split(".")
                    parts = parts[: max(0, len(parts) - drop)]
                    base = ".".join(parts + ([node.module] if node.module else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    mi.imports[alias.asname or alias.name] = (base, alias.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    if _contains_lock_ctor(node.value):
                        mi.globals_lock[t.id] = f"{ctx.module}.{t.id}"
                    elif isinstance(node.value, ast.Call):
                        cname = dotted_name(node.value.func)
                        mi.globals_instance[t.id] = ("", cname)  # resolved lazily
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(
                    module=ctx.module,
                    qualname=f"{ctx.module}.{node.name}",
                    name=node.name, cls=None, node=node,
                )
                mi.functions[node.name] = fi
                self.functions[fi.qualname] = fi
            elif isinstance(node, ast.ClassDef):
                self._collect_class(ctx, mi, node)

    def _collect_class(self, ctx: FileContext, mi: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(module=ctx.module, name=node.name, node=node)
        ci.bases = [dotted_name(b) for b in node.bases if dotted_name(b)]
        mi.classes[node.name] = ci
        self.classes[(ctx.module, node.name)] = ci
        for item in node.body:
            if isinstance(item, ast.Assign) and len(item.targets) == 1 and isinstance(
                item.targets[0], ast.Name
            ):
                # class-level attr (e.g. `_touch_lock = _threading.Lock()`)
                name = item.targets[0].id
                info = AttrInfo(name=name, lineno=item.lineno, rhs=item.value)
                if _contains_lock_ctor(item.value):
                    info.kind = "lock"
                info.guarded_by = self._guard_token(ctx, item.lineno)
                ci.attrs.setdefault(name, info)
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            fi = FunctionInfo(
                module=ctx.module,
                qualname=f"{ctx.module}.{node.name}.{item.name}",
                name=item.name, cls=node.name, node=item,
            )
            ci.methods[item.name] = fi
            self.functions[fi.qualname] = fi
            for sub in ast.walk(item):
                tgt = None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt, rhs = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    tgt, rhs = sub.target, sub.value
                else:
                    continue
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                info = ci.attrs.get(tgt.attr)
                is_lock = _contains_lock_ctor(rhs)
                if info is None or (is_lock and info.kind != "lock"):
                    info = AttrInfo(name=tgt.attr, lineno=sub.lineno, rhs=rhs)
                    if is_lock:
                        info.kind = "lock"
                    ci.attrs[tgt.attr] = info
                guard = self._guard_token(ctx, sub.lineno)
                if guard and not info.guarded_by:
                    info.guarded_by = guard
        # `self.X = param` in __init__ inherits the param's annotation
        init = ci.methods.get("__init__")
        if init is not None:
            ann = {
                a.arg: _annotation_class(a.annotation)
                for a in list(init.node.args.args) + list(init.node.args.kwonlyargs)
                if a.annotation is not None
            }
            for info in ci.attrs.values():
                if info.kind != "other" or info.ann_class is not None:
                    continue
                rhs = info.rhs
                # unwrap `x if x is not False else None`-style publication
                cands = [rhs]
                if isinstance(rhs, ast.IfExp):
                    cands = [rhs.body, rhs.orelse]
                for cand in cands:
                    if isinstance(cand, ast.Name) and ann.get(cand.id):
                        info.ann_class = ann[cand.id]
                        break

    @staticmethod
    def _guard_token(ctx: FileContext, lineno: int) -> Optional[str]:
        if 1 <= lineno <= len(ctx.lines):
            m = _GUARDED_BY_RE.search(ctx.lines[lineno - 1])
            if m:
                return m.group(1)
        return None

    # -- resolution ----------------------------------------------------------

    def canonical_lock(self, module: str, cls: str, attr: str) -> Optional[str]:
        """Lock id for a class attribute, following one alias level
        (``self.lock = RECORDER.lock``)."""
        key = (module, cls, attr)
        if key in self._canon_memo:
            return self._canon_memo[key]
        self._canon_memo[key] = None  # cycle guard
        ci = self.classes.get((module, cls))
        got: Optional[str] = None
        if ci is not None:
            info = ci.attrs.get(attr)
            if info is not None:
                if info.kind == "lock":
                    got = f"{module}.{cls}.{attr}"
                elif info.rhs is not None:
                    alias = self.resolve_value(info.rhs, module, cls, {})
                    if alias is not None and alias[0] == "lock":
                        got = alias[1]
        self._canon_memo[key] = got
        return got

    def class_of_instance(self, module: str, cname: str) -> Optional[Tuple[str, str]]:
        """Resolve a dotted class-name string in a module's namespace."""
        mi = self.modules.get(module)
        if mi is None:
            return None
        head, _, rest = cname.partition(".")
        if rest == "" and head in mi.classes:
            return (module, head)
        if head in mi.imports:
            tmod, sym = mi.imports[head]
            target = self._find_module(tmod)
            if sym is None:
                # `import pkg.mod as head` → rest names the class
                if target is not None and rest:
                    sub = rest.rsplit(".", 1)
                    if len(sub) == 1 and rest in self.modules[target].classes:
                        return (target, rest)
                return None
            if target is not None:
                tmi = self.modules[target]
                if rest == "" and sym in tmi.classes:
                    return (target, sym)
        return None

    def returns_instance(self, qual: str) -> Optional[Tuple[str, str]]:
        """(module, Class) a function returns, from its annotation or from
        all-return-constructor bodies."""
        if qual in self._ret_memo:
            return self._ret_memo[qual]
        self._ret_memo[qual] = None
        fi = self.functions.get(qual)
        got: Optional[Tuple[str, str]] = None
        if fi is not None:
            cname = _annotation_class(getattr(fi.node, "returns", None))
            if cname:
                got = self.class_of_instance(fi.module, cname)
            if got is None:
                for sub in ast.walk(fi.node):
                    if isinstance(sub, ast.Return) and isinstance(sub.value, ast.Call):
                        got = self.class_of_instance(
                            fi.module, dotted_name(sub.value.func)
                        )
                        if got:
                            break
        self._ret_memo[qual] = got
        return got

    def resolve_value(
        self,
        expr: ast.AST,
        module: str,
        cls: Optional[str],
        locals_: Dict[str, Tuple[str, ...]],
    ) -> Optional[Tuple]:
        """Best-effort static value of an expression:
        ``("instance", mod, Class)`` | ``("class", mod, Class)`` |
        ``("func", qualname)`` | ``("lock", lock_id)`` |
        ``("module", mod)`` | None."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and cls is not None:
                return ("instance", module, cls)
            if expr.id in locals_:
                return locals_[expr.id]
            mi = self.modules.get(module)
            if mi is None:
                return None
            if expr.id in mi.globals_lock:
                return ("lock", mi.globals_lock[expr.id])
            if expr.id in mi.globals_instance:
                got = self.class_of_instance(module, mi.globals_instance[expr.id][1])
                if got:
                    return ("instance", got[0], got[1])
                # singleton built by a factory function
                fq = self._resolve_func_name(module, mi.globals_instance[expr.id][1])
                if fq:
                    inst = self.returns_instance(fq)
                    if inst:
                        return ("instance", inst[0], inst[1])
                return None
            if expr.id in mi.classes:
                return ("class", module, expr.id)
            if expr.id in mi.functions:
                return ("func", mi.functions[expr.id].qualname)
            if expr.id in mi.imports:
                tmod, sym = mi.imports[expr.id]
                target = self._find_module(tmod)
                if sym is None:
                    return ("module", target or tmod)
                if target is not None:
                    tmi = self.modules[target]
                    if sym in tmi.classes:
                        return ("class", target, sym)
                    if sym in tmi.functions:
                        return ("func", tmi.functions[sym].qualname)
                    if sym in tmi.globals_lock:
                        return ("lock", tmi.globals_lock[sym])
                    if sym in tmi.globals_instance:
                        got = self.class_of_instance(target, tmi.globals_instance[sym][1])
                        if got:
                            return ("instance", got[0], got[1])
                # `from pkg import submodule`: the bound name IS a module
                sub = self._find_module(f"{tmod}.{sym}" if tmod else sym)
                if sub is not None:
                    return ("module", sub)
                return None
            return None
        if isinstance(expr, ast.Attribute):
            base = self.resolve_value(expr.value, module, cls, locals_)
            if base is None:
                return None
            if base[0] == "module":
                mi = self.modules.get(base[1])
                if mi is None:
                    return None
                if expr.attr in mi.classes:
                    return ("class", base[1], expr.attr)
                if expr.attr in mi.functions:
                    return ("func", mi.functions[expr.attr].qualname)
                if expr.attr in mi.globals_lock:
                    return ("lock", mi.globals_lock[expr.attr])
                if expr.attr in mi.globals_instance:
                    got = self.class_of_instance(
                        base[1], mi.globals_instance[expr.attr][1]
                    )
                    if got:
                        return ("instance", got[0], got[1])
                return None
            if base[0] == "instance":
                ci = self.classes.get((base[1], base[2]))
                if ci is None:
                    return None
                info = ci.attrs.get(expr.attr)
                if info is not None:
                    if info.kind == "lock":
                        lock = self.canonical_lock(base[1], base[2], expr.attr)
                        return ("lock", lock) if lock else None
                    inst = self.attr_instance(base[1], base[2], expr.attr)
                    if inst:
                        return ("instance", inst[0], inst[1])
                    # alias attr pointing at a lock elsewhere
                    if info.rhs is not None:
                        alias = self.resolve_value(info.rhs, base[1], base[2], {})
                        if alias is not None and alias[0] == "lock":
                            return alias
                    return None
                if expr.attr in ci.methods:
                    return ("func", ci.methods[expr.attr].qualname)
            if base[0] == "class":
                ci = self.classes.get((base[1], base[2]))
                if ci is not None:
                    if expr.attr in ci.methods:
                        return ("func", ci.methods[expr.attr].qualname)
                    info = ci.attrs.get(expr.attr)
                    if info is not None and info.kind == "lock":
                        lock = self.canonical_lock(base[1], base[2], expr.attr)
                        return ("lock", lock) if lock else None
            return None
        if isinstance(expr, ast.Call):
            f = self.resolve_value(expr.func, module, cls, locals_)
            if f is None:
                return None
            if f[0] == "class":
                return ("instance", f[1], f[2])
            if f[0] == "func":
                inst = self.returns_instance(f[1])
                if inst:
                    return ("instance", inst[0], inst[1])
            return None
        if isinstance(expr, ast.IfExp):
            return self.resolve_value(expr.body, module, cls, locals_) or self.resolve_value(
                expr.orelse, module, cls, locals_
            )
        return None

    def _resolve_func_name(self, module: str, dotted: str) -> Optional[str]:
        mi = self.modules.get(module)
        if mi is None or not dotted:
            return None
        head, _, rest = dotted.partition(".")
        if not rest and head in mi.functions:
            return mi.functions[head].qualname
        if head in mi.imports:
            tmod, sym = mi.imports[head]
            target = self._find_module(tmod)
            if target is not None:
                tmi = self.modules[target]
                name = sym if rest == "" else rest
                if name and name in tmi.functions:
                    return tmi.functions[name].qualname
        return None

    def attr_instance(self, module: str, cls: str, attr: str) -> Optional[Tuple[str, str]]:
        ci = self.classes.get((module, cls))
        if ci is None:
            return None
        info = ci.attrs.get(attr)
        if info is None:
            return None
        if info.instance_of is not None:
            return info.instance_of
        if info.ann_class is not None:
            got = self.class_of_instance(module, info.ann_class)
            if got is not None:
                info.instance_of = got
                return got
        if info.rhs is not None:
            got = self.resolve_value(info.rhs, module, cls, {})
            if got is not None and got[0] == "instance":
                info.instance_of = (got[1], got[2])
                return info.instance_of
        return None

    def is_thread_subclass(self, module: str, cls: str) -> bool:
        ci = self.classes.get((module, cls))
        if ci is None:
            return False
        return any(b.rsplit(".", 1)[-1] == "Thread" for b in ci.bases)

    # -- phase 2: per-function scan -----------------------------------------

    def _scan_functions(self, ctx: FileContext) -> None:
        mi = self.modules[ctx.module]
        for fi in list(mi.functions.values()):
            self._scan_function(ctx, fi)
        for ci in mi.classes.values():
            for fi in ci.methods.values():
                self._scan_function(ctx, fi)

    def _scan_function(self, ctx: FileContext, fi: FunctionInfo) -> None:
        locals_: Dict[str, Tuple] = {}
        node = fi.node
        # typed parameters (the typed core annotates its signatures)
        for arg in list(node.args.args) + list(node.args.kwonlyargs):
            cname = _annotation_class(arg.annotation)
            if cname:
                got = self.class_of_instance(fi.module, cname)
                if got:
                    locals_[arg.arg] = ("instance", got[0], got[1])
        # first-assignment local inference (calls with known return types)
        for sub in ast.walk(node):
            tgt = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and isinstance(
                sub.targets[0], ast.Name
            ):
                tgt, rhs = sub.targets[0].id, sub.value
            elif isinstance(sub, ast.AnnAssign) and isinstance(sub.target, ast.Name):
                cname = _annotation_class(sub.annotation)
                if cname:
                    got = self.class_of_instance(fi.module, cname)
                    if got and sub.target.id not in locals_:
                        locals_[sub.target.id] = ("instance", got[0], got[1])
                continue
            else:
                continue
            if tgt in locals_:
                continue
            got = self.resolve_value(rhs, fi.module, fi.cls, locals_)
            if got is not None and got[0] in ("instance", "lock"):
                locals_[tgt] = got
        scanner = _FunctionScanner(self, ctx, fi, locals_)
        for stmt in getattr(node, "body", []):
            scanner.visit(stmt)

    def _note_call(self, site: CallSite) -> None:
        self.calls_from.setdefault(site.caller, []).append(site)
        if site.callee:
            self.call_sites.setdefault(site.callee, []).append(site)

    # -- derived queries -----------------------------------------------------

    def direct_locks(self, qual: str) -> List[CriticalSection]:
        return [cs for cs in self.critical_sections if cs.func == qual]

    def locks_within(self, qual: str, depth: int = 2, _seen=None) -> List[Tuple[str, str]]:
        """Lock ids a function acquires, through ``depth`` call levels.
        Returns (lock id, via-text) pairs."""
        if _seen is None:
            _seen = set()
        if qual in _seen or depth < 0:
            return []
        _seen.add(qual)
        out = [(cs.lock, "") for cs in self.direct_locks(qual)]
        if depth > 0:
            for site in self.calls_from.get(qual, []):
                if site.callee:
                    for lock, via in self.locks_within(site.callee, depth - 1, _seen):
                        short = site.callee.rsplit(".", 2)
                        out.append((lock, site.target or ".".join(short[-2:])))
        return out

    def attributed_to_lock(self, qual: str, lock: str) -> bool:
        """True when every intra-project call site of ``qual`` runs inside a
        critical section of ``lock`` (directly, or in a caller that is
        itself attributed — the call-graph attribution the OSL1201
        annotations lean on). A function nobody calls is NOT attributed.

        Sound on recursion: attribution is computed over the condensation
        of the caller graph, so a mutual-recursion cluster is attributed
        iff every entry INTO the cluster is held-or-attributed (and at
        least one exists) — a lock-free cycle can never attest itself,
        while a locked helper pair that recurses into each other stays
        annotation-clean. Intra-cluster call sites change no lock state
        and are ignored unless they are themselves held."""
        attributed = self._attr_memo.get(lock)
        if attributed is None:
            attributed = self._attr_memo[lock] = self._attribution_for(lock)
        return qual in attributed

    def _attribution_for(self, lock: str) -> Set[str]:
        # dependency edge q -> caller for every call site of q not already
        # inside the lock; SCCs of that graph are the recursion clusters
        deps: Dict[str, List[str]] = {}
        for qual, sites in self.call_sites.items():
            deps[qual] = [
                s.caller
                for s in sites
                if not any(lid == lock for lid, _n in s.held)
            ]
        order: List[str] = []  # iterative post-order DFS over deps
        seen: Set[str] = set()
        for root in deps:
            if root in seen:
                continue
            stack: List[Tuple[str, int]] = [(root, 0)]
            seen.add(root)
            while stack:
                node, i = stack.pop()
                nxt = deps.get(node, ())
                while i < len(nxt) and nxt[i] in seen:
                    i += 1
                if i < len(nxt):
                    stack.append((node, i + 1))
                    seen.add(nxt[i])
                    stack.append((nxt[i], 0))
                else:
                    order.append(node)
        # Kosaraju phase 2: DFS the reverse graph in reverse post-order
        rdeps: Dict[str, List[str]] = {}
        for q, callers in deps.items():
            for c in callers:
                rdeps.setdefault(c, []).append(q)
        scc_of: Dict[str, int] = {}
        for node in reversed(order):
            if node in scc_of:
                continue
            sid = len(scc_of)
            work = [node]
            scc_of[node] = sid
            while work:
                n = work.pop()
                for m in rdeps.get(n, ()):
                    if m not in scc_of and (m in deps or m in rdeps):
                        scc_of[m] = scc_of[node]
                        work.append(m)
        clusters: Dict[int, List[str]] = {}
        for q in deps:
            clusters.setdefault(scc_of[q], []).append(q)
        attributed: Set[str] = set()
        # a cluster is attributed iff every entry into it — every call
        # site of every member whose caller sits outside the cluster, plus
        # any held intra-cluster site — is inside the lock or in an
        # attributed caller, and at least one such entry exists. Iterate
        # to a fixpoint: coverage through attributed callers cascades.
        changed = True
        while changed:
            changed = False
            for sid, members in clusters.items():
                if members[0] in attributed:
                    continue
                entries = 0
                ok = True
                for q in members:
                    for s in self.call_sites.get(q, ()):
                        if any(lid == lock for lid, _n in s.held):
                            entries += 1
                            continue
                        if scc_of.get(s.caller) == sid and s.caller in deps:
                            continue  # intra-cluster, unheld: no state change
                        entries += 1
                        if s.caller not in attributed:
                            ok = False
                if ok and entries:
                    attributed.update(members)
                    changed = True
        return attributed

    def resolve_guard(self, module: str, cls: str, token: str) -> Optional[str]:
        """Resolve a ``# guarded-by:`` token to a canonical lock id: a bare
        attr of the same class, ``GLOBAL.lockattr`` / ``Class._lock`` via
        the module namespace, or a module-global lock."""
        if "." not in token:
            got = self.canonical_lock(module, cls, token)
            if got:
                return got
            mi = self.modules.get(module)
            if mi and token in mi.globals_lock:
                return mi.globals_lock[token]
            # fall through: a bare name can also be an import
            # (`from .locks import GLOBAL_LOCK`), which resolve_value sees
        try:
            expr = ast.parse(token, mode="eval").body
        except SyntaxError:
            return None  # malformed token -> the unresolved-guard finding
        got = self.resolve_value(expr, module, cls, {})
        if got is not None and got[0] == "lock":
            return got[1]
        return None


class _FunctionScanner(ast.NodeVisitor):
    """One pass over a function body: critical sections, lock edges, call
    sites (with the lexically-held lock stack), resolved attribute
    accesses. Nested defs/lambdas are scanned with the definition-site
    lock stack (the dominant use is an immediately-invoked key/callback
    while the lock is held)."""

    def __init__(self, project: ProjectContext, ctx: FileContext, fi: FunctionInfo,
                 locals_: Dict[str, Tuple]) -> None:
        self.p = project
        self.ctx = ctx
        self.fi = fi
        self.locals = locals_
        self.held: List[Tuple[str, frozenset]] = []
        self._seen_attr: Set[ast.AST] = set()
        self.in_init = fi.name in ("__init__", "__post_init__") and fi.cls is not None

    # -- helpers -------------------------------------------------------------

    def _resolve(self, expr: ast.AST):
        return self.p.resolve_value(expr, self.fi.module, self.fi.cls, self.locals)

    def _lock_of(self, expr: ast.AST) -> Optional[Tuple[str, frozenset]]:
        names = frozenset(
            n.attr if isinstance(n, ast.Attribute) else n.id
            for n in ast.walk(expr)
            if isinstance(n, (ast.Attribute, ast.Name))
        )
        got = self._resolve(expr)
        if got is not None and got[0] == "lock":
            return got[1], names
        dotted = dotted_name(expr)
        leaf = dotted.rsplit(".", 1)[-1].lower() if dotted else ""
        if leaf.endswith(_LOCKISH_SUFFIX):
            return f"{self.ctx.module}:<{dotted}>", names
        return None

    def _record_access(self, node: ast.Attribute, kind: str) -> None:
        if node in self._seen_attr:
            return
        self._seen_attr.add(node)
        base = self._resolve(node.value)
        if base is None or base[0] != "instance":
            return
        ci = self.p.classes.get((base[1], base[2]))
        if ci is None or node.attr not in ci.attrs:
            return
        self.p.accesses.append(
            AttrAccess(
                owner=(base[1], base[2]),
                attr=node.attr,
                kind=kind,
                path=self.ctx.path,
                func=self.fi.qualname,
                node=node,
                held=tuple(lid for lid, _n in self.held),
                in_init=self.in_init
                and base[1] == self.fi.module
                and base[2] == self.fi.cls
                and isinstance(node.value, ast.Name)
                and node.value.id == "self",
            )
        )

    # -- visitors ------------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired = 0
        for item in node.items:
            # the item's context expression runs under every lock acquired
            # so far (items evaluate left-to-right, each entered before the
            # next evaluates): calls made here — `with lock, open(p):` —
            # belong in the call graph with that held stack
            self.visit(item.context_expr)
            got = self._lock_of(item.context_expr)
            if got is None:
                continue
            lock_id, names = got
            self.p.critical_sections.append(
                CriticalSection(
                    lock=lock_id, names=set(names), path=self.ctx.path,
                    func=self.fi.qualname, node=node,
                )
            )
            for held_id, _hn in self.held:
                if held_id != lock_id:
                    key = (held_id, lock_id)
                    if key not in self.p.lock_edges:
                        self.p.lock_edges[key] = LockEdge(
                            src=held_id, dst=lock_id, path=self.ctx.path,
                            node=node, via="",
                        )
            self.held.append((lock_id, names))
            acquired += 1
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            del self.held[len(self.held) - acquired:]

    visit_AsyncWith = visit_With

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Attribute):
                self._record_access(t.value, "mutate")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.target, ast.Subscript) and isinstance(
            node.target.value, ast.Attribute
        ):
            self._record_access(node.target.value, "mutate")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # receiver mutation: self.attr.append(...)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in MUTATOR_METHODS
            and isinstance(node.func.value, ast.Attribute)
        ):
            self._record_access(node.func.value, "mutate")
        callee = None
        got = self._resolve(node.func)
        if got is not None and got[0] == "func":
            callee = got[1]
        elif got is not None and got[0] == "class":
            callee = f"{got[1]}.{got[2]}.__init__"
        site = CallSite(
            caller=self.fi.qualname,
            callee=callee,
            target=dotted_name(node.func),
            path=self.ctx.path,
            node=node,
            held=tuple(self.held),
        )
        self.p._note_call(site)
        # thread spawn sites (OSL1204): Thread(target=f) / pool.submit(f)
        leaf = site.target.rsplit(".", 1)[-1] if site.target else ""
        if leaf == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = self._resolve(kw.value)
                    self.p.spawn_sites.append(
                        (self.ctx, node, "thread",
                         tgt[1] if tgt and tgt[0] == "func" else None)
                    )
        elif leaf in ("submit", "start_new_thread") and node.args:
            tgt = self._resolve(node.args[0])
            self.p.spawn_sites.append(
                (self.ctx, node, "submit",
                 tgt[1] if tgt and tgt[0] == "func" else None)
            )
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        kind = "load"
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            kind = "store"
        self._record_access(node, kind)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for stmt in node.body:  # definition-site held stack, see class doc
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


def _select_rules(rules: Optional[Sequence[str]]) -> List[Rule]:
    if rules is None:
        return list(RULES.values())
    out = []
    by_code = {r.code.lower(): r for r in RULES.values()}
    for name in rules:
        key = name.strip().lower()
        rule = RULES.get(key) or by_code.get(key)
        if rule is None:
            raise KeyError(f"unknown rule {name!r}; known: {sorted(RULES)}")
        out.append(rule)
    return out


def _make_context(source: str, path: str) -> Tuple[Optional[FileContext], Optional[Finding]]:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return None, Finding(
            rule="parse-error",
            code="OSL000",
            path=path,
            line=e.lineno or 1,
            col=e.offset or 0,
            message=f"syntax error: {e.msg}",
        )
    lines = source.splitlines()
    ctx = FileContext(
        path=path, source=source, tree=tree, lines=lines, module=_module_name(path)
    )
    ctx.suppress_line, ctx.suppress_file = _suppressions(lines)
    return ctx, None


def _check_file(ctx: FileContext, rules: Sequence[Rule]) -> List[Finding]:
    """Per-file rules over one context, suppression applied. Shared by the
    plain and cached lint flows so their results stay byte-identical."""
    out: List[Finding] = []
    for rule in rules:
        if rule.project_rule or not rule.applies_to(ctx.path):
            continue
        for f in rule.check(ctx):
            if not _suppressed(f, ctx.suppress_line, ctx.suppress_file):
                out.append(f)
    return out


def _check_project(project: ProjectContext, rules: Sequence[Rule]) -> List[Finding]:
    """Project-rule pass, path filters and suppression applied. Shared by
    the plain and cached lint flows."""
    out: List[Finding] = []
    for rule in rules:
        if not rule.project_rule:
            continue
        for f in rule.project_check(project):
            fctx = project.by_path.get(f.path)
            if fctx is not None and not rule.applies_to(f.path):
                continue
            if fctx is None or not _suppressed(f, fctx.suppress_line, fctx.suppress_file):
                out.append(f)
    return out


def _run(
    contexts: List[FileContext],
    parse_errors: List[Finding],
    rules: Optional[Sequence[str]],
) -> List[Finding]:
    selected = _select_rules(rules)
    project: Optional[ProjectContext] = None
    if any(r.project_rule or r.needs_project for r in selected):
        project = ProjectContext(contexts)
    findings: List[Finding] = list(parse_errors)
    for ctx in contexts:
        ctx.project = project
        findings.extend(_check_file(ctx, selected))
    if project is not None:
        findings.extend(_check_project(project, selected))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint one source string (the unit tests' entry point). The
    whole-program context is built over just this file."""
    ctx, err = _make_context(source, path)
    if ctx is None:
        return [err] if err else []
    return _run([ctx], [], rules)


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d not in ("__pycache__", ".git"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    stats: Optional[dict] = None,
    cache_path: Optional[str] = None,
    jobs: Optional[int] = None,
) -> List[Finding]:
    """Lint files/directories; directories are walked for ``.py`` files.
    Every file is parsed ONCE and the AST shared across all rules; pass a
    ``stats`` dict to receive ``{"files", "rules", "seconds"}`` for the
    `make lint` wall-time report.

    With ``cache_path``, lint results are cached by content hash
    (``analysis/cache.py``): unchanged files skip their per-file rules
    (and, when nothing in the project changed, everything skips — no
    parses at all). ``stats`` then also carries ``cache_hits``,
    ``cache_misses`` and ``project_pass`` ("reused"/"rebuilt"/"n/a").

    ``jobs`` fans the per-file rule tier (the cache-miss loop) out over a
    process pool; the whole-program tier stays serial (it is one shared
    symbol table). ``None`` auto-sizes to the machine; ``1`` forces the
    serial path. Results are byte-identical either way: workers return
    per-file findings that are merged back in walk order."""
    t0 = time.perf_counter()
    if cache_path is None:
        contexts: List[FileContext] = []
        parse_errors: List[Finding] = []
        for fpath in _iter_py_files(paths):
            with open(fpath, "r", encoding="utf-8") as fh:
                source = fh.read()
            ctx, err = _make_context(source, fpath)
            if ctx is not None:
                contexts.append(ctx)
            elif err is not None:
                parse_errors.append(err)
        findings = _run(contexts, parse_errors, rules)
        if stats is not None:
            stats["files"] = len(contexts) + len(parse_errors)
            stats["rules"] = len(_select_rules(rules))
            stats["seconds"] = time.perf_counter() - t0
        return findings
    findings = _lint_paths_cached(paths, rules, stats, cache_path, jobs)
    if stats is not None:
        stats["seconds"] = time.perf_counter() - t0
    return findings


def _companion_files(py_paths: Sequence[str]) -> List[str]:
    """Non-Python inputs whole-program rules consult (today: the ``.cc``
    engine sources living beside linted files), sorted for stable
    digests."""
    dirs = sorted({os.path.dirname(p) for p in py_paths})
    out: List[str] = []
    for d in dirs:
        try:
            names = os.listdir(d or ".")
        except OSError:
            continue
        out.extend(os.path.join(d, n) for n in sorted(names) if n.endswith(".cc"))
    return out


def _lint_file_worker(item: Tuple[str, str, Tuple[str, ...]]) -> Tuple[str, List[dict]]:
    """Per-file rule tier for ONE file — the process-pool unit. Top-level
    so the executor can pickle it; re-parses the source (ASTs don't cross
    process boundaries) and runs the named rules through the same
    ``_check_file`` dispatch as the serial path, so findings are
    byte-identical. Returns ``(path, finding dicts)``."""
    fpath, source, rule_names = item
    selected = _select_rules(list(rule_names))
    ctx, err = _make_context(source, fpath)
    out: List[Finding] = []
    if err is not None:
        out.append(err)
    if ctx is not None:
        out.extend(_check_file(ctx, selected))
    return fpath, [f.as_dict() for f in out]


#: Below this many cache misses the pool's fork/import overhead exceeds
#: the rule work; the miss loop stays serial.
_PARALLEL_MIN_MISSES = 8


def _resolve_jobs(jobs: Optional[int], n_misses: int) -> int:
    """Worker count for the per-file tier. ``None``/``0`` auto-sizes to
    the machine (capped — lint is parse-bound, not embarrassingly wide);
    small miss counts and single-core boxes degrade to serial."""
    if not jobs:
        jobs = min(os.cpu_count() or 1, 8)
    if jobs <= 1 or n_misses < _PARALLEL_MIN_MISSES:
        return 1
    return min(jobs, n_misses)


def _lint_paths_cached(
    paths: Sequence[str],
    rules: Optional[Sequence[str]],
    stats: Optional[dict],
    cache_path: str,
    jobs: Optional[int] = None,
) -> List[Finding]:
    """The content-hash-cached lint flow (see :mod:`analysis.cache`).

    Rule split: *local* rules (per-file, no whole-program context) cache
    per file; *global* rules (``project_rule`` or ``needs_project``) cache
    as one unit keyed by a digest over every file hash — the symbol
    table/call graph they consult is global, so any edit rebuilds them."""
    import hashlib

    from .cache import LintCache, analyzer_fingerprint

    selected = _select_rules(rules)
    local_rules = [r for r in selected if not (r.project_rule or r.needs_project)]
    global_rules = [r for r in selected if r.project_rule or r.needs_project]
    local_key = ",".join(sorted(r.code for r in local_rules))
    cache = LintCache(cache_path)

    sources: Dict[str, str] = {}
    shas: Dict[str, str] = {}
    order: List[str] = []
    for fpath in _iter_py_files(paths):
        with open(fpath, "r", encoding="utf-8") as fh:
            src = fh.read()
        order.append(fpath)
        sources[fpath] = src
        shas[fpath] = hashlib.sha256(src.encode()).hexdigest()

    local_findings: List[Finding] = []
    misses: List[str] = []
    hits = 0
    for fpath in order:
        got = cache.file_findings(fpath, shas[fpath], local_key)
        if got is not None:
            hits += 1
            local_findings.extend(Finding(**d) for d in got)
        else:
            misses.append(fpath)

    h = hashlib.sha256()
    for fpath in order:
        h.update(fpath.encode())
        h.update(shas[fpath].encode())
    # companion sources the project rules read but the walker does not
    # lint: the abi-parity pass (OSL1604) parses the C++ engine sources
    # next to the native package, so a C++-only ABI edit must invalidate
    # the cached project pass too
    for comp in _companion_files(order):
        h.update(comp.encode())
        try:
            with open(comp, "rb") as fh:
                h.update(hashlib.sha256(fh.read()).hexdigest().encode())
        except OSError:
            h.update(b"<unreadable>")
    h.update(",".join(sorted(r.code for r in global_rules)).encode())
    h.update(analyzer_fingerprint().encode())
    project_digest = h.hexdigest()

    project_findings: List[Finding] = []
    project_state = "n/a"
    cached_project = cache.project_findings(project_digest) if global_rules else None
    if global_rules and cached_project is not None:
        project_findings = [Finding(**d) for d in cached_project]
        project_state = "reused"

    # parse what we must: cache-missed files (unless pool workers will
    # re-parse them in their own processes); every file when the project
    # pass has to rebuild
    use_jobs = _resolve_jobs(jobs, len(misses))
    need_parse = set(misses) if use_jobs == 1 else set()
    if global_rules and cached_project is None:
        need_parse = set(order)
        project_state = "rebuilt"
    pos = {p: i for i, p in enumerate(order)}
    contexts: Dict[str, FileContext] = {}
    parse_errors: Dict[str, Finding] = {}
    for fpath in sorted(need_parse, key=pos.__getitem__):
        ctx, err = _make_context(sources[fpath], fpath)
        if ctx is not None:
            contexts[fpath] = ctx
        elif err is not None:
            parse_errors[fpath] = err

    # per-file rules over the cache misses (same dispatch as _run). With
    # jobs > 1 the misses fan out over a process pool — each worker
    # re-parses its file and returns finding dicts; merging back in walk
    # order keeps the output byte-identical to the serial loop.
    miss_results: Dict[str, List[dict]] = {}
    if use_jobs > 1:
        from concurrent.futures import ProcessPoolExecutor

        rule_names = tuple(r.name for r in local_rules)
        payload = [(p, sources[p], rule_names) for p in misses]
        with ProcessPoolExecutor(max_workers=use_jobs) as pool:
            for fpath, dicts in pool.map(_lint_file_worker, payload):
                miss_results[fpath] = dicts
    else:
        for fpath in misses:
            out: List[Finding] = []
            err = parse_errors.get(fpath)
            if err is not None:
                out.append(err)
            ctx = contexts.get(fpath)
            if ctx is not None:
                out.extend(_check_file(ctx, local_rules))
            miss_results[fpath] = [f.as_dict() for f in out]
    for fpath in misses:
        dicts = miss_results[fpath]
        cache.put_file(fpath, shas[fpath], local_key, dicts)
        local_findings.extend(Finding(**d) for d in dicts)

    # whole-program pass when anything changed (same dispatch as _run)
    if global_rules and project_state == "rebuilt":
        ordered_ctx = [contexts[p] for p in order if p in contexts]
        project = ProjectContext(ordered_ctx)
        for ctx in ordered_ctx:
            ctx.project = project
        out = []
        for ctx in ordered_ctx:
            out.extend(_check_file(ctx, global_rules))
        out.extend(_check_project(project, global_rules))
        project_findings = out
        cache.put_project(project_digest, [f.as_dict() for f in out])

    cache.prune(order)
    cache.save()
    findings = local_findings + project_findings
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    if stats is not None:
        stats["files"] = len(order)
        stats["rules"] = len(selected)
        stats["cache_hits"] = hits
        stats["cache_misses"] = len(misses)
        stats["project_pass"] = project_state
        stats["jobs"] = use_jobs
    return findings


def render_human(findings: List[Finding], stats: Optional[dict] = None) -> str:
    lines = [
        f"{f.path}:{f.line}:{f.col + 1}: {f.code} [{f.rule}] {f.message}" for f in findings
    ]
    tail = f"opensim-lint: {len(findings)} finding(s)" if findings else "opensim-lint: clean"
    if stats:
        tail += (
            f" ({stats.get('files', 0)} files parsed once, "
            f"{stats.get('rules', 0)} rules, {stats.get('seconds', 0.0):.2f}s)"
        )
        if "cache_hits" in stats:
            tail += (
                f" [cache: {stats['cache_hits']} hit / "
                f"{stats.get('cache_misses', 0)} miss, project pass "
                f"{stats.get('project_pass', 'n/a')}, "
                f"{stats.get('jobs', 1)} worker(s)]"
            )
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings: List[Finding]) -> str:
    return json.dumps([f.as_dict() for f in findings], indent=2)


def render_sarif(findings: List[Finding]) -> str:
    """SARIF 2.1.0 — CI annotators and editors ingest this directly
    (``python -m opensim_tpu.analysis --format sarif``)."""
    rule_ids: Dict[str, dict] = {}
    for r in RULES.values():
        rule_ids[r.code] = {
            "id": r.code,
            "name": r.name,
            "shortDescription": {"text": r.description or r.name},
        }
    rule_ids["OSL000"] = {
        "id": "OSL000",
        "name": "parse-error",
        "shortDescription": {"text": "file failed to parse"},
    }
    results = []
    for f in findings:
        results.append(
            {
                "ruleId": f.code,
                "level": "error" if f.code == "OSL000" else "warning",
                "message": {"text": f"[{f.rule}] {f.message}"},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": f.path.replace(os.sep, "/"),
                            },
                            "region": {
                                "startLine": max(1, f.line),
                                "startColumn": f.col + 1,
                            },
                        }
                    }
                ],
            }
        )
    doc = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "opensim-lint",
                        "informationUri": "docs/static-analysis.md",
                        "rules": sorted(rule_ids.values(), key=lambda r: r["id"]),
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)
