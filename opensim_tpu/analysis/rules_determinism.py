"""determinism (OSL301): iteration-order nondeterminism on ordered paths.

Two patterns that break run-to-run reproducibility (encoder streams must be
byte-stable so content fingerprints and golden reports hold):

- iterating a ``set`` (literal, comprehension, ``set()``/``frozenset()``
  call) without ``sorted(...)`` — set order varies with PYTHONHASHSEED;
- inside a fingerprint/hash-building function (one that feeds a hasher
  constructed from ``hashlib.*`` via ``.update``), iterating
  ``.items()`` / ``.keys()`` / ``.values()`` without ``sorted(...)``:
  dict order is insertion order, which for hand-assembled clusters is
  call-site dependent — a fingerprint must not depend on it.

Plain dict iteration outside hash scopes is NOT flagged (insertion order
is deterministic for a fixed build path).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from .core import FileContext, Finding, Rule, dotted_name, parent_map, register

_DICT_VIEWS = {"items", "keys", "values"}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and dotted_name(node.func) in ("set", "frozenset"):
        return True
    return False


def _inside_sorted(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    cur = parents.get(node)
    while cur is not None:
        # NOTE: `sum` is deliberately NOT exempt — float addition is
        # non-associative, so summing a set varies in the last ulp with
        # iteration order (enough to flip score ties in this repo)
        if isinstance(cur, ast.Call) and dotted_name(cur.func) in ("sorted", "min", "max", "len", "any", "all"):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        cur = parents.get(cur)
    return False


@register
class DeterminismRule(Rule):
    name = "determinism"
    code = "OSL301"
    description = "unordered iteration feeding an order-sensitive stream"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        parents = parent_map(ctx.tree)

        # -- set iteration anywhere -----------------------------------------
        iter_sites: List[ast.AST] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iter_sites.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.DictComp, ast.SetComp)):
                iter_sites.extend(gen.iter for gen in node.generators)
            elif isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr == "join" and node.args:
                    iter_sites.append(node.args[0])
                # `sum` qualifies: float addition is non-associative, so a
                # set's iteration order moves the result in the last ulp
                elif dotted_name(fn) in ("list", "tuple", "enumerate", "sum") and node.args:
                    iter_sites.append(node.args[0])
        for site in iter_sites:
            if _is_set_expr(site) and not _inside_sorted(site, parents):
                yield self.finding(
                    ctx,
                    site,
                    "iteration over a set is ordered by PYTHONHASHSEED; wrap "
                    "in sorted(...) before it feeds an ordered stream",
                )

        # -- dict views inside hash-building functions ----------------------
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not self._is_hash_builder(fn):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _DICT_VIEWS
                    and not node.args
                    and not _inside_sorted(node, parents)
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"`.{node.func.attr}()` order feeds a content "
                        f"fingerprint in `{fn.name}`; wrap in sorted(...) so "
                        "the hash is independent of dict build order",
                    )

    @staticmethod
    def _is_hash_builder(fn: ast.AST) -> bool:
        """Function constructs a hasher from hashlib.* and .update()s it."""
        hasher_names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if dotted_name(node.value.func).startswith("hashlib."):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            hasher_names.add(tgt.id)
        if not hasher_names:
            return False
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in hasher_names
            ):
                return True
        return False
