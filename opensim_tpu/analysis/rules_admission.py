"""admission-lock-io (OSL1001): blocking I/O while holding the
admission/dispatch lock.

The admission queue's liveness contract (``server/admission.py``) is that
its condition lock only ever guards queue mutations — O(1) pointer work.
Any *blocking* operation inside that critical section (a window sleep, a
socket read, a future/event wait, subprocess or file I/O) would stall every
concurrent ``submit()``: admission latency becomes the blocked operation's
latency, and the bounded queue turns into an unbounded convoy of HTTP
handler threads parked on the lock. The dispatcher's coalescing window
sleep famously belongs *outside* the lock — this rule keeps it (and every
future refactor) honest.

Flagged inside any ``with`` block whose context expression mentions a
lock/condition attribute (a name ending in ``lock`` or ``cond``) in the
admission/dispatch modules:

- ``time.sleep`` / bare ``sleep``
- ``.wait`` / ``.wait_for`` / ``.join`` / ``.result`` (event, future,
  thread joins — blocking until *someone else* makes progress, the convoy
  maker)
- socket/HTTP I/O (``urlopen``, ``.recv``, ``.accept``, ``.connect``,
  ``select.select``)
- ``subprocess`` calls and ``open``

``notify``/``notify_all`` and plain queue mutations stay legal, as do
waits on the condition variable itself *when the with-block is the
canonical ``while …: cond.wait()`` consumer loop* — a condition wait
releases the lock while blocked, so it cannot convoy. The rule recognizes
that one pattern (``<name>.wait()`` where ``<name>`` appears in the
``with`` expression) and flags every other wait.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from .core import FileContext, Finding, Rule, dotted_name, register

_BLOCKING_LEAVES = {
    "sleep", "wait_for", "join", "result", "recv", "recv_into", "accept",
    "connect", "urlopen", "select", "check_call", "check_output", "run",
    "communicate",
}
# `.wait` handled separately: waiting on the held condition itself releases
# the lock (the canonical consumer loop) and is exempt
_WAIT_LEAVES = {"wait"}
_BLOCKING_ROOTS = {"subprocess"}


def _lock_names(with_node: ast.With) -> Set[str]:
    """Names appearing in the with-items' context expressions, used both to
    decide the rule applies (mentions a lock/cond) and to exempt waits on
    the condition object itself."""
    names: Set[str] = set()
    for item in with_node.items:
        for n in ast.walk(item.context_expr):
            if isinstance(n, ast.Attribute):
                names.add(n.attr)
            elif isinstance(n, ast.Name):
                names.add(n.id)
    return names


def _is_lock_with(with_node: ast.With) -> bool:
    return any(
        n.lower().endswith(("lock", "cond", "condition"))
        for n in _lock_names(with_node)
    )


def _call_target(node: ast.Call) -> str:
    name = dotted_name(node.func)
    if name:
        return name
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


def _body_walk(with_node: ast.With) -> Iterable[ast.AST]:
    for stmt in with_node.body:
        yield from ast.walk(stmt)


@register
class AdmissionLockIoRule(Rule):
    name = "admission-lock-io"
    code = "OSL1001"
    description = "blocking I/O while holding the admission/dispatch lock"
    paths = ("server/admission", "server/pool", "server/rest")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for with_node in ast.walk(ctx.tree):
            if not isinstance(with_node, ast.With) or not _is_lock_with(with_node):
                continue
            held = _lock_names(with_node)
            for node in _body_walk(with_node):
                if not isinstance(node, ast.Call):
                    continue
                target = _call_target(node)
                leaf = target.rsplit(".", 1)[-1]
                root = target.split(".", 1)[0]
                blocking = (
                    leaf in _BLOCKING_LEAVES
                    or root in _BLOCKING_ROOTS
                    or (target == "open" and not _is_os_open(node))
                )
                if leaf in _WAIT_LEAVES:
                    # cond.wait() on the HELD condition releases the lock
                    # while blocked — the one legal wait
                    owner = target.rsplit(".", 2)
                    owner_name = owner[-2] if len(owner) >= 2 else ""
                    blocking = owner_name not in held
                if blocking:
                    yield self.finding(
                        ctx,
                        node,
                        f"blocking call `{target}` while holding the "
                        "admission/dispatch lock; move the wait/sleep/I-O "
                        "outside the critical section "
                        "(server/admission.py locking discipline)",
                    )


def _is_os_open(node: ast.Call) -> bool:
    # os.open (fd-level, nonblocking flags possible) is not the flagged
    # buffered-file `open`
    return dotted_name(node.func) == "os.open"
