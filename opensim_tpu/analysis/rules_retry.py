"""unbounded-retry (OSL601): retry loops without a bound or backoff.

The resilience layer (``opensim_tpu/resilience/retry.py``) gives every
network/device call site bounded attempts with jittered exponential backoff.
Hand-rolled retry loops regress both properties in the two ways this rule
detects:

- **no bound** — a ``while True:`` loop that makes a network/device call and
  contains an exception handler that neither re-raises nor escapes the loop
  (no ``raise``/``return``/``break`` in the handler body): the failure is
  swallowed and the call retried forever. A ``while True`` whose handler
  escapes is fine — the first failure terminates the loop.
- **no backoff** — ``time.sleep(<numeric constant>)`` lexically inside any
  loop body: constant-interval retrying synchronizes clients into retry
  storms exactly when the backend is least able to absorb them. Computed
  sleeps (``sleep(delay)``) are not flagged.

Fix either by calling :func:`opensim_tpu.resilience.retry.retry_call`, or by
bounding the loop and deriving the sleep from the attempt number.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, dotted_name, register

# call names that talk to a network or an accelerator device — the targets
# an unbounded retry loop would hammer
_NETWORK_SUFFIXES = {
    "urlopen",
    "urlretrieve",
    "getaddrinfo",
    "create_connection",
    "connect",
    "recv",
    "send",
    "sendall",
    "request",
    "device_put",
    "block_until_ready",
    "run_scan",
    "cluster_from_kubeconfig",
}
_NETWORK_PREFIXES = ("urllib.", "socket.", "http.client.", "requests.")


def _is_network_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if name.startswith(_NETWORK_PREFIXES):
        return True
    leaf = name.rsplit(".", 1)[-1] if name else ""
    if leaf in _NETWORK_SUFFIXES:
        return True
    return isinstance(node.func, ast.Attribute) and node.func.attr in _NETWORK_SUFFIXES


def _handler_escapes(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return True
    return False


def _loop_body_walk(loop: ast.AST, stop_at_loops: bool = False) -> Iterable[ast.AST]:
    """Walk a loop's body/orelse WITHOUT descending into nested function or
    class definitions (their loops are visited on their own).
    ``stop_at_loops`` also stops at nested loops (yielding the loop node but
    not its body) — the constant-sleep check attributes each sleep to its
    NEAREST enclosing loop only, so nesting never double-reports."""
    stack: List[ast.AST] = list(getattr(loop, "body", [])) + list(getattr(loop, "orelse", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        if stop_at_loops and isinstance(node, (ast.While, ast.For)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_while_true(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.While)
        and isinstance(node.test, ast.Constant)
        and node.test.value is True
    )


def _constant_sleep(node: ast.AST) -> bool:
    if not (isinstance(node, ast.Call) and node.args and not node.keywords):
        return False
    name = dotted_name(node.func)
    if not (name.endswith("time.sleep") or name == "sleep"):
        return False
    arg = node.args[0]
    return isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float))


@register
class UnboundedRetryRule(Rule):
    name = "unbounded-retry"
    code = "OSL601"
    description = "retry loop without a bound or backoff"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            body = list(_loop_body_walk(loop))
            if _is_while_true(loop):
                swallowing = [
                    h
                    for h in body
                    if isinstance(h, ast.ExceptHandler) and not _handler_escapes(h)
                ]
                has_net = any(isinstance(n, ast.Call) and _is_network_call(n) for n in body)
                if swallowing and has_net:
                    yield self.finding(
                        ctx,
                        loop,
                        "`while True` retries a network/device call with no "
                        "attempt bound (the except handler never escapes the "
                        "loop); use resilience.retry.retry_call or bound the "
                        "attempts",
                    )
            for n in _loop_body_walk(loop, stop_at_loops=True):
                if _constant_sleep(n):
                    yield self.finding(
                        ctx,
                        n,
                        "constant time.sleep inside a loop is a backoff-less "
                        "retry; use resilience.retry.retry_call's jittered "
                        "exponential backoff or derive the delay from the "
                        "attempt number",
                    )
