"""jit-boundary (OSL101): host-side work inside jit-traced code.

Finds functions reachable from a ``jax.jit`` / ``jax.lax`` / ``pallas_call``
tracing entry point in the same file, and flags constructs that run on the
HOST at trace time (or fail outright under a tracer):

- calls into ``time.*`` / ``random.*`` / ``np.random.*`` / ``datetime.now``
  — they execute once at trace time and bake a constant into the program;
- ``.item()`` — forces a device sync and breaks under jit;
- ``np.asarray`` / ``np.array`` on a function parameter — parameters of a
  traced function are tracers, and numpy coercion forces a host transfer;
- ``if`` / ``while`` whose test calls ``jnp.*`` / ``lax.*`` — Python
  control flow on a traced boolean raises ConcretizationTypeError.

Reachability is per-file (simple-name call graph); cross-module tracing is
out of scope and documented in docs/static-analysis.md.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from .core import FileContext, Finding, Rule, dotted_name, register

# decorators / callables whose function argument is traced
_JIT_NAMES = {"jax.jit", "jit"}
_TRACING_CALLS = _JIT_NAMES | {
    "jax.vmap",
    "vmap",
    "jax.pmap",
    "pmap",
    "jax.checkpoint",
    "jax.lax.scan",
    "lax.scan",
    "jax.lax.cond",
    "lax.cond",
    "jax.lax.while_loop",
    "lax.while_loop",
    "jax.lax.fori_loop",
    "lax.fori_loop",
    "jax.lax.switch",
    "lax.switch",
    "jax.lax.map",
    "lax.map",
    "pl.pallas_call",
    "pallas_call",
}
_PARTIAL_NAMES = {"functools.partial", "partial"}

_HOST_CALL_PREFIXES = (
    "time.",
    "random.",
    "np.random.",
    "numpy.random.",
    "datetime.datetime.now",
    "datetime.now",
)

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_jit_decorator(dec: ast.AST) -> bool:
    if dotted_name(dec) in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        if fn in _JIT_NAMES:
            return True
        if fn in _PARTIAL_NAMES:
            return any(dotted_name(a) in _JIT_NAMES for a in dec.args)
    return False


def _traced_value_call(node: ast.AST) -> bool:
    """Does the expression contain a call into jnp./lax. (a traced value)?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = dotted_name(sub.func)
            if fn.startswith(("jnp.", "jax.numpy.", "lax.", "jax.lax.")):
                return True
    return False


@register
class JitBoundaryRule(Rule):
    name = "jit-boundary"
    code = "OSL101"
    description = "host-side work inside jit-traced code"
    paths = ("opensim_tpu/engine/", "opensim_tpu/ops/", "opensim_tpu/parallel/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        defs: Dict[str, List[ast.AST]] = {}
        all_funcs: List[ast.AST] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, _FuncNode):
                defs.setdefault(node.name, []).append(node)
                all_funcs.append(node)

        roots: Set[ast.AST] = set()
        for node in all_funcs:
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                roots.add(node)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and dotted_name(node.func) in _TRACING_CALLS:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        roots.update(defs.get(arg.id, ()))
                    elif isinstance(arg, ast.Lambda):
                        roots.add(arg)
                    elif isinstance(arg, _FuncNode):
                        roots.add(arg)

        # propagate through the same-file simple-name call graph
        reachable: Set[ast.AST] = set(roots)
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            body = fn.body if isinstance(fn, _FuncNode) else [fn.body]
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                        for callee in defs.get(sub.func.id, ()):
                            if callee not in reachable:
                                reachable.add(callee)
                                frontier.append(callee)

        for fn in sorted(reachable, key=lambda n: getattr(n, "lineno", 0)):
            yield from self._check_traced_function(ctx, fn)

    def _check_traced_function(self, ctx: FileContext, fn: ast.AST) -> Iterable[Finding]:
        if isinstance(fn, _FuncNode):
            params = {a.arg for a in fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs}
            body = fn.body
            label = fn.name
        else:  # Lambda
            params = {a.arg for a in fn.args.args}
            body = [fn.body]
            label = "<lambda>"

        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    if name.startswith(_HOST_CALL_PREFIXES):
                        yield self.finding(
                            ctx,
                            node,
                            f"host-side call `{name}` inside jit-traced `{label}` "
                            "executes once at trace time (stale clock/PRNG baked "
                            "into the compiled program)",
                        )
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args
                    ):
                        yield self.finding(
                            ctx,
                            node,
                            f"`.item()` inside jit-traced `{label}` forces a host "
                            "sync and fails on tracers; keep the value on device",
                        )
                    elif name in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
                        if node.args and isinstance(node.args[0], ast.Name) and node.args[0].id in params:
                            yield self.finding(
                                ctx,
                                node,
                                f"`{name}` on parameter `{node.args[0].id}` of "
                                f"jit-traced `{label}` coerces a tracer to host "
                                "numpy (transfer or ConcretizationTypeError)",
                            )
                elif isinstance(node, (ast.If, ast.While)) and _traced_value_call(node.test):
                    kw = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        ctx,
                        node,
                        f"Python `{kw}` on a traced value inside `{label}`; use "
                        "jnp.where / lax.cond / lax.while_loop instead",
                    )
