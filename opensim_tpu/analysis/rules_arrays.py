"""OSL18xx — the array-contract rule pack.

Built on :mod:`analysis.arrays` (abstract interpretation of numpy/jax
values over the dataflow CFGs, checked against the contract registry in
``encoding/dtypes.py``) and :mod:`analysis.abi`. Four rules:

- **OSL1801 array-off-policy** — an array created without (or with a
  non-policy) dtype reaches an ``EncodedCluster``/``ScanState``/
  ``NodeArenas`` field or a kernel-entry argument whose contract declares
  a different width. The finding anchors at the creation site (the
  ``np.zeros``/``np.asarray``/literal without a ``dtype=`` from
  ``encoding/dtypes.py``), interprocedurally when the array crosses a
  function boundary before binding.

- **OSL1802 silent-upcast** — a dtype promotion (mixed-width binop,
  ``np.where``, int true-division, float ufunc on ints, numpy's i64
  ``sum`` accumulator) on a path that reaches an arena write or kernel
  boundary whose contract is narrower. Anchors at the promotion site: the
  exact expression where float32 silently became float64.

- **OSL1803 shape-contract** — rank or named-axis-order mismatch against
  the declared ``(dtype, axes)`` contract at a binding site; axis names
  are the symbolic shape vocabulary from ``encoding/state.py`` with the
  builder-local aliases in ``AXIS_ALIASES``. Unknown axes (``?``) never
  fire — only a known-vs-known mismatch does.

- **OSL1804 contract-abi-parity** — the three-way sync: the contract
  registry in ``encoding/dtypes.py`` vs the policy constants it names vs
  the ``EncodedCluster``/``ScanState`` field sets vs the native
  ``_BUFFERS`` packing and the C++ ``ScanArgs`` widths. OSL1604 gates
  scan_engine.cc against the ctypes mirror; this rule closes the
  remaining drift axis — BOTH native sides narrowed while the Python
  contract stays wide (or vice versa) — naming the exact field.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

from . import abi
from .abi import _module_lists
from .arrays import Contracts, _parse_dtypes_module, get_array_findings
from .core import FileContext, Finding, ProjectContext, Rule, register


@dataclass
class _Site:
    lineno: int
    col_offset: int


_SCOPE_PATHS = ("encoding/", "engine/", "parallel/", "native/", "ops/")


class _ArrayRuleBase(Rule):
    project_rule = True
    paths = _SCOPE_PATHS
    exclude_paths = ("tests/",)

    def project_check(self, project: ProjectContext) -> Iterable[Finding]:
        for f in get_array_findings(project):
            if f.code == self.code:
                yield self.finding(f.path, _Site(f.line, f.col), f.message)


@register
class OffPolicyArrayRule(_ArrayRuleBase):
    name = "array-off-policy"
    code = "OSL1801"
    description = (
        "array built without a policy dtype (encoding/dtypes.py) reaches a "
        "contracted arena field or kernel boundary of a different width"
    )


@register
class SilentUpcastRule(_ArrayRuleBase):
    name = "silent-upcast"
    code = "OSL1802"
    description = (
        "dtype promotion on a path reaching an arena write or kernel "
        "boundary whose contract is narrower (interprocedural)"
    )


@register
class ShapeContractRule(_ArrayRuleBase):
    name = "shape-contract"
    code = "OSL1803"
    description = (
        "rank/axis-order mismatch against the declared (dtype, axes) "
        "contract at an arena or kernel binding"
    )


def _compatible(tag: str, width: str) -> bool:
    """Contract tag vs marshalled width. bool masks cross the ctypes
    boundary as u8 (``np.bool_`` is 1 byte) — that pairing is the one
    sanctioned widening."""
    return width == tag or (tag == "bool" and width == "u8")


@register
class ContractAbiParityRule(Rule):
    name = "contract-abi-parity"
    code = "OSL1804"
    description = (
        "contract registry, dtypes policy, EncodedCluster/ScanState fields, "
        "native packing and C++ ScanArgs widths drifted out of three-way sync"
    )
    project_rule = True

    def project_check(self, project: ProjectContext) -> Iterable[Finding]:
        dtypes_ctx: Optional[FileContext] = None
        state_ctx: Optional[FileContext] = None
        native_ctx: Optional[FileContext] = None
        for ctx in project.contexts:
            p = ctx.path.replace(os.sep, "/")
            if p.endswith("encoding/dtypes.py"):
                dtypes_ctx = ctx
            elif p.endswith("encoding/state.py"):
                state_ctx = ctx
            elif p.endswith("native/__init__.py"):
                native_ctx = ctx
        if dtypes_ctx is None:
            return
        con = _parse_dtypes_module(dtypes_ctx.tree, dtypes_ctx.path)
        if not con.arena and not con.state:
            return  # a dtypes.py predating the registry: nothing to gate

        def anchor(fname: str) -> _Site:
            return _Site(con.entry_lines.get(fname, 1), 0)

        for msg in con.problems:
            yield self.finding(dtypes_ctx.path, _Site(1, 0),
                               f"contract registry parse problem: {msg}")

        # 1. every contract names a policy constant that resolves
        for table_name, table in (("ARENA_CONTRACTS", con.arena),
                                  ("STATE_CONTRACTS", con.state)):
            for fname, (policy, _axes) in table.items():
                if policy not in con.policies:
                    yield self.finding(
                        dtypes_ctx.path, anchor(fname),
                        f"{table_name}[{fname!r}] names `{policy}`, which is "
                        "not a *_DTYPE policy constant in encoding/dtypes.py",
                    )
        for fn, params in con.kernel_args.items():
            for pname, (policy, _axes) in params.items():
                if policy not in con.policies:
                    yield self.finding(
                        dtypes_ctx.path, _Site(1, 0),
                        f"KERNEL_ARG_CONTRACTS[{fn!r}][{pname!r}] names "
                        f"`{policy}`, which is not a *_DTYPE policy constant",
                    )

        # 2. registry key sets == the NamedTuple field sets
        if state_ctx is not None:
            yield from self._check_fields(dtypes_ctx, state_ctx, con, anchor)

        # 3. native packing + C++ ScanArgs widths vs the contract tags
        if native_ctx is not None:
            yield from self._check_native(dtypes_ctx, native_ctx, con, anchor)

    # -- registry keys vs encoding/state.py -----------------------------------

    def _check_fields(self, dtypes_ctx, state_ctx, con: Contracts, anchor):
        import ast

        for cls_name, table, table_name in (
            ("EncodedCluster", con.arena, "ARENA_CONTRACTS"),
            ("ScanState", con.state, "STATE_CONTRACTS"),
        ):
            fields = None
            for node in ast.walk(state_ctx.tree):
                if isinstance(node, ast.ClassDef) and node.name == cls_name:
                    fields = [
                        item.target.id
                        for item in node.body
                        if isinstance(item, ast.AnnAssign)
                        and isinstance(item.target, ast.Name)
                    ]
                    break
            if fields is None:
                continue
            for fname in fields:
                if fname not in table:
                    yield self.finding(
                        dtypes_ctx.path, _Site(1, 0),
                        f"{cls_name} field `{fname}` (encoding/state.py) has "
                        f"no {table_name} entry — every arena field must "
                        "declare its (policy dtype, axes) contract",
                    )
            for fname in table:
                if fname not in fields:
                    yield self.finding(
                        dtypes_ctx.path, anchor(fname),
                        f"{table_name} entry `{fname}` names no {cls_name} "
                        "field (stale contract after a field removal/rename?)",
                    )

    # -- native packing + C++ widths vs contracts ------------------------------

    def _contract_for(self, con: Contracts, buf_name: str) -> Optional[Tuple[str, str]]:
        """(policy name, resolved tag) for a native buffer name, or None
        when the buffer carries no Python-side contract (outputs,
        profile/debug arrays)."""
        fname = con.buffer_aliases.get(buf_name, buf_name)
        entry = con.arena.get(fname) or con.state.get(fname)
        if entry is None:
            for params in con.kernel_args.values():
                if fname in params:
                    entry = params[fname]
                    break
        if entry is None:
            return None
        policy = entry[0]
        tag = con.policies.get(policy)
        return (policy, tag) if tag is not None else None

    def _check_native(self, dtypes_ctx, native_ctx, con: Contracts, anchor):
        buffers = _module_lists(native_ctx.tree).get("_BUFFERS", [])
        for item in buffers:
            if not isinstance(item, tuple):
                continue
            buf_name, width = item
            got = self._contract_for(con, buf_name)
            if got is None:
                continue
            policy, tag = got
            if not _compatible(tag, width):
                yield self.finding(
                    dtypes_ctx.path, anchor(con.buffer_aliases.get(buf_name, buf_name)),
                    f"contract-ABI width drift: `{buf_name}` is contracted "
                    f"{policy} ({tag}) but native/__init__.py packs it as "
                    f"{width} — narrow/widen the contract and the native "
                    "packing together",
                )
        cc_path = os.path.join(os.path.dirname(native_ctx.path), "scan_engine.cc")
        if not os.path.isfile(cc_path):
            return
        with open(cc_path, "r", encoding="utf-8") as fh:
            cc_fields, _problems = abi.parse_cc_struct(fh.read())
        for cc_name, kind in cc_fields:
            if not kind.startswith("ptr:"):
                continue  # scalar dims/weights carry no array contract
            width = kind[len("ptr:"):]
            got = self._contract_for(con, cc_name)
            if got is None:
                continue
            policy, tag = got
            if not _compatible(tag, width):
                yield self.finding(
                    dtypes_ctx.path, anchor(con.buffer_aliases.get(cc_name, cc_name)),
                    f"contract-ABI width drift: `{cc_name}` is contracted "
                    f"{policy} ({tag}) but C++ ScanArgs (scan_engine.cc) "
                    f"declares {kind} — the contract registry and the native "
                    "engine disagree on this field's width",
                )
