"""unsupervised-watch-loop (OSL801): `while True` watch/reconnect loops
that bypass the resilience layer.

Extends OSL601 (unbounded-retry) to the live twin's failure surface: a
watch consumer that reconnects in a bare ``while True:`` loop has no
attempt bound, no jittered backoff, and no path to the supervised
``degraded`` state — exactly the crash-loop ``server/watch.py`` exists to
prevent. The reflector contract is:

- loops gated on a stop/supervision condition (``while not stop.is_set()``),
  never a literal ``while True``, and
- every (re)connect and relist wrapped in
  :func:`opensim_tpu.resilience.retry.retry_call` (bounded attempts,
  full-jitter backoff).

This rule flags any ``while True:`` loop that calls a watch/stream-style
API (a call whose dotted leaf is ``watch``, ``stream``, or ``reconnect``)
without ``retry_call`` appearing anywhere in the loop body. Either fix
satisfies it: route the connect through ``retry_call``, or restructure the
loop under a supervised stop condition.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register
from .rules_retry import _is_while_true, _loop_body_walk

# call leaves that (re)establish an event stream — the operations a
# supervised consumer must bound
_WATCH_LEAVES = {"watch", "stream", "reconnect"}


def _is_watch_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    leaf = name.rsplit(".", 1)[-1] if name else ""
    if leaf in _WATCH_LEAVES:
        return True
    return isinstance(node.func, ast.Attribute) and node.func.attr in _WATCH_LEAVES


def _calls_retry_call(body: Iterable[ast.AST]) -> bool:
    for n in body:
        if isinstance(n, ast.Call):
            name = dotted_name(n.func)
            if name.rsplit(".", 1)[-1] == "retry_call":
                return True
    return False


@register
class UnsupervisedWatchLoopRule(Rule):
    name = "unsupervised-watch-loop"
    code = "OSL801"
    description = "`while True` watch/reconnect loop bypassing resilience.retry"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for loop in ast.walk(ctx.tree):
            if not _is_while_true(loop):
                continue
            body = list(_loop_body_walk(loop))
            has_watch = any(isinstance(n, ast.Call) and _is_watch_call(n) for n in body)
            if has_watch and not _calls_retry_call(body):
                yield self.finding(
                    ctx,
                    loop,
                    "`while True` (re)establishes a watch/event stream with "
                    "no attempt bound or backoff; wrap the connect in "
                    "resilience.retry.retry_call and gate the loop on a "
                    "supervision condition (see server/watch.py)",
                )
