"""metric-registry (OSL1101): metric-family registration stays in
``obs/metrics.py``.

The ``/metrics`` surface grew past thirty families across four modules
(REST counters, watch supervisor, admission controller, capacity
observatory). Cardinality governance — which families exist, what labels
they carry, what a scrape can cost — only works if registration lives in
ONE place: the ``FAMILIES`` registry in ``obs/metrics.py``. A family
registered ad hoc elsewhere ships help text and label sets no reviewer of
the registry ever sees, and the exposition-conformance test can pass while
two modules render sibling families that drift apart.

The rule flags, in any module other than ``obs/metrics.py``:

- direct construction of ``CounterVec(...)`` / ``HistogramVec(...)`` —
  use :func:`obs.metrics.make_counter` / :func:`obs.metrics.make_histogram`,
  which force the family through the registry (and inherit its help text);
- calls to ``exposition_headers(...)`` — use
  :func:`obs.metrics.family_header`, which fails loudly on an unregistered
  family name.

Fix by adding the family to ``FAMILIES`` and constructing through the
registry helpers; see docs/static-analysis.md.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register

_BANNED_CALLS = {
    "CounterVec": "make_counter",
    "HistogramVec": "make_histogram",
    "exposition_headers": "family_header",
}


def _leaf(node: ast.Call) -> str:
    name = dotted_name(node.func)
    if name:
        return name.rsplit(".", 1)[-1]
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


@register
class MetricRegistryRule(Rule):
    name = "metric-registry"
    code = "OSL1101"
    description = "metric-family registration outside obs/metrics.py"
    # the registry module necessarily constructs the primitives; tests
    # exercise arbitrary families on purpose
    exclude_paths = ("obs/metrics.py", "tests/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _leaf(node)
            replacement = _BANNED_CALLS.get(leaf)
            if replacement is None:
                continue
            yield self.finding(
                ctx,
                node,
                f"{leaf}(...) registers a metric family outside "
                f"obs/metrics.py; add the family to FAMILIES and use "
                f"obs.metrics.{replacement}(...) so cardinality governance "
                "stays in one place",
            )
