"""metric-registry (OSL1101): metric-family registration stays in
``obs/metrics.py``.

The ``/metrics`` surface grew past thirty families across four modules
(REST counters, watch supervisor, admission controller, capacity
observatory). Cardinality governance — which families exist, what labels
they carry, what a scrape can cost — only works if registration lives in
ONE place: the ``FAMILIES`` registry in ``obs/metrics.py``. A family
registered ad hoc elsewhere ships help text and label sets no reviewer of
the registry ever sees, and the exposition-conformance test can pass while
two modules render sibling families that drift apart.

The rule flags, in any module other than ``obs/metrics.py``:

- direct construction of ``CounterVec(...)`` / ``HistogramVec(...)`` —
  use :func:`obs.metrics.make_counter` / :func:`obs.metrics.make_histogram`,
  which force the family through the registry (and inherit its help text);
- calls to ``exposition_headers(...)`` — use
  :func:`obs.metrics.family_header`, which fails loudly on an unregistered
  family name.

Fix by adding the family to ``FAMILIES`` and constructing through the
registry helpers; see docs/static-analysis.md.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register

_BANNED_CALLS = {
    "CounterVec": "make_counter",
    "HistogramVec": "make_histogram",
    "exposition_headers": "family_header",
}


def _leaf(node: ast.Call) -> str:
    name = dotted_name(node.func)
    if name:
        return name.rsplit(".", 1)[-1]
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


@register
class MetricRegistryRule(Rule):
    name = "metric-registry"
    code = "OSL1101"
    description = "metric-family registration outside obs/metrics.py"
    # the registry module necessarily constructs the primitives; tests
    # exercise arbitrary families on purpose
    exclude_paths = ("obs/metrics.py", "tests/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _leaf(node)
            replacement = _BANNED_CALLS.get(leaf)
            if replacement is None:
                continue
            yield self.finding(
                ctx,
                node,
                f"{leaf}(...) registers a metric family outside "
                f"obs/metrics.py; add the family to FAMILIES and use "
                f"obs.metrics.{replacement}(...) so cardinality governance "
                "stays in one place",
            )


# ---------------------------------------------------------------------------
# OSL1901 family-doc-sync — the FAMILIES registry and the metrics table in
# docs/observability.md name the same families
# ---------------------------------------------------------------------------

_DOC_NAME = "observability.md"
_DOC_ROW = re.compile(r"^\|\s*`([A-Za-z_:][A-Za-z0-9_:]*)`", re.M)
_WALK_UP_MAX = 6


def _parse_families(tree: ast.Module):
    """(names, lineno) of the module-level ``FAMILIES`` dict literal, or
    (None, 1) when the module has none."""
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.target is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "FAMILIES":
                value = getattr(node, "value", None)
                if not isinstance(value, ast.Dict):
                    return None, node.lineno
                names = set()
                for key in value.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        names.add(key.value)
                return names, node.lineno
    return None, 1


def _find_doc(start_dir: str):
    """Walk up from the registry module's directory looking for
    ``docs/observability.md`` (repo layout and corpus fixtures both
    resolve within a few levels)."""
    d = start_dir or "."
    for _ in range(_WALK_UP_MAX):
        candidate = os.path.join(d, "docs", _DOC_NAME)
        if os.path.isfile(candidate):
            return candidate
        parent = os.path.dirname(d) or "."
        if parent == d:
            break
        d = parent
    return None


@register
class FamilyDocSyncRule(Rule):
    name = "family-doc-sync"
    code = "OSL1901"
    description = (
        "metric family registered in obs/metrics.py FAMILIES but missing "
        "from the docs/observability.md metrics table (or vice versa)"
    )
    # the registry module is the single anchor (OSL1101); the doc table is
    # its human-readable mirror — this rule is the sync gate between them
    paths = ("obs/metrics.py",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        families, lineno = _parse_families(ctx.tree)
        if families is None:
            return
        anchor = _Anchor(lineno)
        doc_path = _find_doc(os.path.dirname(os.path.abspath(ctx.path)))
        if doc_path is None:
            yield self.finding(
                ctx.path, anchor,
                f"cannot verify family/doc sync: docs/{_DOC_NAME} not found "
                "above the FAMILIES registry (the metrics table lives there)",
            )
            return
        with open(doc_path, "r", encoding="utf-8") as fh:
            documented = set(_DOC_ROW.findall(fh.read()))
        # only exposition families belong to the table; the doc may show
        # other backticked first-cells (env knobs, endpoints) in other
        # tables — restrict the reverse check to simon_* names
        documented = {n for n in documented if n.startswith("simon_")}
        for name in sorted(families - documented):
            yield self.finding(
                ctx.path, anchor,
                f"family {name!r} is registered in FAMILIES but missing from "
                f"the docs/{_DOC_NAME} metrics table — document it (help "
                "text, type, labels) or unregister it",
            )
        for name in sorted(documented - families):
            yield self.finding(
                ctx.path, anchor,
                f"family {name!r} appears in the docs/{_DOC_NAME} metrics "
                "table but is not registered in FAMILIES — stale doc row "
                "(the family was removed or renamed)",
            )


class _Anchor:
    def __init__(self, lineno: int) -> None:
        self.lineno = lineno
        self.col_offset = 0
