"""deadline-span (OSL701): Deadline phase boundaries without trace spans.

The resilience layer and the observability layer are two views of the SAME
phase structure: everywhere a function enforces the request deadline
(``check_deadline("phase")``) or installs a deadline scope
(``deadline_scope(...)``), the tracer must be able to say how long that
phase took and whether it failed — otherwise a 504's ``phase`` field names
a boundary the flight recorder has no span for, and the latency histograms
go dark exactly where requests die.

The rule flags any function that calls a Deadline API but opens no span in
the same function body (``obs.span`` / ``record_span`` / ``event`` /
``start_trace`` / ``trace_scope``). Nested ``def``/``lambda`` bodies are
not credited to the outer function — a span opened inside a callback does
not cover the enclosing boundary.

Fix by wrapping the phase in ``with obs.span("phase"):`` (or recording a
measured duration with ``obs.record_span``); see docs/observability.md.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, dotted_name, register

_DEADLINE_CALLS = {"check_deadline", "deadline_scope"}
_SPAN_CALLS = {
    "span",
    "record_span",
    "event",
    "start_trace",
    "trace_scope",
    "child_from_seconds",
}


def _leaf(node: ast.Call) -> str:
    name = dotted_name(node.func)
    if name:
        return name.rsplit(".", 1)[-1]
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


def _own_body_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's body without descending into nested function/class
    definitions (their deadline calls are judged on their own)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


@register
class DeadlineSpanRule(Rule):
    name = "deadline-span"
    code = "OSL701"
    description = "Deadline phase boundary without a matching trace span"
    # the modules DEFINING the two layers are exempt: deadline.py's own
    # helpers necessarily name the Deadline APIs, obs is the span layer
    exclude_paths = ("resilience/deadline.py", "opensim_tpu/obs/", "tests/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            first_deadline = None
            has_span = False
            for node in _own_body_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _leaf(node)
                if leaf in _DEADLINE_CALLS and first_deadline is None:
                    first_deadline = node
                elif leaf in _SPAN_CALLS:
                    has_span = True
            if first_deadline is not None and not has_span:
                yield self.finding(
                    ctx,
                    first_deadline,
                    f"function {fn.name!r} opens a Deadline phase boundary "
                    "but records no trace span; wrap the phase in "
                    "`with obs.span(...)` (or obs.record_span) so the "
                    "flight recorder and latency histograms cover it",
                )
