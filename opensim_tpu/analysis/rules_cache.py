"""cache-mutation (OSL401): in-place mutation after fingerprinting.

The NOTES.md hazard class the fuzzers keep re-finding: ``PrepareCache``
keys entries by content fingerprint, and fingerprints hash object identity
+ version — so editing an already-fingerprinted object in place leaves a
stale cache entry serving results for a cluster that no longer exists.

Within one function, after a name is passed to ``fingerprint_cluster`` /
``fingerprint_apps`` / ``simulate_cached``, this rule flags:

- attribute/subscript assignment rooted at that name
  (``cluster.pods[0].phase = ...``);
- mutator-method calls rooted at it (``cluster.pods.append(...)``);
- the same two through a loop variable drawn from it
  (``for p in cluster.pods: p.metadata.labels[...] = ...``).

The escape hatch IS the fix: call ``cache.invalidate(obj)`` (or bump the
object with ``obj.touch()``) after mutating — a later call naming the
mutated object (directly or through a loop alias) clears that object's
findings; an argument-less ``cache.invalidate()`` clears everything.
Analysis is per-function: nested functions get their own scope (a closure
mutating an outer fingerprinted name is outside the rule's reach).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import FileContext, Finding, Rule, dotted_name, register

_FINGERPRINT_CALLS = {
    "fingerprint_cluster": 1,
    "fingerprint_apps": 1,
    "simulate_cached": 2,  # (cluster, apps, cache)
}
_MUTATORS = {
    "append",
    "extend",
    "insert",
    "add",
    "update",
    "setdefault",
    "pop",
    "popitem",
    "remove",
    "discard",
    "clear",
    "sort",
    "reverse",
}
_RELEASE_ATTRS = {"invalidate", "touch"}


_FuncLike = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_scope(fn: ast.AST):
    """ast.walk that stays inside one function scope: nested function
    definitions are not descended into (each gets its own check pass)."""
    stack = [fn]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FuncLike):
                continue
            stack.append(child)


def _root_name(node: ast.AST) -> str:
    """Leftmost dotted root of an attribute/subscript chain: the chain
    ``cluster.pods[0].phase`` roots at ``cluster``; ``self.base.pods`` roots
    at ``self.base`` (two segments, so methods can track self attributes)."""
    parts: List[str] = []
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return ""
    if node.id == "self" and parts:
        return f"self.{parts[-1]}"
    return node.id


@register
class CacheMutationRule(Rule):
    name = "cache-mutation"
    code = "OSL401"
    description = "in-place mutation of a fingerprinted object"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, fn)

    def _check_function(self, ctx: FileContext, fn: ast.AST) -> Iterable[Finding]:
        # pass 1 (line-ordered events): fingerprints, releases, loop aliases
        fingerprinted: Dict[str, int] = {}  # name -> first fingerprint line
        # (line, released root or None=wildcard): .touch() releases its
        # receiver, .invalidate(x) releases x, .invalidate() releases all
        releases: List[Tuple[int, Optional[str]]] = []
        aliases: List[Tuple[str, str]] = []  # (loop var, source root)
        for node in _walk_scope(fn):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                short = callee.rsplit(".", 1)[-1]
                nargs = _FINGERPRINT_CALLS.get(short)
                if nargs:
                    for arg in node.args[:nargs]:
                        root = _root_name(arg)
                        if root:
                            line = getattr(node, "lineno", 0)
                            fingerprinted.setdefault(root, line)
                            fingerprinted[root] = min(fingerprinted[root], line)
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _RELEASE_ATTRS
                ):
                    line = getattr(node, "lineno", 0)
                    if node.func.attr == "touch":
                        releases.append((line, _root_name(node.func.value) or None))
                    elif node.args:
                        releases.append((line, _root_name(node.args[0]) or None))
                    else:
                        releases.append((line, None))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                src = _root_name(node.iter)
                if src and isinstance(node.target, ast.Name):
                    aliases.append((node.target.id, src))
        if not fingerprinted:
            return

        def resolve(root: str) -> str:
            """Chase loop aliases until a fingerprinted name (or dead end)."""
            seen: Set[str] = set()
            while root and root not in seen:
                seen.add(root)
                if root in fingerprinted:
                    return root
                for var, src in aliases:
                    if var == root:
                        root = src
                        break
                else:
                    break
            return ""

        def tracked(root: str) -> Tuple[str, int]:
            """(fingerprinted name, fingerprint line) or ('', 0)."""
            name = resolve(root)
            return (name, fingerprinted[name]) if name else ("", 0)

        def released_after(line: int, name: str) -> bool:
            return any(
                rl >= line and (root is None or root == name or resolve(root) == name)
                for rl, root in releases
            )

        # pass 2: mutations on tracked roots after their fingerprint line
        for node in _walk_scope(fn):
            line = getattr(node, "lineno", 0)
            targets: List[ast.AST] = []
            verb = ""
            if isinstance(node, ast.Assign):
                targets, verb = list(node.targets), "assignment to"
            elif isinstance(node, ast.AugAssign):
                targets, verb = [node.target], "augmented assignment to"
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                targets, verb = [node.func.value], f"`.{node.func.attr}()` on"
            for tgt in targets:
                if isinstance(tgt, ast.Name) and not isinstance(node, ast.Call):
                    continue  # rebinding a local is not a mutation
                root = _root_name(tgt)
                name, fp_line = tracked(root)
                if not name or line < fp_line or released_after(line, name):
                    continue
                yield self.finding(
                    ctx,
                    node,
                    f"{verb} `{ast.unparse(tgt)}` mutates `{name}` after it "
                    "was fingerprinted; the cache entry is now stale — call "
                    "PrepareCache.invalidate(obj) or obj.touch() "
                    "(docs/static-analysis.md#cache-mutation)",
                )
