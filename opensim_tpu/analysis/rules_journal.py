"""journal-discipline (OSL1301): journal bytes are written in ONE place.

The crash-safety story of the watch-event journal (``server/journal.py``,
docs/live-twin.md "Durability & replay") rests on an invariant: every byte
in a segment file is either the magic header or a CRC32-framed record, so
recovery can classify ANY tail state — torn frame, short header, absurd
length, flipped bit — as "truncate here, loudly". One unframed write from
anywhere else and a corrupt journal stops degrading to a relist and starts
crashing recovery.

The rule flags:

- outside ``server/journal.py``: ``open(path, mode)`` where the mode
  writes/appends and the path expression mentions a journal (a literal
  containing ``journal`` or ``.seg``, or a name/attribute spelled
  ``*journal*``) — journal files are opened only by the journal module;
- outside ``server/journal.py``: any ``os.fsync(...)`` — the fsync policy
  knob (``OPENSIM_JOURNAL_FSYNC``) is only enforceable while the journal
  module owns every fsync of its files, and nothing else in this repo has
  durability semantics to fsync;
- inside ``server/journal.py``: ``self._f.write(...)`` anywhere but the
  framing helper (``_write_framed``) and the magic stamps
  (``_open_for_append`` / ``_start_segment``) — an unchecksummed record
  write is exactly the corruption the framing exists to rule out.

Fix by routing writes through :meth:`Journal._write_framed` (or, outside
the journal module, through the ``Journal`` API); see
docs/static-analysis.md.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register

#: functions in server/journal.py allowed to touch the segment file raw:
#: the framing helper itself and the two magic-stamp sites
_FRAMING_FUNCS = ("_write_framed", "_open_for_append", "_start_segment")

_WRITE_MODES = ("a", "w", "x", "+")


def _mentions_journal(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            low = sub.value.lower()
            if "journal" in low or low.endswith(".seg"):
                return True
        elif isinstance(sub, ast.Name) and "journal" in sub.id.lower():
            return True
        elif isinstance(sub, ast.Attribute) and "journal" in sub.attr.lower():
            return True
    return False


def _write_mode(node: ast.Call) -> bool:
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return False
    return any(ch in mode.value for ch in _WRITE_MODES)


@register
class JournalDisciplineRule(Rule):
    name = "journal-discipline"
    code = "OSL1301"
    description = "journal bytes written outside server/journal.py's framing path"
    # tests corrupt journals on purpose (that's what they test)
    exclude_paths = ("tests/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        in_journal = ctx.path.replace("\\", "/").endswith("server/journal.py")
        if in_journal:
            yield from self._check_journal_module(ctx)
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name == "os.fsync" or (
                isinstance(node.func, ast.Attribute) and node.func.attr == "fsync"
            ):
                yield self.finding(
                    ctx, node,
                    "os.fsync outside server/journal.py: the journal module "
                    "owns durability (OPENSIM_JOURNAL_FSYNC); route writes "
                    "through the Journal API",
                )
            elif name == "open" and node.args and _write_mode(node) and _mentions_journal(node.args[0]):
                yield self.finding(
                    ctx, node,
                    "journal file opened for writing outside "
                    "server/journal.py: every journal byte must go through "
                    "Journal._write_framed's CRC32 framing",
                )

    def _check_journal_module(self, ctx: FileContext) -> Iterable[Finding]:
        # map each node to its enclosing function name
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if func.name in _FRAMING_FUNCS:
                continue
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "write"
                    and dotted_name(node.func.value) == "self._f"
                ):
                    yield self.finding(
                        ctx, node,
                        f"unchecksummed segment write in {func.name}(): only "
                        "_write_framed (CRC32 framing) and the magic stamps "
                        "may write journal bytes",
                    )
