"""reason-literal (OSL901): unschedulable reasons must come from the
registered reason-code enum.

The decision-audit layer (ISSUE 7) hangs everything — kube-parity message
rendering, cross-engine reason equality, the ``simon_unschedulable_total``
reason labels, ``simon explain`` — off ONE table of reason strings
(``engine/reasons.py``). An inline literal handed to ``UnscheduledPod``
bypasses that registry: it renders a string no reason code maps back to, so
the aggregate counters, the explanations, and the report text silently
disagree about the same pod.

The rule flags ``UnscheduledPod(...)`` constructions whose reason argument
(second positional, or ``reason=``) is an inline string: a constant, an
f-string, a string concatenation, or ``"...".format(...)``. Reasons built
by the registry helpers (``reasons.node_not_found(...)``,
``reasons.render_unschedulable(...)``, …) or carried in variables pass.

Fix by adding the phrasing to ``engine/reasons.py`` (a new ``Reason``
member or helper) and constructing the string there.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .core import FileContext, Finding, Rule, dotted_name, register


def _literal_string(node: ast.AST) -> bool:
    """Is this expression an inline string literal in any disguise?"""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    if isinstance(node, ast.JoinedStr):  # f-string
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Mod)):
        # "a" + x, "a %s" % x — literal on either side taints the expression
        return _literal_string(node.left) or _literal_string(node.right)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr == "format":
            return _literal_string(node.func.value)
    return False


def _reason_arg(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "reason":
            return kw.value
    if len(call.args) >= 2:
        return call.args[1]
    return None


@register
class ReasonLiteralRule(Rule):
    name = "reason-literal"
    code = "OSL901"
    description = "inline unschedulable-reason string bypassing the reason-code registry"
    # the registry module necessarily contains the literals; tests exercise
    # arbitrary reason strings on purpose
    exclude_paths = ("engine/reasons.py", "tests/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            leaf = name.rsplit(".", 1)[-1] if name else ""
            if leaf != "UnscheduledPod":
                continue
            arg = _reason_arg(node)
            if arg is not None and _literal_string(arg):
                yield self.finding(
                    ctx,
                    arg,
                    "UnscheduledPod reason is an inline string literal; "
                    "unschedulable reasons must come from the registered "
                    "reason-code enum (engine/reasons.py helpers such as "
                    "node_not_found/preempted/render_unschedulable) so "
                    "every engine, counter, and report renders the same "
                    "diagnostic",
                )
