"""Incremental lint cache — flat ``make lint`` wall time as the repo grows.

Content-hash cache for lint results, two buckets:

- **per-file**: findings of the *local* rules (per-file, no project
  context) keyed by the file's sha256 + the selected local rule set.
  An unchanged file re-runs nothing and — when the project pass is also
  cached — is never even re-parsed.
- **project**: findings of the whole-program pass (``project_rule`` rules
  plus per-file rules with ``needs_project``) keyed by a digest over
  EVERY file's hash. Any edit anywhere rebuilds the ProjectContext (the
  symbol table/call graph/dataflow fixpoints are global), but the
  unchanged files' local-rule results still come from cache.

Both buckets are salted with an **analyzer fingerprint** — a hash over
the analysis package's own sources — so editing a rule invalidates
everything without a version constant to forget to bump.

The cache degrades to a no-op on any I/O or decode problem: lint results
are always recomputable, so corruption is handled by ignoring the file
and rewriting it.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional

__all__ = ["LintCache", "analyzer_fingerprint", "DEFAULT_CACHE_PATH"]

DEFAULT_CACHE_PATH = os.path.join(".lint", "cache.json")

_FINGERPRINT: Optional[str] = None


def analyzer_fingerprint() -> str:
    """sha256 over the analysis package's own ``.py`` sources: a rule or
    engine edit invalidates every cached result automatically."""
    global _FINGERPRINT
    if _FINGERPRINT is None:
        h = hashlib.sha256()
        here = os.path.dirname(os.path.abspath(__file__))
        for name in sorted(os.listdir(here)):
            if not name.endswith(".py"):
                continue
            h.update(name.encode())
            try:
                with open(os.path.join(here, name), "rb") as fh:
                    h.update(fh.read())
            except OSError:
                h.update(b"<unreadable>")
        _FINGERPRINT = h.hexdigest()
    return _FINGERPRINT


class LintCache:
    """JSON-backed result cache. All lookups verify the analyzer
    fingerprint; mismatches read as a cold cache."""

    VERSION = 1

    def __init__(self, path: str) -> None:
        self.path = path
        self.data: dict = {"version": self.VERSION, "analyzer": analyzer_fingerprint(),
                           "files": {}, "project": {}}
        self._loaded_ok = False
        try:
            with open(path, "r", encoding="utf-8") as fh:
                got = json.load(fh)
            if (
                isinstance(got, dict)
                and got.get("version") == self.VERSION
                and got.get("analyzer") == analyzer_fingerprint()
            ):
                self.data = got
                self._loaded_ok = True
        except (OSError, ValueError):
            pass

    # -- per-file bucket -----------------------------------------------------

    def file_findings(self, path: str, sha: str, rules_key: str) -> Optional[List[dict]]:
        entry = self.data["files"].get(path)
        if not isinstance(entry, dict) or entry.get("sha") != sha:
            return None
        got = (entry.get("local") or {}).get(rules_key)
        return got if isinstance(got, list) else None

    def put_file(self, path: str, sha: str, rules_key: str, findings: List[dict]) -> None:
        entry = self.data["files"].get(path)
        if not isinstance(entry, dict) or entry.get("sha") != sha:
            entry = {"sha": sha, "local": {}}
            self.data["files"][path] = entry
        entry.setdefault("local", {})[rules_key] = findings

    # -- project bucket ------------------------------------------------------

    #: project results kept per distinct path-set digest, so a scoped
    #: `simon lint <subdir> --cache` run cannot clobber the full-repo slot
    PROJECT_SLOTS = 4

    def project_findings(self, digest: str) -> Optional[List[dict]]:
        proj = self.data.get("project") or {}
        entry = proj.get(digest) if isinstance(proj, dict) else None
        if not isinstance(entry, dict):
            return None
        got = entry.get("findings")
        return got if isinstance(got, list) else None

    def put_project(self, digest: str, findings: List[dict]) -> None:
        proj = self.data.get("project")
        if not isinstance(proj, dict) or "findings" in proj:
            proj = {}  # fresh store (or legacy single-slot layout)
        seq = 1 + max((e.get("seq", 0) for e in proj.values() if isinstance(e, dict)),
                      default=0)
        proj[digest] = {"findings": findings, "seq": seq}
        while len(proj) > self.PROJECT_SLOTS:
            oldest = min(proj, key=lambda d: proj[d].get("seq", 0))
            del proj[oldest]
        self.data["project"] = proj

    # -- persistence ---------------------------------------------------------

    def prune(self, live_paths) -> None:
        """Drop entries whose file is GONE from disk. Entries merely
        outside the current lint set survive — a scoped
        `simon lint <subdir>` run must not evict the full-repo results."""
        live = set(live_paths)
        self.data["files"] = {
            p: e
            for p, e in self.data["files"].items()
            if p in live or os.path.isfile(p)
        }

    def save(self) -> None:
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{self.path}.tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.data, fh)
            os.replace(tmp, self.path)
        except OSError:
            pass  # cache is best-effort; next run recomputes
