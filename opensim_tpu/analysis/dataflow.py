"""Interprocedural dataflow engine over the :class:`ProjectContext`.

PR 10's whole-program pass gave the analyzer a symbol table and a call
graph; this module grows it into real dataflow — the class of tooling the
reference gets for free from Go's type system and ``go vet``:

- **Per-function CFGs** (:func:`build_cfg`) at statement granularity with
  classic **reaching definitions** (:meth:`CFG.reaching_defs`), plus a
  generic forward worklist (:func:`forward_analyze`) shared by every
  abstract interpretation below.

- **Function discovery beyond the symbol table** (:class:`FnUnit`): the
  ProjectContext only records top-level functions and class methods, but
  the serving stack hides code in nested scopes (``make_handler``'s
  ``Handler.do_GET``). The engine enumerates *every* def — nested
  functions, methods of classes defined inside functions — and resolves
  calls through lexical scope chains, ``self``, ``functools.partial``, and
  the ProjectContext's import-aware resolver.

- **Effect inference** (:meth:`DataflowEngine.direct_effects` /
  :meth:`transitive_effects`): per-function effect sets — mutates
  module/instance state, performs I/O, reads clock/RNG, forces a
  host-device sync — with transitive effects computed as a fixpoint over
  the call graph (monotone union, so recursion converges).

- **JIT region tracking** (:meth:`jit_roots` / :meth:`jit_reachable`):
  trace roots from ``@jax.jit``-family decorators, function references
  passed to ``lax.scan``/``vmap``/``pallas_call``-family entry points
  (through ``functools.partial`` and lambdas), and two explicit markers
  for regions the resolver cannot see syntactically::

      def step(carry, x):  # opensim-lint: jit-region
      # opensim-lint: jit-region-module   (first 10 lines: whole module)

- **Forward taint lattice** (:class:`TaintEngine`): untrusted inputs
  (HTTP query/body, CLI args, YAML documents, stdin) are tainted at the
  source; taint propagates flow-sensitively through the CFG and
  interprocedurally through per-function summaries (param→sink,
  param→return, return-taint) iterated to fixpoint over the call graph.
  Calls to a **registered validator** — any function carrying a
  ``@sanitizer``-named decorator (``utils/validate.py``) or listed in
  ``EXTRA_SANITIZERS`` — return clean values; numeric coercions
  (``int``/``float``/``bool``/``len``) sanitize structurally.

The lattice is sets-of-tags with union join: every transfer function is
monotone and the tag universe per function is finite, so all fixpoints
terminate. Limitations (documented in docs/static-analysis.md): taint is
not tracked through object attributes across methods (validate at the
boundary instead), and calls that resolve to nothing propagate taint from
arguments to result conservatively but produce no findings inside the
callee.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext, ProjectContext, dotted_name

__all__ = [
    "Atom",
    "Block",
    "CFG",
    "build_cfg",
    "forward_analyze",
    "Effect",
    "FnUnit",
    "Tag",
    "TaintEngine",
    "SinkHit",
    "DataflowEngine",
]

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)

# ---------------------------------------------------------------------------
# control-flow graphs
# ---------------------------------------------------------------------------


@dataclass
class Atom:
    """One transfer-function unit inside a basic block: either a simple
    statement (``role="stmt"``) or the evaluated fragment of a compound
    statement (an ``if``/``while`` test, a ``for`` iterable + target bind,
    a ``with`` item, an except-handler name bind)."""

    node: ast.AST
    role: str = "stmt"  # stmt | test | iter | withitem | except | return


@dataclass
class Block:
    id: int
    atoms: List[Atom] = field(default_factory=list)
    succ: List[int] = field(default_factory=list)


class CFG:
    """Intraprocedural control-flow graph for one function body.

    ``entry``/``exit`` are block ids; ``blocks[exit]`` is always empty.
    Nested function/class bodies are NOT inlined — a nested ``def`` is a
    single defining atom (the nested body belongs to its own
    :class:`FnUnit`)."""

    def __init__(self) -> None:
        self.blocks: List[Block] = []
        self.entry = self._new()
        self.exit = self._new()

    def _new(self) -> int:
        b = Block(id=len(self.blocks))
        self.blocks.append(b)
        return b.id

    def _edge(self, src: int, dst: int) -> None:
        if dst not in self.blocks[src].succ:
            self.blocks[src].succ.append(dst)

    def preds(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {b.id: [] for b in self.blocks}
        for b in self.blocks:
            for s in b.succ:
                out[s].append(b.id)
        return out

    # -- reaching definitions ------------------------------------------------

    def reaching_defs(self) -> Dict[int, Dict[str, Set[int]]]:
        """Classic may-reach definitions: for each block, the map
        ``var -> {lineno of defs that reach block entry}``. Parameters and
        imports count as definitions at their own line."""
        gen: Dict[int, Dict[str, Set[int]]] = {}
        for b in self.blocks:
            g: Dict[str, Set[int]] = {}
            for atom in b.atoms:
                for name, node in atom_defs(atom):
                    g[name] = {getattr(node, "lineno", 0)}  # strong update
            gen[b.id] = g
        in_: Dict[int, Dict[str, Set[int]]] = {b.id: {} for b in self.blocks}
        preds = self.preds()
        work = [b.id for b in self.blocks]
        while work:
            bid = work.pop(0)
            state: Dict[str, Set[int]] = {}
            for p in preds[bid]:
                out_p = dict(in_[p])
                for name, lines in gen[p].items():
                    out_p[name] = set(lines)
                for name, lines in out_p.items():
                    state.setdefault(name, set()).update(lines)
            if state != in_[bid]:
                in_[bid] = state
                for s in self.blocks[bid].succ:
                    if s not in work:
                        work.append(s)
        return in_


def atom_defs(atom: Atom) -> List[Tuple[str, ast.AST]]:
    """Names an atom (re)defines, with the defining node."""
    node = atom.node
    out: List[Tuple[str, ast.AST]] = []

    def targets(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.append((t.id, t))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                targets(el)
        elif isinstance(t, ast.Starred):
            targets(t.value)

    if atom.role == "iter" and isinstance(node, (ast.For, ast.AsyncFor)):
        targets(node.target)
    elif atom.role == "withitem" and isinstance(node, ast.withitem):
        if node.optional_vars is not None:
            targets(node.optional_vars)
    elif atom.role == "except" and isinstance(node, ast.ExceptHandler):
        if node.name:
            out.append((node.name, node))
    elif isinstance(node, ast.Assign):
        for t in node.targets:
            targets(t)
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets(node.target)
    elif isinstance(node, _FuncNode + (ast.ClassDef,)):
        out.append((node.name, node))
    elif isinstance(node, ast.Import):
        for alias in node.names:
            out.append(((alias.asname or alias.name.split(".")[0]), node))
    elif isinstance(node, ast.ImportFrom):
        for alias in node.names:
            if alias.name != "*":
                out.append((alias.asname or alias.name, node))
    # walrus targets anywhere in the atom's expressions
    for sub in ast.walk(node):
        if isinstance(sub, ast.NamedExpr) and isinstance(sub.target, ast.Name):
            out.append((sub.target.id, sub.target))
    return out


class _CFGBuilder:
    def __init__(self) -> None:
        self.cfg = CFG()
        self.loop_stack: List[Tuple[int, int]] = []  # (head, after)

    def build(self, body: Sequence[ast.stmt]) -> CFG:
        end = self._stmts(body, self.cfg.entry)
        if end is not None:
            self.cfg._edge(end, self.cfg.exit)
        return self.cfg

    def _stmts(self, body: Sequence[ast.stmt], cur: Optional[int]) -> Optional[int]:
        for stmt in body:
            if cur is None:
                return None  # unreachable code after return/raise/break
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, stmt: ast.stmt, cur: int) -> Optional[int]:
        cfg = self.cfg
        add = cfg.blocks[cur].atoms.append
        if isinstance(stmt, ast.If):
            add(Atom(stmt, "test"))
            then = cfg._new()
            cfg._edge(cur, then)
            t_end = self._stmts(stmt.body, then)
            after = cfg._new()
            if stmt.orelse:
                els = cfg._new()
                cfg._edge(cur, els)
                e_end = self._stmts(stmt.orelse, els)
                if e_end is not None:
                    cfg._edge(e_end, after)
            else:
                cfg._edge(cur, after)
            if t_end is not None:
                cfg._edge(t_end, after)
            return after
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = cfg._new()
            cfg._edge(cur, head)
            cfg.blocks[head].atoms.append(
                Atom(stmt, "test" if isinstance(stmt, ast.While) else "iter")
            )
            body_b = cfg._new()
            after = cfg._new()
            cfg._edge(head, body_b)
            cfg._edge(head, after)
            self.loop_stack.append((head, after))
            b_end = self._stmts(stmt.body, body_b)
            self.loop_stack.pop()
            if b_end is not None:
                cfg._edge(b_end, head)
            if stmt.orelse:
                els = cfg._new()
                cfg._edge(head, els)
                o_end = self._stmts(stmt.orelse, els)
                if o_end is not None:
                    cfg._edge(o_end, after)
            return after
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                add(Atom(item, "withitem"))
            return self._stmts(stmt.body, cur)
        if isinstance(stmt, ast.Try):
            body_b = cfg._new()
            cfg._edge(cur, body_b)
            first = len(cfg.blocks) - 1
            b_end = self._stmts(stmt.body, body_b)
            body_blocks = [b.id for b in cfg.blocks[first:]]
            after = cfg._new()
            o_end = b_end
            if stmt.orelse and b_end is not None:
                o_end = self._stmts(stmt.orelse, b_end)
            # any statement inside the try may transfer to any handler
            ends: List[Optional[int]] = [o_end]
            for handler in stmt.handlers:
                h = cfg._new()
                cfg.blocks[h].atoms.append(Atom(handler, "except"))
                for bid in body_blocks:
                    cfg._edge(bid, h)
                ends.append(self._stmts(handler.body, h))
            if stmt.finalbody:
                fin = cfg._new()
                for e in ends:
                    if e is not None:
                        cfg._edge(e, fin)
                f_end = self._stmts(stmt.finalbody, fin)
                if f_end is not None:
                    cfg._edge(f_end, after)
                return after
            for e in ends:
                if e is not None:
                    cfg._edge(e, after)
            return after
        if isinstance(stmt, ast.Return):
            add(Atom(stmt, "return"))
            cfg._edge(cur, cfg.exit)
            return None
        if isinstance(stmt, ast.Raise):
            add(Atom(stmt))
            cfg._edge(cur, cfg.exit)
            return None
        if isinstance(stmt, ast.Break):
            if self.loop_stack:
                cfg._edge(cur, self.loop_stack[-1][1])
            return None
        if isinstance(stmt, ast.Continue):
            if self.loop_stack:
                cfg._edge(cur, self.loop_stack[-1][0])
            return None
        add(Atom(stmt))
        return cur


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for a FunctionDef/AsyncFunctionDef (or any statement list
    wrapped in an object with ``body``)."""
    return _CFGBuilder().build(getattr(fn, "body", fn))


def forward_analyze(cfg: CFG, init, transfer, join):
    """Generic forward worklist over ``cfg``. ``init`` is the entry state;
    ``transfer(atom, state) -> state`` must be monotone; ``join(a, b)``
    the lattice union. Returns ``{block id: in-state}``. States must
    support ``==``."""
    in_: Dict[int, object] = {cfg.entry: init}
    preds = cfg.preds()
    order = [b.id for b in cfg.blocks]
    work = list(order)
    out_cache: Dict[int, object] = {}

    def block_out(bid: int) -> object:
        state = in_.get(bid)
        if state is None:
            return None
        for atom in cfg.blocks[bid].atoms:
            state = transfer(atom, state)
        return state

    while work:
        bid = work.pop(0)
        if bid != cfg.entry:
            merged = None
            for p in preds[bid]:
                o = out_cache.get(p)
                if o is None:
                    continue
                merged = o if merged is None else join(merged, o)
            if merged is None:
                continue
            if bid in in_ and merged == in_[bid]:
                out_cache.setdefault(bid, block_out(bid))
                continue
            in_[bid] = merged
        new_out = block_out(bid)
        if out_cache.get(bid) != new_out:
            out_cache[bid] = new_out
            for s in cfg.blocks[bid].succ:
                if s not in work:
                    work.append(s)
    return in_


# ---------------------------------------------------------------------------
# function units: every def in the project, nested scopes included
# ---------------------------------------------------------------------------


@dataclass
class FnUnit:
    """One analyzable function anywhere in a module (top-level, method,
    nested def, method of a class defined inside a function)."""

    qual: str  # module.outer.Class.meth (full lexical path)
    module: str
    cls: Optional[str]  # innermost class name when a method
    node: ast.AST
    ctx: FileContext
    params: List[str] = field(default_factory=list)
    visible: Dict[str, str] = field(default_factory=dict)  # name -> unit qual
    class_scope: Dict[str, str] = field(default_factory=dict)  # method -> qual


_JIT_DECOR = {"jax.jit", "jit"}
_TRACING_CALLS = _JIT_DECOR | {
    "jax.vmap", "vmap", "jax.pmap", "pmap", "jax.checkpoint",
    "jax.lax.scan", "lax.scan", "jax.lax.cond", "lax.cond",
    "jax.lax.while_loop", "lax.while_loop", "jax.lax.fori_loop",
    "lax.fori_loop", "jax.lax.switch", "lax.switch", "jax.lax.map",
    "lax.map", "pl.pallas_call", "pallas_call", "shard_map",
    "jax.experimental.shard_map.shard_map",
}
_PARTIAL = {"functools.partial", "partial"}

_JIT_MARK_RE = re.compile(r"#\s*opensim-lint:\s*jit-region\b")
_JIT_MODULE_MARK_RE = re.compile(r"#\s*opensim-lint:\s*jit-region-module\b")

# -- effect tables -----------------------------------------------------------

_IO_EXACT = {
    "open", "io.open", "os.system", "os.popen", "os.urandom",
    "os.remove", "os.unlink", "os.replace", "os.rename", "os.makedirs",
    "os.mkdir", "os.rmdir", "os.fsync", "os.fdatasync", "os.open",
    "os.write", "os.read", "os.truncate", "os.chmod", "input", "print",
}
_IO_PREFIX = ("subprocess.", "shutil.", "socket.", "urllib.request.")
_CLOCK_EXACT = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "time.perf_counter_ns", "time.sleep",
    "datetime.datetime.now", "datetime.now", "datetime.datetime.utcnow",
    "datetime.utcnow",
}
_RNG_PREFIX = ("random.", "np.random.", "numpy.random.", "secrets.")
_RNG_EXACT = {"os.urandom", "uuid.uuid1", "uuid.uuid4", "random"}
_SYNC_EXACT = {
    "jax.device_get", "np.asarray", "np.array", "numpy.asarray", "numpy.array",
}
_SYNC_METHODS = {"item", "block_until_ready", "tolist"}


def _src_of(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except (ValueError, RecursionError, AttributeError):
        return type(node).__name__


@dataclass(frozen=True)
class Effect:
    """One inferred side effect at a concrete site."""

    kind: str  # "io" | "clock" | "rng" | "host-sync" | "state-write"
    desc: str
    line: int
    col: int

    def __str__(self) -> str:  # compact for messages/tests
        return f"{self.kind}:{self.desc}"


# ---------------------------------------------------------------------------
# taint tags
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Tag:
    """One taint provenance: a real untrusted source (``kind`` names it),
    a function parameter placeholder (``kind="param"``), or a traced
    value (``kind="traced"``/``"traced-param"``) for the tracer-leak
    pass."""

    kind: str
    desc: str = ""
    line: int = 0
    index: int = -1  # param index for kind == "param"/"traced-param"

    @property
    def is_param(self) -> bool:
        return self.kind in ("param", "traced-param")


TagSet = FrozenSet[Tag]
_EMPTY: TagSet = frozenset()

#: dotted-name leaves whose call RESULT is untrusted input
_SOURCE_LEAVES = {
    "parse_qs": "http-query",
    "parse_qsl": "http-query",
    "parse_args": "cli-arg",
    "parse_known_args": "cli-arg",
    "safe_load": "yaml-field",
    "full_load": "yaml-field",
    "unsafe_load": "yaml-field",
    "input": "stdin",
}
#: dotted names (exact) whose VALUE is untrusted input
_SOURCE_NAMES = {"sys.argv": "cli-arg"}
#: attribute-chain fragments marking HTTP request internals
_HTTP_BODY_RE = re.compile(r"(^|\.)rfile\.read$")

#: calls that return sanitized values regardless of argument taint
_COERCION_SANITIZERS = {"int", "float", "bool", "len", "ord", "hash", "id", "isinstance"}

#: recognized even when the callee does not resolve (partial-project lint
#: runs — e.g. `make lint opensim_tpu/analysis` — cannot see
#: utils/validate.py): the shared validator module's convention is part
#: of the rule contract, so `validate.<fn>(...)` and the two canonical
#: validator names always read as registered sanitizers
_SANITIZER_MODULE = "validate"
_SANITIZER_LEAVES = {"user_path", "child_path"}

#: sink table: dotted-name leaf (or exact) -> human label. ``args`` says
#: which positional arguments are sensitive ("all" or a set of indexes).
_SINKS_EXACT = {
    "open": ("open()", "all"),
    "io.open": ("open()", "all"),
    "os.remove": ("os.remove()", "all"),
    "os.unlink": ("os.unlink()", "all"),
    "os.replace": ("os.replace()", "all"),
    "os.rename": ("os.rename()", "all"),
    "os.makedirs": ("os.makedirs()", "all"),
    "os.mkdir": ("os.mkdir()", "all"),
    "os.rmdir": ("os.rmdir()", "all"),
    "os.listdir": ("os.listdir()", "all"),
    "os.chmod": ("os.chmod()", "all"),
    "os.path.join": ("os.path.join()", "all"),
    "os.system": ("os.system()", "all"),
    "os.popen": ("os.popen()", "all"),
    "shutil.rmtree": ("shutil.rmtree()", "all"),
    "shutil.copy": ("shutil.copy()", "all"),
    "shutil.move": ("shutil.move()", "all"),
}
_SINK_PREFIXES = (
    ("subprocess.", "subprocess"),
)
#: bare-callable leaves that construct filesystem paths
_SINK_CTOR_LEAVES = {"Path": "pathlib.Path()"}


@dataclass(frozen=True)
class SinkHit:
    """A tainted value reaching a sink. ``tags`` carries provenance; param
    tags mean 'when the enclosing function's parameter is tainted'."""

    unit: str
    sink: str
    tags: TagSet
    line: int
    col: int
    desc: str


@dataclass
class FnSummary:
    """Interprocedural taint summary for one unit."""

    param_sinks: Dict[int, str] = field(default_factory=dict)  # index -> sink label
    param_to_ret: Set[int] = field(default_factory=set)
    ret_tags: TagSet = _EMPTY  # real source tags flowing to the return value

    def key(self) -> Tuple:
        return (
            tuple(sorted(self.param_sinks.items())),
            tuple(sorted(self.param_to_ret)),
            self.ret_tags,
        )


def _is_sanitizer_def(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", ()):
        name = dotted_name(dec) or (
            dotted_name(dec.func) if isinstance(dec, ast.Call) else ""
        )
        if name.rsplit(".", 1)[-1] == "sanitizer":
            return True
    return False


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


class DataflowEngine:
    """Lazy, memoized dataflow facade built over a ProjectContext. Rules
    grab it via :func:`get_engine` so every OSL16xx rule in one run shares
    the unit table, CFGs, effect fixpoint, and taint summaries."""

    #: qualname suffixes treated as registered sanitizers even without a
    #: decorator (external or generated code the AST cannot mark)
    EXTRA_SANITIZERS: Tuple[str, ...] = ()

    def __init__(self, project: ProjectContext) -> None:
        self.project = project
        self.units: Dict[str, FnUnit] = {}
        self._module_globals: Dict[str, Set[str]] = {}
        self._cfgs: Dict[str, CFG] = {}
        self._edges: Optional[Dict[str, List[Tuple[str, ast.Call]]]] = None
        self._direct_eff: Dict[str, Tuple[Effect, ...]] = {}
        self._trans_eff: Optional[Dict[str, Dict[Effect, str]]] = None
        self._roots: Optional[Dict[str, str]] = None
        self._reach: Optional[Dict[str, Tuple[str, Tuple[str, ...]]]] = None
        self._sanitizers: Set[str] = set()
        self._discover()

    # -- discovery -----------------------------------------------------------

    def _discover(self) -> None:
        for ctx in self.project.contexts:
            mod = ctx.module
            tops: Set[str] = set()
            for stmt in ctx.tree.body:
                for name, _node in atom_defs(Atom(stmt)):
                    tops.add(name)
            self._module_globals[mod] = tops
            self._walk_scope(ctx, ctx.tree.body, mod, None, {}, {})
            # module-level "unit" for tracing calls / sinks in init code
            body = [
                s for s in ctx.tree.body if not isinstance(s, _FuncNode + (ast.ClassDef,))
            ]
            unit = FnUnit(
                qual=f"{mod}.<module>", module=mod, cls=None,
                node=ast.Module(body=list(body), type_ignores=[]), ctx=ctx,
            )
            unit.visible = {
                n: f"{mod}.{n}"
                for n in tops
                if f"{mod}.{n}" in self.units
            }
            self.units[unit.qual] = unit

    def _walk_scope(
        self,
        ctx: FileContext,
        body: Sequence[ast.stmt],
        prefix: str,
        cls: Optional[str],
        enclosing: Dict[str, str],
        class_scope: Dict[str, str],
    ) -> None:
        local: Dict[str, str] = dict(enclosing)
        for stmt in body:
            if isinstance(stmt, _FuncNode):
                qual = f"{prefix}.{stmt.name}"
                if cls is not None:
                    # methods are reached via self.m(), not as bare names
                    class_scope[stmt.name] = qual
                else:
                    local[stmt.name] = qual
        for stmt in body:
            if isinstance(stmt, _FuncNode):
                qual = f"{prefix}.{stmt.name}"
                a = stmt.args
                params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
                # the function body also sees its own immediately-nested defs
                child_visible = dict(local)
                for inner in stmt.body:
                    if isinstance(inner, _FuncNode):
                        child_visible[inner.name] = f"{qual}.{inner.name}"
                unit = FnUnit(
                    qual=qual, module=ctx.module, cls=cls, node=stmt, ctx=ctx,
                    params=params, visible=child_visible,
                    class_scope=class_scope if cls is not None else {},
                )
                self.units[qual] = unit
                if _is_sanitizer_def(stmt):
                    self._sanitizers.add(qual)
                self._walk_scope(ctx, stmt.body, qual, None, child_visible, {})
            elif isinstance(stmt, ast.ClassDef):
                self._walk_scope(
                    ctx, stmt.body, f"{prefix}.{stmt.name}", stmt.name, local, {}
                )
            elif isinstance(stmt, (ast.If, ast.Try, ast.With, ast.AsyncWith,
                                   ast.For, ast.AsyncFor, ast.While)):
                # defs under conditionals (TYPE_CHECKING guards, try/except
                # import fallbacks) still bind names in this scope
                inner: List[ast.stmt] = list(getattr(stmt, "body", []))
                for part in ("orelse", "finalbody"):
                    inner.extend(getattr(stmt, part, []) or [])
                for h in getattr(stmt, "handlers", []) or []:
                    inner.extend(h.body)
                self._walk_scope(ctx, inner, prefix, cls, local, class_scope)

    def cfg(self, qual: str) -> CFG:
        got = self._cfgs.get(qual)
        if got is None:
            got = self._cfgs[qual] = build_cfg(self.units[qual].node)
        return got

    def is_sanitizer(self, qual: Optional[str]) -> bool:
        if not qual:
            return False
        if qual in self._sanitizers:
            return True
        return any(qual.endswith(sfx) for sfx in self.EXTRA_SANITIZERS)

    # -- call resolution -----------------------------------------------------

    def resolve_call(self, unit: FnUnit, call: ast.Call) -> Optional[str]:
        """Unit qualname a call lands in, or None for external/unresolved.
        Sees through lexical scope, ``self.m()``, module imports (via the
        ProjectContext resolver), and class construction (-> __init__)."""
        return self._resolve_func_expr(unit, call.func)

    def _resolve_func_expr(self, unit: FnUnit, fexpr: ast.AST) -> Optional[str]:
        if isinstance(fexpr, ast.Name):
            got = unit.visible.get(fexpr.id)
            if got is not None:
                return got
        if (
            isinstance(fexpr, ast.Attribute)
            and isinstance(fexpr.value, ast.Name)
            and fexpr.value.id == "self"
            and fexpr.attr in unit.class_scope
        ):
            return unit.class_scope[fexpr.attr]
        got = self.project.resolve_value(fexpr, unit.module, unit.cls, {})
        if got is not None:
            if got[0] == "func":
                return self._unit_for_symbol(got[1])
            if got[0] == "class":
                return self._unit_for_symbol(f"{got[1]}.{got[2]}.__init__")
        return None

    def _unit_for_symbol(self, qual: str) -> Optional[str]:
        return qual if qual in self.units else None

    def edges(self) -> Dict[str, List[Tuple[str, ast.Call]]]:
        """caller unit -> [(callee unit, call node)]; ``functools.partial``
        references contribute a reachability edge at the partial site."""
        if self._edges is not None:
            return self._edges
        out: Dict[str, List[Tuple[str, ast.Call]]] = {}
        for qual, unit in self.units.items():
            lst: List[Tuple[str, ast.Call]] = []
            for call in self._own_calls(unit):
                callee = self.resolve_call(unit, call)
                if callee is not None:
                    lst.append((callee, call))
                name = dotted_name(call.func)
                if name in _PARTIAL and call.args:
                    ref = self._resolve_func_expr(unit, call.args[0])
                    if ref is not None:
                        lst.append((ref, call))
            out[qual] = lst
        self._edges = out
        return out

    def _own_calls(self, unit: FnUnit) -> Iterable[ast.Call]:
        """Call nodes in a unit's own body (nested defs excluded; lambda
        bodies included — they execute in this frame's dynamic extent
        often enough, and over-approximation is safe for reachability)."""
        for stmt in self._own_stmts(unit):
            for sub in self._walk_skip_defs(stmt):
                if isinstance(sub, ast.Call):
                    yield sub

    def _own_stmts(self, unit: FnUnit) -> List[ast.stmt]:
        return list(getattr(unit.node, "body", []))

    @staticmethod
    def _walk_skip_defs(root: ast.AST) -> Iterable[ast.AST]:
        stack = [root]
        first = True
        while stack:
            node = stack.pop()
            if not first and isinstance(node, _FuncNode + (ast.ClassDef,)):
                continue  # nested scope: its own unit
            first = False
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- effect inference ----------------------------------------------------

    def direct_effects(self, qual: str) -> Tuple[Effect, ...]:
        got = self._direct_eff.get(qual)
        if got is not None:
            return got
        unit = self.units[qual]
        effects: List[Effect] = []
        globals_ = self._module_globals.get(unit.module, set())
        declared_global: Set[str] = set()
        for stmt in self._own_stmts(unit):
            for sub in self._walk_skip_defs(stmt):
                if isinstance(sub, (ast.Global, ast.Nonlocal)):
                    declared_global.update(sub.names)
        params = set(unit.params)
        for stmt in self._own_stmts(unit):
            for sub in self._walk_skip_defs(stmt):
                eff = self._effect_of_node(sub, unit, params, globals_, declared_global)
                if eff is not None:
                    effects.append(eff)
        got = tuple(effects)
        self._direct_eff[qual] = got
        return got

    def _effect_of_node(
        self,
        node: ast.AST,
        unit: FnUnit,
        params: Set[str],
        globals_: Set[str],
        declared_global: Set[str],
    ) -> Optional[Effect]:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name in _CLOCK_EXACT:
                return Effect("clock", name, line, col)
            if name in _RNG_EXACT or name.startswith(_RNG_PREFIX):
                return Effect("rng", name, line, col)
            if name in _IO_EXACT or name.startswith(_IO_PREFIX):
                return Effect("io", name, line, col)
            if name in _SYNC_EXACT:
                # np coercion is legal on static trace-time values; like
                # OSL101, flag it only on function parameters (tracers)
                if name.endswith(("asarray", "array")):
                    if not (
                        node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in params
                    ):
                        return None
                return Effect("host-sync", name, line, col)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS
                and not node.args
            ):
                return Effect("host-sync", f".{node.func.attr}()", line, col)
            # in-place mutation of a parameter's or global's container
            if isinstance(node.func, ast.Attribute):
                from .core import MUTATOR_METHODS

                if node.func.attr in MUTATOR_METHODS:
                    base = node.func.value
                    root = base
                    while isinstance(root, (ast.Attribute, ast.Subscript)):
                        root = root.value
                    if isinstance(root, ast.Name) and (
                        root.id in globals_ or root.id in declared_global
                        or (root.id == "self" and unit.cls is not None)
                    ):
                        return Effect(
                            "state-write", f"{dotted_name(base)}.{node.func.attr}()",
                            line, col,
                        )
            return None
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in tgts:
                if isinstance(t, ast.Name) and t.id in declared_global:
                    return Effect("state-write", f"global {t.id}", line, col)
                root = t
                while isinstance(root, (ast.Attribute, ast.Subscript)):
                    root = root.value
                if root is t:
                    continue
                if isinstance(root, ast.Name):
                    fname = unit.node.name if isinstance(unit.node, _FuncNode) else ""
                    if root.id == "self" and unit.cls is not None:
                        if fname in ("__init__", "__post_init__", "__new__"):
                            continue
                        return Effect("state-write", _src_of(t), line, col)
                    if root.id in globals_ and root.id not in params:
                        return Effect("state-write", _src_of(t), line, col)
        return None

    def transitive_effects(self, qual: str) -> Dict[Effect, str]:
        """Every effect a call to ``qual`` can reach, mapped to the unit
        that performs it directly. Fixpoint over the call graph; cycles
        converge because the union only grows."""
        if self._trans_eff is None:
            eff: Dict[str, Dict[Effect, str]] = {
                q: {e: q for e in self.direct_effects(q)} for q in self.units
            }
            edges = self.edges()
            changed = True
            while changed:
                changed = False
                for q, outs in edges.items():
                    mine = eff[q]
                    for callee, _node in outs:
                        for e, origin in eff.get(callee, {}).items():
                            if e not in mine:
                                mine[e] = origin
                                changed = True
            self._trans_eff = eff
        return self._trans_eff.get(qual, {})

    # -- jit regions ---------------------------------------------------------

    def jit_roots(self) -> Dict[str, str]:
        """Unit qual -> reason string ('@jax.jit', 'passed to lax.scan at
        path:line', 'jit-region marker', 'jit-region-module marker')."""
        if self._roots is not None:
            return self._roots
        roots: Dict[str, str] = {}
        for qual, unit in self.units.items():
            node = unit.node
            if isinstance(node, _FuncNode):
                for dec in node.decorator_list:
                    if self._is_jit_decorator(dec):
                        roots.setdefault(qual, f"@{dotted_name(dec) or 'jax.jit'}")
                lines = unit.ctx.lines
                for ln in (node.lineno, node.lineno - 1):
                    if 1 <= ln <= len(lines) and _JIT_MARK_RE.search(lines[ln - 1]):
                        roots.setdefault(qual, "jit-region marker")
        for ctx in self.project.contexts:
            if any(_JIT_MODULE_MARK_RE.search(l) for l in ctx.lines[:10]):
                for qual, unit in self.units.items():
                    if unit.module == ctx.module and isinstance(unit.node, _FuncNode):
                        roots.setdefault(qual, "jit-region-module marker")
        # function references handed to tracing entry points
        for qual, unit in self.units.items():
            local_assigns: Dict[str, ast.AST] = {}
            for stmt in self._own_stmts(unit):
                for sub in self._walk_skip_defs(stmt):
                    if (
                        isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)
                    ):
                        local_assigns[sub.targets[0].id] = sub.value
            for call in self._own_calls(unit):
                name = dotted_name(call.func)
                if name not in _TRACING_CALLS:
                    continue
                where = f"{name} at {unit.ctx.path}:{getattr(call, 'lineno', 0)}"
                for arg in list(call.args) + [kw.value for kw in call.keywords]:
                    self._root_from_ref(unit, arg, where, roots, local_assigns)
        self._roots = roots
        return roots

    def _root_from_ref(
        self,
        unit: FnUnit,
        arg: ast.AST,
        where: str,
        roots: Dict[str, str],
        local_assigns: Optional[Dict[str, ast.AST]] = None,
        _depth: int = 0,
    ) -> None:
        if _depth > 4:
            return
        if isinstance(arg, ast.Lambda):
            # the lambda body runs traced: its resolved callees are roots
            for sub in ast.walk(arg.body):
                if isinstance(sub, ast.Call):
                    got = self.resolve_call(unit, sub)
                    if got is not None:
                        roots.setdefault(got, f"lambda body, {where}")
            return
        if isinstance(arg, ast.Call) and dotted_name(arg.func) in _PARTIAL and arg.args:
            self._root_from_ref(unit, arg.args[0], where, roots, local_assigns, _depth + 1)
            return
        got = self._resolve_func_expr(unit, arg)
        if got is not None:
            roots.setdefault(got, f"passed to {where}")
            return
        # a local bound earlier in the same body (step = partial(_step, ...))
        if (
            isinstance(arg, ast.Name)
            and local_assigns is not None
            and arg.id in local_assigns
        ):
            self._root_from_ref(
                unit, local_assigns[arg.id], where, roots, local_assigns, _depth + 1
            )

    @staticmethod
    def _is_jit_decorator(dec: ast.AST) -> bool:
        if dotted_name(dec) in _JIT_DECOR:
            return True
        if isinstance(dec, ast.Call):
            fn = dotted_name(dec.func)
            if fn in _JIT_DECOR:
                return True
            if fn in _PARTIAL:
                return any(dotted_name(a) in _JIT_DECOR for a in dec.args)
        return False

    def jit_reachable(self) -> Dict[str, Tuple[str, Tuple[str, ...]]]:
        """Unit -> (root unit, call chain root..unit exclusive). BFS over
        the unit call graph from every jit root."""
        if self._reach is not None:
            return self._reach
        edges = self.edges()
        reach: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        from collections import deque

        queue: deque = deque()
        for root in sorted(self.jit_roots()):
            if root not in reach:
                reach[root] = (root, ())
                queue.append(root)
        while queue:
            q = queue.popleft()
            root, chain = reach[q]
            for callee, _node in edges.get(q, ()):  # noqa: B007
                if callee not in reach:
                    reach[callee] = (root, chain + (q,))
                    queue.append(callee)
        self._reach = reach
        return reach


# ---------------------------------------------------------------------------
# taint engine
# ---------------------------------------------------------------------------


class TaintEngine:
    """Forward taint over every unit, interprocedural via summaries."""

    MAX_ROUNDS = 8

    def __init__(self, engine: DataflowEngine) -> None:
        self.df = engine
        self.summaries: Dict[str, FnSummary] = {}

    def run(self) -> List[SinkHit]:
        units = self.df.units
        for _round in range(self.MAX_ROUNDS):
            changed = False
            for qual in units:
                new = self._analyze(qual, collect=False)
                old = self.summaries.get(qual)
                if old is None or old.key() != new.key():
                    self.summaries[qual] = new
                    changed = True
            if not changed:
                break
        hits: List[SinkHit] = []
        for qual in units:
            self._analyze(qual, collect=True, hits=hits)
        return hits

    # -- per-unit abstract interpretation ------------------------------------

    def _analyze(
        self,
        qual: str,
        collect: bool,
        hits: Optional[List[SinkHit]] = None,
    ) -> FnSummary:
        unit = self.df.units[qual]
        cfg = self.df.cfg(unit.qual)
        summary = FnSummary()
        init: Dict[str, TagSet] = {
            p: frozenset({Tag("param", p, 0, i)}) for i, p in enumerate(unit.params)
        }
        pass_ = _TaintPass(self, unit, summary, collect, hits)
        forward_analyze(
            cfg,
            init,
            pass_.transfer,
            _join_states,
        )
        return summary


def _join_states(a: Dict[str, TagSet], b: Dict[str, TagSet]) -> Dict[str, TagSet]:
    if a == b:
        return a
    out = dict(a)
    for k, v in b.items():
        cur = out.get(k)
        out[k] = v if cur is None else (cur | v)
    return out


class _TaintPass:
    def __init__(
        self,
        engine: TaintEngine,
        unit: FnUnit,
        summary: FnSummary,
        collect: bool,
        hits: Optional[List[SinkHit]],
    ) -> None:
        self.te = engine
        self.df = engine.df
        self.unit = unit
        self.summary = summary
        self.collect = collect
        self.hits = hits
        self._seen_hits: Set[Tuple[int, int, str]] = set()

    # -- transfer ------------------------------------------------------------

    def transfer(self, atom: Atom, state: Dict[str, TagSet]) -> Dict[str, TagSet]:
        node = atom.node
        new = state
        if atom.role == "test":
            self.eval(node.test if hasattr(node, "test") else node, state)
            return new
        if atom.role == "iter" and isinstance(node, (ast.For, ast.AsyncFor)):
            tags = self.eval(node.iter, state)
            return self._bind_target(node.target, tags, new)
        if atom.role == "withitem" and isinstance(node, ast.withitem):
            tags = self.eval(node.context_expr, state)
            if node.optional_vars is not None:
                # file handles etc. do not carry path taint into content
                return self._bind_target(node.optional_vars, _EMPTY, new)
            return new
        if atom.role == "except":
            return new
        if atom.role == "return" and isinstance(node, ast.Return):
            if node.value is not None:
                tags = self.eval(node.value, state)
                self._note_return(tags)
            return new
        if isinstance(node, ast.Assign):
            tags = self.eval(node.value, state)
            for t in node.targets:
                new = self._bind_target(t, tags, new, state)
            return new
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            tags = self.eval(node.value, state)
            return self._bind_target(node.target, tags, new, state)
        if isinstance(node, ast.AugAssign):
            tags = self.eval(node.value, state)
            if isinstance(node.target, ast.Name):
                prev = state.get(node.target.id, _EMPTY)
                new = dict(new)
                new[node.target.id] = prev | tags
            return new
        if isinstance(node, ast.Expr):
            self.eval(node.value, state)
            return new
        if isinstance(node, (ast.Assert, ast.Raise)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self.eval(sub, state)
            return new
        if isinstance(node, ast.Delete):
            new = dict(new)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    new.pop(t.id, None)
            return new
        return new

    def _bind_target(
        self,
        target: ast.AST,
        tags: TagSet,
        new: Dict[str, TagSet],
        state: Optional[Dict[str, TagSet]] = None,
    ) -> Dict[str, TagSet]:
        if isinstance(target, ast.Name):
            out = dict(new)
            out[target.id] = tags
            return out
        if isinstance(target, (ast.Tuple, ast.List)):
            out = new
            for el in target.elts:
                out = self._bind_target(el, tags, out, state)
            return out
        if isinstance(target, ast.Starred):
            return self._bind_target(target.value, tags, new, state)
        if isinstance(target, ast.Subscript) and tags:
            # weak update: d[k] = tainted marks the container
            root = target.value
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name):
                out = dict(new)
                out[root.id] = out.get(root.id, _EMPTY) | tags
                return out
        return new

    def _note_return(self, tags: TagSet) -> None:
        for tag in tags:
            if tag.kind == "param":
                self.summary.param_to_ret.add(tag.index)
            elif tag.kind not in ("traced", "traced-param"):
                self.summary.ret_tags = self.summary.ret_tags | {tag}

    # -- expression evaluation ----------------------------------------------

    def eval(self, expr: ast.AST, state: Dict[str, TagSet]) -> TagSet:
        if isinstance(expr, ast.Name):
            got = _SOURCE_NAMES.get(expr.id)
            if got:
                return frozenset({Tag(got, expr.id, getattr(expr, "lineno", 0))})
            return state.get(expr.id, _EMPTY)
        if isinstance(expr, ast.Constant):
            return _EMPTY
        if isinstance(expr, ast.Attribute):
            name = dotted_name(expr)
            if name in _SOURCE_NAMES:
                return frozenset(
                    {Tag(_SOURCE_NAMES[name], name, getattr(expr, "lineno", 0))}
                )
            return self.eval(expr.value, state)
        if isinstance(expr, ast.Subscript):
            tags = self.eval(expr.value, state)
            if isinstance(expr.slice, ast.expr):
                tags = tags | self.eval(expr.slice, state)
            return tags
        if isinstance(expr, ast.Call):
            return self._eval_call(expr, state)
        if isinstance(expr, ast.BoolOp):
            out = _EMPTY
            for v in expr.values:
                out = out | self.eval(v, state)
            return out
        if isinstance(expr, ast.BinOp):
            return self.eval(expr.left, state) | self.eval(expr.right, state)
        if isinstance(expr, ast.UnaryOp):
            return self.eval(expr.operand, state)
        if isinstance(expr, ast.IfExp):
            self.eval(expr.test, state)
            return self.eval(expr.body, state) | self.eval(expr.orelse, state)
        if isinstance(expr, ast.Compare):
            self.eval(expr.left, state)
            for c in expr.comparators:
                self.eval(c, state)
            return _EMPTY  # booleans are clean
        if isinstance(expr, ast.JoinedStr):
            out = _EMPTY
            for v in expr.values:
                if isinstance(v, ast.FormattedValue):
                    out = out | self.eval(v.value, state)
            return out
        if isinstance(expr, ast.FormattedValue):
            return self.eval(expr.value, state)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = _EMPTY
            for el in expr.elts:
                out = out | self.eval(el, state)
            return out
        if isinstance(expr, ast.Dict):
            out = _EMPTY
            for k, v in zip(expr.keys, expr.values):
                if k is not None:
                    out = out | self.eval(k, state)
                out = out | self.eval(v, state)
            return out
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._eval_comp(expr, [expr.elt], state)
        if isinstance(expr, ast.DictComp):
            return self._eval_comp(expr, [expr.key, expr.value], state)
        if isinstance(expr, ast.NamedExpr):
            tags = self.eval(expr.value, state)
            if isinstance(expr.target, ast.Name):
                state[expr.target.id] = tags  # in-place: walrus binds here
            return tags
        if isinstance(expr, ast.Starred):
            return self.eval(expr.value, state)
        if isinstance(expr, ast.Lambda):
            return _EMPTY
        if isinstance(expr, ast.Await):
            return self.eval(expr.value, state)
        return _EMPTY

    def _eval_comp(self, comp: ast.AST, elts: List[ast.AST], state: Dict[str, TagSet]) -> TagSet:
        local = dict(state)
        for gen in comp.generators:
            tags = self.eval(gen.iter, local)
            local = self._bind_target(gen.target, tags, local)
            for cond in gen.ifs:
                self.eval(cond, local)
        out = _EMPTY
        for e in elts:
            out = out | self.eval(e, local)
        return out

    # -- calls: sources, sanitizers, sinks, summaries ------------------------

    def _eval_call(self, call: ast.Call, state: Dict[str, TagSet]) -> TagSet:
        name = dotted_name(call.func)
        leaf = name.rsplit(".", 1)[-1] if name else ""
        arg_tags = [self.eval(a, state) for a in call.args]
        kw_tags = [(kw.arg, self.eval(kw.value, state)) for kw in call.keywords]
        all_args: TagSet = _EMPTY
        for t in arg_tags:
            all_args = all_args | t
        for _k, t in kw_tags:
            all_args = all_args | t

        # sinks first: the sink fires on the PRE-call taint of its args
        self._check_sink(call, name, leaf, arg_tags, kw_tags)

        # sources
        src_kind = _SOURCE_LEAVES.get(leaf)
        if src_kind == "stdin" and name != "input":
            src_kind = None  # x.input(...) is not the builtin
        if src_kind is not None:
            return frozenset({Tag(src_kind, name or leaf, getattr(call, "lineno", 0))})
        if _HTTP_BODY_RE.search(name or ""):
            return frozenset({Tag("http-body", name, getattr(call, "lineno", 0))})

        # sanitizers
        if leaf in _COERCION_SANITIZERS:
            return _EMPTY
        if leaf in _SANITIZER_LEAVES or (
            "." in name and name.rsplit(".", 2)[-2] == _SANITIZER_MODULE
        ):
            return _EMPTY
        callee = self.df.resolve_call(self.unit, call)
        if self.df.is_sanitizer(callee):
            return _EMPTY

        # interprocedural: apply the callee's summary
        if callee is not None:
            return self._apply_summary(call, callee, arg_tags, kw_tags)

        # unresolved call: taint flows args -> result (str(x), x.strip(), json.loads)
        recv = _EMPTY
        if isinstance(call.func, ast.Attribute):
            recv = self.eval(call.func.value, state)
        return all_args | recv

    def _apply_summary(
        self,
        call: ast.Call,
        callee: str,
        arg_tags: List[TagSet],
        kw_tags: List[Tuple[Optional[str], TagSet]],
    ) -> TagSet:
        cunit = self.df.units[callee]
        summ = self.te.summaries.get(callee)
        if summ is None:
            # not yet analyzed this round: conservative args->result
            out = _EMPTY
            for t in arg_tags:
                out = out | t
            for _k, t in kw_tags:
                out = out | t
            return out
        offset = 0
        if cunit.cls is not None and cunit.params and cunit.params[0] in ("self", "cls"):
            if isinstance(call.func, ast.Attribute) or callee.endswith(".__init__"):
                offset = 1
        index_tags: Dict[int, TagSet] = {}
        for i, t in enumerate(arg_tags):
            index_tags[i + offset] = t
        for k, t in kw_tags:
            if k is None:
                continue
            if k in cunit.params:
                index_tags[cunit.params.index(k)] = t
        result: TagSet = frozenset(summ.ret_tags)
        for idx, tags in index_tags.items():
            if not tags:
                continue
            if idx in summ.param_sinks:
                self._record_hit(call, summ.param_sinks[idx], tags,
                                 f"via {callee.rsplit('.', 1)[-1]}()")
            if idx in summ.param_to_ret:
                result = result | tags
        return result

    def _check_sink(
        self,
        call: ast.Call,
        name: str,
        leaf: str,
        arg_tags: List[TagSet],
        kw_tags: List[Tuple[Optional[str], TagSet]],
    ) -> None:
        label = None
        if name in _SINKS_EXACT:
            label = _SINKS_EXACT[name][0]
        else:
            for prefix, lab in _SINK_PREFIXES:
                if name.startswith(prefix):
                    label = f"{lab} ({name})"
                    break
        if label is None and leaf in _SINK_CTOR_LEAVES:
            label = _SINK_CTOR_LEAVES[leaf]
        if label is None:
            return
        tainted: TagSet = _EMPTY
        for t in arg_tags:
            tainted = tainted | t
        for _k, t in kw_tags:
            tainted = tainted | t
        if tainted:
            self._record_hit(call, label, tainted, "")

    def _record_hit(self, call: ast.Call, sink: str, tags: TagSet, how: str) -> None:
        real = frozenset(t for t in tags if not t.is_param)
        line = getattr(call, "lineno", 0)
        col = getattr(call, "col_offset", 0)
        for tag in tags:
            if tag.kind == "param":
                prev = self.summary.param_sinks.get(tag.index)
                if prev is None:
                    self.summary.param_sinks[tag.index] = sink
        if not self.collect or not real or self.hits is None:
            return
        key = (line, col, sink)
        if key in self._seen_hits:
            return
        self._seen_hits.add(key)
        srcs = sorted({f"{t.kind}:{t.desc}" + (f"@{t.line}" if t.line else "") for t in real})
        self.hits.append(
            SinkHit(
                unit=self.unit.qual,
                sink=sink,
                tags=real,
                line=line,
                col=col,
                desc=(how + " " if how else "") + "sources: " + ", ".join(srcs),
            )
        )


# ---------------------------------------------------------------------------
# tracer-leak pass (OSL1602): traced values stored into outliving state
# ---------------------------------------------------------------------------

_TRACED_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.", "jax.random.", "jax.nn.")


class _TracerPass(_TaintPass):
    """Taint variant for jit-reachable functions: every parameter and
    every ``jnp.``/``lax.``-family result is a *traced* value; storing one
    into state that outlives the trace (``self.attr``, a module global, a
    ``nonlocal``) bakes a tracer into host state — it escapes the trace
    and either leaks (UnexpectedTracerError later) or goes silently
    stale."""

    def __init__(self, engine: TaintEngine, unit: FnUnit, hits: List[SinkHit],
                 globals_: Set[str]) -> None:
        super().__init__(engine, unit, FnSummary(), True, hits)
        self.globals_ = globals_
        self.declared: Set[str] = set()
        self._assigned: Set[str] = set(unit.params)
        for stmt in self.df._own_stmts(unit):
            for sub in self.df._walk_skip_defs(stmt):
                if isinstance(sub, (ast.Global, ast.Nonlocal)):
                    self.declared.update(sub.names)
                elif isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                                      ast.Import, ast.ImportFrom)):
                    for name, _node in atom_defs(Atom(sub)):
                        self._assigned.add(name)
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    for name, _node in atom_defs(Atom(sub, "iter")):
                        self._assigned.add(name)
                elif isinstance(sub, ast.withitem):
                    for name, _node in atom_defs(Atom(sub, "withitem")):
                        self._assigned.add(name)

    def _outlives(self, target: ast.AST) -> Optional[str]:
        """Non-None (a label) when a store to ``target`` outlives the
        trace frame."""
        root = target
        while isinstance(root, (ast.Attribute, ast.Subscript)):
            root = root.value
        if not isinstance(root, ast.Name):
            return None
        if root.id == "self" and self.unit.cls is not None and root is not target:
            return f"instance state `{_src_of(target)}`"
        if root.id in self.declared:
            return f"nonlocal/global `{root.id}`"
        if root.id in self.globals_ and root.id not in self._assigned:
            # a module-level name never rebound locally: stores/mutations
            # through it reach module state (X[k] = v, X.append(v))
            return f"module state `{_src_of(target)}`"
        return None

    def _record_leak(self, node: ast.AST, label: str, tags: TagSet) -> None:
        if not tags or self.hits is None:
            return
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        key = (line, col, label)
        if key in self._seen_hits:
            return
        self._seen_hits.add(key)
        srcs = sorted({t.desc or t.kind for t in tags})
        self.hits.append(
            SinkHit(unit=self.unit.qual, sink=label, tags=tags, line=line,
                    col=col, desc="traced value from " + ", ".join(srcs))
        )

    def transfer(self, atom: Atom, state: Dict[str, TagSet]) -> Dict[str, TagSet]:
        node = atom.node
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)) and getattr(
            node, "value", None
        ) is not None:
            tags = self.eval(node.value, state)
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                label = self._outlives(t)
                if label:
                    self._record_leak(node, label, tags)
        return super().transfer(atom, state)

    def _eval_call(self, call: ast.Call, state: Dict[str, TagSet]) -> TagSet:
        from .core import MUTATOR_METHODS

        name = dotted_name(call.func)
        arg_tags = [self.eval(a, state) for a in call.args]
        kw_tags = [self.eval(kw.value, state) for kw in call.keywords]
        all_args: TagSet = _EMPTY
        for t in arg_tags + kw_tags:
            all_args = all_args | t
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in MUTATOR_METHODS
        ):
            label = self._outlives(call.func.value)
            if label:
                self._record_leak(call, f"{label} (.{call.func.attr}())", all_args)
        if name.startswith(_TRACED_PREFIXES):
            return frozenset({Tag("traced", name, getattr(call, "lineno", 0))})
        leaf = name.rsplit(".", 1)[-1] if name else ""
        if leaf in _COERCION_SANITIZERS:
            return _EMPTY
        recv = _EMPTY
        if isinstance(call.func, ast.Attribute):
            recv = self.eval(call.func.value, state)
        return all_args | recv


# ---------------------------------------------------------------------------
# shared per-run instances
# ---------------------------------------------------------------------------


def get_engine(project: ProjectContext) -> DataflowEngine:
    """One DataflowEngine per ProjectContext (rules in the same run share
    unit tables, CFGs, effect fixpoints and taint summaries)."""
    eng = getattr(project, "_dataflow_engine", None)
    if eng is None:
        eng = DataflowEngine(project)
        project._dataflow_engine = eng  # type: ignore[attr-defined]
    return eng


def get_taint_hits(project: ProjectContext) -> List[SinkHit]:
    """Memoized interprocedural taint run over the whole project."""
    hits = getattr(project, "_taint_hits", None)
    if hits is None:
        hits = TaintEngine(get_engine(project)).run()
        project._taint_hits = hits  # type: ignore[attr-defined]
    return hits


def get_tracer_leaks(project: ProjectContext) -> List[SinkHit]:
    """Memoized tracer-leak sweep over every jit-reachable unit."""
    leaks = getattr(project, "_tracer_leaks", None)
    if leaks is None:
        df = get_engine(project)
        te = TaintEngine(df)
        leaks = []
        for qual in sorted(df.jit_reachable()):
            unit = df.units[qual]
            if not isinstance(unit.node, _FuncNode):
                continue
            pass_ = _TracerPass(te, unit, leaks, df._module_globals.get(unit.module, set()))
            init = {
                p: frozenset({Tag("traced-param", p, 0, i)})
                for i, p in enumerate(unit.params)
                if not (i == 0 and p in ("self", "cls"))
            }
            forward_analyze(df.cfg(qual), init, pass_.transfer, _join_states)
        project._tracer_leaks = leaks  # type: ignore[attr-defined]
    return leaks
