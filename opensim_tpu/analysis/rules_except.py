"""exception-swallow (OSL501): broad handlers that hide failures.

A ``except Exception`` (or bare ``except``) whose body neither re-raises
nor logs leaves no trace of the failure — the simulator then reports a
result computed from partial state, which is worse than crashing. The rule
accepts any of:

- a ``raise`` anywhere in the handler body (re-raise or translation);
- a structured log: a call to ``logging``/``warnings`` machinery or to a
  logger method (``.warning()``, ``.error()``, ``.exception()``, ...);

Narrowed handlers (``except ValueError: pass``) are not flagged — naming
the exception is the other sanctioned fix.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register

_BROAD = {"Exception", "BaseException"}
_LOG_METHODS = {
    "warning",
    "warn",
    "error",
    "exception",
    "critical",
    "info",
    "debug",
    "log",
}
_LOG_PREFIXES = ("logging.", "warnings.", "log.", "logger.", "trace.")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    return dotted_name(handler.type) in _BROAD


def _handled(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name.startswith(_LOG_PREFIXES):
                return True
            if isinstance(node.func, ast.Attribute) and node.func.attr in _LOG_METHODS:
                return True
    return False


@register
class ExceptionSwallowRule(Rule):
    name = "exception-swallow"
    code = "OSL501"
    description = "broad except without re-raise or structured log"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) and not _handled(node):
                caught = "bare except" if node.type is None else f"except {dotted_name(node.type)}"
                yield self.finding(
                    ctx,
                    node,
                    f"`{caught}` swallows the failure (no raise, no log); "
                    "narrow the exception or log via utils/trace's logger",
                )
