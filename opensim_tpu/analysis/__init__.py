"""opensim-lint: AST-level correctness analyzer for this repo.

Usage:
    python -m opensim_tpu.analysis [paths...] [--json] [--rules a,b]
    make lint

Rules (short name = suppression id; see docs/static-analysis.md):
    OSL101 jit-boundary       host-side work inside jit-traced code
    OSL201 dtype-drift        encoder arrays off the Go dtype policy
    OSL301 determinism        unordered iteration on ordered streams
    OSL401 cache-mutation     mutation of fingerprinted objects
    OSL501 exception-swallow  broad except without raise/log
    OSL601 unbounded-retry    retry loop without a bound or backoff
    OSL701 deadline-span      Deadline phase boundary without a trace span
    OSL801 unsupervised-watch-loop  `while True` watch/reconnect loop
                              bypassing resilience.retry
    OSL901 reason-literal     inline unschedulable-reason string bypassing
                              the reason-code registry (engine/reasons.py)
    OSL1001 admission-lock-io blocking I/O while holding the admission/
                              dispatch lock (server/admission.py)
    OSL1101 metric-registry   metric-family registration outside
                              obs/metrics.py's FAMILIES registry
    OSL1201 unguarded-shared-state  `# guarded-by:` attribute touched
                              outside its lock's critical sections
    OSL1202 lock-order-inversion    cycle in the whole-program static
                              lock-acquisition graph
    OSL1203 blocking-call-under-lock  OSL1001 generalized to every
                              critical section in the repo
    OSL1204 thread-unsafe-contextvar  ambient Deadline/Trace read in a
                              thread entry without explicit handoff
    OSL1301 journal-discipline  unchecksummed/foreign writes on journal
                              paths (server/journal.py owns the format)
    OSL1401 env-registry      raw os.environ read of an OPENSIM_* knob
                              outside utils/envknobs.py
    OSL1501 campaign-step-registry  campaign step-type dispatch outside
                              planner/campaign.py's STEP_TYPES registry
    OSL1601 jit-impurity      side effect (I/O, clock/RNG, host sync,
                              state write) in a function transitively
                              reachable from a jit-traced region
    OSL1602 tracer-leak       traced value stored into state that
                              outlives the trace
    OSL1603 input-taint       untrusted input (HTTP/CLI/YAML) reaches a
                              filesystem/subprocess sink without a
                              registered @sanitizer validator
    OSL1604 abi-parity        C++/Python ABI declarations drifted
                              (ScanArgs layout, abi version, serial wire)
    OSL1701 shm-discipline    shared-memory segment create/attach/unlink
                              outside server/fleet.py (the fleet's
                              /dev/shm hygiene owner)
    OSL1801 array-off-policy  array built without a policy dtype reaches
                              a contracted arena field or kernel boundary
    OSL1802 silent-upcast     dtype promotion on a path reaching an arena
                              write or kernel boundary (interprocedural)
    OSL1803 shape-contract    rank/axis-order mismatch vs the declared
                              (dtype, axes) contract
    OSL1804 contract-abi-parity  contract registry / dtypes policy /
                              native ScanArgs widths out of three-way sync

The OSL12xx family is whole-program (symbol table + call graph + lock
graph across all linted files); its runtime counterpart is the lock-order
sanitizer ``analysis/lockwatch.py`` (`make tsan`, ``OPENSIM_LOCKWATCH=1``).
The OSL16xx family runs on the interprocedural dataflow engine
(``analysis/dataflow.py``: per-function CFGs + reaching definitions,
call-graph effect fixpoint, forward taint lattice) and the cross-language
ABI parser (``analysis/abi.py``); see docs/static-analysis.md. The
OSL18xx family is the array-contract engine (``analysis/arrays.py``): an
abstract interpreter computing a (dtype, rank, symbolic-axis) lattice
over the same CFGs, checked against the contract registry in
``encoding/dtypes.py`` and the C++ ``ScanArgs`` widths.
"""

from .core import (  # noqa: F401
    RULES,
    FileContext,
    Finding,
    ProjectContext,
    Rule,
    lint_paths,
    lint_source,
    register,
    render_human,
    render_json,
    render_sarif,
)

# importing the rule modules registers them
from . import (  # noqa: F401,E402
    rules_admission,
    rules_arrays,
    rules_cache,
    rules_campaign,
    rules_concurrency,
    rules_dataflow,
    rules_determinism,
    rules_dtype,
    rules_env,
    rules_except,
    rules_fleet,
    rules_jit,
    rules_journal,
    rules_metrics,
    rules_obs,
    rules_reasons,
    rules_retry,
    rules_watch,
)
