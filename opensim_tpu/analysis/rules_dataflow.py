"""OSL16xx — the interprocedural dataflow rule pack.

Built on :mod:`analysis.dataflow` (per-function CFGs, effect inference,
taint lattice, jit-region tracking) and :mod:`analysis.abi` (cross-
language struct parsing). Four rules:

- **OSL1601 jit-impurity** — OSL101 generalized from one syntactic file
  to call-graph depth: any function transitively reachable from a
  jit-traced region (``@jax.jit``-family decorators, function refs passed
  to ``lax.scan``/``vmap``/``pallas_call``, ``# opensim-lint: jit-region``
  markers) with an inferred side effect — I/O, clock/RNG reads,
  host-device syncs, module/instance state writes. All of these execute
  once at trace time and go silently stale in the compiled program.

- **OSL1602 tracer-leak** — a traced value (function parameter or
  ``jnp.``/``lax.``-family result) stored into state that outlives the
  trace (``self.attr``, module globals, nonlocals): the tracer escapes
  and either raises ``UnexpectedTracerError`` much later or bakes stale
  data into host state.

- **OSL1603 untrusted-input-taint** — HTTP query/body params, CLI args,
  YAML documents, and stdin flowing into ``open()``/path joins/
  ``subprocess`` without passing a **registered validator** (a function
  carrying a ``@sanitizer`` decorator — see ``utils/validate.py``).
  Flow-sensitive per function, interprocedural through call-graph
  summaries.

- **OSL1604 abi-parity** — parses the ``ScanArgs`` struct declaration in
  ``native/scan_engine.cc`` and the packing order in
  ``native/__init__.py`` and gates field count, order, and width
  equality; also cross-checks ``opensim_abi_version()`` against
  ``ABI_VERSION`` and the serial wire magic/version between
  ``native/serial.py`` and ``serial_engine.cc``. The abi-v4 "keep order
  in sync" comment is now a build-failing check.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterable

from . import abi
from .core import FileContext, Finding, ProjectContext, Rule, register
from .dataflow import get_engine, get_taint_hits, get_tracer_leaks


@dataclass
class _Site:
    lineno: int
    col_offset: int


_EFFECT_WHY = {
    "io": "I/O executes once at trace time and never again in the compiled program",
    "clock": "the clock is read once at trace time (stale constant baked in)",
    "rng": "host RNG draws once at trace time (same 'random' value every step)",
    "host-sync": "forces a host-device sync / fails outright on tracers",
    "state-write": "host state mutates at trace time only, then silently never again",
}


@register
class JitImpurityRule(Rule):
    name = "jit-impurity"
    code = "OSL1601"
    description = (
        "side effect in a function transitively reachable from a jit-traced region"
    )
    project_rule = True
    exclude_paths = ("tests/",)

    def project_check(self, project: ProjectContext) -> Iterable[Finding]:
        df = get_engine(project)
        reach = df.jit_reachable()
        roots = df.jit_roots()
        for qual in sorted(reach):
            unit = df.units[qual]
            root, chain = reach[qual]
            for eff in df.direct_effects(qual):
                via = " -> ".join(q.rsplit(".", 1)[-1] for q in chain + (qual,))
                how = roots.get(root, "jit root")
                short_root = root.rsplit(".", 1)[-1]
                where = (
                    f"jit-traced `{short_root}` ({how})"
                    if qual == root
                    else f"reachable from jit-traced `{short_root}` ({how}) via {via}"
                )
                yield self.finding(
                    unit.ctx.path,
                    _Site(eff.line, eff.col),
                    f"`{eff.desc}` ({eff.kind}) in `{qual.rsplit('.', 1)[-1]}`, "
                    f"{where}: {_EFFECT_WHY[eff.kind]}",
                )


@register
class TracerLeakRule(Rule):
    name = "tracer-leak"
    code = "OSL1602"
    description = "traced value stored into state that outlives the trace"
    project_rule = True
    exclude_paths = ("tests/",)

    def project_check(self, project: ProjectContext) -> Iterable[Finding]:
        df = get_engine(project)
        for hit in get_tracer_leaks(project):
            unit = df.units[hit.unit]
            yield self.finding(
                unit.ctx.path,
                _Site(hit.line, hit.col),
                f"{hit.desc} stored into {hit.sink} inside jit-reachable "
                f"`{hit.unit.rsplit('.', 1)[-1]}`: the tracer outlives the "
                "trace (UnexpectedTracerError later, or silently stale host state)",
            )


@register
class InputTaintRule(Rule):
    name = "input-taint"
    code = "OSL1603"
    description = (
        "untrusted input reaches a filesystem/subprocess sink without a "
        "registered validator"
    )
    project_rule = True
    exclude_paths = ("tests/",)

    def project_check(self, project: ProjectContext) -> Iterable[Finding]:
        df = get_engine(project)
        for hit in get_taint_hits(project):
            unit = df.units[hit.unit]
            yield self.finding(
                unit.ctx.path,
                _Site(hit.line, hit.col),
                f"untrusted input reaches {hit.sink} in "
                f"`{hit.unit.rsplit('.', 1)[-1]}` ({hit.desc}); route it "
                "through a registered validator (@sanitizer, utils/validate.py)",
            )


@register
class AbiParityRule(Rule):
    name = "abi-parity"
    code = "OSL1604"
    description = (
        "C++/Python ABI declarations drifted (ScanArgs layout, abi version, "
        "serial wire tag)"
    )
    project_rule = True

    def project_check(self, project: ProjectContext) -> Iterable[Finding]:
        for ctx in project.contexts:
            p = ctx.path.replace(os.sep, "/")
            if p.endswith("native/__init__.py"):
                yield from self._check_scan(ctx)
            elif p.endswith("native/serial.py"):
                yield from self._check_serial(ctx)

    # -- ScanArgs struct + abi version ---------------------------------------

    def _check_scan(self, ctx: FileContext) -> Iterable[Finding]:
        py_fields, py_problems = abi.parse_py_layout(ctx.tree)
        if not py_fields and not py_problems:
            return
        # skip ONLY the no-mirror case (a native/__init__.py without a
        # ScanArgs class); any other parse problem — a packing list that
        # stopped being a module-level list literal, an unknown ctype —
        # must FAIL the gate, not silently disable it
        if (
            py_problems
            and not py_fields
            and py_problems[0].startswith("class ScanArgs not found")
        ):
            return
        anchor = _Site(self._class_line(ctx, "ScanArgs"), 0)
        cc_path = os.path.join(os.path.dirname(ctx.path), "scan_engine.cc")
        if not os.path.isfile(cc_path):
            yield self.finding(
                ctx.path, anchor,
                "cannot verify ScanArgs ABI: scan_engine.cc not found next to "
                "the ctypes mirror",
            )
            return
        with open(cc_path, "r", encoding="utf-8") as fh:
            cc_text = fh.read()
        cc_fields, cc_problems = abi.parse_cc_struct(cc_text)
        for msg in py_problems + cc_problems:
            yield self.finding(ctx.path, anchor, f"ABI parse problem: {msg}")
        for msg in abi.compare_layouts(cc_fields, py_fields):
            yield self.finding(
                ctx.path, anchor,
                f"ScanArgs ABI drift between scan_engine.cc and the ctypes "
                f"mirror: {msg}",
            )
        v_cc = abi.parse_cc_abi_version(cc_text)
        v_py = abi.parse_py_abi_version(ctx.tree)
        if v_py is None:
            yield self.finding(
                ctx.path, anchor,
                "ABI_VERSION constant missing from native/__init__.py (the "
                "machine-readable anchor for opensim_abi_version())",
            )
        elif v_cc is not None and v_cc != v_py:
            yield self.finding(
                ctx.path, anchor,
                f"ABI version drift: opensim_abi_version() returns {v_cc} but "
                f"native/__init__.py declares ABI_VERSION = {v_py}",
            )

    # -- serial wire tag -----------------------------------------------------

    def _check_serial(self, ctx: FileContext) -> Iterable[Finding]:
        magic_py, ver_py = abi.parse_py_serial_wire(ctx.tree)
        anchor = _Site(1, 0)
        cc_path = os.path.join(os.path.dirname(ctx.path), "serial_engine.cc")
        if not os.path.isfile(cc_path):
            return
        if magic_py is None or ver_py is None:
            yield self.finding(
                ctx.path, anchor,
                "WIRE_MAGIC/WIRE_VERSION constants missing from "
                "native/serial.py (the machine-readable anchors for the "
                "serial_engine.cc header guards)",
            )
            return
        with open(cc_path, "r", encoding="utf-8") as fh:
            magic_cc, ver_cc = abi.parse_cc_serial_wire(fh.read())
        if magic_cc is not None and magic_cc != magic_py:
            yield self.finding(
                ctx.path, anchor,
                f"serial wire magic drift: serial_engine.cc expects "
                f"{magic_cc:#x}, serial.py writes {magic_py:#x}",
            )
        if ver_cc is not None and ver_cc != ver_py:
            yield self.finding(
                ctx.path, anchor,
                f"serial wire version drift: serial_engine.cc expects "
                f"{ver_cc}, serial.py writes {ver_py}",
            )

    @staticmethod
    def _class_line(ctx: FileContext, name: str) -> int:
        import ast

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                return node.lineno
        return 1
