"""campaign-step-registry (OSL1501): campaign step types live in the
central ``STEP_TYPES`` registry (``planner/campaign.py``).

The campaign DSL (ISSUE 13) dispatches lifecycle steps — drain waves,
reclaim storms, journal replays — through one registered table, the same
single-place-of-declaration discipline as the metric-family registry
(OSL1101) and the journal format ownership (OSL1301). A step type handled
by ad-hoc ``if step == "drain-wave"`` dispatch in some other module ships
behavior the registry's reviewer never sees: it bypasses the typed
``parse``/``run`` contract, the strict-field validation, and the
``docs/campaigns.md`` step catalog generated from the registry.

The rule flags, in any module other than ``planner/campaign.py``:

- calls to ``register_step(...)`` — step registration happens ONLY in the
  registry module, where every step's parse/run contract is reviewed
  together;
- equality/membership comparisons against the campaign-specific step-type
  literals (``"drain-wave"``, ``"reclaim-storm"``, ``"add-nodes"``,
  ``"scale-down-check"``, ``"from-journal"``) — the ad-hoc dispatch
  pattern. (The short generic names ``deploy``/``scale``/``defrag`` are
  legitimately compared elsewhere — REST request kinds, CLI subcommands —
  so only the unambiguous hyphenated types trigger; their dispatch is
  still registry-owned because only ``campaign.py`` defines their
  handlers.)

Fix by declaring the step in ``STEP_TYPES`` via ``@register_step`` and
routing behavior through the step's ``run``; see docs/static-analysis.md.
``tests/test_campaign.py`` gates :data:`DISPATCH_LITERALS` against the
live registry so the rule cannot drift from the DSL.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register

#: campaign-specific step-type literals whose comparison IS step dispatch
#: (kept a subset of planner.campaign.STEP_TYPES by the sync test)
DISPATCH_LITERALS = frozenset(
    {"drain-wave", "reclaim-storm", "add-nodes", "scale-down-check", "from-journal"}
)


def _literal_strings(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                yield elt.value


@register
class CampaignStepRegistryRule(Rule):
    name = "campaign-step-registry"
    code = "OSL1501"
    description = "campaign step-type dispatch outside planner/campaign.py's STEP_TYPES registry"
    # the registry module necessarily compares and registers step types;
    # tests exercise arbitrary specs on purpose
    exclude_paths = ("planner/campaign.py", "tests/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or (
                    node.func.attr if isinstance(node.func, ast.Attribute) else ""
                )
                if name.rsplit(".", 1)[-1] == "register_step":
                    yield self.finding(
                        ctx,
                        node,
                        "register_step(...) outside planner/campaign.py: campaign "
                        "step types are declared ONLY in the central STEP_TYPES "
                        "registry so every step's parse/run contract is reviewed "
                        "in one place",
                    )
            elif isinstance(node, ast.Compare):
                if not any(isinstance(op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn)) for op in node.ops):
                    continue
                hits = set()
                for side in [node.left] + list(node.comparators):
                    hits.update(s for s in _literal_strings(side) if s in DISPATCH_LITERALS)
                for lit in sorted(hits):
                    yield self.finding(
                        ctx,
                        node,
                        f"ad-hoc dispatch on campaign step type {lit!r}: route through "
                        "planner/campaign.py's STEP_TYPES registry (the step's "
                        "parse/run contract) instead of string comparison",
                    )
