"""Stdlib fallback for the typed-core gate.

The image does not ship mypy; ``make mypy`` degrades to this AST check so
the signature contract is still enforced in CI: every function in the
strict modules must have a complete signature (all parameters + return
annotated), and public signatures must not carry ``type: ignore``.
When mypy IS available it runs instead, with the stricter per-module
settings in pyproject.toml's ``[tool.mypy]``.
"""

from __future__ import annotations

import ast
from typing import List

#: Modules under [[tool.mypy.overrides]] strict settings in pyproject.toml.
STRICT_MODULES = (
    "opensim_tpu/engine/prepcache.py",
    "opensim_tpu/encoding/state.py",
    "opensim_tpu/encoding/dtypes.py",
    "opensim_tpu/models/quantity.py",
)


def check_typed_core(root: str = ".") -> List[str]:
    """Return human-readable problems ([] = clean)."""
    import os

    problems: List[str] = []
    for rel in STRICT_MODULES:
        path = os.path.join(root, rel)
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source)
        lines = source.splitlines()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            missing: List[str] = []
            a = node.args
            for arg in a.posonlyargs + a.args + a.kwonlyargs:
                if arg.annotation is None and arg.arg not in ("self", "cls"):
                    missing.append(arg.arg)
            if a.vararg is not None and a.vararg.annotation is None:
                missing.append("*" + a.vararg.arg)
            if a.kwarg is not None and a.kwarg.annotation is None:
                missing.append("**" + a.kwarg.arg)
            if node.returns is None:
                missing.append("return")
            if missing:
                problems.append(
                    f"{rel}:{node.lineno}: `{node.name}` incomplete signature "
                    f"(missing: {', '.join(missing)})"
                )
            # the signature may span several lines: check every line from
            # the `def` through the one before the first body statement —
            # and always at least the `def` line itself (one-line defs)
            sig_end = node.body[0].lineno - 1 if node.body else node.lineno
            sig_end = max(sig_end, node.lineno)
            for ln in range(node.lineno, min(sig_end, len(lines)) + 1):
                if "type: ignore" in lines[ln - 1]:
                    problems.append(
                        f"{rel}:{ln}: `{node.name}` carries `type: ignore` "
                        "on a public signature"
                    )
    return problems
