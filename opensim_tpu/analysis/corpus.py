"""Detector-awake lint corpus — proof every registered rule still fires.

A detector that silently stops firing is worse than no detector: the
perf-guard and tsan gates already self-check their detectors, and this
module extends the pattern to every OSL rule. ``tests/lint_corpus/``
holds, per rule:

- ``<CODE>_fire.py`` — a minimal fixture the rule MUST fire on;
- ``<CODE>_clean.py`` — the paired clean variant it MUST stay quiet on;
- or ``<CODE>_fire/`` / ``<CODE>_clean/`` directories for rules that need
  more than one file (OSL1604 ships a mutated ``native/`` tree).

Because many rules are path-scoped (``paths = ("engine/", ...)``), a
fixture's FIRST line may declare the virtual path it should be linted
under::

    # lint-corpus-path: opensim_tpu/engine/fixture.py

:func:`check_corpus` runs each fixture with ONLY its rule selected and
returns a list of problems (empty == every detector awake):

- a registered rule with no fire fixture (new rule, no corpus entry);
- a fire fixture that does not fire, or a clean fixture that does;
- a fixture naming an unregistered rule code (stale after rule removal);
- a rule with no clean fixture (nothing pins the rule's precision).

Wired into ``make lint`` (``--corpus tests/lint_corpus``) and the tier-1
suite (``tests/test_lint_corpus.py``).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from .core import RULES, FileContext, _make_context, _run

__all__ = ["check_corpus", "corpus_inventory", "run_fixture"]

_PATH_RE = re.compile(r"#\s*lint-corpus-path:\s*(\S+)")
_NAME_RE = re.compile(r"^(OSL\d+)_(fire|clean)(?:_[A-Za-z0-9_]+)?(?:\.py)?$")


def _virtual_path(source: str, default: str) -> str:
    first = source.split("\n", 1)[0]
    m = _PATH_RE.search(first)
    return m.group(1) if m else default


def run_fixture(path: str, rule_code: str) -> Tuple[List[str], Optional[str]]:
    """Lint one fixture (file or directory) with only ``rule_code``
    selected. Returns (codes of findings, error string or None)."""
    rule = next((r for r in RULES.values() if r.code == rule_code), None)
    if rule is None:
        return [], f"unknown rule code {rule_code}"
    contexts: List[FileContext] = []
    errors: List[str] = []
    files: List[str] = []
    if os.path.isdir(path):
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(dirnames)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    else:
        files.append(path)
    for fpath in files:
        with open(fpath, "r", encoding="utf-8") as fh:
            source = fh.read()
        ctx, err = _make_context(source, _virtual_path(source, fpath))
        if err is not None:
            errors.append(f"{fpath}: does not parse: {err.message}")
        elif ctx is not None:
            contexts.append(ctx)
    if errors:
        return [], "; ".join(errors)
    findings = _run(contexts, [], [rule.name])
    return [f.code for f in findings], None


def corpus_inventory(corpus_dir: str) -> Dict[str, Dict[str, List[str]]]:
    """{rule code: {"fire": [paths], "clean": [paths]}} from the corpus
    directory layout (files and fixture directories both count)."""
    inv: Dict[str, Dict[str, List[str]]] = {}
    for name in sorted(os.listdir(corpus_dir)):
        full = os.path.join(corpus_dir, name)
        if name.startswith((".", "_")) or name == "README.md":
            continue
        m = _NAME_RE.match(name)
        if m is None:
            if name.endswith(".py") or os.path.isdir(full):
                inv.setdefault("<unparsable>", {}).setdefault("fire", []).append(full)
            continue
        code, kind = m.group(1), m.group(2)
        inv.setdefault(code, {}).setdefault(kind, []).append(full)
    return inv


def check_corpus(corpus_dir: str) -> List[str]:
    """Run the full corpus gate; returns problems (empty == pass)."""
    problems: List[str] = []
    if not os.path.isdir(corpus_dir):
        return [f"corpus directory {corpus_dir} does not exist"]
    inv = corpus_inventory(corpus_dir)
    for full in inv.pop("<unparsable>", {}).get("fire", []):
        problems.append(
            f"{full}: fixture name must look like OSL123_fire[.py] / "
            "OSL123_clean[.py]"
        )
    registered = {r.code for r in RULES.values()}
    for code in sorted(registered):
        entry = inv.get(code, {})
        if not entry.get("fire"):
            problems.append(f"{code}: no firing fixture in {corpus_dir} — add "
                            f"{code}_fire.py so the detector stays provably awake")
        if not entry.get("clean"):
            problems.append(f"{code}: no clean fixture in {corpus_dir} — add "
                            f"{code}_clean.py pinning what the rule must NOT flag")
    for code in sorted(inv):
        if code not in registered:
            problems.append(
                f"{code}: corpus fixtures exist but no such rule is registered "
                "(stale fixture after a rule removal?)"
            )
            continue
        for kind in ("fire", "clean"):
            for path in inv[code].get(kind, []):
                codes, err = run_fixture(path, code)
                if err is not None:
                    problems.append(f"{path}: {err}")
                    continue
                fired = code in codes
                stray = sorted({c for c in codes if c not in (code, "OSL000")})
                if stray:
                    problems.append(
                        f"{path}: unexpected findings {stray} from a "
                        f"single-rule run of {code}"
                    )
                if kind == "fire" and not fired:
                    problems.append(
                        f"{path}: detector asleep — {code} did not fire on its "
                        "fire fixture"
                    )
                elif kind == "clean" and fired:
                    problems.append(
                        f"{path}: precision regression — {code} fired on its "
                        "clean fixture"
                    )
    return problems
