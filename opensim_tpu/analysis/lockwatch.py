"""lockwatch — runtime lock-order sanitizer for the threaded serving core.

The dynamic half of the OSL12xx concurrency family (the static half is
``analysis/rules_concurrency.py``): a lockdep-style instrumented lock
wrapper that records, per thread, the stack of currently-held locks and
folds every observed acquisition order into one process-global order
graph. The moment two locks are ever taken in both orders — even on two
different *runs through the code*, never mind an actual interleaving —
the cycle is reported with both acquisition stacks. This is how the Go
reference gets its guarantees from ``-race`` + deadlock-free informer
discipline without ever deadlocking in CI: the *order violation* is
caught deterministically, the deadlock itself would need scheduler luck.

Also measured: per-acquisition **hold time**. A critical section that
holds any lock longer than ``OPENSIM_LOCKWATCH_HOLD_MS`` (default 500)
is recorded as an outlier with its release stack — the convoy-maker
OSL1001/OSL1203 hunt statically, caught at runtime.

Usage:

- ``make tsan`` (tools/tsan.py): installs the wrapper, runs the threaded
  test modules under it, fails on any inversion or hold-time outlier,
  and proves the detector works via a seeded A→B/B→A self-test.
- ``OPENSIM_LOCKWATCH=1 python ...`` + :func:`install` early in startup:
  every ``threading.Lock()`` / ``threading.RLock()`` (and therefore
  ``Condition``/``Event`` internals) created *afterwards from repo code*
  is instrumented. Locks created from stdlib/third-party frames are left
  raw, so the graph stays signal.

Design notes:

- Lock **identity is the creation site** (``file:line``), not the object:
  every ``Timeline._lock`` instance shares one graph node, exactly like
  lockdep's lock-class keying. Same-site pairs (two cache entries' locks)
  are not ordered against each other — document hierarchies separately.
- The bookkeeping mutex is a raw ``_thread`` lock and is strictly
  leaf-level (never held while taking a user lock), so the sanitizer
  cannot deadlock the program it watches.
- ``Condition.wait`` support: the wrapper implements the
  ``_release_save``/``_acquire_restore``/``_is_owned`` protocol, so a
  wait correctly pops the lock from the held stack (a parked consumer is
  NOT holding its lock) and hold time is charged per ownership segment,
  not across the wait.
"""

from __future__ import annotations

import _thread
import linecache
import logging
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

from ..utils import envknobs

log = logging.getLogger("opensim_tpu.analysis")

__all__ = ["LockWatch", "TracedLock", "enabled", "install", "uninstall", "current"]


def enabled() -> bool:
    """``OPENSIM_LOCKWATCH=1`` switches the sanitizer on (tools/tsan.py
    sets it; production serving never pays the bookkeeping)."""
    return envknobs.raw("OPENSIM_LOCKWATCH").strip().lower() in ("1", "on", "true")


def hold_threshold_ms() -> float:
    """``OPENSIM_LOCKWATCH_HOLD_MS`` (default 500): ownership segments
    longer than this are reported as hold-time outliers. A typo degrades
    to the default with a warning (the env-knob contract)."""
    raw = envknobs.raw("OPENSIM_LOCKWATCH_HOLD_MS")
    if raw:
        try:
            return max(1.0, float(raw))
        except ValueError:
            log.warning("ignoring unparseable OPENSIM_LOCKWATCH_HOLD_MS=%r", raw)
    return 500.0


def hold_exempt() -> Tuple[str, ...]:
    """``OPENSIM_LOCKWATCH_HOLD_EXEMPT``: comma-separated creation-site
    substrings whose holds are tracked but never *outliers* (an ad-hoc
    escape hatch for local runs; empty by default so a new convoy-maker
    anywhere fails ``make tsan``). The durable mechanism is per-lock: a
    trailing ``# lockwatch: hold-exempt`` comment on the creating source
    line, justification riding the same line, mirroring the opensim-lint
    suppression convention — the by-design long holders (REST
    single-flight/probe locks, prep-cache per-entry lock, watch flush
    lock, all of which span engine work whose latency is gated by
    perf-smoke/loadgen-smoke instead) are marked that way. Inversions
    are NEVER exempt either way."""
    raw = envknobs.raw("OPENSIM_LOCKWATCH_HOLD_EXEMPT")
    return tuple(s.strip() for s in raw.split(",") if s.strip())


def _stack(limit: int = 14) -> str:
    frames = traceback.extract_stack()
    keep = [
        f"{os.path.basename(fr.filename)}:{fr.lineno} in {fr.name}"
        for fr in frames
        if "lockwatch" not in fr.filename
    ]
    return " <- ".join(reversed(keep[-limit:]))


class LockWatch:
    """The global order graph + per-thread held stacks. One instance is
    process-global under :func:`install`; tests build private instances
    and wrap locks explicitly with :meth:`wrap`."""

    def __init__(
        self,
        hold_ms: Optional[float] = None,
        hold_exempt_sites: Optional[Tuple[str, ...]] = None,
    ) -> None:
        self._mu = _thread.allocate_lock()  # leaf-only bookkeeping lock
        self._tls = threading.local()
        self.hold_ms = hold_threshold_ms() if hold_ms is None else float(hold_ms)
        self.hold_exempt_sites = (
            hold_exempt() if hold_exempt_sites is None else hold_exempt_sites
        )
        self.locks_created = 0
        self.acquisitions = 0
        # id(lock) -> (owner thread's counts dict, held-stack entry): lets a
        # cross-thread release (legal on a plain Lock — handoff signaling)
        # find and close the acquiring thread's entry instead of leaving it
        # stale on that thread's stack manufacturing false order edges
        self._live: Dict[int, Tuple[dict, list]] = {}
        # (src_name, dst_name) -> {"count", "stack"} — first observed stack
        self.edges: Dict[Tuple[str, str], dict] = {}
        self.adj: Dict[str, set] = {}
        self.inversions: List[dict] = []
        self.hold_outliers: List[dict] = []
        self.max_hold_ms: Dict[str, float] = {}
        self._seen_cycles: set = set()

    # -- per-thread state ----------------------------------------------------

    def _stackframe(self) -> List[list]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _counts(self) -> Dict[int, int]:
        c = getattr(self._tls, "counts", None)
        if c is None:
            c = self._tls.counts = {}
        return c

    # -- graph ---------------------------------------------------------------

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src -> ... -> dst in the order graph (caller holds _mu)."""
        seen = {src}
        stackq = [(src, [src])]
        while stackq:
            node, path = stackq.pop()
            for nxt in self.adj.get(node, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    stackq.append((nxt, path + [nxt]))
        return None

    def note_acquire(self, lock: "TracedLock") -> None:
        """Called before a first-level acquire: record edges from every
        held lock to this one, detecting inversions as they form."""
        held = self._prune(self._stackframe())
        with self._mu:
            self.acquisitions += 1
        if not held:
            return
        dst = lock.name
        for entry in held:
            src = entry[0].name
            if src == dst:
                continue  # same lock class (e.g. two cache entries): unordered
            key = (src, dst)
            with self._mu:
                known = key in self.edges
            if known:
                with self._mu:
                    self.edges[key]["count"] += 1
                continue
            stack = _stack()
            with self._mu:
                if key in self.edges:
                    self.edges[key]["count"] += 1
                    continue
                # inversion check BEFORE inserting: does dst already reach src?
                path = self._path(dst, src)
                self.edges[key] = {"count": 1, "stack": stack}
                self.adj.setdefault(src, set()).add(dst)
                if path is not None:
                    cycle = tuple(sorted(set(path + [dst])))
                    if cycle in self._seen_cycles:
                        continue
                    self._seen_cycles.add(cycle)
                    prior = self.edges.get((path[0], path[1]), {}).get("stack", "?")
                    self.inversions.append(
                        {
                            "acquiring": dst,
                            "held": src,
                            "cycle": path + [dst],
                            "thread": threading.current_thread().name,
                            "stack": stack,
                            "prior_stack": prior,
                        }
                    )

    @staticmethod
    def _prune(held: List[list]) -> List[list]:
        """Drop entries closed by a cross-thread release (lock slot nulled
        by :meth:`note_pop` on the releasing thread)."""
        if any(e[0] is None for e in held):
            held[:] = [e for e in held if e[0] is not None]
        return held

    def note_push(self, lock: "TracedLock") -> None:
        entry = [lock, time.monotonic()]
        self._stackframe().append(entry)
        with self._mu:
            self._live[id(lock)] = (self._counts(), entry)

    def note_pop(self, lock: "TracedLock") -> None:
        held = self._prune(self._stackframe())
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _l, t0 = held.pop(i)
                with self._mu:
                    self._live.pop(id(lock), None)
                self._close_segment(lock, t0)
                return
        # not on this thread's stack: a plain Lock released by a thread
        # other than the acquirer (handoff signaling). Close the owner's
        # entry in place — nulling the lock slot marks it for pruning —
        # and clear the owner's reentrancy count so its next acquire of
        # this lock is tracked as first-level again.
        with self._mu:
            rec = self._live.pop(id(lock), None)
        if rec is not None:
            owner_counts, entry = rec
            owner_counts.pop(id(lock), None)
            t0 = entry[1]
            entry[0] = None
            self._close_segment(lock, t0)

    def _close_segment(self, lock: "TracedLock", t0: float) -> None:
        ms = (time.monotonic() - t0) * 1000.0
        with self._mu:
            if ms > self.max_hold_ms.get(lock.name, 0.0):
                self.max_hold_ms[lock.name] = ms
        if (
            ms > self.hold_ms
            and not lock.hold_exempt
            and not any(s in lock.name for s in self.hold_exempt_sites)
        ):
            stack = _stack()
            with self._mu:
                self.hold_outliers.append(
                    {
                        "lock": lock.name,
                        "ms": round(ms, 3),
                        "thread": threading.current_thread().name,
                        "stack": stack,
                    }
                )

    # -- construction / reporting -------------------------------------------

    def wrap(self, inner, name: str, hold_exempt: bool = False) -> "TracedLock":
        with self._mu:
            self.locks_created += 1
        return TracedLock(self, inner, name, hold_exempt)

    def report(self) -> dict:
        with self._mu:
            return {
                "locks": self.locks_created,
                "acquisitions": self.acquisitions,
                "edges": len(self.edges),
                "inversions": list(self.inversions),
                "hold_outliers": list(self.hold_outliers),
                "hold_threshold_ms": self.hold_ms,
                "max_hold_ms": dict(
                    sorted(self.max_hold_ms.items(), key=lambda kv: -kv[1])[:10]
                ),
            }


def format_report(rep: dict) -> str:
    lines = [
        f"lockwatch: {rep['locks']} lock(s), {rep['acquisitions']} acquisition(s), "
        f"{rep['edges']} order edge(s), {len(rep['inversions'])} inversion(s), "
        f"{len(rep['hold_outliers'])} hold outlier(s) "
        f"(threshold {rep['hold_threshold_ms']:.0f} ms)"
    ]
    for inv in rep["inversions"]:
        lines.append(
            f"  INVERSION acquiring {inv['acquiring']} while holding "
            f"{inv['held']} on {inv['thread']} (cycle: {' -> '.join(inv['cycle'])})"
        )
        lines.append(f"    now:   {inv['stack']}")
        lines.append(f"    prior: {inv['prior_stack']}")
    for h in rep["hold_outliers"]:
        lines.append(f"  HOLD {h['lock']} for {h['ms']:.1f} ms on {h['thread']}")
        lines.append(f"    at: {h['stack']}")
    if rep["max_hold_ms"]:
        worst = ", ".join(f"{k}={v:.1f}ms" for k, v in rep["max_hold_ms"].items())
        lines.append(f"  longest holds: {worst}")
    return "\n".join(lines)


class TracedLock:
    """Lock/RLock wrapper feeding a :class:`LockWatch`. Implements the
    full lock protocol including the Condition integration hooks, so it
    can sit underneath ``threading.Condition``/``Event`` transparently."""

    __slots__ = ("_w", "_inner", "name", "hold_exempt")

    def __init__(
        self, watch: LockWatch, inner, name: str, hold_exempt: bool = False
    ) -> None:
        self._w = watch
        self._inner = inner
        self.name = name
        self.hold_exempt = hold_exempt

    def acquire(self, blocking: bool = True, timeout: float = -1):
        counts = self._w._counts()
        me = id(self)
        if counts.get(me, 0) > 0:  # reentrant re-acquire (RLock)
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                counts[me] += 1
            return ok
        self._w.note_acquire(self)  # order is recorded at the attempt
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            counts[me] = 1
            self._w.note_push(self)
        return ok

    def release(self) -> None:
        counts = self._w._counts()
        me = id(self)
        n = counts.get(me, 0)
        if n > 1:
            counts[me] = n - 1
            self._inner.release()
            return
        counts.pop(me, None)
        self._w.note_pop(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked() if hasattr(self._inner, "locked") else False

    # -- Condition protocol (threading.Condition borrows these) -------------

    def _is_owned(self) -> bool:
        return self._w._counts().get(id(self), 0) > 0

    def _release_save(self):
        counts = self._w._counts()
        n = counts.pop(id(self), 0)
        self._w.note_pop(self)  # a parked waiter does NOT hold the lock
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        return (state, n)

    def _acquire_restore(self, saved) -> None:
        state, n = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._w._counts()[id(self)] = max(1, n)
        self._w.note_push(self)

    def __repr__(self) -> str:
        return f"<TracedLock {self.name} over {self._inner!r}>"


# ---------------------------------------------------------------------------
# process-global installation (make tsan / OPENSIM_LOCKWATCH=1)
# ---------------------------------------------------------------------------

WATCH: Optional[LockWatch] = None
_ORIG: Dict[str, object] = {}


def _creation_site() -> Optional[Tuple[str, bool]]:
    """(file:line, hold-exempt?) of the repo frame creating a lock, or
    None for stdlib/third-party creations (left uninstrumented — noise
    control). A trailing ``# lockwatch: hold-exempt`` comment on the
    creating source line marks the lock's holds as by-design long (the
    flush/serialization locks that legitimately span engine work);
    inversions are still tracked for it."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        base = os.path.basename(fn)
        if (
            "lockwatch" in base
            or base == "threading.py"
            or fn.startswith("<")
        ):
            f = f.f_back
            continue
        norm = fn.replace(os.sep, "/")
        if "opensim_tpu" in norm or "/tests/" in norm or base.startswith("test_"):
            parts = norm.rsplit("/", 2)
            src = linecache.getline(fn, f.f_lineno)
            return (
                f"{'/'.join(parts[-2:])}:{f.f_lineno}",
                "lockwatch: hold-exempt" in src,
            )
        return None
    return None


def _factory(orig):
    def make(*args, **kwargs):
        inner = orig(*args, **kwargs)
        w = WATCH
        if w is None:
            return inner
        site = _creation_site()
        if site is None:
            return inner
        return w.wrap(inner, site[0], hold_exempt=site[1])

    return make


def install(hold_ms: Optional[float] = None) -> LockWatch:
    """Monkeypatch ``threading.Lock``/``threading.RLock`` so every lock
    created afterwards **from repo code** is traced. Idempotent. Call as
    early as possible (module-level singletons created before install stay
    raw)."""
    global WATCH
    if WATCH is not None:
        return WATCH
    WATCH = LockWatch(hold_ms)
    _ORIG["Lock"] = threading.Lock
    _ORIG["RLock"] = threading.RLock
    threading.Lock = _factory(_ORIG["Lock"])  # type: ignore[misc]
    threading.RLock = _factory(_ORIG["RLock"])  # type: ignore[misc]
    return WATCH


def uninstall() -> Optional[dict]:
    """Restore the original constructors; returns the final report."""
    global WATCH
    if WATCH is None:
        return None
    rep = WATCH.report()
    threading.Lock = _ORIG.pop("Lock")  # type: ignore[misc]
    threading.RLock = _ORIG.pop("RLock")  # type: ignore[misc]
    WATCH = None
    return rep


def current() -> Optional[LockWatch]:
    return WATCH


def self_test() -> bool:
    """Seeded A→B/B→A inversion a healthy sanitizer MUST catch — the
    `make tsan` proof that a green run means 'no inversions observed',
    not 'detector asleep'. Runs on a private LockWatch; the global graph
    is untouched."""
    w = LockWatch(hold_ms=10_000)
    a = w.wrap(_thread.allocate_lock(), "selftest:A")
    b = w.wrap(_thread.allocate_lock(), "selftest:B")
    with a:
        with b:
            pass
    with b:
        with a:  # the inversion
            pass
    return len(w.report()["inversions"]) == 1
