"""CLI entry: ``python -m opensim_tpu.analysis [paths...]``."""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List, Optional

from . import RULES, lint_paths, render_human, render_json, render_sarif


def pyproject_defaults(path: str = "pyproject.toml") -> Dict[str, List[str]]:
    """Defaults from ``[tool.opensim-lint]`` (``paths``/``rules`` string
    arrays). Uses tomllib where available (3.11+); this image runs 3.10,
    so a minimal literal reader covers the two keys we define."""
    if not os.path.isfile(path):
        return {}
    with open(path, "rb") as fh:
        raw = fh.read()
    try:
        import tomllib  # Python 3.11+
    except ImportError:
        tomllib = None
    if tomllib is not None:
        try:
            table = tomllib.loads(raw.decode()).get("tool", {}).get("opensim-lint", {})
            return {k: v for k, v in table.items() if isinstance(v, list)}
        except tomllib.TOMLDecodeError:
            pass  # malformed elsewhere in the file: the minimal reader below
    m = re.search(r"^\[tool\.opensim-lint\]\s*$(.*?)(?=^\[|\Z)", raw.decode(), re.M | re.S)
    if not m:
        return {}
    out: Dict[str, List[str]] = {}
    for key, body in re.findall(r"^(\w[\w-]*)\s*=\s*\[(.*?)\]", m.group(1), re.M | re.S):
        out[key] = re.findall(r'"([^"]+)"', body)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="opensim-lint",
        description="repo-specific AST correctness analyzer (see docs/static-analysis.md)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=None,
        help="files/directories to lint (default: [tool.opensim-lint] paths "
        "in ./pyproject.toml, else opensim_tpu)",
    )
    ap.add_argument("--json", action="store_true", help="emit findings as JSON")
    ap.add_argument(
        "--format",
        choices=("human", "json", "sarif"),
        default=None,
        help="output format (sarif emits SARIF 2.1.0 for CI/editor "
        "annotation; --json is shorthand for --format json)",
    )
    ap.add_argument(
        "--rules",
        help="comma-separated rule names/codes to run (default: all)",
    )
    ap.add_argument("--list-rules", action="store_true", help="list rules and exit")
    ap.add_argument(
        "--check-typed-core",
        action="store_true",
        help="stdlib typed-core signature check (make mypy fallback)",
    )
    args = ap.parse_args(argv)

    if args.check_typed_core:
        from .typed_core import check_typed_core

        problems = check_typed_core()
        for p in problems:
            print(p)
        print(
            f"typed-core: {len(problems)} problem(s)"
            if problems
            else "typed-core: signatures complete"
        )
        return 1 if problems else 0

    if args.list_rules:
        for name in sorted(RULES):
            r = RULES[name]
            print(f"{r.code}  {r.name:18s} {r.description}")
        return 0

    cfg = pyproject_defaults()
    if args.rules:
        rules: Optional[List[str]] = [r for r in args.rules.split(",") if r]
    else:
        rules = cfg.get("rules") or None
    paths = args.paths or cfg.get("paths") or ["opensim_tpu"]
    fmt = args.format or ("json" if args.json else "human")
    stats: dict = {}
    findings = lint_paths(paths, rules=rules, stats=stats)
    if fmt == "json":
        print(render_json(findings))
    elif fmt == "sarif":
        print(render_sarif(findings))
    else:
        # total lint wall time rides the `make lint` output: every file is
        # parsed once and the AST shared across all rules
        print(render_human(findings, stats=stats))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
