"""CLI entry: ``python -m opensim_tpu.analysis [paths...]``."""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List, Optional

from . import RULES, lint_paths, render_human, render_json, render_sarif


def pyproject_defaults(path: str = "pyproject.toml") -> Dict[str, List[str]]:
    """Defaults from ``[tool.opensim-lint]`` (``paths``/``rules`` string
    arrays). Uses tomllib where available (3.11+); this image runs 3.10,
    so a minimal literal reader covers the two keys we define."""
    if not os.path.isfile(path):
        return {}
    with open(path, "rb") as fh:
        raw = fh.read()
    try:
        import tomllib  # Python 3.11+
    except ImportError:
        tomllib = None
    if tomllib is not None:
        try:
            table = tomllib.loads(raw.decode()).get("tool", {}).get("opensim-lint", {})
            return {k: v for k, v in table.items() if isinstance(v, list)}
        except tomllib.TOMLDecodeError:
            pass  # malformed elsewhere in the file: the minimal reader below
    m = re.search(r"^\[tool\.opensim-lint\]\s*$(.*?)(?=^\[|\Z)", raw.decode(), re.M | re.S)
    if not m:
        return {}
    out: Dict[str, List[str]] = {}
    for key, body in re.findall(r"^(\w[\w-]*)\s*=\s*\[(.*?)\]", m.group(1), re.M | re.S):
        out[key] = re.findall(r'"([^"]+)"', body)
    return out


def _git_changed_files(roots: List[str]) -> Optional[List[str]]:
    """Lintable files with uncommitted changes (``git status
    --porcelain``), scoped to the configured lint roots. A modified
    ``.cc`` engine source pulls in the native package next to it so the
    ABI rules (OSL1604/OSL1804) re-check the boundary. Returns None when
    not in a git checkout (caller falls back to a full run)."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "status", "--porcelain"],
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None

    def in_scope(path: str) -> bool:
        norm = path.replace(os.sep, "/")
        return any(
            norm == r or norm.startswith(r.rstrip("/") + "/")
            for r in (root.replace(os.sep, "/") for root in roots)
        )

    out: List[str] = []
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: lint the new name
            path = path.split(" -> ", 1)[1]
        if not in_scope(path) or not os.path.exists(path):
            continue
        if path.endswith(".py") or os.path.isdir(path):
            out.append(path)
        elif path.endswith(".cc"):
            mirror = os.path.join(os.path.dirname(path), "__init__.py")
            if os.path.isfile(mirror):
                out.append(mirror)
    return sorted(set(out))


def _checked_flag_paths(args):
    """Validate the path-valued flags (registered validators, OSL1603);
    raises ValueError with the usual one-liner text."""
    from ..utils.validate import user_path

    cache_path = None
    if args.cache and not args.no_cache:
        cache_path = user_path(args.cache, label="--cache")
    sarif_out = user_path(args.sarif_out or "", label="--sarif-out", allow_empty=True)
    corpus_dir = user_path(args.corpus or "", label="--corpus", allow_empty=True)
    return cache_path, sarif_out, corpus_dir


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="opensim-lint",
        description="repo-specific AST correctness analyzer (see docs/static-analysis.md)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=None,
        help="files/directories to lint (default: [tool.opensim-lint] paths "
        "in ./pyproject.toml, else opensim_tpu)",
    )
    ap.add_argument("--json", action="store_true", help="emit findings as JSON")
    ap.add_argument(
        "--format",
        choices=("human", "json", "sarif"),
        default=None,
        help="output format (sarif emits SARIF 2.1.0 for CI/editor "
        "annotation; --json is shorthand for --format json)",
    )
    ap.add_argument(
        "--rules",
        help="comma-separated rule names/codes to run (default: all)",
    )
    ap.add_argument("--list-rules", action="store_true", help="list rules and exit")
    ap.add_argument(
        "--cache",
        metavar="PATH",
        default=None,
        help="content-hash result cache (unchanged files skip their rules; "
        "default .lint/cache.json under make lint, off otherwise)",
    )
    ap.add_argument(
        "--no-cache",
        action="store_true",
        help="disable the result cache even if --cache was given",
    )
    ap.add_argument(
        "--sarif-out",
        metavar="PATH",
        default=None,
        help="ALSO write SARIF 2.1.0 to this path (stable artifact for CI "
        "upload), independent of --format",
    )
    ap.add_argument(
        "--corpus",
        metavar="DIR",
        default=None,
        help="after linting, run the detector-awake corpus gate over DIR "
        "(every registered rule must fire on its fixture and stay quiet "
        "on the clean variant)",
    )
    ap.add_argument(
        "--changed",
        action="store_true",
        help="lint only files with uncommitted git changes under the "
        "configured paths (plus the native package when a .cc engine "
        "source changed) — the fast pre-commit loop; whole-program rules "
        "see just this subset and cache in their own project slot",
    )
    ap.add_argument(
        "--jobs",
        type=int,
        default=None,
        metavar="N",
        help="process-pool width for the per-file rule tier on cache "
        "misses (default: auto-size to the machine; 1 forces serial; "
        "results are byte-identical either way)",
    )
    ap.add_argument(
        "--check-typed-core",
        action="store_true",
        help="stdlib typed-core signature check (make mypy fallback)",
    )
    args = ap.parse_args(argv)

    if args.check_typed_core:
        from .typed_core import check_typed_core

        problems = check_typed_core()
        for p in problems:
            print(p)
        print(
            f"typed-core: {len(problems)} problem(s)"
            if problems
            else "typed-core: signatures complete"
        )
        return 1 if problems else 0

    if args.list_rules:
        for name in sorted(RULES):
            r = RULES[name]
            print(f"{r.code}  {r.name:18s} {r.description}")
        return 0

    cfg = pyproject_defaults()
    if args.rules:
        rules: Optional[List[str]] = [r for r in args.rules.split(",") if r]
    else:
        rules = cfg.get("rules") or None
    paths = args.paths or cfg.get("paths") or ["opensim_tpu"]
    fmt = args.format or ("json" if args.json else "human")
    if args.changed:
        changed = _git_changed_files(paths)
        if changed is None:
            print("opensim-lint: --changed needs a git checkout", file=sys.stderr)
            return 2
        if not changed:
            print("opensim-lint: --changed: no modified files under "
                  + ", ".join(paths) + "; nothing to lint")
            return 0
        paths = changed
    try:
        cache_path, sarif_out, corpus_dir = _checked_flag_paths(args)
    except ValueError as e:
        print(f"opensim-lint: {e}", file=sys.stderr)
        return 2
    stats: dict = {}
    findings = lint_paths(
        paths, rules=rules, stats=stats, cache_path=cache_path, jobs=args.jobs
    )
    if sarif_out:
        out_dir = os.path.dirname(sarif_out)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(sarif_out, "w", encoding="utf-8") as fh:
            fh.write(render_sarif(findings))
    if fmt == "json":
        print(render_json(findings))
    elif fmt == "sarif":
        print(render_sarif(findings))
    else:
        # total lint wall time rides the `make lint` output: every file is
        # parsed once and the AST shared across all rules (and, with
        # --cache, unchanged files skip their rules entirely)
        print(render_human(findings, stats=stats))
    rc = 1 if findings else 0
    if corpus_dir:
        from .corpus import check_corpus, corpus_inventory

        problems = check_corpus(corpus_dir)
        if problems:
            for p in problems:
                print(f"lint-corpus: {p}")
            rc = 1
        else:
            inv = corpus_inventory(corpus_dir)
            n_fix = sum(len(v) for e in inv.values() for v in e.values())
            print(
                f"lint-corpus: {len(RULES)} rules, {n_fix} fixtures, "
                "all detectors awake"
            )
    return rc


if __name__ == "__main__":
    sys.exit(main())
