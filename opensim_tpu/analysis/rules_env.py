"""env-registry (OSL1401): ``OPENSIM_*`` environment reads go through
``utils/envknobs.py``.

The knob surface is ~45 variables; before the registry each one was read
ad hoc (``os.environ.get`` + local parse + local default), so the surface
was undiscoverable, a typo'd name silently read as unset, and the
documented default could drift from the parsed one. ``utils/envknobs.py``
is now the one read path: :func:`~opensim_tpu.utils.envknobs.raw` fails
loudly on an unregistered name and the registry generates ``docs/env.md``.

The rule flags, in any module other than ``utils/envknobs.py``:

- ``os.environ.get("OPENSIM_…")`` / ``os.getenv("OPENSIM_…")`` calls;
- ``os.environ["OPENSIM_…"]`` subscripts in read (Load) context;
- ``"OPENSIM_…" in os.environ`` membership probes.

WRITES stay legal (``os.environ["OPENSIM_X"] = v`` — the CLI's
``--backend`` plumbing and tests arm knobs for downstream code);
governance is about undeclared reads. Fix by registering the knob in
``utils/envknobs.py`` (name, type, default, validator, doc) and reading it
via ``envknobs.raw(...)`` / ``envknobs.value(...)``; see
docs/static-analysis.md.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register

_FIX = (
    "read it through utils/envknobs.py (envknobs.raw/value) and register "
    "the knob there so docs/env.md covers it"
)


def _is_environ(node: ast.AST) -> bool:
    name = dotted_name(node)
    return bool(name) and (name == "environ" or name.endswith(".environ"))


def _opensim_const(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value.startswith("OPENSIM_")
    )


@register
class EnvRegistryRule(Rule):
    name = "env-registry"
    code = "OSL1401"
    description = "raw os.environ read of an OPENSIM_* knob outside utils/envknobs.py"
    # the registry module IS the read path; tests arm knobs on purpose
    exclude_paths = ("utils/envknobs.py", "tests/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                leaf = name.rsplit(".", 1)[-1]
                reads_env = (
                    (leaf == "get" and isinstance(node.func, ast.Attribute)
                     and _is_environ(node.func.value))
                    or leaf == "getenv"
                )
                if reads_env and node.args and _opensim_const(node.args[0]):
                    yield self.finding(
                        ctx, node,
                        f"{node.args[0].value} is read straight from the "
                        f"environment; {_FIX}",
                    )
            elif isinstance(node, ast.Subscript):
                if (
                    isinstance(node.ctx, ast.Load)
                    and _is_environ(node.value)
                    and _opensim_const(node.slice)
                ):
                    yield self.finding(
                        ctx, node,
                        f"{node.slice.value} is subscript-read straight from "
                        f"the environment; {_FIX}",
                    )
            elif isinstance(node, ast.Compare):
                if (
                    _opensim_const(node.left)
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and _is_environ(node.comparators[0])
                ):
                    yield self.finding(
                        ctx, node,
                        f"{node.left.value} membership-probed straight on the "
                        f"environment; {_FIX} (envknobs.is_set)",
                    )
