"""Cross-language ABI layout parsing for the abi-parity pass (OSL1604).

The C++ scan engine's ``ScanArgs`` struct and the ctypes mirror in
``native/__init__.py`` used to be kept in sync by a comment
(``// keep order in sync with native/__init__.py``). This module turns
that comment into a machine check: it parses BOTH declarations —

- the C++ side straight out of ``scan_engine.cc`` (member declarations of
  ``struct ScanArgs`` between the ``// abi-begin: ScanArgs`` /
  ``// abi-end: ScanArgs`` anchors, falling back to brace matching), plus
  the ``opensim_abi_version()`` constant;
- the Python side out of the ``native/__init__.py`` AST: the packing
  lists (``_DIMS``/``_FEATURES``/…/``_BUFFERS``) and, crucially, the
  ``ScanArgs._fields_`` *composition expression*, so the concatenation
  order is read from the code instead of being hardcoded here;
- the serial engine's wire tag: ``WIRE_MAGIC``/``WIRE_VERSION`` in
  ``native/serial.py`` against the ``r.u32() != 0x…`` guards in
  ``serial_engine.cc``.

Every field is normalized to a small width vocabulary (``i64``/``f64``
scalars, ``ptr:u8``/``ptr:i32``/``ptr:i64``/``ptr:f32``/``ptr:f64``
pointers) and compared for count, order, and width;
:func:`compare_layouts` names the exact drifted field.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

__all__ = [
    "parse_cc_struct",
    "parse_cc_abi_version",
    "parse_cc_serial_wire",
    "parse_py_layout",
    "parse_py_abi_version",
    "parse_py_serial_wire",
    "compare_layouts",
]

Field = Tuple[str, str]  # (name, normalized kind)

_CC_SCALARS = {"int64_t": "i64", "double": "f64", "int32_t": "i32", "uint8_t": "u8", "float": "f32"}
_CC_PTRS = {
    "uint8_t": "ptr:u8", "int32_t": "ptr:i32", "int64_t": "ptr:i64",
    "float": "ptr:f32", "double": "ptr:f64",
}
_CTYPES_SCALARS = {"c_int64": "i64", "c_double": "f64", "c_int32": "i32", "c_uint8": "u8", "c_float": "f32"}

_ABI_BEGIN_RE = re.compile(r"//\s*abi-begin:\s*(\w+)")
_ABI_END_RE = re.compile(r"//\s*abi-end:\s*(\w+)")
_ABI_VERSION_RE = re.compile(r"opensim_abi_version\s*\(\s*\)\s*\{\s*return\s+(\d+)\s*;")


def _strip_line_comments(text: str) -> str:
    return "\n".join(line.split("//", 1)[0] for line in text.splitlines())


def _struct_body(text: str, struct: str) -> Optional[str]:
    """Member text of ``struct <name> { ... };`` — the anchored span when
    ``// abi-begin:/abi-end:`` markers are present, else brace matching."""
    begin = end = None
    for i, line in enumerate(text.splitlines()):
        m = _ABI_BEGIN_RE.search(line)
        if m and m.group(1) == struct:
            begin = i + 1
        m = _ABI_END_RE.search(line)
        if m and m.group(1) == struct:
            end = i
    if begin is not None and end is not None and end > begin:
        span = "\n".join(text.splitlines()[begin:end])
        # the anchored span still contains the `struct X {` / `};` lines
        # when the anchors sit outside them; cut to the braces if present
        if "{" in span:
            span = span.split("{", 1)[1]
        if "}" in span:
            span = span.rsplit("}", 1)[0]
        return span
    m = re.search(r"struct\s+" + re.escape(struct) + r"\s*\{", text)
    if m is None:
        return None
    depth = 0
    start = m.end()
    for i in range(m.end() - 1, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start:i]
    return None


def parse_cc_struct(text: str, struct: str = "ScanArgs") -> Tuple[List[Field], List[str]]:
    """(ordered fields, problems) from a C++ struct declaration."""
    body = _struct_body(text, struct)
    if body is None:
        return [], [f"struct {struct} not found in C++ source"]
    body = _strip_line_comments(body)
    body = re.sub(r"/\*.*?\*/", " ", body, flags=re.S)
    fields: List[Field] = []
    problems: List[str] = []
    for raw in body.split(";"):
        decl = " ".join(raw.split())
        if not decl:
            continue
        decl = decl.replace("const ", "")
        is_ptr = "*" in decl
        decl = decl.replace("*", " ")
        parts = [p for p in decl.split() if p]
        if len(parts) < 2:
            problems.append(f"unparsable member declaration: {raw.strip()!r}")
            continue
        ctype, names = parts[0], " ".join(parts[1:])
        table = _CC_PTRS if is_ptr else _CC_SCALARS
        kind = table.get(ctype)
        if kind is None:
            problems.append(f"unknown C type {ctype!r} in {raw.strip()!r}")
            continue
        for name in (n.strip() for n in names.split(",")):
            if name:
                fields.append((name, kind))
    return fields, problems


def parse_cc_abi_version(text: str) -> Optional[int]:
    m = _ABI_VERSION_RE.search(text)
    return int(m.group(1)) if m else None


def parse_cc_serial_wire(text: str) -> Tuple[Optional[int], Optional[int]]:
    """(magic, version) expected by the C++ serial parser: the first two
    ``r.u32() != <const>`` guards."""
    guards = re.findall(r"r\.u32\(\)\s*!=\s*(0x[0-9A-Fa-f]+|\d+)", text)
    magic = int(guards[0], 0) if len(guards) >= 1 else None
    version = int(guards[1], 0) if len(guards) >= 2 else None
    return magic, version


# ---------------------------------------------------------------------------
# python side
# ---------------------------------------------------------------------------


def _module_lists(tree: ast.Module) -> Dict[str, list]:
    """Module-level list literals: name -> evaluated list. String lists
    evaluate to strings; ``_BUFFERS``-style tuple lists evaluate to
    (name, kind) using the third tuple element (the dtype tag)."""
    out: Dict[str, list] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name) or not isinstance(node.value, ast.List):
            continue
        items: list = []
        ok = True
        for el in node.value.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                items.append(el.value)
            elif isinstance(el, ast.Tuple) and len(el.elts) >= 3:
                name_el, kind_el = el.elts[0], el.elts[2]
                if (
                    isinstance(name_el, ast.Constant)
                    and isinstance(name_el.value, str)
                    and isinstance(kind_el, ast.Constant)
                    and isinstance(kind_el.value, str)
                ):
                    items.append((name_el.value, kind_el.value))
                else:
                    ok = False
                    break
            else:
                ok = False
                break
        if ok:
            out[t.id] = items
    return out


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _name_chain(expr: ast.AST) -> Optional[List[str]]:
    """``A + B + C`` as ['A', 'B', 'C'] (or a single name)."""
    if isinstance(expr, ast.Name):
        return [expr.id]
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _name_chain(expr.left)
        right = _name_chain(expr.right)
        if left is not None and right is not None:
            return left + right
    return None


def parse_py_layout(
    tree: ast.Module, struct: str = "ScanArgs"
) -> Tuple[List[Field], List[str]]:
    """(ordered fields, problems) from the ctypes mirror: evaluates the
    packing lists and walks the ``_fields_`` composition expression so the
    concatenation order comes from the code under test."""
    lists = _module_lists(tree)
    cls: Optional[ast.ClassDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == struct:
            cls = node
            break
    if cls is None:
        return [], [f"class {struct} not found in Python source"]
    fields_expr: Optional[ast.AST] = None
    for node in cls.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "_fields_" for t in node.targets
        ):
            fields_expr = node.value
    if fields_expr is None:
        return [], [f"{struct}._fields_ assignment not found"]

    problems: List[str] = []
    fields: List[Field] = []

    def expand(expr: ast.AST) -> None:
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            expand(expr.left)
            expand(expr.right)
            return
        if isinstance(expr, ast.ListComp):
            elt = expr.elt
            gen = expr.generators[0]
            names = _name_chain(gen.iter)
            if names is None:
                problems.append(
                    f"unsupported _fields_ comprehension iterable at line {expr.lineno}"
                )
                return
            # tuple-unpack target => the (name, ptr, dtype) buffer list
            if isinstance(gen.target, ast.Tuple):
                for lname in names:
                    for item in lists.get(lname, []):
                        if isinstance(item, tuple):
                            fields.append((item[0], f"ptr:{item[1]}"))
                        else:
                            problems.append(
                                f"{lname}: expected (name, ptr, dtype) tuples"
                            )
                return
            if not isinstance(elt, ast.Tuple) or len(elt.elts) != 2:
                problems.append(f"unsupported _fields_ element at line {expr.lineno}")
                return
            ctype_leaf = _dotted(elt.elts[1]).rsplit(".", 1)[-1]
            kind = _CTYPES_SCALARS.get(ctype_leaf)
            if kind is None:
                problems.append(f"unknown ctypes scalar {ctype_leaf!r}")
                return
            for lname in names:
                if lname not in lists:
                    problems.append(f"packing list {lname} not found at module level")
                    continue
                for item in lists[lname]:
                    if isinstance(item, str):
                        fields.append((item, kind))
                    else:
                        problems.append(f"{lname}: expected field-name strings")
            return
        problems.append(f"unsupported _fields_ expression node {type(expr).__name__}")

    expand(fields_expr)
    return fields, problems


def _module_int(tree: ast.Module, name: str) -> Optional[int]:
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, int)
        ):
            return node.value.value
    return None


def parse_py_abi_version(tree: ast.Module) -> Optional[int]:
    return _module_int(tree, "ABI_VERSION")


def parse_py_serial_wire(tree: ast.Module) -> Tuple[Optional[int], Optional[int]]:
    return _module_int(tree, "WIRE_MAGIC"), _module_int(tree, "WIRE_VERSION")


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------


def compare_layouts(
    cc: List[Field], py: List[Field], limit: int = 8
) -> List[str]:
    """Human-oriented mismatch list (empty == byte-identical layouts).
    Every message names the exact field so the fix is one hop away."""
    out: List[str] = []
    if len(cc) != len(py):
        out.append(
            f"field count drift: C++ declares {len(cc)} ScanArgs members, "
            f"Python packs {len(py)}"
        )
    for i, ((cn, ck), (pn, pk)) in enumerate(zip(cc, py)):
        if len(out) >= limit:
            out.append("... further field drift suppressed")
            break
        if cn != pn:
            out.append(
                f"field {i}: order drift — C++ declares `{cn}` ({ck}) where "
                f"Python packs `{pn}` ({pk})"
            )
            # after one order drift every later pair mismatches; stop at
            # the first so the message points at the actual edit
            break
        if ck != pk:
            out.append(
                f"field {i} `{cn}`: width drift — C++ {ck} vs Python {pk}"
            )
    if len(cc) != len(py) and not any("order drift" in m for m in out):
        extra = cc[len(py):] or py[len(cc):]
        side = "C++" if len(cc) > len(py) else "Python"
        names = ", ".join(n for n, _k in extra[:4])
        out.append(f"unmatched trailing fields on the {side} side: {names}")
    return out
