"""dtype-drift (OSL201): encoder arrays off the Go parity dtype policy.

The encoded cluster must match the vendored Go scheduler's arithmetic:
resource math is float32 (scores are compared bit-exactly against the
serial oracle) and ids/indices are int32. Bare ``np.float64`` or a
default-dtype constructor silently widens an array — XLA then inserts
converts, and score ties can flip relative to the Go baseline.

Every float/int array in ``encoding/`` must name its dtype, and the only
place ``np.float64`` may appear is ``encoding/dtypes.py`` — the module
that defines the policy (float64 is legal there only as the documented
log-table accumulation dtype).
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import FileContext, Finding, Rule, dotted_name, register

# constructor -> positional arity at which the dtype is already explicit
_CONSTRUCTOR_DTYPE_ARITY = {
    "np.zeros": 2,
    "np.ones": 2,
    "np.empty": 2,
    "np.full": 3,
    "numpy.zeros": 2,
    "numpy.ones": 2,
    "numpy.empty": 2,
    "numpy.full": 3,
    # arange/array infer from operands — require the kwarg always
    "np.arange": 99,
    "np.array": 99,
    "numpy.arange": 99,
    "numpy.array": 99,
}

_FLOAT64_NAMES = {"np.float64", "numpy.float64"}


@register
class DtypeDriftRule(Rule):
    name = "dtype-drift"
    code = "OSL201"
    description = "encoder array without the explicit Go-parity dtype"
    paths = ("opensim_tpu/encoding/",)
    exclude_paths = ("opensim_tpu/encoding/dtypes.py",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and dotted_name(node) in _FLOAT64_NAMES:
                yield self.finding(
                    ctx,
                    node,
                    "bare np.float64 in an encoder path; use the policy "
                    "constants in opensim_tpu/encoding/dtypes.py (Go "
                    "int64/float32 parity)",
                )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                arity = _CONSTRUCTOR_DTYPE_ARITY.get(name)
                if arity is None:
                    continue
                if len(node.args) >= arity:
                    continue
                if any(kw.arg == "dtype" for kw in node.keywords):
                    continue
                yield self.finding(
                    ctx,
                    node,
                    f"`{name}` without an explicit dtype defaults to float64/"
                    "platform-int; name the dtype (see encoding/dtypes.py)",
                )
